"""Thrift CompactProtocol struct codec for the KvStore wire surface.

The reference's peer channel exchanges thrift structs serialized with
``TCompactProtocol`` (reference IDL: openr/if/KvStore.thrift; service:
openr/if/OpenrCtrl.thrift KvStoreService). ``openr_tpu.utils.wire`` is
the framework's own self-describing codec; THIS module is the
interop path — it produces and consumes the exact compact-protocol
bytes a reference node emits, so an openr-tpu daemon can sit on the
wire with stock Open/R peers.

Implemented from the thrift compact protocol specification
(thrift/doc/specs/thrift-compact-protocol.md):

- unsigned LEB128 varints; zigzag(i16/i32/i64) for integer values
- struct field header: ``(delta << 4) | type`` when the field-id delta
  from the previous field is in [1, 15], else ``0x00 | type`` followed
  by the zigzag-varint field id
- BOOL is carried in the field-header type nibble (1=true, 2=false);
  standalone bools (collection elements) are one byte 1/2
- binary/string: varint byte-length + payload
- list/set: ``(size << 4) | elem_type`` when size < 15, else
  ``0xF0 | elem_type`` + varint size
- map: empty maps are the single byte 0x00, otherwise varint size +
  one byte ``(key_type << 4) | value_type``
- nested structs recurse; every struct ends with STOP (0x00)

Fields are written in IDL *declaration* order (the generated reference
serializers emit in declaration order, which for these structs differs
from field-id order — the IDL comments call the numbering out as
deliberate); the decoder accepts any order, per the spec.
"""

from __future__ import annotations

import struct as _struct
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

# compact-protocol wire types
T_STOP = 0x00
T_TRUE = 0x01
T_FALSE = 0x02
T_BYTE = 0x03
T_I16 = 0x04
T_I32 = 0x05
T_I64 = 0x06
T_DOUBLE = 0x07
T_BINARY = 0x08  # also string
T_LIST = 0x09
T_SET = 0x0A
T_MAP = 0x0B
T_STRUCT = 0x0C

# type descriptors: ("i64",) | ("i32",) | ("i16",) | ("byte",) |
# ("bool",) | ("string",) | ("binary",) | ("list", elem) |
# ("set", elem) | ("map", key, val) | ("struct", StructSchema)
_WIRE_TYPE = {
    "bool": T_TRUE,  # placeholder; bools resolve per-value in headers
    "byte": T_BYTE,
    "i16": T_I16,
    "i32": T_I32,
    "i64": T_I64,
    "double": T_DOUBLE,
    "string": T_BINARY,
    "binary": T_BINARY,
    "list": T_LIST,
    "set": T_SET,
    "map": T_MAP,
    "struct": T_STRUCT,
}


@dataclass(frozen=True)
class Field:
    """One IDL field: id, type descriptor, python key. ``optional``
    fields are skipped when the value is None; required fields with
    value None raise."""

    fid: int
    ftype: Tuple
    name: str
    optional: bool = False


@dataclass(frozen=True)
class StructSchema:
    name: str
    fields: Tuple[Field, ...]  # IDL declaration order

    def by_id(self) -> Dict[int, Field]:
        return {f.fid: f for f in self.fields}


class _Writer:
    def __init__(self):
        self.buf = bytearray()

    def byte(self, b: int) -> None:
        self.buf.append(b & 0xFF)

    def varint(self, n: int) -> None:
        assert n >= 0, n
        while True:
            if n < 0x80:
                self.buf.append(n)
                return
            self.buf.append((n & 0x7F) | 0x80)
            n >>= 7

    def zigzag(self, n: int, bits: int) -> None:
        mask = (1 << bits) - 1
        self.varint(((n << 1) ^ (n >> (bits - 1))) & mask)

    def binary(self, b: bytes) -> None:
        self.varint(len(b))
        self.buf.extend(b)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 70:
                raise ValueError("varint too long")

    def zigzag(self, bits: int) -> int:
        u = self.varint()
        n = (u >> 1) ^ -(u & 1)
        # normalize to signed range
        if n >= 1 << (bits - 1):
            n -= 1 << bits
        return n

    def binary(self) -> bytes:
        n = self.varint()
        out = self.data[self.pos : self.pos + n]
        if len(out) != n:
            raise ValueError("truncated binary")
        self.pos += n
        return bytes(out)


def _wire_type_of(ftype: Tuple, value: Any) -> int:
    if ftype[0] == "bool":
        return T_TRUE if value else T_FALSE
    return _WIRE_TYPE[ftype[0]]


def _write_value(w: _Writer, ftype: Tuple, value: Any) -> None:
    kind = ftype[0]
    if kind == "bool":
        w.byte(T_TRUE if value else T_FALSE)  # standalone (collection)
    elif kind == "byte":
        w.byte(value & 0xFF)
    elif kind in ("i16", "i32", "i64"):
        bits = {"i16": 16, "i32": 32, "i64": 64}[kind]
        w.zigzag(int(value), bits)
    elif kind == "double":
        # 8 bytes BIG-endian: fbthrift's CompactProtocol kept the
        # pre-spec big-endian double encoding (a documented divergence
        # from the Apache compact spec's little-endian), and THIS
        # codec's contract is byte-exact fbthrift interop — the wire
        # the reference's stack actually emits
        w.buf.extend(_struct.pack(">d", float(value)))
    elif kind == "string":
        w.binary(value.encode("utf-8"))
    elif kind == "binary":
        w.binary(bytes(value))
    elif kind in ("list", "set"):
        elem = ftype[1]
        items = sorted(value) if kind == "set" else list(value)
        et = _WIRE_TYPE[elem[0]] if elem[0] != "bool" else T_TRUE
        if len(items) < 15:
            w.byte((len(items) << 4) | et)
        else:
            w.byte(0xF0 | et)
            w.varint(len(items))
        for item in items:
            _write_value(w, elem, item)
    elif kind == "map":
        ktype, vtype = ftype[1], ftype[2]
        if not value:
            w.byte(0)
            return
        w.varint(len(value))
        kt = _WIRE_TYPE[ktype[0]] if ktype[0] != "bool" else T_TRUE
        vt = _WIRE_TYPE[vtype[0]] if vtype[0] != "bool" else T_TRUE
        w.byte((kt << 4) | vt)
        # deterministic output: sort keys (maps are unordered on the
        # wire; reference emits hash-map order, any order decodes)
        for k in sorted(value):
            _write_value(w, ktype, k)
            _write_value(w, vtype, value[k])
    elif kind == "struct":
        _write_struct(w, ftype[1], value)
    else:
        raise TypeError(f"unsupported type {kind}")


def _write_struct(w: _Writer, schema: StructSchema, values: Dict) -> None:
    last_fid = 0
    for f in schema.fields:
        value = values.get(f.name)
        if value is None:
            if f.optional:
                continue
            raise ValueError(f"{schema.name}.{f.name} is required")
        wtype = _wire_type_of(f.ftype, value)
        delta = f.fid - last_fid
        if 0 < delta <= 15:
            w.byte((delta << 4) | wtype)
        else:
            w.byte(wtype)
            w.zigzag(f.fid, 16)
        if f.ftype[0] != "bool":  # bool value rode in the header
            _write_value(w, f.ftype, value)
        last_fid = f.fid
    w.byte(T_STOP)


def _skip(r: _Reader, wtype: int, standalone: bool = False) -> None:
    """``standalone`` distinguishes the two bool encodings: a FIELD
    bool rides entirely in the field-header nibble (zero value bytes),
    while a collection/map ELEMENT bool is one byte (01/02). Skipping
    with the wrong context desyncs every subsequent byte."""
    if wtype in (T_TRUE, T_FALSE):
        if standalone:
            r.byte()
        return
    if wtype == T_BYTE:
        r.byte()
    elif wtype in (T_I16, T_I32, T_I64):
        r.varint()
    elif wtype == T_DOUBLE:
        if r.pos + 8 > len(r.data):
            raise ValueError("truncated double")
        r.pos += 8
    elif wtype == T_BINARY:
        r.binary()
    elif wtype in (T_LIST, T_SET):
        head = r.byte()
        size = head >> 4
        et = head & 0x0F
        if size == 15:
            size = r.varint()
        for _ in range(size):
            _skip(r, et, standalone=True)
    elif wtype == T_MAP:
        size = r.varint()
        if size:
            head = r.byte()
            for _ in range(size):
                _skip(r, head >> 4, standalone=True)
                _skip(r, head & 0x0F, standalone=True)
    elif wtype == T_STRUCT:
        while True:
            b = r.byte()
            if b == T_STOP:
                return
            wt = b & 0x0F
            if (b >> 4) == 0:
                r.zigzag(16)
            _skip(r, wt)
    else:
        raise ValueError(f"cannot skip wire type {wtype}")


def _read_value(
    r: _Reader, ftype: Tuple, wtype: int, standalone: bool = False
) -> Any:
    kind = ftype[0]
    if kind == "bool":
        # field context: the value IS the header nibble (zero bytes);
        # collection/map element context (standalone): one byte 01/02.
        # The elem-type nibble is T_TRUE in both cases, so the caller's
        # context flag — not the wire type — must decide.
        if standalone:
            return r.byte() == T_TRUE
        return wtype == T_TRUE
    if kind == "byte":
        b = r.byte()
        return b - 256 if b >= 128 else b
    if kind in ("i16", "i32", "i64"):
        return r.zigzag({"i16": 16, "i32": 32, "i64": 64}[kind])
    if kind == "double":
        raw = r.data[r.pos : r.pos + 8]
        if len(raw) != 8:
            raise ValueError("truncated double")
        r.pos += 8
        return _struct.unpack(">d", raw)[0]
    if kind == "string":
        return r.binary().decode("utf-8")
    if kind == "binary":
        return r.binary()
    if kind in ("list", "set"):
        head = r.byte()
        size = head >> 4
        if size == 15:
            size = r.varint()
        elem = ftype[1]
        items = [
            _read_value(r, elem, head & 0x0F, standalone=True)
            for _ in range(size)
        ]
        return set(items) if kind == "set" else items
    if kind == "map":
        size = r.varint()
        out: Dict = {}
        if size == 0:
            return out
        head = r.byte()
        for _ in range(size):
            k = _read_value(r, ftype[1], head >> 4, standalone=True)
            v = _read_value(r, ftype[2], head & 0x0F, standalone=True)
            out[k] = v
        return out
    if kind == "struct":
        return _read_struct(r, ftype[1])
    raise TypeError(f"unsupported type {kind}")


def _read_struct(r: _Reader, schema: StructSchema) -> Dict:
    fields = schema.by_id()
    out: Dict = {}
    last_fid = 0
    while True:
        head = r.byte()
        if head == T_STOP:
            return out
        wtype = head & 0x0F
        delta = head >> 4
        fid = last_fid + delta if delta else r.zigzag(16)
        last_fid = fid
        f = fields.get(fid)
        if f is None:
            _skip(r, wtype)  # forward compatibility: unknown field
            continue
        out[f.name] = _read_value(r, f.ftype, wtype)


def encode(schema: StructSchema, values: Dict) -> bytes:
    """Serialize ``values`` (a plain dict keyed by field name) as one
    compact-protocol struct."""
    w = _Writer()
    _write_struct(w, schema, values)
    return bytes(w.buf)


def decode(schema: StructSchema, data: bytes) -> Dict:
    """Parse one compact-protocol struct into a dict keyed by field
    name. Unknown fields are skipped (forward compatibility); absent
    fields are absent from the dict (callers apply IDL defaults)."""
    return _read_struct(_Reader(data), schema)


# -- KvStore.thrift schemas (field ids + declaration order verbatim) -----

# reference: openr/if/KvStore.thrift:21-41
VALUE = StructSchema(
    "Value",
    (
        Field(1, ("i64",), "version"),
        Field(3, ("string",), "originatorId"),
        Field(2, ("binary",), "value", optional=True),
        Field(4, ("i64",), "ttl"),
        Field(5, ("i64",), "ttlVersion"),
        Field(6, ("i64",), "hash", optional=True),
    ),
)

# reference: openr/if/KvStore.thrift:62-85
KEY_SET_PARAMS = StructSchema(
    "KeySetParams",
    (
        Field(2, ("map", ("string",), ("struct", VALUE)), "keyVals"),
        Field(3, ("bool",), "solicitResponse"),
        Field(5, ("list", ("string",)), "nodeIds", optional=True),
        Field(6, ("string",), "floodRootId", optional=True),
        Field(7, ("i64",), "timestamp_ms", optional=True),
    ),
)

# reference: openr/if/KvStore.thrift:87-89
KEY_GET_PARAMS = StructSchema(
    "KeyGetParams", (Field(1, ("list", ("string",)), "keys"),)
)

# reference: openr/if/KvStore.thrift:91-115
KEY_DUMP_PARAMS = StructSchema(
    "KeyDumpParams",
    (
        Field(1, ("string",), "prefix"),
        Field(3, ("set", ("string",)), "originatorIds"),
        Field(6, ("bool",), "ignoreTtl"),
        Field(7, ("bool",), "doNotPublishValue"),
        Field(
            2,
            ("map", ("string",), ("struct", VALUE)),
            "keyValHashes",
            optional=True,
        ),
        Field(4, ("i32",), "oper", optional=True),
        Field(5, ("list", ("string",)), "keys", optional=True),
    ),
)

# reference: openr/if/KvStore.thrift:229-254
PUBLICATION = StructSchema(
    "Publication",
    (
        Field(2, ("map", ("string",), ("struct", VALUE)), "keyVals"),
        Field(3, ("list", ("string",)), "expiredKeys"),
        Field(4, ("list", ("string",)), "nodeIds", optional=True),
        Field(5, ("list", ("string",)), "tobeUpdatedKeys", optional=True),
        Field(6, ("string",), "floodRootId", optional=True),
        Field(7, ("string",), "area"),
    ),
)

# reference: openr/if/KvStore.thrift:205-219 (KvStoreRequest; the DUAL
# and flood-topo arms are carried by the framework's own RPC surface)
KV_STORE_REQUEST = StructSchema(
    "KvStoreRequest",
    (
        Field(1, ("i32",), "cmd"),
        Field(11, ("string",), "area"),
        Field(
            2, ("struct", KEY_SET_PARAMS), "keySetParams", optional=True
        ),
        Field(
            3, ("struct", KEY_GET_PARAMS), "keyGetParams", optional=True
        ),
        Field(
            6, ("struct", KEY_DUMP_PARAMS), "keyDumpParams", optional=True
        ),
    ),
)

# Command enum values (KvStore.thrift:47-52)
CMD_KEY_SET = 1
CMD_KEY_DUMP = 3


# -- dataclass adapters --------------------------------------------------


def _value_to_wire(v) -> Dict:
    out = {
        "version": v.version,
        "originatorId": v.originator_id,
        "ttl": v.ttl,
        "ttlVersion": v.ttl_version,
    }
    if v.value is not None:
        out["value"] = v.value
    if v.hash is not None:
        out["hash"] = v.hash
    return out


def _value_from_wire(d: Dict):
    from openr_tpu.types import Value

    return Value(
        version=d.get("version", 0),
        originator_id=d.get("originatorId", ""),
        value=d.get("value"),
        ttl=d.get("ttl", 0),
        ttl_version=d.get("ttlVersion", 0),
        hash=d.get("hash"),
    )


def encode_value(v) -> bytes:
    return encode(VALUE, _value_to_wire(v))


def decode_value(data: bytes):
    return _value_from_wire(decode(VALUE, data))


def _publication_to_wire(pub) -> Dict:
    out: Dict = {
        "keyVals": {
            k: _value_to_wire(v) for k, v in pub.key_vals.items()
        },
        "expiredKeys": list(pub.expired_keys),
        "area": pub.area,
    }
    if pub.nodes is not None:
        out["nodeIds"] = list(pub.nodes)
    if pub.tobe_updated_keys is not None:
        out["tobeUpdatedKeys"] = list(pub.tobe_updated_keys)
    if pub.flood_root_id is not None:
        out["floodRootId"] = pub.flood_root_id
    return out


def _publication_from_wire(d: Dict):
    from openr_tpu.types import Publication

    return Publication(
        key_vals={
            k: _value_from_wire(v)
            for k, v in d.get("keyVals", {}).items()
        },
        expired_keys=list(d.get("expiredKeys", [])),
        nodes=d.get("nodeIds"),
        tobe_updated_keys=d.get("tobeUpdatedKeys"),
        flood_root_id=d.get("floodRootId"),
        area=d.get("area", "0"),
    )


def encode_publication(pub) -> bytes:
    return encode(PUBLICATION, _publication_to_wire(pub))


def decode_publication(data: bytes):
    return _publication_from_wire(decode(PUBLICATION, data))


def _key_set_params_to_wire(p) -> Dict:
    """Our KeySetParams.originator_id rides the wire as the reference's
    ``nodeIds`` traversal list (the reference appends each hop's node id
    for loop suppression; the framework tracks only the sender)."""
    out: Dict = {
        "keyVals": {
            k: _value_to_wire(v) for k, v in p.key_vals.items()
        },
        "solicitResponse": p.solicit_response,
    }
    if p.originator_id:
        out["nodeIds"] = [p.originator_id]
    if p.flood_root_id is not None:
        out["floodRootId"] = p.flood_root_id
    if p.timestamp_ms is not None:
        out["timestamp_ms"] = p.timestamp_ms
    return out


def _key_set_params_from_wire(d: Dict):
    from openr_tpu.types import KeySetParams

    node_ids = d.get("nodeIds") or []
    return KeySetParams(
        key_vals={
            k: _value_from_wire(v)
            for k, v in d.get("keyVals", {}).items()
        },
        solicit_response=d.get("solicitResponse", True),
        originator_id=node_ids[-1] if node_ids else "",
        flood_root_id=d.get("floodRootId"),
        timestamp_ms=d.get("timestamp_ms"),
    )


def encode_key_set_params(p) -> bytes:
    return encode(KEY_SET_PARAMS, _key_set_params_to_wire(p))


def decode_key_set_params(data: bytes):
    return _key_set_params_from_wire(decode(KEY_SET_PARAMS, data))


def _key_dump_params_to_wire(p) -> Dict:
    out: Dict = {
        "prefix": p.prefix,
        "originatorIds": set(p.originator_ids),
        "ignoreTtl": True,
        "doNotPublishValue": False,
    }
    if p.key_val_hashes is not None:
        out["keyValHashes"] = {
            k: _value_to_wire(v) for k, v in p.key_val_hashes.items()
        }
    if p.keys is not None:
        out["keys"] = list(p.keys)
    return out


def _key_dump_params_from_wire(d: Dict):
    from openr_tpu.types import KeyDumpParams

    hashes = d.get("keyValHashes")
    return KeyDumpParams(
        prefix=d.get("prefix", ""),
        originator_ids=set(d.get("originatorIds", ())),
        keys=d.get("keys"),
        key_val_hashes=(
            {k: _value_from_wire(v) for k, v in hashes.items()}
            if hashes is not None
            else None
        ),
    )


def encode_key_dump_params(p) -> bytes:
    return encode(KEY_DUMP_PARAMS, _key_dump_params_to_wire(p))


def decode_key_dump_params(data: bytes):
    return _key_dump_params_from_wire(decode(KEY_DUMP_PARAMS, data))


# -- Network.thrift schemas (shared by FibService and Spark wires) -------

# reference: openr/if/Network.thrift:55-58
BINARY_ADDRESS = StructSchema(
    "BinaryAddress",
    (
        Field(1, ("binary",), "addr"),
        Field(3, ("string",), "ifName", optional=True),
    ),
)

# reference: openr/if/Network.thrift:60-63
IP_PREFIX = StructSchema(
    "IpPrefix",
    (
        Field(1, ("struct", BINARY_ADDRESS), "prefixAddress"),
        Field(2, ("i16",), "prefixLength"),
    ),
)

# reference: openr/if/Network.thrift:47-53
MPLS_ACTION = StructSchema(
    "MplsAction",
    (
        Field(1, ("i32",), "action"),
        Field(2, ("i32",), "swapLabel", optional=True),
        Field(3, ("list", ("i32",)), "pushLabels", optional=True),
    ),
)

# reference: openr/if/Network.thrift:65-96 (metric is field 51,
# area 53, neighborNodeName 54 — deliberately sparse ids)
NEXT_HOP = StructSchema(
    "NextHopThrift",
    (
        Field(1, ("struct", BINARY_ADDRESS), "address"),
        Field(2, ("i32",), "weight"),
        Field(3, ("struct", MPLS_ACTION), "mplsAction", optional=True),
        Field(51, ("i32",), "metric"),
        Field(53, ("string",), "area", optional=True),
        Field(54, ("string",), "neighborNodeName", optional=True),
    ),
)

# reference: openr/if/Network.thrift:121-135 (field 2 deprecated)
UNICAST_ROUTE = StructSchema(
    "UnicastRoute",
    (
        Field(1, ("struct", IP_PREFIX), "dest"),
        Field(3, ("i32",), "adminDistance", optional=True),
        Field(4, ("list", ("struct", NEXT_HOP)), "nextHops"),
        Field(5, ("i32",), "prefixType", optional=True),
        Field(6, ("binary",), "data", optional=True),
        Field(7, ("bool",), "doNotInstall"),
    ),
)

# reference: openr/if/Network.thrift:98-104
MPLS_ROUTE = StructSchema(
    "MplsRoute",
    (
        Field(1, ("i32",), "topLabel"),
        Field(3, ("i32",), "adminDistance", optional=True),
        Field(4, ("list", ("struct", NEXT_HOP)), "nextHops"),
    ),
)


def _bin_addr_to_wire(a) -> Dict:
    out: Dict = {"addr": a.addr}
    if a.if_name is not None:
        out["ifName"] = a.if_name
    return out


def _bin_addr_from_wire(d: Dict):
    from openr_tpu.types import BinaryAddress

    return BinaryAddress(addr=d.get("addr", b""), if_name=d.get("ifName"))


def _ip_prefix_to_wire(p) -> Dict:
    return {
        "prefixAddress": _bin_addr_to_wire(p.prefix_address),
        "prefixLength": p.prefix_length,
    }


def _ip_prefix_from_wire(d: Dict):
    from openr_tpu.types import IpPrefix

    return IpPrefix(
        prefix_address=_bin_addr_from_wire(d.get("prefixAddress", {})),
        prefix_length=d.get("prefixLength", 0),
    )


def _next_hop_to_wire(nh) -> Dict:
    out: Dict = {
        "address": _bin_addr_to_wire(nh.address),
        "weight": nh.weight,
        "metric": nh.metric,
    }
    if nh.area is not None:
        out["area"] = nh.area
    if nh.neighbor_node_name is not None:
        out["neighborNodeName"] = nh.neighbor_node_name
    if nh.mpls_action is not None:
        act: Dict = {"action": int(nh.mpls_action.action)}
        if nh.mpls_action.swap_label is not None:
            act["swapLabel"] = nh.mpls_action.swap_label
        if nh.mpls_action.push_labels is not None:
            act["pushLabels"] = list(nh.mpls_action.push_labels)
        out["mplsAction"] = act
    return out


def _next_hop_from_wire(d: Dict):
    from openr_tpu.types import MplsAction, MplsActionCode, NextHop

    action = None
    act = d.get("mplsAction")
    if act is not None:
        action = MplsAction(
            action=MplsActionCode(act.get("action", 0)),
            swap_label=act.get("swapLabel"),
            push_labels=(
                tuple(act["pushLabels"])
                if act.get("pushLabels") is not None
                else None
            ),
        )
    return NextHop(
        address=_bin_addr_from_wire(d.get("address", {})),
        weight=d.get("weight", 0),
        mpls_action=action,
        metric=d.get("metric", 0),
        area=d.get("area"),
        neighbor_node_name=d.get("neighborNodeName"),
    )


def _unicast_route_to_wire(r) -> Dict:
    out: Dict = {
        "dest": _ip_prefix_to_wire(r.dest),
        "nextHops": [_next_hop_to_wire(nh) for nh in r.next_hops],
        "doNotInstall": r.do_not_install,
    }
    if r.admin_distance is not None:
        out["adminDistance"] = int(r.admin_distance)
    if r.prefix_type is not None:
        out["prefixType"] = int(r.prefix_type)
    if r.data is not None:
        out["data"] = r.data
    return out


def _unicast_route_from_wire(d: Dict):
    from openr_tpu.types import AdminDistance, PrefixType, UnicastRoute

    return UnicastRoute(
        dest=_ip_prefix_from_wire(d.get("dest", {})),
        next_hops=tuple(
            _next_hop_from_wire(nh) for nh in d.get("nextHops", [])
        ),
        admin_distance=(
            AdminDistance(d["adminDistance"])
            if d.get("adminDistance") is not None
            else None
        ),
        prefix_type=(
            PrefixType(d["prefixType"])
            if d.get("prefixType") is not None
            else None
        ),
        data=d.get("data"),
        do_not_install=d.get("doNotInstall", False),
    )


def _mpls_route_to_wire(r) -> Dict:
    out: Dict = {
        "topLabel": r.top_label,
        "nextHops": [_next_hop_to_wire(nh) for nh in r.next_hops],
    }
    if r.admin_distance is not None:
        out["adminDistance"] = int(r.admin_distance)
    return out


def _mpls_route_from_wire(d: Dict):
    from openr_tpu.types import AdminDistance, MplsRoute

    return MplsRoute(
        top_label=d.get("topLabel", 0),
        next_hops=tuple(
            _next_hop_from_wire(nh) for nh in d.get("nextHops", [])
        ),
        admin_distance=(
            AdminDistance(d["adminDistance"])
            if d.get("adminDistance") is not None
            else None
        ),
    )


# -- Lsdb.thrift schemas (the ctrl surface's adjacency/prefix dumps) -----

# reference: openr/if/Lsdb.thrift Adjacency (ids 1,2,3,5,4,6,7,8,9,10,11
# — declaration order has nextHopV4 at id 5 between 3 and 4)
ADJACENCY = StructSchema(
    "Adjacency",
    (
        Field(1, ("string",), "otherNodeName"),
        Field(2, ("string",), "ifName"),
        Field(3, ("struct", BINARY_ADDRESS), "nextHopV6"),
        Field(5, ("struct", BINARY_ADDRESS), "nextHopV4"),
        Field(4, ("i32",), "metric"),
        Field(6, ("i32",), "adjLabel"),
        Field(7, ("bool",), "isOverloaded"),
        Field(8, ("i32",), "rtt"),
        Field(9, ("i64",), "timestamp"),
        Field(10, ("i64",), "weight"),
        Field(11, ("string",), "otherIfName"),
    ),
)

# reference: openr/if/Lsdb.thrift AdjacencyDatabase (perfEvents omitted)
ADJACENCY_DATABASE = StructSchema(
    "AdjacencyDatabase",
    (
        Field(1, ("string",), "thisNodeName"),
        Field(2, ("bool",), "isOverloaded"),
        Field(3, ("list", ("struct", ADJACENCY)), "adjacencies"),
        Field(4, ("i32",), "nodeLabel"),
        Field(6, ("string",), "area"),
    ),
)

# reference: openr/if/Lsdb.thrift PrefixMetrics
PREFIX_METRICS = StructSchema(
    "PrefixMetrics",
    (
        Field(1, ("i32",), "version"),
        Field(2, ("i32",), "path_preference"),
        Field(3, ("i32",), "source_preference"),
        Field(4, ("i32",), "distance"),
    ),
)

# reference: openr/if/Lsdb.thrift PrefixEntry (declaration order
# 1,2,3,4,7,5,6,8,9,10,11,12; deprecated mv/ephemeral omitted)
PREFIX_ENTRY = StructSchema(
    "PrefixEntry",
    (
        Field(1, ("struct", IP_PREFIX), "prefix"),
        Field(2, ("i32",), "type"),
        Field(3, ("binary",), "data", optional=True),
        Field(4, ("i32",), "forwardingType"),
        Field(7, ("i32",), "forwardingAlgorithm"),
        Field(8, ("i64",), "minNexthop", optional=True),
        Field(9, ("i32",), "prependLabel", optional=True),
        Field(10, ("struct", PREFIX_METRICS), "metrics"),
        Field(11, ("set", ("string",)), "tags"),
        Field(12, ("list", ("string",)), "area_stack"),
    ),
)

# reference: openr/if/Lsdb.thrift PrefixDatabase (numbering intentional:
# 1,3,5,7; perfEvents omitted)
PREFIX_DATABASE = StructSchema(
    "PrefixDatabase",
    (
        Field(1, ("string",), "thisNodeName"),
        Field(3, ("list", ("struct", PREFIX_ENTRY)), "prefixEntries"),
        Field(5, ("bool",), "deletePrefix"),
        Field(7, ("string",), "area"),
    ),
)

# reference: openr/if/Fib.thrift RouteDatabase (perfEvents omitted)
ROUTE_DATABASE = StructSchema(
    "RouteDatabase",
    (
        Field(1, ("string",), "thisNodeName"),
        Field(4, ("list", ("struct", UNICAST_ROUTE)), "unicastRoutes"),
        Field(5, ("list", ("struct", MPLS_ROUTE)), "mplsRoutes"),
    ),
)

# reference: openr/if/KvStore.thrift PeerSpec
PEER_SPEC = StructSchema(
    "PeerSpec",
    (
        Field(1, ("string",), "peerAddr"),
        Field(2, ("string",), "cmdUrl"),
        Field(4, ("i32",), "ctrlPort"),
    ),
)

# reference: openr/if/Spark.thrift OpenrVersions
OPENR_VERSIONS = StructSchema(
    "OpenrVersions",
    (
        Field(1, ("i32",), "version"),
        Field(2, ("i32",), "lowestSupportedVersion"),
    ),
)

# reference: openr/if/OpenrCtrl.thrift exception OpenrError
OPENR_ERROR = StructSchema(
    "OpenrError", (Field(1, ("string",), "message"),)
)


def _adjacency_to_wire(a) -> Dict:
    return {
        "otherNodeName": a.other_node_name,
        "ifName": a.if_name,
        "nextHopV6": _bin_addr_to_wire(a.next_hop_v6),
        "nextHopV4": _bin_addr_to_wire(a.next_hop_v4),
        "metric": int(a.metric),
        "adjLabel": int(a.adj_label),
        "isOverloaded": bool(a.is_overloaded),
        "rtt": int(a.rtt),
        "timestamp": int(a.timestamp),
        "weight": int(a.weight),
        "otherIfName": a.other_if_name,
    }


def _adjacency_from_wire(d: Dict):
    from openr_tpu.types import Adjacency

    return Adjacency(
        other_node_name=d.get("otherNodeName", ""),
        if_name=d.get("ifName", ""),
        next_hop_v6=_bin_addr_from_wire(d.get("nextHopV6", {})),
        next_hop_v4=_bin_addr_from_wire(d.get("nextHopV4", {})),
        metric=d.get("metric", 1),
        adj_label=d.get("adjLabel", 0),
        is_overloaded=d.get("isOverloaded", False),
        rtt=d.get("rtt", 0),
        timestamp=d.get("timestamp", 0),
        weight=d.get("weight", 1),
        other_if_name=d.get("otherIfName", ""),
    )


def adjacency_db_to_wire(db) -> Dict:
    return {
        "thisNodeName": db.this_node_name,
        "isOverloaded": bool(db.is_overloaded),
        "adjacencies": [
            _adjacency_to_wire(a) for a in db.adjacencies
        ],
        "nodeLabel": int(db.node_label),
        "area": db.area,
    }


def adjacency_db_from_wire(d: Dict):
    from openr_tpu.types import AdjacencyDatabase

    return AdjacencyDatabase(
        this_node_name=d.get("thisNodeName", ""),
        is_overloaded=d.get("isOverloaded", False),
        adjacencies=tuple(
            _adjacency_from_wire(a) for a in d.get("adjacencies", [])
        ),
        node_label=d.get("nodeLabel", 0),
        area=d.get("area", "0"),
    )


def _prefix_entry_to_wire(e) -> Dict:
    out: Dict = {
        "prefix": _ip_prefix_to_wire(e.prefix),
        "type": int(e.type.value if hasattr(e.type, "value") else e.type),
        "forwardingType": int(
            e.forwarding_type.value
            if hasattr(e.forwarding_type, "value")
            else e.forwarding_type
        ),
        "forwardingAlgorithm": int(
            e.forwarding_algorithm.value
            if hasattr(e.forwarding_algorithm, "value")
            else e.forwarding_algorithm
        ),
        "metrics": {
            "version": e.metrics.version,
            "path_preference": e.metrics.path_preference,
            "source_preference": e.metrics.source_preference,
            "distance": e.metrics.distance,
        },
        "tags": sorted(e.tags),
        "area_stack": list(e.area_stack),
    }
    if e.data is not None:
        out["data"] = e.data
    if e.min_nexthop is not None:
        out["minNexthop"] = int(e.min_nexthop)
    if e.prepend_label is not None:
        out["prependLabel"] = int(e.prepend_label)
    return out


def _prefix_entry_from_wire(d: Dict):
    from openr_tpu.types import (
        PrefixEntry,
        PrefixForwardingAlgorithm,
        PrefixForwardingType,
        PrefixMetrics,
        PrefixType,
    )

    m = d.get("metrics", {})
    return PrefixEntry(
        prefix=_ip_prefix_from_wire(d.get("prefix", {})),
        type=PrefixType(d.get("type", PrefixType.DEFAULT.value)),
        forwarding_type=PrefixForwardingType(d.get("forwardingType", 0)),
        forwarding_algorithm=PrefixForwardingAlgorithm(
            d.get("forwardingAlgorithm", 0)
        ),
        min_nexthop=d.get("minNexthop"),
        prepend_label=d.get("prependLabel"),
        metrics=PrefixMetrics(
            version=m.get("version", 1),
            path_preference=m.get("path_preference", 0),
            source_preference=m.get("source_preference", 0),
            distance=m.get("distance", 0),
        ),
        tags=tuple(sorted(d.get("tags", ()))),
        area_stack=tuple(d.get("area_stack", ())),
        data=d.get("data"),
    )


def prefix_db_to_wire(db) -> Dict:
    return {
        "thisNodeName": db.this_node_name,
        "prefixEntries": [
            _prefix_entry_to_wire(e) for e in db.prefix_entries
        ],
        "deletePrefix": bool(db.delete_prefix),
        "area": db.area,
    }


def prefix_db_from_wire(d: Dict):
    from openr_tpu.types import PrefixDatabase

    return PrefixDatabase(
        this_node_name=d.get("thisNodeName", ""),
        prefix_entries=tuple(
            _prefix_entry_from_wire(e) for e in d.get("prefixEntries", [])
        ),
        delete_prefix=d.get("deletePrefix", False),
        area=d.get("area", "0"),
    )


def route_db_to_wire(db) -> Dict:
    return {
        "thisNodeName": db.this_node_name,
        "unicastRoutes": [
            _unicast_route_to_wire(r) for r in db.unicast_routes
        ],
        "mplsRoutes": [_mpls_route_to_wire(r) for r in db.mpls_routes],
    }


def route_db_from_wire(d: Dict):
    from openr_tpu.types.fib import RouteDatabase

    return RouteDatabase(
        this_node_name=d.get("thisNodeName", ""),
        unicast_routes=[
            _unicast_route_from_wire(r)
            for r in d.get("unicastRoutes", [])
        ],
        mpls_routes=[
            _mpls_route_from_wire(r) for r in d.get("mplsRoutes", [])
        ],
    )


# -- Dual.thrift schemas (flood-optimization over the peer wire) ---------

# reference: openr/if/Dual.thrift:24-31
DUAL_MESSAGE = StructSchema(
    "DualMessage",
    (
        Field(1, ("string",), "dstId"),
        Field(2, ("i64",), "distance"),
        Field(3, ("i32",), "type"),
    ),
)

# reference: openr/if/Dual.thrift:33-38
DUAL_MESSAGES = StructSchema(
    "DualMessages",
    (
        Field(1, ("string",), "srcId"),
        Field(2, ("list", ("struct", DUAL_MESSAGE)), "messages"),
    ),
)

# reference: openr/if/KvStore.thrift:155-165
FLOOD_TOPO_SET_PARAMS = StructSchema(
    "FloodTopoSetParams",
    (
        Field(1, ("string",), "rootId"),
        Field(2, ("string",), "srcId"),
        Field(3, ("bool",), "setChild"),
        Field(4, ("bool",), "allRoots", optional=True),
    ),
)


def dual_messages_to_wire(src_id: str, msgs) -> Dict:
    return {
        "srcId": src_id,
        "messages": [
            {
                "dstId": m.dst_id,
                "distance": int(m.distance),
                "type": int(m.type),
            }
            for m in msgs
        ],
    }


def dual_messages_from_wire(d: Dict):
    from openr_tpu.dual.dual import DualMessage, DualMessageType

    return d.get("srcId", ""), [
        DualMessage(
            dst_id=m.get("dstId", ""),
            distance=m.get("distance", 0),
            type=DualMessageType(m.get("type", 1)),
        )
        for m in d.get("messages", [])
    ]


# -- OpenrCtrl tail surface (perf, links, spark, spt, rib policy, ---------
# -- advertised/received routes, build info, areas, config) ---------------

# reference: openr/if/Lsdb.thrift:24-32
PERF_EVENT = StructSchema(
    "PerfEvent",
    (
        Field(1, ("string",), "nodeName"),
        Field(2, ("string",), "eventDescr"),
        Field(3, ("i64",), "unixTs"),
    ),
)

PERF_EVENTS = StructSchema(
    "PerfEvents",
    (Field(1, ("list", ("struct", PERF_EVENT)), "events"),),
)

# reference: openr/if/Fib.thrift:36-39
PERF_DATABASE = StructSchema(
    "PerfDatabase",
    (
        Field(1, ("string",), "thisNodeName"),
        Field(2, ("list", ("struct", PERF_EVENTS)), "eventInfo"),
    ),
)

# reference: openr/if/Lsdb.thrift:47-52
INTERFACE_INFO = StructSchema(
    "InterfaceInfo",
    (
        Field(1, ("bool",), "isUp"),
        Field(2, ("i64",), "ifIndex"),
        Field(5, ("list", ("struct", IP_PREFIX)), "networks"),
    ),
)

# reference: openr/if/LinkMonitor.thrift:18-23
INTERFACE_DETAILS = StructSchema(
    "InterfaceDetails",
    (
        Field(1, ("struct", INTERFACE_INFO), "info"),
        Field(2, ("bool",), "isOverloaded"),
        Field(3, ("i32",), "metricOverride", optional=True),
        Field(4, ("i64",), "linkFlapBackOffMs", optional=True),
    ),
)

# reference: openr/if/LinkMonitor.thrift:25-30 (numbering 1,3,6 is the
# IDL's own)
DUMP_LINKS_REPLY = StructSchema(
    "DumpLinksReply",
    (
        Field(1, ("string",), "thisNodeName"),
        Field(3, ("bool",), "isOverloaded"),
        Field(6, ("map", ("string",), ("struct", INTERFACE_DETAILS)),
              "interfaceDetails"),
    ),
)

# reference: openr/if/LinkMonitor.thrift:67-85
BUILD_INFO = StructSchema(
    "BuildInfo",
    (
        Field(1, ("string",), "buildUser"),
        Field(2, ("string",), "buildTime"),
        Field(3, ("i64",), "buildTimeUnix"),
        Field(4, ("string",), "buildHost"),
        Field(5, ("string",), "buildPath"),
        Field(6, ("string",), "buildRevision"),
        Field(7, ("i64",), "buildRevisionCommitTimeUnix"),
        Field(8, ("string",), "buildUpstreamRevision"),
        Field(9, ("i64",), "buildUpstreamRevisionCommitTimeUnix"),
        Field(10, ("string",), "buildPackageName"),
        Field(11, ("string",), "buildPackageVersion"),
        Field(12, ("string",), "buildPackageRelease"),
        Field(13, ("string",), "buildPlatform"),
        Field(14, ("string",), "buildRule"),
        Field(15, ("string",), "buildType"),
        Field(16, ("string",), "buildTool"),
        Field(17, ("string",), "buildMode"),
    ),
)

# reference: openr/if/Spark.thrift:141-171
SPARK_NEIGHBOR = StructSchema(
    "SparkNeighbor",
    (
        Field(1, ("string",), "nodeName"),
        Field(2, ("string",), "state"),
        Field(3, ("string",), "area"),
        Field(4, ("struct", BINARY_ADDRESS), "transportAddressV6"),
        Field(5, ("struct", BINARY_ADDRESS), "transportAddressV4"),
        Field(6, ("i32",), "openrCtrlThriftPort"),
        Field(7, ("i32",), "kvStoreCmdPort"),
        Field(8, ("string",), "remoteIfName"),
        Field(9, ("string",), "localIfName"),
        Field(10, ("i64",), "rttUs"),
        Field(11, ("i32",), "label"),
    ),
)

# reference: openr/if/KvStore.thrift:201-204
AREAS_CONFIG = StructSchema(
    "AreasConfig",
    (Field(1, ("set", ("string",)), "areas"),),
)

# reference: openr/if/KvStore.thrift:171-180
SPT_INFO = StructSchema(
    "SptInfo",
    (
        Field(1, ("bool",), "passive"),
        Field(2, ("i64",), "cost"),
        Field(3, ("string",), "parent", optional=True),
        Field(4, ("set", ("string",)), "children"),
    ),
)

# reference: openr/if/Dual.thrift:42-48
DUAL_PER_NEIGHBOR_COUNTERS = StructSchema(
    "DualPerNeighborCounters",
    (
        Field(1, ("i64",), "pktSent"),
        Field(2, ("i64",), "pktRecv"),
        Field(3, ("i64",), "msgSent"),
        Field(4, ("i64",), "msgRecv"),
    ),
)

# reference: openr/if/Dual.thrift:51-60
DUAL_PER_ROOT_COUNTERS = StructSchema(
    "DualPerRootCounters",
    (
        Field(1, ("i64",), "querySent"),
        Field(2, ("i64",), "queryRecv"),
        Field(3, ("i64",), "replySent"),
        Field(4, ("i64",), "replyRecv"),
        Field(5, ("i64",), "updateSent"),
        Field(6, ("i64",), "updateRecv"),
        Field(7, ("i64",), "totalSent"),
        Field(8, ("i64",), "totalRecv"),
    ),
)

# reference: openr/if/Dual.thrift:72-75
DUAL_COUNTERS = StructSchema(
    "DualCounters",
    (
        Field(1, ("map", ("string",),
                 ("struct", DUAL_PER_NEIGHBOR_COUNTERS)),
              "neighborCounters"),
        Field(2, ("map", ("string",),
                 ("map", ("string",),
                  ("struct", DUAL_PER_ROOT_COUNTERS))),
              "rootCounters"),
    ),
)

# reference: openr/if/KvStore.thrift:188-197
SPT_INFOS = StructSchema(
    "SptInfos",
    (
        Field(1, ("map", ("string",), ("struct", SPT_INFO)), "infos"),
        Field(2, ("struct", DUAL_COUNTERS), "counters"),
        Field(3, ("string",), "floodRootId", optional=True),
        Field(4, ("set", ("string",)), "floodPeers"),
    ),
)

# reference: openr/if/OpenrCtrl.thrift:31-68
NODE_AND_AREA = StructSchema(
    "NodeAndArea",
    (
        Field(1, ("string",), "node"),
        Field(2, ("string",), "area"),
    ),
)

ADVERTISED_ROUTE = StructSchema(
    "AdvertisedRoute",
    (
        Field(1, ("i32",), "key"),
        Field(2, ("struct", PREFIX_ENTRY), "route"),
    ),
)

ADVERTISED_ROUTE_DETAIL = StructSchema(
    "AdvertisedRouteDetail",
    (
        Field(1, ("struct", IP_PREFIX), "prefix"),
        Field(2, ("i32",), "bestKey"),
        Field(3, ("list", ("i32",)), "bestKeys"),
        Field(4, ("list", ("struct", ADVERTISED_ROUTE)), "routes"),
    ),
)

ADVERTISED_ROUTE_FILTER = StructSchema(
    "AdvertisedRouteFilter",
    (
        Field(1, ("list", ("struct", IP_PREFIX)), "prefixes",
              optional=True),
        Field(2, ("i32",), "prefixType", optional=True),
    ),
)

RECEIVED_ROUTE = StructSchema(
    "ReceivedRoute",
    (
        Field(1, ("struct", NODE_AND_AREA), "key"),
        Field(2, ("struct", PREFIX_ENTRY), "route"),
    ),
)

RECEIVED_ROUTE_DETAIL = StructSchema(
    "ReceivedRouteDetail",
    (
        Field(1, ("struct", IP_PREFIX), "prefix"),
        Field(2, ("struct", NODE_AND_AREA), "bestKey"),
        Field(3, ("list", ("struct", NODE_AND_AREA)), "bestKeys"),
        Field(4, ("list", ("struct", RECEIVED_ROUTE)), "routes"),
    ),
)

RECEIVED_ROUTE_FILTER = StructSchema(
    "ReceivedRouteFilter",
    (
        Field(1, ("list", ("struct", IP_PREFIX)), "prefixes",
              optional=True),
        Field(2, ("string",), "nodeName", optional=True),
        Field(3, ("string",), "areaName", optional=True),
    ),
)

# reference: openr/if/OpenrCtrl.thrift:84-162 (RibPolicy family)
RIB_ROUTE_MATCHER = StructSchema(
    "RibRouteMatcher",
    (Field(1, ("list", ("struct", IP_PREFIX)), "prefixes",
           optional=True),),
)

RIB_ROUTE_ACTION_WEIGHT = StructSchema(
    "RibRouteActionWeight",
    (
        Field(2, ("i32",), "default_weight"),
        Field(3, ("map", ("string",), ("i32",)), "area_to_weight"),
        Field(4, ("map", ("string",), ("i32",)), "neighbor_to_weight"),
    ),
)

RIB_ROUTE_ACTION = StructSchema(
    "RibRouteAction",
    (Field(1, ("struct", RIB_ROUTE_ACTION_WEIGHT), "set_weight",
           optional=True),),
)

RIB_POLICY_STATEMENT = StructSchema(
    "RibPolicyStatement",
    (
        Field(1, ("string",), "name"),
        Field(2, ("struct", RIB_ROUTE_MATCHER), "matcher"),
        Field(3, ("struct", RIB_ROUTE_ACTION), "action"),
    ),
)

RIB_POLICY = StructSchema(
    "RibPolicy",
    (
        Field(1, ("list", ("struct", RIB_POLICY_STATEMENT)),
              "statements"),
        Field(2, ("i32",), "ttl_secs"),
    ),
)

# reference: openr/if/OpenrConfig.thrift:176-180
AREA_CONFIG = StructSchema(
    "AreaConfig",
    (
        Field(1, ("string",), "area_id"),
        Field(2, ("list", ("string",)), "interface_regexes"),
        Field(3, ("list", ("string",)), "neighbor_regexes"),
    ),
)

# reference: openr/if/OpenrConfig.thrift:24-38
KVSTORE_CONFIG = StructSchema(
    "KvstoreConfig",
    (
        Field(1, ("i32",), "key_ttl_ms"),
        Field(2, ("i32",), "sync_interval_s"),
        Field(3, ("i32",), "ttl_decrement_ms"),
        Field(8, ("bool",), "enable_flood_optimization",
              optional=True),
        Field(9, ("bool",), "is_flood_root", optional=True),
    ),
)

# reference: openr/if/OpenrConfig.thrift:40-47
LINK_MONITOR_CONFIG = StructSchema(
    "LinkMonitorConfig",
    (
        Field(1, ("i32",), "linkflap_initial_backoff_ms"),
        Field(2, ("i32",), "linkflap_max_backoff_ms"),
        Field(3, ("bool",), "use_rtt_metric"),
        Field(4, ("list", ("string",)), "include_interface_regexes"),
        Field(5, ("list", ("string",)), "exclude_interface_regexes"),
        Field(6, ("list", ("string",)),
              "redistribute_interface_regexes"),
    ),
)

# reference: openr/if/OpenrConfig.thrift:57-68
SPARK_CONFIG = StructSchema(
    "SparkConfig",
    (
        Field(1, ("i32",), "neighbor_discovery_port"),
        Field(2, ("i32",), "hello_time_s"),
        Field(3, ("i32",), "fastinit_hello_time_ms"),
        Field(4, ("i32",), "keepalive_time_s"),
        Field(5, ("i32",), "hold_time_s"),
        Field(6, ("i32",), "graceful_restart_time_s"),
    ),
)

# reference: openr/if/OpenrConfig.thrift:70-74
WATCHDOG_CONFIG = StructSchema(
    "WatchdogConfig",
    (
        Field(1, ("i32",), "interval_s"),
        Field(2, ("i32",), "thread_timeout_s"),
        Field(3, ("i32",), "max_memory_mb"),
    ),
)

# reference: openr/if/OpenrConfig.thrift:238-314. The field ids cover
# the surface this framework models; ids absent here (BGP translation,
# originated prefixes, eor, prefix allocation details) are simply not
# emitted — a stock decoder applies IDL defaults, the same
# forward-compatibility contract this codec's own decoder honours.
OPENR_CONFIG = StructSchema(
    "OpenrConfig",
    (
        Field(1, ("string",), "node_name"),
        Field(2, ("string",), "domain"),
        Field(3, ("list", ("struct", AREA_CONFIG)), "areas"),
        Field(4, ("string",), "listen_addr"),
        Field(5, ("i32",), "openr_ctrl_port"),
        Field(6, ("bool",), "dryrun", optional=True),
        Field(7, ("bool",), "enable_v4", optional=True),
        Field(8, ("bool",), "enable_netlink_fib_handler",
              optional=True),
        Field(11, ("i32",), "prefix_forwarding_type"),
        Field(12, ("i32",), "prefix_forwarding_algorithm"),
        Field(13, ("bool",), "enable_segment_routing", optional=True),
        Field(15, ("struct", KVSTORE_CONFIG), "kvstore_config"),
        Field(16, ("struct", LINK_MONITOR_CONFIG),
              "link_monitor_config"),
        Field(17, ("struct", SPARK_CONFIG), "spark_config"),
        Field(18, ("bool",), "enable_watchdog", optional=True),
        Field(19, ("struct", WATCHDOG_CONFIG), "watchdog_config",
              optional=True),
        Field(22, ("bool",), "enable_ordered_fib_programming",
              optional=True),
        Field(24, ("bool",), "enable_rib_policy"),
        Field(51, ("bool",), "enable_best_route_selection"),
    ),
)
