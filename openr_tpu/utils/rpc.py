"""Framed wire-codec RPC: the cross-process transport substrate.

Plays the role fbthrift RPC plays in the reference (KvStoreService peer
sync, FibService platform agent): a length-framed TCP protocol whose
blobs are encoded with the canonical wire codec, so schema objects
(Value, Publication, UnicastRoute, ...) travel losslessly between
processes.

Frame layout:
  u32 total_len | u8 nblobs | ( u32 blob_len | blob_bytes ) * nblobs

Request blobs:  [method_name_utf8, wire(arg0), wire(arg1), ...]
Response blobs: [status_utf8 ("ok" | "err:<repr>"), wire(result)]

Servers register methods with their argument/result schemas; decoding is
schema-directed on both sides.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from openr_tpu.utils import wire


def _pack_frame(blobs: Sequence[bytes]) -> bytes:
    body = bytes([len(blobs)]) + b"".join(
        struct.pack(">I", len(b)) + b for b in blobs
    )
    return struct.pack(">I", len(body)) + body


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# Frame sanity cap, deliberately below 0x16030100 (a TLS ClientHello's
# first bytes read as a length prefix): a plain server hangs up on a
# TLS probe IMMEDIATELY instead of blocking on a ~369MB phantom
# payload, which is what makes every client's secure->plain fallback
# cost ~1ms rather than a probe timeout. The single authoritative
# definition — ctrl/server.py imports it.
MAX_FRAME = 128 * 1024 * 1024


def _recv_frame(sock: socket.socket) -> Optional[List[bytes]]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (total,) = struct.unpack(">I", header)
    if total > MAX_FRAME:
        return None  # garbage or a TLS handshake: hang up
    body = _recv_exact(sock, total)
    if body is None:
        return None
    nblobs = body[0]
    blobs: List[bytes] = []
    pos = 1
    for _ in range(nblobs):
        (blen,) = struct.unpack(">I", body[pos : pos + 4])
        pos += 4
        blobs.append(body[pos : pos + blen])
        pos += blen
    return blobs


def wrap_server_connection(sock, ssl_context, handshake_timeout=5.0):
    """Server-side TLS wrap with a BOUNDED handshake, for use on the
    per-connection handler thread — never on the accept thread, where a
    client that connects and sends nothing would block every subsequent
    accept and wedge shutdown. Returns the wrapped socket, or None when
    the handshake fails/times out (caller just returns)."""
    if ssl_context is None:
        return sock
    import ssl

    old = sock.gettimeout()
    sock.settimeout(handshake_timeout)
    try:
        sock = ssl_context.wrap_socket(sock, server_side=True)
    except (ssl.SSLError, OSError):
        try:
            sock.close()
        except OSError:
            pass
        return None
    sock.settimeout(old)
    return sock


def probe_tls(host: str, port: int, timeout_s: float = 10.0):
    """Secure-then-plain detection (reference client factory,
    openr_client.py:27-140): returns a permissive client SSLContext
    (self-signed accepted — the reference's onbox mode) when the server
    completes a TLS handshake, else None. The probe handshake is
    bounded; a plain server hangs up instantly on the ClientHello (its
    bytes exceed the frame cap), so the fallback costs ~1ms."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    try:
        probe = socket.create_connection((host, port), timeout=timeout_s)
        # generous handshake bound: a PLAIN server hangs up on the
        # ClientHello instantly (frame cap), so only genuinely slow TLS
        # handshakes spend time here — misreading one as "plain" would
        # downgrade to a connection the TLS server then rejects
        probe.settimeout(min(5.0, timeout_s))
        try:
            probe = ctx.wrap_socket(probe, server_hostname=host)
            probe.close()
            return ctx
        except (ssl.SSLError, OSError):
            try:
                probe.close()
            except OSError:
                pass
    except OSError:
        pass  # connection-level failure: let the real client raise it
    return None


def apply_bind_family(server_cls, host: str) -> None:
    """Pick the socketserver address family from the bind host: a v6
    host (incl. "::" dual-stack) needs AF_INET6 — link-local neighbor
    transports can only dial a v6 listener. Shared by every TCP server
    in the framework so v6-bind fixes happen in one place."""
    if ":" in host:
        server_cls.address_family = socket.AF_INET6


class RpcServer:
    """Threaded TCP server dispatching registered wire-RPC methods.

    ``ssl_context``: serve TLS (reference: the ctrl thrift server's
    optional TLS with the acceptable-peers list; the py client factory
    tries secure then falls back to plain, openr_client.py:27-140).
    Accepted sockets are wrapped server-side; a plain-text client
    connecting to a TLS server fails its first frame and falls back."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None, listen: bool = True):
        self._methods: Dict[str, Tuple[Callable, List[Any], Any]] = {}
        self._active: set = set()
        self._active_lock = threading.Lock()
        self._ssl_context = ssl_context
        outer = self
        if not listen:
            # pure dispatcher for byte-sniffing demultiplexers: no
            # socket is bound, start()/stop() are no-ops
            self._server = None
            self._thread = None
            self.port = 0
            return

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                wrapped = wrap_server_connection(
                    self.request, outer._ssl_context
                )
                if wrapped is None:
                    return
                outer.serve_connection(wrapped)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        apply_bind_family(_Server, host)
        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"rpc-server:{self.port}",
            daemon=True,
        )

    def serve_connection(self, sock) -> None:
        """Run the request loop on an already-accepted socket — the
        shared entry for the own listener's handler AND external
        demultiplexers (the dual-stack peer server hands sniffed
        connections here directly, no loopback splice)."""
        with self._active_lock:
            self._active.add(sock)
        try:
            while True:
                try:
                    blobs = _recv_frame(sock)
                except (ConnectionError, OSError):
                    return
                if blobs is None:
                    return
                self._dispatch(sock, blobs)
        finally:
            with self._active_lock:
                self._active.discard(sock)

    def register(
        self,
        name: str,
        fn: Callable,
        arg_types: List[Any],
        result_type: Any = None,
    ) -> None:
        self._methods[name] = (fn, arg_types, result_type)

    def start(self) -> None:
        if self._thread is not None:
            self._thread.start()

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        # a stopped server must stop serving: close established
        # connections too, so peers detect the death instead of talking
        # to a zombie handler thread
        with self._active_lock:
            for sock in list(self._active):
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            self._active.clear()

    def _dispatch(self, sock: socket.socket, blobs: List[bytes]) -> None:
        try:
            name = blobs[0].decode("utf-8")
            entry = self._methods.get(name)
            if entry is None:
                raise KeyError(f"no rpc method {name!r}")
            fn, arg_types, _ = entry
            args = [
                wire.loads(blob, tp)
                for blob, tp in zip(blobs[1:], arg_types)
            ]
            result = fn(*args)
            response = [b"ok", wire.dumps(result)]
        except Exception as e:  # noqa: BLE001 - relayed to the caller
            response = [f"err:{e!r}".encode("utf-8"), wire.dumps(None)]
        try:
            sock.sendall(_pack_frame(response))
        except (ConnectionError, OSError):
            pass


class RpcClient:
    """Blocking wire-RPC client with per-call mutex (one in-flight call
    per connection, like a thrift channel)."""

    def __init__(
        self, host: str, port: int, timeout_s: float = 10.0,
        ssl_context=None,
    ):
        self._addr = (host, port)
        self._timeout = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._ssl_context = ssl_context

    def _connect(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(
                self._addr, timeout=self._timeout
            )
            if self._ssl_context is not None:
                sock = self._ssl_context.wrap_socket(
                    sock, server_hostname=self._addr[0]
                )
            self._sock = sock
        return self._sock

    def call(self, name: str, args: Sequence[Any], result_type: Any = None):
        blobs = [name.encode("utf-8")] + [wire.dumps(a) for a in args]
        with self._lock:
            try:
                sock = self._connect()
                sock.sendall(_pack_frame(blobs))
                response = _recv_frame(sock)
            except (ConnectionError, OSError):
                self.close()
                raise
            if response is None:
                self.close()
                raise ConnectionError("rpc: server closed connection")
        status = response[0].decode("utf-8")
        if status != "ok":
            raise RuntimeError(f"rpc {name}: {status[4:]}")
        return wire.loads(response[1], result_type)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


def connect_with_tls_fallback(
    host: str, port: int, timeout_s: float = 10.0
) -> RpcClient:
    """The reference client factory's behavior (openr_client.py:
    get_openr_ctrl_client tries a secure client, falls back to
    plain-text for onbox use)."""
    return RpcClient(
        host, port, timeout_s,
        ssl_context=probe_tls(host, port, timeout_s),
    )


def peek_first_bytes(sock, n: int, deadline_s: float = 30.0):
    """Wait until the first ``n`` bytes of a connection are buffered
    and return them WITHOUT consuming (MSG_PEEK). Clients that write a
    frame header and payload in separate sends (several stock thrift
    transports do) need more than one peek. Returns None on timeout or
    hang-up. Shared by every dual-stack byte-sniffing listener
    (kvstore/dualstack.py, ctrl/server.py)."""
    import time as _time

    deadline = _time.monotonic() + deadline_s
    while True:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            return None
        sock.settimeout(remaining)
        try:
            head = sock.recv(n, socket.MSG_PEEK)
        except OSError:
            return None
        if not head:
            return None  # peer hung up
        if len(head) >= n:
            return head
        # partial arrival: yield briefly rather than hot-spinning on
        # MSG_PEEK (which does not consume and so returns immediately)
        _time.sleep(0.005)
