"""Lossless-enough JSON projection of schema objects for the ctrl/CLI
surface (the reference serializes thrift structs; we project dataclasses)."""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

from openr_tpu.types import BinaryAddress, IpPrefix


def to_jsonable(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (bytes, bytearray)):
        return {"__bytes__": bytes(obj).hex()}
    if isinstance(obj, enum.Enum):
        return obj.name
    if isinstance(obj, BinaryAddress):
        return obj.to_str() + (f"%{obj.if_name}" if obj.if_name else "")
    if isinstance(obj, IpPrefix):
        return obj.to_str()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_jsonable(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, dict):
        return {_key(k): to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        items = [to_jsonable(v) for v in obj]
        if isinstance(obj, (set, frozenset)):
            items.sort(key=repr)
        return items
    return repr(obj)


def _key(k: Any) -> str:
    if isinstance(k, str):
        return k
    if isinstance(k, (IpPrefix,)):
        return k.to_str()
    if isinstance(k, tuple):
        return "|".join(_key(x) for x in k)
    return str(k)
