"""Step detection over a noisy time series (RTT change detection).

Behavioral parity with the reference ``openr/common/StepDetector.h``:
fast and slow sliding-window means; when their relative difference rises
above ``upper_threshold`` percent we are on a step's rising edge, and when
it falls back below ``lower_threshold`` percent the step is confirmed and
reported via callback with the fast mean. A small absolute threshold
catches staircase drift the relative test misses. Spark uses this to
re-advertise adjacency RTT metrics only on genuine changes.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Tuple


@dataclass
class StepDetectorConfig:
    """reference: StepDetectorConfig in openr/if/OpenrConfig.thrift"""

    fast_window_size: int = 10
    slow_window_size: int = 60
    lower_threshold: float = 2.0  # percent
    upper_threshold: float = 5.0  # percent
    abs_threshold: float = 500.0  # same unit as the samples

    def __post_init__(self) -> None:
        assert self.lower_threshold < self.upper_threshold
        assert self.fast_window_size < self.slow_window_size


class _SlidingWindow:
    def __init__(self, max_samples: int):
        self._samples: Deque[float] = deque(maxlen=max_samples)
        self._max = max_samples

    def add(self, value: float) -> None:
        self._samples.append(value)

    def avg(self) -> float:
        if not self._samples:
            return 0.0
        return sum(self._samples) / len(self._samples)

    def count(self) -> int:
        return len(self._samples)


class StepDetector:
    def __init__(
        self,
        config: StepDetectorConfig,
        step_cb: Callable[[float], None],
    ):
        self._config = config
        self._fast = _SlidingWindow(config.fast_window_size)
        self._slow = _SlidingWindow(config.slow_window_size)
        self._step_cb = step_cb
        self._in_transit = False
        self._last_avg = 0.0
        self._last_avg_init = False

    def add_value(self, value: float) -> None:
        self._fast.add(value)
        self._slow.add(value)
        fast_avg = self._fast.avg()
        slow_avg = self._slow.avg()

        if (
            not self._last_avg_init
            and self._slow.count() >= self._config.slow_window_size // 2
        ):
            self._last_avg = slow_avg
            self._last_avg_init = True

        if slow_avg == 0:
            return
        diff_pct = abs((fast_avg - slow_avg) / slow_avg) * 100.0

        if self._in_transit:
            if diff_pct <= self._config.lower_threshold:
                # falling edge: the step is confirmed
                self._in_transit = False
                self._report(fast_avg)
        else:
            if diff_pct >= self._config.upper_threshold:
                self._in_transit = True
            elif (
                self._last_avg_init
                and abs(fast_avg - self._last_avg) >= self._config.abs_threshold
            ):
                # staircase drift: many small steps the ratio test misses
                self._report(fast_avg)

    def _report(self, new_mean: float) -> None:
        self._step_cb(new_mean)
        self._last_avg = new_mean
        self._last_avg_init = True
