"""LSDB key naming helpers.

reference: openr/common/Constants.h markers, openr/common/Util.cpp
getNodeNameFromKey, and the PrefixKey class
(openr/common/Util.h / PrefixKey: "prefix:<node>:<area>:[<prefix>]").
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

from openr_tpu.types import IpPrefix
from openr_tpu.utils.constants import (
    ADJ_DB_MARKER,
    FIB_TIME_MARKER,
    PREFIX_DB_MARKER,
)

_PER_PREFIX_KEY_RE = re.compile(
    r"^prefix:(?P<node>[^:]+):(?P<area>[^:]+):\[(?P<prefix>[^\]]+)\]$"
)


def adj_key(node: str) -> str:
    return f"{ADJ_DB_MARKER}{node}"


def prefix_db_key(node: str) -> str:
    return f"{PREFIX_DB_MARKER}{node}"


def per_prefix_key(node: str, area: str, prefix: IpPrefix) -> str:
    return f"{PREFIX_DB_MARKER}{node}:{area}:[{prefix.to_str()}]"


def fib_time_key(node: str) -> str:
    return f"{FIB_TIME_MARKER}{node}"


def get_node_name_from_key(key: str) -> str:
    """reference: openr/common/Util.cpp:1040 getNodeNameFromKey"""
    parts = key.split(":")
    return parts[1] if len(parts) >= 2 else ""


def parse_per_prefix_key(key: str) -> Optional[Tuple[str, str, IpPrefix]]:
    """(node, area, prefix) for per-prefix keys, else None."""
    m = _PER_PREFIX_KEY_RE.match(key)
    if m is None:
        return None
    try:
        prefix = IpPrefix.from_str(m.group("prefix"))
    except ValueError:
        return None
    return (m.group("node"), m.group("area"), prefix)


def is_adj_key(key: str) -> bool:
    return key.startswith(ADJ_DB_MARKER)


def is_prefix_key(key: str) -> bool:
    return key.startswith(PREFIX_DB_MARKER)


def is_fib_time_key(key: str) -> bool:
    return key.startswith(FIB_TIME_MARKER)
