"""Canonical binary wire codec for openr-tpu message types.

Plays the role the thrift binary protocol plays in the reference
(``openr/if/*.thrift`` generated serializers): every schema type in
``openr_tpu.types`` round-trips through a deterministic, compact binary
encoding. Determinism matters because the KvStore CRDT merge breaks ties on
the *serialized value bytes* (reference: openr/kvstore/KvStore.cpp:263
``mergeKeyValues`` comparing ``value_ref()->compare(...)``), so two nodes
encoding the same logical object must produce identical bytes.

Encoding (tag byte + payload):
  N             None
  T / F         bool
  I <zigzag>    int (varint, zigzag for negatives)
  S <len> utf8  str
  B <len> raw   bytes
  L <n> items   list / tuple
  D <n> k v...  dict, entries sorted by encoded key
  O <name> <n> fields   dataclass: class name + field values in field order

Decoding is schema-directed: ``loads(data, cls)`` rebuilds ``cls`` using its
dataclass field types (Optional / Tuple / List / Dict supported), so frozen
dataclasses and IntEnums come back as the right Python types.
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Tuple, get_args, get_origin, get_type_hints


#: per-class field-order memo for the encode path (field order is
#: static; ``dataclasses.fields`` rebuilds the tuple on every call)
_FIELDS_MEMO: Dict[type, tuple] = {}


def _encode_varint(n: int, out: bytearray) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 127) if n < 0 else (n << 1)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _encode(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(ord("N"))
    elif obj is True:
        out.append(ord("T"))
    elif obj is False:
        out.append(ord("F"))
    elif isinstance(obj, enum.IntEnum):
        out.append(ord("I"))
        _encode_varint(_zigzag(int(obj)), out)
    elif isinstance(obj, int):
        out.append(ord("I"))
        _encode_varint(_zigzag(obj), out)
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(ord("S"))
        _encode_varint(len(raw), out)
        out.extend(raw)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(ord("B"))
        _encode_varint(len(obj), out)
        out.extend(obj)
    elif isinstance(obj, (list, tuple)):
        out.append(ord("L"))
        _encode_varint(len(obj), out)
        for item in obj:
            _encode(item, out)
    elif isinstance(obj, (dict,)):
        entries = []
        for k, v in obj.items():
            kb = bytearray()
            _encode(k, kb)
            vb = bytearray()
            _encode(v, vb)
            entries.append((bytes(kb), bytes(vb)))
        entries.sort()
        out.append(ord("D"))
        _encode_varint(len(entries), out)
        for kb, vb in entries:
            out.extend(kb)
            out.extend(vb)
    elif isinstance(obj, (set, frozenset)):
        items = []
        for item in obj:
            ib = bytearray()
            _encode(item, ib)
            items.append(bytes(ib))
        items.sort()
        out.append(ord("L"))
        _encode_varint(len(items), out)
        for ib in items:
            out.extend(ib)
    elif dataclasses.is_dataclass(obj):
        out.append(ord("O"))
        name = type(obj).__name__.encode("utf-8")
        _encode_varint(len(name), out)
        out.extend(name)
        flds = _FIELDS_MEMO.get(type(obj))
        if flds is None:
            flds = dataclasses.fields(obj)
            _FIELDS_MEMO[type(obj)] = flds
        _encode_varint(len(flds), out)
        for f in flds:
            _encode(getattr(obj, f.name), out)
    else:
        raise TypeError(f"wire: cannot encode {type(obj)!r}")


def dumps(obj: Any) -> bytes:
    out = bytearray()
    _encode(obj, out)
    return bytes(out)


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        shift = 0
        result = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    def raw(self, n: int) -> bytes:
        chunk = self.data[self.pos : self.pos + n]
        self.pos += n
        return chunk


#: per-class (type hints, fields) memo. ``get_type_hints`` re-evaluates
#: every stringified annotation (PEP 563) on each call — decoding one
#: 1000-adjacency AdjacencyDatabase would pay that eval per nested
#: Adjacency. Hints and field order are static per class; cache them.
_CLASS_MEMO: Dict[type, Tuple[Dict[str, Any], tuple]] = {}


def _class_memo(tp: type) -> Tuple[Dict[str, Any], tuple]:
    memo = _CLASS_MEMO.get(tp)
    if memo is None:
        memo = (get_type_hints(tp), dataclasses.fields(tp))
        _CLASS_MEMO[tp] = memo
    return memo


def _is_optional(tp) -> Tuple[bool, Any]:
    if get_origin(tp) is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return True, args[0]
    return False, tp


def _decode(r: _Reader, tp: Any) -> Any:
    tag = r.byte()
    if tag == ord("N"):
        return None
    _, tp = _is_optional(tp)
    if tag == ord("T"):
        return True
    if tag == ord("F"):
        return False
    if tag == ord("I"):
        val = _unzigzag(r.varint())
        if isinstance(tp, type) and issubclass(tp, enum.IntEnum):
            return tp(val)
        return val
    if tag == ord("S"):
        return r.raw(r.varint()).decode("utf-8")
    if tag == ord("B"):
        return bytes(r.raw(r.varint()))
    if tag == ord("L"):
        n = r.varint()
        origin = get_origin(tp)
        args = get_args(tp)
        if origin in (list, typing.List):
            elem = args[0] if args else Any
            return [_decode(r, elem) for _ in range(n)]
        if origin in (set, frozenset):
            elem = args[0] if args else Any
            return {_decode(r, elem) for _ in range(n)}
        # default: tuple (covers Tuple[X, ...] and untyped)
        if args and len(args) == 2 and args[1] is Ellipsis:
            elem = args[0]
            return tuple(_decode(r, elem) for _ in range(n))
        elem_types = list(args) if args else [Any] * n
        if len(elem_types) < n:
            elem_types += [Any] * (n - len(elem_types))
        return tuple(_decode(r, elem_types[i]) for i in range(n))
    if tag == ord("D"):
        n = r.varint()
        args = get_args(tp)
        kt, vt = (args[0], args[1]) if len(args) == 2 else (Any, Any)
        return {_decode(r, kt): _decode(r, vt) for _ in range(n)}
    if tag == ord("O"):
        name = r.raw(r.varint()).decode("utf-8")
        nfields = r.varint()
        if not (dataclasses.is_dataclass(tp) and isinstance(tp, type)):
            raise TypeError(f"wire: object {name!r} but target type is {tp!r}")
        if tp.__name__ != name:
            raise TypeError(f"wire: expected {tp.__name__!r}, found {name!r}")
        hints, flds = _class_memo(tp)
        values: Dict[str, Any] = {}
        for i in range(nfields):
            if i < len(flds):
                f = flds[i]
                values[f.name] = _decode(r, hints.get(f.name, Any))
            else:  # forward compat: ignore unknown trailing fields
                _decode(r, Any)
        return tp(**values)
    raise ValueError(f"wire: bad tag {tag!r} at {r.pos - 1}")


def loads(data: bytes, cls: Any) -> Any:
    r = _Reader(data)
    obj = _decode(r, cls)
    if r.pos != len(data):
        raise ValueError(f"wire: trailing bytes ({len(data) - r.pos})")
    return obj


def generate_hash(version: int, originator_id: str, value: bytes | None) -> int:
    """Stable hash over (version, originatorId, value) used by KvStore
    anti-entropy sync. reference: openr/common/Util.h generateHash.

    64-bit FNV-1a over the canonical encoding; signed-int64 result so it can
    ride in the same field the reference uses (thrift i64).
    """
    payload = dumps([version, originator_id, value])
    h = 0xCBF29CE484222325
    for b in payload:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    # to signed 64-bit
    return h - (1 << 64) if h >= (1 << 63) else h
