"""fbthrift THeader framing: unwrap/wrap for the dual-stack listeners.

Stock fbthrift clients default to the Header transport (the reference's
peer/ctrl channels are fbthrift clients — e.g. the KvStore thrift peer
sync, kvstore/KvStore.cpp:1400 requestThriftPeerSync). A Header frame
is NOT a bare framed thrift message: after the 4-byte frame length the
payload leads with the 0x0FFF magic, so the byte-sniffing listeners
would previously misclassify a Header-wrapped dial. This module parses
exactly the fbthrift HeaderFormat (fbthrift THeader.h / the public
THeader framing spec):

    u32  LENGTH        (excluded from itself)
    u16  MAGIC 0x0FFF
    u16  FLAGS
    u32  SEQUENCE NUMBER
    u16  HEADER SIZE   (in 4-byte words, counting from after this u16)
    varint PROTOCOL ID (0 = binary, 2 = compact)
    varint NUM TRANSFORMS, then varint transform ids
    info headers (INFO_KEYVALUE = 1: varint count, then varstring
    key/value pairs), zero-padded to the declared header size
    PAYLOAD            (the thrift message in the declared protocol)

Untransformed compact-protocol (id 2) AND binary-protocol (id 0)
payloads are supported — compact is the repo's native interop wire,
binary is the fbthrift client default when no protocol is configured
(utils/thrift_binary.py decodes it over the same schema tables).
Unsupported protocol ids or transforms raise (the caller hangs up; a
stock client surfaces a transport error rather than silence). All
header-info parsing is bounded by the declared header size: a
malformed frame whose varints/varstrings would cross into the payload
raises instead of misparsing payload bytes as header info.
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

MAGIC = 0x0FFF
PROTO_BINARY = 0
PROTO_COMPACT = 2
INFO_KEYVALUE = 1
INFO_PKEYVALUE = 2


def looks_like_theader(frame_payload: bytes) -> bool:
    """True when a framed payload leads with the THeader magic."""
    return (
        len(frame_payload) >= 2
        and struct.unpack(">H", frame_payload[:2])[0] == MAGIC
    )


def _read_varint(data: bytes, pos: int, end: int) -> Tuple[int, int]:
    """Bounded LEB128 read: never consumes bytes at/past ``end`` and
    caps the shift (an endless 0x80 run raises instead of scanning to
    the buffer's physical end)."""
    result = 0
    shift = 0
    while True:
        if pos >= end:
            raise ValueError("THeader varint crosses header boundary")
        if shift > 32:
            raise ValueError("THeader varint too long")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _read_varstring(data: bytes, pos: int, end: int) -> Tuple[bytes, int]:
    n, pos = _read_varint(data, pos, end)
    if pos + n > end:
        raise ValueError("THeader varstring crosses header boundary")
    return data[pos : pos + n], pos + n


def unwrap(
    frame_payload: bytes,
) -> Tuple[bytes, int, Dict[str, str], int]:
    """THeader frame payload -> (thrift message, seqid, info
    key/values, protocol id). Raises ValueError on ANY malformed frame
    (truncation included) — callers catch one exception type and hang
    up."""
    try:
        return _unwrap(frame_payload)
    except (IndexError, struct.error) as exc:
        raise ValueError(f"truncated THeader frame: {exc}") from exc


def _unwrap(
    frame_payload: bytes,
) -> Tuple[bytes, int, Dict[str, str], int]:
    if not looks_like_theader(frame_payload):
        raise ValueError("not a THeader frame")
    flags, seqid, header_words = struct.unpack(
        ">HIH", frame_payload[2:10]
    )
    del flags  # no flag semantics for plain request/response
    header_end = 10 + header_words * 4
    if header_end > len(frame_payload):
        raise ValueError("THeader header overruns frame")
    pos = 10
    proto, pos = _read_varint(frame_payload, pos, header_end)
    if proto not in (PROTO_COMPACT, PROTO_BINARY):
        raise ValueError(
            f"unsupported THeader protocol id {proto} "
            "(compact/binary only)"
        )
    n_transforms, pos = _read_varint(frame_payload, pos, header_end)
    if n_transforms:
        raise ValueError(
            f"unsupported THeader transforms ({n_transforms})"
        )
    info: Dict[str, str] = {}
    while pos < header_end:
        info_id, pos = _read_varint(frame_payload, pos, header_end)
        if info_id == 0:  # zero padding
            break
        if info_id not in (INFO_KEYVALUE, INFO_PKEYVALUE):
            raise ValueError(f"unknown THeader info id {info_id}")
        count, pos = _read_varint(frame_payload, pos, header_end)
        for _ in range(count):
            k, pos = _read_varstring(frame_payload, pos, header_end)
            v, pos = _read_varstring(frame_payload, pos, header_end)
            info[k.decode("utf-8", "replace")] = v.decode(
                "utf-8", "replace"
            )
    return frame_payload[header_end:], seqid, info, proto


def _write_varint(buf: bytearray, n: int) -> None:
    while True:
        if n < 0x80:
            buf.append(n)
            return
        buf.append((n & 0x7F) | 0x80)
        n >>= 7


def wrap(message: bytes, seqid: int,
         info: Optional[Dict[str, str]] = None,
         proto: int = PROTO_COMPACT) -> bytes:
    """Thrift message -> THeader frame payload declaring ``proto``
    (the outer 4-byte frame length is the transport's job,
    utils/thrift_rpc frame())."""
    header = bytearray()
    _write_varint(header, proto)
    _write_varint(header, 0)  # no transforms
    if info:
        _write_varint(header, INFO_KEYVALUE)
        _write_varint(header, len(info))
        for k, v in info.items():
            kb, vb = k.encode("utf-8"), v.encode("utf-8")
            _write_varint(header, len(kb))
            header.extend(kb)
            _write_varint(header, len(vb))
            header.extend(vb)
    while len(header) % 4:
        header.append(0)
    return (
        struct.pack(">HHIH", MAGIC, 0, seqid & 0xFFFFFFFF,
                    len(header) // 4)
        + bytes(header)
        + message
    )
