"""Per-module event loop: the daemon's async runtime substrate.

Behavioral parity with the reference ``openr/common/OpenrEventBase.h``
(folly EventBase wrapper): every protocol module owns exactly one
OpenrEventBase running on its own named thread; all module state is
touched only from that thread. Cross-module communication happens through
``openr_tpu.messaging`` queues, whose readers are registered here (the
analogue of the reference's fiber tasks, OpenrEventBase.h:48
addFiberTask) and delivered as callbacks on the module thread.

Also hosts the coalescing/rate-limiting primitives the modules rely on:
- ``ExponentialBackoff``  (reference: common/ExponentialBackoff.h)
- ``AsyncThrottle``       (reference: common/AsyncThrottle.h)
- ``AsyncDebounce``       (reference: common/AsyncDebounce.h:27-62)
"""

from __future__ import annotations

import heapq
import itertools
import queue as _queue
import random
import threading
import time
from typing import Callable, List, Optional, Tuple

from openr_tpu.analysis.annotations import thread_confined
from openr_tpu.messaging.queue import QueueClosedError, RQueue

# upper bound on the event loop's idle wait so last_loop_ts stays fresh
# for the Watchdog even on a completely quiet event base; small enough
# that it stays well under any plausible watchdog threshold
_WATCHDOG_TICK_S = 0.1


class TimerHandle:
    __slots__ = ("deadline", "seq", "fn", "cancelled")

    def __init__(self, deadline: float, seq: int, fn: Callable[[], None]):
        self.deadline = deadline
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: "TimerHandle") -> bool:
        return (self.deadline, self.seq) < (other.deadline, other.seq)


class OpenrEventBase:
    """Single-threaded event loop with timers and queue-reader tasks."""

    def __init__(self, name: str = "evb"):
        self.name = name
        self._callbacks: "_queue.Queue[Callable[[], None]]" = _queue.Queue()
        self._timers: List[TimerHandle] = []
        self._timer_lock = threading.Lock()
        self._seq = itertools.count()
        self._running = threading.Event()
        self._stop_requested = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reader_threads: List[threading.Thread] = []
        # liveness for the watchdog (reference: Watchdog.h monitors evbs)
        self.last_loop_ts: float = time.monotonic()

    # -- lifecycle --------------------------------------------------------

    def run(self) -> None:
        """Run the loop on the calling thread until stop()."""
        self._running.set()
        try:
            while not self._stop_requested.is_set():
                self.last_loop_ts = time.monotonic()
                timeout = self._run_due_timers()
                # bound the idle wait: an evb with no timers and no
                # traffic (Monitor on a quiet network) would otherwise
                # block forever in get(), its last_loop_ts would go
                # stale, and the Watchdog would abort a HEALTHY daemon.
                # Idle-blocked is healthy; a hung callback still never
                # returns here and still trips the watchdog.
                if timeout is None or timeout > _WATCHDOG_TICK_S:
                    timeout = _WATCHDOG_TICK_S
                try:
                    cb = self._callbacks.get(timeout=timeout)
                except _queue.Empty:
                    continue
                try:
                    cb()
                except Exception:  # noqa: BLE001
                    # a module callback must never kill the module loop
                    import logging

                    logging.getLogger(__name__).exception(
                        "%s: unhandled exception in event callback", self.name
                    )
        finally:
            self._running.clear()

    def run_in_thread(self) -> None:
        assert self._thread is None
        self._thread = threading.Thread(
            target=self.run, name=self.name, daemon=True
        )
        self._thread.start()
        self.wait_until_running()

    def wait_until_running(self, timeout: float = 5.0) -> None:
        if not self._running.wait(timeout=timeout):
            raise TimeoutError(f"{self.name}: loop did not start")

    def stop(self) -> None:
        self._stop_requested.set()
        # wake the loop
        self._callbacks.put(lambda: None)

    def join(self, timeout: float = 10.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        for t in self._reader_threads:
            t.join(timeout=timeout)

    @property
    def is_running(self) -> bool:
        return self._running.is_set()

    def in_event_base_thread(self) -> bool:
        return threading.current_thread() is self._thread

    # -- scheduling -------------------------------------------------------

    def run_in_event_base(self, fn: Callable[[], None]) -> None:
        """Enqueue fn to run on the loop thread."""
        self._callbacks.put(fn)

    def run_immediately_or_in_event_base(self, fn: Callable[[], None]) -> None:
        if self.in_event_base_thread():
            fn()
        else:
            self.run_in_event_base(fn)

    def call_and_wait(self, fn: Callable[[], object], timeout: float = 10.0):
        """Run fn on the loop thread, block for its result (the analogue of
        the reference's folly::SemiFuture module read APIs)."""
        if self.in_event_base_thread():
            return fn()
        done = threading.Event()
        result: list = [None, None]

        def wrapper() -> None:
            try:
                result[0] = fn()
            except BaseException as e:  # noqa: BLE001 - relayed to caller
                result[1] = e
            finally:
                done.set()

        self.run_in_event_base(wrapper)
        if not done.wait(timeout=timeout):
            raise TimeoutError(f"{self.name}: call_and_wait timed out")
        if result[1] is not None:
            raise result[1]
        return result[0]

    def schedule_timeout(
        self, delay_s: float, fn: Callable[[], None]
    ) -> TimerHandle:
        handle = TimerHandle(
            time.monotonic() + max(0.0, delay_s), next(self._seq), fn
        )
        with self._timer_lock:
            heapq.heappush(self._timers, handle)
        # wake the loop so it recomputes its sleep
        self._callbacks.put(lambda: None)
        return handle

    def schedule_periodic(
        self, interval_s: float, fn: Callable[[], None], jitter_first: bool = False
    ) -> "PeriodicHandle":
        return PeriodicHandle(self, interval_s, fn, jitter_first)

    def _run_due_timers(self) -> Optional[float]:
        """Fire expired timers; return seconds until the next one."""
        while True:
            with self._timer_lock:
                while self._timers and self._timers[0].cancelled:
                    heapq.heappop(self._timers)
                if not self._timers:
                    return None
                now = time.monotonic()
                if self._timers[0].deadline > now:
                    return self._timers[0].deadline - now
                handle = heapq.heappop(self._timers)
            if not handle.cancelled:
                try:
                    handle.fn()
                except Exception:  # noqa: BLE001
                    import logging

                    logging.getLogger(__name__).exception(
                        "%s: unhandled exception in timer", self.name
                    )

    # -- queue reader tasks (the "fibers") --------------------------------

    def add_queue_reader(
        self, rqueue: RQueue, callback: Callable[[object], None]
    ) -> None:
        """Deliver every message from rqueue as a callback on the loop
        thread (reference: fiber reading loops like Decision.cpp:1433)."""

        def forward() -> None:
            while not self._stop_requested.is_set():
                try:
                    item = rqueue.get(timeout=0.2)
                except QueueClosedError:
                    return
                except Exception:
                    continue
                self.run_in_event_base(lambda item=item: callback(item))

        t = threading.Thread(
            target=forward, name=f"{self.name}::reader", daemon=True
        )
        t.start()
        self._reader_threads.append(t)


class PeriodicHandle:
    """Repeating timer bound to an event base."""

    def __init__(
        self,
        evb: OpenrEventBase,
        interval_s: float,
        fn: Callable[[], None],
        jitter_first: bool,
    ):
        self._evb = evb
        self._interval = interval_s
        self._fn = fn
        self._cancelled = False
        first = interval_s if jitter_first else 0.0
        self._handle = evb.schedule_timeout(first, self._tick)

    def _tick(self) -> None:
        if self._cancelled:
            return
        self._fn()
        if not self._cancelled:
            self._handle = self._evb.schedule_timeout(self._interval, self._tick)

    def cancel(self) -> None:
        self._cancelled = True
        self._handle.cancel()


# per-instance pacing state owned by whichever single loop created the
# backoff (an evb retry loop, the journal streamer thread, a client's
# reconnect path) — never shared across threads. The shared-state rule
# merges instances by class, so cross-role access to one instance is
# impossible by construction — hence "owner" confinement.
@thread_confined("owner", "_current", "_last_error_ts")
class ExponentialBackoff:
    """reference: common/ExponentialBackoff.h — per-key retry pacing.

    ``jitter=True`` opts into DECORRELATED jitter (the AWS
    exponential-backoff-and-jitter scheme): each error re-draws the
    delay uniformly from ``[initial, 3 * previous]`` (clamped to
    ``max``) from a private seeded stream, so N breakers that opened on
    the same event spread their re-probes instead of re-hammering the
    device in lockstep. Default OFF: the deterministic doubling path is
    byte-identical to the reference and some callers pin its exact
    sequence."""

    def __init__(self, initial_s: float, max_s: float,
                 jitter: bool = False, seed: Optional[int] = None):
        assert initial_s > 0 and max_s >= initial_s
        self._initial = initial_s
        self._max = max_s
        self._current = 0.0
        self._last_error_ts = 0.0
        self._jitter = bool(jitter)
        self._rng = random.Random(seed) if jitter else None

    def can_try_now(self) -> bool:
        return self.get_time_remaining_until_retry() <= 0

    def report_success(self) -> None:
        self._current = 0.0

    def report_error(self) -> None:
        self._last_error_ts = time.monotonic()
        if self._jitter:
            prev = self._current if self._current > 0.0 else self._initial
            self._current = min(
                self._max,
                self._rng.uniform(
                    self._initial, max(self._initial, prev * 3.0)
                ),
            )
        elif self._current == 0.0:
            self._current = self._initial
        else:
            self._current = min(self._current * 2, self._max)

    def at_max_backoff(self) -> bool:
        return self._current >= self._max

    @property
    def max_backoff(self) -> float:
        return self._max

    def set_max(self, max_s: float) -> None:
        """Retarget the ceiling (rate-adaptive debounce). Raising the max
        lets the next report_error extend further; lowering it clamps any
        in-flight backoff so the change takes effect immediately."""
        assert max_s >= self._initial
        self._max = max_s
        if self._current > max_s:
            self._current = max_s

    def get_current_backoff(self) -> float:
        return self._current

    def get_time_remaining_until_retry(self) -> float:
        if self._current == 0.0:
            return 0.0
        return max(0.0, self._last_error_ts + self._current - time.monotonic())


class AsyncThrottle:
    """Coalesce bursts: callback runs at most once per ``timeout_s``.
    reference: common/AsyncThrottle.h."""

    def __init__(
        self, evb: OpenrEventBase, timeout_s: float, callback: Callable[[], None]
    ):
        self._evb = evb
        self._timeout = timeout_s
        self._callback = callback
        self._handle: Optional[TimerHandle] = None

    def __call__(self) -> None:
        if self._handle is not None and not self._handle.cancelled:
            return
        if self._timeout <= 0:
            self._callback()
            return
        self._handle = self._evb.schedule_timeout(self._timeout, self._fire)

    def _fire(self) -> None:
        self._handle = None
        self._callback()

    def is_active(self) -> bool:
        return self._handle is not None and not self._handle.cancelled

    def cancel(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None


class AsyncDebounce:
    """Debounce with exponential extension: every invocation while pending
    pushes the deadline out (doubling from min toward max); once the
    backoff is saturated further invocations no longer delay the fire.
    reference: common/AsyncDebounce.h:27-62."""

    def __init__(
        self,
        evb: OpenrEventBase,
        min_backoff_s: float,
        max_backoff_s: float,
        callback: Callable[[], None],
    ):
        self._evb = evb
        self._backoff = ExponentialBackoff(min_backoff_s, max_backoff_s)
        self._callback = callback
        self._handle: Optional[TimerHandle] = None

    def __call__(self) -> None:
        if not self._backoff.at_max_backoff():
            self._backoff.report_error()
            if self._handle is not None:
                self._handle.cancel()
            self._handle = self._evb.schedule_timeout(
                self._backoff.get_current_backoff(), self._fire
            )
        assert self._handle is not None and not self._handle.cancelled

    def _fire(self) -> None:
        self._handle = None
        self._backoff.report_success()
        self._callback()

    def is_scheduled(self) -> bool:
        return self._handle is not None and not self._handle.cancelled

    def at_max_backoff(self) -> bool:
        """True once the extension ceiling is saturated: further
        invocations no longer push the deadline out, so a pending fire
        time is FINAL. This is the debounce *terminal* — the window
        where speculating on the current coalesced backlog is sound
        under latest-wins (nothing can reopen the window, only join
        it)."""
        return self._backoff.at_max_backoff()

    @property
    def max_backoff_s(self) -> float:
        return self._backoff.max_backoff

    def set_max_backoff(self, max_s: float) -> None:
        """Adjust the extension ceiling in place (the admission path's
        rate-adaptive debounce). A pending fire keeps its deadline; only
        future extensions see the new ceiling — except that lowering the
        ceiling clamps the backoff immediately, so a saturated debounce
        under a narrowed ceiling fires sooner on the next invocation."""
        self._backoff.set_max(max_s)
