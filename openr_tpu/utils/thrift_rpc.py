"""Shared framed-CompactProtocol RPC machinery.

The transport every interop channel speaks: TFramedTransport (4-byte
big-endian length prefix) carrying TCompactProtocol messages with the
standard envelope

    0x82 | (version=1 | type<<5) | varint(seqid) | varstring(name)

followed by the args struct; replies carry a result struct whose
success field is id 0, declared-exception-free errors ride a
TApplicationException. Used by the KvStore peer channel
(kvstore/thrift_peer.py) and the FibService platform channel
(platform/thrift_fib.py); fbthrift's Rocket/THeader outer transports
are a different layer — classic framed transport is the interop-stable
one.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from openr_tpu.utils import thrift_compact as tc
from openr_tpu.utils.rpc import MAX_FRAME, apply_bind_family

PROTOCOL_ID = 0x82
VERSION = 1
TYPE_CALL = 1
TYPE_REPLY = 2
TYPE_EXCEPTION = 3

# TApplicationException (thrift builtin), compact-encoded
TAPP_EXC = tc.StructSchema(
    "TApplicationException",
    (
        tc.Field(1, ("string",), "message", optional=True),
        tc.Field(2, ("i32",), "type", optional=True),
    ),
)


def encode_message(
    name: str, mtype: int, seqid: int, schema, values: Dict
) -> bytes:
    """One compact-protocol message (frame header excluded)."""
    w = tc._Writer()
    w.byte(PROTOCOL_ID)
    w.byte((VERSION & 0x1F) | (mtype << 5))
    w.varint(seqid)
    w.binary(name.encode("utf-8"))
    return bytes(w.buf) + tc.encode(schema, values)


def decode_message_header(data: bytes) -> Tuple[str, int, int, int]:
    """Returns (name, mtype, seqid, args_offset)."""
    r = tc._Reader(data)
    proto = r.byte()
    if proto != PROTOCOL_ID:
        raise ValueError(f"not a compact-protocol message: 0x{proto:02x}")
    vt = r.byte()
    if (vt & 0x1F) != VERSION:
        raise ValueError(f"unsupported compact version {vt & 0x1F}")
    mtype = (vt >> 5) & 0x07
    seqid = r.varint()
    name = r.binary().decode("utf-8")
    return name, mtype, seqid, r.pos


class _CompactCodec:
    """Uniform codec facade (encode/decode structs + message envelope)
    so dispatch code is protocol-agnostic; thrift_binary presents the
    same four names natively."""

    encode_message = staticmethod(encode_message)
    decode_message_header = staticmethod(decode_message_header)
    encode = staticmethod(tc.encode)
    decode = staticmethod(tc.decode)


def is_thrift_head(head: bytes) -> bool:
    """Classify a connection's first 6 bytes (4-byte frame length +
    two payload bytes) as one of the thrift wires: bare framed compact
    (0x82), THeader (0x0FFF magic), or bare framed strict binary
    (0x8001 version word). The single shared predicate for every
    byte-sniffing demultiplexer — a new wire is added HERE, once."""
    return (
        len(head) >= 6
        and (
            head[4] == PROTOCOL_ID
            or head[4:6] == b"\x0f\xff"
            or head[4:6] == b"\x80\x01"
        )
    )


def frame(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


def read_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = read_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = struct.unpack(">I", hdr)
    if length > MAX_FRAME:
        raise ValueError(f"oversized frame {length}")
    return read_exact(sock, length)


def read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    # bytearray accumulation: += on bytes is quadratic, and full-sync
    # payloads can be tens of MB
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


# method name -> (args_schema, handler(args_dict) ->
#                 (result_schema, result_dict))
MethodTable = Dict[str, Tuple[object, Callable[[Dict], Tuple[object, Dict]]]]


class FramedCompactServer:
    """Threaded TCP server dispatching a framed-compact method table.
    Dispatch errors reply as TApplicationException rather than closing
    the connection (a stock thrift client expects a reply frame, not a
    bare EOF).

    ``listen=False`` builds a pure DISPATCHER: no socket is ever bound
    and start()/stop() are no-ops — for byte-sniffing demultiplexers
    (kvstore/dualstack.py, ctrl/server.py) that accept on their own
    port and hand classified connections to ``serve_connection``.
    Without this, every demux would carry a hidden live loopback
    listener just to reuse the request loop."""

    def __init__(
        self, methods: MethodTable, host: str = "0.0.0.0", port: int = 0,
        listen: bool = True,
    ):
        outer = self
        self._methods = methods
        self._thread: Optional[threading.Thread] = None
        if not listen:
            self._server = None
            self.port = 0
            return

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                outer.serve_connection(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        apply_bind_family(Server, host)
        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]

    def serve_connection(self, sock) -> None:
        """Run the request loop on an already-accepted socket (shared
        by the own listener and external demultiplexers). Each frame
        may be a bare framed-compact message, a bare framed-binary
        message, OR a THeader-wrapped one in either protocol (the
        fbthrift default transport — a stock client's dial, reference
        kvstore/KvStore.cpp:1400); replies mirror the request's
        wrapping AND protocol."""
        from openr_tpu.utils import theader, thrift_binary as tb

        while True:
            try:
                data = read_frame(sock)
            except (OSError, ValueError):
                return
            if data is None:
                return
            wrapped_seqid = None
            proto = theader.PROTO_COMPACT
            if theader.looks_like_theader(data):
                try:
                    data, wrapped_seqid, _info, proto = theader.unwrap(
                        data
                    )
                except ValueError:
                    return  # unsupported protocol/transform: hang up
            elif tb.looks_like_binary(data):
                proto = theader.PROTO_BINARY
            try:
                reply = self._dispatch(data, proto)
            except Exception as exc:
                reply = self._exception_reply(data, exc, proto)
                if reply is None:  # header itself unparseable
                    return
            if wrapped_seqid is not None:
                reply = theader.wrap(reply, wrapped_seqid, proto=proto)
            try:
                sock.sendall(frame(reply))
            except OSError:
                return

    @staticmethod
    def _codec(proto: int):
        """Message/struct codec for a THeader protocol id: compact
        (the repo's native interop wire) or binary (fbthrift's
        unconfigured-client default)."""
        from openr_tpu.utils import theader

        if proto == theader.PROTO_BINARY:
            from openr_tpu.utils import thrift_binary

            return thrift_binary
        return _CompactCodec

    def _dispatch(self, data: bytes, proto: int) -> bytes:
        codec = self._codec(proto)
        name, mtype, seqid, off = codec.decode_message_header(data)
        if mtype != TYPE_CALL:
            raise ValueError(f"unexpected message type {mtype}")
        entry = self._methods.get(name)
        if entry is None:
            return codec.encode_message(
                name, TYPE_EXCEPTION, seqid, TAPP_EXC,
                {"message": f"unknown method {name!r}", "type": 1},
            )
        args_schema, handler = entry
        result_schema, result = handler(
            codec.decode(args_schema, data[off:])
        )
        return codec.encode_message(
            name, TYPE_REPLY, seqid, result_schema, result
        )

    @classmethod
    def _exception_reply(
        cls, data: bytes, exc: Exception, proto: int
    ) -> Optional[bytes]:
        codec = cls._codec(proto)
        try:
            name, _mtype, seqid, _off = codec.decode_message_header(data)
        except Exception:
            return None
        return codec.encode_message(
            name, TYPE_EXCEPTION, seqid, TAPP_EXC,
            {"message": f"{type(exc).__name__}: {exc}", "type": 6},
        )

    def start(self) -> None:
        if self._server is None:
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="framed-compact-rpc",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None


class FramedCompactClient:
    """One-connection framed thrift caller (reconnects per call after
    a transport error). ``theader=True`` wraps every call in the
    fbthrift Header transport — the shape a STOCK fbthrift client puts
    on the wire — and unwraps replies (tests use this to prove the
    dual-stack listeners accept a Header-framed dial).
    ``binary=True`` encodes calls with TBinaryProtocol (the fbthrift
    default when no protocol is configured) instead of compact —
    combinable with ``theader`` to model every stock client shape."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 theader: bool = False, binary: bool = False):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._seqid = 0
        self._theader = theader
        self._binary = binary

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                self._addr, timeout=self._timeout_s
            )
        return self._sock

    def call(self, name: str, args_schema, args: Dict,
             result_schema) -> Dict:
        if self._binary:
            from openr_tpu.utils import thrift_binary as codec
        else:
            codec = _CompactCodec
        with self._lock:
            self._seqid += 1
            seqid = self._seqid
            payload = codec.encode_message(
                name, TYPE_CALL, seqid, args_schema, args
            )
            if self._theader:
                from openr_tpu.utils import theader as th

                payload = th.wrap(
                    payload, seqid,
                    proto=(th.PROTO_BINARY if self._binary
                           else th.PROTO_COMPACT),
                )
            try:
                sock = self._connect()
                sock.sendall(frame(payload))
                data = read_frame(sock)
            except OSError:
                self.close()
                raise
            if data is None:
                self.close()
                raise ConnectionError("peer closed mid-call")
            if self._theader:
                from openr_tpu.utils import theader as th

                if not th.looks_like_theader(data):
                    self.close()
                    raise ConnectionError(
                        "peer replied without THeader wrapping"
                    )
                data, rhdr_seq, _info, _proto = th.unwrap(data)
                if rhdr_seq != seqid:
                    self.close()
                    raise ConnectionError(
                        f"out-of-sync THeader reply {rhdr_seq}"
                    )
            rname, mtype, rseq, off = codec.decode_message_header(data)
            if mtype == TYPE_EXCEPTION:
                exc = codec.decode(TAPP_EXC, data[off:])
                raise RuntimeError(
                    f"peer exception: {exc.get('message')}"
                )
            if rname != name or rseq != seqid:
                self.close()
                raise ConnectionError(
                    f"out-of-sync reply {rname}/{rseq}"
                )
            return codec.decode(result_schema, data[off:])

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
