"""Framework-wide constants (reference: openr/common/Constants.h)."""

from __future__ import annotations

# key markers in the flooded store (reference: Constants.h kAdjDbMarker /
# kPrefixDbMarker)
ADJ_DB_MARKER = "adj:"
PREFIX_DB_MARKER = "prefix:"
FIB_TIME_MARKER = "fibtime:"

PREFIX_NAME_SEPARATOR = ":"

DEFAULT_AREA = "0"

# default ports (reference: Constants.h:254-263)
CTRL_PORT = 2018
KVSTORE_PORT = 60002
FIB_AGENT_PORT = 60100
SPARK_MCAST_PORT = 6666

# debounce window for route rebuilds (reference: common/Flags.cpp:87-96)
DECISION_DEBOUNCE_MIN_MS = 10
DECISION_DEBOUNCE_MAX_MS = 250

# KvStore timers (reference: Constants.h)
KVSTORE_DB_SYNC_INTERVAL_S = 60
TTL_DECREMENT_MS = 1  # floor applied when re-flooding TTLs
# finite TTL for withdraw tombstones so delete markers age out of every
# store instead of accumulating (reference: clearKey floods with the
# key's finite TTL, Constants.h kKvStoreDbTtl)
KVSTORE_TOMBSTONE_TTL_MS = 300_000

# default best-route-selection metrics assigned at prefix origination.
# Non-zero so a re-originated copy (distance+1) still clears the
# zero-metric selection sentinel yet always loses to the original
# (reference: Constants.h:244-245 kDefaultPathPreference /
# kDefaultSourcePreference, applied in buildOriginatedPrefixDb)
DEFAULT_PATH_PREFERENCE = 1000
DEFAULT_SOURCE_PREFERENCE = 200

# MPLS label ranges (reference: Constants.h kSrGlobalRange / kSrLocalRange)
SR_GLOBAL_RANGE = (101, 49999)
SR_LOCAL_RANGE = (50000, 59999)
MPLS_LABEL_MAX = (1 << 20) - 1


def is_mpls_label_valid(label: int) -> bool:
    """Label fits in 20 bits. The reference deliberately does NOT reject
    the reserved 0-15 range (reference: openr/common/Util.h:284
    isMplsLabelValid, '(mplsLabel & 0xfff00000) == 0'). Label 0 is
    filtered by the MPLS label-route loops (buildRouteDb's 'topLabel == 0'
    guards); the unicast PUSH path intentionally accepts it — the
    reference pushes a 0 node label too (Decision.cpp:1287-1292)."""
    return 0 <= label <= MPLS_LABEL_MAX
