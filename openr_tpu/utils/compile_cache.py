"""Persistent XLA compilation cache shared by the jax entry points
(bench children, scale legs, the daemon, the test conftest).

Each bench/watcher leg runs in a fresh process and used to re-pay
every jit compile (0.5-40 s per kernel via the remote-compile tunnel;
several minutes total at 100k shapes). jax's persistent cache keys
compiled executables by computation + platform + version, so pointing
every process at one on-disk directory makes the second process skip
straight to execution — measured through the axon relay: a cold 10.1 s
toy compile replayed in 2.4 s. CPU test runs benefit the same way.

The default location is the per-user cache (~/.cache/openr_tpu/jax, or
$XDG_CACHE_HOME/openr_tpu/jax) so every checkout and bench worktree
shares one warm cache; when the home directory is unwritable (hermetic
CI sandboxes) it falls back to a repo-local .jax_cache. The cache grows
without bound — see docs/RUNBOOK.md for the growth/pruning note.

Opt-out: set OPENR_TPU_NO_COMPILE_CACHE=1 (e.g. to measure true
cold-compile latency).
"""

from __future__ import annotations

import os

_REPO_FALLBACK_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    ".jax_cache",
)


def default_dir() -> str:
    """Per-user cache dir, falling back to the repo checkout when the
    user cache root cannot be created."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    path = os.path.join(base, "openr_tpu", "jax")
    try:
        os.makedirs(path, exist_ok=True)
        return path
    except OSError:
        return _REPO_FALLBACK_DIR


def enable(cache_dir: str | None = None) -> bool:
    """Idempotently enable the persistent compilation cache. Returns
    False when opted out or jax is unavailable."""
    if os.environ.get("OPENR_TPU_NO_COMPILE_CACHE"):
        return False
    try:
        import jax
    except Exception:
        return False
    path = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or default_dir()
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # default min threshold skips sub-second compiles; the kernel
        # zoo here is all multi-second, keep the default behavior
    except Exception:
        return False
    return True
