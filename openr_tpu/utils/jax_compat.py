"""Version-portable access to jax APIs that moved between releases."""

import functools

import jax

try:
    shard_map = jax.shard_map  # promoted to the top level in newer jax
except AttributeError:  # jax 0.4/0.5: still under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        # the experimental version has no replication rule for
        # while_loop (every mesh kernel here runs one); the promoted
        # API dropped that static check entirely
        kwargs.setdefault("check_rep", False)
        return _shard_map(*args, **kwargs)
