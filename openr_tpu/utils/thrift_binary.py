"""Thrift BinaryProtocol codec over the shared schema tables.

A stock fbthrift client configured with the DEFAULT binary protocol
(``THRIFT_BINARY_PROTOCOL``) puts TBinaryProtocol bytes inside its
THeader frames (protocol id 0 in the header); the reference's channels
negotiate this freely (reference: openr/kvstore/KvStore.cpp:1400 peer
channel setup — fbthrift picks the protocol from client config, the
server honours whatever the header declares). ``utils/thrift_compact``
covers protocol id 2; THIS module covers protocol id 0 so a
binary-configured stock client gets service instead of a hangup.

It reuses the exact ``StructSchema``/``Field`` descriptors from
``thrift_compact`` — the schema tables are protocol-agnostic (field
ids + type descriptors straight from the IDL); only the byte encoding
differs. Implemented from the thrift binary protocol specification
(thrift/doc/specs/thrift-binary-protocol.md):

- fixed-width big-endian integers (no varints, no zigzag)
- bool is one byte 0x00/0x01
- string/binary: i32 byte-length + payload
- list/set: elem-type byte + i32 size + elements
- map: key-type byte + value-type byte + i32 size + pairs
- struct field: type byte + i16 field id + value; STOP (0x00) ends
- strict message envelope: i32 (0x80010000 | mtype), string name,
  i32 seqid
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Tuple

from openr_tpu.utils.thrift_compact import StructSchema

# binary-protocol wire types (differ from compact's!)
B_STOP = 0
B_BOOL = 2
B_BYTE = 3
B_DOUBLE = 4
B_I16 = 6
B_I32 = 8
B_I64 = 10
B_STRING = 11
B_STRUCT = 12
B_MAP = 13
B_SET = 14
B_LIST = 15

_WIRE_TYPE = {
    "bool": B_BOOL,
    "byte": B_BYTE,
    "i16": B_I16,
    "i32": B_I32,
    "i64": B_I64,
    "double": B_DOUBLE,
    "string": B_STRING,
    "binary": B_STRING,
    "list": B_LIST,
    "set": B_SET,
    "map": B_MAP,
    "struct": B_STRUCT,
}

VERSION_1 = 0x80010000
VERSION_MASK = 0xFFFF0000


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        if len(out) != n:
            raise ValueError("truncated binary-protocol data")
        self.pos += n
        return bytes(out)

    def u8(self) -> int:
        return self.take(1)[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def double(self) -> float:
        return struct.unpack(">d", self.take(8))[0]

    def binary(self) -> bytes:
        n = self.i32()
        if n < 0:
            raise ValueError("negative binary length")
        return self.take(n)


def _write_value(buf: bytearray, ftype: Tuple, value: Any) -> None:
    kind = ftype[0]
    if kind == "bool":
        buf.append(1 if value else 0)
    elif kind == "byte":
        buf.extend(struct.pack(">b", int(value)))
    elif kind == "i16":
        buf.extend(struct.pack(">h", int(value)))
    elif kind == "i32":
        buf.extend(struct.pack(">i", int(value)))
    elif kind == "i64":
        buf.extend(struct.pack(">q", int(value)))
    elif kind == "double":
        buf.extend(struct.pack(">d", float(value)))
    elif kind == "string":
        b = value.encode("utf-8")
        buf.extend(struct.pack(">i", len(b)))
        buf.extend(b)
    elif kind == "binary":
        b = bytes(value)
        buf.extend(struct.pack(">i", len(b)))
        buf.extend(b)
    elif kind in ("list", "set"):
        elem = ftype[1]
        items = sorted(value) if kind == "set" else list(value)
        buf.append(_WIRE_TYPE[elem[0]])
        buf.extend(struct.pack(">i", len(items)))
        for item in items:
            _write_value(buf, elem, item)
    elif kind == "map":
        ktype, vtype = ftype[1], ftype[2]
        buf.append(_WIRE_TYPE[ktype[0]])
        buf.append(_WIRE_TYPE[vtype[0]])
        buf.extend(struct.pack(">i", len(value)))
        # deterministic output, same discipline as the compact codec
        for k in sorted(value):
            _write_value(buf, ktype, k)
            _write_value(buf, vtype, value[k])
    elif kind == "struct":
        _write_struct(buf, ftype[1], value)
    else:
        raise TypeError(f"unsupported type {kind}")


def _write_struct(
    buf: bytearray, schema: StructSchema, values: Dict
) -> None:
    for f in schema.fields:
        value = values.get(f.name)
        if value is None:
            if f.optional:
                continue
            raise ValueError(f"{schema.name}.{f.name} is required")
        buf.append(_WIRE_TYPE[f.ftype[0]])
        buf.extend(struct.pack(">h", f.fid))
        _write_value(buf, f.ftype, value)
    buf.append(B_STOP)


def _skip(r: _Reader, wtype: int) -> None:
    if wtype == B_BOOL or wtype == B_BYTE:
        r.take(1)
    elif wtype == B_I16:
        r.take(2)
    elif wtype == B_I32:
        r.take(4)
    elif wtype in (B_I64, B_DOUBLE):
        r.take(8)
    elif wtype == B_STRING:
        r.binary()
    elif wtype in (B_LIST, B_SET):
        et = r.u8()
        size = r.i32()
        if size < 0:
            raise ValueError("negative collection size")
        for _ in range(size):
            _skip(r, et)
    elif wtype == B_MAP:
        kt, vt = r.u8(), r.u8()
        size = r.i32()
        if size < 0:
            raise ValueError("negative map size")
        for _ in range(size):
            _skip(r, kt)
            _skip(r, vt)
    elif wtype == B_STRUCT:
        while True:
            t = r.u8()
            if t == B_STOP:
                return
            r.i16()
            _skip(r, t)
    else:
        raise ValueError(f"cannot skip binary wire type {wtype}")


def _read_value(r: _Reader, ftype: Tuple) -> Any:
    kind = ftype[0]
    if kind == "bool":
        return r.u8() != 0
    if kind == "byte":
        b = r.u8()
        return b - 256 if b >= 128 else b
    if kind == "i16":
        return r.i16()
    if kind == "i32":
        return r.i32()
    if kind == "i64":
        return r.i64()
    if kind == "double":
        return r.double()
    if kind == "string":
        return r.binary().decode("utf-8")
    if kind == "binary":
        return r.binary()
    if kind in ("list", "set"):
        r.u8()  # declared elem type; schema drives the parse
        size = r.i32()
        if size < 0:
            raise ValueError("negative collection size")
        items = [_read_value(r, ftype[1]) for _ in range(size)]
        return set(items) if kind == "set" else items
    if kind == "map":
        r.u8()
        r.u8()
        size = r.i32()
        if size < 0:
            raise ValueError("negative map size")
        out: Dict = {}
        for _ in range(size):
            k = _read_value(r, ftype[1])
            out[k] = _read_value(r, ftype[2])
        return out
    if kind == "struct":
        return _read_struct(r, ftype[1])
    raise TypeError(f"unsupported type {kind}")


def _read_struct(r: _Reader, schema: StructSchema) -> Dict:
    fields = schema.by_id()
    out: Dict = {}
    while True:
        wtype = r.u8()
        if wtype == B_STOP:
            return out
        fid = r.i16()
        f = fields.get(fid)
        if f is None:
            _skip(r, wtype)  # forward compatibility: unknown field
            continue
        out[f.name] = _read_value(r, f.ftype)


def encode(schema: StructSchema, values: Dict) -> bytes:
    """Serialize ``values`` (plain dict keyed by field name) as one
    binary-protocol struct."""
    buf = bytearray()
    _write_struct(buf, schema, values)
    return bytes(buf)


def decode(schema: StructSchema, data: bytes) -> Dict:
    """Parse one binary-protocol struct into a dict keyed by field
    name; unknown fields skipped, absent fields absent."""
    return _read_struct(_Reader(data), schema)


def encode_message(
    name: str, mtype: int, seqid: int, schema, values: Dict
) -> bytes:
    """One strict binary-protocol message (frame header excluded)."""
    nb = name.encode("utf-8")
    return (
        struct.pack(">I", VERSION_1 | (mtype & 0xFF))
        + struct.pack(">i", len(nb))
        + nb
        + struct.pack(">i", seqid)
        + encode(schema, values)
    )


def decode_message_header(data: bytes) -> Tuple[str, int, int, int]:
    """Returns (name, mtype, seqid, args_offset). Accepts strict
    messages only (the fbthrift default; non-strict has no version
    word and is long-deprecated)."""
    r = _Reader(data)
    head = struct.unpack(">I", r.take(4))[0]
    if (head & VERSION_MASK) != (VERSION_1 & VERSION_MASK):
        raise ValueError(
            f"not a strict binary-protocol message: 0x{head:08x}"
        )
    mtype = head & 0xFF
    name = r.binary().decode("utf-8")
    seqid = r.i32()
    return name, mtype, seqid, r.pos


def looks_like_binary(data: bytes) -> bool:
    """True when a framed payload leads with the strict binary-protocol
    version word (0x8001....) — how the byte-sniffing listeners
    classify a bare framed-binary dial."""
    return len(data) >= 4 and data[0] == 0x80 and data[1] == 0x01
