"""Spark packets in the reference's thrift CompactProtocol wire format.

The reference serializes ``SparkHelloPacket`` (openr/if/Spark.thrift:
ReflectedNeighborInfo:25, SparkHelloMsg:60, SparkHeartbeatMsg:73,
SparkHandshakeMsg:78, SparkHelloPacket:113) with CompactProtocol onto
the ``ff02::1`` multicast socket. This module maps the framework's
Spark dataclasses onto that exact byte layout so an openr-tpu daemon
can discover (and be discovered by) stock Open/R neighbors on the same
LAN. Hold/GR times ride in milliseconds, exactly like the reference
(Spark.cpp:781 sends holdTime_.count() of a milliseconds duration;
:1496 reads it back as milliseconds).

Differences the adapters absorb:
- the reference's handshake/heartbeat carry no interface name (the
  receiver knows its own rx interface; the REMOTE interface comes from
  the hello msg) — decode leaves ``if_name`` empty and the Spark FSM
  keeps the hello-learned value;
- ``domainName`` carries the daemon's configured domain
  (OpenrConfig.domain; a stock neighbor drops mismatches);
- the framework's packet-level version maps to the hello msg's
  ``version`` field (the only place the reference carries one).

Format sniffing: the framework's native codec (utils/wire.py) always
starts a packet with the dataclass marker byte ``'O'`` (0x4F), which can
never begin a compact-protocol struct whose first field id is >= 3
(header 0x3C/0x4C...). Spark accepts BOTH formats on receive and sends
whichever ``wire_format`` selects — the dual-stack pattern the
reference uses for its own wire migrations (KvStore.cpp:2940-2973).
"""

from __future__ import annotations

from typing import Dict

from openr_tpu.types.spark import (
    ReflectedNeighborInfo,
    SparkHandshakeMsg,
    SparkHeartbeatMsg,
    SparkHelloMsg,
    SparkPacket,
)
from openr_tpu.utils import thrift_compact as tc

# Network.thrift BinaryAddress schema + adapters are shared with the
# FibService wire (utils/thrift_compact.py)
BINARY_ADDRESS = tc.BINARY_ADDRESS

REFLECTED_NEIGHBOR_INFO = tc.StructSchema(
    "ReflectedNeighborInfo",
    (
        tc.Field(1, ("i64",), "seqNum"),
        tc.Field(2, ("i64",), "lastNbrMsgSentTsInUs"),
        tc.Field(3, ("i64",), "lastMyMsgRcvdTsInUs"),
    ),
)

SPARK_HELLO_MSG = tc.StructSchema(
    "SparkHelloMsg",
    (
        tc.Field(1, ("string",), "domainName"),
        tc.Field(2, ("string",), "nodeName"),
        tc.Field(3, ("string",), "ifName"),
        tc.Field(4, ("i64",), "seqNum"),
        tc.Field(
            5,
            ("map", ("string",), ("struct", REFLECTED_NEIGHBOR_INFO)),
            "neighborInfos",
        ),
        tc.Field(6, ("i32",), "version"),
        tc.Field(7, ("bool",), "solicitResponse"),
        tc.Field(8, ("bool",), "restarting"),
        tc.Field(9, ("i64",), "sentTsInUs"),
    ),
)

SPARK_HEARTBEAT_MSG = tc.StructSchema(
    "SparkHeartbeatMsg",
    (
        tc.Field(1, ("string",), "nodeName"),
        tc.Field(2, ("i64",), "seqNum"),
    ),
)

SPARK_HANDSHAKE_MSG = tc.StructSchema(
    "SparkHandshakeMsg",
    (
        tc.Field(1, ("string",), "nodeName"),
        tc.Field(2, ("bool",), "isAdjEstablished"),
        tc.Field(3, ("i64",), "holdTime"),
        tc.Field(4, ("i64",), "gracefulRestartTime"),
        tc.Field(5, ("struct", BINARY_ADDRESS), "transportAddressV6"),
        tc.Field(6, ("struct", BINARY_ADDRESS), "transportAddressV4"),
        tc.Field(7, ("i32",), "openrCtrlThriftPort"),
        tc.Field(9, ("i32",), "kvStoreCmdPort"),
        tc.Field(10, ("string",), "area"),
        tc.Field(11, ("string",), "neighborNodeName", optional=True),
    ),
)

SPARK_HELLO_PACKET = tc.StructSchema(
    "SparkHelloPacket",
    (
        tc.Field(
            3, ("struct", SPARK_HELLO_MSG), "helloMsg", optional=True
        ),
        tc.Field(
            4,
            ("struct", SPARK_HEARTBEAT_MSG),
            "heartbeatMsg",
            optional=True,
        ),
        tc.Field(
            5,
            ("struct", SPARK_HANDSHAKE_MSG),
            "handshakeMsg",
            optional=True,
        ),
    ),
)

# the native codec's first byte for any dataclass packet; a compact
# SparkHelloPacket starts with a field header whose id >= 3 (0x3C...)
NATIVE_MARKER = ord("O")

# the reference's date-coded protocol version (Constants.h:274
# kOpenrVersion / :277 kOpenrSupportedVersion{20200604}): a stock
# Open/R neighbor drops hellos whose version is below its supported
# floor, so the thrift wire must speak the reference's numbering —
# the framework-internal version (1) stays internal
OPENR_VERSION = 20200825
OPENR_SUPPORTED_VERSION = 20200604


_addr_to_wire = tc._bin_addr_to_wire
_addr_from_wire = tc._bin_addr_from_wire


def encode_packet(pkt: SparkPacket, domain: str = "") -> bytes:
    """One SparkPacket -> compact-protocol SparkHelloPacket bytes."""
    out: Dict = {}
    if pkt.hello is not None:
        h = pkt.hello
        out["helloMsg"] = {
            "domainName": domain,
            "nodeName": h.node_name,
            "ifName": h.if_name,
            "seqNum": h.seq_num,
            "neighborInfos": {
                nbr: {
                    "seqNum": info.seq_num,
                    "lastNbrMsgSentTsInUs": info.last_nbr_msg_sent_ts_us,
                    "lastMyMsgRcvdTsInUs": info.last_my_msg_rcvd_ts_us,
                }
                for nbr, info in h.neighbor_infos.items()
            },
            # reference numbering on the wire (a stock neighbor
            # rejects anything below its date-coded floor)
            "version": OPENR_VERSION,
            "solicitResponse": h.solicit_response,
            "restarting": h.restarting,
            "sentTsInUs": h.sent_ts_us,
        }
    if pkt.heartbeat is not None:
        out["heartbeatMsg"] = {
            "nodeName": pkt.heartbeat.node_name,
            "seqNum": pkt.heartbeat.seq_num,
        }
    if pkt.handshake is not None:
        m = pkt.handshake
        out["handshakeMsg"] = {
            "nodeName": m.node_name,
            "isAdjEstablished": m.is_adj_established,
            "holdTime": m.hold_time_ms,
            "gracefulRestartTime": m.graceful_restart_time_ms,
            "transportAddressV6": _addr_to_wire(m.transport_address_v6),
            "transportAddressV4": _addr_to_wire(m.transport_address_v4),
            "openrCtrlThriftPort": m.openr_ctrl_port,
            "kvStoreCmdPort": m.kvstore_peer_port,
            "area": m.area,
            **(
                {"neighborNodeName": m.neighbor_node_name}
                if m.neighbor_node_name is not None
                else {}
            ),
        }
    return tc.encode(SPARK_HELLO_PACKET, out)


def decode_packet(data: bytes) -> SparkPacket:
    """Compact-protocol SparkHelloPacket bytes -> SparkPacket."""
    d = tc.decode(SPARK_HELLO_PACKET, data)
    pkt = SparkPacket()
    hello = d.get("helloMsg")
    if hello is not None:
        pkt.hello = SparkHelloMsg(
            node_name=hello.get("nodeName", ""),
            if_name=hello.get("ifName", ""),
            seq_num=hello.get("seqNum", 0),
            neighbor_infos={
                nbr: ReflectedNeighborInfo(
                    seq_num=i.get("seqNum", 0),
                    last_nbr_msg_sent_ts_us=i.get(
                        "lastNbrMsgSentTsInUs", 0
                    ),
                    last_my_msg_rcvd_ts_us=i.get(
                        "lastMyMsgRcvdTsInUs", 0
                    ),
                )
                for nbr, i in hello.get("neighborInfos", {}).items()
            },
            solicit_response=hello.get("solicitResponse", False),
            restarting=hello.get("restarting", False),
            sent_ts_us=hello.get("sentTsInUs", 0),
        )
        v = hello.get("version", OPENR_VERSION)
        # map the reference's date-coded version onto the framework's
        # internal numbering: anything at/above the reference floor is
        # acceptable (internally version 1); a below-floor sender maps
        # to 0 so Spark's version check rejects it
        pkt.version = 1 if v >= OPENR_SUPPORTED_VERSION or v == 1 else 0
    heartbeat = d.get("heartbeatMsg")
    if heartbeat is not None:
        pkt.heartbeat = SparkHeartbeatMsg(
            node_name=heartbeat.get("nodeName", ""),
            if_name="",  # receiver uses its rx interface
            seq_num=heartbeat.get("seqNum", 0),
        )
    handshake = d.get("handshakeMsg")
    if handshake is not None:
        pkt.handshake = SparkHandshakeMsg(
            node_name=handshake.get("nodeName", ""),
            if_name="",  # remote interface comes from the hello msg
            is_adj_established=handshake.get("isAdjEstablished", False),
            hold_time_ms=handshake.get("holdTime", 3000),
            graceful_restart_time_ms=handshake.get(
                "gracefulRestartTime", 30000
            ),
            transport_address_v6=_addr_from_wire(
                handshake.get("transportAddressV6", {})
            ),
            transport_address_v4=_addr_from_wire(
                handshake.get("transportAddressV4", {})
            ),
            openr_ctrl_port=handshake.get("openrCtrlThriftPort", 2018),
            area=handshake.get("area", "0"),
            neighbor_node_name=handshake.get("neighborNodeName"),
            kvstore_peer_port=handshake.get("kvStoreCmdPort", 0),
        )
    return pkt
