"""IoProvider: the raw-packet I/O seam under Spark.

Behavioral parity with the reference ``openr/spark/IoProvider.h`` (socket
syscall virtualization) and ``openr/tests/mocks/MockIoProvider.{h,cpp}``
(simulated multicast LAN with per-pair latency and partition control) —
so many Spark instances can run in one process over a controlled fabric.

A UDP-multicast-backed implementation for real deployments lives in
``UdpIoProvider`` (ff02::1-style iface-scoped multicast; reference:
Constants.h:136,263 port 6666).
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

# callback(local_if_name, payload_bytes)
RecvCallback = Callable[[str, bytes], None]


class IoProvider:
    def attach(self, if_name: str, callback: RecvCallback) -> None:
        """Open the interface for send/recv; deliver inbound packets to
        callback (from the provider's thread)."""
        raise NotImplementedError

    def detach(self, if_name: str) -> None:
        raise NotImplementedError

    def send(self, if_name: str, payload: bytes) -> None:
        """Multicast payload out of if_name."""
        raise NotImplementedError


class MockIoProvider(IoProvider):
    """Simulated LAN: packets sent on an iface are delivered to every
    connected iface after the configured latency.
    reference: tests/mocks/MockIoProvider.h:41."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # if_name -> [(peer_if_name, latency_ms)]
        self._connected: Dict[str, List[Tuple[str, int]]] = defaultdict(list)
        self._endpoints: Dict[str, RecvCallback] = {}
        self._partitioned: set = set()
        # (deliver_at_monotonic, seq, dst_if, payload)
        self._mailbox: "queue.PriorityQueue" = queue.PriorityQueue()
        self._seq = 0
        self._running = True
        self._thread = threading.Thread(
            target=self._process_mailboxes, name="mock-io", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._thread.join(timeout=2)

    # -- topology control (test API) --------------------------------------

    def set_connected_pairs(
        self, pairs: Dict[str, List[Tuple[str, int]]]
    ) -> None:
        """reference: MockIoProvider.h:83 setConnectedPairs."""
        with self._lock:
            self._connected = defaultdict(list, {
                k: list(v) for k, v in pairs.items()
            })

    def connect_pair(self, if_a: str, if_b: str, latency_ms: int = 1) -> None:
        with self._lock:
            self._connected[if_a].append((if_b, latency_ms))
            self._connected[if_b].append((if_a, latency_ms))

    def connect_one_way(
        self, if_from: str, if_to: str, latency_ms: int = 1
    ) -> None:
        """Unidirectional connectivity (the reference's ConnectedIfPairs
        is directional too): packets flow if_from -> if_to only — a
        broken-cable / asymmetric-filter scenario."""
        with self._lock:
            self._connected[if_from].append((if_to, latency_ms))

    def partition(self, if_name: str) -> None:
        """Drop all packets to/from if_name (link cut)."""
        with self._lock:
            self._partitioned.add(if_name)

    def heal(self, if_name: str) -> None:
        with self._lock:
            self._partitioned.discard(if_name)

    # -- IoProvider -------------------------------------------------------

    def attach(self, if_name: str, callback: RecvCallback) -> None:
        with self._lock:
            self._endpoints[if_name] = callback

    def detach(self, if_name: str) -> None:
        with self._lock:
            self._endpoints.pop(if_name, None)

    def send(self, if_name: str, payload: bytes) -> None:
        with self._lock:
            if if_name in self._partitioned:
                return
            peers = list(self._connected.get(if_name, ()))
            self._seq += 1
            seq = self._seq
        now = time.monotonic()
        for peer_if, latency_ms in peers:
            self._mailbox.put(
                (now + latency_ms / 1000.0, seq, peer_if, payload)
            )

    # -- delivery loop ----------------------------------------------------

    def _process_mailboxes(self) -> None:
        """reference: MockIoProvider.h:78 processMailboxes."""
        while self._running:
            try:
                deliver_at, seq, dst_if, payload = self._mailbox.get(
                    timeout=0.1
                )
            except queue.Empty:
                continue
            delay = deliver_at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            with self._lock:
                if dst_if in self._partitioned:
                    continue
                cb = self._endpoints.get(dst_if)
            if cb is not None:
                try:
                    cb(dst_if, payload)
                except Exception:
                    pass


class UdpIoProvider(IoProvider):
    """Link-local UDP multicast transport for real multi-host deployment
    (one socket per interface, mcast group + port as in the reference)."""

    MCAST_GROUP = "ff02::1"

    def __init__(self, port: int = 6666):
        self._port = port
        self._socks: Dict[str, socket.socket] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._running = True

    def attach(self, if_name: str, callback: RecvCallback) -> None:
        if_index = socket.if_nametoindex(if_name)
        sock = socket.socket(socket.AF_INET6, socket.SOCK_DGRAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(("::", self._port))
        group = socket.inet_pton(socket.AF_INET6, self.MCAST_GROUP)
        mreq = group + if_index.to_bytes(4, "little")
        sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_JOIN_GROUP, mreq)
        sock.setsockopt(
            socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_IF, if_index
        )
        sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_LOOP, 0)
        sock.settimeout(0.2)
        self._socks[if_name] = sock

        def recv_loop() -> None:
            while self._running and if_name in self._socks:
                try:
                    data, _ = sock.recvfrom(65535)
                except socket.timeout:
                    continue
                except OSError:
                    return
                callback(if_name, data)

        t = threading.Thread(
            target=recv_loop, name=f"udp-io:{if_name}", daemon=True
        )
        t.start()
        self._threads[if_name] = t

    def detach(self, if_name: str) -> None:
        sock = self._socks.pop(if_name, None)
        if sock is not None:
            sock.close()

    def send(self, if_name: str, payload: bytes) -> None:
        sock = self._socks.get(if_name)
        if sock is not None:
            sock.sendto(payload, (self.MCAST_GROUP, self._port))
