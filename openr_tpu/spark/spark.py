"""Spark: neighbor discovery over interface-scoped multicast.

Behavioral parity with the reference ``openr/spark/Spark.{h,cpp}``:

- periodic hello packets carrying reflected neighbor info so both ends
  confirm bidirectional visibility (processHelloMsg, Spark.cpp:1175)
- per-(iface, neighbor) FSM IDLE -> WARM -> NEGOTIATE -> ESTABLISHED with
  a RESTART state for graceful restart (Spark.h:45-51)
- handshake exchange negotiating area / hold times / transport addresses
  (processHandshakeMsg, Spark.cpp:1419)
- heartbeats refreshing the hold timer; expiry -> neighbor down
  (processHeartbeatMsg, Spark.cpp:1566)
- RTT measurement from the 4-timestamp echo (t4-t1)-(t3-t2) fed through a
  StepDetector so only significant changes re-advertise
- graceful-restart announcement on shutdown (floodRestartingMsg,
  Spark.h:92); a restarting neighbor's adjacency is held for its
  advertised GR window
- interface add/remove driven by InterfaceDatabase updates
  (processInterfaceUpdates, Spark.cpp:1703)

Events are published as SparkNeighborEvent on the neighbor-updates queue,
consumed by LinkMonitor.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.spark.io_provider import IoProvider
from openr_tpu.types import BinaryAddress
from openr_tpu.types.spark import (
    InterfaceDatabase,
    ReflectedNeighborInfo,
    SparkHandshakeMsg,
    SparkHeartbeatMsg,
    SparkHelloMsg,
    SparkNeighbor,
    SparkNeighborEvent,
    SparkNeighborEventType,
    SparkPacket,
)
from openr_tpu.spark import thrift_wire
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import OpenrEventBase
from openr_tpu.utils.stepdetector import StepDetector, StepDetectorConfig


class SparkNeighState(enum.IntEnum):
    """reference: Spark.h:45-51."""

    IDLE = 0
    WARM = 1
    NEGOTIATE = 2
    ESTABLISHED = 3
    RESTART = 4


def _now_us() -> int:
    return int(time.monotonic() * 1_000_000)


@dataclass
class _Neighbor:
    node_name: str
    local_if: str
    state: SparkNeighState = SparkNeighState.IDLE
    remote_if: str = ""
    area: str = ""
    seq_num: int = 0
    # reflection bookkeeping for RTT
    last_their_sent_ts_us: int = 0
    last_my_rcvd_ts_us: int = 0
    rtt_us: int = 0
    hold_time_ms: int = 3000
    gr_time_ms: int = 30000
    transport_v6: BinaryAddress = field(default_factory=BinaryAddress)
    transport_v4: BinaryAddress = field(default_factory=BinaryAddress)
    ctrl_port: int = 2018
    kvstore_peer_port: int = 0
    hold_timer=None
    gr_timer=None
    rtt_detector: Optional[StepDetector] = None

    def to_info(self) -> SparkNeighbor:
        return SparkNeighbor(
            node_name=self.node_name,
            local_if_name=self.local_if,
            remote_if_name=self.remote_if,
            transport_address_v6=self.transport_v6,
            transport_address_v4=self.transport_v4,
            openr_ctrl_port=self.ctrl_port,
            kvstore_peer_port=self.kvstore_peer_port,
            area=self.area,
            rtt_us=self.rtt_us,
        )


class Spark:
    def __init__(
        self,
        my_node_name: str,
        io_provider: IoProvider,
        neighbor_updates_queue: ReplicateQueue,
        interface_updates_queue: Optional[ReplicateQueue] = None,
        area: str = "0",
        interface_areas: Optional[Dict[str, str]] = None,
        hello_interval_s: float = 0.5,
        fast_hello_interval_s: float = 0.05,
        handshake_interval_s: float = 0.05,
        heartbeat_interval_s: float = 0.2,
        hold_time_s: float = 1.5,
        graceful_restart_time_s: float = 10.0,
        ctrl_port: int = 2018,
        kvstore_peer_port: int = 0,
        v4_addr: Optional[BinaryAddress] = None,
        v6_addr: Optional[BinaryAddress] = None,
        wire_format: str = "native",
        domain: str = "openr",
    ):
        self.my_node_name = my_node_name
        self.area = area
        # border routers place interfaces in different areas (reference:
        # per-area interface regexes in OpenrConfig AreaConfig); unlisted
        # interfaces fall back to the default area
        self._interface_areas = dict(interface_areas or {})
        self.evb = OpenrEventBase(name=f"spark:{my_node_name}")
        self._io = io_provider
        self._neighbor_updates = neighbor_updates_queue
        self._hello_interval = hello_interval_s
        self._fast_hello_interval = fast_hello_interval_s
        self._handshake_interval = handshake_interval_s
        self._heartbeat_interval = heartbeat_interval_s
        self._hold_time_ms = int(hold_time_s * 1000)
        self._gr_time_ms = int(graceful_restart_time_s * 1000)
        self._ctrl_port = ctrl_port
        # advertised to neighbors in handshakes so they can dial our
        # KvStore peer server (reference: Spark.thrift:97 kvStoreCmdPort)
        self._kvstore_peer_port = kvstore_peer_port
        # "native" = the framework codec; "thrift" = the reference's
        # CompactProtocol SparkHelloPacket layout (spark/thrift_wire.py)
        # so stock Open/R neighbors on the LAN can parse our packets.
        # RECEIVE always accepts both (format sniffed by first byte) —
        # the reference's own dual-stack migration pattern.
        assert wire_format in ("native", "thrift"), wire_format
        self._wire_format = wire_format
        # rides thrift-wire hellos as domainName: a stock Open/R
        # neighbor drops hellos whose domain mismatches its own
        self._domain = domain
        self._v4 = v4_addr or BinaryAddress()
        self._v6 = v6_addr or BinaryAddress()
        # if_name -> {neighbor_node -> _Neighbor}
        self._tracked: Dict[str, Dict[str, _Neighbor]] = {}
        self._timers: Dict[str, list] = {}
        self._seq = 0
        self.counters: Dict[str, int] = {
            "spark.hello_sent": 0,
            "spark.hello_recv": 0,
            "spark.handshake_sent": 0,
            "spark.heartbeat_sent": 0,
            "spark.neighbor_up": 0,
            "spark.neighbor_down": 0,
            "spark.invalid_version": 0,
        }
        if interface_updates_queue is not None:
            self.evb.add_queue_reader(
                interface_updates_queue.get_reader(f"spark:{my_node_name}"),
                self._on_interface_updates,
            )

    def set_kvstore_peer_port(self, port: int) -> None:
        """Set the advertised peer port once the KvStore peer server has
        bound (an ephemeral bind resolves only after construction).
        Must be called before start()."""
        self._kvstore_peer_port = port

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.evb.run_in_thread()

    def stop(self, graceful_restart: bool = False) -> None:
        if graceful_restart:
            self.evb.call_and_wait(self._flood_restarting)
        self.evb.stop()
        self.evb.join()
        for if_name in list(self._tracked):
            self._io.detach(if_name)

    # -- interface management --------------------------------------------

    def area_for_interface(self, if_name: str) -> str:
        return self._interface_areas.get(if_name, self.area)

    def add_interface(self, if_name: str) -> None:
        self.evb.call_and_wait(lambda: self._add_interface(if_name))

    def remove_interface(self, if_name: str) -> None:
        self.evb.call_and_wait(lambda: self._remove_interface(if_name))

    def _on_interface_updates(self, if_db: InterfaceDatabase) -> None:
        """reference: Spark.cpp:1703 processInterfaceUpdates."""
        want = {
            name for name, info in if_db.interfaces.items() if info.is_up
        }
        have = set(self._tracked)
        for name in want - have:
            self._add_interface(name)
        for name in have - want:
            self._remove_interface(name)

    def _add_interface(self, if_name: str) -> None:
        if if_name in self._tracked:
            return
        self._tracked[if_name] = {}
        self._io.attach(
            if_name,
            lambda local_if, data: self.evb.run_in_event_base(
                lambda: self._process_packet(local_if, data)
            ),
        )
        hello = self.evb.schedule_periodic(
            self._fast_hello_interval,
            lambda: self._send_hello(if_name),
        )
        heartbeat = self.evb.schedule_periodic(
            self._heartbeat_interval,
            lambda: self._send_heartbeat(if_name),
            jitter_first=True,
        )
        self._timers[if_name] = [hello, heartbeat]
        self._send_hello(if_name, solicit=True)

    def _remove_interface(self, if_name: str) -> None:
        neighbors = self._tracked.pop(if_name, {})
        for timer in self._timers.pop(if_name, []):
            timer.cancel()
        self._io.detach(if_name)
        for neighbor in neighbors.values():
            if neighbor.state in (
                SparkNeighState.ESTABLISHED,
                SparkNeighState.RESTART,
            ):
                self._emit(SparkNeighborEventType.NEIGHBOR_DOWN, neighbor)

    # -- senders ----------------------------------------------------------

    def _send_hello(
        self, if_name: str, solicit: bool = False, restarting: bool = False
    ) -> None:
        if if_name not in self._tracked:
            return
        self._seq += 1
        infos = {}
        for name, neighbor in self._tracked[if_name].items():
            infos[name] = ReflectedNeighborInfo(
                seq_num=neighbor.seq_num,
                last_nbr_msg_sent_ts_us=neighbor.last_their_sent_ts_us,
                last_my_msg_rcvd_ts_us=neighbor.last_my_rcvd_ts_us,
            )
        msg = SparkHelloMsg(
            node_name=self.my_node_name,
            if_name=if_name,
            seq_num=self._seq,
            neighbor_infos=infos,
            solicit_response=solicit,
            restarting=restarting,
            sent_ts_us=_now_us(),
        )
        self._io.send(if_name, self._encode(SparkPacket(hello=msg)))
        self.counters["spark.hello_sent"] += 1

    def _send_handshake(self, if_name: str, to_neighbor: str) -> None:
        msg = SparkHandshakeMsg(
            node_name=self.my_node_name,
            if_name=if_name,
            is_adj_established=self._tracked.get(if_name, {})
            .get(to_neighbor, _Neighbor("", ""))
            .state
            == SparkNeighState.ESTABLISHED,
            hold_time_ms=self._hold_time_ms,
            graceful_restart_time_ms=self._gr_time_ms,
            transport_address_v6=self._v6,
            transport_address_v4=self._v4,
            openr_ctrl_port=self._ctrl_port,
            kvstore_peer_port=self._kvstore_peer_port,
            area=self.area_for_interface(if_name),
            neighbor_node_name=to_neighbor,
        )
        self._io.send(if_name, self._encode(SparkPacket(handshake=msg)))
        self.counters["spark.handshake_sent"] += 1

    def _send_heartbeat(self, if_name: str) -> None:
        if if_name not in self._tracked:
            return
        if not any(
            n.state == SparkNeighState.ESTABLISHED
            for n in self._tracked[if_name].values()
        ):
            return
        self._seq += 1
        msg = SparkHeartbeatMsg(
            node_name=self.my_node_name,
            if_name=if_name,
            seq_num=self._seq,
            hold_time_ms=self._hold_time_ms,
        )
        self._io.send(if_name, self._encode(SparkPacket(heartbeat=msg)))
        self.counters["spark.heartbeat_sent"] += 1

    def flood_restarting(self) -> None:
        """Announce graceful restart on every tracked interface without
        stopping (reference: OpenrCtrl floodRestartingMsg)."""
        self.evb.call_and_wait(self._flood_restarting)

    def _flood_restarting(self) -> None:
        """reference: Spark.h:92 floodRestartingMsg."""
        for if_name in self._tracked:
            self._send_hello(if_name, restarting=True)

    # -- receive path -----------------------------------------------------

    # lowest protocol version we interoperate with (reference:
    # Spark.cpp packet validation against kOpenrSupportedVersion)
    LOWEST_SUPPORTED_VERSION = 1

    def _encode(self, pkt: SparkPacket) -> bytes:
        if self._wire_format == "thrift":
            return thrift_wire.encode_packet(pkt, domain=self._domain)
        return wire.dumps(pkt)

    def _process_packet(self, if_name: str, data: bytes) -> None:
        """reference: Spark.cpp:1597 processPacket."""
        if if_name not in self._tracked:
            return
        try:
            if data and data[0] == thrift_wire.NATIVE_MARKER:
                packet = wire.loads(data, SparkPacket)
            else:
                packet = thrift_wire.decode_packet(data)
        except Exception:
            return
        if packet.version < self.LOWEST_SUPPORTED_VERSION:
            self.counters["spark.invalid_version"] += 1
            return
        if packet.hello is not None:
            self._process_hello(if_name, packet.hello)
        elif packet.handshake is not None:
            self._process_handshake(if_name, packet.handshake)
        elif packet.heartbeat is not None:
            self._process_heartbeat(if_name, packet.heartbeat)

    def _get_or_create(self, if_name: str, node: str) -> _Neighbor:
        neighbors = self._tracked[if_name]
        if node not in neighbors:
            neighbors[node] = _Neighbor(node_name=node, local_if=if_name)
        return neighbors[node]

    def _process_hello(self, if_name: str, msg: SparkHelloMsg) -> None:
        """reference: Spark.cpp:1175 processHelloMsg."""
        if msg.node_name == self.my_node_name:
            return  # our own multicast echo
        self.counters["spark.hello_recv"] += 1
        now_us = _now_us()
        neighbor = self._get_or_create(if_name, msg.node_name)
        neighbor.remote_if = msg.if_name
        neighbor.seq_num = msg.seq_num
        neighbor.last_their_sent_ts_us = msg.sent_ts_us
        neighbor.last_my_rcvd_ts_us = now_us

        if msg.restarting:
            if neighbor.state in (
                SparkNeighState.ESTABLISHED,
                SparkNeighState.RESTART,
            ):
                self._enter_restart(neighbor)
            return

        if neighbor.state == SparkNeighState.IDLE:
            neighbor.state = SparkNeighState.WARM

        they_hear_us = self.my_node_name in msg.neighbor_infos
        if they_hear_us:
            refl = msg.neighbor_infos[self.my_node_name]
            # 4-timestamp RTT: (t4 - t1) - (t3 - t2)
            if refl.last_nbr_msg_sent_ts_us and refl.last_my_msg_rcvd_ts_us:
                rtt = (now_us - refl.last_nbr_msg_sent_ts_us) - (
                    msg.sent_ts_us - refl.last_my_msg_rcvd_ts_us
                )
                if rtt > 0:
                    self._update_rtt(neighbor, rtt)
            if neighbor.state == SparkNeighState.WARM:
                neighbor.state = SparkNeighState.NEGOTIATE
                self._send_handshake(if_name, neighbor.node_name)
            elif neighbor.state == SparkNeighState.NEGOTIATE:
                self._send_handshake(if_name, neighbor.node_name)
            elif neighbor.state == SparkNeighState.RESTART:
                # neighbor came back from graceful restart
                neighbor.state = SparkNeighState.ESTABLISHED
                self._cancel_timer(neighbor, "gr_timer")
                self._refresh_hold(neighbor)
                self._emit(
                    SparkNeighborEventType.NEIGHBOR_RESTARTED, neighbor
                )
        elif msg.solicit_response:
            self._send_hello(if_name, solicit=False)

    def _process_handshake(self, if_name: str, msg: SparkHandshakeMsg) -> None:
        """reference: Spark.cpp:1419 processHandshakeMsg."""
        if msg.node_name == self.my_node_name:
            return
        if (
            msg.neighbor_node_name is not None
            and msg.neighbor_node_name != self.my_node_name
        ):
            return
        neighbor = self._get_or_create(if_name, msg.node_name)
        if msg.area != self.area_for_interface(if_name):
            return  # area mismatch: no adjacency
        if msg.if_name:
            # the thrift wire's handshake carries no interface name; the
            # hello-learned remote_if stands (reference: the remote
            # ifName only rides SparkHelloMsg)
            neighbor.remote_if = msg.if_name
        neighbor.area = msg.area
        neighbor.hold_time_ms = msg.hold_time_ms
        neighbor.gr_time_ms = msg.graceful_restart_time_ms
        neighbor.transport_v6 = msg.transport_address_v6
        neighbor.transport_v4 = msg.transport_address_v4
        neighbor.ctrl_port = msg.openr_ctrl_port
        neighbor.kvstore_peer_port = msg.kvstore_peer_port

        if neighbor.state in (
            SparkNeighState.WARM,
            SparkNeighState.NEGOTIATE,
        ):
            neighbor.state = SparkNeighState.ESTABLISHED
            self._refresh_hold(neighbor)
            self.counters["spark.neighbor_up"] += 1
            self._emit(SparkNeighborEventType.NEIGHBOR_UP, neighbor)
            if not msg.is_adj_established:
                # make sure the other side can establish too
                self._send_handshake(if_name, neighbor.node_name)
        elif neighbor.state == SparkNeighState.ESTABLISHED:
            self._refresh_hold(neighbor)
            if not msg.is_adj_established:
                # the other side restarted its negotiation: answer so it
                # can (re-)establish
                self._send_handshake(if_name, neighbor.node_name)

    def _process_heartbeat(self, if_name: str, msg: SparkHeartbeatMsg) -> None:
        """reference: Spark.cpp:1566 processHeartbeatMsg."""
        if msg.node_name == self.my_node_name:
            return
        neighbor = self._tracked[if_name].get(msg.node_name)
        if neighbor is None or neighbor.state != SparkNeighState.ESTABLISHED:
            return
        self._refresh_hold(neighbor)

    # -- helpers ----------------------------------------------------------

    def _update_rtt(self, neighbor: _Neighbor, rtt_us: int) -> None:
        if neighbor.rtt_detector is None:
            neighbor.rtt_us = rtt_us

            def on_step(new_mean: float, neighbor=neighbor) -> None:
                neighbor.rtt_us = int(new_mean)
                if neighbor.state == SparkNeighState.ESTABLISHED:
                    self._emit(
                        SparkNeighborEventType.NEIGHBOR_RTT_CHANGE, neighbor
                    )

            neighbor.rtt_detector = StepDetector(
                StepDetectorConfig(
                    fast_window_size=10,
                    slow_window_size=60,
                    lower_threshold=2.0,
                    upper_threshold=5.0,
                    abs_threshold=500,
                ),
                on_step,
            )
        neighbor.rtt_detector.add_value(float(rtt_us))

    def _refresh_hold(self, neighbor: _Neighbor) -> None:
        self._cancel_timer(neighbor, "hold_timer")
        neighbor.hold_timer = self.evb.schedule_timeout(
            neighbor.hold_time_ms / 1000.0,
            lambda: self._hold_expired(neighbor),
        )

    def _cancel_timer(self, neighbor: _Neighbor, attr: str) -> None:
        timer = getattr(neighbor, attr, None)
        if timer is not None:
            timer.cancel()
            setattr(neighbor, attr, None)

    def _hold_expired(self, neighbor: _Neighbor) -> None:
        if neighbor.state == SparkNeighState.ESTABLISHED:
            self._neighbor_down(neighbor)

    def _enter_restart(self, neighbor: _Neighbor) -> None:
        """Graceful restart: hold the adjacency for the GR window."""
        neighbor.state = SparkNeighState.RESTART
        self._cancel_timer(neighbor, "hold_timer")
        self._cancel_timer(neighbor, "gr_timer")
        neighbor.gr_timer = self.evb.schedule_timeout(
            neighbor.gr_time_ms / 1000.0,
            lambda: self._gr_expired(neighbor),
        )
        self._emit(SparkNeighborEventType.NEIGHBOR_RESTARTING, neighbor)

    def _gr_expired(self, neighbor: _Neighbor) -> None:
        if neighbor.state == SparkNeighState.RESTART:
            self._neighbor_down(neighbor)

    def _neighbor_down(self, neighbor: _Neighbor) -> None:
        self._cancel_timer(neighbor, "hold_timer")
        self._cancel_timer(neighbor, "gr_timer")
        neighbor.state = SparkNeighState.IDLE
        self.counters["spark.neighbor_down"] += 1
        self._emit(SparkNeighborEventType.NEIGHBOR_DOWN, neighbor)
        self._tracked.get(neighbor.local_if, {}).pop(neighbor.node_name, None)

    def _emit(self, event_type: SparkNeighborEventType, neighbor: _Neighbor):
        self._neighbor_updates.push(
            SparkNeighborEvent(
                event_type=event_type, neighbor=neighbor.to_info()
            )
        )

    # -- introspection ----------------------------------------------------

    def get_neighbors(self) -> Dict[str, Dict[str, SparkNeighState]]:
        return self.evb.call_and_wait(
            lambda: {
                if_name: {n: nb.state for n, nb in neighbors.items()}
                for if_name, neighbors in self._tracked.items()
            }
        )

    def get_counters(self) -> Dict[str, int]:
        return self.evb.call_and_wait(lambda: dict(self.counters))
