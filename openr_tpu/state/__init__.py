"""Crash-safe state plane: WAL + checkpoint of the LSDB and the route
engine's warm-start material, persisted through ``PersistentStore``'s
atomic-commit path.

``StatePlane`` journals every accepted KvStore merge, collapses the
journal into a periodic checkpoint, and snapshots Decision's resident
ELL warm material (distance rows + patch journal + overload mask — the
per-tenant evict-to-host format from ``ops.world_batch`` generalized to
the primary engine). On boot, ``StatePlane.recover()`` replays
journal-over-checkpoint and ``Decision.warm_boot`` rehydrates the route
engine WARM: bit-identical RouteDatabase vs a cold oracle build, zero
jit compiles beyond persistent-cache hits.
"""

from openr_tpu.state.plane import (
    FAULT_CHECKPOINT_WRITE,
    JournalRecord,
    LsdbCheckpoint,
    RecoveredState,
    StatePlane,
)
from openr_tpu.state.snapshot import (
    EngineSnapshot,
    capture_engine_snapshot,
    graph_digest,
    rehydrate_engine,
)

__all__ = [
    "EngineSnapshot",
    "FAULT_CHECKPOINT_WRITE",
    "JournalRecord",
    "LsdbCheckpoint",
    "RecoveredState",
    "StatePlane",
    "capture_engine_snapshot",
    "graph_digest",
    "rehydrate_engine",
]
