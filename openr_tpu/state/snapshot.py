"""Engine warm-start snapshots: the resident ELL material as host bytes.

Generalizes ``ops.world_batch``'s per-tenant evict-to-host record
(packed host mirror + pending-edge journal + solved overload mask) to
the primary engine: everything ``EllState`` needs to warm-start its
next ``reconverge`` — the solved distance rows, the source batch they
belong to, the mergeable ``(tail, head) -> (w_snapshot, w_current)``
patch journal, the overload mask at the last solve, and the structural
flag — captured as a wire-encodable dataclass keyed by a digest of the
band graph it was solved under.

Rehydration is digest-gated: ``compile_ell`` over the recovered
LinkState must reproduce a bit-identical band graph (same node set,
band layout, weights, mask) for the distance rows to be valid warm
seeds. A journal that advanced past the snapshot changes the digest
and the engine seeds cold — slower, never wrong. Either way the warm
check in ``EllState.reconverge`` (``_warm_key`` vs the solve's source
batch) is the final gate, so a stale snapshot can only cost work.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from openr_tpu.telemetry import get_registry
from openr_tpu.utils import wire

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def _fnv1a(payload: bytes, h: int = _FNV_OFFSET) -> int:
    for b in payload:
        h ^= b
        h = (h * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def graph_digest(graph) -> int:
    """Content digest of a compiled ELL band graph.

    Covers everything a warm seed's validity depends on: node set and
    order, pad width, per-band source/weight arrays, and the overload
    mask. Two LinkStates with identical adjacency content compile to
    digest-equal graphs regardless of the per-process journal history.
    """
    head = wire.dumps(
        [int(graph.n), int(graph.n_pad), list(graph.node_names)]
    )
    h = _fnv1a(head)
    for arr in (*graph.src, *graph.w):
        h = _fnv1a(np.ascontiguousarray(np.asarray(arr)).tobytes(), h)
    ov = np.ascontiguousarray(np.asarray(graph.overloaded, dtype=np.uint8))
    h = _fnv1a(ov.tobytes(), h)
    return h


@dataclass
class EngineSnapshot:
    """Wire-encodable resident warm material for one area's engine."""

    area: str = ""
    graph_digest: int = 0
    warm_key: Tuple[int, ...] = ()
    batch: int = 0
    n_pad: int = 0
    d_rows: bytes = b""  # int32 [batch, n_pad], row-major
    pending_edges: Dict[Tuple[int, int], Tuple[int, int]] = field(
        default_factory=dict
    )
    ov_solved: bytes = b""  # uint8 [n_pad]
    pending_structural: bool = False


def capture_engine_snapshot(area: str, ls) -> Optional["EngineSnapshot"]:
    """Snapshot the resident warm material for ``ls``, if any.

    Returns None when the resident cache has no version-matched solved
    state for this LinkState (nothing warm to persist). Reads the
    device distance rows back to host — call outside a solve window,
    after a rebuild settles.
    """
    from openr_tpu.decision import spf_solver

    state = spf_solver.export_resident_state(ls)
    if state is None:
        return None
    d_host = np.asarray(state._d_dev, dtype=np.int32)
    ov = np.asarray(state._ov_solved, dtype=np.uint8)
    return EngineSnapshot(
        area=area,
        graph_digest=graph_digest(state.graph),
        warm_key=tuple(int(s) for s in state._warm_key),
        batch=int(d_host.shape[0]),
        n_pad=int(d_host.shape[1]),
        d_rows=d_host.tobytes(),
        pending_edges={
            (int(s), int(h)): (int(a), int(b))
            for (s, h), (a, b) in state._pending_edges.items()
        },
        ov_solved=ov.tobytes(),
        pending_structural=bool(state._pending_structural),
    )


def rehydrate_engine(ls, snap: Optional["EngineSnapshot"]) -> bool:
    """Seed the resident ELL cache for ``ls`` from a snapshot.

    Compiles the band layout from the LinkState (host work, no jit)
    and, when the compiled graph digest matches the snapshot, restores
    the solved distance rows + journal so the next ``reconverge`` runs
    WARM. Digest mismatch (or no snapshot) seeds a cold resident state
    — still saving the resident cache's own full compile at first use.
    Returns True on a warm seed.
    """
    import jax.numpy as jnp

    from openr_tpu.decision import spf_solver
    from openr_tpu.ops import spf_sparse

    reg = get_registry()
    graph = spf_sparse.compile_ell(ls)
    state = spf_sparse.EllState(graph)
    warm = (
        snap is not None
        and snap.batch > 0
        and snap.n_pad == int(graph.n_pad)
        and graph_digest(graph) == snap.graph_digest
    )
    if warm:
        d = np.frombuffer(snap.d_rows, dtype=np.int32).reshape(
            snap.batch, snap.n_pad
        )
        state._d_dev = jnp.asarray(d)
        state._warm_key = tuple(int(s) for s in snap.warm_key)
        state._pending_edges = {
            (int(s), int(h)): (int(a), int(b))
            for (s, h), (a, b) in snap.pending_edges.items()
        }
        state._ov_solved = (
            np.frombuffer(snap.ov_solved, dtype=np.uint8)
            .astype(bool)
            .copy()
        )
        state._pending_structural = bool(snap.pending_structural)
        reg.counter_bump("state.warm_seeds")
    else:
        reg.counter_bump("state.cold_seeds")
    spf_solver.seed_resident_state(ls, state)
    return warm
