"""Write-ahead journal + periodic checkpoint of the KvStore LSDB.

Record layout inside the ``PersistentStore`` (one wire-encoded object
per key, committed tmp+rename+fsync by the store):

- ``state:lsdb:ckpt``              — ``LsdbCheckpoint``: the full
  ``{area: {key: Value}}`` map as of journal seq ``seq`` (exclusive).
- ``state:lsdb:journal:<seq>``     — ``JournalRecord``: one accepted
  KvStore merge (the post-CRDT-merge winners only), zero-padded seq so
  the store's sorted key order IS replay order.
- ``state:engine:<area>``          — ``EngineSnapshot``: the resident
  ELL warm material for that area's primary engine (see
  ``state.snapshot``).

Recovery ordering: load checkpoint, replay journal records with
``seq >= ckpt.seq`` in seq order (accepted updates are strictly newer
under the CRDT merge order, so a plain per-key overwrite replays the
merge), then rehydrate engines against the recovered LSDB — an engine
snapshot whose graph digest no longer matches (journal advanced past
it) seeds cold, never wrong.

The ``state.checkpoint_write`` fault seam fires before the checkpoint
commit: a failed checkpoint leaves the journal intact, so chaos storms
prove checkpoint loss is recoverable.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional

from openr_tpu.config_store.persistent_store import PersistentStore
from openr_tpu.faults import FaultInjected, fault_point, register_fault_site
from openr_tpu.state.snapshot import EngineSnapshot
from openr_tpu.telemetry import get_registry
from openr_tpu.types.kvstore import Value

FAULT_CHECKPOINT_WRITE = register_fault_site("state.checkpoint_write")

_CKPT_KEY = "state:lsdb:ckpt"
_JOURNAL_PREFIX = "state:lsdb:journal:"
_ENGINE_PREFIX = "state:engine:"


@dataclass
class JournalRecord:
    """One accepted KvStore merge (post-merge winners only)."""

    seq: int = 0
    area: str = ""
    key_vals: Dict[str, Value] = field(default_factory=dict)


@dataclass
class LsdbCheckpoint:
    """Full LSDB as of journal ``seq`` (exclusive)."""

    seq: int = 0
    key_vals_by_area: Dict[str, Dict[str, Value]] = field(
        default_factory=dict
    )


@dataclass
class RecoveredState:
    """What ``StatePlane.recover()`` hands the warm-booting process."""

    key_vals_by_area: Dict[str, Dict[str, Value]] = field(
        default_factory=dict
    )
    engine_snapshots: Dict[str, EngineSnapshot] = field(
        default_factory=dict
    )
    journal_replayed: int = 0
    had_checkpoint: bool = False


def _journal_key(seq: int) -> str:
    return f"{_JOURNAL_PREFIX}{seq:012d}"


def journal_suffix(records, applied_seq: int) -> list:
    """The un-applied tail of an ordered journal: every record whose
    ``seq`` is strictly past ``applied_seq``. ONE definition shared by
    recovery (replay past the checkpoint anchor, whose ``seq`` is
    exclusive) and the fleet plane's replica stream, where the
    never-promote-past-an-un-shipped-suffix hazard rule is exactly
    "this list must be empty — or its loss consciously counted —
    before a standby may take over" (fleet/journal.py)."""
    return [
        rec for rec in records
        if rec is not None and rec.seq > applied_seq
    ]


def replay_journal(
    ckpt: Optional[LsdbCheckpoint],
    records: Iterable[JournalRecord],
) -> Dict[str, Dict[str, Value]]:
    """The checkpoint+journal recovery fold, as a pure function: start
    from the checkpoint LSDB (empty when None), apply every record with
    ``seq >= ckpt.seq`` in the given order as a plain per-key overwrite
    (post-CRDT winners are strictly newer, so overwrite IS the merge).

    ``recover()`` uses it against the backing store; the incident
    replayer (``twin/replay.py``) uses it against a post-mortem
    bundle's anchor + journal slice — one recovery semantics, two
    sources."""
    lsdb: Dict[str, Dict[str, Value]] = {}
    base_seq = 0
    if ckpt is not None:
        lsdb = {a: dict(kv) for a, kv in ckpt.key_vals_by_area.items()}
        base_seq = ckpt.seq
    for rec in journal_suffix(records, base_seq - 1):
        lsdb.setdefault(rec.area, {}).update(rec.key_vals)
    return lsdb


class StatePlane:
    """The WAL/checkpoint writer and the boot-time replayer.

    Journal appends arrive on the KvStore evb (via the merge hook);
    checkpoints may be cut from any thread. The in-memory LSDB mirror
    under ``_lock`` is the checkpoint source — it is exactly
    checkpoint + journal, so a checkpoint never needs to re-read disk.
    """

    def __init__(
        self, store: PersistentStore, checkpoint_every: int = 64
    ) -> None:
        self._store = store
        self._lock = threading.Lock()
        self._lsdb: Dict[str, Dict[str, Value]] = {}
        self._next_seq = 0
        self._ckpt_seq = 0
        self._checkpoint_every = max(1, int(checkpoint_every))
        self._replaying = False

    # -- journal ------------------------------------------------------

    def on_kvstore_merge(
        self, area: str, updates: Dict[str, Value]
    ) -> None:
        """KvStore merge hook: journal one accepted update batch.

        Called with the post-merge winners only (strictly newer under
        the CRDT order), so the mirror update is a plain overwrite.
        """
        if not updates or self._replaying:
            return
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._lsdb.setdefault(area, {}).update(updates)
            journal_len = self._next_seq - self._ckpt_seq
        self._store.store(
            _journal_key(seq),
            JournalRecord(seq=seq, area=area, key_vals=dict(updates)),
        )
        get_registry().counter_bump("state.journal_appends")
        if journal_len >= self._checkpoint_every:
            self.maybe_checkpoint()

    # -- checkpoint ---------------------------------------------------

    def checkpoint(self) -> None:
        """Collapse the journal into a fresh full-LSDB checkpoint.

        Raises if the commit fails (including the injected
        ``state.checkpoint_write`` seam); the journal is untouched on
        failure, so recovery replays through the old checkpoint.
        """
        with self._lock:
            upto = self._next_seq
            snap = {a: dict(kv) for a, kv in self._lsdb.items()}
        fault_point(FAULT_CHECKPOINT_WRITE)
        self._store.store(
            _CKPT_KEY, LsdbCheckpoint(seq=upto, key_vals_by_area=snap)
        )
        for key in self._store.keys():
            if key.startswith(_JOURNAL_PREFIX):
                if int(key[len(_JOURNAL_PREFIX):]) < upto:
                    self._store.erase(key)
        with self._lock:
            self._ckpt_seq = max(self._ckpt_seq, upto)
        reg = get_registry()
        reg.counter_bump("state.checkpoint_writes")
        reg.counter_set("state.checkpoint_seq", upto)

    def checkpoint_due(self) -> bool:
        """True when the journal has grown past the checkpoint cadence."""
        return self.journal_length() >= self._checkpoint_every

    def maybe_checkpoint(self, only_if_due: bool = False) -> bool:
        """Checkpoint, absorbing failures (counted, journal intact).

        With ``only_if_due`` the cut is cadence-gated: callers on hot
        paths (Decision's post-converge hook) skip the full-LSDB write
        until the journal has actually grown past ``checkpoint_every``.
        """
        if only_if_due and not self.checkpoint_due():
            return False
        try:
            self.checkpoint()
            return True
        except (FaultInjected, OSError, ValueError, TypeError):
            get_registry().counter_bump("state.checkpoint_failures")
            return False

    # -- engine snapshots ---------------------------------------------

    def record_engine_snapshot(self, snap: EngineSnapshot) -> None:
        self._store.store(f"{_ENGINE_PREFIX}{snap.area}", snap)
        get_registry().counter_bump("state.engine_snapshots")

    # -- recovery -----------------------------------------------------

    def recover(self) -> RecoveredState:
        """Replay journal-over-checkpoint from the backing store.

        Also primes this plane's in-memory mirror and seq counters so
        the recovered process keeps journaling from where the crashed
        one stopped.
        """
        reg = get_registry()
        ckpt = self._store.load(_CKPT_KEY, LsdbCheckpoint)
        base_seq = ckpt.seq if ckpt is not None else 0
        journal: list = []
        for key in self._store.keys():  # sorted => seq order
            if not key.startswith(_JOURNAL_PREFIX):
                continue
            rec = self._store.load(key, JournalRecord)
            if rec is None or rec.seq < base_seq:
                continue
            journal.append(rec)
        lsdb = replay_journal(ckpt, journal)
        replayed = len(journal)
        max_seq = max([base_seq] + [rec.seq + 1 for rec in journal])
        engines: Dict[str, EngineSnapshot] = {}
        for key in self._store.keys():
            if key.startswith(_ENGINE_PREFIX):
                snap = self._store.load(key, EngineSnapshot)
                if snap is not None:
                    engines[snap.area] = snap
        with self._lock:
            self._lsdb = {a: dict(kv) for a, kv in lsdb.items()}
            self._next_seq = max_seq
            self._ckpt_seq = base_seq
        reg.counter_bump("state.recoveries")
        reg.counter_bump("state.journal_replayed", replayed)
        return RecoveredState(
            key_vals_by_area=lsdb,
            engine_snapshots=engines,
            journal_replayed=replayed,
            had_checkpoint=ckpt is not None,
        )

    # -- introspection ------------------------------------------------

    def journal_length(self) -> int:
        with self._lock:
            return self._next_seq - self._ckpt_seq

    def flight_anchor(self) -> Dict[str, int]:
        """Anchor extras for the flight recorder's post-mortem bundles
        (installed via ``set_anchor_provider`` by a Decision that owns
        this plane): where the durable WAL stood when the bundle was
        cut, so an offline triager can pair the bundle with the
        matching on-disk checkpoint."""
        with self._lock:
            return {
                "state_checkpoint_seq": self._ckpt_seq,
                "state_journal_seq": self._next_seq,
            }

    def lsdb_mirror(self) -> Dict[str, Dict[str, Value]]:
        with self._lock:
            return {a: dict(kv) for a, kv in self._lsdb.items()}
