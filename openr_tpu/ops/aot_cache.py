"""AOT executable cache: the committed-dispatch hot path calls a
compiled executable directly, with zero Python retrace / signature
checks.

``jax.jit``'s call path re-derives the (args -> executable) key on
every invocation: pytree flatten, static-argument hashing, signature
canonicalization — tens of microseconds of host work per dispatch that
the churn path pays thousands of times per second. This cache hoists
that work to the FIRST call per shape: ``jit(fn).lower(*dyn,
**statics).compile()`` bakes the statics into a ``Compiled`` executable
that is then invoked with the dynamic operands only (passing a static
again at call time is a pytree mismatch — the statics no longer exist
as parameters). Every later event with the same shape key goes
``dict lookup -> executable`` and nothing else.

Keying: ``(tag, statics, dynamic signature)`` where the dynamic
signature is the pytree structure plus per-leaf (shape, dtype,
sharding). Sharding is part of the key on purpose: an executable
compiled for single-chip operands cannot consume mesh-sharded
residents, and the single-chip and mesh engines of one test process
share this process-global cache.

Fallback ladder (never raises past the jitted semantics): a failed
lower/compile poisons the key and the call rides the plain jitted
function (``ops.aot_fallbacks``); a failed EXECUTABLE call (placement
drift, donated-buffer reuse, transfer guards) falls back the same way
per call. The executables themselves ride jax's persistent compilation
cache when one is configured, so "compile on miss" is a disk load, not
an XLA run, across processes.

Counters: ``ops.aot_compiles`` / ``ops.aot_hits`` /
``ops.aot_fallbacks``. Every call counts one committed dispatch via
``dispatch_accounting.count_dispatch``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from openr_tpu.ops import dispatch_accounting
from openr_tpu.telemetry import get_registry
from openr_tpu.telemetry.profiler import get_profiler

_UNCOMPILABLE = object()  # poison marker: lower/compile failed once


def _profiled(tag: str, thunk):
    """Run one dispatch under device-time attribution: host wall time
    always, sampled block-for-ready device time per the profiler's
    cadence, both folded into the active event window's stage table.
    Disabled profiler == the bare call (one attribute read)."""
    prof = get_profiler()
    if not prof.enabled:
        return thunk()
    with prof.annotate(tag):
        t0 = time.perf_counter()
        out = thunk()
        host_ms = (time.perf_counter() - t0) * 1000.0
    device_ms = prof.on_dispatch(tag, out, host_ms)
    dispatch_accounting.attribute_stage(tag, host_ms, device_ms)
    return out


def cache_dir() -> Optional[str]:
    """Directory the persistent artifacts (autotune winners, jax's
    compilation cache when the caller wires it) live in. None when
    ``OPENR_CACHE_DIR`` is unset — in-memory only, no disk writes."""
    d = os.environ.get("OPENR_CACHE_DIR")
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    return d


def _leaf_sig(leaf: Any) -> Tuple:
    if isinstance(leaf, jax.Array):
        try:
            sh = leaf.sharding
        except Exception:  # noqa: BLE001 - deleted/traced arrays
            sh = None
        return (tuple(leaf.shape), str(leaf.dtype), sh)
    if isinstance(leaf, np.ndarray):
        return (tuple(leaf.shape), str(leaf.dtype), "host")
    return (type(leaf).__name__, leaf if isinstance(
        leaf, (bool, int, float, str, type(None))) else None)


def signature(dyn_args: Tuple) -> Tuple:
    leaves, treedef = jax.tree_util.tree_flatten(dyn_args)
    return (treedef, tuple(_leaf_sig(x) for x in leaves))


class AotDispatchCache:
    """Process-global (tag, statics, signature) -> Compiled map."""

    def __init__(self) -> None:
        self._exes: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()

    def stats(self) -> Dict[str, int]:
        reg = get_registry()
        return {
            "entries": len(self._exes),
            "compiles": int(reg.counter_get("ops.aot_compiles")),
            "hits": int(reg.counter_get("ops.aot_hits")),
            "fallbacks": int(reg.counter_get("ops.aot_fallbacks")),
        }

    def clear(self) -> None:
        with self._lock:
            self._exes.clear()

    def _lookup(self, tag: str, fn, dyn_args: Tuple,
                statics: Dict[str, Any]):
        try:
            key = (tag, tuple(sorted(statics.items())),
                   signature(dyn_args))
            hash(key)
        except TypeError:
            return None, None  # unhashable statics: jitted path
        exe = self._exes.get(key)
        return key, exe

    def call(self, tag: str, fn, dyn_args: Tuple,
             statics: Dict[str, Any]):
        """Dispatch ``fn(*dyn_args, **statics)`` through the cached
        executable for this shape key, compiling it on first miss."""
        reg = get_registry()
        dispatch_accounting.count_dispatch()
        key, exe = self._lookup(tag, fn, dyn_args, statics)
        if key is None or exe is _UNCOMPILABLE:
            reg.counter_bump("ops.aot_fallbacks")
            return _profiled(tag, lambda: fn(*dyn_args, **statics))
        if exe is None:
            try:
                exe = fn.lower(*dyn_args, **statics).compile()
            except Exception:  # noqa: BLE001 - poison + jitted path
                with self._lock:
                    self._exes[key] = _UNCOMPILABLE
                reg.counter_bump("ops.aot_fallbacks")
                return _profiled(tag, lambda: fn(*dyn_args, **statics))
            with self._lock:
                self._exes[key] = exe
            reg.counter_bump("ops.aot_compiles")
        else:
            reg.counter_bump("ops.aot_hits")
        try:
            # dynamic operands ONLY: the statics were baked at lower
            # time and no longer exist as parameters of the executable
            return _profiled(tag, lambda: exe(*dyn_args))
        except Exception:  # noqa: BLE001 - absorb into jitted path
            reg.counter_bump("ops.aot_fallbacks")
            return _profiled(tag, lambda: fn(*dyn_args, **statics))

    def warm(self, tag: str, fn, dyn_args: Tuple,
             statics: Dict[str, Any]) -> bool:
        """Build (or load from jax's persistent cache) the executable
        for this shape key without running it — the engine-construction
        prewarm."""
        key, exe = self._lookup(tag, fn, dyn_args, statics)
        if key is None or exe is _UNCOMPILABLE:
            return False
        if exe is not None:
            return True
        try:
            exe = fn.lower(*dyn_args, **statics).compile()
        except Exception:  # noqa: BLE001 - poison, warm is best-effort
            with self._lock:
                self._exes[key] = _UNCOMPILABLE
            return False
        with self._lock:
            self._exes[key] = exe
        get_registry().counter_bump("ops.aot_compiles")
        return True


_CACHE = AotDispatchCache()


def get_aot_cache() -> AotDispatchCache:
    return _CACHE


def aot_call(tag: str, fn, dyn_args: Tuple, statics: Dict[str, Any]):
    return _CACHE.call(tag, fn, dyn_args, statics)
