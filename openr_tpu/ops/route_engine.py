"""Incremental destination-major route sweep: churn re-solves ONLY the
affected destinations, on device, in one dispatch.

The full route sweep (ops.route_sweep) computes the network-wide route
product — per-destination digests, next-hop structure for every
source — in N_pad/B blocks. Under churn that is wasteful: a metric
change touches few destinations' shortest-path structure.

The destination-major orientation makes incrementality EXACT and
simple: row t of DR is an independent single-destination problem
(reverse SPF to t) — rows never interact — so re-solving an arbitrary
subset of rows from scratch is correct regardless of what changed.
That sidesteps the monotonicity trap of in-place re-relaxation (weight
increases cannot be fixed by further min-relaxation).

Per churn event — metric changes, overload flips, AND link add/remove
between known nodes (the detection diffs the directed edge set, so a
removed edge that was tight or an added edge that improves/ties marks
the row; a row outgrowing its slot class widens its band in place,
ell_patch(widen=True), preserving node ids and the resident DR). Only
node add/remove — a renumbering event — cold-rebuilds:

1. host: diff the changed directed edges {(u, v): w_old -> w_new} and
   overload flips (an O(degree) LinkState journal read),
2. ONE fused device dispatch over the RESIDENT state:
   a. affected-row detection against the resident DR — row t is
      affected iff some changed edge was TIGHT in the old graph
      (DR[t, u] == w_old + DR[t, v], it may have carried a shortest
      path) or IMPROVES in the new one (w_new + DR[t, v] < DR[t, u]).
      Overload flips inject their incident edges with effective
      weights on both sides. The test is sound-conservative: it can
      only over-select (distances enter unchanged rows' relaxations
      never),
   b. scatter the patched band rows (O(degree) transfer),
   c. re-init + fixed-point the affected rows (a [K, N] solve,
      bucketed to a handful of compiled shapes),
   d. route extraction (nh counts, canonical digests, sample rows)
      for exactly those rows, scatter the fresh rows/digests into the
      resident state,
3. readback: DELTA-COMPACTED on device — the fresh product rows are
   diffed bit-for-bit against the resident previous packed product and
   prefix-sum-compacted, so only the rows that actually CHANGED cross
   the device->host boundary (plus a 2-int meta row carrying the
   affected and changed counts) — O(changed), not O(K) and never
   O(N^2); the caller sees which destinations moved and their fresh
   routes,
4. consume: the compacted readback stays an IN-FLIGHT device array.
   The device state commits immediately and the host applies event k's
   delta into the resident RouteSweepResult while event k+1's
   patch+solve dispatches (the double-buffer overlap window —
   ``churn(..., defer_consume=True)`` hands the caller the
   PendingDelta handle explicitly; the default consumes synchronously
   before returning, preserving the classic contract).

Memory: DR stays device-resident at [n_pad, n_pad] int32 — whole on a
single chip (~400 MB at 10k, 12k bound, the same envelope as the
incremental KSP2 engine), or ROW-SHARDED over a device mesh
(``mesh=`` at construction): each device owns n_pad/ndev destination
rows, detection + re-solve run per shard (rows never interact; the
only collective is the 1-bit convergence vote), and the bound scales
with sqrt(ndev) — ~100k on a 64-way mesh. The sharded event costs two
dispatches (band patch + detect/solve) instead of one.

Reference semantics: the product matches SpfSolver::buildRouteDb /
getNextHopsWithMetric (Decision.cpp:569-734, :1124) for every source
toward every destination; the incremental contract mirrors
Decision's debounced incremental rebuilds (Decision.cpp route rebuild
on delta) at the network-wide scale.
"""

from __future__ import annotations

import functools
import os
import random
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.ops.spf import INF
from openr_tpu.ops import host_sweep
from openr_tpu.ops import route_sweep as rs
from openr_tpu.ops.spf_sparse import (
    _out_edges,
    _tenant_view_solve,
    compile_ell,
    ell_dispatch,
    ell_patch,
    pad_patch_rows,
)
from openr_tpu.analysis.annotations import (
    committed_dispatch,
    fault_boundary,
    mirrored_by,
    requires_drain,
    resident_buffers,
    solve_window,
)
from openr_tpu.ops import dispatch_accounting as da
from openr_tpu.ops.aot_cache import aot_call, get_aot_cache
from openr_tpu.faults.injector import (
    consume_fault,
    fault_point,
    is_device_loss,
    register_fault_site,
)
from openr_tpu.faults.supervisor import DegradationSupervisor
from openr_tpu.integrity import ResidentEngineContract, get_auditor
from openr_tpu.integrity import kernels as integrity_kernels
from openr_tpu.telemetry import get_flight_recorder, get_registry, get_tracer

# degradation-ladder injection sites (armable by name; see
# openr_tpu.faults.injector)
FAULT_DISPATCH = register_fault_site("route_engine.dispatch")
FAULT_CONSUME = register_fault_site("route_engine.consume")
FAULT_COLD_BUILD = register_fault_site("route_engine.cold_build")
FAULT_FRONTIER = register_fault_site("route_engine.frontier_resolve")
# the accelerator itself dying under the residents (vs. a failed
# dispatch on a healthy device): fires at the same dispatch/consume
# crossings, recognized by faults.is_device_loss, recovered by the
# ladder's dedicated rung (_device_recover)
FAULT_DEVICE_LOST = register_fault_site("device.lost")
# silent corruption: a CONSUMED (non-raising) seam at the churn /
# solve_views entries that flips seeded bits in the live residents —
# the integrity plane's audit tiers must then detect within one
# cadence and heal bit-identically (tools/integrity_smoke.py)
FAULT_CORRUPT = register_fault_site("device.corrupt_resident")

ENGINE_MAX_NODES = 12288  # same residency envelope as ksp2_engine
# affected-row solve buckets: the dispatch runs at the hint bucket and
# RETRIES at a larger one on overflow (the jit is functional — nothing
# commits until the count fits, so a retry re-detects against the
# untouched resident state); beyond the largest bucket the event takes
# the FULL-WIDTH refresh — the patched resident layout is kept and
# every row re-solves in one cold-build-shaped dispatch, skipping the
# host layout recompile that makes a true cold build expensive (a
# fat-tree link up/down event affects every destination row through
# ECMP next-hop churn, so past 1024 nodes this is the common link-event
# path — first measured on-chip at 10k, where bucket overflow used to
# cold-rebuild 10/10 link events)
_ROW_BUCKETS = (32, 128, 512, 1024)
# frontier cone-expansion jump cap (static per compiled shape): each
# jump costs one relax-shaped pass, so past this the cone is deeper
# than re-deriving it is worth — the bucketed seed degrades to the
# whole-row reset and the overflow path to the full-width refresh,
# both still bit-identical (the cap only ever coarsens the reset)
_FRONTIER_MAX_JUMPS = 16
# fraction of rows past which a converged frontier still falls back to
# the full-width refresh (constructor-overridable): with most rows in
# the cone the warm seed saves nothing over the cold-shaped dispatch
# and the probe already paid its cost
_DEFAULT_FRONTIER_THRESHOLD = 0.5


def _pack_product(dr, nh_count, d_s, packed_mask, pos_w):
    """The ONE packing site for the engine's per-row route product:
    [digest, nh_total, sample metrics, sample masks] — shared by every
    cold build and churn dispatch of BOTH backends, which is what
    keeps the cross-backend digest contract a single definition.
    Returns (digests, packed [B, W])."""
    digests = rs._digest_rows(dr, nh_count, pos_w)
    nh_total = jnp.sum(nh_count, axis=1, dtype=jnp.int32)
    b = dr.shape[0]
    packed = jnp.concatenate(
        [
            jax.lax.bitcast_convert_type(digests, jnp.int32)[:, None],
            nh_total[:, None],
            d_s,
            jax.lax.bitcast_convert_type(
                packed_mask, jnp.int32
            ).reshape(b, -1),
        ],
        axis=1,
    )
    return digests, packed


@functools.partial(jax.jit, static_argnames=("bands", "n"))
def _full_resident_sweep(v_t, w_t, overloaded, samp_ids, samp_v,
                         samp_w, pos_w, bands, n):
    """Cold build: solve ALL destination rows, extract the route
    product, return (DR, digests, packed) with DR + digests staying
    resident. One dispatch at engine scale (n <= 12k)."""
    t_ids = jnp.arange(n, dtype=jnp.int32)
    dr = rs._rev_fixed_point(bands, v_t, w_t, overloaded, t_ids, n)
    nh_count = rs._nh_counts(dr, bands, v_t, w_t, overloaded, t_ids)
    d_s, packed_mask = rs._sample_stats(
        dr, samp_ids, samp_v, samp_w, overloaded, t_ids
    )
    digests, packed = _pack_product(
        dr, nh_count, d_s, packed_mask, pos_w
    )
    return dr, digests, packed


def _detect_rows(dr, e_u, e_v, e_w_old, e_w_new, k, row_start):
    """Affected-row detection against a (shard of the) RESIDENT
    pre-patch DR. Raw weights (not overload-effective) make the test
    conservative: coincidental tightness over-selects, never
    under-selects; overload flips arrive as INF transitions from the
    host.

    Old side: the edge was TIGHT (it may have carried a shortest path
    or an ECMP tie that the change breaks). New side is NON-strict: an
    edge landing exactly ON the current best creates new equal-cost
    next hops — distances unchanged, ECMP masks (and digests) changed
    (the undrain case).

    Returns (count, local row ids [k], global destination ids [k]);
    padding entries repeat the FIRST affected id so every duplicate
    scatter index writes an identical fresh row — deterministic and
    correct."""
    dr_u = dr[:, e_u]  # [rows, E]
    dr_v = dr[:, e_v]
    tight_old = dr_u == jnp.minimum(e_w_old[None, :] + dr_v, INF)
    ties_or_improves_new = (
        jnp.minimum(e_w_new[None, :] + dr_v, INF) <= dr_u
    )
    usable = (e_w_old[None, :] < INF) | (e_w_new[None, :] < INF)
    affected = jnp.any(
        (tight_old | ties_or_improves_new) & usable, axis=1
    )  # [rows]
    count = jnp.sum(affected.astype(jnp.int32))
    local = jnp.nonzero(affected, size=k, fill_value=0)[0].astype(
        jnp.int32
    )
    valid = jnp.arange(k) < count
    local = jnp.where(valid, local, local[0])
    return count, local, local + row_start


def _increase_rows(dr, e_u, e_v, e_w_old, e_w_new):
    """Rows whose resident DR may UNDERESTIMATE the post-patch
    distances: some edge whose weight went UP was tight under the old
    row. Every other affected row keeps its old row as a sound warm
    seed for the re-solve — same argument as spf_sparse._warm_seed,
    destination-major (old rows are valid upper bounds under pure
    decreases and equal-cost ties)."""
    tight_old = dr[:, e_u] == jnp.minimum(
        e_w_old[None, :] + dr[:, e_v], INF
    )
    return jnp.any(tight_old & (e_w_new > e_w_old)[None, :], axis=1)


def _resolve_and_pack(
    solve_rows, nh_counts, overloaded, ids, local_ids, count, dr,
    digests, packed_res, samp_ids, samp_v, samp_w, pos_w, n, k,
):
    """Re-init + fixed-point the affected rows (independent problems),
    extract their route product, scatter fresh rows/digests/product
    into the resident (shard of) DR. When count == 0 every id repeats
    one row and the write is that row's own fresh re-solve: a no-op by
    value. Returns (dr, digests, packed_res, out [k+1, 1+W]):

      out row 0: [affected_count, changed_count, 0, ...] — the TRUE
        affected count drives the overflow retry ladder; changed_count
        bounds the readback,
      out rows 1..changed_count: [dest id, product] for exactly the
        affected rows whose packed product CHANGED bit-for-bit against
        the resident previous product, prefix-sum-compacted in row
        order. Rows past changed_count are zero.

    The changed test compares FULL packed rows, not digests: a digest
    can survive a sample-mask flip (equal-cost slot swap keeps the
    distance and the fanout count while moving mask membership), so
    compacting on digests alone would drop real route changes.
    Detection padding repeats the first affected id; those duplicates
    fall outside the ``arange(k) < count`` live window and never reach
    the compaction, so compacted ids are unique.

    ``solve_rows(ids) -> [k, n]`` and ``nh_counts(rows, ids)`` are the
    relaxation-backend callables (ELL bands or grouped segments); the
    detection, scatter, digest and packing algebra is shared so the two
    backends stay bit-comparable."""
    rows = solve_rows(ids)
    nh_count = nh_counts(rows, ids)
    d_s, packed_mask = rs._sample_stats(
        rows, samp_ids, samp_v, samp_w, overloaded, ids
    )
    row_digests, product = _pack_product(
        rows, nh_count, d_s, packed_mask, pos_w
    )
    dr = dr.at[local_ids].set(rows)
    digests = digests.at[local_ids].set(row_digests)
    live = jnp.arange(k) < count
    changed = live & jnp.any(product != packed_res[local_ids], axis=1)
    ch_count = jnp.sum(changed.astype(jnp.int32))
    packed_res = packed_res.at[local_ids].set(product)
    body = jnp.concatenate([ids[:, None], product], axis=1)
    # prefix-sum compaction: changed rows scatter to 1..ch_count,
    # unchanged rows to the dropped out-of-bounds slot
    pos = jnp.cumsum(changed.astype(jnp.int32)) - 1
    dest = jnp.where(changed, pos + 1, k + 1)
    out = jnp.zeros((k + 1, body.shape[1]), dtype=jnp.int32)
    out = out.at[dest].set(body, mode="drop")
    out = out.at[0, 0].set(count)
    out = out.at[0, 1].set(ch_count)
    return dr, digests, packed_res, out


def _compact_changed_body(new_packed, prev_packed, n):
    """Full-width delta epilogue: diff the fresh [n_pad, W] packed
    product bit-for-bit against the resident previous one and
    prefix-sum-compact the changed rows to the front, each prefixed by
    its destination id. Returns (changed_count, out [n_pad, 1+W]) —
    the host reads the scalar, then slices out[:changed_count]: the
    full-width refresh pays an O(changed) readback like the bucketed
    path instead of hauling every row home. Padding destinations
    (t >= n) re-solve identically every time and are masked out.
    Traced body — shared by the standalone jit below and the fused
    overflow chains, so the compaction rides the same executable as
    the solve it diffs."""
    npad = new_packed.shape[0]
    ids = jnp.arange(npad, dtype=jnp.int32)
    changed = (ids < n) & jnp.any(new_packed != prev_packed, axis=1)
    ch_count = jnp.sum(changed.astype(jnp.int32))
    pos = jnp.cumsum(changed.astype(jnp.int32)) - 1
    dest = jnp.where(changed, pos, npad)
    body = jnp.concatenate([ids[:, None], new_packed], axis=1)
    out = jnp.zeros((npad, body.shape[1]), dtype=jnp.int32)
    out = out.at[dest].set(body, mode="drop")
    return ch_count, out


_compact_changed = functools.partial(
    jax.jit, static_argnames=("n",)
)(_compact_changed_body)


def _compact_rows_with_ids(new_packed, prev_packed, cap):
    """Traced body of compact_rows_with_ids — shared with the fused
    world_dispatch below so the delta epilogue rides the same
    executable as the solve it diffs."""
    bsz, rows, n = new_packed.shape
    changed = jnp.any(new_packed != prev_packed, axis=2).reshape(-1)
    ch_count = jnp.sum(changed.astype(jnp.int32))
    flat = new_packed.reshape(bsz * rows, n)
    ids = jnp.arange(bsz * rows, dtype=jnp.int32)
    body = jnp.concatenate(
        [(ids // rows)[:, None], (ids % rows)[:, None], flat], axis=1
    )
    pos = jnp.cumsum(changed.astype(jnp.int32)) - 1
    dest = jnp.where(changed, pos, cap)
    out = jnp.zeros((cap + 1, 2 + n), dtype=jnp.int32)
    out = out.at[dest].set(body, mode="drop")
    return ch_count, out


@functools.partial(jax.jit, static_argnames=("cap",))
def compact_rows_with_ids(new_packed, prev_packed, cap):
    """Tenant-batched delta epilogue (consumed by ops.world_batch):
    diff a [B, R, N] packed block bit-for-bit against the resident
    previous one and prefix-sum-compact the changed rows to the front,
    each prefixed by a [tenant, row] id column pair — the batched
    generalization of _compact_changed's single-graph delta readback,
    with the tenant id riding the compacted rows so one readback fans
    back out to B per-tenant host mirrors. Returns
    (changed_count, out [cap+1, 2+N]): the host reads the scalar, then
    slices out[:changed_count]; when the delta overflows ``cap`` the
    caller falls back to a full-block readback (counted, never silent).
    Unchanged rows scatter into the dropped slot at ``cap``; overflow
    positions land out of bounds and mode="drop" discards them, so the
    resident previous block is never torn by a too-small cap."""
    return _compact_rows_with_ids(new_packed, prev_packed, cap)


@functools.partial(jax.jit, static_argnames=("cap",))
def world_dispatch(
    src, w, ov, srcs, p_rows, p_src, p_w,
    inc_t, inc_h, inc_w, d_prev, packed_prev, cap,
):
    """The fused per-bucket tenant dispatch: patch scatter + batched
    view solve (spf_sparse._tenant_view_solve under vmap) + tenant-id
    delta compaction against the resident previous block — ONE device
    round trip per shape bucket per churn round, the tenant-plane twin
    of _churn_step. Returns (packed, d, src, w, changed_count, out):
    the first four rebind as the bucket's new resident block (inputs
    are NOT donated — the overflow fallback and rehydration re-read
    them, the double-buffer hazard rule), the last two drive the
    compacted readback exactly as compact_rows_with_ids documents."""
    packed, d, src, w = jax.vmap(_tenant_view_solve)(
        src, w, ov, srcs, p_rows, p_src, p_w,
        inc_t, inc_h, inc_w, d_prev,
    )
    ch_count, out = _compact_rows_with_ids(packed, packed_prev, cap)
    return packed, d, src, w, ch_count, out


@functools.partial(jax.jit, static_argnames=("bands", "n", "k"))
def _churn_step(
    v_t, w_t, patch_ids_t, patch_v_t, patch_w_t,
    dr, digests, packed_res,
    e_u, e_v, e_w_old, e_w_new,
    overloaded_new,
    samp_ids, samp_v, samp_w, pos_w,
    bands, n, k,
):
    """The fused single-chip incremental dispatch: detection against
    the resident DR, band-row patch scatter, affected-row re-solve and
    extraction — one device round trip per churn event. None of the
    resident inputs (dr/digests/packed_res) are donated: the overflow
    retry ladder re-dispatches at a larger bucket against the SAME
    untouched resident arrays (the double-buffer hazard rule)."""
    count, local_ids, ids = _detect_rows(
        dr, e_u, e_v, e_w_old, e_w_new, k, 0
    )
    # warm seed for the re-solve: pre-patch rows with the
    # increase-affected CONE reset cell-granular (rs._cone_expand, the
    # frontier kernel over the PRE-patch bands — XLA CSEs the shared
    # dr gathers with _detect_rows). If the expansion hit the jump cap
    # the cone is an under-approximation and the seed degrades to the
    # pre-frontier whole-row reset; either way the re-solve stays
    # bit-identical by the unique-fixed-point squeeze, the cone just
    # leaves already-final cells converged from iteration zero.
    sel = dr[local_ids]
    cone, _rows, _cells, _jumps, cone_ok = rs._cone_expand(
        sel, bands, v_t, w_t, e_u, e_v, e_w_old, e_w_new,
        _FRONTIER_MAX_JUMPS,
    )
    inc_row = _increase_rows(dr, e_u, e_v, e_w_old, e_w_new)
    warm0 = jnp.where(
        cone_ok,
        jnp.where(cone, INF, sel),
        jnp.where(inc_row[local_ids][:, None], INF, sel),
    )
    # scatter patched band rows (same bucketed shape discipline as
    # EllState.reconverge)
    new_v = tuple(
        s.at[pids, :].set(pv)
        for s, pids, pv in zip(v_t, patch_ids_t, patch_v_t)
    )
    new_w = tuple(
        w.at[pids, :].set(pw)
        for w, pids, pw in zip(w_t, patch_ids_t, patch_w_t)
    )
    dr, digests, packed_res, out = _resolve_and_pack(
        lambda t: rs._rev_fixed_point(
            bands, new_v, new_w, overloaded_new, t, n, init=warm0
        ),
        lambda rows, t: rs._nh_counts(
            rows, bands, new_v, new_w, overloaded_new, t
        ),
        overloaded_new, ids, local_ids, count,
        dr, digests, packed_res, samp_ids, samp_v, samp_w, pos_w, n, k,
    )
    return new_v, new_w, dr, digests, packed_res, out


@functools.partial(
    jax.jit, static_argnames=("bands", "n", "max_jumps")
)
def _frontier_probe(
    v_t, w_t, dr, e_u, e_v, e_w_old, e_w_new, cell_limit, bands, n,
    max_jumps,
):
    """Frontier probe dispatch: expand the increase-affected cone over
    the full resident DR and the PRE-patch bands (rs._cone_expand) and
    return it ON DEVICE plus a 4-int meta [frontier_rows,
    frontier_cells, jumps, converged]. The host reads only the meta to
    make the frontier-vs-full-refresh policy call; the cone itself
    stays resident as the follow-up _frontier_step's seed mask.
    ``cell_limit`` is a device scalar (shape [1]) so threshold changes
    never recompile; the expansion early-exits once the cone overflows
    it (the fallback is already decided, no point finishing the
    closure)."""
    cone, rows, cells, jumps, ok = rs._cone_expand(
        dr, bands, v_t, w_t, e_u, e_v, e_w_old, e_w_new, max_jumps,
        cell_limit=cell_limit[0],
    )
    # float32 meta: the cell count already is (int32 overflows at
    # 100k-node cone sizes), the rest are small ints cast losslessly
    meta = jnp.stack(
        [rows.astype(jnp.float32), cells,
         jumps.astype(jnp.float32), ok.astype(jnp.float32)]
    )
    return cone, meta


@functools.partial(jax.jit, static_argnames=("bands", "n"))
def _frontier_step(
    v_t, w_t, cone, dr, overloaded, samp_ids, samp_v, samp_w, pos_w,
    bands, n,
):
    """The frontier re-solve dispatch: full-width WARM fixed point over
    the PATCHED bands, seeded from the resident DR with only the cone
    cells reset to INF (+ the unit anchor inside _rev_fixed_point) —
    the masked min-plus relaxation then converges in ~cone-radius
    iterations instead of graph diameter, because every cell outside
    the cone is already at its fixed point (structural increases) or a
    sound upper bound (decreases / link up). Same extraction + packing
    as the cold-shaped _full_resident_sweep, so the product is
    bit-identical and the delta-compacted readback epilogue
    (_compact_changed) applies unchanged. The residents are NOT
    donated: a frontier failure falls back to _full_refresh against
    the same untouched arrays (the retry-ladder hazard rule)."""
    t_ids = jnp.arange(n, dtype=jnp.int32)
    warm0 = jnp.where(cone, INF, dr)
    dr2 = rs._rev_fixed_point(
        bands, v_t, w_t, overloaded, t_ids, n, init=warm0
    )
    nh_count = rs._nh_counts(dr2, bands, v_t, w_t, overloaded, t_ids)
    d_s, packed_mask = rs._sample_stats(
        dr2, samp_ids, samp_v, samp_w, overloaded, t_ids
    )
    digests, packed = _pack_product(
        dr2, nh_count, d_s, packed_mask, pos_w
    )
    return dr2, digests, packed


@functools.partial(
    jax.jit, static_argnames=("bands", "n", "n_real", "max_jumps")
)
def _overflow_chain(
    v_old_t, w_old_t, v_new_t, w_new_t, dr, packed_res,
    e_u, e_v, e_w_old, e_w_new, cell_limit, overloaded_new,
    samp_ids, samp_v, samp_w, pos_w, bands, n, n_real, max_jumps,
):
    """The fused overflow decision chain: probe + frontier-vs-full
    branch + re-solve + extraction + delta compaction in ONE
    executable, with the policy decision made ON DEVICE instead of a
    16-byte meta readback and a host ``if``.

    The branch reduces to a seed select: the full-width refresh is
    exactly the frontier re-solve with an all-True cone (an all-INF
    warm seed collapses to the cold unit init inside
    ``rs._rev_fixed_point``), so ``use_frontier`` only widens the
    reset mask — no ``lax.cond`` over differently-shaped programs, and
    the answer is bit-identical to whichever split-path dispatch the
    host branch would have picked. The probe runs over the PRE-patch
    tensors, the solve over the PATCHED ones (both passed in: patch
    scatter is its own tiny dispatch in the same submit phase). The
    meta row rides home on the async lane for post-hoc policy
    telemetry only — a warm multi-window burst never breaks the
    dispatch chain on it."""
    cone, rows, cells, jumps, ok = rs._cone_expand(
        dr, bands, v_old_t, w_old_t, e_u, e_v, e_w_old, e_w_new,
        max_jumps, cell_limit=cell_limit[0],
    )
    meta = jnp.stack(
        [rows.astype(jnp.float32), cells,
         jumps.astype(jnp.float32), ok.astype(jnp.float32)]
    )
    use_frontier = jnp.logical_and(ok, cells <= cell_limit[0])
    eff_cone = jnp.logical_or(cone, jnp.logical_not(use_frontier))
    t_ids = jnp.arange(n, dtype=jnp.int32)
    warm0 = jnp.where(eff_cone, INF, dr)
    dr2 = rs._rev_fixed_point(
        bands, v_new_t, w_new_t, overloaded_new, t_ids, n, init=warm0
    )
    nh_count = rs._nh_counts(
        dr2, bands, v_new_t, w_new_t, overloaded_new, t_ids
    )
    d_s, packed_mask = rs._sample_stats(
        dr2, samp_ids, samp_v, samp_w, overloaded_new, t_ids
    )
    digests, packed = _pack_product(
        dr2, nh_count, d_s, packed_mask, pos_w
    )
    ch_count, comp = _compact_changed_body(packed, packed_res, n_real)
    return dr2, digests, packed, ch_count, comp, meta


# -- mesh-sharded dispatches ----------------------------------------------

from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from openr_tpu.utils.jax_compat import shard_map

from openr_tpu.ops.spf_sparse import SOURCES_AXIS  # noqa: E402
from openr_tpu.parallel.mesh import (  # noqa: E402
    ShardingPlan, replicated_jit,
)


def _patch_bands_fn(v_t, w_t, patch_ids_t, patch_v_t, patch_w_t):
    """Scatter patched band rows into the (replicated) resident band
    tensors — the sharded engine's band patch rides this one small
    dispatch instead of being fused into the churn step (replicated
    outputs from inside shard_map would need cross-shard replication
    bookkeeping for no bandwidth win; the patch is O(degree))."""
    new_v = tuple(
        s.at[pids, :].set(pv)
        for s, pids, pv in zip(v_t, patch_ids_t, patch_v_t)
    )
    new_w = tuple(
        w.at[pids, :].set(pw)
        for w, pids, pw in zip(w_t, patch_ids_t, patch_w_t)
    )
    return new_v, new_w


# single-chip dispatch of the band patch; the mesh engines instead ride
# parallel.mesh.replicated_jit(_patch_bands_fn, mesh) so the patched
# tensors come back COMMITTED replicated, matching the sharded churn
# step's replicated in_specs — otherwise XLA re-replicates the bands on
# every churn dispatch (the reshard storm the plan exists to prevent)
_patch_bands = jax.jit(_patch_bands_fn)


@functools.partial(jax.jit, static_argnames=("start", "size"))
def _rows_slice(seg, start, size):
    """Jitted static row slice of a device segment. Eager basic
    indexing (``seg[1:1+m]``) uploads its start indices host->device
    at every call — an IMPLICIT transfer the churn path's
    transfer_guard contract forbids; under jit the indices are
    compiled constants. One tiny executable per (shape, start, size),
    cached — the same per-(shape, m) executable cache the eager slice
    primitive was already paying for."""
    return jax.lax.slice_in_dim(seg, start, start + size)


@jax.jit
def _seg_meta(seg):
    """Jitted read of a segment's leading meta pair [affected,
    changed] — same implicit-index-upload avoidance as _rows_slice."""
    return jax.lax.slice(seg, (0, 0), (1, 2))[0]


@functools.partial(jax.jit, static_argnames=("bands", "n", "mesh"))
def _sharded_full_resident(
    v_t, w_t, overloaded, samp_ids, samp_v, samp_w, pos_w, bands, n,
    mesh,
):
    """Sharded cold build: every device solves its block of destination
    rows (the axis the single-chip engine holds whole); DR and digests
    come back SHARDED over the mesh — the resident footprint per device
    is n_pad^2/ndev, which is what breaks the single-chip 12k bound.
    Only collective: the 1-bit convergence vote per iteration."""
    nb = len(v_t)

    def shard_fn(t_blk, *rest):
        v_r = rest[:nb]
        w_r = rest[nb : 2 * nb]
        ov_r, sid_r, sv_r, sw_r, pw_r = rest[2 * nb :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        dr = rs._rev_fixed_point(
            bands, v_r, w_r, ov_r, t_blk, n, vote=vote
        )
        nh_count = rs._nh_counts(dr, bands, v_r, w_r, ov_r, t_blk)
        digests = rs._digest_rows(dr, nh_count, pw_r)
        nh_total = jnp.sum(nh_count, axis=1, dtype=jnp.int32)
        d_s, packed_mask = rs._sample_stats(
            dr, sid_r, sv_r, sw_r, ov_r, t_blk
        )
        b = t_blk.shape[0]
        packed = jnp.concatenate(
            [
                jax.lax.bitcast_convert_type(digests, jnp.int32)[
                    :, None
                ],
                nh_total[:, None],
                d_s,
                jax.lax.bitcast_convert_type(
                    packed_mask, jnp.int32
                ).reshape(b, -1),
            ],
            axis=1,
        )
        return dr, digests, packed

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS)]
            + [P(None, None)] * (2 * nb)
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
        ),
    )(
        jnp.arange(n, dtype=jnp.int32),
        *v_t, *w_t, overloaded, samp_ids, samp_v, samp_w, pos_w,
    )


@functools.partial(jax.jit, static_argnames=("bands", "n", "k", "mesh"))
def _sharded_churn_step(
    v_t, w_t, dr, digests, packed_res,
    e_u, e_v, e_w_old, e_w_new,
    overloaded_new,
    samp_ids, samp_v, samp_w, pos_w,
    bands, n, k, mesh,
):
    """The sharded incremental dispatch: detection runs PER SHARD
    against its resident DR rows (destination rows never interact, so
    each shard's affected set is exactly its own rows' detection), the
    re-solve runs on each shard's affected rows with the convergence
    vote lifted over the mesh, and the delta-compacted readback comes
    back as ndev stacked [k+1, 1+W] segments (each shard's
    affected/changed counts in its meta row — the host reads each
    shard's changed rows from its OWN addressable shard, see
    _split_segments). Band tensors arrive ALREADY PATCHED
    (_patch_bands)."""
    nb = len(v_t)
    rows_per = n // mesh.devices.size

    def shard_fn(dr_s, dg_s, pk_s, *rest):
        v_r = rest[:nb]
        w_r = rest[nb : 2 * nb]
        (e_u_r, e_v_r, e_wo_r, e_wn_r, ov_r,
         sid_r, sv_r, sw_r, pw_r) = rest[2 * nb :]
        row_start = (
            jax.lax.axis_index(SOURCES_AXIS) * rows_per
        ).astype(jnp.int32)
        count, local_ids, ids = _detect_rows(
            dr_s, e_u_r, e_v_r, e_wo_r, e_wn_r, k, row_start
        )
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        return _resolve_and_pack(
            lambda t: rs._rev_fixed_point(
                bands, v_r, w_r, ov_r, t, n, vote=vote
            ),
            lambda rows, t: rs._nh_counts(
                rows, bands, v_r, w_r, ov_r, t
            ),
            ov_r, ids, local_ids, count, dr_s, dg_s, pk_s,
            sid_r, sv_r, sw_r, pw_r, n, k,
        )

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS, None), P(SOURCES_AXIS),
             P(SOURCES_AXIS, None)]
            + [P(None, None)] * (2 * nb)
            + [P(None)] * 4
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS, None),
        ),
    )(
        dr, digests, packed_res, *v_t, *w_t,
        e_u, e_v, e_w_old, e_w_new, overloaded_new,
        samp_ids, samp_v, samp_w, pos_w,
    )


@functools.partial(
    jax.jit, static_argnames=("bands", "n", "max_jumps", "mesh")
)
def _sharded_frontier_probe(
    v_t, w_t, dr, e_u, e_v, e_w_old, e_w_new, cell_limit, bands, n,
    max_jumps, mesh,
):
    """Sharded frontier probe: each shard expands the cone over its own
    resident DR rows (rows never interact), with the growth bit and the
    frontier row/cell counts psum-voted so every shard runs the same
    number of jumps. The meta row is device-invariant by construction
    (voted counts + shared iteration counter) and comes back
    replicated; the cone stays row-sharded for _sharded_frontier_step."""
    nb = len(v_t)

    def shard_fn(dr_s, *rest):
        v_r = rest[:nb]
        w_r = rest[nb : 2 * nb]
        e_u_r, e_v_r, e_wo_r, e_wn_r, lim_r = rest[2 * nb :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        cone, rows, cells, jumps, ok = rs._cone_expand(
            dr_s, bands, v_r, w_r, e_u_r, e_v_r, e_wo_r, e_wn_r,
            max_jumps, vote=vote, cell_limit=lim_r[0],
        )
        meta = jnp.stack(
            [rows.astype(jnp.float32), cells,
             jumps.astype(jnp.float32), ok.astype(jnp.float32)]
        )
        return cone, meta

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS, None)]
            + [P(None, None)] * (2 * nb)
            + [P(None)] * 5
        ),
        out_specs=(P(SOURCES_AXIS, None), P(None)),
    )(dr, *v_t, *w_t, e_u, e_v, e_w_old, e_w_new, cell_limit)


@functools.partial(
    jax.jit, static_argnames=("bands", "n", "mesh")
)
def _sharded_frontier_step(
    v_t, w_t, cone, dr, overloaded, samp_ids, samp_v, samp_w, pos_w,
    bands, n, mesh,
):
    """Sharded frontier re-solve: the full-width warm dispatch over the
    PATCHED (replicated) bands with each shard seeding its own DR rows
    outside its cone shard — the convergence vote is the only
    collective, exactly like the sharded cold build it replaces."""
    nb = len(v_t)

    def shard_fn(t_blk, cone_s, dr_s, *rest):
        v_r = rest[:nb]
        w_r = rest[nb : 2 * nb]
        ov_r, sid_r, sv_r, sw_r, pw_r = rest[2 * nb :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        warm0 = jnp.where(cone_s, INF, dr_s)
        dr2 = rs._rev_fixed_point(
            bands, v_r, w_r, ov_r, t_blk, n, vote=vote, init=warm0
        )
        nh_count = rs._nh_counts(dr2, bands, v_r, w_r, ov_r, t_blk)
        d_s, packed_mask = rs._sample_stats(
            dr2, sid_r, sv_r, sw_r, ov_r, t_blk
        )
        digests, packed = _pack_product(
            dr2, nh_count, d_s, packed_mask, pw_r
        )
        return dr2, digests, packed

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS), P(SOURCES_AXIS, None),
             P(SOURCES_AXIS, None)]
            + [P(None, None)] * (2 * nb)
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
        ),
    )(
        jnp.arange(n, dtype=jnp.int32), cone, dr, *v_t, *w_t,
        overloaded, samp_ids, samp_v, samp_w, pos_w,
    )


@functools.partial(
    jax.jit,
    static_argnames=("bands", "n", "n_real", "max_jumps", "mesh"),
)
def _sharded_overflow_chain(
    v_old_t, w_old_t, v_new_t, w_new_t, dr, packed_res,
    e_u, e_v, e_w_old, e_w_new, cell_limit, overloaded_new,
    samp_ids, samp_v, samp_w, pos_w, bands, n, n_real, max_jumps,
    mesh,
):
    """Sharded fused overflow chain: per-shard cone expansion with the
    counters/growth bit psum-voted (the policy inputs are
    device-invariant by construction, so every shard takes the SAME
    seed-select branch), warm re-solve over the patched replicated
    bands, per-shard extraction — one shard_map, no replicated policy
    readback in the middle. The delta compaction runs on the
    row-sharded packed product after the shard_map, inside the same
    executable; meta comes back replicated for post-hoc telemetry."""
    nb = len(v_old_t)

    def shard_fn(t_blk, dr_s, *rest):
        v_o = rest[:nb]
        w_o = rest[nb : 2 * nb]
        v_n = rest[2 * nb : 3 * nb]
        w_n = rest[3 * nb : 4 * nb]
        (e_u_r, e_v_r, e_wo_r, e_wn_r, lim_r, ov_r,
         sid_r, sv_r, sw_r, pw_r) = rest[4 * nb :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        cone, rows, cells, jumps, ok = rs._cone_expand(
            dr_s, bands, v_o, w_o, e_u_r, e_v_r, e_wo_r, e_wn_r,
            max_jumps, vote=vote, cell_limit=lim_r[0],
        )
        meta = jnp.stack(
            [rows.astype(jnp.float32), cells,
             jumps.astype(jnp.float32), ok.astype(jnp.float32)]
        )
        use_frontier = jnp.logical_and(ok, cells <= lim_r[0])
        eff_cone = jnp.logical_or(
            cone, jnp.logical_not(use_frontier)
        )
        warm0 = jnp.where(eff_cone, INF, dr_s)
        dr2 = rs._rev_fixed_point(
            bands, v_n, w_n, ov_r, t_blk, n, vote=vote, init=warm0
        )
        nh_count = rs._nh_counts(dr2, bands, v_n, w_n, ov_r, t_blk)
        d_s, packed_mask = rs._sample_stats(
            dr2, sid_r, sv_r, sw_r, ov_r, t_blk
        )
        digests, packed = _pack_product(
            dr2, nh_count, d_s, packed_mask, pw_r
        )
        return dr2, digests, packed, meta

    dr2, digests, packed, meta = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS), P(SOURCES_AXIS, None)]
            + [P(None, None)] * (4 * nb)
            + [P(None)] * 6
            + [P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
            P(None),
        ),
    )(
        jnp.arange(n, dtype=jnp.int32), dr,
        *v_old_t, *w_old_t, *v_new_t, *w_new_t,
        e_u, e_v, e_w_old, e_w_new, cell_limit, overloaded_new,
        samp_ids, samp_v, samp_w, pos_w,
    )
    ch_count, comp = _compact_changed_body(packed, packed_res, n_real)
    return dr2, digests, packed, ch_count, comp, meta


class _DeviceStateInvalid(RuntimeError):
    """The resident device state is stale (a host fallback bypassed
    it): the warm rung refuses to run and the ladder walks to the cold
    rebuild, which rederives everything."""


class PendingDelta:
    """Handle to ONE churn event's in-flight delta-compacted readback.

    The device state (bands, DR, digests, packed product) is already
    committed when the handle exists; only the HOST mirror
    (engine.result) lags until the delta is consumed. ``wait()``
    consumes (via the engine, which owns ordering) and returns the
    sorted moved destination names. The engine holds at most one
    pending delta: the next churn event consumes it inside its own
    dispatch window (the double-buffer overlap), so a pipelined caller
    pays zero dedicated host time for the readback."""

    __slots__ = (
        "_engine", "segs", "counts", "ch_counts", "k", "dslices",
        "fw_count", "consumed", "names", "delta_rows",
        "readback_bytes", "overlap_ms", "meta_dev", "meta_limit",
    )

    def __init__(self, engine, segs, counts, ch_counts, k,
                 fw_count=None, meta_dev=None, meta_limit=0.0):
        self._engine = engine
        self.segs = segs          # per-shard device [k+1, 1+W] arrays
        self.counts = counts      # per-shard affected counts
        self.ch_counts = ch_counts  # per-shard CHANGED counts
        self.k = k
        # FULL-WIDTH mode (fw_count is a device scalar): the segment is
        # a _compact_changed output [n_pad, 1+W] whose changed rows
        # start at ROW 0 and whose count has not crossed to host yet —
        # the count rides the async lane now and is reaped at consume
        # time, so even the overflow rungs keep the two-touch window
        self.fw_count = fw_count
        self.consumed = False
        self.names: List[str] = []
        self.delta_rows = 0
        self.readback_bytes = 0
        self.overlap_ms = 0.0
        # kick EVERY shard's changed-rows transfer now: each device
        # copies its own O(changed) slice to host concurrently while
        # the next event dispatches, so consume time is an apply, not a
        # serial per-device drain on the readback lane
        self.dslices = []
        for seg, m in zip(segs, ch_counts):
            sl = None
            if m:
                if isinstance(seg, jax.Array):
                    sl = _rows_slice(seg, 1, int(m))
                    da.kick_async(sl)
                else:  # host shim arrays
                    sl = seg[1 : 1 + m]
            self.dslices.append(sl)
        if fw_count is not None:
            da.kick_async(fw_count)
        # fused-overflow-chain mode: the probe meta rode the dispatch
        # and its policy classification (frontier vs full-width
        # counters) is settled at consume time, off the event window
        self.meta_dev = meta_dev
        self.meta_limit = meta_limit
        if meta_dev is not None:
            da.kick_async(meta_dev)

    def wait(self) -> List[str]:
        if not self.consumed:
            self._engine.flush()
        return self.names


class _Speculation:
    """One staged speculative churn dispatch (latest-wins guess at the
    debounce window's final composition). Everything here is
    FUNCTIONAL output of _run_bucket — the resident tensors are never
    donated (retry-ladder hazard rule), so cancelling a speculation is
    dropping this object: no device state to unwind, no readback to
    drain (the kicked meta copies land and are garbage-collected).
    ``dr_ref`` pins the exact resident DR the dispatch read; every
    commit path replaces the engine's ``_dr`` binding, so an
    identity mismatch at adoption time means another event committed
    underneath the speculation and it MUST cancel."""

    __slots__ = (
        "union", "version", "aversion", "dr_ref", "ctx", "segments",
        "counts", "ch_counts", "commit_state", "ov_new", "k",
        "new_out", "ov_flips", "structural",
    )


@mirrored_by(
    _dr="re-derived from the resident band tensors (integrity_heal) "
        "or the LinkState (_build)",
    _digests_dev="result.digests (delta-applied on every consume)",
    _packed_dev="_packed_host (settle-on-success row scatter)",
)
@resident_buffers("_dr", "_digests_dev", "_packed_dev")
class RouteSweepEngine(ResidentEngineContract):
    """Resident incremental network-wide route product.

    cold_build(ls) -> RouteSweepResult (full product)
    churn(ls, affected_nodes) -> (moved destination names, their
    fresh per-sample route rows refreshed in self.result) or None when
    the event needs a cold rebuild (node add/remove or a sample node's
    slot-table reshape). Link add/remove and band widening stay on the
    incremental path; affected-count overflow past the largest bucket
    takes the full-width refresh (patched layout kept, all rows
    re-solved in one dispatch — no host recompile) and still reports
    the moved names from the DEVICE product diff.

    Every event class reads back only the delta: the rows whose packed
    product changed bit-for-bit, compacted on device. With
    ``defer_consume=True`` churn returns a PendingDelta instead of
    names and the host-side apply overlaps the NEXT event's dispatch
    (call ``flush()`` — or ``PendingDelta.wait()`` — to drain).
    ``churn_coalesced`` folds a debounce window's worth of patches into
    one fused dispatch + one readback."""

    def __init__(self, ls, sample_names: Sequence[str],
                 align: int = 128, mesh: Optional[Mesh] = None,
                 frontier_threshold: float = _DEFAULT_FRONTIER_THRESHOLD):
        self.sample_names = tuple(sample_names)
        self.mesh = mesh
        # the build-time placement contract: under a mesh every
        # resident gets an explicit NamedSharding (rows striped,
        # bands/edges replicated) so churn dispatches never reshard
        self.plan = ShardingPlan(mesh) if mesh is not None else None
        # pre-mesh alignment, kept so a device-loss mesh shrink can
        # re-derive the per-shard row block for the surviving devices
        self._base_align = align
        if mesh is not None:
            # every shard must own an equal block of destination rows
            align = align * mesh.devices.size
        self._align = align
        self._k_hint = _ROW_BUCKETS[0]
        self._pending: Optional[PendingDelta] = None
        # at most one staged speculative dispatch (see speculate_churn)
        self._speculation: Optional[_Speculation] = None
        # service-plane visibility into the dispatch-level double
        # buffer: 1 while a delta-compacted readback is in flight
        # (consumed inside the next churn's dispatch window) — the same
        # overlap the Decision emit stage applies one layer up
        get_registry().gauge(
            "ops.pending_delta_inflight",
            lambda: float(self._pending is not None),
        )
        self.last_delta_rows = 0
        self.last_readback_bytes = 0
        self.last_overlap_ms = 0.0
        # overflow policy knob: a converged frontier covering more than
        # this fraction of the [n, n] route product still rides the
        # full-width refresh
        self.frontier_threshold = float(frontier_threshold)
        self.last_frontier_rows = -1
        self.last_frontier_jumps = -1
        self.last_frontier_cells = -1.0
        # False between a failed/bypassed device path and the next
        # successful cold build: gates the warm rung off stale residents
        self._device_valid = False
        # True between an observed device loss (is_device_loss at a
        # rung boundary) and the recover rung re-landing the residents;
        # gates the recover rung so it is a no-op on ordinary faults
        self._device_lost = False
        self.host_fallbacks = 0
        self.device_rebuilds = 0
        self.mesh_shrinks = 0
        # settle-on-success host mirror of the resident packed product
        # (rows < n scatter-updated on every delta consume): tier-2
        # digest reference and the warm-heal bit-identity witness
        self._packed_host: Optional[np.ndarray] = None
        self._corrupt_events = 0
        self.supervisor = DegradationSupervisor("route_engine")
        self._build(ls)
        get_auditor().register(self)

    def _max_nodes(self) -> int:
        """Residency bound: the resident DR is [n_pad, n_pad] int32 —
        whole on a single chip, row-sharded over a mesh (per-device
        footprint n_pad^2/ndev), so the bound scales with sqrt(ndev):
        12k single-chip, ~100k on a 64-way mesh."""
        if self.mesh is None:
            return ENGINE_MAX_NODES
        import math

        return int(ENGINE_MAX_NODES * math.sqrt(self.mesh.devices.size))

    # -- state -------------------------------------------------------------

    def _compile_backend(self, ls):
        """Backend hook: compile the layout + sweeper for a cold
        build."""
        graph = compile_ell(ls, align=self._align, direction="out")
        return graph, rs.RouteSweeper(
            graph, self.sample_names, plan=self.plan
        )

    def _full_resident(self, graph):
        """Backend hook: the cold full-product dispatch (DR + digests
        resident, packed product back)."""
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip cold
            # build (mesh is None): one device, no axis to spec
            return ell_dispatch(
                "ell_full_resident", _full_resident_sweep,
                (
                    self.sweeper.v_t, self.sweeper.w_t,
                    self.sweeper.overloaded,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(bands=graph.bands, n=graph.n_pad),
            )
        return ell_dispatch(
            "ell_full_resident_sharded", _sharded_full_resident,
            (
                self.sweeper.v_t, self.sweeper.w_t,
                self.sweeper.overloaded,
                self.sweeper._samp_ids_dev, self.sweeper._samp_v_dev,
                self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
            ),
            dict(bands=graph.bands, n=graph.n_pad, mesh=self.mesh),
        )

    @requires_drain("flush")
    def _build(self, ls) -> None:
        # a cold rebuild replaces the whole result: drain any in-flight
        # delta first so a caller-held PendingDelta handle resolves
        self.flush()
        # invalid until this build completes: a failure below leaves
        # the engine torn (mirrors vs residents), and the gate forces
        # every later event through another cold build or the host rung
        self._device_valid = False
        # a staged speculation read the pre-build residents: dead now
        self._speculation = None
        graph, sweeper = self._compile_backend(ls)
        if graph.n_pad > self._max_nodes():
            raise ValueError(
                f"route engine residency bound: {graph.n_pad} > "
                f"{self._max_nodes()} (use the block/mesh sweep, or "
                "a larger mesh)"
            )
        self.graph = graph
        self.sweeper = sweeper
        # RAW collapsed min weights of the directed edges, indexed both
        # ways for O(degree) event diffing. STRICTLY raw: overload
        # flips never mutate these mirrors — effective-weight
        # transitions exist only inside one event's detection list
        # (conflating them made a later metric change on a drained
        # node's edge undetectable, a silent-stale-routes bug).
        self._w_out: Dict[int, Dict[int, int]] = {}
        self._w_in: Dict[int, Dict[int, int]] = {}
        for nm in graph.node_names:
            u = graph.node_index[nm]
            for v, w in _out_edges(ls, nm, graph.node_index).items():
                self._w_out.setdefault(u, {})[v] = w
                self._w_in.setdefault(v, {})[u] = w
        self._ov_host = {
            nm: ls.is_node_overloaded(nm) for nm in graph.node_names
        }
        fault_point(FAULT_COLD_BUILD)
        dr, digests, packed = self._full_resident(graph)
        self._dr = dr
        self._digests_dev = digests
        # the packed product stays RESIDENT: every later dispatch diffs
        # its fresh rows against this to compact the readback
        self._packed_dev = packed
        # explicit gather (device_get): under a mesh np.asarray would
        # be an implicit cross-device transfer the guard rejects
        packed_host = jax.device_get(packed)
        self.result = rs.assemble_result(self.sweeper, packed_host)
        # private copy: assemble_result may keep views of its input
        self._packed_host = np.array(packed_host)
        self.version = ls.topology_version
        self.aversion = ls.attributes_version
        self._device_valid = True
        self.cold_builds = getattr(self, "cold_builds", 0) + 1
        self.incremental_events = getattr(
            self, "incremental_events", 0
        )
        self.full_refreshes = getattr(self, "full_refreshes", 0)
        self.coalesced_events = getattr(self, "coalesced_events", 0)
        self.structural_events = getattr(self, "structural_events", 0)
        self.frontier_resolves = getattr(self, "frontier_resolves", 0)
        self.frontier_fallbacks = getattr(
            self, "frontier_fallbacks", 0
        )
        get_registry().counter_bump("route_engine.cold_builds")
        get_flight_recorder().note(
            "engine", path="cold_build", n=int(graph.n_pad)
        )

    def _refresh_sample_bands(self, patched, affected_nodes) -> bool:
        """A churn event that touched a SAMPLE node's own adjacencies
        changes the slot tables the next-hop masks are computed over
        (route_sweep._sample_stats closes over samp_v/samp_w) — refresh
        them from the PATCHED graph BEFORE the dispatch, so this very
        event's packed sample rows use current tables. Returns False
        when the slot-table shape changed (sample degree crossed a pad
        boundary — the packed width moves): the caller cold-rebuilds.
        Early mutation of the sweeper tables is safe on every fallback
        path because a cold rebuild rederives them from scratch."""
        if not (affected_nodes & set(self.sample_names)):
            return True
        sweeper = self.sweeper
        samp_v, samp_w = rs._sample_bands(patched, sweeper.sample_ids)
        if samp_v.shape != sweeper.samp_v.shape:
            return False
        up = (
            self.plan.replicate if self.plan is not None
            else jnp.asarray
        )
        sweeper.samp_v = self.result.samp_v = samp_v
        sweeper.samp_w = self.result.samp_w = samp_w
        sweeper._samp_v_dev = up(samp_v)
        sweeper._samp_w_dev = up(samp_w)
        return True

    # -- events ------------------------------------------------------------

    def _layout_changed(self, ctx) -> bool:
        """Backend hook: did this event change the static band layout
        (shapes under the resident tensors)? Speculation and bursts
        refuse such events — the committed path owns the recompile.
        ELL bands are plain (start, rows, k) records, comparable by
        value; the grouped backend overrides (its patch helper returns
        None on any layout break, so a ctx implies stability)."""
        return ctx["patched"].bands != self.graph.bands

    def _prepare_patch(self, ls, affected_sorted):
        """Backend hook: derive the patched graph + device patch
        tensors for one churn event. Returns a ctx dict (consumed by
        _run_bucket/_commit_device) or None when the event breaks the
        layout (caller cold-rebuilds)."""
        patched = ell_patch(self.graph, ls, affected_sorted, widen=True)
        if patched is None:
            return None
        # band patch tensors: the shared discipline (bucketed row
        # scatter; a WIDENED band — tensor shape changed — re-uploads
        # wholesale with a no-op scatter; node ids stay fixed so the
        # resident DR stays valid, at the cost of one jit recompile)
        from openr_tpu.ops.spf_sparse import band_patch_inputs

        in_v, in_w, patch_ids, patch_v, patch_w = band_patch_inputs(
            self.sweeper.v_t, self.sweeper.w_t, patched
        )
        if self.plan is not None:
            # commit the fresh patch uploads (and any widened band
            # re-upload) REPLICATED before the replicated_jit patch
            # dispatch reads them: an uncommitted operand would make
            # the dispatch replicate it itself — a device-to-device
            # copy per event (and a transfer_guard violation)
            up = self.plan.replicate
            in_v = tuple(up(t) for t in in_v)
            in_w = tuple(up(t) for t in in_w)
            patch_ids = tuple(up(t) for t in patch_ids)
            patch_v = tuple(up(t) for t in patch_v)
            patch_w = tuple(up(t) for t in patch_w)
        return {
            "patched": patched,
            "in_v": in_v, "in_w": in_w,
            "patch_ids": patch_ids,
            "patch_v": patch_v, "patch_w": patch_w,
            "patched_bands": None,  # sharded path: lazily dispatched
        }

    @solve_window
    @committed_dispatch
    def _run_bucket(self, ctx, k, e_dev, ov_new):
        """Backend hook: one detect+solve dispatch at bucket size k.
        Returns (segments, commit_state) where segments are per-shard
        IN-FLIGHT device arrays [k+1, 1+W] — nothing is copied to host
        here; the caller reads the tiny meta row for the retry ladder
        and the changed rows only at consume time. Every launch goes
        through the AOT executable cache (aot_call): after warmup the
        event window runs a pre-compiled XLA program with zero Python
        retrace/signature checks on the hot path."""
        e_u_d, e_v_d, e_wo_d, e_wn_d = e_dev
        fault_point(FAULT_DISPATCH)
        fault_point(FAULT_DEVICE_LOST)
        graph = ctx["patched"]
        if self.mesh is None:
            (new_v, new_w_t, dr, digests, packed_res,
             # openr-lint: disable=sharding-spec -- single-chip churn
             # dispatch (mesh is None): no mesh axis to spec; the mesh
             # branch below rides _sharded_churn_step's shard_map specs
             packed_dev) = ell_dispatch(
                "ell_churn_step", _churn_step,
                (
                    ctx["in_v"], ctx["in_w"],
                    ctx["patch_ids"], ctx["patch_v"], ctx["patch_w"],
                    self._dr, self._digests_dev, self._packed_dev,
                    e_u_d, e_v_d, e_wo_d, e_wn_d,
                    ov_new,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(bands=graph.bands, n=graph.n_pad, k=k),
            )
            # the fused step already patched the bands on device: cache
            # them so an overflow's _apply_patch_resident adopts these
            # instead of re-dispatching _patch_bands
            ctx["patched_bands"] = (new_v, new_w_t)
            segments = [packed_dev]
        else:
            self._ensure_residents()
            # band patch in its own small dispatch (see
            # _patch_bands_fn) — loop-invariant, dispatched once
            if ctx["patched_bands"] is None:
                ctx["patched_bands"] = self._dispatch_patch(ctx)
            new_v, new_w_t = ctx["patched_bands"]
            dr, digests, packed_res, packed_dev = ell_dispatch(
                "ell_churn_step_sharded", _sharded_churn_step,
                (
                    new_v, new_w_t,
                    self._dr, self._digests_dev, self._packed_dev,
                    e_u_d, e_v_d, e_wo_d, e_wn_d,
                    ov_new,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(
                    bands=graph.bands, n=graph.n_pad, k=k,
                    mesh=self.mesh,
                ),
            )
            segments = self._split_segments(packed_dev, k)
        return segments, (new_v, new_w_t, dr, digests, packed_res)

    def _ensure_residents(self) -> None:
        """Churn-path placement tripwire (mesh engines): the resident
        DR / digests / packed product must already sit at their
        planned shardings — the sharded dispatches re-commit them via
        out_specs, so any mismatch here means something moved them and
        the next dispatch would pay an XLA reshard. Counted as
        ops.reshard_events (and corrected) by ShardingPlan.ensure."""
        plan = self.plan
        self._dr = plan.ensure(self._dr, plan.rows, "_dr")
        self._digests_dev = plan.ensure(
            self._digests_dev, plan.vec, "_digests_dev"
        )
        self._packed_dev = plan.ensure(
            self._packed_dev, plan.rows, "_packed_dev"
        )

    def _dispatch_patch(self, ctx):
        """Backend hook: the standalone band-patch dispatch (mesh path;
        the single-chip engine fuses the patch into the churn step).
        Under a mesh the patch rides replicated_jit so its outputs are
        COMMITTED replicated — matching the sharded churn step's
        replicated in_specs, no broadcast copy at the consumer."""
        fn = (
            replicated_jit(_patch_bands_fn, self.mesh)
            if self.mesh is not None else _patch_bands
        )
        return fn(
            ctx["in_v"], ctx["in_w"],
            ctx["patch_ids"], ctx["patch_v"], ctx["patch_w"],
        )

    def _split_segments(self, packed_dev, k: int):
        """Per-shard [k+1, 1+W] segments of a sharded churn readback,
        read from the array's ADDRESSABLE SHARDS (ordered by row
        offset) — each shard's meta row and changed rows transfer from
        the device that solved them; rows a shard didn't solve never
        cross to host."""
        shards = sorted(
            packed_dev.addressable_shards,
            key=lambda sh: sh.index[0].start or 0,
        )
        return [sh.data for sh in shards]

    @solve_window
    def _commit_device(self, ctx, commit_state, ov_new) -> None:
        """Backend hook: adopt the dispatch's device state."""
        new_v, new_w_t, dr, digests, packed_res = commit_state
        self.sweeper.v_t = new_v
        self.sweeper.w_t = new_w_t
        self.sweeper.overloaded = ov_new
        self._dr = dr
        self._digests_dev = digests
        self._packed_dev = packed_res
        self.graph = self.sweeper.graph = ctx["patched"]

    @solve_window
    def _apply_patch_resident(self, ctx, ov_new) -> None:
        """Backend hook: adopt the event's band patch into the resident
        sweeper tensors WITHOUT a row re-solve — the full-width refresh
        applies this then runs the cold-build-shaped dispatch over the
        patched tensors (a widened band changed the static band shapes,
        so that dispatch recompiles once — the documented widening
        cost — but the layout itself is never re-derived on host)."""
        if ctx["patched_bands"] is None:
            ctx["patched_bands"] = self._dispatch_patch(ctx)
        new_v, new_w_t = ctx["patched_bands"]
        self.sweeper.v_t = new_v
        self.sweeper.w_t = new_w_t
        self.sweeper.overloaded = ov_new
        self.graph = self.sweeper.graph = ctx["patched"]

    def _commit_host_mirrors(self, ls, new_out, ov_flips) -> None:
        """Fold one committed event's raw-weight diff and overload
        flips into the O(E) host mirrors (shared by the bucketed and
        full-width commit paths)."""
        for u, seen in new_out.items():
            old = self._w_out.get(u, {})
            for v in set(old) - set(seen):
                self._w_in.get(v, {}).pop(u, None)
            self._w_out[u] = dict(seen)
            for v, w in seen.items():
                self._w_in.setdefault(v, {})[u] = w
        for nm in ov_flips:
            self._ov_host[nm] = ls.is_node_overloaded(nm)

    def _full_refresh(self, ls, ctx, ov_new, new_out, ov_flips,
                      defer=False):
        """Overflow path: the affected-row count exceeds every solve
        bucket (a fat-tree link up/down affects EVERY destination row
        through ECMP next-hop churn), so re-solving a subset saves
        nothing — but the LAYOUT is still patchable. Keep the patched
        resident tensors and run the full-width dispatch; the host
        layout recompile (the dominant cold-build cost: seconds at 10k)
        is skipped entirely.

        The readback is delta-compacted ON DEVICE against the resident
        previous packed product (_compact_changed): the host reads one
        scalar + the changed rows, applies them in place
        (assemble_result delta mode) and reports the moved names from
        that same diff — no full-product transfer, no host digest
        copy+diff, no RouteSweepResult re-assembly."""
        self._apply_patch_resident(ctx, ov_new)
        dr, digests, packed = self._full_resident(self.graph)
        # counted apart from incremental_events: the four event
        # classes (bucketed incremental / frontier re-solve /
        # full-width refresh / cold rebuild) stay disjoint in
        # artifacts
        self.full_refreshes += 1
        get_registry().counter_bump("route_engine.full_refreshes")
        get_flight_recorder().note("engine", path="full_refresh")
        return self._commit_full_width(
            ls, dr, digests, packed, new_out, ov_flips, defer=defer
        )

    @committed_dispatch
    def _commit_full_width(self, ls, dr, digests, packed, new_out,
                           ov_flips, defer=False):
        """Shared commit tail of the full-width refresh and the
        frontier re-solve: both produce a complete (dr, digests,
        packed) product in one wide dispatch, compact the diff on
        device, and apply only the changed rows on host. With
        ``defer=True`` the changed count stays an in-flight device
        scalar riding the async lane (PendingDelta full-width mode):
        the overflow rungs then also submit-and-walk-away, keeping the
        committed two-touch event window."""
        ch_count, comp = aot_call(
            "compact_changed", _compact_changed,
            (packed, self._packed_dev),
            dict(n=self.graph.n),
        )
        self._dr = dr
        self._digests_dev = digests
        self._packed_dev = packed
        self._commit_host_mirrors(ls, new_out, ov_flips)
        self.version = ls.topology_version
        self.aversion = ls.attributes_version
        # remember that events are running wide: start the next probe
        # at the top bucket (one dispatch) instead of re-climbing the
        # ladder; small events decay the hint back down as usual
        self._k_hint = _ROW_BUCKETS[-1]
        if defer:
            pending = PendingDelta(
                self, [comp], [-1], [None], int(comp.shape[0]),
                fw_count=ch_count,
            )
            self._pending = pending
            return pending
        da.kick_async(ch_count)
        m = int(da.reap_read(ch_count, kicked=True))
        names: List[str] = []
        # openr-lint: disable=host-branch-in-chain -- post-reap delta apply: the window already closed; the count only sizes the host mirror copy (audited)
        if m:
            names = self._apply_delta_rows(
                da.reap_read(_rows_slice(comp, 0, m))
            )
        bytes_read = m * comp.shape[1] * 4 + 4  # rows + the scalar
        self.last_delta_rows = m
        self.last_readback_bytes = bytes_read
        self.last_overlap_ms = 0.0
        reg = get_registry()
        reg.observe("ops.delta_rows", float(m))
        reg.observe("ops.readback_bytes", float(bytes_read))
        return sorted(names)

    @solve_window
    def _dispatch_frontier_probe(self, ctx, e_dev, limit):
        """Backend hook: dispatch the affected-cone probe
        (rs._cone_expand) against the PRE-patch resident tensors.
        Returns ``(cone, meta)`` — both in-flight device arrays, meta
        being the float32 row ``[rows, cells, jumps, converged]`` —
        or None when the backend has no frontier kernel (the caller
        then rides the full-width refresh).

        Ordering contract: this MUST run before _apply_patch_resident
        commits the event's band patch — the cone is the
        tight-closure under the OLD weights, so the resident
        v_t/w_t/_dr it reads have to be the pre-event ones (they are:
        bucketed dispatches are functional and nothing commits until
        _commit_device)."""
        e_u_d, e_v_d, e_wo_d, e_wn_d = e_dev
        lim = jnp.asarray([limit], dtype=jnp.float32)
        if self.plan is not None:
            lim = self.plan.replicate(lim)
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip frontier
            # probe (mesh is None): no mesh axis to spec
            return ell_dispatch(
                "ell_frontier_probe", _frontier_probe,
                (
                    self.sweeper.v_t, self.sweeper.w_t, self._dr,
                    e_u_d, e_v_d, e_wo_d, e_wn_d, lim,
                ),
                dict(
                    bands=self.graph.bands, n=self.graph.n_pad,
                    max_jumps=_FRONTIER_MAX_JUMPS,
                ),
            )
        return ell_dispatch(
            "ell_frontier_probe_sharded", _sharded_frontier_probe,
            (
                self.sweeper.v_t, self.sweeper.w_t, self._dr,
                e_u_d, e_v_d, e_wo_d, e_wn_d, lim,
            ),
            dict(
                bands=self.graph.bands, n=self.graph.n_pad,
                max_jumps=_FRONTIER_MAX_JUMPS, mesh=self.mesh,
            ),
        )

    @solve_window
    def _frontier_resident(self, cone):
        """Backend hook: the masked full-width dispatch — every row
        launches, but only cone cells re-relax from INF; all other
        cells keep their resident distances, which stay valid upper
        bounds (every cell whose old tight path crossed an increased
        edge is in the cone), so the fixed point converges in
        O(cone diameter) sweeps instead of O(graph diameter). Expects
        the band patch ALREADY adopted (_apply_patch_resident ran)."""
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip frontier
            # re-solve (mesh is None): no mesh axis to spec
            return ell_dispatch(
                "ell_frontier_step", _frontier_step,
                (
                    self.sweeper.v_t, self.sweeper.w_t, cone, self._dr,
                    self.sweeper.overloaded,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(bands=self.graph.bands, n=self.graph.n_pad),
            )
        return ell_dispatch(
            "ell_frontier_step_sharded", _sharded_frontier_step,
            (
                self.sweeper.v_t, self.sweeper.w_t, cone, self._dr,
                self.sweeper.overloaded,
                self.sweeper._samp_ids_dev, self.sweeper._samp_v_dev,
                self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
            ),
            dict(
                bands=self.graph.bands, n=self.graph.n_pad,
                mesh=self.mesh,
            ),
        )

    @solve_window
    def _dispatch_overflow_chain(self, ctx, e_dev, ov_new, limit):
        """Backend hook: the FUSED overflow decision chain — probe,
        on-device frontier-vs-full-width seed select, warm re-solve,
        extraction and delta compaction in one dispatch
        (_overflow_chain). Returns the chain product tuple
        ``(dr, digests, packed, ch_count, comp, meta)`` with meta an
        in-flight device row, or None when the event WIDENED the band
        layout (static shapes changed under the resident tensors —
        the split probe/branch path owns that recompile)."""
        if ctx["patched"].bands != self.graph.bands:
            return None
        if ctx["patched_bands"] is None:
            ctx["patched_bands"] = self._dispatch_patch(ctx)
        new_v, new_w = ctx["patched_bands"]
        e_u_d, e_v_d, e_wo_d, e_wn_d = e_dev
        lim = jnp.asarray([limit], dtype=jnp.float32)
        if self.plan is not None:
            lim = self.plan.replicate(lim)
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip fused
            # overflow chain (mesh is None): no mesh axis to spec
            return ell_dispatch(
                "ell_overflow_chain", _overflow_chain,
                (
                    self.sweeper.v_t, self.sweeper.w_t, new_v, new_w,
                    self._dr, self._packed_dev,
                    e_u_d, e_v_d, e_wo_d, e_wn_d, lim, ov_new,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(
                    bands=self.graph.bands, n=self.graph.n_pad,
                    n_real=self.graph.n, max_jumps=_FRONTIER_MAX_JUMPS,
                ),
            )
        return ell_dispatch(
            "ell_overflow_chain_sharded", _sharded_overflow_chain,
            (
                self.sweeper.v_t, self.sweeper.w_t, new_v, new_w,
                self._dr, self._packed_dev,
                e_u_d, e_v_d, e_wo_d, e_wn_d, lim, ov_new,
                self.sweeper._samp_ids_dev, self.sweeper._samp_v_dev,
                self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
            ),
            dict(
                bands=self.graph.bands, n=self.graph.n_pad,
                n_real=self.graph.n, max_jumps=_FRONTIER_MAX_JUMPS,
                mesh=self.mesh,
            ),
        )

    def _note_overflow_meta(self, meta, limit) -> str:
        """Post-hoc policy classification of a fused overflow chain's
        reaped probe meta: the SAME float32 compare the device seed
        select made, so the frontier/full-width counters match the
        branch the chain actually took. Mirrors the split path's
        counter/flight bookkeeping exactly (both counters bump on a
        fallback: it IS a full refresh)."""
        reg = get_registry()
        rows, jumps = int(meta[0]), int(meta[2])
        cells = float(meta[1])
        converged = bool(meta[3])
        self.last_frontier_rows = rows
        self.last_frontier_jumps = jumps
        self.last_frontier_cells = cells
        reg.observe("ops.frontier_rows", float(rows))
        reg.observe("ops.frontier_cells", cells)
        reg.observe("ops.frontier_jumps", float(jumps))
        if converged and np.float32(cells) <= np.float32(limit):
            self.frontier_resolves += 1
            reg.counter_bump("route_engine.frontier_resolves")
            get_flight_recorder().note(
                "engine", path="frontier_resolve"
            )
            return "frontier"
        self.frontier_fallbacks += 1
        reg.counter_bump("ops.frontier_fallbacks")
        get_flight_recorder().note(
            "engine", path="frontier_fallback", rows=rows, jumps=jumps
        )
        self.full_refreshes += 1
        reg.counter_bump("route_engine.full_refreshes")
        get_flight_recorder().note("engine", path="full_refresh")
        return "full_width"

    def _commit_overflow_chain(self, ls, chain, ctx, ov_new, new_out,
                               ov_flips, limit, defer=False):
        """Commit tail of the fused overflow chain: adopt the patch +
        chain product, then reap (or defer) the compacted delta AND
        the policy meta in one read phase — the counters classify
        post-hoc from the same meta the device branched on."""
        dr, digests, packed, ch_count, comp, meta_dev = chain
        # the chain read the pre-patch residents; adopt the patched
        # tensors now (patched_bands already dispatched, no extra
        # program launch)
        self._apply_patch_resident(ctx, ov_new)
        self._dr = dr
        self._digests_dev = digests
        self._packed_dev = packed
        self._commit_host_mirrors(ls, new_out, ov_flips)
        self.version = ls.topology_version
        self.aversion = ls.attributes_version
        self._k_hint = _ROW_BUCKETS[-1]
        if defer:
            pending = PendingDelta(
                self, [comp], [-1], [None], int(comp.shape[0]),
                fw_count=ch_count, meta_dev=meta_dev,
                meta_limit=limit,
            )
            self._pending = pending
            return pending
        da.kick_async(ch_count)
        da.kick_async(meta_dev)
        self._note_overflow_meta(
            da.reap_read(meta_dev, kicked=True), limit
        )
        m = int(da.reap_read(ch_count, kicked=True))
        names: List[str] = []
        if m:
            names = self._apply_delta_rows(
                da.reap_read(_rows_slice(comp, 0, m))
            )
        bytes_read = m * comp.shape[1] * 4 + 4
        self.last_delta_rows = m
        self.last_readback_bytes = bytes_read
        self.last_overlap_ms = 0.0
        reg = get_registry()
        reg.observe("ops.delta_rows", float(m))
        reg.observe("ops.readback_bytes", float(bytes_read))
        return sorted(names)

    @committed_dispatch
    def _overflow_refresh(self, ls, ctx, ov_new, new_out, ov_flips,
                          e_dev, defer=False):
        """Overflow policy: the affected-row count exceeded every
        solve bucket. The warm path is the FUSED chain
        (_dispatch_overflow_chain): probe + frontier-vs-full-width
        decision + re-solve + compaction in one dispatch, the branch
        taken ON DEVICE — no 16-byte meta readback between the probe
        and the re-solve, so a pipelined burst's dispatch chain never
        breaks here. When the event widened the band layout the split
        probe/branch path runs instead (the widening recompile
        dominates; one policy readback is noise there). Either way the
        readback stays delta-compacted (O(changed)).

        A chain/probe failure degrades WITHIN the warm rung: the
        full-width refresh is this path's own fallback, so the
        supervisor ladder (warm -> cold -> host) never sees a frontier
        error."""
        reg = get_registry()
        tracer = get_tracer()
        span = tracer.span_active("ops.frontier_resolve")
        rows = jumps = -1
        path = "full_width"
        try:
            # budget in CELLS (re-solve work), not rows-with-any-cell:
            # a single link down seeds one cell in nearly every
            # destination row, so a row count saturates at n while the
            # actual cone stays a sliver of the [n, n] product
            limit = self.frontier_threshold * float(self.graph.n) ** 2
            chain = None
            widened = False
            try:
                fault_point(FAULT_FRONTIER)
                chain = self._dispatch_overflow_chain(
                    ctx, e_dev, ov_new, limit
                )
                widened = chain is None
            except Exception:
                # degrade, don't propagate: full-width gives the same
                # bit-identical answer, just slower (counted so a
                # frontier-fallback storm is visible in telemetry)
                reg.counter_bump("route_engine.frontier_errors")
            if chain is not None:
                path = "fused_chain"
                got = self._commit_overflow_chain(
                    ls, chain, ctx, ov_new, new_out, ov_flips, limit,
                    defer=defer,
                )
                rows = self.last_frontier_rows
                jumps = self.last_frontier_jumps
                return got
            if widened:
                # split path (band widening recompiles anyway): probe,
                # then one async-lane policy readback + host branch
                probe = None
                try:
                    probe = self._dispatch_frontier_probe(
                        ctx, e_dev, limit
                    )
                except Exception:
                    reg.counter_bump("route_engine.frontier_errors")
                if probe is not None:
                    cone, meta = probe
                    # 16-byte policy readback: kicked onto the async
                    # lane so the decision read folds into the
                    # window's single read phase instead of a
                    # dedicated blocking sync
                    da.kick_async(meta)
                    meta = da.reap_read(meta, kicked=True)
                    rows, jumps = int(meta[0]), int(meta[2])
                    cells = float(meta[1])
                    converged = bool(meta[3])
                    self.last_frontier_rows = rows
                    self.last_frontier_jumps = jumps
                    self.last_frontier_cells = cells
                    reg.observe("ops.frontier_rows", float(rows))
                    reg.observe("ops.frontier_cells", cells)
                    reg.observe("ops.frontier_jumps", float(jumps))
                    # openr-lint: disable=host-branch-in-chain -- widened-layout split path: the band reshape recompiles the chain anyway, so the one policy branch stays host-side (audited)
                    if converged and cells <= limit:
                        path = "frontier"
                        return self._frontier_refresh(
                            ls, ctx, ov_new, new_out, ov_flips, cone,
                            defer=defer,
                        )
            self.frontier_fallbacks += 1
            reg.counter_bump("ops.frontier_fallbacks")
            get_flight_recorder().note(
                "engine", path="frontier_fallback", rows=rows, jumps=jumps
            )
            return self._full_refresh(
                ls, ctx, ov_new, new_out, ov_flips, defer=defer
            )
        finally:
            tracer.end_span_active(
                span, path=path, frontier_rows=rows,
                frontier_jumps=jumps,
            )

    def _frontier_refresh(self, ls, ctx, ov_new, new_out, ov_flips,
                          cone, defer=False):
        """Frontier path: adopt the band patch resident, then one
        masked dispatch seeds cone cells at INF while every other cell
        keeps its resident distance. Bit-identical to the cold solve
        by the unique-fixed-point argument (int32 min-plus over the
        patched weights has one fixed point, and any seed S with
        d* <= S converges to it); commits through the same
        delta-compacted tail as _full_refresh."""
        self._apply_patch_resident(ctx, ov_new)
        dr, digests, packed = self._frontier_resident(cone)
        self.frontier_resolves += 1
        get_registry().counter_bump("route_engine.frontier_resolves")
        get_flight_recorder().note("engine", path="frontier_resolve")
        return self._commit_full_width(
            ls, dr, digests, packed, new_out, ov_flips, defer=defer
        )

    def flush(self):
        """Consume the in-flight delta, if any (host-side apply of the
        pending event's changed rows into self.result). Returns the
        consumed PendingDelta or None."""
        return self._consume_pending(overlap=False)

    def _apply_delta_rows(self, rows: np.ndarray) -> List[str]:
        """Apply one compacted [m, 1+W] readback ([dest id, product]
        per row) into the resident host result, returning the touched
        destination names. O(m) — the host never walks all rows."""
        rows = rows[rows[:, 0] < self.graph.n]
        if not len(rows):
            return []
        # settle the packed mirror on success, same rows: after every
        # consume the mirror matches the resident product bit-for-bit
        # on real rows (the tier-2 digest invariant)
        if self._packed_host is not None:
            self._packed_host[rows[:, 0]] = rows[:, 1:]
        rs.assemble_result(self.sweeper, rows, into=self.result)
        names = self.graph.node_names
        return [names[int(t)] for t in rows[:, 0]]

    @committed_dispatch
    def _consume_pending(self, overlap: bool):
        """Drain the pending delta: read each shard's changed rows
        (O(changed) transfer) and apply them in place. When ``overlap``
        is True this runs INSIDE the next event's dispatch window —
        the host-side apply and the device solve proceed concurrently
        (the double-buffer payoff, recorded as
        ops.route_engine.overlap_ms). This is the window's REAP side:
        every read rides a copy kicked async at PendingDelta creation,
        so the host normally finds the bytes already landed."""
        p = self._pending
        if p is None:
            return None
        self._pending = None
        # a consume failure drops this delta un-applied; every deeper
        # ladder rung reassembles the whole result, so the staleness
        # cannot outlive the walk
        fault_point(FAULT_CONSUME)
        fault_point(FAULT_DEVICE_LOST)
        if overlap:
            # window N's staged reap drains inside window N+1's span:
            # the double-buffer overlap, witnessed for the per-drain
            # accounting
            da.note_overlapped_reap()
        if p.meta_dev is not None:
            # fused-overflow-chain pending: settle the policy
            # classification (frontier vs full-width counters) from
            # the meta row that rode the async lane since commit
            self._note_overflow_meta(
                da.reap_read(p.meta_dev, kicked=True), p.meta_limit
            )
            p.meta_dev = None
        tracer = get_tracer()
        span = tracer.span_active("ops.route_engine.delta_consume")
        reg = get_registry()
        sharded = self.mesh is not None
        t0 = time.perf_counter()
        names: List[str] = []
        total_rows = 0
        total_bytes = 0
        for seg, sl, m in zip(p.segs, p.dslices, p.ch_counts):
            t_sh = time.perf_counter()
            # openr-lint: disable=host-branch-in-chain -- pending-delta consume IS the drain point: every branch here runs after the overlapped reap lands (audited)
            if m is None:
                # FULL-WIDTH pending: the changed count rode the async
                # lane since the overflow commit; reap it, then pull
                # exactly the changed rows (compacted from ROW 0 — a
                # _compact_changed segment carries no meta row)
                m = int(da.reap_read(p.fw_count, kicked=True))
                shard_bytes = 4
                # openr-lint: disable=host-branch-in-chain -- post-reap apply: the count only sizes the row pull (audited)
                if m:
                    names.extend(self._apply_delta_rows(
                        da.reap_read(_rows_slice(seg, 0, m))
                    ))
                    total_rows += m
                    shard_bytes += m * seg.shape[1] * 4
                total_bytes += shard_bytes
                continue
            # meta row already crossed (retry ladder); count it
            shard_bytes = seg.shape[1] * 4
            # openr-lint: disable=host-branch-in-chain -- post-reap apply: the count only sizes the row pull (audited)
            if m:
                # the per-shard copy was kicked async at PendingDelta
                # creation: the reap normally finds the host value
                # already landed (explicit, guard-exempt)
                rows = (
                    da.reap_read(sl, kicked=True)
                    if isinstance(sl, jax.Array) else np.asarray(sl)
                )
                names.extend(self._apply_delta_rows(rows))
                total_rows += m
                shard_bytes += m * seg.shape[1] * 4
            total_bytes += shard_bytes
            if sharded:
                reg.counter_bump(
                    "ops.shard_readback_bytes", shard_bytes
                )
                if overlap:
                    reg.observe(
                        "ops.shard_consume_overlap_ms",
                        (time.perf_counter() - t_sh) * 1000.0,
                    )
        ms = (time.perf_counter() - t0) * 1000.0
        p.names = sorted(set(names))
        p.consumed = True
        p.delta_rows = total_rows
        p.readback_bytes = total_bytes
        p.overlap_ms = ms if overlap else 0.0
        self.last_delta_rows = total_rows
        self.last_readback_bytes = total_bytes
        self.last_overlap_ms = p.overlap_ms
        reg.observe("ops.delta_rows", float(total_rows))
        reg.observe("ops.readback_bytes", float(total_bytes))
        if overlap:
            reg.observe("ops.route_engine.overlap_ms", ms)
        tracer.end_span_active(
            span, overlap=overlap, delta_rows=total_rows,
            readback_bytes=total_bytes,
        )
        return p

    def churn_coalesced(self, ls, affected_sets, defer_consume=False):
        """Fold N patches that landed inside one debounce window into
        ONE fused dispatch + ONE compacted readback. Exactly
        equivalent to N sequential churn() calls by construction: the
        event diff compares the CURRENT LinkState against the resident
        raw-weight mirrors, so the union affected set describes the
        net effect and intermediate states are never observed."""
        union: Set[str] = set()
        for s in affected_sets:
            union |= set(s)
        if len(affected_sets) > 1:
            self.coalesced_events += 1
            get_registry().counter_bump(
                "route_engine.coalesced_events"
            )
        return self.churn(ls, union, defer_consume=defer_consume)

    def churn_window(self, ls, affected_sets, defer_consume=False):
        """Committed-dispatch entry point for one debounce window: N
        debounced events become ONE device program under ONE
        accounting window (``ops.host_touches.churn_window``). The
        batched result is bit-identical to N sequential ``churn()``
        calls — same union-diff argument as ``churn_coalesced`` — but
        the host only touches the device twice: once to submit the
        fused dispatch chain, once to reap the compacted delta.

        When a staged speculation (speculate_churn) matches this
        window's final composition — same union, same LinkState
        versions, residents untouched since staging — the window
        ADOPTS the already-dispatched solve (ops.spec_hits) and only
        pays the commit + reap; any mismatch cancels the speculation
        (ops.spec_cancels, never silent) and the committed path below
        re-dispatches from the unchanged residents, so the result is
        bit-identical to the sequential oracle either way."""
        union: Set[str] = set()
        for s in affected_sets:
            union |= set(s)
        spec = self._speculation
        self._speculation = None
        if spec is not None:
            if (
                spec.union == frozenset(union)
                and spec.version == ls.topology_version
                and spec.aversion == ls.attributes_version
                and spec.dr_ref is self._dr
                and self._device_valid
            ):
                return self._adopt_speculation(
                    ls, spec, affected_sets, defer_consume
                )
            get_registry().counter_bump("ops.spec_cancels")
            get_flight_recorder().note("engine", path="spec_cancel")
        with da.event_window("churn_window"):
            return self.churn_coalesced(
                ls, affected_sets, defer_consume=defer_consume
            )

    def speculate_churn(self, ls, affected_sets) -> bool:
        """Stage a SPECULATIVE dispatch of the debounce backlog's
        most-likely final composition (latest-wins: the coalesced
        union as of now) before the window closes — the device solves
        while the host is otherwise idling out the debounce timer. The
        dispatch is purely functional (residents never donated), so a
        wrong guess costs nothing but the wasted device cycles:
        churn_window cancels it and re-dispatches committed.

        Counted, never silent: ops.spec_dispatches on staging,
        ops.spec_skips when a composition refuses speculation (sample
        -band mutation, layout widening, bucket overflow — the paths
        whose side effects are not cancellable or whose committed
        replay differs), ops.spec_cancels on an abandoned or
        mismatched attempt. Returns True when a speculation is
        staged."""
        reg = get_registry()
        union: Set[str] = set()
        for s in affected_sets:
            union |= set(s)
        self._speculation = None
        if not union or not self._device_valid:
            reg.counter_bump("ops.spec_skips")
            return False
        if union & set(self.sample_names):
            # _refresh_sample_bands mutates the sweeper slot tables
            # EARLY (before dispatch) — not cancellable, so a window
            # touching a sample node's adjacencies never speculates
            reg.counter_bump("ops.spec_skips")
            return False
        try:
            ctx = self._prepare_patch(ls, sorted(union))
            if ctx is None or self._layout_changed(ctx):
                # layout break: the committed path cold-rebuilds (or
                # recompiles the widened shapes) — nothing to adopt
                reg.counter_bump("ops.spec_skips")
                return False
            _raw, new_out, ov_flips, changed = self._event_diff(
                ls, union, self.graph
            )
            if not changed:
                # attribute-only backlog: nothing route-affecting
                reg.counter_bump("ops.spec_skips")
                return False
            structural = any(
                wo >= INF or wn >= INF
                for (wo, wn) in changed.values()
            )
            ov_new, e_dev = self._upload_event(
                ctx["patched"], changed
            )
            k = next(b for b in _ROW_BUCKETS if b >= self._k_hint)
            if self._pending is not None:
                # the staged dispatch submits while the previous
                # window's reap is still in flight: depth-2 pipelining
                da.note_pipelined_dispatch(2)
            segments, commit_state = self._run_bucket(
                ctx, k, e_dev, ov_new
            )
            meta_rows = [
                _seg_meta(seg) if isinstance(seg, jax.Array)
                else seg[0, :2]
                for seg in segments
            ]
            n_meta = sum(
                1 for seg in segments if isinstance(seg, jax.Array)
            )
            if n_meta:
                da.count_dispatch(n_meta)
            for mrow in meta_rows:
                da.kick_async(mrow)
            metas = [
                da.reap_read(mrow, kicked=True)
                if isinstance(mrow, jax.Array) else mrow
                for mrow in meta_rows
            ]
            counts = [int(m[0]) for m in metas]
            ch_counts = [int(m[1]) for m in metas]
            if max(counts) > k:
                # overflow composition: the committed path walks the
                # bucket ladder / overflow policy — adopting a partial
                # bucket is never profitable
                reg.counter_bump("ops.spec_skips")
                return False
        except Exception:
            # speculation runs OUTSIDE the supervisor ladder: any
            # failure (chaos seam included) abandons the attempt and
            # the committed path re-dispatches from the unchanged
            # residents — a fault mid-speculation degrades within the
            # ladder at commit time, never up it
            reg.counter_bump("ops.spec_cancels")
            get_flight_recorder().note("engine", path="spec_abandon")
            return False
        spec = _Speculation()
        spec.union = frozenset(union)
        spec.version = ls.topology_version
        spec.aversion = ls.attributes_version
        spec.dr_ref = self._dr
        spec.ctx = ctx
        spec.segments = segments
        spec.counts = counts
        spec.ch_counts = ch_counts
        spec.commit_state = commit_state
        spec.ov_new = ov_new
        spec.k = k
        spec.new_out = new_out
        spec.ov_flips = ov_flips
        spec.structural = structural
        self._speculation = spec
        reg.counter_bump("ops.spec_dispatches")
        return True

    def _adopt_speculation(self, ls, spec, affected_sets,
                           defer_consume):
        """Commit a matched speculation as the window's result: the
        solve already ran, so the window is commit + reap only. The
        counter bookkeeping mirrors _churn_device exactly — an adopted
        window is indistinguishable from a committed one in the
        artifacts except for ops.spec_hits."""
        reg = get_registry()
        reg.counter_bump("ops.spec_hits")
        get_flight_recorder().note("engine", path="spec_hit")
        with da.event_window("churn_window"):
            if len(affected_sets) > 1:
                self.coalesced_events += 1
                reg.counter_bump("route_engine.coalesced_events")
            if spec.structural:
                self.structural_events += 1
                reg.counter_bump("route_engine.structural_events")
            # the previous window's delta (if any) drains here, inside
            # the adopted window — same overlap as _churn_device
            self._consume_pending(overlap=True)
            self._commit_device(spec.ctx, spec.commit_state,
                                spec.ov_new)
            self._commit_host_mirrors(ls, spec.new_out, spec.ov_flips)
            self.version = ls.topology_version
            self.aversion = ls.attributes_version
            self.incremental_events += 1
            reg.counter_bump("route_engine.incremental_events")
            self._k_hint = max(
                _ROW_BUCKETS[0], min(1024, 2 * max(spec.counts))
            )
            pending = PendingDelta(
                self, spec.segments, spec.counts, spec.ch_counts,
                spec.k,
            )
            self._pending = pending
            if defer_consume:
                return pending
            self._consume_pending(overlap=False)
            return pending.names

    def churn_burst(self, ls, apply_events, defer_consume=False):
        """Pipelined multi-event burst: every window's committed
        dispatch submits back to back — window N+1's solve is on the
        stream before window N's reap lands — then ALL reaps settle in
        one read run, so the whole burst costs ~2 host touches
        (ops.touches_per_drain) instead of 2 per window.

        ``apply_events`` is a list of callables; each mutates the
        LinkState and returns its affected-node set (the latest-wins
        delivery shape the debounce terminal hands the engine).
        Bit-identical to applying the events sequentially: each
        window's dispatch reads the previous window's COMMITTED device
        state (functional dispatches, residents never donated), and
        any hazard — bucket overflow, layout widening, sample-band
        mutation, a chaos-seam fault — cancels the burst back to a
        pre-burst snapshot and replays the whole thing as ONE
        coalesced committed window (ops.burst_cancels; the union-diff
        argument makes the replay equal the sequential chain).
        Returns the sorted union of moved destination names, or the
        LAST window's PendingDelta under ``defer_consume=True``."""
        if not apply_events:
            return []
        if not self._device_valid:
            # degraded: no residents to pipeline against — fold the
            # burst into one supervised window
            sets = [set(ev()) for ev in apply_events]
            return self.churn_window(
                ls, sets, defer_consume=defer_consume
            )
        with da.pipeline_drain("churn_burst"):
            return self._churn_burst_drain(
                ls, apply_events, defer_consume
            )

    def _burst_snapshot(self):
        """Pre-burst restore point: device refs (functional dispatches
        never donate them) + deep copies of the host mirrors the
        optimistic per-window commits mutate."""
        return dict(
            dr=self._dr, dig=self._digests_dev,
            packed=self._packed_dev,
            v_t=self.sweeper.v_t, w_t=self.sweeper.w_t,
            ov=self.sweeper.overloaded, graph=self.graph,
            w_out={u: dict(d) for u, d in self._w_out.items()},
            w_in={u: dict(d) for u, d in self._w_in.items()},
            ov_host=dict(self._ov_host),
            version=self.version, aversion=self.aversion,
            k_hint=self._k_hint,
        )

    def _burst_rollback(self, snap) -> None:
        self._dr = snap["dr"]
        self._digests_dev = snap["dig"]
        self._packed_dev = snap["packed"]
        self.sweeper.v_t = snap["v_t"]
        self.sweeper.w_t = snap["w_t"]
        self.sweeper.overloaded = snap["ov"]
        self.graph = self.sweeper.graph = snap["graph"]
        self._w_out = snap["w_out"]
        self._w_in = snap["w_in"]
        self._ov_host = snap["ov_host"]
        self.version = snap["version"]
        self.aversion = snap["aversion"]
        self._k_hint = snap["k_hint"]

    def _churn_burst_drain(self, ls, apply_events, defer_consume):
        """The drain body: submit phase pipelines every window's
        dispatch at ONE fixed bucket (climbing the ladder mid-burst
        would interleave a meta reap between submits and break the
        S...S,R...R phase shape), optimistically committing device
        state + host mirrors per window; the settle phase reaps every
        meta and every delta in one read run. Any overflow or
        pre-dispatch hazard rolls back to the snapshot and replays the
        burst as one coalesced supervised window."""
        reg = get_registry()
        self._speculation = None
        snap = self._burst_snapshot()
        union: Set[str] = set()
        # fixed bucket for the whole burst: first ladder rung >= hint
        k = next(b for b in _ROW_BUCKETS if b >= self._k_hint)
        staged: List[dict] = []
        cancel = False
        idx = 0
        try:
            while idx < len(apply_events):
                ev = apply_events[idx]
                idx += 1
                aff = set(ev())
                union |= aff
                if not aff:
                    continue
                if aff & set(self.sample_names):
                    cancel = True
                    break
                ctx = self._prepare_patch(ls, sorted(aff))
                if ctx is None or self._layout_changed(ctx):
                    cancel = True
                    break
                _raw, new_out, ov_flips, changed = self._event_diff(
                    ls, aff, self.graph
                )
                if not changed:
                    self.version = ls.topology_version
                    self.aversion = ls.attributes_version
                    continue
                structural = any(
                    wo >= INF or wn >= INF
                    for (wo, wn) in changed.values()
                )
                ov_new, e_dev = self._upload_event(
                    ctx["patched"], changed
                )
                if staged or self._pending is not None:
                    da.note_pipelined_dispatch(len(staged) + 1)
                segments, commit_state = self._run_bucket(
                    ctx, k, e_dev, ov_new
                )
                meta_rows = [
                    _seg_meta(seg) if isinstance(seg, jax.Array)
                    else seg[0, :2]
                    for seg in segments
                ]
                n_meta = sum(
                    1 for seg in segments
                    if isinstance(seg, jax.Array)
                )
                if n_meta:
                    da.count_dispatch(n_meta)
                for mrow in meta_rows:
                    da.kick_async(mrow)
                if not staged:
                    # first window drains any pre-burst delta while
                    # the burst solves (the double-buffer overlap)
                    self._consume_pending(overlap=True)
                # optimistic adoption: window N+1's dispatch must read
                # window N's committed state to equal the sequential
                # chain; the snapshot guards the whole prefix
                self._commit_device(ctx, commit_state, ov_new)
                self._commit_host_mirrors(ls, new_out, ov_flips)
                self.version = ls.topology_version
                self.aversion = ls.attributes_version
                staged.append(dict(
                    segments=segments, meta_rows=meta_rows,
                    structural=structural,
                ))
                da.note_window()
        except Exception:
            # chaos seam / dispatch failure mid-burst: degrade WITHIN
            # the ladder — roll back and let the supervised replay
            # walk warm -> cold -> host as usual, never up it
            cancel = True
        if not cancel and staged:
            # settle: one read run over every window's meta
            all_counts: List[List[int]] = []
            all_ch: List[List[int]] = []
            for st in staged:
                metas = [
                    da.reap_read(mrow, kicked=True)
                    if isinstance(mrow, jax.Array) else mrow
                    for mrow in st["meta_rows"]
                ]
                all_counts.append([int(m[0]) for m in metas])
                all_ch.append([int(m[1]) for m in metas])
            if max(max(c) for c in all_counts) > k:
                cancel = True
        if cancel:
            # one cancel path for every hazard: finish delivering the
            # remaining LinkState mutations, restore the pre-burst
            # state, and replay the net effect as ONE supervised
            # coalesced window (union-diff => bit-identical)
            while idx < len(apply_events):
                union |= set(apply_events[idx]())
                idx += 1
            self._burst_rollback(snap)
            reg.counter_bump("ops.burst_cancels")
            get_flight_recorder().note(
                "engine", path="burst_cancel",
                windows=len(apply_events),
            )
            if len(apply_events) > 1:
                self.coalesced_events += 1
                reg.counter_bump("route_engine.coalesced_events")
            return self.churn(
                ls, union, defer_consume=defer_consume
            )
        if not staged:
            # attribute-only burst
            if not defer_consume:
                self.flush()
            return []
        self._k_hint = max(
            _ROW_BUCKETS[0],
            min(1024, 2 * max(max(c) for c in all_counts)),
        )
        names: List[str] = []
        last = len(staged) - 1
        result = None
        for i, st in enumerate(staged):
            self.incremental_events += 1
            reg.counter_bump("route_engine.incremental_events")
            if st["structural"]:
                self.structural_events += 1
                reg.counter_bump("route_engine.structural_events")
            pending = PendingDelta(
                self, st["segments"], all_counts[i], all_ch[i], k
            )
            self._pending = pending
            if defer_consume and i == last:
                result = pending
                break
            self._consume_pending(overlap=False)
            names.extend(pending.names)
        if result is not None:
            return result
        return sorted(set(names))

    def churn(self, ls, affected_nodes: Set[str],
              defer_consume: bool = False):
        """Apply one churn event, SUPERVISED: the degradation ladder
        walks warm incremental re-solve → device-loss recovery → drain
        + cold device rebuild → host NumPy fallback, each rung
        producing a bit-identical route product, until one succeeds
        (LadderExhausted if none does). Returns the warm path's
        affected destination NAMES / PendingDelta
        (``defer_consume=True``), or None from the deeper rungs — the
        pre-existing cold-rebuild contract. The recover rung is inert
        (fails straight through) unless a rung failure was recognized
        as a device loss."""
        # corruption seam (non-raising): disarmed cost is one attribute
        # read inside consume_fault — the sanctioned churn-path budget
        if consume_fault(FAULT_CORRUPT):
            self._corrupt_events += 1
            self.corrupt_resident(self._corrupt_events)
        with da.event_window("churn"):
            return self._churn_supervised(ls, affected_nodes,
                                          defer_consume)

    def _churn_supervised(self, ls, affected_nodes: Set[str],
                          defer_consume: bool = False):
        return self.supervisor.run((
            ("warm", lambda: self._rung_guard(
                self._churn_device, ls, affected_nodes, defer_consume
            )),
            ("recover", lambda: self._rung_guard(
                self._device_recover, ls, affected_nodes, defer_consume
            )),
            ("cold", lambda: self._rung_guard(self._cold_recover, ls)),
            ("host", lambda: self._host_fallback(ls)),
        ))

    def _rung_guard(self, fn, *args):
        """Run one ladder rung, marking the engine device-lost when the
        failure is the accelerator dying (typed DeviceLostError, the
        ``device.lost`` seam, or a device-loss flavored
        XlaRuntimeError) — the marker arms the recover rung. The
        exception still propagates so the supervisor walks the
        ladder."""
        try:
            return fn(*args)
        except Exception as exc:  # noqa: BLE001 - re-raised below
            if is_device_loss(exc):
                self._device_valid = False
                self._device_lost = True
                get_registry().counter_bump("recovery.device_lost")
            raise

    @fault_boundary
    def _cold_recover(self, ls) -> None:
        """Ladder rung 2: drain + cold device rebuild. Layout, host
        mirrors, and residents are all rederived from the LinkState —
        the cold-twin contract of the parity suite makes the result
        bit-identical to the warm path's."""
        self._build(ls)
        return None

    def _make_sweeper(self, graph):
        """Backend hook: a fresh sweeper (device band/sample uploads)
        over an ALREADY-COMPILED host graph — the device-loss recovery
        path, which must not pay the host layout recompile."""
        return rs.RouteSweeper(graph, self.sample_names, plan=self.plan)

    @committed_dispatch
    def _probe_device(self, dev) -> bool:
        """Liveness probe for one mesh device (monkeypatchable: tests
        and the chaos harness simulate partial mesh loss here)."""
        try:
            # openr-lint: disable=committed-dispatch -- liveness probe:
            # the blocking sync IS the signal (recover rung, never on
            # the warm submit/reap path)
            jax.device_put(
                np.zeros((), np.int32), dev
            ).block_until_ready()
            return True
        except Exception:  # noqa: BLE001 - any failure means dead
            return False

    def _surviving_devices(self):
        return [d for d in self.mesh.devices.flat if self._probe_device(d)]

    @fault_boundary
    @requires_drain("_discard_pending")
    def _device_recover(self, ls, affected_nodes: Set[str],
                        defer_consume: bool = False):
        """Ladder rung 1: rebuild the residents on a live device from
        the host mirrors after a device loss. Single-chip (and a mesh
        whose devices all answer the liveness probe): re-land the
        resident sweeper + full product from ``self.graph`` — host
        layout intact, no ``compile_ell``, the dispatch shapes are
        already jitted. A mesh that lost devices SHRINKS to the
        survivors (typed ``recovery.mesh_shrinks`` counter — never
        silent) and cold-builds on the smaller mesh. Either way the
        rung finishes by re-running the warm churn body for the event
        that observed the loss, so the caller sees the ordinary warm
        contract."""
        if not self._device_lost:
            raise _DeviceStateInvalid(
                "no device loss observed (recover rung idle)"
            )
        self._discard_pending()
        reg = get_registry()
        tracer = get_tracer()
        span = tracer.span_active("recovery.device_rebuild")
        self._device_lost = False
        shrunk = False
        if self.mesh is not None:
            survivors = self._surviving_devices()
            if not survivors:
                tracer.end_span_active(span, ok=False)
                raise _DeviceStateInvalid(
                    "device recovery: no surviving devices in mesh"
                )
            if len(survivors) < self.mesh.devices.size:
                shrunk = True
                self.mesh_shrinks += 1
                reg.counter_bump("recovery.mesh_shrinks")
                self.mesh = Mesh(
                    np.asarray(survivors), self.mesh.axis_names
                )
                self.plan = ShardingPlan(self.mesh)
                self._align = self._base_align * self.mesh.devices.size
                reg.counter_set(
                    "recovery.mesh_size", self.mesh.devices.size
                )
        if shrunk:
            # per-shard row blocks changed: the layout must re-align,
            # so this is a true cold build on the surviving mesh
            self._build(ls)
        else:
            self.sweeper = self._make_sweeper(self.graph)
            dr, digests, packed = self._full_resident(self.graph)
            self._dr = dr
            self._digests_dev = digests
            self._packed_dev = packed
            packed_host = jax.device_get(packed)
            self.result = rs.assemble_result(self.sweeper, packed_host)
            self._packed_host = np.array(packed_host)
            self._device_valid = True
        self.device_rebuilds += 1
        reg.counter_bump("recovery.device_rebuilds")
        tracer.end_span_active(span, shrunk=shrunk)
        # the residents now mirror the last COMMITTED event; the event
        # that observed the loss has not landed — run it warm
        return self._churn_device(ls, affected_nodes, defer_consume)

    def _discard_pending(self) -> None:
        """Drop the in-flight delta WITHOUT the host-side apply: the
        host fallback replaces the whole result, so the pending rows
        are subsumed. A caller-held PendingDelta resolves (empty)."""
        p = self._pending
        self._pending = None
        # a staged speculation read residents this fallback bypasses
        self._speculation = None
        if p is not None:
            p.consumed = True
            get_registry().counter_bump("route_engine.deltas_discarded")

    # -- integrity plane (ResidentEngineContract) ---------------------

    audit_kind = "ell"

    def audit_ready(self) -> bool:
        return (
            self._device_valid
            and self._pending is None
            and self._packed_host is not None
        )

    def audit_residual(self) -> int:
        # openr-lint: disable=sharding-spec -- read-only audit probe off the churn path; bare jit stays placement-agnostic across single-chip and mesh engines (see integrity.kernels)
        return int(jax.device_get(integrity_kernels.ell_residual(
            self._dr, self.sweeper.v_t, self.sweeper.w_t,
            self.sweeper.overloaded, self.graph.bands,
        )))

    def audit_digest_pair(self) -> Tuple[int, int]:
        # real rows only: padding destination rows are never
        # delta-read-back, so they stay outside the mirror invariant
        n = self.graph.n
        # openr-lint: disable=sharding-spec -- read-only audit probe off the churn path; bare jit stays placement-agnostic across single-chip and mesh engines (see integrity.kernels)
        probe = integrity_kernels.fnv_device(self._packed_dev[:n])
        dev = int(jax.device_get(probe))
        host = integrity_kernels.fnv_host(self._packed_host[:n])
        return dev, host

    def audit_row_count(self) -> int:
        return self.graph.n

    def audit_sample_rows(self, rows: Sequence[int]) -> int:
        # pad the sample to a fixed pow2 bucket (>= 8) with repeats of
        # the first row — one compiled oracle shape, duplicates just
        # re-check the same row
        ids = list(int(r) for r in rows)
        b = 8
        while b < len(ids):
            b *= 2
        ids = ids + [ids[0]] * (b - len(ids))
        ids_t = jnp.asarray(np.asarray(ids, dtype=np.int32))
        if self.plan is not None:
            ids_t = self.plan.replicate(ids_t)
        return int(jax.device_get(self._sample_oracle(ids_t)))

    def _sample_oracle(self, ids_t):
        """Backend hook: tier-3 cold re-solve of the given rows."""
        # openr-lint: disable=sharding-spec -- read-only audit probe off the churn path; bare jit stays placement-agnostic across single-chip and mesh engines (see integrity.kernels)
        return integrity_kernels.ell_sample_oracle(
            self._dr, ids_t, self.sweeper.v_t, self.sweeper.w_t,
            self.sweeper.overloaded, self.graph.bands,
            self.graph.n_pad,
        )

    def quarantine(self, reason: str) -> None:
        """Poison the warm rung: the next churn's warm walk raises
        ``_DeviceStateInvalid`` and the ladder cold-rebuilds, even if
        ``integrity_heal`` never runs."""
        self._device_valid = False
        get_registry().counter_bump("route_engine.quarantines")

    @fault_boundary
    @requires_drain("_discard_pending")
    def integrity_heal(self) -> bool:
        """Warm heal: re-derive every resident from the resident band
        tensors — the ``_device_recover`` non-shrink body without the
        loss gate: no host layout recompile, no LinkState needed. The
        packed MIRROR is deliberately left untouched: the auditor's
        re-audit digest compares the healed device product against the
        PRE-corruption settle-on-success mirror, so a heal that fails
        to reproduce the exact bits is caught (and the engine stays
        quarantined for the ladder's true cold rebuild). Band-tensor
        corruption is therefore outside this heal's reach by design —
        the re-audit fails and the cold rung re-derives the bands from
        the LinkState."""
        self._discard_pending()
        dr, digests, packed = self._full_resident(self.graph)
        self._dr = dr
        self._digests_dev = digests
        self._packed_dev = packed
        self.result = rs.assemble_result(
            self.sweeper, jax.device_get(packed)
        )
        self._device_valid = True
        get_registry().counter_bump("route_engine.integrity_heals")
        return True

    def corrupt_resident(self, seed: int) -> None:
        """Deterministic ``device.corrupt_resident`` seam: flip one
        seeded bit in the resident packed product (tier-2 detects
        unconditionally — the mirror still holds the true bits) and OR
        one seeded bit into a resident DR cell (a RAISE, which tier 1
        usually catches: an uncorrupted neighbor re-derives the shorter
        true value; see kernels.py for the blind-spot analysis)."""
        rng = random.Random(seed)
        n = self.graph.n
        r = rng.randrange(n)
        c = rng.randrange(int(self._packed_dev.shape[1]))
        bit = jnp.int32(1 << rng.randrange(31))
        self._packed_dev = self._packed_dev.at[r, c].set(
            self._packed_dev[r, c] ^ bit
        )
        r2 = rng.randrange(n)
        c2 = rng.randrange(n)
        bit2 = jnp.int32(1 << rng.randrange(20))
        self._dr = self._dr.at[r2, c2].set(self._dr[r2, c2] | bit2)
        if self.plan is not None:
            # .at[].set may drop the explicit placement: re-pin so the
            # next churn dispatch sees the planned sharding
            self._packed_dev = self.plan.place(
                self._packed_dev, self.plan.rows
            )
            self._dr = self.plan.place(self._dr, self.plan.rows)
        get_registry().counter_bump("integrity.corruptions")

    def snapshot_resident_state(self) -> Optional[Dict[str, Any]]:
        """Warm-start material (versions + host copies of every
        resident) — sufficient for ``rehydrate_resident_state`` to
        re-land the residents bit-identically with zero solves."""
        if not self.audit_ready():
            return None
        return {
            "kind": self.audit_kind,
            "version": self.version,
            "aversion": self.aversion,
            "node_names": tuple(self.graph.node_names),
            "dr": np.array(jax.device_get(self._dr)),
            "digests": np.array(jax.device_get(self._digests_dev)),
            "packed": np.array(self._packed_host),
        }

    @requires_drain("flush")
    def rehydrate_resident_state(self, snap: Any) -> bool:
        """Re-land the residents from a snapshot taken by the SAME
        engine class at the SAME (topology, attributes, name-order)
        state; anything else returns False and the caller stays on its
        cold path."""
        if (
            not isinstance(snap, dict)
            or snap.get("kind") != self.audit_kind
            or snap.get("version") != self.version
            or snap.get("aversion") != self.aversion
            or tuple(snap.get("node_names", ()))
            != tuple(self.graph.node_names)
        ):
            return False
        self.flush()
        up = (
            self.plan.shard_rows if self.plan is not None
            else jnp.asarray
        )
        self._dr = up(snap["dr"])
        self._digests_dev = up(snap["digests"])
        self._packed_dev = up(snap["packed"])
        self.result = rs.assemble_result(
            self.sweeper, np.array(snap["packed"])
        )
        self._packed_host = np.array(snap["packed"])
        self._device_valid = True
        get_registry().counter_bump("route_engine.rehydrates")
        return True

    @fault_boundary
    @requires_drain("_discard_pending")
    def _host_fallback(self, ls) -> None:
        """Ladder rung 2: the device path is down — recompute the whole
        packed product on the host (ops.host_sweep, bit-identical to a
        cold device sweep by the replica contract) and mark the device
        residents invalid so no later warm rung reads them. Self-heals
        once the supervisor's breaker lets a cold rebuild through."""
        self._discard_pending()
        shim, packed = host_sweep.host_route_product(
            ls, self.sample_names, align=self._align
        )
        self.result = rs.assemble_result(shim, packed)
        self._device_valid = False
        # the device residents are stale relative to this host product:
        # drop the mirror so audit_ready gates the audit plane off too
        self._packed_host = None
        self.version = ls.topology_version
        self.aversion = ls.attributes_version
        self.host_fallbacks += 1
        get_registry().counter_bump("route_engine.host_fallbacks")
        return None

    def _event_diff(self, ls, affected_nodes: Set[str], graph):
        """Pure host-side event diff against the resident raw-weight
        mirrors: O(degree) per affected node, no device crossing.
        Returns ``(raw_changed, new_out, ov_flips, changed)`` — shared
        by the committed churn path and the speculative staging path
        (which must observe the SAME diff the committed dispatch
        would)."""
        # RAW weight diff of the affected nodes' out-edges (O(degree)
        # via the origin index + spf_sparse._out_edges, the same
        # collapse logic the compile uses)
        raw_changed: Dict[Tuple[int, int], Tuple[int, int]] = {}
        new_out: Dict[int, Dict[int, int]] = {}
        for nm in affected_nodes:
            u = graph.node_index[nm]
            seen = _out_edges(ls, nm, graph.node_index)
            new_out[u] = seen
            old = self._w_out.get(u, {})
            for v, wo in old.items():
                wn = seen.get(v, INF)
                if wn != wo:
                    raw_changed[(u, v)] = (wo, wn)
            for v, wn in seen.items():
                if v not in old:
                    raw_changed[(u, v)] = (INF, wn)
        # overload flips among the affected nodes (the churn contract:
        # a node whose drain state changed is in affected_nodes)
        ov_flips = {
            nm
            for nm in affected_nodes
            if nm in self._ov_host
            and ls.is_node_overloaded(nm) != self._ov_host[nm]
        }
        # DETECTION transitions: the raw diffs plus effective-weight
        # flips for edges whose usability changed with a node's drain
        # state. These are an event-local list — the raw mirrors above
        # are never polluted by them.
        changed: Dict[Tuple[int, int], Tuple[int, int]] = dict(
            raw_changed
        )
        for nm in ov_flips:
            x = graph.node_index[nm]
            draining = ls.is_node_overloaded(nm)
            # the reverse-relax mask blocks on the forward edge's DST
            # (transit there): flipping x changes the usability of
            # every edge INTO x (O(degree) via the dst index); edges
            # OUT of x are unaffected (origination is always allowed)
            for u, wo in self._w_in.get(x, {}).items():
                wn = new_out.get(u, self._w_out.get(u, {})).get(
                    x, wo
                )
                if draining:
                    changed[(u, x)] = (wo, INF)  # may break paths
                else:
                    changed[(u, x)] = (INF, wn)  # may create paths
        return raw_changed, new_out, ov_flips, changed

    def _upload_event(self, patched, changed):
        """Upload one event's edge-transition list (padded to a pow2
        bucket: one compiled shape per bucket, not per distinct churn
        size) and the patched overload mask. Padding edges are
        self-loops with INF on both sides -> never usable. Returns
        ``(ov_new, e_dev)`` committed replicated under a mesh (the
        sharded steps read them with P(None) in_specs; an unplaced
        upload would make XLA insert the broadcast on every
        dispatch)."""
        e_u = np.asarray([u for (u, _v) in changed], dtype=np.int32)
        e_v = np.asarray([v for (_u, v) in changed], dtype=np.int32)
        e_wo = np.asarray(
            [wo for (wo, _wn) in changed.values()], dtype=np.int32
        )
        e_wn = np.asarray(
            [wn for (_wo, wn) in changed.values()], dtype=np.int32
        )
        eb = 8
        while eb < len(e_u):
            eb *= 2
        pad = eb - len(e_u)
        if pad:
            e_u = np.concatenate([e_u, np.zeros(pad, np.int32)])
            e_v = np.concatenate([e_v, np.zeros(pad, np.int32)])
            e_wo = np.concatenate(
                [e_wo, np.full(pad, INF, np.int32)]
            )
            e_wn = np.concatenate(
                [e_wn, np.full(pad, INF, np.int32)]
            )
        up = self.plan.replicate if self.plan is not None \
            else jnp.asarray
        ov_new = up(patched.overloaded)
        e_dev = (up(e_u), up(e_v), up(e_wo), up(e_wn))
        return ov_new, e_dev

    @fault_boundary
    @committed_dispatch
    def _churn_device(self, ls, affected_nodes: Set[str],
                      defer_consume: bool = False):
        """Ladder rung 0 (warm): one incremental device event. Returns
        the list of affected destination NAMES (their digests/sample
        rows in self.result are refreshed in place); falls back to a
        cold rebuild (and returns None) when incrementality does not
        apply. With ``defer_consume=True`` the device state commits but
        the host apply is left in flight: the return value is a
        PendingDelta (consumed by the next churn inside its dispatch
        window, or by flush()/wait()) — self.result is stale until
        then."""
        if not self._device_valid:
            raise _DeviceStateInvalid(
                "device residents stale (host fallback active)"
            )
        graph = self.graph
        ctx = self._prepare_patch(ls, sorted(affected_nodes))
        if ctx is None or not self._refresh_sample_bands(
            ctx["patched"], affected_nodes
        ):
            self._build(ls)
            return None
        patched = ctx["patched"]

        raw_changed, new_out, ov_flips, changed = self._event_diff(
            ls, affected_nodes, graph
        )
        if not changed:
            # attribute-only event: nothing route-affecting
            self.version = ls.topology_version
            self.aversion = ls.attributes_version
            if not defer_consume:
                self.flush()
            return []
        # event classification: STRUCTURAL events (link up/down,
        # drain flips) have an INF endpoint in some transition;
        # metric churn never does. Counted apart so the frontier
        # policy's coverage is auditable (a structural event that
        # rides full-width below threshold is a regression — see
        # tests/test_frontier_parity.py).
        if any(
            wo >= INF or wn >= INF for (wo, wn) in changed.values()
        ):
            self.structural_events += 1
            get_registry().counter_bump(
                "route_engine.structural_events"
            )

        ov_new, e_dev = self._upload_event(patched, changed)
        buckets = [b for b in _ROW_BUCKETS if b >= self._k_hint]
        # pipelining witness: a pending delta means the PREVIOUS
        # window's reap is still in flight while this window's
        # dispatch submits — depth-2 double buffering
        was_pending = self._pending is not None
        # segments: per-shard IN-FLIGHT [k+1, 1+W] device arrays (ONE
        # for the single-chip engine), each leading with its own meta
        # row [affected, changed] — the bucket k bounds the PER-SHARD
        # affected count; only the meta crosses during the ladder
        segments: List = []
        counts: List[int] = []
        ch_counts: List[int] = []
        commit_state = None
        k = None
        overlapped = False
        for k in buckets:
            segments, commit_state = self._run_bucket(
                ctx, k, e_dev, ov_new
            )
            # kick every shard's 8-byte meta copy while still in the
            # SUBMIT phase: the transfers ride all devices' readback
            # lanes concurrently instead of draining one shard at a
            # time, and the window's host touches stay at two
            # (submit everything, then reap everything)
            meta_rows = [
                _seg_meta(seg) if isinstance(seg, jax.Array)
                else seg[0, :2]
                for seg in segments
            ]
            n_meta = sum(
                1 for seg in segments if isinstance(seg, jax.Array)
            )
            if n_meta:
                da.count_dispatch(n_meta)
            for mrow in meta_rows:
                da.kick_async(mrow)
            if not overlapped:
                if was_pending:
                    da.note_pipelined_dispatch(2)
                # the overlap window: the PREVIOUS event's delta is
                # consumed on host while this dispatch solves on device
                self._consume_pending(overlap=True)
                overlapped = True
            metas = [
                da.reap_read(mrow, kicked=True)
                if isinstance(mrow, jax.Array) else mrow
                for mrow in meta_rows
            ]
            counts = [int(m[0]) for m in metas]
            ch_counts = [int(m[1]) for m in metas]
            # openr-lint: disable=host-branch-in-chain -- bucket-ladder retry: climbing a rung recompiles anyway, so the overflow check stays host-side (audited)
            if max(counts) <= k:
                break
        # openr-lint: disable=host-branch-in-chain -- bucket-ladder retry: climbing a rung recompiles anyway, so the overflow check stays host-side (audited)
        if max(counts) > k:
            # beyond every bucket: keep the patched layout and let the
            # overflow policy pick frontier re-solve vs full-width
            # refresh (no host recompile on either path)
            return self._overflow_refresh(
                ls, ctx, ov_new, new_out, ov_flips, e_dev,
                defer=defer_consume,
            )
        # hint tracks the typical event size (decays toward small)
        self._k_hint = max(
            _ROW_BUCKETS[0], min(1024, 2 * max(counts))
        )

        # commit the device state NOW; the host-side result apply rides
        # the pending delta (consumed below, or deferred into the next
        # event's dispatch window)
        self._commit_device(ctx, commit_state, ov_new)
        self._commit_host_mirrors(ls, new_out, ov_flips)
        self.version = ls.topology_version
        self.aversion = ls.attributes_version
        self.incremental_events += 1
        get_registry().counter_bump("route_engine.incremental_events")
        pending = PendingDelta(self, segments, counts, ch_counts, k)
        self._pending = pending
        if defer_consume:
            return pending
        self._consume_pending(overlap=False)
        return pending.names


# -- grouped-backend engine ------------------------------------------------

from openr_tpu.ops import spf_grouped as sg  # noqa: E402


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "impl")
)
def _grouped_full_resident(
    v_t, w_t, overloaded, samp_ids, samp_v, samp_w, pos_w, meta, n,
    impl,
):
    """Grouped-backend cold build: every destination row solved through
    the gather-free block-bipartite relaxation (ops.spf_grouped), DR +
    digests staying resident. The packed layout and digest algebra are
    identical to the ELL engine's — the two backends are
    bit-comparable by canonical digest."""
    t_ids = jnp.arange(n, dtype=jnp.int32)
    dr = sg._grouped_fixed_point(
        meta, v_t, w_t, overloaded, t_ids, n, reverse=True, impl=impl
    )
    nh_count = sg._grouped_nh_counts(
        dr, meta, v_t, w_t, overloaded, t_ids
    )
    d_s, packed_mask = rs._sample_stats(
        dr, samp_ids, samp_v, samp_w, overloaded, t_ids
    )
    digests, packed = _pack_product(
        dr, nh_count, d_s, packed_mask, pos_w
    )
    return dr, digests, packed


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "mesh", "impl")
)
def _sharded_grouped_full_resident(
    v_t, w_t, overloaded, samp_ids, samp_v, samp_w, pos_w, meta, n,
    mesh, impl,
):
    nseg = len(v_t)

    def shard_fn(t_blk, *rest):
        v_r = rest[:nseg]
        w_r = rest[nseg : 2 * nseg]
        ov_r, sid_r, sv_r, sw_r, pw_r = rest[2 * nseg :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        dr = sg._grouped_fixed_point(
            meta, v_r, w_r, ov_r, t_blk, n, reverse=True, vote=vote,
            impl=impl,
        )
        nh_count = sg._grouped_nh_counts(
            dr, meta, v_r, w_r, ov_r, t_blk
        )
        d_s, packed_mask = rs._sample_stats(
            dr, sid_r, sv_r, sw_r, ov_r, t_blk
        )
        digests, packed = _pack_product(
            dr, nh_count, d_s, packed_mask, pw_r
        )
        return dr, digests, packed

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS)]
            + [P(None, None)] * nseg
            + [P(None, None, None)] * nseg
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
        ),
    )(
        jnp.arange(n, dtype=jnp.int32),
        *v_t, *w_t, overloaded, samp_ids, samp_v, samp_w, pos_w,
    )


def _patch_segments_fn(w_t, upd_g, upd_s, upd_r, upd_w):
    """Scatter per-segment weight updates into the (replicated)
    resident segment tensors — the grouped analogue of _patch_bands.
    Padding entries repeat a real update (duplicates write the same
    value)."""
    return tuple(
        w.at[g, s, r].set(v)
        for w, g, s, r, v in zip(w_t, upd_g, upd_s, upd_r, upd_w)
    )


# single-chip dispatch; mesh engines ride replicated_jit (committed
# replicated outputs — see _patch_bands)
_patch_segments = jax.jit(_patch_segments_fn)


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "k", "impl")
)
def _grouped_churn_step(
    v_t, w_t, upd_g, upd_s, upd_r, upd_w,
    dr, digests, packed_res,
    e_u, e_v, e_w_old, e_w_new,
    overloaded_new,
    samp_ids, samp_v, samp_w, pos_w,
    meta, n, k, impl,
):
    """Fused single-chip grouped churn dispatch: detection against the
    resident DR, segment-slot weight scatter, affected-row re-solve
    through the grouped relaxation — one device round trip, with the
    same delta-compacted readback as the ELL step."""
    count, local_ids, ids = _detect_rows(
        dr, e_u, e_v, e_w_old, e_w_new, k, 0
    )
    new_w = _patch_segments(w_t, upd_g, upd_s, upd_r, upd_w)
    dr, digests, packed_res, out = _resolve_and_pack(
        lambda t: sg._grouped_fixed_point(
            meta, v_t, new_w, overloaded_new, t, n, reverse=True,
            impl=impl,
        ),
        lambda rows, t: sg._grouped_nh_counts(
            rows, meta, v_t, new_w, overloaded_new, t
        ),
        overloaded_new, ids, local_ids, count,
        dr, digests, packed_res, samp_ids, samp_v, samp_w, pos_w, n, k,
    )
    return new_w, dr, digests, packed_res, out


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "k", "mesh", "impl")
)
def _sharded_grouped_churn_step(
    v_t, w_t, dr, digests, packed_res,
    e_u, e_v, e_w_old, e_w_new,
    overloaded_new,
    samp_ids, samp_v, samp_w, pos_w,
    meta, n, k, mesh, impl,
):
    """Sharded grouped churn: per-shard detection + re-solve over the
    row-sharded resident DR (segment tensors arrive ALREADY PATCHED by
    _patch_segments, mirroring the ELL sharded path), delta-compacted
    per-shard readback."""
    nseg = len(v_t)
    rows_per = n // mesh.devices.size

    def shard_fn(dr_s, dg_s, pk_s, *rest):
        v_r = rest[:nseg]
        w_r = rest[nseg : 2 * nseg]
        (e_u_r, e_v_r, e_wo_r, e_wn_r, ov_r,
         sid_r, sv_r, sw_r, pw_r) = rest[2 * nseg :]
        row_start = (
            jax.lax.axis_index(SOURCES_AXIS) * rows_per
        ).astype(jnp.int32)
        count, local_ids, ids = _detect_rows(
            dr_s, e_u_r, e_v_r, e_wo_r, e_wn_r, k, row_start
        )
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        return _resolve_and_pack(
            lambda t: sg._grouped_fixed_point(
                meta, v_r, w_r, ov_r, t, n, reverse=True, vote=vote,
                impl=impl,
            ),
            lambda rows, t: sg._grouped_nh_counts(
                rows, meta, v_r, w_r, ov_r, t
            ),
            ov_r, ids, local_ids, count, dr_s, dg_s, pk_s,
            sid_r, sv_r, sw_r, pw_r, n, k,
        )

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS, None), P(SOURCES_AXIS),
             P(SOURCES_AXIS, None)]
            + [P(None, None)] * nseg
            + [P(None, None, None)] * nseg
            + [P(None)] * 4
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS, None),
        ),
    )(
        dr, digests, packed_res, *v_t, *w_t,
        e_u, e_v, e_w_old, e_w_new, overloaded_new,
        samp_ids, samp_v, samp_w, pos_w,
    )


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "max_jumps")
)
def _grouped_frontier_probe(
    v_t, w_t, dr, e_u, e_v, e_w_old, e_w_new, cell_limit, meta, n,
    max_jumps,
):
    """Grouped frontier probe: the affected-cone expansion over the
    full resident DR and the PRE-patch segment slabs
    (sg._grouped_cone_expand) — the grouped twin of _frontier_probe,
    returning the same resident cone + 4-float meta
    [rows, cells, jumps, converged] policy row."""
    cone, rows, cells, jumps, ok = sg._grouped_cone_expand(
        dr, meta, v_t, w_t, e_u, e_v, e_w_old, e_w_new, max_jumps,
        cell_limit=cell_limit[0],
    )
    meta_row = jnp.stack(
        [rows.astype(jnp.float32), cells,
         jumps.astype(jnp.float32), ok.astype(jnp.float32)]
    )
    return cone, meta_row


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "impl")
)
def _grouped_frontier_step(
    v_t, w_t, cone, dr, overloaded, samp_ids, samp_v, samp_w, pos_w,
    meta, n, impl,
):
    """Grouped frontier re-solve: full-width WARM fixed point through
    the gather-free grouped relaxation over the PATCHED segments, cone
    cells seeded at INF, every other cell keeping its resident
    distance — the grouped twin of _frontier_step, with the identical
    extraction/packing so the product stays bit-identical to the cold
    grouped build. Residents are NOT donated (retry-ladder hazard
    rule)."""
    t_ids = jnp.arange(n, dtype=jnp.int32)
    warm0 = jnp.where(cone, INF, dr)
    dr2 = sg._grouped_fixed_point(
        meta, v_t, w_t, overloaded, t_ids, n, reverse=True, impl=impl,
        init=warm0,
    )
    nh_count = sg._grouped_nh_counts(
        dr2, meta, v_t, w_t, overloaded, t_ids
    )
    d_s, packed_mask = rs._sample_stats(
        dr2, samp_ids, samp_v, samp_w, overloaded, t_ids
    )
    digests, packed = _pack_product(
        dr2, nh_count, d_s, packed_mask, pos_w
    )
    return dr2, digests, packed


@functools.partial(
    jax.jit,
    static_argnames=("meta", "n", "n_real", "max_jumps", "impl"),
)
def _grouped_overflow_chain(
    v_t, w_old_t, w_new_t, dr, packed_res,
    e_u, e_v, e_w_old, e_w_new, cell_limit, overloaded_new,
    samp_ids, samp_v, samp_w, pos_w, meta, n, n_real, max_jumps,
    impl,
):
    """Grouped fused overflow chain: cone probe over the PRE-patch
    segment slabs, on-device frontier-vs-full seed select (the same
    collapse as _overflow_chain: full-width == frontier with an
    all-True cone), warm grouped re-solve over the PATCHED segments,
    extraction + delta compaction — one executable, meta riding the
    async lane for telemetry only. Segment shapes never change under
    grouped_patch, so this chain covers every grouped overflow."""
    cone, rows, cells, jumps, ok = sg._grouped_cone_expand(
        dr, meta, v_t, w_old_t, e_u, e_v, e_w_old, e_w_new, max_jumps,
        cell_limit=cell_limit[0],
    )
    meta_row = jnp.stack(
        [rows.astype(jnp.float32), cells,
         jumps.astype(jnp.float32), ok.astype(jnp.float32)]
    )
    use_frontier = jnp.logical_and(ok, cells <= cell_limit[0])
    eff_cone = jnp.logical_or(cone, jnp.logical_not(use_frontier))
    t_ids = jnp.arange(n, dtype=jnp.int32)
    warm0 = jnp.where(eff_cone, INF, dr)
    dr2 = sg._grouped_fixed_point(
        meta, v_t, w_new_t, overloaded_new, t_ids, n, reverse=True,
        impl=impl, init=warm0,
    )
    nh_count = sg._grouped_nh_counts(
        dr2, meta, v_t, w_new_t, overloaded_new, t_ids
    )
    d_s, packed_mask = rs._sample_stats(
        dr2, samp_ids, samp_v, samp_w, overloaded_new, t_ids
    )
    digests, packed = _pack_product(
        dr2, nh_count, d_s, packed_mask, pos_w
    )
    ch_count, comp = _compact_changed_body(packed, packed_res, n_real)
    return dr2, digests, packed, ch_count, comp, meta_row


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "max_jumps", "mesh")
)
def _sharded_grouped_frontier_probe(
    v_t, w_t, dr, e_u, e_v, e_w_old, e_w_new, cell_limit, meta, n,
    max_jumps, mesh,
):
    """Sharded grouped frontier probe: each shard expands the cone
    over its own resident DR rows with the counters and growth bit
    psum-voted (device-invariant meta, replicated), the cone staying
    row-sharded for _sharded_grouped_frontier_step — same contract as
    _sharded_frontier_probe."""
    nseg = len(v_t)

    def shard_fn(dr_s, *rest):
        v_r = rest[:nseg]
        w_r = rest[nseg : 2 * nseg]
        e_u_r, e_v_r, e_wo_r, e_wn_r, lim_r = rest[2 * nseg :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        cone, rows, cells, jumps, ok = sg._grouped_cone_expand(
            dr_s, meta, v_r, w_r, e_u_r, e_v_r, e_wo_r, e_wn_r,
            max_jumps, vote=vote, cell_limit=lim_r[0],
        )
        meta_row = jnp.stack(
            [rows.astype(jnp.float32), cells,
             jumps.astype(jnp.float32), ok.astype(jnp.float32)]
        )
        return cone, meta_row

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS, None)]
            + [P(None, None)] * nseg
            + [P(None, None, None)] * nseg
            + [P(None)] * 5
        ),
        out_specs=(P(SOURCES_AXIS, None), P(None)),
    )(dr, *v_t, *w_t, e_u, e_v, e_w_old, e_w_new, cell_limit)


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "mesh", "impl")
)
def _sharded_grouped_frontier_step(
    v_t, w_t, cone, dr, overloaded, samp_ids, samp_v, samp_w, pos_w,
    meta, n, mesh, impl,
):
    """Sharded grouped frontier re-solve over the PATCHED (replicated)
    segment tensors, each shard warm-seeding its own DR rows outside
    its cone shard; the convergence vote is the only collective."""
    nseg = len(v_t)

    def shard_fn(t_blk, cone_s, dr_s, *rest):
        v_r = rest[:nseg]
        w_r = rest[nseg : 2 * nseg]
        ov_r, sid_r, sv_r, sw_r, pw_r = rest[2 * nseg :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        warm0 = jnp.where(cone_s, INF, dr_s)
        dr2 = sg._grouped_fixed_point(
            meta, v_r, w_r, ov_r, t_blk, n, reverse=True, vote=vote,
            impl=impl, init=warm0,
        )
        nh_count = sg._grouped_nh_counts(
            dr2, meta, v_r, w_r, ov_r, t_blk
        )
        d_s, packed_mask = rs._sample_stats(
            dr2, sid_r, sv_r, sw_r, ov_r, t_blk
        )
        digests, packed = _pack_product(
            dr2, nh_count, d_s, packed_mask, pw_r
        )
        return dr2, digests, packed

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS), P(SOURCES_AXIS, None),
             P(SOURCES_AXIS, None)]
            + [P(None, None)] * nseg
            + [P(None, None, None)] * nseg
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
        ),
    )(
        jnp.arange(n, dtype=jnp.int32), cone, dr, *v_t, *w_t,
        overloaded, samp_ids, samp_v, samp_w, pos_w,
    )


@functools.partial(
    jax.jit,
    static_argnames=("meta", "n", "n_real", "max_jumps", "mesh",
                     "impl"),
)
def _sharded_grouped_overflow_chain(
    v_t, w_old_t, w_new_t, dr, packed_res,
    e_u, e_v, e_w_old, e_w_new, cell_limit, overloaded_new,
    samp_ids, samp_v, samp_w, pos_w, meta, n, n_real, max_jumps,
    mesh, impl,
):
    """Sharded grouped fused overflow chain — the grouped twin of
    _sharded_overflow_chain: psum-voted per-shard probe (policy inputs
    device-invariant, every shard takes the same seed select), warm
    grouped re-solve over the patched replicated segments, per-shard
    extraction, delta compaction after the shard_map in the same
    executable."""
    nseg = len(v_t)

    def shard_fn(t_blk, dr_s, *rest):
        v_r = rest[:nseg]
        w_o = rest[nseg : 2 * nseg]
        w_n = rest[2 * nseg : 3 * nseg]
        (e_u_r, e_v_r, e_wo_r, e_wn_r, lim_r, ov_r,
         sid_r, sv_r, sw_r, pw_r) = rest[3 * nseg :]
        vote = lambda bit: jax.lax.psum(bit, SOURCES_AXIS)  # noqa: E731
        cone, rows, cells, jumps, ok = sg._grouped_cone_expand(
            dr_s, meta, v_r, w_o, e_u_r, e_v_r, e_wo_r, e_wn_r,
            max_jumps, vote=vote, cell_limit=lim_r[0],
        )
        meta_row = jnp.stack(
            [rows.astype(jnp.float32), cells,
             jumps.astype(jnp.float32), ok.astype(jnp.float32)]
        )
        use_frontier = jnp.logical_and(ok, cells <= lim_r[0])
        eff_cone = jnp.logical_or(
            cone, jnp.logical_not(use_frontier)
        )
        warm0 = jnp.where(eff_cone, INF, dr_s)
        dr2 = sg._grouped_fixed_point(
            meta, v_r, w_n, ov_r, t_blk, n, reverse=True, vote=vote,
            impl=impl, init=warm0,
        )
        nh_count = sg._grouped_nh_counts(
            dr2, meta, v_r, w_n, ov_r, t_blk
        )
        d_s, packed_mask = rs._sample_stats(
            dr2, sid_r, sv_r, sw_r, ov_r, t_blk
        )
        digests, packed = _pack_product(
            dr2, nh_count, d_s, packed_mask, pw_r
        )
        return dr2, digests, packed, meta_row

    dr2, digests, packed, meta_row = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS), P(SOURCES_AXIS, None)]
            + [P(None, None)] * nseg
            + [P(None, None, None)] * (2 * nseg)
            + [P(None)] * 6
            + [P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=(
            P(SOURCES_AXIS, None),
            P(SOURCES_AXIS),
            P(SOURCES_AXIS, None),
            P(None),
        ),
    )(
        jnp.arange(n, dtype=jnp.int32), dr,
        *v_t, *w_old_t, *w_new_t,
        e_u, e_v, e_w_old, e_w_new, cell_limit, overloaded_new,
        samp_ids, samp_v, samp_w, pos_w,
    )
    ch_count, comp = _compact_changed_body(packed, packed_res, n_real)
    return dr2, digests, packed, ch_count, comp, meta_row


class GroupedRouteSweepEngine(RouteSweepEngine):
    """The incremental engine over the GROUPED (block-bipartite)
    relaxation backend — the gather-free flagship compute path
    (ops.spf_grouped, measured 3.5x over the ELL sweep on CPU),
    now with the same resident-DR incrementality and mesh sharding
    as the ELL engine.

    Churn contract: metric changes, overload flips and edge REMOVALS
    patch segment weight slots in place (spf_grouped.grouped_patch —
    node ids untouched, resident DR valid, a removed slot stays
    restorable). A NEW adjacency breaks the signature grouping and
    cold-rebuilds: the dense segments exist precisely because rows
    share source signatures, so structure growth is a layout event
    (the ELL engine covers growth-heavy churn; digests are
    bit-comparable across the two engines)."""

    audit_kind = "grouped"

    def audit_residual(self) -> int:
        # openr-lint: disable=sharding-spec -- read-only audit probe off the churn path; bare jit stays placement-agnostic across single-chip and mesh engines (see integrity.kernels)
        return int(jax.device_get(integrity_kernels.grouped_residual(
            self._dr, self.sweeper.v_t, self.sweeper.w_t,
            self.sweeper.overloaded, self.sweeper.meta,
            sg.get_grouped_impl(),
        )))

    def _sample_oracle(self, ids_t):
        # openr-lint: disable=sharding-spec -- read-only audit probe off the churn path; bare jit stays placement-agnostic across single-chip and mesh engines (see integrity.kernels)
        return integrity_kernels.grouped_sample_oracle(
            self._dr, ids_t, self.sweeper.v_t, self.sweeper.w_t,
            self.sweeper.overloaded, self.sweeper.meta,
            self.graph.n_pad, sg.get_grouped_impl(),
        )

    def _compile_backend(self, ls):
        graph = sg.compile_out_grouped(ls, align=self._align)
        self._slots = sg.slot_table(graph)
        return graph, sg.GroupedRouteSweeper(
            graph, self.sample_names, plan=self.plan
        )

    def _make_sweeper(self, graph):
        # device-loss recovery: re-land the segment tensors from the
        # current host graph; the slot table keys on layout, which a
        # patch never changes, so self._slots stays valid
        return sg.GroupedRouteSweeper(
            graph, self.sample_names, plan=self.plan
        )

    def _full_resident(self, graph):
        impl = sg.get_grouped_impl()
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip cold
            # build (mesh is None): one device, no axis to spec
            return aot_call(
                "grouped_full_resident", _grouped_full_resident,
                (
                    self.sweeper.v_t, self.sweeper.w_t,
                    self.sweeper.overloaded,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(meta=self.sweeper.meta, n=graph.n_pad, impl=impl),
            )
        return aot_call(
            "grouped_full_resident_sharded",
            _sharded_grouped_full_resident,
            (
                self.sweeper.v_t, self.sweeper.w_t,
                self.sweeper.overloaded,
                self.sweeper._samp_ids_dev, self.sweeper._samp_v_dev,
                self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
            ),
            dict(
                meta=self.sweeper.meta, n=graph.n_pad,
                mesh=self.mesh, impl=impl,
            ),
        )

    def _refresh_sample_bands(self, patched, affected_nodes) -> bool:
        if not (affected_nodes & set(self.sample_names)):
            return True
        sweeper = self.sweeper
        rows = [
            patched.out_slots(int(sid)) for sid in sweeper.sample_ids
        ]
        samp_v, samp_w = rs.pack_sample_rows(rows, sweeper.sample_ids)
        if samp_v.shape != sweeper.samp_v.shape:
            return False
        up = (
            self.plan.replicate if self.plan is not None
            else jnp.asarray
        )
        sweeper.samp_v = self.result.samp_v = samp_v
        sweeper.samp_w = self.result.samp_w = samp_w
        sweeper._samp_v_dev = up(samp_v)
        sweeper._samp_w_dev = up(samp_w)
        return True

    def _layout_changed(self, ctx) -> bool:
        # segment shapes never change under grouped_patch (it returns
        # None on any layout break), so a ctx implies a stable layout;
        # GridBand holds ndarrays, so the ELL value-compare would
        # raise on it anyway
        return False

    def _prepare_patch(self, ls, affected_sorted):
        got = sg.grouped_patch(
            self.graph, ls, affected_sorted, self._slots
        )
        if got is None:
            return None
        patched, updates = got
        # bucketed per-segment update index/value tensors: pad each
        # touched segment's list to a pow2 with repeats of entry 0
        # (identical value — idempotent); untouched segments get a
        # 1-entry no-op rewriting slot (0,0,0) to its CURRENT value
        # (known from the patched host arrays)
        seg_ws = [s.w for b in patched.bands for s in b.segments]
        up = (
            self.plan.replicate if self.plan is not None
            else jnp.asarray
        )
        upd_g, upd_s, upd_r, upd_w = [], [], [], []
        for si, w_host in enumerate(seg_ws):
            ups = updates.get(si)
            if not ups:
                ups = [(0, 0, 0, int(w_host[0, 0, 0]))]
            eb = 1
            while eb < len(ups):
                eb *= 2
            ups = ups + [ups[0]] * (eb - len(ups))
            arr = np.asarray(ups, dtype=np.int32)
            upd_g.append(up(arr[:, 0]))
            upd_s.append(up(arr[:, 1]))
            upd_r.append(up(arr[:, 2]))
            upd_w.append(up(arr[:, 3]))
        return {
            "patched": patched,
            "upd": (tuple(upd_g), tuple(upd_s), tuple(upd_r),
                    tuple(upd_w)),
            "patched_segs": None,
        }

    @solve_window
    @committed_dispatch
    def _run_bucket(self, ctx, k, e_dev, ov_new):
        e_u_d, e_v_d, e_wo_d, e_wn_d = e_dev
        fault_point(FAULT_DISPATCH)
        fault_point(FAULT_DEVICE_LOST)
        graph = ctx["patched"]
        impl = sg.get_grouped_impl()
        upd_g, upd_s, upd_r, upd_w = ctx["upd"]
        if self.mesh is None:
            (new_w, dr, digests, packed_res,
             # openr-lint: disable=sharding-spec -- single-chip churn
             # dispatch (mesh is None): no mesh axis to spec
             packed_dev) = aot_call(
                "grouped_churn_step", _grouped_churn_step,
                (
                    self.sweeper.v_t, self.sweeper.w_t,
                    upd_g, upd_s, upd_r, upd_w,
                    self._dr, self._digests_dev, self._packed_dev,
                    e_u_d, e_v_d, e_wo_d, e_wn_d,
                    ov_new,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(
                    meta=self.sweeper.meta, n=graph.n_pad, k=k,
                    impl=impl,
                ),
            )
            # cache the fused step's on-device segment patch for an
            # overflow's _apply_patch_resident (mirrors the ELL path)
            ctx["patched_segs"] = new_w
            segments = [packed_dev]
        else:
            self._ensure_residents()
            if ctx["patched_segs"] is None:
                ctx["patched_segs"] = self._dispatch_patch(ctx)
            new_w = ctx["patched_segs"]
            (dr, digests, packed_res,
             packed_dev) = aot_call(
                "grouped_churn_step_sharded", _sharded_grouped_churn_step,
                (
                    self.sweeper.v_t, new_w,
                    self._dr, self._digests_dev, self._packed_dev,
                    e_u_d, e_v_d, e_wo_d, e_wn_d,
                    ov_new,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(
                    meta=self.sweeper.meta, n=graph.n_pad, k=k,
                    mesh=self.mesh, impl=impl,
                ),
            )
            segments = self._split_segments(packed_dev, k)
        return segments, (new_w, dr, digests, packed_res)

    @solve_window
    def _commit_device(self, ctx, commit_state, ov_new) -> None:
        new_w, dr, digests, packed_res = commit_state
        self.sweeper.w_t = new_w
        self.sweeper.overloaded = ov_new
        self._dr = dr
        self._digests_dev = digests
        self._packed_dev = packed_res
        self.graph = self.sweeper.graph = ctx["patched"]

    @solve_window
    def _apply_patch_resident(self, ctx, ov_new) -> None:
        """Grouped full-width refresh patch: scatter the event's
        segment-slot weight updates into the resident segment tensors
        (segment SHAPES never change under grouped_patch, so the
        full-width dispatch re-runs without recompiling)."""
        if ctx["patched_segs"] is None:
            ctx["patched_segs"] = self._dispatch_patch(ctx)
        self.sweeper.w_t = ctx["patched_segs"]
        self.sweeper.overloaded = ov_new
        self.graph = self.sweeper.graph = ctx["patched"]

    def _dispatch_patch(self, ctx):
        upd_g, upd_s, upd_r, upd_w = ctx["upd"]
        fn = (
            replicated_jit(_patch_segments_fn, self.mesh)
            if self.mesh is not None else _patch_segments
        )
        return fn(self.sweeper.w_t, upd_g, upd_s, upd_r, upd_w)

    @solve_window
    def _dispatch_frontier_probe(self, ctx, e_dev, limit):
        """Grouped frontier probe: the dense cone expansion over the
        [G, S, R] segment slabs (sg._grouped_cone_expand) against the
        PRE-patch resident tensors — same ordering contract as the ELL
        hook (nothing commits before _apply_patch_resident, so the
        resident w_t/_dr this reads are the pre-event ones)."""
        e_u_d, e_v_d, e_wo_d, e_wn_d = e_dev
        lim = jnp.asarray([limit], dtype=jnp.float32)
        if self.plan is not None:
            lim = self.plan.replicate(lim)
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip frontier
            # probe (mesh is None): no mesh axis to spec
            return aot_call(
                "grouped_frontier_probe", _grouped_frontier_probe,
                (
                    self.sweeper.v_t, self.sweeper.w_t, self._dr,
                    e_u_d, e_v_d, e_wo_d, e_wn_d, lim,
                ),
                dict(
                    meta=self.sweeper.meta, n=self.graph.n_pad,
                    max_jumps=_FRONTIER_MAX_JUMPS,
                ),
            )
        return aot_call(
            "grouped_frontier_probe_sharded",
            _sharded_grouped_frontier_probe,
            (
                self.sweeper.v_t, self.sweeper.w_t, self._dr,
                e_u_d, e_v_d, e_wo_d, e_wn_d, lim,
            ),
            dict(
                meta=self.sweeper.meta, n=self.graph.n_pad,
                max_jumps=_FRONTIER_MAX_JUMPS, mesh=self.mesh,
            ),
        )

    @solve_window
    def _frontier_resident(self, cone):
        """Grouped masked full-width dispatch: warm fixed point with
        only cone cells reset, over the ALREADY-PATCHED resident
        segment tensors (_apply_patch_resident ran)."""
        impl = sg.get_grouped_impl()
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip frontier
            # re-solve (mesh is None): no mesh axis to spec
            return aot_call(
                "grouped_frontier_step", _grouped_frontier_step,
                (
                    self.sweeper.v_t, self.sweeper.w_t, cone, self._dr,
                    self.sweeper.overloaded,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(
                    meta=self.sweeper.meta, n=self.graph.n_pad,
                    impl=impl,
                ),
            )
        return aot_call(
            "grouped_frontier_step_sharded",
            _sharded_grouped_frontier_step,
            (
                self.sweeper.v_t, self.sweeper.w_t, cone, self._dr,
                self.sweeper.overloaded,
                self.sweeper._samp_ids_dev, self.sweeper._samp_v_dev,
                self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
            ),
            dict(
                meta=self.sweeper.meta, n=self.graph.n_pad,
                mesh=self.mesh, impl=impl,
            ),
        )

    @solve_window
    def _dispatch_overflow_chain(self, ctx, e_dev, ov_new, limit):
        """Grouped fused overflow chain: segment SHAPES never change
        under grouped_patch, so every grouped overflow fuses — probe on
        the pre-patch slabs, on-device seed select, warm re-solve on
        the patched slabs, extraction + compaction in one dispatch."""
        if ctx["patched_segs"] is None:
            ctx["patched_segs"] = self._dispatch_patch(ctx)
        new_w = ctx["patched_segs"]
        e_u_d, e_v_d, e_wo_d, e_wn_d = e_dev
        lim = jnp.asarray([limit], dtype=jnp.float32)
        if self.plan is not None:
            lim = self.plan.replicate(lim)
        impl = sg.get_grouped_impl()
        if self.mesh is None:
            # openr-lint: disable=sharding-spec -- single-chip fused
            # overflow chain (mesh is None): no mesh axis to spec
            return aot_call(
                "grouped_overflow_chain", _grouped_overflow_chain,
                (
                    self.sweeper.v_t, self.sweeper.w_t, new_w,
                    self._dr, self._packed_dev,
                    e_u_d, e_v_d, e_wo_d, e_wn_d, lim, ov_new,
                    self.sweeper._samp_ids_dev,
                    self.sweeper._samp_v_dev,
                    self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
                ),
                dict(
                    meta=self.sweeper.meta, n=self.graph.n_pad,
                    n_real=self.graph.n, max_jumps=_FRONTIER_MAX_JUMPS,
                    impl=impl,
                ),
            )
        return aot_call(
            "grouped_overflow_chain_sharded",
            _sharded_grouped_overflow_chain,
            (
                self.sweeper.v_t, self.sweeper.w_t, new_w,
                self._dr, self._packed_dev,
                e_u_d, e_v_d, e_wo_d, e_wn_d, lim, ov_new,
                self.sweeper._samp_ids_dev, self.sweeper._samp_v_dev,
                self.sweeper._samp_w_dev, self.sweeper._pos_w_dev,
            ),
            dict(
                meta=self.sweeper.meta, n=self.graph.n_pad,
                n_real=self.graph.n, max_jumps=_FRONTIER_MAX_JUMPS,
                mesh=self.mesh, impl=impl,
            ),
        )
