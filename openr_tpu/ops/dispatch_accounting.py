"""Host-touch accounting for the committed-dispatch contract.

The committed-dispatch invariant (ROADMAP "kill the host overhead"):
one event window touches the device exactly twice — SUBMIT (every
program launch rides one stream push, back to back) and REAP (every
readback rides a ``copy_to_host_async`` staged at submit time, drained
in one read run). A host round trip anywhere between those two phases
serializes the device pipeline, which is precisely the 600x
e2e-vs-device gap BENCH_r05 measured.

This module is the ONE sanctioned crossing point. Event-path code
never calls ``jax.device_get`` / ``.block_until_ready`` directly (the
``committed-dispatch`` lint rule enforces that); it calls:

- ``count_dispatch()``   — a device program was launched,
- ``kick_async(arr)``    — stage a readback on the async lane (free:
  rides the dispatch stream, not a host touch),
- ``reap_read(arr, kicked=...)`` — materialize a readback on host.
  ``kicked=True`` means the transfer was staged earlier and the reap
  normally finds it landed (counted ``ops.async_reaps``);
  ``kicked=False`` is a genuine blocking device->host sync (counted
  ``ops.blocking_syncs``).

``event_window(tag)`` brackets one event: consecutive dispatches
collapse into one submit phase and consecutive reads into one read
phase, so ``touches = submit_phases + read_phases`` is exactly the
number of times the host turned the device around. Per-window touches
feed the ``ops.host_touches`` histogram; the counters
``ops.host_dispatches`` / ``ops.blocking_syncs`` / ``ops.async_reaps``
accumulate globally (windowed or not). Re-entrant: an inner
``event_window`` joins the active one, so a coalesced churn window
spanning N folded events still reads as ONE submit + ONE reap.

``pipeline_drain(tag)`` brackets one pipelined BURST of event windows:
window N+1's submit overlaps window N's reap, so the unit of host cost
is the drain, not the window. Every ``event_window`` opened inside a
drain joins it (same re-entrancy), which is what makes the per-drain
touch histogram honest: the reap that window N+1 drains on window N's
behalf lands in ONE shared read phase instead of being double-counted
against both windows. Per-drain touches feed ``ops.touches_per_drain``
(+ the folded window count in ``ops.windows_per_drain``); the
pipelining itself is witnessed by ``note_pipelined_dispatch`` — called
at each submit that happens while a prior window's reap is still in
flight — and ``note_overlapped_reap`` at each reap drained inside a
successor's window.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

import jax

from openr_tpu.telemetry import get_registry
from openr_tpu.telemetry.flight import get_flight_recorder
from openr_tpu.telemetry.profiler import get_profiler

_TLS = threading.local()


class EventWindow:
    """Phase accounting for one committed event window."""

    __slots__ = (
        "tag", "dispatches", "blocking_syncs", "async_reaps",
        "submit_phases", "read_phases", "_last",
        "t0", "device_ms", "stages", "windows", "drain",
    )

    def __init__(self, tag: str, drain: bool = False):
        self.tag = tag
        self.dispatches = 0
        self.blocking_syncs = 0
        self.async_reaps = 0
        self.submit_phases = 0
        self.read_phases = 0
        self._last: Optional[str] = None
        self.t0 = time.perf_counter()
        # device-time attribution (fed by attribute_stage): total
        # device ms inside this window + per-tag [calls, host, device]
        self.device_ms = 0.0
        self.stages: Dict[str, List[float]] = {}
        # logical event windows folded into this one (joins bump it);
        # drain=True marks a pipeline_drain bracket, whose retirement
        # feeds the per-drain histograms instead of only per-window
        self.windows = 1
        self.drain = drain

    def _mark(self, phase: str) -> None:
        if self._last != phase:
            if phase == "submit":
                self.submit_phases += 1
            else:
                self.read_phases += 1
            self._last = phase

    @property
    def touches(self) -> int:
        return self.submit_phases + self.read_phases


def current_window() -> Optional[EventWindow]:
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def _retire(w: EventWindow) -> None:
    """Observe a popped window and hand it to the profiling plane.
    Runs OUTSIDE the window (stack already popped): ratio bookkeeping,
    flight record, trigger checks, and any deferred post-mortem dump
    are all safe here."""
    reg = get_registry()
    reg.observe("ops.host_touches", float(w.touches))
    reg.observe(f"ops.host_touches.{w.tag}", float(w.touches))
    if w.drain:
        reg.counter_bump("ops.pipeline_drains")
        reg.observe("ops.touches_per_drain", float(w.touches))
        reg.observe("ops.windows_per_drain", float(w.windows))
    wall_ms = (time.perf_counter() - w.t0) * 1000.0
    get_profiler().on_window(w.tag, wall_ms, w.device_ms)
    get_flight_recorder().on_window(w.tag, wall_ms, w)


@contextmanager
def event_window(tag: str = "event") -> Iterator[EventWindow]:
    """Bracket one committed event. Joins an already-active window
    (same thread) instead of nesting, so the OUTERMOST caller owns the
    per-event touch observation."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    if stack:
        stack[-1].windows += 1
        yield stack[-1]
        return
    w = EventWindow(tag)
    stack.append(w)
    try:
        yield w
    finally:
        stack.pop()
        _retire(w)


@contextmanager
def pipeline_drain(tag: str = "drain") -> Iterator[EventWindow]:
    """Bracket one pipelined burst of event windows. The drain opens a
    drain-flagged window on the same stack, so every ``event_window``
    inside it joins (the burst's overlapped submits and reaps merge
    into shared phases — no double-counting the reap window N+1 drains
    for window N). Retirement feeds ``ops.touches_per_drain`` and
    ``ops.windows_per_drain`` on top of the per-window histograms.
    Joining an already-active window degrades to that window (the
    outermost bracket owns the observation)."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    if stack:
        yield stack[-1]
        return
    w = EventWindow(tag, drain=True)
    w.windows = 0  # only joined event windows count toward the burst
    stack.append(w)
    try:
        yield w
    finally:
        stack.pop()
        _retire(w)


def note_window(n: int = 1) -> None:
    """Count ``n`` logical event windows folded into the active window
    or drain WITHOUT opening a join — for burst bodies that stage their
    windows inline (one submit run, one settle run) rather than through
    nested ``event_window`` brackets. No-op outside a window."""
    w = current_window()
    if w is not None:
        w.windows += n


def note_pipelined_dispatch(depth: int = 2) -> None:
    """Witness that a window's committed dispatch was submitted while
    a prior window's reap was still in flight (the acceptance-criterion
    signal for pipeline depth >= 2). ``depth`` is the number of windows
    concurrently in flight after this submit."""
    reg = get_registry()
    reg.counter_bump("ops.pipelined_dispatches")
    reg.observe("ops.pipeline_depth", float(depth))


def note_overlapped_reap() -> None:
    """Witness that a prior window's staged reap was drained inside a
    successor window's submit/solve span (the double-buffer overlap)."""
    get_registry().counter_bump("ops.overlapped_reaps")


def attribute_stage(tag: str, host_ms: float, device_ms: float) -> None:
    """Fold one profiled dispatch into the active window's device-time
    attribution (no-op outside a window). Called by the aot_cache for
    every timed call; keeps ``touches``-style accounting untouched."""
    w = current_window()
    if w is None:
        return
    w.device_ms += device_ms
    s = w.stages.get(tag)
    if s is None:
        w.stages[tag] = [1, host_ms, device_ms]
    else:
        s[0] += 1
        s[1] += host_ms
        s[2] += device_ms


def count_dispatch(n: int = 1) -> None:
    """Record n device program launches (one submit phase while
    consecutive)."""
    get_registry().counter_bump("ops.host_dispatches", n)
    w = current_window()
    if w is not None:
        w.dispatches += n
        w._mark("submit")


def kick_async(arr) -> None:
    """Stage a device->host transfer on the async readback lane.
    Not a host touch: the copy rides the device stream and lands while
    the host does other work. Host shim arrays pass through."""
    try:
        arr.copy_to_host_async()
    except AttributeError:
        pass


def reap_read(arr, kicked: bool = False):
    """Materialize one readback on host (the sanctioned
    ``jax.device_get`` crossing). ``kicked=True`` asserts the transfer
    was staged via ``kick_async`` earlier — an async reap, not a
    blocking sync."""
    reg = get_registry()
    w = current_window()
    if kicked:
        reg.counter_bump("ops.async_reaps")
        if w is not None:
            w.async_reaps += 1
    else:
        reg.counter_bump("ops.blocking_syncs")
        if w is not None:
            w.blocking_syncs += 1
    if w is not None:
        w._mark("read")
    return jax.device_get(arr)
