"""Measured per-shape kernel autotuner.

BENCH rounds keep flipping the jnp-vs-pallas min-plus winner with
shape and round (0.337 vs 1.815 ms in r03, 1.049 vs 0.131 ms in r05 on
the same leg): neither implementation dominates, so hardcoding either
leaves measured milliseconds on the table somewhere. Instead of a
global default, ``impl="auto"`` resolves to a MEASURED winner per
``(platform, kernel, shape)`` key at build time: time each candidate on
synthetic operands of the real shape (one warmup for compile, best of
``reps`` timed runs), memoize the winner in process, and persist it as
JSON next to the AOT/persistent compile cache (``aot_cache.cache_dir``,
set via ``OPENR_CACHE_DIR``) so later processes skip the measurement.

Resolution happens in the PUBLIC eager wrappers (``spf.
all_pairs_distances`` et al.) before jit entry — the winner is an
ordinary static ``impl`` argument by the time a trace sees it, so
"auto" never appears inside a compiled executable's key. A candidate
that raises (pallas without a TPU lowering for the shape) is
disqualified for that key, never fatal.

The measurer is injectable (``Autotuner(measure=...)``) so tests drive
deterministic winner selection without timing noise.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from openr_tpu.ops.aot_cache import cache_dir
from openr_tpu.telemetry import get_registry

_PERSIST_FILE = "autotune.json"


def _default_measure(thunk: Callable[[], None], reps: int = 3) -> float:
    """Best-of-reps wall time in ms; one untimed warmup run eats the
    compile."""
    thunk()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        thunk()
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return best


class Autotuner:
    def __init__(self, measure: Optional[Callable] = None,
                 persist: bool = True):
        self._measure = measure or _default_measure
        self._persist = persist
        self._winners: Dict[str, str] = {}
        self._loaded = False

    def _path(self) -> Optional[str]:
        d = cache_dir()
        return os.path.join(d, _PERSIST_FILE) if d else None

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        path = self._path() if self._persist else None
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                self._winners.update({
                    k: v["winner"] for k, v in data.items()
                    if isinstance(v, dict) and "winner" in v
                })
            except Exception:  # noqa: BLE001 - cache is best-effort
                pass

    def _save(self, key: str, winner: str,
              timings: Dict[str, float]) -> None:
        path = self._path() if self._persist else None
        if not path:
            return
        try:
            data = {}
            if os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
            data[key] = {"winner": winner, "ms": timings}
            with open(path, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
        except Exception:  # noqa: BLE001 - cache is best-effort
            pass

    def record(self, kernel: str, shape_key: str, winner: str,
               timings: Optional[Dict[str, float]] = None) -> None:
        """Adopt an EXTERNALLY measured winner (e.g. bench.py's oracle-
        gated probe, which times the real reconverge loop rather than a
        synthetic contraction) — memoized and persisted exactly like a
        ``pick`` result, so later processes inherit the bench's
        measurement."""
        self._load()
        platform = jax.devices()[0].platform
        key = f"{platform}:{kernel}:{shape_key}"
        self._winners[key] = winner
        self._save(key, winner, timings or {})

    def pick(self, kernel: str, shape_key: str,
             candidates: Dict[str, Callable[[], None]]) -> str:
        """Winner name for (platform, kernel, shape): memoized, then
        persisted, then measured."""
        self._load()
        platform = jax.devices()[0].platform
        key = f"{platform}:{kernel}:{shape_key}"
        got = self._winners.get(key)
        if got in candidates:
            return got
        reg = get_registry()
        timings: Dict[str, float] = {}
        for name, thunk in candidates.items():
            try:
                timings[name] = self._measure(thunk)
            except Exception:  # noqa: BLE001 - disqualified candidate
                reg.counter_bump("ops.autotune_disqualified")
        if not timings:
            winner = next(iter(candidates))
        else:
            winner = min(timings, key=timings.get)
        self._winners[key] = winner
        self._save(key, winner, timings)
        reg.counter_bump("ops.autotune_measurements")
        return winner


_TUNER = Autotuner()


def get_autotuner() -> Autotuner:
    return _TUNER


def set_autotuner(tuner: Autotuner) -> None:
    global _TUNER
    _TUNER = tuner


@functools.partial(jax.jit, static_argnames=("impl",))
def _minplus_probe(a, b, impl):
    from openr_tpu.ops.spf import _minplus

    return _minplus(a, b, impl)


def resolve_minplus(shape: Tuple[int, ...]) -> str:
    """Measured jnp-vs-pallas winner for the dense min-plus contraction
    at this [S, N] x [N, N] shape (spf's public wrappers call this when
    the impl is "auto", before jit entry)."""
    from openr_tpu.ops.spf import INF

    s = int(shape[0])
    n = int(shape[-1])

    def thunk(impl):
        a = jnp.full((s, n), INF // 2, jnp.int32)
        b = jnp.full((n, n), INF // 2, jnp.int32)

        def run():
            _minplus_probe(a, b, impl).block_until_ready()

        return run

    return _TUNER.pick(
        "minplus", f"{s}x{n}",
        {"jnp": thunk("jnp"), "pallas": thunk("pallas")},
    )


@functools.partial(jax.jit, static_argnames=("impl",))
def _grouped_probe(gath, w, impl):
    from openr_tpu.ops.spf_grouped import _contract

    return _contract(gath, w, impl)


def resolve_grouped(shape: Tuple[int, int, int, int]) -> str:
    """Measured winner for the grouped [B, G, S] x [G, S, R] block
    contraction."""
    from openr_tpu.ops.spf import INF

    b, g, s, r = (int(x) for x in shape)

    def thunk(impl):
        gath = jnp.full((b, g, s), INF // 2, jnp.int32)
        w = jnp.full((g, s, r), INF // 2, jnp.int32)

        def run():
            _grouped_probe(gath, w, impl).block_until_ready()

        return run

    return _TUNER.pick(
        "grouped_minplus", f"{b}x{g}x{s}x{r}",
        {"jnp": thunk("jnp"), "pallas": thunk("pallas"),
         "pallas_t": thunk("pallas_t")},
    )
