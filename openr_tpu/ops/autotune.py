"""Measured per-shape kernel autotuner.

BENCH rounds keep flipping the jnp-vs-pallas min-plus winner with
shape and round (0.337 vs 1.815 ms in r03, 1.049 vs 0.131 ms in r05 on
the same leg): neither implementation dominates, so hardcoding either
leaves measured milliseconds on the table somewhere. Instead of a
global default, ``impl="auto"`` resolves to a MEASURED winner per
``(platform, kernel, shape)`` key at build time: time each candidate on
synthetic operands of the real shape (one warmup for compile, best of
``reps`` timed runs), memoize the winner in process, and persist it as
JSON next to the AOT/persistent compile cache (``aot_cache.cache_dir``,
set via ``OPENR_CACHE_DIR``) so later processes skip the measurement.

Resolution happens in the PUBLIC eager wrappers (``spf.
all_pairs_distances`` et al.) before jit entry — the winner is an
ordinary static ``impl`` argument by the time a trace sees it, so
"auto" never appears inside a compiled executable's key. A candidate
that raises (pallas without a TPU lowering for the shape) is
disqualified for that key, never fatal.

The measurer is injectable (``Autotuner(measure=...)``) so tests drive
deterministic winner selection without timing noise.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from openr_tpu.ops.aot_cache import cache_dir
from openr_tpu.telemetry import get_registry

_PERSIST_FILE = "autotune.json"

# kernel family -> legal winner names. Persistence is keyed on the
# family and every loaded entry is validated against it, so a winner
# measured for one family can never be replayed onto a dispatch of
# another that shares the same (platform, shape) — e.g. a dense
# "pallas_t" minplus winner silently arming the sparse ell_relax
# dispatch, which has no such implementation. Unknown families and
# out-of-family winners are dropped on load (re-measured), never fatal.
_FAMILY_CANDIDATES = {
    "minplus": ("jnp", "pallas"),
    "grouped_minplus": ("jnp", "pallas", "pallas_t"),
    "ell_relax": ("jnp", "pallas"),
}

_SCHEMA_VERSION = 2


def _valid_entry(key: str, entry) -> Optional[Tuple[str, str]]:
    """(family, winner) when the persisted entry is adoptable, else
    None. Keys are ``platform:family:shape``; v2 entries also carry an
    explicit ``family`` field that must agree with the key (a mismatch
    means the file was hand-edited or corrupted — re-measure)."""
    if not isinstance(entry, dict):
        return None
    winner = entry.get("winner")
    parts = key.split(":")
    if len(parts) != 3 or not isinstance(winner, str):
        return None
    family = parts[1]
    if family not in _FAMILY_CANDIDATES:
        return None
    if winner not in _FAMILY_CANDIDATES[family]:
        return None
    tagged = entry.get("family")
    if tagged is not None and tagged != family:
        return None
    return family, winner


def _parse_persisted(data) -> Dict[str, Dict]:
    """Lenient reader for both schemas: v2 ``{"version": 2, "winners":
    {...}}`` and the legacy flat ``{key: {"winner": ...}}`` dict.
    Invalid/unknown entries are dropped (those keys re-measure)."""
    if not isinstance(data, dict):
        return {}
    winners = data.get("winners", data)
    if not isinstance(winners, dict):
        return {}
    out: Dict[str, Dict] = {}
    for key, entry in winners.items():
        ok = _valid_entry(key, entry)
        if ok is None:
            continue
        family, winner = ok
        out[key] = {
            "family": family,
            "winner": winner,
            "ms": entry.get("ms", {}),
        }
    return out


def _default_measure(thunk: Callable[[], None], reps: int = 3) -> float:
    """Best-of-reps wall time in ms; one untimed warmup run eats the
    compile."""
    thunk()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        thunk()
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return best


class Autotuner:
    def __init__(self, measure: Optional[Callable] = None,
                 persist: bool = True):
        self._measure = measure or _default_measure
        self._persist = persist
        self._winners: Dict[str, str] = {}
        self._loaded = False

    def _path(self) -> Optional[str]:
        d = cache_dir()
        return os.path.join(d, _PERSIST_FILE) if d else None

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        path = self._path() if self._persist else None
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                self._winners.update({
                    k: v["winner"]
                    for k, v in _parse_persisted(data).items()
                })
            except Exception:  # noqa: BLE001 - cache is best-effort
                pass

    def _save(self, key: str, winner: str,
              timings: Dict[str, float]) -> None:
        path = self._path() if self._persist else None
        if not path:
            return
        try:
            winners = {}
            if os.path.exists(path):
                with open(path) as f:
                    # legacy flat files migrate here: valid entries are
                    # rewritten under the v2 schema, invalid ones drop
                    winners = _parse_persisted(json.load(f))
            family = key.split(":")[1]
            winners[key] = {
                "family": family, "winner": winner, "ms": timings,
            }
            with open(path, "w") as f:
                json.dump(
                    {"version": _SCHEMA_VERSION, "winners": winners},
                    f, indent=1, sort_keys=True,
                )
        except Exception:  # noqa: BLE001 - cache is best-effort
            pass

    def record(self, kernel: str, shape_key: str, winner: str,
               timings: Optional[Dict[str, float]] = None) -> None:
        """Adopt an EXTERNALLY measured winner (e.g. bench.py's oracle-
        gated probe, which times the real reconverge loop rather than a
        synthetic contraction) — memoized and persisted exactly like a
        ``pick`` result, so later processes inherit the bench's
        measurement."""
        assert kernel in _FAMILY_CANDIDATES, kernel
        assert winner in _FAMILY_CANDIDATES[kernel], (kernel, winner)
        self._load()
        platform = jax.devices()[0].platform
        key = f"{platform}:{kernel}:{shape_key}"
        self._winners[key] = winner
        self._save(key, winner, timings or {})

    def pick(self, kernel: str, shape_key: str,
             candidates: Dict[str, Callable[[], None]]) -> str:
        """Winner name for (platform, kernel, shape): memoized, then
        persisted, then measured."""
        self._load()
        platform = jax.devices()[0].platform
        key = f"{platform}:{kernel}:{shape_key}"
        got = self._winners.get(key)
        if got in candidates:
            return got
        reg = get_registry()
        timings: Dict[str, float] = {}
        for name, thunk in candidates.items():
            try:
                timings[name] = self._measure(thunk)
            except Exception:  # noqa: BLE001 - disqualified candidate
                reg.counter_bump("ops.autotune_disqualified")
        if not timings:
            winner = next(iter(candidates))
        else:
            winner = min(timings, key=timings.get)
        self._winners[key] = winner
        self._save(key, winner, timings)
        reg.counter_bump("ops.autotune_measurements")
        return winner


_TUNER = Autotuner()


def get_autotuner() -> Autotuner:
    return _TUNER


def set_autotuner(tuner: Autotuner) -> None:
    global _TUNER
    _TUNER = tuner


@functools.partial(jax.jit, static_argnames=("impl",))
def _minplus_probe(a, b, impl):
    from openr_tpu.ops.spf import _minplus

    return _minplus(a, b, impl)


def resolve_minplus(shape: Tuple[int, ...]) -> str:
    """Measured jnp-vs-pallas winner for the dense min-plus contraction
    at this [S, N] x [N, N] shape (spf's public wrappers call this when
    the impl is "auto", before jit entry)."""
    from openr_tpu.ops.spf import INF

    s = int(shape[0])
    n = int(shape[-1])

    def thunk(impl):
        a = jnp.full((s, n), INF // 2, jnp.int32)
        b = jnp.full((n, n), INF // 2, jnp.int32)

        def run():
            _minplus_probe(a, b, impl).block_until_ready()

        return run

    return _TUNER.pick(
        "minplus", f"{s}x{n}",
        {"jnp": thunk("jnp"), "pallas": thunk("pallas")},
    )


@functools.partial(jax.jit, static_argnames=("impl",))
def _grouped_probe(gath, w, impl):
    from openr_tpu.ops.spf_grouped import _contract

    return _contract(gath, w, impl)


def resolve_grouped(shape: Tuple[int, int, int, int]) -> str:
    """Measured winner for the grouped [B, G, S] x [G, S, R] block
    contraction."""
    from openr_tpu.ops.spf import INF

    b, g, s, r = (int(x) for x in shape)

    def thunk(impl):
        gath = jnp.full((b, g, s), INF // 2, jnp.int32)
        w = jnp.full((g, s, r), INF // 2, jnp.int32)

        def run():
            _grouped_probe(gath, w, impl).block_until_ready()

        return run

    return _TUNER.pick(
        "grouped_minplus", f"{b}x{g}x{s}x{r}",
        {"jnp": thunk("jnp"), "pallas": thunk("pallas"),
         "pallas_t": thunk("pallas_t")},
    )


@functools.partial(jax.jit, static_argnames=("impl",))
def _ell_relax_probe(d, src, w, overloaded, impl):
    from openr_tpu.ops.spf_sparse import _uniform_relax

    return _uniform_relax(d, src, w, overloaded, impl=impl)


def resolve_ell_relax(shape: Tuple[int, int]) -> str:
    """Measured jnp-vs-pallas winner for the sliced-ELL relaxation at
    this (n_pad, k_slot) band shape. The probe runs the single-band
    uniform relax (identical algebra to the banded kernel — the slot
    class the shape key describes) on synthetic operands: a
    [TILE_S, n] distance panel against [n, k] slot tensors. The S
    extent is excluded from the key on purpose: it varies per dispatch
    (view batches, all-sources blocks, sweep batches) while the band
    geometry — which decides gather locality, the thing being measured
    — does not."""
    from openr_tpu.ops.spf import INF

    n, k = (int(x) for x in shape)

    def thunk(impl):
        d = jnp.full((8, n), INF // 2, jnp.int32)
        src = jnp.zeros((n, k), jnp.int32)
        w = jnp.full((n, k), INF // 2, jnp.int32)
        ov = jnp.zeros((n,), jnp.bool_)

        def run():
            _ell_relax_probe(d, src, w, ov, impl).block_until_ready()

        return run

    return _TUNER.pick(
        "ell_relax", f"{n}x{k}",
        {"jnp": thunk("jnp"), "pallas": thunk("pallas")},
    )
