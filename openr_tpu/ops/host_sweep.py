"""Host (NumPy) replica of the device route product — the FALLBACK rung
of the route-engine degradation ladder.

When the device path is down (dispatch, readback, and cold rebuild all
failed), the supervisor still owes the caller a route product that is
bit-identical to what the device would have produced. This module
recomputes it entirely on the host over the SAME out-direction ELL
bands (``spf_sparse.compile_ell(direction="out")``), mirroring each
device kernel exactly:

- ``route_sweep._rev_relax`` / ``_rev_fixed_point``: the int32
  min-relaxation is overflow-free by construction (``INF + INF ==
  2**31 - 2`` fits int32), both sides clamp with ``minimum(.., INF)``
  per relax, both start from the same unit init, and both apply the
  same monotone Jacobi operator until no element changes — so the
  iterate sequences, not just the limits, are identical;
- ``_nh_counts`` / ``_sample_stats``: the same equality-test algebra
  and the same little-endian uint32 bit packing;
- the digest comes from ``route_sweep.host_digest`` (already the test
  oracle) over ``canonical_pos_weights``;
- the packed [n_pad, W] layout matches ``_route_block_body`` /
  ``route_engine._pack_product`` column for column, so
  ``route_sweep.assemble_result`` consumes it unchanged.

Padding columns beyond the last band are never relaxed on the device
(``_rev_relax`` passes them through) and never relaxed here, so they
hold their init values (INF, or 0 on the diagonal of a padding
destination row) on both sides.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np

from openr_tpu.ops import route_sweep as rs
from openr_tpu.ops.spf import INF
from openr_tpu.ops.spf_sparse import EllGraph, compile_ell

__all__ = ["HostSweepShim", "host_packed_product", "host_route_product"]


def _block_fixed_point(
    graph: EllGraph, overloaded: np.ndarray, t_ids: np.ndarray
) -> np.ndarray:
    """DR rows [B, n_pad] for destination batch ``t_ids``: reversed
    Jacobi relaxation to the fixed point, element-identical to
    ``_rev_fixed_point`` (same operator, same init, same stop rule)."""
    n_pad = graph.n_pad
    b = len(t_ids)
    dr = np.full((b, n_pad), INF, dtype=np.int32)
    dr[np.arange(b), t_ids] = 0
    for _ in range(n_pad):
        nxt = dr.copy()
        pos = 0
        for band, v_b, w_b in zip(graph.bands, graph.src, graph.w):
            blocked = overloaded[v_b][None, :, :] & (
                v_b[None, :, :] != t_ids[:, None, None]
            )  # [B, rows, k]
            w_eff = np.where(blocked, INF, w_b[None, :, :])
            gathered = dr[:, v_b]  # [B, rows, k]
            relaxed = np.minimum(gathered + w_eff, INF).min(axis=2)
            nxt[:, pos : pos + band.rows] = np.minimum(
                dr[:, pos : pos + band.rows], relaxed.astype(np.int32)
            )
            pos += band.rows
        if np.array_equal(nxt, dr):
            break
        dr = nxt
    return dr


def _block_nh_counts(
    graph: EllGraph,
    overloaded: np.ndarray,
    dr: np.ndarray,
    t_ids: np.ndarray,
) -> np.ndarray:
    """Per-node ECMP slot counts [B, n_pad] (replica of _nh_counts;
    padding columns stay 0 as on device)."""
    out = np.zeros_like(dr)
    pos = 0
    for band, v_b, w_b in zip(graph.bands, graph.src, graph.w):
        blocked = overloaded[v_b][None, :, :] & (
            v_b[None, :, :] != t_ids[:, None, None]
        )
        total = np.minimum(
            dr[:, v_b] + np.where(blocked, INF, w_b[None, :, :]), INF
        )
        d_row = dr[:, pos : pos + band.rows]
        cond = (
            (total == d_row[:, :, None])
            & (d_row < INF)[:, :, None]
            & (w_b < INF)[None, :, :]
        )
        out[:, pos : pos + band.rows] = cond.sum(axis=2, dtype=np.int32)
        pos += band.rows
    return out


def _block_sample_stats(
    dr: np.ndarray,
    samp_ids: np.ndarray,
    samp_v: np.ndarray,
    samp_w: np.ndarray,
    overloaded: np.ndarray,
    t_ids: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """([B, S] int32 metrics, [B, S, K/32] uint32 packed masks) —
    replica of _sample_stats including the bit-packing order."""
    blocked = overloaded[samp_v][None, :, :] & (
        samp_v[None, :, :] != t_ids[:, None, None]
    )  # [B, S, K]
    total = np.minimum(
        dr[:, samp_v] + np.where(blocked, INF, samp_w[None, :, :]), INF
    )
    d_s = dr[:, samp_ids]  # [B, S]
    cond = (
        (total == d_s[:, :, None])
        & (d_s < INF)[:, :, None]
        & (samp_w < INF)[None, :, :]
    )
    b, s, k = cond.shape
    bits = cond.reshape(b, s, k // 32, 32).astype(np.uint32)
    weights = np.left_shift(
        np.uint32(1), np.arange(32, dtype=np.uint32)
    )
    packed = np.sum(
        bits * weights[None, None, None, :], axis=3, dtype=np.uint32
    )
    return d_s, packed


def host_packed_product(
    graph: EllGraph,
    sample_ids: np.ndarray,
    samp_v: np.ndarray,
    samp_w: np.ndarray,
    block: int = 256,
) -> np.ndarray:
    """The full [n_pad, W] packed route product, column-compatible with
    ``_route_block_body`` (digest | nh_total | sample metrics | masks).
    Destination rows are processed in blocks to bound the [B, rows, k]
    gather temporaries."""
    n_pad = graph.n_pad
    overloaded = np.asarray(graph.overloaded, dtype=bool)
    pos_w = rs.canonical_pos_weights(graph)
    s = len(sample_ids)
    kw = samp_v.shape[1] // 32
    packed = np.zeros((n_pad, 2 + s + s * kw), dtype=np.int32)
    for start in range(0, n_pad, block):
        t_ids = np.arange(
            start, min(start + block, n_pad), dtype=np.int32
        )
        dr = _block_fixed_point(graph, overloaded, t_ids)
        nh = _block_nh_counts(graph, overloaded, dr, t_ids)
        d_s, masks = _block_sample_stats(
            dr, sample_ids, samp_v, samp_w, overloaded, t_ids
        )
        packed[t_ids, 0] = rs.host_digest(dr, nh, pos_w).view(np.int32)
        packed[t_ids, 1] = nh.sum(axis=1, dtype=np.int32)
        packed[t_ids, 2 : 2 + s] = d_s
        packed[t_ids, 2 + s :] = masks.view(np.int32).reshape(
            len(t_ids), -1
        )
    return packed


@dataclass
class HostSweepShim:
    """The slice of RouteSweeper that assemble_result reads — lets the
    host product flow through the one shared assembly site."""

    graph: EllGraph
    sample_names: Tuple[str, ...]
    sample_ids: np.ndarray
    samp_v: np.ndarray
    samp_w: np.ndarray


def host_route_product(
    ls, sample_names: Sequence[str], align: int = 128, block: int = 256
) -> Tuple[HostSweepShim, np.ndarray]:
    """Compile the out-ELL from a LinkState and compute the whole
    packed product on the host. ``assemble_result(shim, packed)``
    yields a RouteSweepResult bit-identical to a cold device sweep of
    the same LinkState at the same align."""
    graph = compile_ell(ls, align=align, direction="out")
    sample_ids = np.asarray(
        [graph.node_index[nm] for nm in sample_names], dtype=np.int32
    )
    samp_v, samp_w = rs._sample_bands(graph, sample_ids)
    packed = host_packed_product(
        graph, sample_ids, samp_v, samp_w, block=block
    )
    shim = HostSweepShim(
        graph=graph,
        sample_names=tuple(sample_names),
        sample_ids=sample_ids,
        samp_v=samp_v,
        samp_w=samp_w,
    )
    return shim, packed
