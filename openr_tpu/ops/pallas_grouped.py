"""Pallas TPU kernel for the BATCHED min-plus contraction of the
grouped SPF backend (ops.spf_grouped).

Per segment the relaxation computes, for every bipartite group g:

    c[g, b, r] = min_s ( gath[g, b, s] + w[g, s, r] )

— G independent small min-plus matmuls. The jnp formulation leaves the
[B, G, S, R] broadcast to XLA's fuser; this kernel tiles it explicitly
so the (TB, TS, TR) temporary lives in VMEM and the weight panel is
revisited from VMEM across the batch, exactly the discipline of the
proven dense kernel (ops.pallas_minplus, measured 5.4x over jnp on
chip at the 1k bench shape). Tile shapes follow the same legality
rules: (sublane, lane) multiples of (8, 128), or a dim equal to the
full array extent.

Grid: (G, B/TB, R/TR, S/TS), s innermost; the output tile is revisited
across s and accumulated with minimum (INF-initialized at s == 0).

Like the dense kernel, selection is BY MEASUREMENT: the scale bench
times both impls at the segment shapes and runs the winner
(spf_grouped.set_grouped_impl); interpret mode covers CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

INF = np.int32((1 << 30) - 1)

TILE_B = 8
_SMALL = 512  # dims up to this stay un-tiled (full-extent blocks)


def _pick_tiles(s: int, r: int):
    """(S_pad, TS, R_pad, TR) satisfying Mosaic block legality."""
    if s <= _SMALL:
        s_pad, ts = s, s
    else:
        s_pad = ((s + 127) // 128) * 128
        ts = 128
    if r <= _SMALL:
        r_pad, tr = r, r
    else:
        r_pad = ((r + 127) // 128) * 128
        tr = 128
    return s_pad, ts, r_pad, tr


def _kernel(g_ref, w_ref, o_ref):
    s_idx = pl.program_id(3)
    a = g_ref[0]  # (TB, TS)
    b = w_ref[0]  # (TS, TR)
    cand = jnp.minimum(
        jnp.min(a[:, :, None] + b[None, :, :], axis=1), INF
    ).astype(jnp.int32)

    @pl.when(s_idx == 0)
    def _init():
        o_ref[0] = jnp.full_like(o_ref[0], INF)

    o_ref[0] = jnp.minimum(o_ref[0], cand)


@functools.partial(jax.jit, static_argnames=("interpret",))
def batched_minplus(
    gath: jnp.ndarray, w: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """[G, B, S] (x) [G, S, R] -> [G, B, R] over (min, +), saturating
    at INF. B must be a multiple of 8; S and R are padded here (INF
    weights keep padding inert)."""
    g, b, s = gath.shape
    g2, s2, r = w.shape
    assert g == g2 and s == s2, (gath.shape, w.shape)
    b_pad = ((b + TILE_B - 1) // TILE_B) * TILE_B
    if b_pad != b:
        gath = jnp.pad(gath, ((0, 0), (0, b_pad - b), (0, 0)))
    s_pad, ts, r_pad, tr = _pick_tiles(s, r)
    if s_pad != s:
        gath = jnp.pad(gath, ((0, 0), (0, 0), (0, s_pad - s)))
        w = jnp.pad(
            w, ((0, 0), (0, s_pad - s), (0, 0)), constant_values=INF
        )
    if r_pad != r:
        w = jnp.pad(
            w, ((0, 0), (0, 0), (0, r_pad - r)), constant_values=INF
        )
    grid = (g, b_pad // TILE_B, r_pad // tr, s_pad // ts)
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((g, b_pad, r_pad), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, TILE_B, ts), lambda gg, i, rr, ss: (gg, i, ss)
            ),
            pl.BlockSpec(
                (1, ts, tr), lambda gg, i, rr, ss: (gg, ss, rr)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, TILE_B, tr), lambda gg, i, rr, ss: (gg, i, rr)
        ),
        interpret=interpret,
    )(gath, w)
    return out[:, :b, :r]
