"""Pallas TPU kernel for the BATCHED min-plus contraction of the
grouped SPF backend (ops.spf_grouped).

Per segment the relaxation computes, for every bipartite group g:

    c[g, b, r] = min_s ( gath[g, b, s] + w[g, s, r] )

— G independent small min-plus matmuls. The jnp formulation leaves the
[B, G, S, R] broadcast to XLA's fuser; this kernel tiles it explicitly
so the broadcast temporary lives in VMEM.

Tiling is GROUP-BLOCKED, sized from on-chip measurement of the actual
fat-tree segment shapes (e.g. 10k nodes: G=624, S=4, R=12 with
B=1024): the first kernel generation iterated the grid per group with
an 8-row batch tile, which at those shapes meant ~165k grid steps of a
few hundred min-adds each — pure grid-step overhead (measured 227 ms
vs 8 ms for jnp per 1024-source block). This generation processes TG
whole groups x TB=128 batch rows per grid step, with TG chosen to
bound the VMEM broadcast temporary, collapsing the same segment to a
few hundred steps. The s dimension is chunked inside the kernel (8 at
a time) so the temporary is (TG, TB, 8, TR) regardless of S; segments
with S beyond the block cap revisit the output tile across an s grid
dimension, accumulated with minimum (INF-initialized at s == 0),
exactly the proven dense-kernel discipline (ops.pallas_minplus).

Block legality (Mosaic): every block's last-two (sublane, lane) dims
are either multiples of (8, 128) or equal to the full array extent;
leading block dims are unconstrained. Padding rows/cols are inert
(weights pad with INF; min ignores them).

Like the dense kernel, selection is BY MEASUREMENT: the scale bench
times both impls at the segment shapes and runs the winner
(spf_grouped.set_grouped_impl); interpret mode covers CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

INF = np.int32((1 << 30) - 1)

_S_CAP = 512  # s block cap; beyond this the grid revisits over s
_TEMP_BUDGET = 1 << 20  # int32 elements of per-step VMEM (blocks + temp)


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _s_plan(s: int):
    """(s_pad, TS): full-extent block up to the cap, 128-aligned
    revisit grid past it. Shared by both tile planners."""
    if s <= _S_CAP:
        s_pad = _pad_to(s, 8)
        return s_pad, s_pad
    s_pad, ts = _pad_to(s, 128), _S_CAP
    while s_pad % ts:
        ts //= 2
    return s_pad, ts


def _group_plan(g: int, per_group: int):
    """(TG, g_pad) under the shared VMEM element budget."""
    tg = max(1, _TEMP_BUDGET // per_group)
    tg = min(tg, g)
    return tg, _pad_to(g, tg)


def _per_group(tb: int, ts: int, tr: int) -> int:
    """int32 elements of per-group VMEM at one grid step (standard
    layout): the gath (TB,TS) and weight (TS,TR) input blocks, the
    output (TB,TR), and the (TB,8,TR) broadcast temporary. Count
    TILED sizes: VMEM lays the last-two dims out in (8, 128) tiles,
    so a tiny trailing dim still occupies full lanes — raw element
    counts under-estimated a TR=4 segment 32x and blew the 16 MB
    scoped-vmem limit on-chip (measured on v5e at 1008)."""
    lanes_s = _pad_to(ts, 128)
    lanes_r = _pad_to(tr, 128)
    return (
        tb * lanes_s  # gath block (tb, ts)
        + _pad_to(ts, 8) * lanes_r  # weight block (ts, tr)
        + tb * lanes_r  # output block (tb, tr)
        + tb * 8 * lanes_r  # broadcast temp (tb, 8, tr)
    )


def _per_group_t(tb: int, ts: int, tr: int) -> int:
    """Per-group VMEM elements for the TRANSPOSED layout (lanes =
    batch): b rides the lane axis, r rides sublanes."""
    lanes_b = _pad_to(tb, 128)
    return (
        _pad_to(ts, 8) * lanes_b  # gath block (ts, tb)
        + _pad_to(ts, 8) * _pad_to(tr, 128)  # weight block (ts, tr)
        + _pad_to(tr, 8) * lanes_b  # output block (tr, tb)
        + 8 * _pad_to(tr, 8) * lanes_b  # broadcast temp (8, tr, tb)
    )


def vmem_bytes(g: int, b_pad: int, s: int, r: int,
               transposed: bool = False) -> int:
    """Planned per-grid-step VMEM residency in bytes for the [B,G,S] x
    [G,S,R] contraction at this shape — TG groups times the per-group
    blocks+temporary the planner budgeted under ``_TEMP_BUDGET``. The
    planner guarantees TG * per_group <= _TEMP_BUDGET elements (4 MB)
    unless a single group alone exceeds the budget (TG floors at 1)."""
    if transposed:
        tg, _, tb, _, _, ts, _, tr = _pick_tiles_t(g, b_pad, s, r)
        return tg * _per_group_t(tb, ts, tr) * 4
    tg, _, tb, _, _, ts, _, tr = _pick_tiles(g, b_pad, s, r)
    return tg * _per_group(tb, ts, tr) * 4


def _accumulate(o_ref, acc, s_idx):
    """INF-clamp + s-grid revisit discipline shared by both kernels:
    the output tile is INF-initialized on the first s step and
    min-accumulated on every revisit."""
    acc = jnp.minimum(acc, INF).astype(jnp.int32)

    @pl.when(s_idx == 0)
    def _init():
        o_ref[...] = jnp.full_like(o_ref[...], INF)

    o_ref[...] = jnp.minimum(o_ref[...], acc)


def _pick_tiles(g: int, b_pad: int, s: int, r: int):
    """(TG, g_pad, TB, b_pad, s_pad, TS, r_pad, TR) under Mosaic
    legality and the VMEM temp budget. Incoming b_pad is a multiple of
    8; it is re-padded to a TB multiple."""
    tb = 128 if b_pad >= 128 else b_pad
    b_ok = _pad_to(b_pad, tb)
    # lane dim: full extent is legal at any size; tiled needs 128-mult
    if r <= _S_CAP:
        r_pad, tr = r, r
    else:
        r_pad, tr = _pad_to(r, 128), 128
    # s is chunked by 8 inside the kernel -> 8-mult; block cap _S_CAP
    s_pad, ts = _s_plan(s)
    # groups per step: bound TOTAL per-step VMEM (blocks + broadcast
    # temporary; tiled sizes — see _per_group)
    tg, g_pad = _group_plan(g, _per_group(tb, ts, tr))
    return tg, g_pad, tb, b_ok, s_pad, ts, r_pad, tr


def _kernel(g_ref, w_ref, o_ref):
    s_idx = pl.program_id(3)
    a = g_ref[...]  # (TG, TB, TS)
    w = w_ref[...]  # (TG, TS, TR)
    nchunk = a.shape[2] // 8

    # static unroll: a fori_loop carrying dynamic_slice over register
    # values does not lower on Mosaic (measured on v5e: KernelType.TC
    # "Unimplemented primitive: dynamic_slice"); TS is static and
    # 8-aligned, so static slices compile — nchunk is at most
    # _S_CAP // 8 = 64 and 1-2 at the real fat-tree segment shapes
    acc = jnp.full(o_ref.shape, INF, jnp.int32)
    for i in range(nchunk):
        ac = jax.lax.slice_in_dim(a, i * 8, (i + 1) * 8, axis=2)
        wc = jax.lax.slice_in_dim(w, i * 8, (i + 1) * 8, axis=1)
        cand = jnp.min(ac[:, :, :, None] + wc[:, None, :, :], axis=2)
        acc = jnp.minimum(acc, cand)
    _accumulate(o_ref, acc, s_idx)


def _pick_tiles_t(g: int, b_pad: int, s: int, r: int):
    """Tile plan for the TRANSPOSED layout (lanes = batch): returns
    (TG, g_pad, TB, b_pad, s_pad, TS, r_pad, TR). b rides the lane
    axis (128-tiled), r rides sublanes (8-tiled) — so a small R costs
    8 sublanes instead of 128 lanes, shrinking the broadcast temp 8x
    at the real fat-tree segment shapes (R = 4..16)."""
    tb = 128 if b_pad >= 128 else b_pad
    b_ok = _pad_to(b_pad, tb)
    # r rides SUBLANES here: 8-aligned, same cap/revisit shape as s
    r_pad, tr = _s_plan(r)
    s_pad, ts = _s_plan(s)
    tg, g_pad = _group_plan(g, _per_group_t(tb, ts, tr))
    return tg, g_pad, tb, b_ok, s_pad, ts, r_pad, tr


def _kernel_t(g_ref, w_ref, o_ref):
    s_idx = pl.program_id(3)
    a = g_ref[...]  # (TG, TS, TB)
    w = w_ref[...]  # (TG, TS, TR)
    nchunk = a.shape[1] // 8

    acc = jnp.full(o_ref.shape, INF, jnp.int32)  # (TG, TR, TB)
    for i in range(nchunk):  # static unroll (see _kernel)
        ac = jax.lax.slice_in_dim(a, i * 8, (i + 1) * 8, axis=1)
        wc = jax.lax.slice_in_dim(w, i * 8, (i + 1) * 8, axis=1)
        cand = jnp.min(
            ac[:, :, None, :] + wc[:, :, :, None], axis=1
        )  # (TG, TR, TB)
        acc = jnp.minimum(acc, cand)
    _accumulate(o_ref, acc, s_idx)


@functools.partial(jax.jit, static_argnames=("interpret",))
def batched_minplus_t(
    gath_t: jnp.ndarray, w: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """[G, S, B] (x) [G, S, R] -> [G, R, B] over (min, +): the
    lane-efficient layout for small R. Padding discipline matches
    batched_minplus (weights pad INF; padded gath rows compute garbage
    the caller's slice discards)."""
    g, s, b = gath_t.shape
    g2, s2, r = w.shape
    assert g == g2 and s == s2, (gath_t.shape, w.shape)
    b_pad = _pad_to(b, 8)
    tg, g_pad, tb, b_pad, s_pad, ts, r_pad, tr = _pick_tiles_t(
        g, b_pad, s, r
    )
    gath_t = jnp.pad(
        gath_t, ((0, g_pad - g), (0, s_pad - s), (0, b_pad - b))
    )
    w = jnp.pad(
        w,
        ((0, g_pad - g), (0, s_pad - s), (0, r_pad - r)),
        constant_values=INF,
    )
    grid = (g_pad // tg, b_pad // tb, r_pad // tr, s_pad // ts)
    out = pl.pallas_call(
        _kernel_t,
        out_shape=jax.ShapeDtypeStruct((g_pad, r_pad, b_pad), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (tg, ts, tb), lambda gg, i, rr, ss: (gg, ss, i)
            ),
            pl.BlockSpec(
                (tg, ts, tr), lambda gg, i, rr, ss: (gg, ss, rr)
            ),
        ],
        out_specs=pl.BlockSpec(
            (tg, tr, tb), lambda gg, i, rr, ss: (gg, rr, i)
        ),
        interpret=interpret,
    )(gath_t, w)
    return out[:g, :r, :b]


@functools.partial(jax.jit, static_argnames=("interpret",))
def batched_minplus(
    gath: jnp.ndarray, w: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """[G, B, S] (x) [G, S, R] -> [G, B, R] over (min, +), saturating
    at INF. Inputs are padded here; INF weight padding keeps padded
    s/r/g slots inert (gath pads with 0 — its padded g/b rows compute
    garbage that the final slice discards)."""
    g, b, s = gath.shape
    g2, s2, r = w.shape
    assert g == g2 and s == s2, (gath.shape, w.shape)
    b_pad = _pad_to(b, 8)
    tg, g_pad, tb, b_pad, s_pad, ts, r_pad, tr = _pick_tiles(
        g, b_pad, s, r
    )
    gath = jnp.pad(
        gath, ((0, g_pad - g), (0, b_pad - b), (0, s_pad - s))
    )
    w = jnp.pad(
        w,
        ((0, g_pad - g), (0, s_pad - s), (0, r_pad - r)),
        constant_values=INF,
    )
    grid = (g_pad // tg, b_pad // tb, r_pad // tr, s_pad // ts)
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((g_pad, b_pad, r_pad), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (tg, tb, ts), lambda gg, i, rr, ss: (gg, i, ss)
            ),
            pl.BlockSpec(
                (tg, ts, tr), lambda gg, i, rr, ss: (gg, ss, rr)
            ),
        ],
        out_specs=pl.BlockSpec(
            (tg, tb, tr), lambda gg, i, rr, ss: (gg, i, rr)
        ),
        interpret=interpret,
    )(gath, w)
    return out[:g, :b, :r]
