"""Destination-major all-sources route sweep: route selection consumed
ON-DEVICE, so the all-sources product never crosses host<->device.

The source-major sweep (ops.spf_sparse.iter_ell_all_sources) computes
d(s, .) row blocks — but ECMP first-hop extraction for source s needs
its NEIGHBORS' rows, which live in other blocks, so the only way to
finish route selection was to read the whole [N, N] matrix back to the
host: 414 MB at 10k nodes, 40 GB at 100k — the e2e was transfer-bound
(13.5 s against 143 ms of device compute at 10k).

This module flips the major axis. Sweeping the REVERSED graph (an
out-edge ELL: row s holds (v, w(s->v)) for every forward edge s->v, see
spf_sparse.compile_ell(direction="out")) makes each block row a
destination column of the forward problem:

    DR[t, s] = d(s -> t)

and within that single row EVERY node's ECMP next-hop test is local:

    v in nh(s -> t)  iff  w(s, v) + DR[t, v] == DR[t, s]

(reference semantics: SpfSolver::getNextHopsWithMetric,
/root/reference/openr/decision/Decision.cpp:1124, consumed by
buildRouteDb, Decision.cpp:569-734). So per destination block the
device computes, with one extra relax-shaped pass:

  - per-node ECMP next-hop slot masks and counts (all N sources),
  - a position-sensitive uint32 digest of (distances, nh counts) per
    destination — the proof that route selection for EVERY source was
    computed, readable back in 4 bytes per destination,
  - full route rows (metric + packed next-hop slot mask) for a small
    set of SAMPLE nodes — enough to assemble a complete RouteDb for
    this node (and oracle-check others) on the host.

Readback per block is O(B) + O(B x samples), not O(B x N): the 10k
sweep returns ~200 KB instead of 414 MB, which is what makes e2e track
device-only time through a slow relay.

Transit/overload semantics match the forward kernels exactly, but the
reversed formulation needs no special init step: a forward path
s -> v1 -> ... -> t is blocked iff some INTERMEDIATE v_i is overloaded
(the source may originate, the destination may terminate — reference
LinkState.cpp:831-838). Relaxing DR[t, s] over edge (s -> v) prepends s
to a v ~> t path, in which v is intermediate unless v == t, so the edge
mask is simply  blocked = overloaded[v] & (v != t)  — row-dependent,
never source-dependent.

The digest doubles as a cross-kernel equivalence check: any alternative
relaxation backend (e.g. the pallas band kernel) must reproduce the
same uint32 per destination, bit-exactly.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.ops.spf import INF
from openr_tpu.ops.spf_sparse import (
    EllGraph,
    _as_device_ids,
    _ell_impl_for,
    compile_ell,
)

__all__ = [
    "RouteSweepResult",
    "RouteSweeper",
    "all_sources_route_sweep",
    "compile_out_ell",
    "host_digest",
]

_DIGEST_MULT_D = np.uint32(2654435761)  # Knuth multiplicative
_DIGEST_MULT_C = np.uint32(40503)
_DIGEST_POS_A = np.uint32(2246822519)  # xxhash prime
_DIGEST_POS_B = np.uint32(0x9E3779B9)


def compile_out_ell(ls, align: int = 128) -> EllGraph:
    """Out-edge (reversed-graph) sliced-ELL bands for the route sweep."""
    return compile_ell(ls, align=align, direction="out")


def _rev_relax(dr, bands, v_t, w_t, overloaded, t_ids, impl=None):
    """One reversed-graph relaxation [B, N] -> [B, N] with the
    row-dependent transit mask: edge (s -> v) may extend a v ~> t path
    unless v is overloaded and v != t. ``impl`` follows the shared
    sliced-ELL selector (spf_sparse._ell_impl_for): "pallas" runs the
    VMEM-tiled band kernel (ops.pallas_ell.rev_band_relax), and the
    destination-digest equivalence check in this module's contract
    gates that it is bit-identical."""
    if impl is None:
        impl = _ell_impl_for(dr.shape[1], max(b.k for b in bands))
    parts = []
    pos = 0
    if impl == "pallas":
        from openr_tpu.ops.pallas_ell import rev_band_relax

        for band, v_b, w_b in zip(bands, v_t, w_t):
            assert band.start == pos, (band, pos)
            parts.append(
                rev_band_relax(dr, v_b, w_b, t_ids, overloaded, pos)
            )
            pos += band.rows
        parts.append(dr[:, pos:])  # padding columns: unchanged
        return jnp.concatenate(parts, axis=1)
    for band, v_b, w_b in zip(bands, v_t, w_t):
        assert band.start == pos, (band, pos)
        blocked = overloaded[v_b][None, :, :] & (
            v_b[None, :, :] != t_ids[:, None, None]
        )  # [B, rows, k]
        w_eff = jnp.where(blocked, INF, w_b[None, :, :])
        gathered = dr[:, v_b]  # [B, rows, k]
        relaxed = jnp.min(jnp.minimum(gathered + w_eff, INF), axis=2)
        parts.append(
            jnp.minimum(dr[:, pos : pos + band.rows], relaxed.astype(jnp.int32))
        )
        pos += band.rows
    parts.append(dr[:, pos:])  # padding columns: unchanged
    return jnp.concatenate(parts, axis=1)


def _rev_fixed_point(bands, v_t, w_t, overloaded, t_ids, n, vote=None,
                     init=None, impl=None):
    """DR rows [B, N] for destination batch ``t_ids`` from unit init.
    ``vote`` lifts the local convergence bit to a global one (psum) for
    the sharded variant, mirroring spf_sparse._ell_fixed_point.
    ``init`` optionally warm-seeds rows with a pointwise upper bound on
    the new fixed point (e.g. the pre-patch resident rows outside the
    increase-affected cone); the unit anchor is min-ed in, and the
    int32 min-relaxation's unique fixed point keeps the result
    bit-identical to the cold solve. ``impl`` as in _rev_relax —
    resolved ONCE here so every loop iteration bakes the same
    kernel."""
    if impl is None:
        impl = _ell_impl_for(n, max(b.k for b in bands))
    b = t_ids.shape[0]
    unit = jnp.full((b, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(b), t_ids].set(0)
    d0 = unit if init is None else jnp.minimum(init, unit)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed > 0, it < n)

    def body(state):
        dr, _, it = state
        nxt = _rev_relax(dr, bands, v_t, w_t, overloaded, t_ids,
                         impl=impl)
        local = jnp.any(nxt < dr).astype(jnp.int32)
        return nxt, local if vote is None else vote(local), it + 1

    dr, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
    return dr


def _cone_expand(sel_dr, bands, v_t, w_t, e_u, e_v, e_w_old, e_w_new,
                 max_jumps, vote=None, cell_limit=None):
    """Affected-cone mask for a weight-increase delta (the frontier
    kernel). Given resident distance rows ``sel_dr`` [B, N] and the
    PRE-patch bands, mark every cell whose tight shortest path crosses
    an increased edge — exactly the cells whose distance may RISE, i.e.
    the cells a warm seed must reset. Seed: cells u where an increased
    edge (u -> v, w_old) was tight; expand by frontier jumps: cell j
    joins when any tight band slot of j (old weights, old distances)
    reaches a cone cell; a jump per `lax.while_loop` iteration until
    the cone stops growing. Tightness is tested on RAW weights (no
    overload mask): every realized tight step is raw-tight, so the
    cone only over-approximates — extra resets stay bit-identical by
    the unique-fixed-point squeeze. Cells already at INF can never
    rise and are excluded (keeps unreachable regions from chaining
    into the cone).

    Returns ``(cone [B, N] bool, rows, cells, jumps, converged)``.
    ``rows`` counts rows with a nonempty cone; ``cells`` the total
    cone population — the re-solve work measure the overflow policy
    thresholds on (a single link down puts ONE cell in nearly every
    row, so a row count saturates while the cone stays tiny).
    ``vote``/psum lifts both globally for the sharded variant, same
    contract as _rev_fixed_point. ``converged`` is False when the
    expansion was cut off by ``max_jumps`` or overflowed
    ``cell_limit`` — the cone is then an UNDER-approximation and the
    caller must fall back to a coarser reset (whole-row, or the
    full-width refresh)."""
    live = sel_dr < INF
    inc_e = (e_w_new > e_w_old) & (e_w_old < INF)
    seed_tight = (
        (sel_dr[:, e_u]
         == jnp.minimum(e_w_old[None, :] + sel_dr[:, e_v], INF))
        & inc_e[None, :]
        & live[:, e_u]
    )  # [B, E]
    cone0 = (
        jnp.zeros(sel_dr.shape, dtype=jnp.int32)
        .at[:, e_u].max(seed_tight.astype(jnp.int32))
    ) > 0

    def count(cone):
        rows = jnp.sum(jnp.any(cone, axis=1), dtype=jnp.int32)
        # float32: the population can reach B*N (1e10 at 100k nodes),
        # past int32; a policy threshold tolerates float rounding
        cells = jnp.sum(cone, dtype=jnp.float32)
        if vote is None:
            return rows, cells
        return vote(rows), vote(cells)

    def grow(cone):
        parts = []
        pos = 0
        for band, v_b, w_b in zip(bands, v_t, w_t):
            d_band = sel_dr[:, pos : pos + band.rows]  # [B, rows]
            total = jnp.minimum(sel_dr[:, v_b] + w_b[None, :, :], INF)
            tight = (
                (total == d_band[:, :, None])
                & (d_band < INF)[:, :, None]
                & (w_b < INF)[None, :, :]
            )  # [B, rows, k]
            parts.append(jnp.any(tight & cone[:, v_b], axis=2))
            pos += band.rows
        parts.append(jnp.zeros_like(cone[:, pos:]))
        return cone | jnp.concatenate(parts, axis=1)

    def cond(state):
        _, _, cells, it, grew = state
        keep = jnp.logical_and(grew > 0, it < max_jumps)
        if cell_limit is not None:
            keep = jnp.logical_and(keep, cells <= cell_limit)
        return keep

    def body(state):
        cone, _, _, it, _ = state
        nxt = grow(cone)
        grew_local = jnp.any(nxt & ~cone).astype(jnp.int32)
        grew = grew_local if vote is None else vote(grew_local)
        rows, cells = count(nxt)
        return nxt, rows, cells, it + 1, grew

    rows0, cells0 = count(cone0)
    cone, rows, cells, jumps, grew = jax.lax.while_loop(
        cond, body,
        (cone0, rows0, cells0, jnp.int32(0),
         (cells0 > 0).astype(jnp.int32)),
    )
    # rows/cells: int32 / float32; jumps int32; converged bool
    converged = grew == 0
    if cell_limit is not None:
        converged = jnp.logical_and(converged, cells <= cell_limit)
    return cone, rows, cells, jumps, converged


def _nh_counts(dr, bands, v_t, w_t, overloaded, t_ids):
    """Per-node ECMP next-hop slot counts [B, N] — route selection for
    every source, evaluated against its own destination row."""
    parts = []
    pos = 0
    for band, v_b, w_b in zip(bands, v_t, w_t):
        blocked = overloaded[v_b][None, :, :] & (
            v_b[None, :, :] != t_ids[:, None, None]
        )
        total = jnp.minimum(
            dr[:, v_b] + jnp.where(blocked, INF, w_b[None, :, :]), INF
        )  # [B, rows, k]
        d_row = dr[:, pos : pos + band.rows]  # [B, rows]
        cond = (
            (total == d_row[:, :, None])
            & (d_row < INF)[:, :, None]
            & (w_b < INF)[None, :, :]
        )
        parts.append(jnp.sum(cond, axis=2, dtype=jnp.int32))
        pos += band.rows
    parts.append(jnp.zeros_like(dr[:, pos:]))
    return jnp.concatenate(parts, axis=1)


def canonical_pos_weights(graph: EllGraph) -> np.ndarray:
    """Per-column digest weights keyed by CANONICAL (name-rank) node
    order, so two graphs over the same node set produce comparable
    digests regardless of their internal band renumbering — the digest
    is a cross-kernel/cross-layout equality witness. Padding columns
    get weight 0 (their content is layout-specific)."""
    n_pad = graph.n_pad
    order = np.argsort(np.asarray(graph.node_names))
    ranks = np.empty(len(order), dtype=np.uint32)
    ranks[order] = np.arange(len(order), dtype=np.uint32)
    pos = np.zeros(n_pad, dtype=np.uint32)
    with np.errstate(over="ignore"):
        pos[: len(ranks)] = (
            ranks * _DIGEST_MULT_C + np.uint32(1)
        ) * _DIGEST_POS_A ^ _DIGEST_POS_B
    return pos


def _digest_rows(dr, nh_count, pos_w):
    """Position-sensitive uint32 fold of (distance, nh count) per row.
    Pure int mixing — wraparound adds/multiplies are deterministic on
    every backend. ``pos_w`` carries the canonical column weights."""
    v = dr.astype(jnp.uint32) * _DIGEST_MULT_D + (
        nh_count.astype(jnp.uint32) + jnp.uint32(0x85EBCA6B)
    )
    return jnp.sum(v * pos_w[None, :], axis=1, dtype=jnp.uint32)


def host_digest(
    d_rows: np.ndarray, nh_counts: np.ndarray,
    pos_w: Optional[np.ndarray] = None,
) -> np.ndarray:
    """NumPy replica of the device digest (oracle for tests). When
    ``pos_w`` is omitted the columns are assumed to already be in
    canonical name-rank order."""
    n = d_rows.shape[1]
    with np.errstate(over="ignore"):
        if pos_w is None:
            pos_w = (
                np.arange(n, dtype=np.uint32) * _DIGEST_MULT_C
                + np.uint32(1)
            ) * _DIGEST_POS_A ^ _DIGEST_POS_B
        v = d_rows.astype(np.uint32) * _DIGEST_MULT_D + (
            nh_counts.astype(np.uint32) + np.uint32(0x85EBCA6B)
        )
        acc = np.zeros(d_rows.shape[0], dtype=np.uint32)
        for j in range(n):
            acc += v[:, j] * pos_w[j]
    return acc


def _sample_stats(dr, samp_ids, samp_v, samp_w, overloaded, t_ids):
    """Metrics + packed next-hop slot masks for the sample nodes:
    ([B, S] int32, [B, S, K/32] uint32). K is a multiple of 32."""
    blocked = overloaded[samp_v][None, :, :] & (
        samp_v[None, :, :] != t_ids[:, None, None]
    )  # [B, S, K]
    total = jnp.minimum(
        dr[:, samp_v] + jnp.where(blocked, INF, samp_w[None, :, :]), INF
    )
    d_s = dr[:, samp_ids]  # [B, S]
    cond = (
        (total == d_s[:, :, None])
        & (d_s < INF)[:, :, None]
        & (samp_w < INF)[None, :, :]
    )
    b, s, k = cond.shape
    bits = cond.reshape(b, s, k // 32, 32).astype(jnp.uint32)
    weights = jnp.left_shift(
        jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32)
    )
    packed = jnp.sum(bits * weights[None, None, None, :], axis=3,
                     dtype=jnp.uint32)
    return d_s, packed


def _route_block_body(v_t, w_t, overloaded, t_ids, samp_ids, samp_v,
                      samp_w, pos_w, bands, n, vote=None):
    """Fixed point + on-device route selection for one destination
    block, packed into a single int32 array [B, W] so the block costs
    exactly ONE device->host transfer:
      col 0                digest (uint32 bitcast)
      col 1                per-destination total ECMP next-hop count
      cols 2 .. 2+S        sample metrics
      cols 2+S ..          sample packed nh masks (uint32 bitcast)
    (decoded by _unpack_blocks — the one other place that knows this
    layout). Shared verbatim by the single-chip and sharded dispatches;
    ``vote`` lifts the convergence bit for the sharded variant."""
    dr = _rev_fixed_point(bands, v_t, w_t, overloaded, t_ids, n, vote=vote)
    nh_count = _nh_counts(dr, bands, v_t, w_t, overloaded, t_ids)
    digest = _digest_rows(dr, nh_count, pos_w)
    nh_total = jnp.sum(nh_count, axis=1, dtype=jnp.int32)
    d_s, packed_mask = _sample_stats(
        dr, samp_ids, samp_v, samp_w, overloaded, t_ids
    )
    b = t_ids.shape[0]
    return jnp.concatenate(
        [
            jax.lax.bitcast_convert_type(digest, jnp.int32)[:, None],
            nh_total[:, None],
            d_s,
            jax.lax.bitcast_convert_type(
                packed_mask, jnp.int32
            ).reshape(b, -1),
        ],
        axis=1,
    )


@functools.partial(jax.jit, static_argnames=("bands", "n"))
def _route_block(v_t, w_t, overloaded, t_ids, samp_ids, samp_v, samp_w,
                 pos_w, bands, n):
    return _route_block_body(
        v_t, w_t, overloaded, t_ids, samp_ids, samp_v, samp_w, pos_w,
        bands, n
    )


def _unpack_blocks(packed: np.ndarray, s: int, kw: int):
    """Decode the _route_block_body column layout for ``T`` packed rows:
    (digests [T] uint32, nh_totals [T] int32, metrics [T, S] int32,
    masks [T, S, kw] uint32)."""
    t = packed.shape[0]
    return (
        packed[:, 0].view(np.uint32).copy(),
        packed[:, 1].copy(),
        packed[:, 2 : 2 + s].copy(),
        packed[:, 2 + s :].view(np.uint32).reshape(t, s, kw).copy(),
    )


def assemble_result(
    sweeper, packed: np.ndarray, into: "RouteSweepResult" = None
) -> "RouteSweepResult":
    """Build a RouteSweepResult from a full [n_pad, W] packed array —
    the ONE assembly site shared by every one-dispatch sweep (the ELL
    and grouped sharded variants).

    Delta mode (``into=``): ``packed`` is a COMPACTED [m, 1+W] delta —
    each row a destination id followed by that row's fresh product —
    and the decoded fields are scattered in place into the existing
    result. O(m) host work instead of O(n_pad): this is how the
    engine's delta-compacted readbacks land without re-assembling the
    whole product (ids must be in-range; the engine filters padding
    rows before calling)."""
    s = len(sweeper.sample_ids)
    kw = sweeper.samp_v.shape[1] // 32
    if into is not None:
        ids = packed[:, 0]
        dg, nt, sm, sk = _unpack_blocks(
            np.ascontiguousarray(packed[:, 1:]), s, kw
        )
        into.digests[ids] = dg
        into.nh_totals[ids] = nt
        into.sample_metrics[ids] = sm
        into.sample_masks[ids] = sk
        return into
    dg, nt, sm, sk = _unpack_blocks(packed, s, kw)
    return RouteSweepResult(
        graph=sweeper.graph,
        sample_names=sweeper.sample_names,
        sample_ids=sweeper.sample_ids,
        samp_v=sweeper.samp_v,
        samp_w=sweeper.samp_w,
        digests=dg,
        nh_totals=nt,
        sample_metrics=sm,
        sample_masks=sk,
    )


def digests_by_name(result: "RouteSweepResult"):
    """Name-keyed canonical digests — the cross-backend comparison
    view (two layouts number nodes differently; names do not)."""
    idx = result.graph.node_index
    return {
        nm: result.digests[idx[nm]] for nm in result.graph.node_names
    }


@dataclass
class RouteSweepResult:
    """Host-side product of a full destination sweep."""

    graph: EllGraph  # out-direction ELL (its node order names the axes)
    sample_names: Tuple[str, ...]
    sample_ids: np.ndarray  # [S]
    samp_v: np.ndarray  # [S, K] out-edge dst ids (self-pad)
    samp_w: np.ndarray  # [S, K] out-edge metrics (INF pad)
    digests: np.ndarray  # [n] uint32 per-destination route digest
    nh_totals: np.ndarray  # [n] int32 sum of all sources' ECMP fanout
    sample_metrics: np.ndarray  # [n, S] d(sample -> t) for every t
    sample_masks: np.ndarray  # [n, S, K/32] uint32 packed nh slots

    def routes_from(self, sample_name: str) -> Dict[str, Tuple[int, Set[str]]]:
        """Full route table of one sample node, assembled from the
        sweep: destination name -> (metric, ECMP next-hop node names).
        Unreachable destinations are omitted; the self row is omitted
        (a node has no route to itself)."""
        s = self.sample_names.index(sample_name)
        names = self.graph.node_names
        sid = int(self.sample_ids[s])
        out: Dict[str, Tuple[int, Set[str]]] = {}
        k = self.samp_v.shape[1]
        words = self.sample_masks[:, s, :]  # [n, K/32]
        for t in range(self.graph.n):
            if t == sid:
                continue
            metric = int(self.sample_metrics[t, s])
            if metric >= INF:
                continue
            nhs: Set[str] = set()
            for slot in range(k):
                if words[t, slot // 32] >> np.uint32(slot % 32) & 1:
                    nhs.add(names[int(self.samp_v[s, slot])])
            out[names[t]] = (metric, nhs)
        return out


def pack_sample_rows(rows, sample_ids):
    """Pack per-sample (neighbor ids, metrics) rows into one [S, K]
    pair, K padded to a multiple of 32 (the nh masks pack into uint32
    words; RouteSweepResult.routes_from decodes this exact layout).
    Shared by every sweep backend so the packing contract has one
    home."""
    k_max = max(1, max(len(v) for v, _ in rows))
    k_pad = max(32, ((k_max + 31) // 32) * 32)
    s = len(rows)
    samp_v = np.zeros((s, k_pad), dtype=np.int32)
    samp_w = np.full((s, k_pad), INF, dtype=np.int32)
    for x, (v, w) in enumerate(rows):
        samp_v[x, : len(v)] = v
        samp_v[x, len(v):] = sample_ids[x]  # inert self-pad
        samp_w[x, : len(w)] = w
    return samp_v, samp_w


def _sample_bands(graph: EllGraph, sample_ids: Sequence[int]):
    """Sample nodes' out-edge rows from the ELL bands, packed."""
    from openr_tpu.ops.spf_sparse import _band_of

    rows = []
    for sid in sample_ids:
        bi, band = _band_of(graph, int(sid))
        r = int(sid) - band.start
        v_row = graph.src[bi][r]
        w_row = graph.w[bi][r]
        keep = w_row < INF
        rows.append((v_row[keep], w_row[keep]))
    return pack_sample_rows(rows, sample_ids)


class RouteSweeper:
    """Resident-band driver for the destination-major route sweep.

    Bands upload once; every block is one dispatch + ONE small
    readback. Mirrors spf_sparse.EllState's residency discipline (on
    relay-backed platforms a per-block re-upload costs a round trip)."""

    def __init__(self, graph: EllGraph, sample_names: Sequence[str],
                 plan=None):
        assert graph.direction == "out", "route sweep needs out-edge ELL"
        # every resident the sharded dispatches read is committed
        # replicated at build time (parallel.mesh.ShardingPlan) — under
        # a mesh an unplaced band tensor makes XLA insert a replication
        # copy on every churn dispatch
        up = plan.replicate if plan is not None else jnp.asarray
        self.graph = graph
        self.plan = plan
        self.v_t = tuple(up(s) for s in graph.src)
        self.w_t = tuple(up(w) for w in graph.w)
        self.overloaded = up(graph.overloaded)
        self.sample_names = tuple(sample_names)
        self.sample_ids = np.asarray(
            [graph.node_index[nm] for nm in self.sample_names],
            dtype=np.int32,
        )
        self.samp_v, self.samp_w = _sample_bands(graph, self.sample_ids)
        self._samp_ids_dev = up(self.sample_ids)
        self._samp_v_dev = up(self.samp_v)
        self._samp_w_dev = up(self.samp_w)
        self._pos_w_dev = up(canonical_pos_weights(graph))

    def solve_block(self, t_ids) -> jnp.ndarray:
        """One destination block -> packed [B, W] int32 (still on
        device; the caller reads it back or chains on it)."""
        # openr-lint: disable=sharding-spec -- single-chip block solve
        # (mesh engines dispatch _sharded_full_resident instead)
        return _route_block(
            self.v_t, self.w_t, self.overloaded,
            _as_device_ids(t_ids),
            self._samp_ids_dev, self._samp_v_dev, self._samp_w_dev,
            self._pos_w_dev,
            self.graph.bands, self.graph.n_pad,
        )

    def sweep(self, block: int = 1024) -> RouteSweepResult:
        n = self.graph.n_pad
        s = len(self.sample_ids)
        kw = self.samp_v.shape[1] // 32
        digests = np.zeros(n, dtype=np.uint32)
        nh_totals = np.zeros(n, dtype=np.int32)
        sample_metrics = np.zeros((n, s), dtype=np.int32)
        sample_masks = np.zeros((n, s, kw), dtype=np.uint32)
        # all block id vectors up front (async upload burst; uploading
        # per block would serialize a relay round trip between blocks)
        id_blocks = []
        for start in range(0, n, block):
            ids = np.arange(start, min(start + block, n), dtype=np.int32)
            if len(ids) < block:  # keep one compiled shape
                ids = np.concatenate(
                    [ids, np.full(block - len(ids), ids[-1], np.int32)]
                )
            id_blocks.append((start, jnp.asarray(ids)))
        for start, ids in id_blocks:
            packed = np.asarray(self.solve_block(ids))
            take = min(block, n - start)
            dg, nt, sm, sk = _unpack_blocks(packed[:take], s, kw)
            digests[start : start + take] = dg
            nh_totals[start : start + take] = nt
            sample_metrics[start : start + take] = sm
            sample_masks[start : start + take] = sk
        return RouteSweepResult(
            graph=self.graph,
            sample_names=self.sample_names,
            sample_ids=self.sample_ids,
            samp_v=self.samp_v,
            samp_w=self.samp_w,
            digests=digests,
            nh_totals=nh_totals,
            sample_metrics=sample_metrics,
            sample_masks=sample_masks,
        )


def all_sources_route_sweep(
    ls, sample_names: Sequence[str], block: int = 1024
) -> RouteSweepResult:
    """Convenience: compile the out-ELL from a LinkState and run the
    full destination sweep with on-device route selection."""
    graph = compile_out_ell(ls)
    return RouteSweeper(graph, sample_names).sweep(block=block)


# -- mesh-sharded variant -------------------------------------------------

from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from openr_tpu.utils.jax_compat import shard_map

from openr_tpu.ops.spf_sparse import SOURCES_AXIS  # noqa: E402


@functools.partial(jax.jit, static_argnames=("bands", "n", "mesh"))
def _sharded_route_blocks(
    v_t, w_t, overloaded, t_ids, samp_ids, samp_v, samp_w, pos_w, bands,
    n, mesh
):
    def shard_fn(t_blk, *rest):
        nb = len(v_t)
        v_r = rest[:nb]
        w_r = rest[nb : 2 * nb]
        ov_r, sid_r, sv_r, sw_r, pw_r = rest[2 * nb :]
        return _route_block_body(
            v_r, w_r, ov_r, t_blk, sid_r, sv_r, sw_r, pw_r, bands, n,
            vote=lambda bit: jax.lax.psum(bit, SOURCES_AXIS),
        )

    nb = len(v_t)
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS)]
            + [P(None, None)] * (2 * nb)
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=P(SOURCES_AXIS, None),
    )(t_ids, *v_t, *w_t, overloaded, samp_ids, samp_v, samp_w, pos_w)


def sharded_route_sweep(
    graph: EllGraph, sample_names: Sequence[str], mesh: Mesh
) -> RouteSweepResult:
    """The full destination sweep in ONE sharded dispatch: each device
    owns a block of destination rows (the same axis the single-chip
    sweep iterates), bands are replicated (O(E)), and the only
    collective is the 1-bit convergence psum — identical scaling shape
    to spf_sparse.sharded_ell_all_sources, but the result crossing the
    mesh boundary is the O(N) route product, not the O(N^2) matrix.
    The mesh size must divide n_pad."""
    sweeper = RouteSweeper(graph, sample_names)
    n = graph.n_pad
    assert n % mesh.devices.size == 0, (n, mesh.devices.size)
    packed = np.asarray(
        _sharded_route_blocks(
            sweeper.v_t, sweeper.w_t, sweeper.overloaded,
            jnp.asarray(np.arange(n, dtype=np.int32)),
            sweeper._samp_ids_dev, sweeper._samp_v_dev,
            sweeper._samp_w_dev, sweeper._pos_w_dev,
            graph.bands, n, mesh,
        )
    )
    return assemble_result(sweeper, packed)
