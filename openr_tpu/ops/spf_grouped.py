"""Block-bipartite grouped SPF kernels: gather-free relaxation on
structured fabrics.

The sliced-ELL kernels (ops.spf_sparse) spend their device time in the
per-edge gather ``d[:, src[r, k]]`` — an irregular lane-gather that TPUs
execute at a few elements per cycle, single-digit percent of the VPU
roof (the round-3 measurement: 188 ms for a 1024x100k block over 800k
edges).

This module removes the big gather. Observation: in a multi-tier
fabric, nodes overwhelmingly share in-neighbor SETS — every rack in a
pod sees the same fabric switches, every plane-k fabric switch sees the
same spines (reference fabric generator:
/root/reference/openr/decision/tests/RoutingBenchmarkUtils.h:53-58).
Nodes sharing a source set form a COMPLETE BIPARTITE BLOCK with their
common sources, and relaxation over such a block is a small dense
min-plus contraction:

    c[b, g, r] = min_s ( d[b, src[g, s]] + w[g, s, r] )

— one tiny gather per GROUP (not per node) to pull the [B, G, S] source
table, then pure broadcast-add-min, which the VPU runs at full lane
utilization. Per-edge work is identical (E x B adds); the irregular
part shrinks by the group fanout (12-6000x on fat-trees).

Compilation (host, O(E log E)): nodes are classed by degree (as in the
sliced ELL), then each class band is structured by hashing every node's
per-source-class neighbor signature:

  - one source class, equal group sizes  -> grid [G, R], one segment;
  - two source classes, both regular and their groupings form a full
    G1 x G2 product -> grid [G1, G2], two segments (the second writes
    transposed);
  - anything else -> the band degrades to singleton groups (G = rows,
    R = 1), which is exactly the ELL gather shape — unstructured graphs
    pay what they paid before, never more.

Node ids are renumbered (class, group, member) so every segment's
output is a contiguous [B, G, R] reshape — no scatter anywhere.

Both relaxation directions are provided: forward (in-edge bands,
transit mask = edge ORIGIN overloaded — LinkState.cpp:809 runSpf with
the :831-838 originate exception handled by an unmasked init relax) and
reverse (out-edge bands for the destination-major route sweep, mask =
``overloaded[v] & (v != t)`` — see ops.route_sweep).

Equality with the ELL kernels is witnessed by the canonical route-sweep
digest (route_sweep.canonical_pos_weights): same node set, same uint32
per destination, bit-exactly, regardless of either layout's internal
renumbering.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from openr_tpu.utils.jax_compat import shard_map
import numpy as np

from openr_tpu.ops.spf import INF
from openr_tpu.ops.spf_sparse import (
    _as_device_ids,
    _in_edges,
    _out_edges,
    _pad_up,
)

# Relaxation contraction backend: "jnp" leaves the broadcast+min-reduce
# to XLA's fuser; "pallas"/"pallas_t" run ops.pallas_grouped (explicit
# VMEM tiling); "auto" resolves to a MEASURED winner via ops.autotune
# (coarse: one representative block shape per platform — the grouped
# contraction's tiling is dominated by platform, not by the exact
# segment dims). Like the dense path (ops.spf minplus), the bench also
# probes all three ON REAL HARDWARE and can pin the winner explicitly.
_GROUPED_IMPL = os.environ.get("OPENR_GROUPED_IMPL", "jnp")

# representative [B, G, S, R] probe block for the "auto" measurement
_AUTO_PROBE_SHAPE = (32, 8, 8, 16)


def set_grouped_impl(impl: str) -> None:
    global _GROUPED_IMPL
    assert impl in ("jnp", "pallas", "pallas_t", "auto"), impl
    _GROUPED_IMPL = impl


def get_grouped_impl() -> str:
    if _GROUPED_IMPL != "auto":
        return _GROUPED_IMPL
    from openr_tpu.ops import autotune

    return autotune.resolve_grouped(_AUTO_PROBE_SHAPE)


def _contract(gath, w, impl):
    """c[b, g, r] = min_s gath[b, g, s] + w[g, s, r] (INF-saturating).
    The pallas path runs in interpret mode off-TPU so CPU tests cover
    the same code path."""
    if impl == "pallas":
        from openr_tpu.ops import pallas_grouped

        interpret = jax.devices()[0].platform == "cpu"
        c = pallas_grouped.batched_minplus(
            jnp.transpose(gath, (1, 0, 2)), w, interpret=interpret
        )  # [G, B, R]
        return jnp.transpose(c, (1, 0, 2))
    if impl == "pallas_t":
        from openr_tpu.ops import pallas_grouped

        interpret = jax.devices()[0].platform == "cpu"
        c = pallas_grouped.batched_minplus_t(
            jnp.transpose(gath, (1, 2, 0)), w, interpret=interpret
        )  # [G, R, B] — lanes carry the batch, sublanes carry R
        return jnp.transpose(c, (2, 0, 1))
    return jnp.min(
        jnp.minimum(gath[:, :, :, None] + w[None], INF), axis=2
    )


@dataclass(frozen=True)
class Segment:
    """One bipartite block family of a band: groups of ``R`` nodes
    sharing ``S`` sources. ``axis=1``: group index is the grid's major
    axis (contribution lands as [B, G1, G2] directly); ``axis=2``:
    group index is the minor axis (contribution transposes in)."""

    axis: int
    src: np.ndarray  # [G, S] int32 source ids (pad: self-ids, w=INF)
    w: np.ndarray  # [G, S, R] int32 edge metrics, INF padding


@dataclass(frozen=True)
class GridBand:
    start: int  # first node id of the band
    g1: int
    g2: int  # band rows = g1 * g2; id = start + a * g2 + b
    segments: Tuple[Segment, ...]


@dataclass(frozen=True)
class GroupedGraph:
    node_names: Tuple[str, ...]  # index == node id (grid-grouped order)
    node_index: Dict[str, int]
    n: int
    n_pad: int
    bands: Tuple[GridBand, ...]
    overloaded: np.ndarray  # [n_pad] bool
    direction: str  # "in" (forward relax) | "out" (reverse relax)

    def out_slots(self, node_id: int) -> Tuple[np.ndarray, np.ndarray]:
        """(neighbor ids, metrics) of this node's band row — for an
        "out" graph these are the node's forward out-edges, the slot
        list the route sweep's sample masks are defined over."""
        for band in self.bands:
            rows = band.g1 * band.g2
            if not (band.start <= node_id < band.start + rows):
                continue
            local = node_id - band.start
            a, b = divmod(local, band.g2)
            vs: List[int] = []
            ws: List[int] = []
            for seg in band.segments:
                g, r = (a, b) if seg.axis == 1 else (b, a)
                for s in range(seg.src.shape[1]):
                    if seg.w[g, s, r] < INF:
                        vs.append(int(seg.src[g, s]))
                        ws.append(int(seg.w[g, s, r]))
            return np.asarray(vs, np.int32), np.asarray(ws, np.int32)
        raise KeyError(node_id)


def _signature_groups(rows: List[str], srcs_by_class, cls):
    """Group band rows by their class-``cls`` source-set signature.
    Returns (groups: list of lists of row names, regular: bool)."""
    sig_map: Dict[Tuple[str, ...], List[str]] = {}
    for nm in rows:
        sig = tuple(sorted(srcs_by_class[nm].get(cls, {})))
        sig_map.setdefault(sig, []).append(nm)
    groups = [sorted(v) for v in sig_map.values()]
    groups.sort(key=lambda g: g[0])
    sizes = {len(g) for g in groups}
    regular = len(sizes) == 1 and () not in sig_map
    return groups, regular


def compile_grouped(
    ls, align: int = 128, direction: str = "in"
) -> GroupedGraph:
    """Structure-detecting compilation from the LinkState. O(E log E)
    host work; no dense matrix anywhere."""
    edges_of = _in_edges if direction == "in" else _out_edges
    raw_names = sorted(ls.get_adjacency_databases().keys())
    raw_index = {nm: i for i, nm in enumerate(raw_names)}
    # per node: src name -> metric (direction-appropriate)
    edges: Dict[str, Dict[str, int]] = {}
    for nm in raw_names:
        by_id = edges_of(ls, nm, raw_index)
        edges[nm] = {raw_names[i]: w for i, w in by_id.items()}
    # class = EXACT degree: finer than the ELL's pow2 classes, so that
    # fabric tiers land in distinct bands even when their degrees share
    # a pow2 bucket (a 3-tier fat-tree with degrees 2/3/6 must become
    # three bands for the signature grouping to see the structure).
    # Irregular graphs get at most O(distinct degrees) bands.
    degree = {nm: max(1, len(edges[nm])) for nm in raw_names}
    node_class = dict(degree)
    # per node: src class -> {src name: metric}
    srcs_by_class: Dict[str, Dict[int, Dict[str, int]]] = {}
    for nm in raw_names:
        per: Dict[int, Dict[str, int]] = {}
        for src, w in edges[nm].items():
            per.setdefault(node_class[src], {})[src] = w
        srcs_by_class[nm] = per

    # ---- band structuring ------------------------------------------------
    classes = sorted({node_class[nm] for nm in raw_names})
    band_plans = []  # (class_k, grid_names [G1][G2], seg plans)
    for ck in classes:
        rows = sorted(nm for nm in raw_names if node_class[nm] == ck)
        src_classes = sorted(
            {c for nm in rows for c in srcs_by_class[nm]}
        )
        plan = None
        if len(src_classes) == 1:
            groups, regular = _signature_groups(
                rows, srcs_by_class, src_classes[0]
            )
            if regular:
                grid = groups  # [G][R]
                plan = (grid, [(src_classes[0], 1)])
        elif len(src_classes) == 2:
            c1, c2 = src_classes
            gr1, reg1 = _signature_groups(rows, srcs_by_class, c1)
            gr2, reg2 = _signature_groups(rows, srcs_by_class, c2)
            if reg1 and reg2 and len(gr1) * len(gr2) == len(rows):
                # product check: every (group1, group2) cell holds
                # exactly one row
                pos1 = {nm: i for i, g in enumerate(gr1) for nm in g}
                pos2 = {nm: j for j, g in enumerate(gr2) for nm in g}
                cells = {(pos1[nm], pos2[nm]) for nm in rows}
                if len(cells) == len(rows):
                    grid = [
                        [None] * len(gr2) for _ in range(len(gr1))
                    ]
                    for nm in rows:
                        grid[pos1[nm]][pos2[nm]] = nm
                    plan = (grid, [(c1, 1), (c2, 2)])
        if plan is None:
            # unstructured: singleton groups, R=1 — the ELL shape
            grid = [[nm] for nm in rows]
            plan = (grid, None)
        band_plans.append((ck, plan))

    # ---- numbering: (class, grid-major) ---------------------------------
    names: List[str] = []
    for ck, (grid, _segs) in band_plans:
        for row in grid:
            names.extend(row)
    names_t = tuple(names)
    index = {nm: i for i, nm in enumerate(names_t)}
    n = len(names_t)
    n_pad = _pad_up(n, align)

    # ---- materialize segments -------------------------------------------
    bands: List[GridBand] = []
    start = 0
    for ck, (grid, seg_plan) in band_plans:
        g1 = len(grid)
        g2 = len(grid[0])
        segments: List[Segment] = []
        if seg_plan is None:
            # one generic segment: per-node source table, R = 1
            s_max = max(1, max(len(edges[r[0]]) for r in grid))
            src = np.zeros((g1, s_max), dtype=np.int32)
            w = np.full((g1, s_max, 1), INF, dtype=np.int32)
            for g, row in enumerate(grid):
                nm = row[0]
                src[g, :] = index[nm]  # inert self-pad
                for s, (sn, sw) in enumerate(
                    sorted(edges[nm].items())
                ):
                    src[g, s] = index[sn]
                    w[g, s, 0] = min(int(sw), int(INF) - 1)
            segments.append(Segment(axis=1, src=src, w=w))
        else:
            for cls, axis in seg_plan:
                if axis == 1:
                    groups = grid  # member r at grid[g][r]
                else:
                    groups = [
                        [grid[a][b] for a in range(g1)]
                        for b in range(g2)
                    ]
                g_count = len(groups)
                r_count = len(groups[0])
                src_names = [
                    sorted(srcs_by_class[groups[g][0]].get(cls, {}))
                    for g in range(g_count)
                ]
                s_max = max(1, max(len(s) for s in src_names))
                src = np.zeros((g_count, s_max), dtype=np.int32)
                w = np.full(
                    (g_count, s_max, r_count), INF, dtype=np.int32
                )
                for g in range(g_count):
                    base = index[groups[g][0]]
                    src[g, :] = base  # inert pad
                    for s, sn in enumerate(src_names[g]):
                        src[g, s] = index[sn]
                        for r, nm in enumerate(groups[g]):
                            w[g, s, r] = min(
                                int(srcs_by_class[nm][cls][sn]),
                                int(INF) - 1,
                            )
                segments.append(Segment(axis=axis, src=src, w=w))
        bands.append(
            GridBand(
                start=start, g1=g1, g2=g2, segments=tuple(segments)
            )
        )
        start += g1 * g2
    assert start == n, (start, n)

    overloaded = np.zeros(n_pad, dtype=bool)
    for nm in names_t:
        overloaded[index[nm]] = ls.is_node_overloaded(nm)
    return GroupedGraph(
        node_names=names_t,
        node_index=index,
        n=n,
        n_pad=n_pad,
        bands=tuple(bands),
        overloaded=overloaded,
        direction=direction,
    )


# ---- device tensors ------------------------------------------------------


@dataclass(frozen=True)
class _BandMeta:
    """Static (hashable) shape info for jit specialization."""

    start: int
    g1: int
    g2: int
    seg_axes: Tuple[int, ...]


def band_meta(graph: GroupedGraph) -> Tuple[_BandMeta, ...]:
    return tuple(
        _BandMeta(
            start=b.start,
            g1=b.g1,
            g2=b.g2,
            seg_axes=tuple(s.axis for s in b.segments),
        )
        for b in graph.bands
    )


def device_tensors(graph: GroupedGraph):
    """Flat tuples of per-segment (src, w) device arrays, in band/seg
    order — the resident state a caller uploads once."""
    srcs = []
    ws = []
    for band in graph.bands:
        for seg in band.segments:
            srcs.append(jnp.asarray(seg.src))
            ws.append(jnp.asarray(seg.w))
    return tuple(srcs), tuple(ws)


def _grouped_relax(d, meta, srcs_t, ws_t, overloaded, t_ids,
                   impl="jnp"):
    """One relaxation [B, N] -> [B, N] over the grouped bands as dense
    per-segment contractions. ``t_ids`` None => forward transit mask
    (edge origin overloaded); else the reverse row-dependent mask
    ``overloaded[v] & (v != t)``."""
    parts = []
    pos = 0
    si = 0
    for band in meta:
        assert band.start == pos, (band, pos)
        rows = band.g1 * band.g2
        acc = d[:, pos : pos + rows]
        for axis in band.seg_axes:
            src = srcs_t[si]
            w = ws_t[si]
            si += 1
            gath = d[:, src]  # [B, G, S] — the only gather, G-sized
            if t_ids is None:
                blocked = overloaded[src][None, :, :]
            else:
                blocked = overloaded[src][None, :, :] & (
                    src[None, :, :] != t_ids[:, None, None]
                )
            gath = jnp.where(blocked, INF, gath)
            c = _contract(gath, w, impl)  # [B, G, R]
            if axis == 2:
                c = jnp.transpose(c, (0, 2, 1))  # -> [B, G1, G2]
            acc = jnp.minimum(acc, c.reshape(c.shape[0], rows))
        parts.append(acc.astype(jnp.int32))
        pos += rows
    parts.append(d[:, pos:])  # padding columns
    return jnp.concatenate(parts, axis=1)


def _grouped_fixed_point(
    meta, srcs_t, ws_t, overloaded, ids, n, reverse, vote=None,
    impl="jnp", init=None,
):
    """Distance fixed point from unit init. ``reverse=False``: rows are
    SOURCES (forward all-sources; init = one unmasked relax so an
    overloaded source still originates). ``reverse=True``: rows are
    DESTINATIONS (route-sweep orientation; the per-row mask needs no
    init special case). ``init`` (reverse only) warm-seeds rows with a
    pointwise upper bound on the new fixed point — the unit anchor is
    min-ed in, and the int32 min-relaxation's unique fixed point keeps
    the result bit-identical to the cold solve (the same contract as
    route_sweep._rev_fixed_point)."""
    b = ids.shape[0]
    unit = jnp.full((b, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(b), ids].set(0)
    if reverse:
        d0 = unit if init is None else jnp.minimum(init, unit)
    else:
        assert init is None, "warm seed is a reverse-sweep contract"
        no_overload = jnp.zeros_like(overloaded)
        d0 = _grouped_relax(
            unit, meta, srcs_t, ws_t, no_overload, None, impl=impl
        )

    t_ids = ids if reverse else None

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed > 0, it < n)

    def body(state):
        d, _, it = state
        nxt = _grouped_relax(
            d, meta, srcs_t, ws_t, overloaded, t_ids, impl=impl
        )
        local = jnp.any(nxt < d).astype(jnp.int32)
        return nxt, local if vote is None else vote(local), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
    return d


@functools.partial(jax.jit, static_argnames=("meta", "n", "impl"))
def _grouped_from_sources(srcs_t, ws_t, overloaded, ids, meta, n, impl):
    return _grouped_fixed_point(
        meta, srcs_t, ws_t, overloaded, ids, n, reverse=False, impl=impl
    )


class GroupedState:
    """Caller-owned resident device tensors (upload once)."""

    def __init__(self, graph: GroupedGraph):
        self.graph = graph
        self.meta = band_meta(graph)
        self.src, self.w = device_tensors(graph)
        self.overloaded = jnp.asarray(graph.overloaded)


def grouped_distances_from_sources(
    graph: GroupedGraph, src_ids, state: Optional[GroupedState] = None
):
    """Forward distances [S, N_pad] from a batch of sources — the
    grouped mirror of spf_sparse.ell_distances_from_sources."""
    st = state if state is not None else GroupedState(graph)
    return _grouped_from_sources(
        st.src, st.w, st.overloaded,
        _as_device_ids(src_ids), st.meta, graph.n_pad, _GROUPED_IMPL,
    )


# ---- destination-major route sweep over grouped bands --------------------


def _grouped_nh_counts(dr, meta, srcs_t, ws_t, overloaded, t_ids):
    """Per-node ECMP next-hop slot counts [B, N] over the grouped
    segments — the dense mirror of route_sweep._nh_counts (same
    algebra: v is a next hop of s toward t iff
    w(s, v) + DR[t, v] == DR[t, s], v not transit-blocked)."""
    b = dr.shape[0]
    parts = []
    pos = 0
    si = 0
    for band in meta:
        rows = band.g1 * band.g2
        acc = jnp.zeros((b, rows), dtype=jnp.int32)
        d_grid = dr[:, pos : pos + rows].reshape(b, band.g1, band.g2)
        for axis in band.seg_axes:
            src = srcs_t[si]
            w = ws_t[si]
            si += 1
            d_g = d_grid if axis == 1 else jnp.transpose(
                d_grid, (0, 2, 1)
            )  # [B, G, R]
            gath = dr[:, src]  # [B, G, S]
            blocked = overloaded[src][None, :, :] & (
                src[None, :, :] != t_ids[:, None, None]
            )
            total = jnp.minimum(
                jnp.where(blocked, INF, gath)[:, :, :, None] + w[None],
                INF,
            )  # [B, G, S, R]
            cond = (
                (total == d_g[:, :, None, :])
                & (d_g < INF)[:, :, None, :]
                & (w < INF)[None]
            )
            c = jnp.sum(cond, axis=2, dtype=jnp.int32)  # [B, G, R]
            if axis == 2:
                c = jnp.transpose(c, (0, 2, 1))
            acc = acc + c.reshape(b, rows)
        parts.append(acc)
        pos += rows
    parts.append(jnp.zeros_like(dr[:, pos:]))
    return jnp.concatenate(parts, axis=1)


def _grouped_cone_expand(sel_dr, meta, srcs_t, ws_t, e_u, e_v, e_w_old,
                         e_w_new, max_jumps, vote=None, cell_limit=None):
    """Affected-cone mask for a weight-increase delta over the GROUPED
    segment slabs — the dense mirror of route_sweep._cone_expand (same
    seed, same growth semantics, same counters), walking each band's
    ``[G, S, R]`` segments instead of per-row ELL slots. Seed: cells u
    where an increased edge (u -> v, w_old) was tight (edge-list based,
    layout-independent). Grow: cell j joins when any RAW-tight segment
    slot of j (old weights, resident distances) reaches a cone cell —
    the per-segment tight test is the same [B, G, S, R] algebra as
    _grouped_nh_counts, joined against ``cone[:, src]`` and landed back
    on the band grid (axis-2 segments transpose in, exactly like
    _grouped_relax). Tightness on RAW weights over-approximates — extra
    resets stay bit-identical by the unique-fixed-point squeeze. INF
    cells can never rise and are excluded.

    Returns ``(cone [B, N] bool, rows, cells, jumps, converged)`` with
    the identical contract as the ELL kernel: ``converged`` False on a
    ``max_jumps`` cutoff or ``cell_limit`` overflow (the cone is then
    an under-approximation and the caller must fall back), and
    ``vote`` psum-lifts the counters/growth bit for sharded callers."""
    b = sel_dr.shape[0]
    live = sel_dr < INF
    inc_e = (e_w_new > e_w_old) & (e_w_old < INF)
    seed_tight = (
        (sel_dr[:, e_u]
         == jnp.minimum(e_w_old[None, :] + sel_dr[:, e_v], INF))
        & inc_e[None, :]
        & live[:, e_u]
    )  # [B, E]
    cone0 = (
        jnp.zeros(sel_dr.shape, dtype=jnp.int32)
        .at[:, e_u].max(seed_tight.astype(jnp.int32))
    ) > 0

    def count(cone):
        rows = jnp.sum(jnp.any(cone, axis=1), dtype=jnp.int32)
        cells = jnp.sum(cone, dtype=jnp.float32)
        if vote is None:
            return rows, cells
        return vote(rows), vote(cells)

    def grow(cone):
        parts = []
        pos = 0
        si = 0
        for band in meta:
            rows = band.g1 * band.g2
            joined = jnp.zeros((b, rows), dtype=bool)
            d_grid = sel_dr[:, pos : pos + rows].reshape(
                b, band.g1, band.g2
            )
            for axis in band.seg_axes:
                src = srcs_t[si]
                w = ws_t[si]
                si += 1
                d_g = d_grid if axis == 1 else jnp.transpose(
                    d_grid, (0, 2, 1)
                )  # [B, G, R]
                gath = sel_dr[:, src]  # [B, G, S]
                total = jnp.minimum(
                    gath[:, :, :, None] + w[None], INF
                )  # [B, G, S, R]
                tight = (
                    (total == d_g[:, :, None, :])
                    & (d_g < INF)[:, :, None, :]
                    & (w < INF)[None]
                )
                j = jnp.any(
                    tight & cone[:, src][:, :, :, None], axis=2
                )  # [B, G, R]
                if axis == 2:
                    j = jnp.transpose(j, (0, 2, 1))
                joined = joined | j.reshape(b, rows)
            parts.append(joined)
            pos += rows
        parts.append(jnp.zeros_like(cone[:, pos:]))
        return cone | jnp.concatenate(parts, axis=1)

    def cond(state):
        _, _, cells, it, grew = state
        keep = jnp.logical_and(grew > 0, it < max_jumps)
        if cell_limit is not None:
            keep = jnp.logical_and(keep, cells <= cell_limit)
        return keep

    def body(state):
        cone, _, _, it, _ = state
        nxt = grow(cone)
        grew_local = jnp.any(nxt & ~cone).astype(jnp.int32)
        grew = grew_local if vote is None else vote(grew_local)
        rows, cells = count(nxt)
        return nxt, rows, cells, it + 1, grew

    rows0, cells0 = count(cone0)
    cone, rows, cells, jumps, grew = jax.lax.while_loop(
        cond, body,
        (cone0, rows0, cells0, jnp.int32(0),
         (cells0 > 0).astype(jnp.int32)),
    )
    converged = grew == 0
    if cell_limit is not None:
        converged = jnp.logical_and(converged, cells <= cell_limit)
    return cone, rows, cells, jumps, converged


def _grouped_route_block_body(
    srcs_t, ws_t, overloaded, t_ids, samp_ids, samp_v, samp_w, pos_w,
    meta, n, vote=None, impl="jnp",
):
    """Grouped twin of route_sweep._route_block_body: same packed
    layout, same digest algebra — only the relaxation backend differs,
    so the canonical digest must agree bit-exactly with the ELL sweep."""
    from openr_tpu.ops import route_sweep as rs

    dr = _grouped_fixed_point(
        meta, srcs_t, ws_t, overloaded, t_ids, n, reverse=True,
        vote=vote, impl=impl,
    )
    nh_count = _grouped_nh_counts(
        dr, meta, srcs_t, ws_t, overloaded, t_ids
    )
    digest = rs._digest_rows(dr, nh_count, pos_w)
    nh_total = jnp.sum(nh_count, axis=1, dtype=jnp.int32)
    d_s, packed_mask = rs._sample_stats(
        dr, samp_ids, samp_v, samp_w, overloaded, t_ids
    )
    b = t_ids.shape[0]
    return jnp.concatenate(
        [
            jax.lax.bitcast_convert_type(digest, jnp.int32)[:, None],
            nh_total[:, None],
            d_s,
            jax.lax.bitcast_convert_type(
                packed_mask, jnp.int32
            ).reshape(b, -1),
        ],
        axis=1,
    )


@functools.partial(jax.jit, static_argnames=("meta", "n", "impl"))
def _grouped_route_block(
    srcs_t, ws_t, overloaded, t_ids, samp_ids, samp_v, samp_w, pos_w,
    meta, n, impl,
):
    return _grouped_route_block_body(
        srcs_t, ws_t, overloaded, t_ids, samp_ids, samp_v, samp_w,
        pos_w, meta, n, impl=impl,
    )


class GroupedRouteSweeper:
    """Destination-major route sweeper over the grouped (out-edge)
    graph — the gather-free backend of ops.route_sweep.RouteSweeper,
    producing the identical RouteSweepResult (canonical digests are
    bit-comparable across the two backends)."""

    def __init__(self, graph: GroupedGraph, sample_names: Sequence[str],
                 plan=None):
        from openr_tpu.ops import route_sweep as rs

        assert graph.direction == "out", "route sweep needs out-edges"
        # replicated build-time placement under a mesh, mirroring
        # RouteSweeper (see parallel.mesh.ShardingPlan)
        up = plan.replicate if plan is not None else jnp.asarray
        self.graph = graph
        self.plan = plan
        self.meta = band_meta(graph)
        self.v_t, self.w_t = (
            tuple(up(seg) for seg in t) for t in device_tensors(graph)
        )
        self.overloaded = up(graph.overloaded)
        self.sample_names = tuple(sample_names)
        self.sample_ids = np.asarray(
            [graph.node_index[nm] for nm in self.sample_names],
            dtype=np.int32,
        )
        rows = [graph.out_slots(int(sid)) for sid in self.sample_ids]
        self.samp_v, self.samp_w = rs.pack_sample_rows(
            rows, self.sample_ids
        )
        self._samp_ids_dev = up(self.sample_ids)
        self._samp_v_dev = up(self.samp_v)
        self._samp_w_dev = up(self.samp_w)
        self._pos_w_dev = up(rs.canonical_pos_weights(graph))

    def solve_block(self, t_ids):
        # openr-lint: disable=sharding-spec -- single-chip block solve
        # (mesh engines dispatch their sharded full-resident twin)
        return _grouped_route_block(
            self.v_t, self.w_t, self.overloaded,
            _as_device_ids(t_ids),
            self._samp_ids_dev, self._samp_v_dev, self._samp_w_dev,
            self._pos_w_dev, self.meta, self.graph.n_pad,
            _GROUPED_IMPL,
        )

    # the block loop and result assembly are layout-independent —
    # reuse RouteSweeper's implementation verbatim
    from openr_tpu.ops.route_sweep import RouteSweeper as _RS

    sweep = _RS.sweep
    del _RS


def compile_out_grouped(ls, align: int = 128) -> GroupedGraph:
    """Out-edge grouped graph for the destination-major route sweep."""
    return compile_grouped(ls, align=align, direction="out")


# ---- incremental weight patching -----------------------------------------


def slot_table(graph: GroupedGraph) -> Dict[int, List[Tuple]]:
    """node id -> [(segment flat index, g, s, r, src id)] for every
    REAL edge slot of the node's band row, in device_tensors order.

    Captured at compile time (a real slot has w < INF in the fresh
    layout), so later in-place removals (slot INF'd by grouped_patch)
    stay in the table and remain RESTORABLE — the inert self-pad slots
    are never in it, which is what keeps a patch from double-counting
    a pad whose id coincides with a real neighbor (nh counts sum over
    slots; a duplicated edge would corrupt the digest)."""
    out: Dict[int, List[Tuple]] = {}
    si = 0
    for band in graph.bands:
        for seg in band.segments:
            # vectorized over the dense [G, S, R] weight tensor: a
            # python triple loop here costs seconds of host time per
            # cold build at engine scale (millions of cells at 10k+)
            gg, ss, rr = np.nonzero(seg.w < INF)
            if seg.axis == 1:
                nodes = band.start + gg * band.g2 + rr
            else:
                nodes = band.start + rr * band.g2 + gg
            sids = seg.src[gg, ss]
            for x in range(len(gg)):
                out.setdefault(int(nodes[x]), []).append(
                    (si, int(gg[x]), int(ss[x]), int(rr[x]),
                     int(sids[x]))
                )
            si += 1
    return out


def grouped_patch(
    graph: GroupedGraph, ls, affected, slots: Dict[int, List[Tuple]]
):
    """In-place weight patch for churn on an existing grouped layout:
    returns (patched GroupedGraph, per-segment update lists
    {seg flat idx: [(g, s, r, new_w)]}) or None when the event breaks
    the layout's structure (unknown node, or an edge toward a neighbor
    the node's slot signature does not carry — a NEW adjacency needs a
    recompile; the signature grouping is what makes the segments
    dense).

    Metric changes and edge REMOVALS (slot set to INF — inert in every
    relaxation) always patch in place: node ids are untouched, so a
    resident DR keyed by them stays valid. A removed slot stays in the
    slot table and is restored by a later patch when the edge returns.
    The patched layout may no longer be what a fresh compile would
    produce (a removal changes the node's degree class) — stale as a
    CANONICAL layout, but exact as a relaxation structure."""
    edges_of = _in_edges if graph.direction == "in" else _out_edges
    names = tuple(sorted(ls.get_adjacency_databases().keys()))
    if len(names) != graph.n or any(
        nm not in graph.node_index for nm in names
    ):
        # node set changed — including a same-count SWAP (one node
        # out, another in), which a bare length check would miss and
        # silently serve routes for a topology that no longer exists
        return None
    updates: Dict[int, List[Tuple[int, int, int, int]]] = {}
    overloaded = graph.overloaded.copy()
    for nm in affected:
        i = graph.node_index.get(nm)
        if i is None:
            return None
        new_edges = edges_of(ls, nm, graph.node_index)
        my_slots = slots.get(i, [])
        slot_srcs = {sid for (_si, _g, _s, _r, sid) in my_slots}
        if set(new_edges) - slot_srcs:
            return None  # new neighbor: structure change
        for (si, g, s, r, sid) in my_slots:
            # real metrics arrive capped at INF-1 by edges_of (the
            # same cap compile_grouped applies); INF is exclusively
            # the removed/pad sentinel. The ONE update list feeds both
            # the host copy below and the device scatter tensors, so
            # the two representations cannot diverge.
            updates.setdefault(si, []).append(
                (g, s, r, int(new_edges.get(sid, INF)))
            )
        overloaded[i] = ls.is_node_overloaded(nm)
    # copy-on-write the touched segments' host arrays
    seg_list: List[Segment] = []
    for band in graph.bands:
        seg_list.extend(band.segments)
    patched_segs = list(seg_list)
    for si, ups in updates.items():
        w = seg_list[si].w.copy()
        for (g, s, r, wv) in ups:
            w[g, s, r] = wv
        patched_segs[si] = Segment(
            axis=seg_list[si].axis, src=seg_list[si].src, w=w
        )
    bands: List[GridBand] = []
    si = 0
    for band in graph.bands:
        k = len(band.segments)
        bands.append(
            GridBand(
                start=band.start, g1=band.g1, g2=band.g2,
                segments=tuple(patched_segs[si : si + k]),
            )
        )
        si += k
    patched = GroupedGraph(
        node_names=graph.node_names, node_index=graph.node_index,
        n=graph.n, n_pad=graph.n_pad, bands=tuple(bands),
        overloaded=overloaded, direction=graph.direction,
    )
    return patched, updates


@functools.partial(
    jax.jit, static_argnames=("meta", "n", "mesh", "impl")
)
def _sharded_grouped_route_blocks(
    srcs_t, ws_t, overloaded, t_ids, samp_ids, samp_v, samp_w, pos_w,
    meta, n, mesh, impl,
):
    from jax.sharding import PartitionSpec as P

    from openr_tpu.ops.spf_sparse import SOURCES_AXIS

    def shard_fn(t_blk, *rest):
        ns = len(srcs_t)
        s_r = rest[:ns]
        w_r = rest[ns : 2 * ns]
        ov_r, sid_r, sv_r, sw_r, pw_r = rest[2 * ns :]
        return _grouped_route_block_body(
            s_r, w_r, ov_r, t_blk, sid_r, sv_r, sw_r, pw_r, meta, n,
            vote=lambda bit: jax.lax.psum(bit, SOURCES_AXIS),
            impl=impl,
        )

    ns = len(srcs_t)
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS)]
            + [P(None, None)] * ns  # src tables [G, S], replicated
            + [P(None, None, None)] * ns  # w tensors [G, S, R]
            + [P(None), P(None), P(None, None), P(None, None), P(None)]
        ),
        out_specs=P(SOURCES_AXIS, None),
    )(t_ids, *srcs_t, *ws_t, overloaded, samp_ids, samp_v, samp_w,
      pos_w)


def sharded_grouped_route_sweep(graph: GroupedGraph, sample_names, mesh):
    """The grouped route sweep in ONE sharded dispatch: destination
    rows sharded over the mesh, segment tables replicated (O(E)), the
    1-bit convergence psum the only collective — the grouped twin of
    route_sweep.sharded_route_sweep, producing the identical
    RouteSweepResult (canonical digests bit-comparable)."""
    from openr_tpu.ops import route_sweep as rs

    sweeper = GroupedRouteSweeper(graph, sample_names)
    n = graph.n_pad
    assert n % mesh.devices.size == 0, (n, mesh.devices.size)
    packed = np.asarray(
        _sharded_grouped_route_blocks(
            sweeper.v_t, sweeper.w_t, sweeper.overloaded,
            jnp.asarray(np.arange(n, dtype=np.int32)),
            sweeper._samp_ids_dev, sweeper._samp_v_dev,
            sweeper._samp_w_dev, sweeper._pos_w_dev,
            sweeper.meta, n, mesh, _GROUPED_IMPL,
        )
    )
    return rs.assemble_result(sweeper, packed)


def structure_report(graph: GroupedGraph) -> dict:
    """How much of the edge volume the structure detection captured:
    per band (g1, g2, segments, slots) + the total gather shrink
    factor vs per-node ELL slots."""
    bands = []
    grouped_slots = 0
    row_slots = 0
    for band in graph.bands:
        rows = band.g1 * band.g2
        seg_info = []
        for seg in band.segments:
            g, s, r = seg.w.shape
            seg_info.append({"axis": seg.axis, "g": g, "s": s, "r": r})
            grouped_slots += g * s
            row_slots += g * s * r
        bands.append(
            {"rows": rows, "g1": band.g1, "g2": band.g2,
             "segments": seg_info}
        )
    return {
        "bands": bands,
        "gather_slots": grouped_slots,
        "ell_equivalent_slots": row_slots,
        "gather_shrink": round(row_slots / max(1, grouped_slots), 1),
    }
