"""Sparse (edge-list) SPF kernels for very large topologies.

The dense kernels in ``openr_tpu.ops.spf`` carry an [N, N] metric matrix
— infeasible at the 100k-node north-star scale (10^10 cells, 40 GB).
Here the graph is a padded edge list compiled *directly from the host
LinkState* (no dense matrix anywhere, host or device) and one relaxation
step costs S x E work via gather + segment-min instead of S x N x N:

    cand[s, e] = d[s, edge_src[e]] + edge_w[e]
    d'[s, v]   = min(d[s, v], min_{e: edge_dst[e] == v} cand[s, e])

which converges to the same fixed point as the reference's per-source
Dijkstra (openr/decision/LinkState.cpp:809 runSpf) in diameter steps
inside a ``lax.while_loop``.

Semantics parity with the dense kernels:
- transit exclusion: out-edges of overloaded nodes are dropped from the
  relaxation edge list; the *initial* rows are produced by one
  relaxation over the FULL edge list from the unit init (diagonal 0),
  which equals the sources' direct-edge rows — so an overloaded source
  still originates (reference: LinkState.cpp:831-838).
- hop-count mode: all edge weights 1.
- INF saturation: d + w clips at INF = 2**30 - 1 (int32-safe).

Edges are sorted by destination (host-side, once per snapshot version)
so segment-min runs with ``indices_are_sorted=True``; padding edges
carry weight INF and can never win a min.

Source-axis sharding mirrors ``openr_tpu.parallel.mesh``: every device
owns a block of source rows, the edge lists are replicated (O(E), tiny
next to the distance block), and the only cross-device traffic is the
1-bit convergence psum per iteration. Per-device memory at 100k nodes
on a 32-device mesh: 100k/32 x 100k x 4 B ~= 1.25 GB of distance rows
plus the O(E) edge list — well inside HBM.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from openr_tpu.ops.spf import INF

_EDGE_PAD = 128
_NODE_PAD = 128


def _pad_up(n: int, align: int) -> int:
    return max(align, ((n + align - 1) // align) * align)


@dataclass(frozen=True)
class SparseGraph:
    """Padded, dst-sorted directed edge lists + node interning for one
    LinkState topology version. ``full_*`` carries every up link (used
    for the init step); ``transit_*`` drops out-edges of overloaded
    nodes (used for relaxation)."""

    node_names: Tuple[str, ...]
    node_index: Dict[str, int]
    n: int
    n_pad: int
    full_src: np.ndarray
    full_dst: np.ndarray
    full_w: np.ndarray
    transit_src: np.ndarray
    transit_dst: np.ndarray
    transit_w: np.ndarray


def _pack(srcs: List[int], dsts: List[int], ws: List[int]):
    e = len(srcs)
    e_pad = _pad_up(e, _EDGE_PAD)
    src = np.zeros(e_pad, dtype=np.int32)
    dst = np.zeros(e_pad, dtype=np.int32)
    w = np.full(e_pad, INF, dtype=np.int32)
    src[:e] = srcs
    dst[:e] = dsts
    w[:e] = ws
    order = np.argsort(dst, kind="stable")
    return src[order], dst[order], w[order]


def compile_sparse(ls, use_link_metric: bool = True,
                   align: int = _NODE_PAD) -> SparseGraph:
    """Edge-list compilation straight from the LinkState — never builds
    an N x N matrix, so it scales to topologies where the dense snapshot
    cannot."""
    names = tuple(sorted(ls.get_adjacency_databases().keys()))
    index = {name: i for i, name in enumerate(names)}
    n = len(names)
    full: Tuple[List[int], List[int], List[int]] = ([], [], [])
    transit: Tuple[List[int], List[int], List[int]] = ([], [], [])
    for name in names:
        i = index[name]
        overloaded = ls.is_node_overloaded(name)
        for link in ls.ordered_links_from_node(name):
            if not link.is_up():
                continue
            j = index.get(link.other_node(name))
            if j is None:
                continue
            w = (
                min(int(link.metric_from(name)), int(INF) - 1)
                if use_link_metric
                else 1
            )
            full[0].append(i)
            full[1].append(j)
            full[2].append(w)
            if not overloaded:
                transit[0].append(i)
                transit[1].append(j)
                transit[2].append(w)
    fs, fd, fw = _pack(*full)
    ts, td, tw = _pack(*transit)
    return SparseGraph(
        node_names=names,
        node_index=index,
        n=n,
        n_pad=_pad_up(n, align),
        full_src=fs,
        full_dst=fd,
        full_w=fw,
        transit_src=ts,
        transit_dst=td,
        transit_w=tw,
    )


def _relax(d, edge_src, edge_dst, edge_w, n):
    """One batched relaxation: [S, N] -> [S, N]."""
    cand = jnp.minimum(d[:, edge_src] + edge_w[None, :], INF)  # [S, E]

    def seg(row):
        return jax.ops.segment_min(
            row, edge_dst, num_segments=n, indices_are_sorted=True
        )

    relaxed = jax.vmap(seg)(cand)  # [S, N]; empty segments come back max
    return jnp.minimum(d, jnp.minimum(relaxed, INF).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("n",))
def _sparse_from_sources(
    src_ids: jnp.ndarray,
    full_src: jnp.ndarray,
    full_dst: jnp.ndarray,
    full_w: jnp.ndarray,
    t_src: jnp.ndarray,
    t_dst: jnp.ndarray,
    t_w: jnp.ndarray,
    n: int,
):
    s = src_ids.shape[0]
    unit = jnp.full((s, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(s), src_ids].set(0)
    # init rows == direct edges of each source (+ 0 diagonal): one relax
    # over the FULL edge list, so overloaded sources still originate
    d0 = _relax(unit, full_src, full_dst, full_w, n)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        nxt = _relax(d, t_src, t_dst, t_w, n)
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d


def sparse_distances_from_sources(graph: SparseGraph, src_ids):
    """Distances [S, N_pad] from a batch of sources over the sparse edge
    lists. Fixed-point-equal to ``ops.spf.distances_from_sources`` on
    the same topology."""
    return _sparse_from_sources(
        jnp.asarray(np.asarray(src_ids, dtype=np.int32)),
        jnp.asarray(graph.full_src),
        jnp.asarray(graph.full_dst),
        jnp.asarray(graph.full_w),
        jnp.asarray(graph.transit_src),
        jnp.asarray(graph.transit_dst),
        jnp.asarray(graph.transit_w),
        graph.n_pad,
    )


SOURCES_AXIS = "sources"


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def _sharded_sparse(
    src_ids, full_src, full_dst, full_w, t_src, t_dst, t_w, n, mesh
):
    def shard_fn(ids_blk, fs, fd, fw, ts, td, tw):
        s = ids_blk.shape[0]
        unit = jnp.full((s, n), INF, dtype=jnp.int32)
        unit = unit.at[jnp.arange(s), ids_blk].set(0)
        d0 = _relax(unit, fs, fd, fw, n)

        def cond(state):
            _, changed, it = state
            return jnp.logical_and(changed > 0, it < n)

        def body(state):
            d, _, it = state
            nxt = _relax(d, ts, td, tw, n)
            local = jnp.any(nxt < d).astype(jnp.int32)
            return nxt, jax.lax.psum(local, SOURCES_AXIS), it + 1

        d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
        return d

    return jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SOURCES_AXIS),
            P(None), P(None), P(None),
            P(None), P(None), P(None),
        ),
        out_specs=P(SOURCES_AXIS, None),
    )(src_ids, full_src, full_dst, full_w, t_src, t_dst, t_w)


def sharded_sparse_all_sources(graph: SparseGraph, mesh: Mesh):
    """All-sources distances [N_pad, N_pad], source rows sharded over
    the mesh, graph as replicated edge lists. This is the 100k-node
    shape: per-device memory is O(N_pad/devices x N_pad + E) and the
    only collective is the convergence bit."""
    n = graph.n_pad
    assert n % mesh.devices.size == 0, (n, mesh.devices.size)
    src_ids = np.arange(n, dtype=np.int32)
    return _sharded_sparse(
        jnp.asarray(src_ids),
        jnp.asarray(graph.full_src),
        jnp.asarray(graph.full_dst),
        jnp.asarray(graph.full_w),
        jnp.asarray(graph.transit_src),
        jnp.asarray(graph.transit_dst),
        jnp.asarray(graph.transit_w),
        n,
        mesh,
    )
