"""Sparse (edge-list) SPF kernels for very large topologies.

The dense kernels in ``openr_tpu.ops.spf`` carry an [N, N] metric matrix
— infeasible at the 100k-node north-star scale (10^10 cells, 40 GB).
Here the graph is a padded edge list compiled *directly from the host
LinkState* (no dense matrix anywhere, host or device) and one relaxation
step costs S x E work via gather + segment-min instead of S x N x N:

    cand[s, e] = d[s, edge_src[e]] + edge_w[e]
    d'[s, v]   = min(d[s, v], min_{e: edge_dst[e] == v} cand[s, e])

which converges to the same fixed point as the reference's per-source
Dijkstra (openr/decision/LinkState.cpp:809 runSpf) in diameter steps
inside a ``lax.while_loop``.

Semantics parity with the dense kernels:
- transit exclusion: out-edges of overloaded nodes are dropped from the
  relaxation edge list; the *initial* rows are produced by one
  relaxation over the FULL edge list from the unit init (diagonal 0),
  which equals the sources' direct-edge rows — so an overloaded source
  still originates (reference: LinkState.cpp:831-838).
- hop-count mode: all edge weights 1.
- INF saturation: d + w clips at INF = 2**30 - 1 (int32-safe).

Edges are sorted by destination (host-side, once per snapshot version)
so segment-min runs with ``indices_are_sorted=True``; padding edges
carry weight INF and can never win a min.

Source-axis sharding mirrors ``openr_tpu.parallel.mesh``: every device
owns a block of source rows, the edge lists are replicated (O(E), tiny
next to the distance block), and the only cross-device traffic is the
1-bit convergence psum per iteration. Per-device memory at 100k nodes
on a 32-device mesh: 100k/32 x 100k x 4 B ~= 1.25 GB of distance rows
plus the O(E) edge list — well inside HBM.
"""

from __future__ import annotations

import functools
import os
import time
from dataclasses import dataclass, replace as _replace
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from openr_tpu.utils.jax_compat import shard_map

from openr_tpu.graph.snapshot import pad_patch_rows
from openr_tpu.ops.spf import INF

_EDGE_PAD = 128
_NODE_PAD = 128

# Churn-path health counters for the resident-band machinery, surfaced
# through decision.spf_solver.get_spf_counters() with a "decision."
# prefix and asserted by the churn smoke test: a refactor that silently
# knocks the hot path back to full recompiles shows up as
# ell_incremental_syncs staying flat while ell_cold_solves climbs.
# Registry-backed shim since the telemetry spine: same bare keys and
# `ELL_COUNTERS[k] += 1` idiom, stored in the process registry under
# the exported "decision." names, so the registry snapshot and
# get_spf_counters() agree by construction.
from openr_tpu.analysis.annotations import donates, solve_window
from openr_tpu.ops import dispatch_accounting as _da
from openr_tpu.ops.aot_cache import aot_call as _aot_call
from openr_tpu.telemetry import get_registry as _get_registry
from openr_tpu.telemetry import get_tracer as _get_tracer

ELL_COUNTERS = _get_registry().counter_dict(
    [
        "ell_incremental_syncs",  # delta scatters into resident bands
        "ell_warm_solves",        # solves seeded from the previous d
        "ell_cold_solves",        # solves from the unit init
        "ell_widen_events",       # widen-on-overflow band re-uploads
        "ell_patch_merges",       # stacked patches coalesced warm
        "ell_structural_warm_solves",  # overload/link flips kept warm
    ],
    prefix="decision.",
)


# Sliced-ELL relax implementation selector — the sparse twin of
# ops.spf's min-plus selector: "jnp" (XLA gather+broadcast), "pallas"
# (explicit VMEM tiling, openr_tpu.ops.pallas_ell), or "auto" — a
# MEASURED per-(n_pad, k_slot) winner from ops.autotune (family
# "ell_relax"). Resolution happens at TRACE time inside the relax
# primitives below (the autotune probe runs eagerly on concrete
# synthetic operands, so an enclosing trace never sees "auto" — only
# the resolved impl is baked into the executable). The committed AOT
# dispatch tags re-key through ``ell_dispatch`` when a non-default
# kernel is armed, so flipping the impl can never replay a stale
# executable; the one plain-jit hot path (_ell_reconverge) carries the
# resolved impl as an ordinary static argument for the same reason.
_ELL_IMPL = os.environ.get("OPENR_ELL_RELAX", "jnp")


def set_ell_relax_impl(impl: str) -> None:
    global _ELL_IMPL
    assert impl in ("jnp", "pallas", "auto"), impl
    _ELL_IMPL = impl


def get_ell_relax_impl() -> str:
    return _ELL_IMPL


def _ell_impl_for(n: int, k: int) -> str:
    """Concrete relax impl for one (n_pad, k_slot) band geometry:
    "auto" resolves to the measured winner (memoized per shape by the
    autotuner, so the probe pays its compile once per process). A
    probe failure is never fatal — the jnp formulation is always
    sound."""
    if _ELL_IMPL != "auto":
        return _ELL_IMPL
    from openr_tpu.ops import autotune

    try:
        return autotune.resolve_ell_relax((int(n), int(k)))
    except Exception:  # noqa: BLE001 - measurement is best-effort
        return "jnp"


def ell_dispatch(tag, fn, dyn_args, statics, shape=None):
    """Committed-dispatch wrapper for executables whose TRACE bakes in
    the sliced-ELL relax impl (everything that iterates _ell_relax /
    _ell_relax_masked / _uniform_relax to a fixed point). A cached AOT
    executable keyed only on (tag, statics, signature) would survive an
    impl flip and silently keep running the old kernel; this wrapper
    resolves the concrete impl for the dispatch's band geometry — from
    ``statics`` (bands + n), or an explicit ``shape=(n, k)`` for
    uniform-block dispatches — and suffixes the tag (``tag@pallas``)
    whenever a non-default kernel is armed. The suffix re-keys the AOT
    cache AND shows up verbatim in ``ops.device_ms.<tag>`` attribution,
    so the flight recorder sees which kernel actually ran. Inner
    functions resolve the SAME memoized per-shape winner at trace time,
    which is what keeps the tag and the traced kernel consistent."""
    if shape is None:
        bands = statics["bands"]
        shape = (statics["n"], max(b.k for b in bands))
    impl = _ell_impl_for(int(shape[0]), int(shape[1]))
    if impl != "jnp":
        tag = f"{tag}@{impl}"
    return _aot_call(tag, fn, dyn_args, statics)


def _pad_up(n: int, align: int) -> int:
    return max(align, ((n + align - 1) // align) * align)


@dataclass(frozen=True)
class SparseGraph:
    """Padded, dst-sorted directed edge lists + node interning for one
    LinkState topology version. ``full_*`` carries every up link (used
    for the init step); ``transit_*`` drops out-edges of overloaded
    nodes (used for relaxation)."""

    node_names: Tuple[str, ...]
    node_index: Dict[str, int]
    n: int
    n_pad: int
    full_src: np.ndarray
    full_dst: np.ndarray
    full_w: np.ndarray
    transit_src: np.ndarray
    transit_dst: np.ndarray
    transit_w: np.ndarray


def _pack(srcs: List[int], dsts: List[int], ws: List[int]):
    e = len(srcs)
    e_pad = _pad_up(e, _EDGE_PAD)
    src = np.zeros(e_pad, dtype=np.int32)
    dst = np.zeros(e_pad, dtype=np.int32)
    w = np.full(e_pad, INF, dtype=np.int32)
    src[:e] = srcs
    dst[:e] = dsts
    w[:e] = ws
    order = np.argsort(dst, kind="stable")
    return src[order], dst[order], w[order]


def compile_sparse(ls, use_link_metric: bool = True,
                   align: int = _NODE_PAD) -> SparseGraph:
    """Edge-list compilation straight from the LinkState — never builds
    an N x N matrix, so it scales to topologies where the dense snapshot
    cannot."""
    names = tuple(sorted(ls.get_adjacency_databases().keys()))
    index = {name: i for i, name in enumerate(names)}
    n = len(names)
    full: Tuple[List[int], List[int], List[int]] = ([], [], [])
    transit: Tuple[List[int], List[int], List[int]] = ([], [], [])
    for name in names:
        i = index[name]
        overloaded = ls.is_node_overloaded(name)
        for link in ls.ordered_links_from_node(name):
            if not link.is_up():
                continue
            j = index.get(link.other_node(name))
            if j is None:
                continue
            w = (
                min(int(link.metric_from(name)), int(INF) - 1)
                if use_link_metric
                else 1
            )
            full[0].append(i)
            full[1].append(j)
            full[2].append(w)
            if not overloaded:
                transit[0].append(i)
                transit[1].append(j)
                transit[2].append(w)
    fs, fd, fw = _pack(*full)
    ts, td, tw = _pack(*transit)
    return SparseGraph(
        node_names=names,
        node_index=index,
        n=n,
        n_pad=_pad_up(n, align),
        full_src=fs,
        full_dst=fd,
        full_w=fw,
        transit_src=ts,
        transit_dst=td,
        transit_w=tw,
    )


def _relax(d, edge_src, edge_dst, edge_w, n):
    """One batched relaxation: [S, N] -> [S, N]."""
    cand = jnp.minimum(d[:, edge_src] + edge_w[None, :], INF)  # [S, E]

    def seg(row):
        return jax.ops.segment_min(
            row, edge_dst, num_segments=n, indices_are_sorted=True
        )

    relaxed = jax.vmap(seg)(cand)  # [S, N]; empty segments come back max
    return jnp.minimum(d, jnp.minimum(relaxed, INF).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("n",))
def _sparse_from_sources(
    src_ids: jnp.ndarray,
    full_src: jnp.ndarray,
    full_dst: jnp.ndarray,
    full_w: jnp.ndarray,
    t_src: jnp.ndarray,
    t_dst: jnp.ndarray,
    t_w: jnp.ndarray,
    n: int,
):
    s = src_ids.shape[0]
    unit = jnp.full((s, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(s), src_ids].set(0)
    # init rows == direct edges of each source (+ 0 diagonal): one relax
    # over the FULL edge list, so overloaded sources still originate
    d0 = _relax(unit, full_src, full_dst, full_w, n)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        nxt = _relax(d, t_src, t_dst, t_w, n)
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d


def _as_device_ids(src_ids) -> jnp.ndarray:
    """int32 device ids; a jax array passes through WITHOUT a host sync
    (chained-dispatch timing depends on ids staying on device)."""
    if isinstance(src_ids, jax.Array):
        return src_ids.astype(jnp.int32)
    return jnp.asarray(np.asarray(src_ids, dtype=np.int32))


def sparse_distances_from_sources(graph: SparseGraph, src_ids):
    """Distances [S, N_pad] from a batch of sources over the sparse edge
    lists. Fixed-point-equal to ``ops.spf.distances_from_sources`` on
    the same topology."""
    return _sparse_from_sources(
        _as_device_ids(src_ids),
        jnp.asarray(graph.full_src),
        jnp.asarray(graph.full_dst),
        jnp.asarray(graph.full_w),
        jnp.asarray(graph.transit_src),
        jnp.asarray(graph.transit_dst),
        jnp.asarray(graph.transit_w),
        graph.n_pad,
    )


# -- ELL (fixed-slot) format: the incremental-churn shape ----------------
#
# The flat edge list above is dst-sorted, so patching one node's edges
# after a topology change would reshuffle the whole list. The ELL layout
# gives every node a fixed band of in-edge slots: row j holds
# (src[j, k], w[j, k]) for every edge INTO j, and relaxation is a pure
# gather + K-reduce —
#
#     d'[s, j] = min(d[s, j], min_k d[s, src[j, k]] + w[j, k])
#
# — no scatter/segment-min anywhere (TPU scatters serialize; gathers
# vectorize).
#
# A single uniform band would be sized by the MAX degree, which is
# catastrophic on degree-skewed graphs (a 10k fat-tree: rack switches
# have 8 links, spine switches ~600 — a uniform band is ~98% padding and
# relaxation work becomes O(N x K_max) instead of O(E)). Nodes are
# therefore renumbered by (degree class, name) so that each power-of-two
# degree class occupies a contiguous id range with its own right-sized
# band ("sliced ELL"): total slots stay O(E) and the per-class
# gather-reduce writes a contiguous output slice — still no scatter.
#
# A churn event touches only the affected nodes' band rows (a LinkState
# link is bidirectional, so a node's in-edges are exactly its own links'
# reverse directions and the journal's affected set covers them): an
# O(rows x K_class) device scatter patch, the same resident-array
# pattern as the dense reconverge_step. This is what makes "1k adj
# events/s at 10k nodes" (BASELINE.json config 4) feasible: per event,
# host work and transfer are O(degree), device work O(S x E).

_ELL_SLOT_PAD = 8


@dataclass(frozen=True)
class EllBand:
    """One degree class: nodes [start, start + rows) hold <= k in-edges."""

    start: int
    rows: int
    k: int


@dataclass(frozen=True)
class EllGraph:
    node_names: Tuple[str, ...]  # index == dense id (class-grouped order!)
    node_index: Dict[str, int]
    n: int
    n_pad: int
    bands: Tuple[EllBand, ...]  # static per-topology; jit specializes on it
    src: Tuple[np.ndarray, ...]  # per band [rows, k] int32 (self-loop pad)
    w: Tuple[np.ndarray, ...]  # per band [rows, k] int32 (INF pad)
    overloaded: np.ndarray  # [n_pad] bool
    # band index -> band-local changed row ids, set by ell_patch so
    # EllState.reconverge scatters only those rows; None == full graph
    changed: Optional[Dict[int, np.ndarray]] = None
    # band indices whose k was grown in-place by ell_patch(widen=True)
    # (a row outgrew its slot class): node ids are UNCHANGED, but the
    # band's tensors have a new shape — consumers must re-upload those
    # bands wholesale instead of row-scattering into resident tensors
    widened: Optional[frozenset] = None
    # "in": row j holds edges INTO j (the forward-relax layout);
    # "out": row j holds edges OUT of j (the reversed-graph layout the
    # destination-major route sweep relaxes over)
    direction: str = "in"
    # per-link slot index for "in" graphs, two-level: node id ->
    # {link key -> (band idx, band-local row, slot)}. What makes a
    # single parallel link excludable in the masked KSP2 kernel. The
    # nesting keeps ell_patch's copy O(N) shallow (replace affected
    # nodes' inner dicts) instead of O(E) deep per churn event.
    slot_of: Optional[Dict[int, Dict[Tuple, Tuple[int, int, int]]]] = None


def _in_edges(ls, name, index) -> Dict[int, int]:
    """origin id -> min reverse-direction metric (parallel links: min)."""
    best: Dict[int, int] = {}
    for link in ls.ordered_links_from_node(name):
        if not link.is_up():
            continue
        other = link.other_node(name)
        i = index.get(other)
        if i is None:
            continue
        m = min(int(link.metric_from(other)), int(INF) - 1)
        if i not in best or m < best[i]:
            best[i] = m
    return best


_EMPTY_SLOTS: dict = {}


def link_key(link) -> Tuple:
    """Canonical per-link identity — Link's own precomputed identity
    tuple (the (node, iface) pair set, the same identity the reference
    gives first-class Links, LinkState.h:82 orderedNames_). Parallel
    links between one node pair differ in their iface pairs."""
    return link.ordered_names


import weakref as _weakref

# weakly keyed by the LIVE LinkState (an id()-keyed memo can alias a
# recycled address whose new graph passes through the same version —
# the SP-reuse soak caught that as a cross-world parity break)
_IN_SLOTS_MEMO: "_weakref.WeakKeyDictionary" = (
    _weakref.WeakKeyDictionary()
)


def _in_edge_slots(ls, name, index) -> List[Tuple[int, int, Tuple]]:
    """PER-LINK in-edge slots of ``name``: [(origin id, metric, link
    key)], sorted (origin id, key). Unlike _in_edges, parallel links
    keep their own slots — the KSP2 edge-disjoint masks must be able
    to exclude ONE member of a LAG without killing its siblings
    (reference: LinkState.cpp:763 getKthPaths' linksToIgnore).

    Memoized per live graph x (topology version, node): every input
    below (membership, liveness, metrics incl. holds) bumps the
    topology version when it changes, and churn-path callers re-derive
    the same high-degree node several times per event (padded patch
    rows repeat names). The id mapping is validated by identity on the
    cached entry rather than keyed by ``id(index)`` — a dict id can be
    recycled across garbage-collected mappings within one topology
    version, which would replay slots for the wrong numbering. Callers
    must not mutate the list."""
    per_ls = _IN_SLOTS_MEMO.get(ls)
    if per_ls is None:
        per_ls = {}
        _IN_SLOTS_MEMO[ls] = per_ls
    memo_key = (ls.topology_version, name)
    cached = per_ls.get(memo_key)
    if cached is not None and cached[0] is index:
        return cached[1]
    slots: List[Tuple[int, int, Tuple]] = []
    for link in ls.ordered_links_from_node(name):
        if not link.is_up():
            continue
        other = link.other_node(name)
        i = index.get(other)
        if i is None:
            continue
        m = min(int(link.metric_from(other)), int(INF) - 1)
        slots.append((i, m, link_key(link)))
    slots.sort(key=lambda t: (t[0], t[2]))
    while len(per_ls) > 256:
        per_ls.pop(next(iter(per_ls)))
    per_ls[memo_key] = (index, slots)
    return slots


def _out_edges(ls, name, index) -> Dict[int, int]:
    """dst id -> min forward-direction metric (parallel links: min).
    Row ``name`` of an out-ELL graph holds (dst, w(name -> dst)) — the
    in-edge bands of the REVERSED graph, which is what the
    destination-major route sweep (ops.route_sweep) relaxes over."""
    best: Dict[int, int] = {}
    for link in ls.ordered_links_from_node(name):
        if not link.is_up():
            continue
        other = link.other_node(name)
        i = index.get(other)
        if i is None:
            continue
        m = min(int(link.metric_from(name)), int(INF) - 1)
        if i not in best or m < best[i]:
            best[i] = m
    return best


def _fill_row(src_row, w_row, edges) -> None:
    for slot, (i, m) in enumerate(sorted(edges.items())):
        src_row[slot] = i
        w_row[slot] = m


def _band_of(graph: EllGraph, node_id: int) -> Tuple[int, EllBand]:
    for bi, band in enumerate(graph.bands):
        if band.start <= node_id < band.start + band.rows:
            return bi, band
    raise KeyError(node_id)


def compile_ell(ls, align: int = _NODE_PAD,
                direction: str = "in") -> EllGraph:
    """Sliced-ELL compilation from the LinkState: O(E) host work and
    O(E) total slots, no dense matrix. ``direction="out"`` builds the
    reversed-graph bands (row j = out-edges of j) consumed by
    ops.route_sweep.

    Direction "in" gives every LINK its own slot (parallel links are
    NOT min-collapsed) and records a slot index, so build_edge_masks
    can exclude one member of a parallel group — the KSP2 requirement.
    Distances are unchanged (the relax min()s across slots). Direction
    "out" keeps the collapsed per-neighbor layout: the route sweep's
    next-hop counts are per-NEIGHBOR there, matching the grouped
    backend's digest semantics."""
    per_link = direction == "in"
    edges_of = _in_edges if direction == "in" else _out_edges
    raw_names = sorted(ls.get_adjacency_databases().keys())
    raw_index = {name: i for i, name in enumerate(raw_names)}
    if per_link:
        # banding only needs the SLOT COUNT, which is independent of
        # the id mapping — skip the full slot derivation (metric reads,
        # link keys, sort) the fill pass below will do anyway
        degree = {
            name: max(
                1,
                sum(
                    1
                    for link in ls.ordered_links_from_node(name)
                    if link.is_up()
                    and link.other_node(name) in raw_index
                ),
            )
            for name in raw_names
        }
    else:
        degree = {
            name: max(1, len(edges_of(ls, name, raw_index)))
            for name in raw_names
        }
    # class id = padded power-of-two >= degree; group by (class, name)
    def class_k(d: int) -> int:
        k = _ELL_SLOT_PAD
        while k < d:
            k *= 2
        return k

    names = tuple(
        sorted(raw_names, key=lambda nm: (class_k(degree[nm]), nm))
    )
    index = {name: i for i, name in enumerate(names)}
    n = len(names)
    n_pad = _pad_up(n, align)

    bands: List[EllBand] = []
    srcs: List[np.ndarray] = []
    ws: List[np.ndarray] = []
    slot_of: Dict[int, Dict[Tuple, Tuple[int, int, int]]] = {}
    overloaded = np.zeros(n_pad, dtype=bool)
    i = 0
    while i < n:
        k = class_k(degree[names[i]])
        j = i
        while j < n and class_k(degree[names[j]]) == k:
            j += 1
        rows = j - i
        src_b = np.tile(
            np.arange(i, j, dtype=np.int32)[:, None], (1, k)
        )  # self-loop padding: inert with w=INF
        w_b = np.full((rows, k), INF, dtype=np.int32)
        for r, name in enumerate(names[i:j]):
            if per_link:
                nid = index[name]
                nd: Dict[Tuple, Tuple[int, int, int]] = {}
                for slot, (sid, m, key) in enumerate(
                    _in_edge_slots(ls, name, index)
                ):
                    src_b[r, slot] = sid
                    w_b[r, slot] = m
                    nd[key] = (len(bands), r, slot)
                slot_of[nid] = nd
            else:
                _fill_row(src_b[r], w_b[r], edges_of(ls, name, index))
        bands.append(EllBand(start=i, rows=rows, k=k))
        srcs.append(src_b)
        ws.append(w_b)
        i = j
    for name in names:
        overloaded[index[name]] = ls.is_node_overloaded(name)
    return EllGraph(
        node_names=names, node_index=index, n=n, n_pad=n_pad,
        bands=tuple(bands), src=tuple(srcs), w=tuple(ws),
        overloaded=overloaded, direction=direction,
        slot_of=slot_of if per_link else None,
    )


def ell_patch(
    graph: EllGraph, ls, affected, widen: bool = False
) -> Optional[EllGraph]:
    """New EllGraph with only the affected nodes' band rows re-derived;
    ``patched.changed`` maps band index -> band-local row ids. Returns
    None when the node set changed, or — unless ``widen`` — when a row
    outgrew its slot-class band (callers fall back to a full compile,
    which may renumber).

    ``widen=True`` grows an overflowing band's k in place instead
    (slots double to the next power of two; node ids are UNCHANGED, so
    resident per-node device state like the route engine's DR matrix
    stays valid). Widened band indices are recorded in
    ``patched.widened``: their tensors changed SHAPE, so a consumer
    holding resident band tensors must re-upload those bands wholesale
    (a row-scatter into the old shape cannot represent them) and
    expects a one-time jit recompile (band shapes are static args)."""
    # node-set validation without sorting 100k names per event: a
    # removal alone changes the count; an add (or rename = remove+add)
    # puts the new name in ``affected``, where the per-name
    # node_index lookup below rejects it
    if len(ls.get_adjacency_databases()) != graph.n:
        return None
    per_link = graph.slot_of is not None
    edges_of = _in_edges if graph.direction == "in" else _out_edges
    src = list(graph.src)
    w = list(graph.w)
    bands = list(graph.bands)
    overloaded = graph.overloaded.copy()
    slot_of = dict(graph.slot_of) if per_link else None
    changed: Dict[int, List[int]] = {}
    widened: set = set()
    copied: set = set()
    for name in affected:
        i = graph.node_index.get(name)
        if i is None:
            return None
        if per_link:
            slots = _in_edge_slots(ls, name, graph.node_index)
        else:
            edges = edges_of(ls, name, graph.node_index)
        bi, band = _band_of(graph, i)
        band = bands[bi]  # may already have been widened this event
        n_entries = len(slots) if per_link else len(edges)
        if n_entries > band.k:
            if not widen:
                return None
            new_k = band.k
            while new_k < n_entries:
                new_k *= 2
            grow = new_k - band.k
            # self-loop src + INF w padding: inert in every relax
            pad_src = np.tile(
                np.arange(
                    band.start, band.start + band.rows, dtype=np.int32
                )[:, None],
                (1, grow),
            )
            src[bi] = np.concatenate([src[bi], pad_src], axis=1)
            w[bi] = np.concatenate(
                [w[bi], np.full((band.rows, grow), INF, np.int32)],
                axis=1,
            )
            bands[bi] = EllBand(
                start=band.start, rows=band.rows, k=new_k
            )
            band = bands[bi]
            widened.add(bi)
            copied.add(bi)  # concatenate already made fresh arrays
        if bi not in copied:
            src[bi] = src[bi].copy()
            w[bi] = w[bi].copy()
            copied.add(bi)
        r = i - band.start
        src[bi][r] = np.full(band.k, i, dtype=np.int32)
        w[bi][r] = INF
        if per_link:
            # replace this node's inner slot dict wholesale (the outer
            # copy above was shallow, so the old graph keeps its own)
            nd: Dict[Tuple, Tuple[int, int, int]] = {}
            for slot, (sid, m, key) in enumerate(slots):
                src[bi][r, slot] = sid
                w[bi][r, slot] = m
                nd[key] = (bi, r, slot)
            slot_of[i] = nd
        else:
            _fill_row(src[bi][r], w[bi][r], edges)
        overloaded[i] = ls.is_node_overloaded(name)
        changed.setdefault(bi, []).append(r)
    return EllGraph(
        node_names=graph.node_names, node_index=graph.node_index,
        n=graph.n, n_pad=graph.n_pad, bands=tuple(bands),
        src=tuple(src), w=tuple(w), overloaded=overloaded,
        changed={bi: np.asarray(sorted(rs), dtype=np.int32)
                 for bi, rs in changed.items()},
        direction=graph.direction,
        slot_of=slot_of,
        widened=frozenset(widened) if widened else None,
    )


def band_row_edge_changes(
    old: EllGraph, patched: EllGraph
) -> List[Tuple[int, int, int, int]]:
    """ALL directed-edge weight changes implied by a patch's changed
    rows: [(tail id, head id, old collapsed weight, new collapsed
    weight)] for every (tail, head) whose min-over-parallel-slots
    weight moved (removal reads as old_w -> INF, addition as
    INF -> new_w). O(changed rows x K_class) host work, no band scan.
    The full (old, new) pair is what lets the warm-start journal MERGE
    stacked patches: the first touch of an edge snapshots the weight
    the resident distances were solved under, later touches only move
    the current side."""
    out: List[Tuple[int, int, int, int]] = []
    changed = patched.changed or {}
    for bi, rows in changed.items():
        band = patched.bands[bi]
        for r in np.asarray(rows):
            r = int(r)
            head = band.start + r
            old_w: Dict[int, int] = {}
            for s, wv in zip(old.src[bi][r], old.w[bi][r]):
                s = int(s)
                wv = int(wv)
                if s == head or wv >= INF:
                    continue  # self-loop / INF padding slots
                if wv < old_w.get(s, INF):
                    old_w[s] = wv
            new_w: Dict[int, int] = {}
            for s, wv in zip(patched.src[bi][r], patched.w[bi][r]):
                s = int(s)
                wv = int(wv)
                if s == head or wv >= INF:
                    continue
                if wv < new_w.get(s, INF):
                    new_w[s] = wv
            for s, wo in old_w.items():
                wn = new_w.get(s, INF)
                if wn != wo:
                    out.append((s, head, wo, wn))
            for s, wn in new_w.items():
                if s not in old_w:
                    out.append((s, head, INF, wn))
    return out


def band_row_edge_delta(
    old: EllGraph, patched: EllGraph
) -> List[Tuple[int, int, int]]:
    """Directed-edge weight INCREASES implied by a patch's changed
    rows: [(tail id, head id, old collapsed weight)] for every
    (tail, head) whose min-over-parallel-slots weight went UP (an edge
    removal reads as old_w -> INF). Decreases are deliberately absent:
    a min-relaxation warm start only needs the increase-affected cone
    — decreased rows keep their previous distances as valid upper
    bounds. Thin view over band_row_edge_changes."""
    return [
        (s, h, wo)
        for s, h, wo, wn in band_row_edge_changes(old, patched)
        if wn > wo
    ]


# sentinel "increase" edge that flags EVERY row's seed for reset (the
# tight test d[0] + 0 == d[0] holds unconditionally): encoding a full
# cold restart as a 1-edge delta keeps the warm and cold paths on ONE
# compiled executable instead of two
_FORCE_RESET_EDGE = (0, 0, 0)


def pad_increase_edges(
    inc: List[Tuple[int, int, int]]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack an increase-edge delta into pow-of-two bucketed arrays
    (tails, heads, old weights). Padding entries carry w = INF, which
    the tight test masks out, so every bucket size is one compiled
    shape."""
    bucket = 4
    while bucket < len(inc):
        bucket *= 2
    tails = np.zeros(bucket, dtype=np.int32)
    heads = np.zeros(bucket, dtype=np.int32)
    ws = np.full(bucket, INF, dtype=np.int32)
    for x, (t, h, w) in enumerate(inc):
        tails[x] = t
        heads[x] = h
        ws[x] = w
    return tails, heads, ws


def direct_metrics(graph: EllGraph, src_id: int, node_ids) -> np.ndarray:
    """Host-side direct min-metric src_id -> each node in node_ids (INF
    when not adjacent), read from the in-edge bands."""
    out = np.full(len(node_ids), INF, dtype=np.int32)
    for x, j in enumerate(node_ids):
        bi, band = _band_of(graph, int(j))
        r = int(j) - band.start
        hits = graph.src[bi][r] == src_id
        if hits.any():
            out[x] = graph.w[bi][r][hits].min()
    return out


def _ell_relax(d, bands, srcs_t, ws_t, overloaded, impl=None):
    """One masked relaxation over the class bands: [S, N] -> [S, N] as
    pure gather + reduce per band, writing contiguous output slices.
    Edges originating at overloaded nodes never extend paths.
    ``impl=None`` resolves the selector at trace time (see
    _ell_impl_for); "pallas" runs the VMEM-tiled band kernel
    (ops.pallas_ell) — bit-identical by the padding/saturation
    contract, so every fixed point downstream is too."""
    if impl is None:
        impl = _ell_impl_for(d.shape[1], max(b.k for b in bands))
    parts = []
    pos = 0
    if impl == "pallas":
        from openr_tpu.ops.pallas_ell import ell_band_relax

        for band, s_b, w_b in zip(bands, srcs_t, ws_t):
            assert band.start == pos, (band, pos)
            parts.append(ell_band_relax(d, s_b, w_b, overloaded, pos))
            pos += band.rows
        parts.append(d[:, pos:])  # padding columns: unchanged
        return jnp.concatenate(parts, axis=1)
    for band, s_b, w_b in zip(bands, srcs_t, ws_t):
        assert band.start == pos, (band, pos)
        w_eff = jnp.where(overloaded[s_b], INF, w_b)  # [rows, k]
        gathered = d[:, s_b]  # [S, rows, k]
        relaxed = jnp.min(
            jnp.minimum(gathered + w_eff[None, :, :], INF), axis=2
        )
        parts.append(
            jnp.minimum(d[:, pos : pos + band.rows], relaxed.astype(jnp.int32))
        )
        pos += band.rows
    parts.append(d[:, pos:])  # padding columns: unchanged
    return jnp.concatenate(parts, axis=1)


def _warm_seed(d_prev, inc_tail, inc_head, inc_w, d0):
    """Seed the relaxation fixed point from the previous distance rows,
    resetting only rows in the increase-affected cone.

    Soundness: the masked min-relax closure of any seed S with
    d* <= S <= d0 equals d* (monotone closure squeezed between the
    fixed point and the cold init's closure). d0 >= d* always; a
    previous row d_prev[s] >= d*_new[s] unless some increased edge lay
    on an old shortest path from s — exactly when the edge was TIGHT
    under the old distances: d_prev[s, head] == d_prev[s, tail] + w_old.
    Tight rows restart from the cold init d0; everything else seeds
    min(d_prev, d0) (the min keeps the unmasked-origination first-hop
    floor that d_prev already carries and d0 re-derives). Raw (unmasked)
    old weights make the test conservative under overload masks; mask
    CHANGES must be forced to a full reset by the caller (the
    _FORCE_RESET_EDGE sentinel). Bit-identical to a cold solve: int32
    min-relaxation has a unique fixed point, no float reassociation."""
    tight = (
        jnp.minimum(d_prev[:, inc_tail] + inc_w[None, :], INF)
        == d_prev[:, inc_head]
    ) & (inc_w[None, :] < INF)
    reset = jnp.any(tight, axis=1)
    return jnp.where(reset[:, None], d0, jnp.minimum(d_prev, d0))


def _device_direct_metrics(srcs_t, ws_t, srcs, bands):
    """On-device direct min-metric srcs[0] -> each batch node (INF when
    not adjacent, and for the source itself) — the resident-band mirror
    of host direct_metrics + _batch_args, so the fused churn dispatch
    needs no host band reads at all."""
    src_id = srcs[0]
    cols = []
    for band, s_b, w_b in zip(bands, srcs_t, ws_t):
        cols.append(jnp.min(jnp.where(s_b == src_id, w_b, INF), axis=1))
    direct = jnp.concatenate(cols)  # [real rows]
    w_sv = direct[srcs]
    return jnp.where(srcs == src_id, INF, w_sv).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("bands", "n", "ell_impl"))
def _ell_view_batch(srcs_t, ws_t, overloaded, srcs, w_sv, bands, n,
                    ell_impl="jnp"):
    """Batched {src} + neighbors distances + packed first hops over the
    sliced-ELL graph — the sparse mirror of ops.spf._spf_view_batch.
    w_sv: [B] host-computed direct metric source -> batch node.
    ``ell_impl`` is the resolved relax impl (plain-jit dispatch — the
    static re-keys on flips, same reasoning as _ell_reconverge)."""
    b = srcs.shape[0]
    unit = jnp.full((b, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(b), srcs].set(0)
    # init rows: one UNMASKED relax (overloaded sources still originate)
    no_overload = jnp.zeros_like(overloaded)
    d0 = _ell_relax(unit, bands, srcs_t, ws_t, no_overload,
                    impl=ell_impl)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        nxt = _ell_relax(d, bands, srcs_t, ws_t, overloaded,
                         impl=ell_impl)
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    fh = _first_hops_from_rows(d, srcs, w_sv, overloaded, n)
    return jnp.concatenate([d, fh.astype(jnp.int32)], axis=0)


def _first_hops_from_rows(d, srcs, w_sv, overloaded, n):
    """ECMP first-hop bits [B, N] from the batch's distance rows (same
    algebra as the dense kernel): neighbor v forwards toward j iff
    w(src,v) + d(v, j) == d(src, j), plus the direct-neighbor case.
    Shared by _ell_view_batch and _ell_all_view_rows — the engine's
    preloaded view must stay byte-identical to the fallback dispatch."""
    b = srcs.shape[0]
    d_src = d[0]
    is_neighbor = w_sv < INF
    reachable = d_src < INF
    total = jnp.minimum(w_sv[:, None] + d, INF)
    transit_ok = (
        is_neighbor[:, None]
        & (~overloaded[srcs])[:, None]
        & (total == d_src[None, :])
    )
    col_is_self = srcs[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (b, n), 1
    )
    direct_ok = col_is_self & (is_neighbor & (w_sv == d_src[srcs]))[:, None]
    return (transit_ok | direct_ok) & reachable[None, :]


@functools.partial(
    jax.jit,
    static_argnames=("bands", "n", "ell_impl"),
    # the previous bands and distance rows are dead after the call —
    # donating them lets XLA scatter/relax in place instead of copying
    # multi-hundred-MB band+distance blocks every churn event
    donate_argnums=(0, 1, 9),
)
def _ell_reconverge(srcs_t, ws_t, patch_ids_t, patch_src_t, patch_w_t,
                    inc_tail, inc_head, inc_w, overloaded, d_prev,
                    srcs, bands, n, ell_impl="jnp"):
    """Fused churn executable: scatter the patched rows, derive the
    direct metrics on device, warm-seed the fixed point from d_prev
    (reset only the increase cone), pack distances + first hops.
    Only the O(rows x K) patch + O(|delta|) increase edges cross
    host->device; only the packed [2B, N] view crosses back.
    ``ell_impl`` is the RESOLVED relax impl as an ordinary static
    argument — this is a plain-jit dispatch (no AOT tag to re-key), so
    an impl flip must re-key the jit cache instead."""
    new_src = tuple(
        s.at[ids, :].set(ps)
        for s, ids, ps in zip(srcs_t, patch_ids_t, patch_src_t)
    )
    new_w = tuple(
        w.at[ids, :].set(pw)
        for w, ids, pw in zip(ws_t, patch_ids_t, patch_w_t)
    )
    w_sv = _device_direct_metrics(new_src, new_w, srcs, bands)
    b = srcs.shape[0]
    unit = jnp.full((b, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(b), srcs].set(0)
    no_overload = jnp.zeros_like(overloaded)
    d0 = _ell_relax(unit, bands, new_src, new_w, no_overload,
                    impl=ell_impl)
    seed = _warm_seed(d_prev, inc_tail, inc_head, inc_w, d0)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        nxt = _ell_relax(d, bands, new_src, new_w, overloaded,
                         impl=ell_impl)
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (seed, jnp.bool_(True), 0))
    fh = _first_hops_from_rows(d, srcs, w_sv, overloaded, n)
    packed = jnp.concatenate([d, fh.astype(jnp.int32)], axis=0)
    return new_src, new_w, packed, d


def _batch_args(graph: EllGraph, srcs):
    srcs = np.asarray(srcs, dtype=np.int32)
    w_sv = direct_metrics(graph, int(srcs[0]), srcs)
    # the source itself is never its own neighbor
    w_sv[srcs == srcs[0]] = INF
    return jnp.asarray(srcs), jnp.asarray(w_sv)


def ell_view_batch_packed(graph: EllGraph, srcs):
    """Distances + first hops [2B, N_pad] (packed, one transfer) for a
    padded source batch over the sliced-ELL graph."""
    srcs_dev, w_sv = _batch_args(graph, srcs)
    return _ell_view_batch(
        tuple(jnp.asarray(s) for s in graph.src),
        tuple(jnp.asarray(w) for w in graph.w),
        jnp.asarray(graph.overloaded),
        srcs_dev, w_sv, graph.bands, graph.n_pad,
        ell_impl=_ell_impl_for(
            graph.n_pad, max(b.k for b in graph.bands)
        ),
    )


def ell_source_batch(graph: EllGraph, ls, src_name: str):
    """The hot-path source batch over an ELL graph: [src] + sorted
    unique up-neighbor ids, padded by repeating src to a power-of-two
    bucket (>= 8, capped at n_pad) — the ELL analogue of
    ops.spf.source_batch, and the one place this layout is defined for
    the sparse path."""
    sid = graph.node_index[src_name]
    nbrs = sorted(
        {
            graph.node_index[link.other_node(src_name)]
            for link in ls.links_from_node(src_name)
            if link.is_up() and link.other_node(src_name) in graph.node_index
        }
    )
    srcs = [sid] + nbrs
    bucket = 8
    while bucket < len(srcs):
        bucket *= 2
    bucket = min(bucket, graph.n_pad)
    return srcs + [sid] * (bucket - len(srcs))


def _ell_fixed_point(srcs_t, ws_t, overloaded, src_ids, bands, n,
                     vote=None, warm=None, impl=None):
    """Shared ELL relaxation fixed-point: distances [S, N] from unit
    init. ``vote`` turns the local convergence bit into the global
    stop condition (identity when None; a psum over the mesh axis for
    the sharded variant — every device iterates until ALL shards
    converge; the relaxation is idempotent past the fixed point).
    Init rows are one UNMASKED relax so overloaded sources still
    originate (reference: LinkState.cpp:831-838). ``warm`` is an
    optional (d_prev, inc_tail, inc_head, inc_w) tuple: seed from the
    previous distances via _warm_seed (bit-identical fixed point,
    fewer iterations under churn). ``impl`` as in _ell_relax —
    resolved ONCE here so both the init relax and the loop body bake
    the same kernel."""
    if impl is None:
        impl = _ell_impl_for(n, max(b.k for b in bands))
    s = src_ids.shape[0]
    unit = jnp.full((s, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(s), src_ids].set(0)
    no_overload = jnp.zeros_like(overloaded)
    d0 = _ell_relax(unit, bands, srcs_t, ws_t, no_overload, impl=impl)
    if warm is not None:
        d_prev, inc_tail, inc_head, inc_w = warm
        d0 = _warm_seed(d_prev, inc_tail, inc_head, inc_w, d0)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed > 0, it < n)

    def body(state):
        d, _, it = state
        nxt = _ell_relax(d, bands, srcs_t, ws_t, overloaded, impl=impl)
        local = jnp.any(nxt < d).astype(jnp.int32)
        return nxt, local if vote is None else vote(local), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
    return d


@functools.partial(jax.jit, static_argnames=("bands", "n", "ell_impl"))
def _ell_from_sources(srcs_t, ws_t, overloaded, src_ids, bands, n,
                      ell_impl="jnp"):
    """Distances [S, N] from a batch of sources over the sliced-ELL
    bands — pure gather + K-reduce per band, NO segment-min scatter
    anywhere. This is the all-sources workhorse: the flat-edge-list
    formulation (_sparse_from_sources) spends its time in
    ``jax.ops.segment_min``, which lowers to serialized scatters on
    TPU; this one vectorizes. ``ell_impl`` re-keys the plain-jit cache
    on kernel flips (see _ell_reconverge)."""
    return _ell_fixed_point(
        srcs_t, ws_t, overloaded, src_ids, bands, n, impl=ell_impl
    )


def ell_distances_from_sources(graph: EllGraph, src_ids,
                               state: "EllState" = None):
    """Distances [S, N_pad] from a batch of sources over the ELL graph.
    Pass ``state`` to reuse device-resident bands (no re-upload).
    Fixed-point-equal to ``sparse_distances_from_sources`` (and the
    host Dijkstra) on the same topology."""
    srcs_t = state.src if state is not None else tuple(
        jnp.asarray(s) for s in graph.src
    )
    ws_t = state.w if state is not None else tuple(
        jnp.asarray(w) for w in graph.w
    )
    ov = (
        state.overloaded
        if state is not None
        else jnp.asarray(graph.overloaded)
    )
    return _ell_from_sources(
        srcs_t, ws_t, ov,
        _as_device_ids(src_ids),
        graph.bands, graph.n_pad,
        ell_impl=_ell_impl_for(
            graph.n_pad, max(b.k for b in graph.bands)
        ),
    )


def iter_ell_all_sources(graph: EllGraph, block: int = 2048):
    """All-sources distances, yielded as (start, [block, N_pad] host
    array) source blocks — the caller streams them so the full
    [N, N] product never has to exist on host (at 100k that is 40 GB).
    The resident bands upload once (EllState) and each block is one
    dispatch + one readback."""
    state = EllState(graph)
    n = graph.n_pad
    # all block id vectors go up front in one async burst: uploading per
    # block would serialize a relay round trip between blocks
    id_blocks = []
    for start in range(0, n, block):
        ids = np.arange(start, min(start + block, n), dtype=np.int32)
        if len(ids) < block:  # keep one compiled shape
            ids = np.concatenate(
                [ids, np.full(block - len(ids), ids[-1], np.int32)]
            )
        id_blocks.append((start, jnp.asarray(ids)))
    for start, ids in id_blocks:
        yield start, np.asarray(
            ell_distances_from_sources(graph, ids, state=state)
        )


def ell_all_sources(graph: EllGraph, block: int = 2048) -> np.ndarray:
    """Materialized all-sources distances [N_pad, N_pad] (moderate N
    only — use iter_ell_all_sources past ~16k nodes)."""
    n = graph.n_pad
    out = np.empty((n, n), dtype=np.int32)
    for start, d_blk in iter_ell_all_sources(graph, block=block):
        take = min(block, n - start)
        out[start : start + take] = d_blk[:take]
    return out


def _ell_relax_masked(d, bands, srcs_t, ws_t, masks_t, overloaded,
                      impl=None):
    """One relaxation with a PER-BATCH edge mask: [B, N] -> [B, N].
    masks_t[bi] is [B, rows, k] bool — True == edge excluded for that
    batch element (the KSP2 edge-disjoint second-path graphs).
    ``impl`` as in _ell_relax."""
    if impl is None:
        impl = _ell_impl_for(d.shape[1], max(b.k for b in bands))
    parts = []
    pos = 0
    if impl == "pallas":
        from openr_tpu.ops.pallas_ell import ell_band_relax_masked

        for band, s_b, w_b, m_b in zip(bands, srcs_t, ws_t, masks_t):
            assert band.start == pos, (band, pos)
            parts.append(
                ell_band_relax_masked(d, s_b, w_b, m_b, overloaded, pos)
            )
            pos += band.rows
        parts.append(d[:, pos:])
        return jnp.concatenate(parts, axis=1)
    for band, s_b, w_b, m_b in zip(bands, srcs_t, ws_t, masks_t):
        assert band.start == pos, (band, pos)
        w_eff = jnp.where(overloaded[s_b], INF, w_b)  # [rows, k]
        w_batched = jnp.where(m_b, INF, w_eff[None, :, :])  # [B, rows, k]
        gathered = d[:, s_b]  # [B, rows, k]
        relaxed = jnp.min(
            jnp.minimum(gathered + w_batched, INF), axis=2
        )
        parts.append(
            jnp.minimum(d[:, pos : pos + band.rows], relaxed.astype(jnp.int32))
        )
        pos += band.rows
    parts.append(d[:, pos:])
    return jnp.concatenate(parts, axis=1)


def _ell_masked_fixed_point(srcs_t, ws_t, masks_t, overloaded, src_id,
                            bands, n, vote=None, impl=None):
    """Single-source distances over B differently-masked graphs:
    [B, N] — the device half of batched KSP2 second-path computation
    (reference semantics: LinkState.cpp:763 getKthPaths' runSpf with
    linksToIgnore, one per destination). Init is an unmasked-overload
    relax so an overloaded SOURCE still originates (mirrors
    _ell_view_batch). ``vote`` turns the local convergence bit into the
    global stop condition (identity when None; a psum for the sharded
    variant) — the SAME parameterization as _ell_fixed_point, and the
    ONE home of this loop (three call sites share it). ``impl`` as in
    _ell_fixed_point — resolved once, shared by init and body."""
    if impl is None:
        impl = _ell_impl_for(n, max(b.k for b in bands))
    b = masks_t[0].shape[0]
    unit = jnp.full((b, n), INF, dtype=jnp.int32)
    unit = unit.at[:, src_id].set(0)
    no_overload = jnp.zeros_like(overloaded)
    d0 = _ell_relax_masked(
        unit, bands, srcs_t, ws_t, masks_t, no_overload, impl=impl
    )

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed > 0, it < n)

    def body(state):
        d, _, it = state
        nxt = _ell_relax_masked(
            d, bands, srcs_t, ws_t, masks_t, overloaded, impl=impl
        )
        local = jnp.any(nxt < d).astype(jnp.int32)
        return nxt, local if vote is None else vote(local), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
    return d


@functools.partial(jax.jit, static_argnames=("bands", "n"))
def _ell_masked_source_batch(srcs_t, ws_t, masks_t, overloaded, src_id,
                             bands, n):
    return _ell_masked_fixed_point(
        srcs_t, ws_t, masks_t, overloaded, src_id, bands, n
    )


def build_edge_masks(graph: EllGraph, exclusion_sets, parallel_pairs=None):
    """Per-band [B, rows, k] bool masks from per-batch-element link
    sets. On a per-link-slot graph (compile_ell direction="in") every
    link — parallel group members included — maps to its OWN slot via
    ``graph.slot_of``, so ok_flags[b] is False only when an exclusion
    references a node outside the graph (reference semantics:
    LinkState.cpp:763 getKthPaths' linksToIgnore treats each Link as
    first-class, LinkState.h:82).

    Collapsed graphs (no slot_of) keep the legacy behavior:
    ``parallel_pairs`` elements are unrepresentable and flag ok=False."""
    b = len(exclusion_sets)
    parallel_pairs = parallel_pairs or set()
    masks = [
        np.zeros((b, band.rows, band.k), dtype=bool)
        for band in graph.bands
    ]
    ok = np.ones(b, dtype=bool)
    per_link = graph.slot_of is not None
    for x, links in enumerate(exclusion_sets):
        for link in links:
            if not per_link and (
                frozenset((link.n1, link.n2)) in parallel_pairs
            ):
                ok[x] = False
                break
            key = link_key(link) if per_link else None
            for head in (link.n1, link.n2):
                tail = link.other_node(head)
                hid = graph.node_index.get(head)
                tid = graph.node_index.get(tail)
                if hid is None or tid is None:
                    ok[x] = False
                    break
                if per_link:
                    hit = graph.slot_of.get(hid, _EMPTY_SLOTS).get(key)
                    if hit is None:
                        # link not in the ELL (e.g. went down after
                        # compile): nothing to mask
                        continue
                    bi, r, slot = hit
                    masks[bi][x, r, slot] = True
                    continue
                bi, band = _band_of(graph, hid)
                r = hid - band.start
                hits = np.flatnonzero(graph.src[bi][r] == tid)
                if len(hits) == 0:
                    continue
                masks[bi][x, r, hits[0]] = True
            if not ok[x]:
                break
    return masks, ok


def ell_masked_distances(graph: EllGraph, src_id: int, masks):
    """Run the batched masked solve; returns host [B, n_pad] int32.
    Rides the committed AOT executable cache — the host-graph twin of
    ``ell_masked_distances_resident`` (the serve plane's per-tenant
    KSP2 view dispatches here, so its warm waves must not retrace)."""
    d = ell_dispatch(
        "ksp2_masked_host", _ell_masked_source_batch,
        (
            tuple(jnp.asarray(s) for s in graph.src),
            tuple(jnp.asarray(w) for w in graph.w),
            tuple(jnp.asarray(m) for m in masks),
            jnp.asarray(graph.overloaded),
            src_id,
        ),
        dict(bands=graph.bands, n=graph.n_pad),
    )
    return np.asarray(d)


def ell_masked_distances_resident(
    state: "EllState", src_id: int, masks, defer: bool = False
):
    """Masked solve over an EllState's device-RESIDENT bands — only the
    masks cross host->device per dispatch. Dispatches through the AOT
    executable cache (``ksp2_masked_resident``) so a warm churn event
    costs a dict lookup, not a jit signature re-derivation. With
    ``defer=True`` the [B, n_pad] product stays ON DEVICE with its
    readback kicked on the async lane — the caller reaps it via
    ``dispatch_accounting.reap_read(rows, kicked=True)`` inside its
    event window (the KSP2 committed-dispatch chain)."""
    d = ell_dispatch(
        "ksp2_masked_resident", _ell_masked_source_batch,
        (
            state.src,
            state.w,
            tuple(jnp.asarray(m) for m in masks),
            state.overloaded,
            src_id,
        ),
        dict(bands=state.graph.bands, n=state.graph.n_pad),
    )
    if defer:
        _da.kick_async(d)
        return d
    return np.asarray(d)


def band_patch_inputs(resident_src, resident_w, patched: EllGraph):
    """The ONE implementation of the band patch discipline shared by
    every resident-band consumer (EllState.apply_patch/.reconverge and
    the route engine's churn prep): per band, either a bucketed
    row-scatter (pad_patch_rows shapes, a zeros(1) no-op when nothing
    changed) or — for a WIDENED band, whose tensor SHAPE changed — a
    wholesale re-upload with a no-op scatter. Returns
    (in_src, in_w, patch_ids, patch_src, patch_w) as tuples of device
    arrays: dispatch inputs plus the scatter triples."""
    changed: Dict[int, np.ndarray] = patched.changed or {}
    widened = patched.widened or frozenset()
    in_src = list(resident_src)
    in_w = list(resident_w)
    patch_ids, patch_src, patch_w = [], [], []
    for bi, band in enumerate(patched.bands):
        if bi in widened:
            in_src[bi] = jnp.asarray(patched.src[bi])
            in_w[bi] = jnp.asarray(patched.w[bi])
            rows = np.zeros(1, dtype=np.int32)
        else:
            rows = changed.get(bi)
            if rows is None or len(rows) == 0:
                rows = np.zeros(1, dtype=np.int32)  # no-op scatter
            else:
                padded = pad_patch_rows(
                    np.asarray(rows, dtype=np.int32)
                )
                rows = (
                    padded
                    if padded is not None
                    else np.arange(band.rows, dtype=np.int32)
                )
        patch_ids.append(jnp.asarray(rows))
        patch_src.append(jnp.asarray(patched.src[bi][rows]))
        patch_w.append(jnp.asarray(patched.w[bi][rows]))
    return (
        tuple(in_src), tuple(in_w),
        tuple(patch_ids), tuple(patch_src), tuple(patch_w),
    )


class EllState:
    """Caller-owned resident device bands for the churn loop.

    Everything a dispatch consumes lives on the device: the bands, and
    the overloaded mask (re-uploaded only when it actually changes — on
    relay-backed platforms every host->device transfer rides a ~70ms
    round trip, so a per-dispatch ``jnp.asarray(overloaded)`` used to
    dominate the measured block time ~70x over the compute)."""

    def __init__(self, graph: EllGraph):
        self.graph = graph
        self.src = tuple(jnp.asarray(s) for s in graph.src)
        self.w = tuple(jnp.asarray(w) for w in graph.w)
        self.overloaded = jnp.asarray(graph.overloaded)
        # warm-start state: the previous solve's distance rows plus the
        # source batch they belong to, and a MERGEABLE journal of every
        # un-solved patch's edge changes. Each journal entry keys
        # (tail, head) -> (w_snapshot, w_current): the snapshot is the
        # collapsed weight the RESIDENT DISTANCES were solved under
        # (first touch wins — an edge changed twice inside one debounce
        # window keeps its original snapshot), the current side tracks
        # the latest patch. At solve time the increase delta is emitted
        # against the snapshots, which is exactly what the tight test
        # is sound against — so stacked patches coalesce into one warm
        # solve instead of degrading to a forced cold seed.
        #
        # STRUCTURAL events (overload-mask flips) stay warm too: the
        # mask at the last solve is kept (_ov_solved), a flipped
        # node's out-edges are journaled at their raw weights, and the
        # solve-time emission compares EFFECTIVE weights (raw, or INF
        # when the tail was/is masked) so a drain reads as an increase
        # delta and an undrain as a plain decrease — no forced cold
        # seed on either.
        self._d_dev = None
        self._warm_key: Optional[Tuple[int, ...]] = None
        self._pending_edges: Dict[
            Tuple[int, int], Tuple[int, int]
        ] = {}
        self._ov_solved = np.array(graph.overloaded, copy=True)
        self._pending_structural = False

    def _sync_overloaded(self, patched: EllGraph) -> bool:
        changed = not np.array_equal(
            self.graph.overloaded, patched.overloaded
        )
        if changed:
            self.overloaded = jnp.asarray(patched.overloaded)
        return changed

    def _note_patch(self, patched: EllGraph, ov_changed: bool) -> None:
        """Fold one patch's delta into the warm-start journal. Stacked
        patches MERGE: an edge already journaled keeps its weight
        snapshot (taken from the last-solved graph) and only advances
        its current side, so a burst of patches inside one debounce
        window still emits a single sound increase delta at solve
        time.

        Overload-mask flips are journaled rather than forcing a cold
        seed: every out-edge of a flipped node enters the journal at
        its raw collapsed weight, and the emission in reconverge
        applies the mask per side (see _emit_increases) — a drain
        becomes an ordinary increase delta, an undrain a decrease.
        Link up/down (a row removal/addition in the patch) already
        reads as a w <-> INF transition through band_row_edge_changes,
        so the same journal carries it."""
        if patched.changed:
            ELL_COUNTERS["ell_incremental_syncs"] += 1
        if patched.widened:
            ELL_COUNTERS["ell_widen_events"] += len(patched.widened)
        if self._d_dev is None:
            return
        if ov_changed:
            # journal the flipped nodes' out-edges from the PRE-patch
            # resident graph (self.graph — replaced only after the
            # patch lands): the effective weight of every such edge
            # moves with the mask even though its raw weight did not.
            # O(E) host scan, vectorized; flips are rare events.
            self._pending_structural = True
            flipped = np.nonzero(
                np.asarray(self.graph.overloaded)
                != np.asarray(patched.overloaded)
            )[0]
            collapsed: Dict[Tuple[int, int], int] = {}
            pos = 0
            for src_b, w_b in zip(self.graph.src, self.graph.w):
                src_h = np.asarray(src_b)
                w_h = np.asarray(w_b)
                hit = np.isin(src_h, flipped) & (w_h < INF)
                for r, sl in zip(*np.nonzero(hit)):
                    key = (int(src_h[r, sl]), pos + int(r))
                    w = int(w_h[r, sl])
                    if w < collapsed.get(key, INF):
                        collapsed[key] = w
                pos += src_h.shape[0]
            for key, w in collapsed.items():
                self._pending_edges.setdefault(key, (w, w))
        if not patched.changed:
            return  # mask-only / no-op sync: raw journal untouched
        if self._pending_edges:
            ELL_COUNTERS["ell_patch_merges"] += 1
        structural = False
        for s, h, wo, wn in band_row_edge_changes(self.graph, patched):
            snap, _cur = self._pending_edges.get((s, h), (wo, wo))
            self._pending_edges[(s, h)] = (snap, wn)
            structural = structural or wo >= INF or wn >= INF
        if structural:
            self._pending_structural = True

    def _emit_increases(self, ov_now: np.ndarray):
        """The journal's increase delta, EFFECTIVE-weight aware: an
        entry is emitted when its raw weight rose (covers the
        origination row — an overloaded source still uses its own
        out-edges) or when its masked weight rose (covers transit
        rows across a drain flip). The emitted weight is the raw
        snapshot: every realized tight step in d_prev used the raw
        value, so the tight test stays sound; rows reset through a
        masked coincidence are merely extra work, never wrong."""
        inc = []
        for (s, h), (snap, cur) in self._pending_edges.items():
            if snap >= INF:
                continue  # edge unusable at solve time: can't tighten
            snap_eff = INF if self._ov_solved[s] else snap
            cur_eff = INF if ov_now[s] else cur
            if cur > snap or cur_eff > snap_eff:
                inc.append((s, h, snap))
        return inc

    def apply_patch(self, patched: EllGraph) -> None:
        """Scatter a patched graph's changed rows into the resident
        bands WITHOUT solving (for consumers that only need synced
        device bands, e.g. the KSP2 masked batches, and the decision
        module's publication-time prewarm). A WIDENED band
        (ell_patch(widen=True) grew its k — a row outgrew its slot
        class) changed tensor SHAPE and is re-uploaded wholesale; node
        ids are unchanged, so every id-keyed resident consumer stays
        valid. The increase delta is journaled so a later reconverge
        can still warm-start across the un-solved patch."""
        ov_changed = self._sync_overloaded(patched)
        self._note_patch(patched, ov_changed)
        in_src, in_w, patch_ids, patch_src, patch_w = (
            band_patch_inputs(self.src, self.w, patched)
        )
        # eager bucketed scatters (one compiled shape per bucket); the
        # no-op rows rewrite identical values
        self.src = tuple(
            s.at[ids, :].set(vals)
            for s, ids, vals in zip(in_src, patch_ids, patch_src)
        )
        self.w = tuple(
            w.at[ids, :].set(vals)
            for w, ids, vals in zip(in_w, patch_ids, patch_w)
        )
        self.graph = _replace(patched, changed=None)

    @solve_window
    def reconverge(self, patched: EllGraph, srcs):
        """Fused churn step: scatter the patched rows into the resident
        bands, solve the batched view warm-started from the previous
        solve's distances (bit-identical to cold — see _warm_seed),
        O(rows x K_class + |delta|) transfer in, O(B x N) out. Widened
        bands (shape changed) are re-uploaded wholesale as the dispatch
        inputs with a no-op scatter — same discipline as apply_patch;
        the new band shapes cost one jit recompile."""
        # span on the enclosing module's active trace (no-op outside a
        # traced churn event); attrs carry the warm/cold verdict plus
        # the device-dispatch vs host-overhead split
        _tracer = _get_tracer()
        _span = _tracer.span_active("ops.ell_reconverge")
        _t0 = time.perf_counter()
        ov_changed = self._sync_overloaded(patched)
        self._note_patch(patched, ov_changed)
        in_src, in_w, patch_ids, patch_src, patch_w = (
            band_patch_inputs(self.src, self.w, patched)
        )
        srcs_key = tuple(int(s) for s in srcs)
        b = len(srcs_key)
        warm = (
            self._d_dev is not None
            and self._warm_key == srcs_key
        )
        if warm:
            # increases vs the SNAPSHOT weights the resident distances
            # were solved under (edges that moved and came back to or
            # below their snapshot need no reset: the old rows are
            # still valid upper bounds); effective-weight aware, so
            # drain flips and link removals ride the same warm seed
            # openr-lint: disable=host-sync-in-window -- overloaded is
            # a host ndarray on EllGraph; no device transfer happens
            ov_now = np.asarray(patched.overloaded)
            inc = self._emit_increases(ov_now)
            d_prev = self._d_dev
            ELL_COUNTERS["ell_warm_solves"] += 1
            if self._pending_structural:
                ELL_COUNTERS["ell_structural_warm_solves"] += 1
        else:
            inc = [_FORCE_RESET_EDGE]
            d_prev = (
                self._d_dev
                if self._d_dev is not None
                and self._d_dev.shape == (b, patched.n_pad)
                else jnp.zeros((b, patched.n_pad), dtype=jnp.int32)
            )
            ELL_COUNTERS["ell_cold_solves"] += 1
        inc_t, inc_h, inc_w = pad_increase_edges(inc)
        # openr-lint: disable=host-sync-in-window -- srcs is a host
        # list of sample ids, not a device array; no transfer happens
        srcs_dev = jnp.asarray(np.asarray(srcs, dtype=np.int32))
        _t_dispatch = time.perf_counter()
        # openr-lint: disable=donation-hazard -- intentional: the warm
        # path CONSUMES the previous resident distances (d_prev is dead
        # after this dispatch) and self._d_dev is rebound to the fresh
        # output below; no retry ladder re-reads the donated buffer
        # openr-lint: disable=sharding-spec -- single-chip resident
        # reconvergence (mesh callers go through the sharded_ell_*
        # shard_map wrappers): no mesh axis to spec
        self.src, self.w, packed, d = _ell_reconverge(
            in_src, in_w, patch_ids, patch_src, patch_w,
            jnp.asarray(inc_t), jnp.asarray(inc_h), jnp.asarray(inc_w),
            self.overloaded, d_prev, srcs_dev,
            patched.bands, patched.n_pad,
            ell_impl=_ell_impl_for(
                patched.n_pad, max(b.k for b in patched.bands)
            ),
        )
        _t_end = time.perf_counter()
        self._d_dev = d
        self._warm_key = srcs_key
        self._pending_edges = {}
        # openr-lint: disable=host-sync-in-window -- host ndarray copy
        # (the overload mask the resident distances were solved under)
        self._ov_solved = np.array(patched.overloaded, copy=True)
        self._pending_structural = False
        self.graph = _replace(patched, changed=None)
        _total_ms = (_t_end - _t0) * 1000.0
        _dispatch_ms = (_t_end - _t_dispatch) * 1000.0
        _reg = _get_registry()
        _reg.observe("ops.ell.reconverge_ms", _total_ms)
        _reg.observe(
            "ops.ell.host_overhead_ms", _total_ms - _dispatch_ms
        )
        _tracer.end_span_active(
            _span,
            warm=warm,
            dispatch_ms=round(_dispatch_ms, 4),
            host_overhead_ms=round(_total_ms - _dispatch_ms, 4),
        )
        return packed


def ell_reconverge_step(state: EllState, patched: EllGraph, srcs):
    """Convenience wrapper around EllState.reconverge."""
    return state.reconverge(patched, srcs)


@functools.partial(
    jax.jit,
    static_argnames=("bands", "n"),
    donate_argnums=(6,),  # d_prev: dead after the call, relax in place
)
def _ell_all_view_rows(
    srcs_t, ws_t, overloaded, view_srcs, w_sv, ep_ids, d_prev,
    inc_tail, inc_head, inc_w, bands, n,
):
    """One fused dispatch for the incremental-KSP2 churn step at
    moderate N (n_pad <= ~4k, where a full all-sources block fits):

      1. all-sources distances D [n, n] over the resident bands,
      2. the batched {root} + neighbors view (distances + packed first
         hops — same algebra as _ell_view_batch) DERIVED from D's rows
         instead of a second fixed point,
      3. row gathers from D (new) and ``d_prev`` (the previous build's
         resident D) for the invalidation endpoints,

    returning (D, packed) where packed = [view_d | view_fh | rows_new |
    rows_old] — the caller reads back only ``packed`` (one transfer) and
    keeps D resident for the next event. On relay-backed platforms each
    extra readback costs a ~70ms round trip, so fusing the view and the
    invalidation rows into the same transfer is what keeps a churn
    rebuild near the single-round-trip floor. The fixed point is
    warm-seeded from ``d_prev`` with the increase-edge delta
    (inc_tail/inc_head/inc_w — see _warm_seed; callers pass the
    _FORCE_RESET_EDGE sentinel for cold semantics)."""
    d_all = _ell_fixed_point(
        srcs_t, ws_t, overloaded,
        jnp.arange(n, dtype=jnp.int32), bands, n,
        warm=(d_prev, inc_tail, inc_head, inc_w),
    )

    # view from D rows (shared first-hop algebra with _ell_view_batch)
    d = d_all[view_srcs]  # [B, n]
    fh = _first_hops_from_rows(d, view_srcs, w_sv, overloaded, n)

    packed = jnp.concatenate(
        [
            d,
            fh.astype(jnp.int32),
            d_all[ep_ids],
            d_prev[ep_ids],
        ],
        axis=0,
    )
    return d_all, packed


@functools.partial(
    jax.jit,
    static_argnames=("bands", "n", "k_budget"),
    donate_argnums=(6, 11),  # d_prev, dm_old: dead after the call
)
def _ell_all_view_rows_masked(
    srcs_t, ws_t, overloaded, view_srcs, w_sv, ep_ids, d_prev,
    inc_tail, inc_head, inc_w, masks_t, dm_old, src_id, bands, n,
    k_budget,
):
    """The 1-round-trip incremental-KSP2 dispatch: everything
    _ell_all_view_rows computes PLUS a speculative masked re-solve of
    every destination's second-path graph against the RESIDENT masks,
    diffed on-device against the previous masked rows so the readback
    carries only the rows that actually moved:

      - dm_new [D, n]: single-source solve over D edge-masked graphs
        (the KSP2 second-path product, ops semantics of
        _ell_masked_source_batch)
      - changed row ids (top k_budget, -1 padded) + their rows
      - count of changed rows (callers fall back to a full dm readback
        when it exceeds the budget)

    Destinations whose masks are stale this event (first paths changed)
    get garbage dm_new rows by construction — the engine re-solves
    exactly those in a follow-up dispatch and scatters the corrections
    into the resident matrix. For every other destination the
    speculative row is exact, which is what turns the common
    metric-churn event into ONE device round trip. The all-sources
    fixed point is warm-seeded from ``d_prev`` (cold when the caller
    passes the _FORCE_RESET_EDGE sentinel); the masked second-path
    solve stays cold — its masks change shape with the first paths, so
    a previous dm row is not a sound upper bound."""
    d_all = _ell_fixed_point(
        srcs_t, ws_t, overloaded,
        jnp.arange(n, dtype=jnp.int32), bands, n,
        warm=(d_prev, inc_tail, inc_head, inc_w),
    )
    d = d_all[view_srcs]
    fh = _first_hops_from_rows(d, view_srcs, w_sv, overloaded, n)

    b = masks_t[0].shape[0]
    dm_new = _ell_masked_fixed_point(
        srcs_t, ws_t, masks_t, overloaded, src_id, bands, n
    )

    row_changed = jnp.any(dm_new != dm_old, axis=1)  # [D]
    changed_ids = jnp.nonzero(
        row_changed, size=k_budget, fill_value=-1
    )[0].astype(jnp.int32)
    count = jnp.sum(row_changed.astype(jnp.int32))
    # ids + count packed into one int32 row of width n (n > k_budget)
    meta = jnp.full((n,), -1, dtype=jnp.int32)
    meta = meta.at[:k_budget].set(changed_ids)
    meta = meta.at[k_budget].set(count)
    changed_rows = dm_new[jnp.clip(changed_ids, 0, b - 1)]  # [K, n]

    packed = jnp.concatenate(
        [
            d,
            fh.astype(jnp.int32),
            d_all[ep_ids],
            d_prev[ep_ids],
            meta[None, :],
            changed_rows,
        ],
        axis=0,
    )
    return d_all, dm_new, packed


def _inc_args(inc):
    """Device increase-edge triple for the warm-seeded dispatches:
    ``inc=None`` means cold semantics (the reset sentinel flags every
    row); an (possibly empty) increase list warm-starts."""
    inc_t, inc_h, inc_w = pad_increase_edges(
        [_FORCE_RESET_EDGE] if inc is None else list(inc)
    )
    return jnp.asarray(inc_t), jnp.asarray(inc_h), jnp.asarray(inc_w)


@donates("d_prev", "dm_old")
def ell_all_view_rows_masked(
    state: EllState, view_srcs, w_sv, ep_ids, d_prev,
    masks_t, dm_old, src_id: int, k_budget: int, inc=None,
    defer: bool = False,
):
    """Run the fused 1-RTT dispatch on the resident bands. Returns
    (d_all_dev, dm_new_dev, packed_host). ``inc`` is the increase-edge
    delta [(tail, head, old_w)] for warm seeding — None forces the
    cold seed; d_prev and dm_old are DONATED (invalid after the
    call). Rides the committed AOT executable cache
    (``ksp2_view_rows_masked``); ``defer=True`` keeps ``packed`` on
    device with its readback kicked async — the caller reaps via
    ``dispatch_accounting.reap_read(packed, kicked=True)`` inside its
    event window, folding the relay round trip into the chain."""
    inc_t, inc_h, inc_w = _inc_args(inc)
    d_all, dm_new, packed = ell_dispatch(
        "ksp2_view_rows_masked", _ell_all_view_rows_masked,
        (
            state.src, state.w, state.overloaded,
            _as_device_ids(view_srcs),
            w_sv if isinstance(w_sv, jax.Array) else jnp.asarray(
                np.asarray(w_sv, dtype=np.int32)
            ),
            _as_device_ids(ep_ids),
            d_prev, inc_t, inc_h, inc_w, masks_t, dm_old, src_id,
        ),
        dict(
            bands=state.graph.bands, n=state.graph.n_pad,
            k_budget=k_budget,
        ),
    )
    if defer:
        _da.kick_async(packed)
        return d_all, dm_new, packed
    return d_all, dm_new, np.asarray(packed)


@donates("d_prev")
def ell_all_view_rows(state: EllState, view_srcs, w_sv, ep_ids, d_prev,
                      inc=None, defer: bool = False):
    """Run the fused all-sources + view + invalidation-rows dispatch on
    the resident bands. Returns (d_all_dev, packed_host). ``inc`` as in
    ell_all_view_rows_masked; d_prev is DONATED. Rides the committed
    AOT executable cache (``ksp2_view_rows``); ``defer=True`` as in
    ell_all_view_rows_masked (device ``packed``, readback kicked,
    caller reaps)."""
    inc_t, inc_h, inc_w = _inc_args(inc)
    d_all, packed = ell_dispatch(
        "ksp2_view_rows", _ell_all_view_rows,
        (
            state.src, state.w, state.overloaded,
            _as_device_ids(view_srcs),
            w_sv if isinstance(w_sv, jax.Array) else jnp.asarray(
                np.asarray(w_sv, dtype=np.int32)
            ),
            _as_device_ids(ep_ids),
            d_prev, inc_t, inc_h, inc_w,
        ),
        dict(bands=state.graph.bands, n=state.graph.n_pad),
    )
    if defer:
        _da.kick_async(packed)
        return d_all, packed
    return d_all, np.asarray(packed)


SOURCES_AXIS = "sources"


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def _sharded_sparse(
    src_ids, full_src, full_dst, full_w, t_src, t_dst, t_w, n, mesh
):
    def shard_fn(ids_blk, fs, fd, fw, ts, td, tw):
        s = ids_blk.shape[0]
        unit = jnp.full((s, n), INF, dtype=jnp.int32)
        unit = unit.at[jnp.arange(s), ids_blk].set(0)
        d0 = _relax(unit, fs, fd, fw, n)

        def cond(state):
            _, changed, it = state
            return jnp.logical_and(changed > 0, it < n)

        def body(state):
            d, _, it = state
            nxt = _relax(d, ts, td, tw, n)
            local = jnp.any(nxt < d).astype(jnp.int32)
            return nxt, jax.lax.psum(local, SOURCES_AXIS), it + 1

        d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
        return d

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SOURCES_AXIS),
            P(None), P(None), P(None),
            P(None), P(None), P(None),
        ),
        out_specs=P(SOURCES_AXIS, None),
    )(src_ids, full_src, full_dst, full_w, t_src, t_dst, t_w)


def sharded_sparse_all_sources(graph: SparseGraph, mesh: Mesh):
    """All-sources distances [N_pad, N_pad], source rows sharded over
    the mesh, graph as replicated edge lists. This is the 100k-node
    shape: per-device memory is O(N_pad/devices x N_pad + E) and the
    only collective is the convergence bit."""
    n = graph.n_pad
    assert n % mesh.devices.size == 0, (n, mesh.devices.size)
    src_ids = np.arange(n, dtype=np.int32)
    return _sharded_sparse(
        jnp.asarray(src_ids),
        jnp.asarray(graph.full_src),
        jnp.asarray(graph.full_dst),
        jnp.asarray(graph.full_w),
        jnp.asarray(graph.transit_src),
        jnp.asarray(graph.transit_dst),
        jnp.asarray(graph.transit_w),
        n,
        mesh,
    )


@functools.partial(jax.jit, static_argnames=("bands", "n", "mesh"))
def _sharded_ell(src_ids, srcs_t, ws_t, overloaded, bands, n, mesh):
    def shard_fn(ids_blk, srcs_r, ws_r, ov_r):
        return _ell_fixed_point(
            srcs_r, ws_r, ov_r, ids_blk, bands, n,
            vote=lambda bit: jax.lax.psum(bit, SOURCES_AXIS),
        )

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SOURCES_AXIS), P(None), P(None), P(None)),
        out_specs=P(SOURCES_AXIS, None),
    )(src_ids, srcs_t, ws_t, overloaded)


@functools.partial(jax.jit, static_argnames=("bands", "n", "mesh"))
def _sharded_ell_masked(
    srcs_t, ws_t, masks_t, overloaded, src_id, bands, n, mesh
):
    def shard_fn(*args):
        masks_blk = args[: len(masks_t)]
        srcs_r = args[len(masks_t) : 2 * len(masks_t)]
        ws_r = args[2 * len(masks_t) : 3 * len(masks_t)]
        ov_r = args[-1]
        return _ell_masked_fixed_point(
            srcs_r, ws_r, masks_blk, ov_r, src_id, bands, n,
            vote=lambda bit: jax.lax.psum(bit, SOURCES_AXIS),
        )

    nb = len(masks_t)
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS, None, None)] * nb  # masks: batch-sharded
            + [P(None, None)] * nb  # bands replicated
            + [P(None, None)] * nb
            + [P(None)]
        ),
        out_specs=P(SOURCES_AXIS, None),
    )(*masks_t, *srcs_t, *ws_t, overloaded)


def sharded_ell_masked_distances(
    graph: EllGraph, src_id: int, masks, mesh: Mesh
) -> np.ndarray:
    """The KSP2 masked batch sharded over the mesh: each device owns a
    block of DESTINATIONS (batch elements of the per-destination
    edge-masked solve, reference semantics LinkState.cpp:763
    getKthPaths); bands are replicated (O(E)), the only collective is
    the 1-bit convergence psum. This is how the KSP2 second-path
    product scales past one chip's mask-memory budget: B x slots bool
    masks divide by the mesh size. The mesh size must divide the
    batch size."""
    b = masks[0].shape[0]
    assert b % mesh.devices.size == 0, (b, mesh.devices.size)
    return np.asarray(
        _sharded_ell_masked(
            tuple(jnp.asarray(s) for s in graph.src),
            tuple(jnp.asarray(w) for w in graph.w),
            tuple(jnp.asarray(m) for m in masks),
            jnp.asarray(graph.overloaded),
            src_id,
            graph.bands,
            graph.n_pad,
            mesh,
        )
    )


def sharded_ell_all_sources(graph: EllGraph, mesh: Mesh):
    """All-sources distances [N_pad, N_pad] over the sliced-ELL bands,
    source rows sharded over the mesh, bands replicated (O(E) each —
    tiny next to the distance block). The gather+K-reduce relaxation
    runs entirely shard-local; the only collective is the 1-bit
    convergence psum per iteration, so scaling to a v4-32 mesh is
    bandwidth-trivial. Per-device memory at 100k nodes on 32 devices:
    100096/32 x 100096 x 4 B ~= 1.25 GB of distance rows."""
    n = graph.n_pad
    assert n % mesh.devices.size == 0, (n, mesh.devices.size)
    return _sharded_ell(
        jnp.asarray(np.arange(n, dtype=np.int32)),
        tuple(jnp.asarray(s) for s in graph.src),
        tuple(jnp.asarray(w) for w in graph.w),
        jnp.asarray(graph.overloaded),
        graph.bands,
        n,
        mesh,
    )


def _sharded_warm_all_pairs(
    srcs_t, ws_t, overloaded, d_prev, inc_tail, inc_head, inc_w,
    bands, n, mesh,
):
    """Warm-seeded all-pairs fixed point with source rows sharded over
    the mesh. The warm seed (_warm_seed) is row-local — its tight test
    reads whole COLUMNS of d_prev at the increase tails/heads, which
    every shard's [rows, n] block carries — so d_prev shards along the
    same axis as the solve and never moves. d_prev is NOT donated on
    this path (the sharded buffer may still back a caller-held ref;
    the single-chip dispatch keeps its donation win)."""
    nb = len(srcs_t)

    def shard_fn(ids_blk, d_prev_blk, it, ih, iw, *rest):
        srcs_r = rest[:nb]
        ws_r = rest[nb : 2 * nb]
        ov_r = rest[-1]
        return _ell_fixed_point(
            srcs_r, ws_r, ov_r, ids_blk, bands, n,
            vote=lambda bit: jax.lax.psum(bit, SOURCES_AXIS),
            warm=(d_prev_blk, it, ih, iw),
        )

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS), P(SOURCES_AXIS, None)]
            + [P(None)] * 3
            + [P(None, None)] * (2 * nb)
            + [P(None)]
        ),
        out_specs=P(SOURCES_AXIS, None),
    )(
        jnp.arange(n, dtype=jnp.int32), d_prev,
        inc_tail, inc_head, inc_w,
        *srcs_t, *ws_t, overloaded,
    )


@functools.partial(jax.jit, static_argnames=("bands", "n", "mesh"))
def _sharded_ell_all_view_rows(
    srcs_t, ws_t, overloaded, view_srcs, w_sv, ep_ids, d_prev,
    inc_tail, inc_head, inc_w, bands, n, mesh,
):
    """Mesh-sharded twin of _ell_all_view_rows: the all-pairs fixed
    point runs with source rows sharded over the mesh (1-bit psum
    vote), WARM-SEEDED from the row-sharded previous distances, and
    the view/endpoint row gathers run as global-view ops on the
    sharded matrix (XLA inserts the row collectives). d_all comes
    back SHARDED — the resident footprint per device is n^2/ndev,
    which is what lifts the KSP2 engine past the single-chip bound."""
    d_all = _sharded_warm_all_pairs(
        srcs_t, ws_t, overloaded, d_prev, inc_tail, inc_head, inc_w,
        bands, n, mesh,
    )
    d = d_all[view_srcs]
    fh = _first_hops_from_rows(d, view_srcs, w_sv, overloaded, n)
    packed = jnp.concatenate(
        [
            d,
            fh.astype(jnp.int32),
            d_all[ep_ids],
            d_prev[ep_ids],
        ],
        axis=0,
    )
    return d_all, packed


def sharded_ell_all_view_rows(
    state: "EllState", view_srcs, w_sv, ep_ids, d_prev, mesh: Mesh,
    inc=None,
):
    """Run the sharded all-sources + view + invalidation-rows dispatch
    on the resident bands. Returns (d_all_dev SHARDED, packed_host).
    ``inc`` is the increase-edge delta for warm seeding (None forces
    the cold seed — same contract as ell_all_view_rows); d_prev is NOT
    donated. n_pad must divide by the mesh size (the engine gates on
    this and falls back to the single-chip dispatch otherwise)."""
    assert state.graph.n_pad % mesh.devices.size == 0, (
        state.graph.n_pad, mesh.devices.size,
    )
    inc_t, inc_h, inc_w = _inc_args(inc)
    d_all, packed = _sharded_ell_all_view_rows(
        state.src, state.w, state.overloaded,
        _as_device_ids(view_srcs),
        w_sv if isinstance(w_sv, jax.Array) else jnp.asarray(
            np.asarray(w_sv, dtype=np.int32)
        ),
        _as_device_ids(ep_ids),
        d_prev, inc_t, inc_h, inc_w,
        state.graph.bands, state.graph.n_pad, mesh,
    )
    return d_all, jax.device_get(packed)


@functools.partial(
    jax.jit, static_argnames=("bands", "n", "k_budget", "mesh")
)
def _sharded_ell_all_view_rows_masked(
    srcs_t, ws_t, overloaded, view_srcs, w_sv, ep_ids, d_prev,
    inc_tail, inc_head, inc_w, masks_t, dm_old, d_real, src_id,
    bands, n, k_budget, mesh,
):
    """Mesh-sharded twin of _ell_all_view_rows_masked — the 1-RTT
    speculative KSP2 dispatch on-mesh. Three pieces:

      - the warm-seeded all-pairs fixed point, source rows sharded
        (see _sharded_warm_all_pairs);
      - the speculative masked second-path solve, DESTINATION batch
        sharded (each device owns D_pad/ndev masked solves over the
        replicated bands — the _sharded_ell_masked layout);
      - the row diff / budget meta / changed-row gather assembled as
        global-view ops on the sharded dm_new.

    The destination batch is padded to a mesh multiple by the caller;
    pad rows are unmasked solves whose rows move every event, so the
    diff is masked to the first ``d_real`` real rows (a device scalar:
    the pad width is a compile-time shape, the real count is not).
    Nothing is donated — matching the plain sharded dispatch (see
    _sharded_warm_all_pairs on why)."""
    nb = len(srcs_t)
    d_all = _sharded_warm_all_pairs(
        srcs_t, ws_t, overloaded, d_prev, inc_tail, inc_head, inc_w,
        bands, n, mesh,
    )
    d = d_all[view_srcs]
    fh = _first_hops_from_rows(d, view_srcs, w_sv, overloaded, n)

    def masked_fn(*args):
        masks_blk = args[:nb]
        srcs_r = args[nb : 2 * nb]
        ws_r = args[2 * nb : 3 * nb]
        ov_r = args[-1]
        return _ell_masked_fixed_point(
            srcs_r, ws_r, masks_blk, ov_r, src_id, bands, n,
            vote=lambda bit: jax.lax.psum(bit, SOURCES_AXIS),
        )

    b = masks_t[0].shape[0]
    dm_new = shard_map(
        masked_fn,
        mesh=mesh,
        in_specs=tuple(
            [P(SOURCES_AXIS, None, None)] * nb  # masks: batch-sharded
            + [P(None, None)] * (2 * nb)  # bands replicated
            + [P(None)]
        ),
        out_specs=P(SOURCES_AXIS, None),
    )(*masks_t, *srcs_t, *ws_t, overloaded)

    valid = jnp.arange(b, dtype=jnp.int32) < d_real
    row_changed = valid & jnp.any(dm_new != dm_old, axis=1)  # [D_pad]
    changed_ids = jnp.nonzero(
        row_changed, size=k_budget, fill_value=-1
    )[0].astype(jnp.int32)
    count = jnp.sum(row_changed.astype(jnp.int32))
    meta = jnp.full((n,), -1, dtype=jnp.int32)
    meta = meta.at[:k_budget].set(changed_ids)
    meta = meta.at[k_budget].set(count)
    changed_rows = dm_new[jnp.clip(changed_ids, 0, b - 1)]  # [K, n]

    packed = jnp.concatenate(
        [
            d,
            fh.astype(jnp.int32),
            d_all[ep_ids],
            d_prev[ep_ids],
            meta[None, :],
            changed_rows,
        ],
        axis=0,
    )
    return d_all, dm_new, packed


def sharded_ell_all_view_rows_masked(
    state: "EllState", view_srcs, w_sv, ep_ids, d_prev,
    masks_t, dm_old, src_id: int, k_budget: int, d_real: int,
    mesh: Mesh, inc=None,
):
    """Run the fused speculative dispatch on-mesh. Returns
    (d_all_dev SHARDED, dm_new_dev SHARDED, packed_host).
    ``d_real`` is the count of REAL destination rows in the padded
    masks batch (pad rows are excluded from the changed-row diff);
    ``inc`` as in ell_all_view_rows_masked. Unlike the single-chip
    twin nothing is donated."""
    assert state.graph.n_pad % mesh.devices.size == 0, (
        state.graph.n_pad, mesh.devices.size,
    )
    assert masks_t[0].shape[0] % mesh.devices.size == 0, (
        masks_t[0].shape[0], mesh.devices.size,
    )
    inc_t, inc_h, inc_w = _inc_args(inc)
    d_all, dm_new, packed = _sharded_ell_all_view_rows_masked(
        state.src, state.w, state.overloaded,
        _as_device_ids(view_srcs),
        w_sv if isinstance(w_sv, jax.Array) else jnp.asarray(
            np.asarray(w_sv, dtype=np.int32)
        ),
        _as_device_ids(ep_ids),
        d_prev, inc_t, inc_h, inc_w, masks_t, dm_old,
        jnp.int32(d_real), src_id,
        state.graph.bands, state.graph.n_pad, k_budget, mesh,
    )
    return d_all, dm_new, jax.device_get(packed)


def sharded_ell_masked_distances_resident(
    state: "EllState", src_id: int, masks, mesh: Mesh
):
    """Mesh-sharded twin of ell_masked_distances_resident: the KSP2
    masked batch over the RESIDENT bands with destinations sharded
    (each device owns batch/ndev masked solves). The batch size must
    divide by the mesh size (callers pad their pow2 buckets up).
    Dispatches through the same jitted _sharded_ell_masked the
    graph-argument wrapper uses — the resident tensors pass straight
    through."""
    b = masks[0].shape[0]
    assert b % mesh.devices.size == 0, (b, mesh.devices.size)
    return np.asarray(
        _sharded_ell_masked(
            state.src, state.w,
            tuple(jnp.asarray(m) for m in masks),
            state.overloaded, src_id,
            state.graph.bands, state.graph.n_pad, mesh,
        )
    )


# ---------------------------------------------------------------------------
# Batched multi-tenant worlds: uniform-ELL packing + leading-axis kernels
# ---------------------------------------------------------------------------
#
# The sliced-ELL layout above specializes its executables on the band
# structure (``bands`` is a static jit argument) — optimal for ONE
# resident graph, hostile to batching: two topologies almost never share
# a band tuple, so a [B, ...] dispatch over banded tensors would retrace
# per tenant set. The tenant plane (ops.world_batch) therefore packs
# each tenant into a UNIFORM [n_slot, k_slot] ELL block — every row
# padded to one shared slot width, the node axis padded to one shared
# count — so a whole shape bucket of tenants runs one
# [B, n_slot, k_slot] executable regardless of which tenants occupy it.
# The padding is inert by construction (self-loop src ids with w = INF,
# the same trick the banded layout uses inside a slot class), so the
# per-tenant result is bit-identical to the banded single-graph solve:
# the int32 min-relaxation has a unique fixed point and the uniform
# relax computes the same monotone map, just with more (INF) slots.


def ell_pack_uniform(
    graph: EllGraph, n_slot: int, k_slot: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Flatten a sliced-ELL graph into one uniform [n_slot, k_slot]
    block: (src, w, overloaded). Rows keep their banded ids (node
    numbering is unchanged); slots past a row's band k and rows past
    n_pad are self-loop/INF padding, inert in every relax."""
    assert n_slot >= graph.n_pad, (n_slot, graph.n_pad)
    assert k_slot >= max(b.k for b in graph.bands), k_slot
    src = np.tile(
        np.arange(n_slot, dtype=np.int32)[:, None], (1, k_slot)
    )
    w = np.full((n_slot, k_slot), INF, dtype=np.int32)
    for band, s_b, w_b in zip(graph.bands, graph.src, graph.w):
        src[band.start : band.start + band.rows, : band.k] = s_b
        w[band.start : band.start + band.rows, : band.k] = w_b
    overloaded = np.zeros(n_slot, dtype=bool)
    overloaded[: len(graph.overloaded)] = graph.overloaded
    return src, w, overloaded


def ell_uniform_rows(
    graph: EllGraph, ids, k_slot: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Uniform-layout (src, w) rows for a set of global node ids — the
    O(rows x k) host prep for scattering a patch into a resident
    uniform block (ops.world_batch's analogue of band_patch_inputs)."""
    ids = np.asarray(ids, dtype=np.int32)
    src = np.tile(ids[:, None], (1, k_slot))
    w = np.full((len(ids), k_slot), INF, dtype=np.int32)
    for x, j in enumerate(ids):
        bi, band = _band_of(graph, int(j))
        r = int(j) - band.start
        src[x, : band.k] = graph.src[bi][r]
        w[x, : band.k] = graph.w[bi][r]
    return src, w


def _uniform_relax(d, src, w, overloaded, impl=None):
    """One masked relaxation over a uniform ELL block: [S, N] -> [S, N]
    as one gather + K-reduce (the single-band special case of
    _ell_relax — identical algebra, so fixed points agree bit-for-bit).
    Edges originating at overloaded nodes never extend paths. ``impl``
    as in _ell_relax; under vmap (the world-batch tenant axis) the
    pallas band kernel batches through pallas_call's vmap rule."""
    if impl is None:
        impl = _ell_impl_for(src.shape[0], src.shape[1])
    if impl == "pallas":
        from openr_tpu.ops.pallas_ell import ell_band_relax

        # one uniform band covering every row: the kernel's output IS
        # the full [S, n] next state
        return ell_band_relax(d, src, w, overloaded, 0)
    w_eff = jnp.where(overloaded[src], INF, w)  # [n, k]
    gathered = d[:, src]  # [S, n, k]
    relaxed = jnp.min(
        jnp.minimum(gathered + w_eff[None, :, :], INF), axis=2
    )
    return jnp.minimum(d, relaxed.astype(jnp.int32))


def _uniform_direct(src, w, srcs):
    """On-device direct min-metric srcs[0] -> each batch node over a
    uniform block (INF when not adjacent, and for the source itself) —
    the uniform twin of _device_direct_metrics, so the batched dispatch
    needs no host band reads."""
    src_id = srcs[0]
    direct = jnp.min(jnp.where(src == src_id, w, INF), axis=1)  # [n]
    w_sv = direct[srcs]
    return jnp.where(srcs == src_id, INF, w_sv).astype(jnp.int32)


def _tenant_view_solve(src, w, overloaded, srcs, p_rows, p_src, p_w,
                       inc_t, inc_h, inc_w, d_prev):
    """One tenant's fused view solve over its uniform block: scatter
    the pending patch rows into the resident block (p_rows carries
    global row ids padded with the out-of-bounds id ``n`` — mode="drop"
    makes padding and idle tenants zero-cost no-ops, so patch
    application costs no extra dispatch and no extra executable),
    derive the direct metrics on device, warm-seed the fixed point
    from d_prev (reset only the increase cone — cold tenants pass the
    _FORCE_RESET_EDGE sentinel, so warm and cold share ONE executable,
    exactly like _ell_reconverge), iterate to the fixed point, pack
    distances + first hops. Shapes only — no static arguments — so
    jax.vmap lifts it to the [B, ...] tenant axis without retracing.
    Returns the post-patch (src, w) too: the caller rebinds them as
    the new resident block, keeping device and host graphs coherent
    with ONE device round trip per bucket."""
    n = src.shape[0]
    s = srcs.shape[0]
    # relax impl resolved ONCE at trace time from the uniform block's
    # (n_slot, k_slot) geometry — under vmap the shapes are the
    # per-tenant ones, so every tenant in a bucket shares one winner
    impl = _ell_impl_for(src.shape[0], src.shape[1])
    src = src.at[p_rows].set(p_src, mode="drop")
    w = w.at[p_rows].set(p_w, mode="drop")
    w_sv = _uniform_direct(src, w, srcs)
    unit = jnp.full((s, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(s), srcs].set(0)
    # init rows: one UNMASKED relax (overloaded sources still originate)
    no_overload = jnp.zeros_like(overloaded)
    d0 = _uniform_relax(unit, src, w, no_overload, impl=impl)
    seed = _warm_seed(d_prev, inc_t, inc_h, inc_w, d0)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        nxt = _uniform_relax(d, src, w, overloaded, impl=impl)
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (seed, jnp.bool_(True), 0))
    fh = _first_hops_from_rows(d, srcs, w_sv, overloaded, n)
    packed = jnp.concatenate([d, fh.astype(jnp.int32)], axis=0)
    return packed, d, src, w


# The batch-lifted solve: every argument carries a leading tenant axis
# ([B, n, k] blocks, [B, R] patch rows (+[B, R, k] values), [B, S]
# source batches, [B, E] increase deltas, [B, S, n] previous
# distances). Under vmap the while_loop iterates until EVERY tenant's
# lanes converge; extra iterations past a tenant's own fixed point are
# identity (min-relax is idempotent there), so per-tenant results never
# depend on batch composition — the padding-masking contract
# tests/test_world_batch.py enforces. Inactive slots ride along as
# all-INF blocks that converge in zero iterations. Resident inputs are
# NOT donated: the delta-readback retry (overflow -> full fallback) and
# the arbiter's rehydration path both re-read them (the same
# double-buffer hazard rule _churn_step follows). The production entry
# is route_engine.world_dispatch, which fuses this with the tenant-id
# delta compaction into one executable per shape bucket; this unfused
# alias exists for kernel-level tests.
world_view_solve = jax.jit(jax.vmap(_tenant_view_solve))
