"""Multi-tenant batched worlds: one device, many graphs, one dispatch.

A route server or controller serving real traffic runs MANY topologies
at once — areas, VRFs, what-if scenarios — while the ELL engines above
are single-graph residents. This module is the tenant plane over them:

- ``WorldManager`` — the arbiter. Tenants (independent LinkState
  worlds) are admitted into **shape buckets**: per-tenant ``n``/``k``/
  source-batch sizes rounded up to shared power-of-two slots, so every
  tenant in a bucket runs the SAME compiled executable
  (``route_engine.world_dispatch``, the ``vmap``-lifted fused view
  solve + patch scatter + delta compaction, with no shape-varying
  static arguments). Tenants joining a warm bucket cost zero retraces
  — the tenancy smoke gate asserts the compile count stays flat.

- ``WorldBucket`` — one ``[B, n_slot, k_slot]`` resident block of B
  tenant slots (uniform-ELL packing, ``spf_sparse.ell_pack_uniform``).
  A dispatch solves every slot in one device round trip
  (``route_engine.world_dispatch``, which fuses the pending patch
  scatter, the batched solve AND the delta compaction into one
  executable); inactive and idle slots are inert by construction
  (all-INF padding converges in zero iterations, and an idle slot
  re-derives its own fixed point — the min-relax is idempotent there —
  so its packed rows never change and never read back). Readback is
  per-tenant delta-compacted: the packed [B, 2S, N] block diffs
  against the resident previous block and only changed rows cross,
  prefixed by a tenant-id column (the
  ``route_engine.compact_rows_with_ids`` epilogue), fanning back out
  to B per-tenant host mirrors.

- **HBM residency** — buckets hold a fixed number of slots; when a
  bucket is full (or the global ``max_resident`` cap is exceeded) the
  least-recently-used tenant is EVICTED to its host snapshot: the host
  keeps the tenant's ``EllGraph``, its packed view mirror (which
  includes the last-solve distance rows) and its un-solved patch
  journal. Re-admission REHYDRATES warm: the uniform block re-packs
  from the graph, the previous distances upload as the warm seed, and
  the journal replays as an increase-edge delta — the first solve
  after rehydration is a warm solve, not a cold one (the
  evict→rehydrate parity test enforces both the bits and the
  warmness). This generalizes the ``SpfSolver._views`` LRU from PR 1
  from host-side view objects to device-resident engine state.

Churn stays warm exactly the way ``EllState`` keeps it warm: patches
journal (tail, head) -> (weight snapshot, current weight) with
first-touch-wins snapshots, overload flips journal the flipped node's
out-edges at raw weights, and solve time emits the effective-weight
increase delta against the snapshots the resident distances were
solved under (see ``EllState._note_patch`` / ``_emit_increases`` for
the soundness argument — the logic here is the same journal over the
host-side tenant record instead of a device-resident band set).

Observability: ``tenancy.*`` counters (active/resident/evictions/
rehydrations/bucket_compiles/... ) and an ``ops.tenant_dispatch`` span
per bucket dispatch carrying batch occupancy.
"""

from __future__ import annotations

import base64
import os
import random
import time
import weakref
from dataclasses import replace as _replace
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.faults import consume_fault, fault_point, is_device_loss
from openr_tpu.integrity import ResidentEngineContract, get_auditor
from openr_tpu.integrity import kernels as integrity_kernels
from openr_tpu.analysis.annotations import committed_dispatch, thread_confined
from openr_tpu.ops import dispatch_accounting as da
from openr_tpu.ops.route_engine import (
    FAULT_CORRUPT,
    FAULT_DEVICE_LOST,
    world_dispatch,
)
from openr_tpu.ops.spf import INF
from openr_tpu.ops.spf_sparse import (
    _FORCE_RESET_EDGE,
    EllGraph,
    band_row_edge_changes,
    compile_ell,
    ell_dispatch,
    ell_pack_uniform,
    ell_patch,
    ell_source_batch,
    ell_uniform_rows,
)
from openr_tpu.telemetry import get_profiler as _get_profiler
from openr_tpu.telemetry import get_registry as _get_registry
from openr_tpu.telemetry import get_tracer as _get_tracer

# per-dispatch increase-delta slots per tenant: ONE fixed shape (no
# pow2 ladder like pad_increase_edges — a ladder would retrace per
# bucket size and break the flat-compile contract). A tenant whose
# journal emits more increases than this takes a forced reset instead:
# still bit-identical, just cold for that one solve.
_INC_SLOTS = 64

# compacted-delta readback rows per dispatch (capped; a bigger delta
# falls back to a full-block readback, counted in delta_overflows)
_DELTA_CAP_MAX = 1024

# pending patch rows carried INTO the fused dispatch per tenant: one
# fixed [B, _PATCH_SLOTS] shape (padded with the out-of-bounds row id,
# dropped by the scatter) so patch application costs no separate
# device dispatch and no extra executable. A tenant accumulating more
# dirty rows than this between solves re-uploads its whole slot
# instead (counted in patch_overflows, never silent).
_PATCH_SLOTS = 32

TENANCY_COUNTERS = _get_registry().counter_dict(
    [
        "active",        # tenants known to the manager (gauge-like)
        "resident",      # tenants currently holding a device slot
        "admissions",    # cold admits (fresh compile_ell worlds)
        "evictions",     # resident -> host-snapshot demotions
        "rehydrations",  # host-snapshot -> warm resident promotions
        "placements",    # slot uploads of any kind (join/rehydrate/resize)
        "bucket_compiles",    # distinct shape buckets materialized
        "bucket_migrations",  # tenant moved between shape buckets
        "graph_shares",       # vantage-view packing: shared-graph reuses
        "override_solves",    # per-vantage override syncs (forced cold)
        "warm_solves",   # tenant solves seeded from previous distances
        "cold_solves",   # tenant solves from the forced-reset sentinel
        "dispatches",    # batched device dispatches (one per bucket)
        "delta_rows",        # compacted rows read back
        "delta_overflows",   # full-block readback fallbacks
        "patch_overflows",   # full-slot re-uploads (patch > row budget)
        "device_loss_recoveries",  # torn dispatches rebuilt from host
        "quarantines",       # integrity audits that poisoned the blocks
        "integrity_heals",   # warm re-placements after a quarantine
        "wave_occupancy",    # gauge-like: last wave's solving/slots pct
        "wave_joins",        # requests that joined an in-flight wave
        "wave_preemptions",  # higher-SLO requests admitted over earlier ones
        "bucket_compactions",  # vacancy-driven bucket shrinks
        "ksp2_views",        # per-tenant second-path view solves
        "park_midflight_carries",  # parked between submit and reap,
                                   # delta still applied to the mirror
        "park_midflight_resets",   # same window, but the record moved
                                   # under the dispatch: forced cold
        "tenant_exports",    # host records serialized for migration
        "tenant_imports",    # migrated records rehydrated here
        "tenant_import_colds",  # imports that could not seed warm
    ],
    prefix="tenancy.",
)

# SLO classes the serve plane stamps on tenants (serve/slo.py owns the
# class table; the tenant plane only carries the label so dispatch
# spans and counters can slice by class without importing serve)
SLO_CLASSES = ("premium", "standard", "bulk")


def _pow2_at_least(x: int, lo: int) -> int:
    p = lo
    while p < x:
        p *= 2
    return p


# Eager per-slot writer, jitted so the slot index is a RUNTIME operand:
# an inline ``buf.at[3].set(...)`` would bake the slot into the program
# and compile once per slot, breaking the flat-compile contract the
# bucket exists for. One executable per (buffer shape, value shape).
@jax.jit
def _slot_set(buf, slot, val):
    return buf.at[slot].set(val)


class TenantWorld:
    """Host-side record for one tenant: its compiled graph, source
    batch, packed-view mirror (rows [0, 2*s_slot) in bucket layout),
    and the un-solved patch journal. This IS the eviction snapshot —
    nothing device-side is needed to rehydrate warm."""

    __slots__ = (
        "tenant_id", "ls_ref", "root", "graph", "version", "srcs",
        "packed_host", "pending_edges", "pending_rows", "ov_solved",
        "pending_structural", "force_reset", "needs_solve", "solved",
        "slot", "bucket", "last_used", "srcs_dirty", "override", "slo",
    )

    def __init__(self, tenant_id: str, ls, root: str,
                 graph: EllGraph, srcs: List[int]):
        self.tenant_id = tenant_id
        self.ls_ref = weakref.ref(ls)
        self.root = root
        self.graph = graph
        self.version = ls.topology_version
        self.srcs = list(srcs)
        self.packed_host: Optional[np.ndarray] = None
        self.pending_edges: Dict[Tuple[int, int], Tuple[int, int]] = {}
        # global row ids whose device copy is stale (applied in-kernel
        # by the next fused dispatch, or subsumed by a full re-pack)
        self.pending_rows: set = set()
        self.ov_solved = np.array(graph.overloaded, copy=True)
        self.pending_structural = False
        self.force_reset = True
        self.needs_solve = True
        self.solved = False
        self.slot: Optional[int] = None
        self.bucket: Optional["WorldBucket"] = None
        self.last_used = 0
        self.srcs_dirty = True
        # vantage-local overload view ({node: overloaded}); empty =
        # the tenant sees the shared LSDB truth
        self.override: Dict[str, bool] = {}
        # SLO class label (serve plane admission ordering + span attrs)
        self.slo = "standard"

    @property
    def dims(self) -> Tuple[int, int, int]:
        """(s_slot, n_slot, k_slot) shape-bucket key this tenant
        rounds up into. The k floor is deliberately coarse (16): real
        mixed fleets mostly differ in degree, and every extra bucket
        is an extra dispatch per churn round plus an extra executable
        — a few INF slots per row are far cheaper than either."""
        return (
            _pow2_at_least(len(self.srcs), 8),
            _pow2_at_least(self.graph.n_pad, 128),
            _pow2_at_least(max(b.k for b in self.graph.bands), 16),
        )

    def view(self) -> Tuple[EllGraph, List[int], np.ndarray]:
        """(graph, srcs, packed [2b, n_pad]) in exactly the layout
        ``ell_view_batch_packed`` / ``EllState.reconverge`` return —
        sliced out of the bucket-shaped mirror, copied (the mirror
        mutates under later dispatches)."""
        assert self.packed_host is not None and self.solved
        b = len(self.srcs)
        s = self.packed_host.shape[0] // 2
        n_pad = self.graph.n_pad
        return self.graph, list(self.srcs), np.concatenate(
            [
                self.packed_host[:b, :n_pad],
                self.packed_host[s : s + b, :n_pad],
            ],
            axis=0,
        )


class WorldBucket:
    """One shape bucket's resident device block: B tenant slots of
    uniform [n_slot, k_slot] ELL plus the per-slot source batches,
    previous distances (the warm seed) and previous packed views (the
    delta-readback baseline). Invariant: ``packed_dev[slot]`` equals
    ``jnp.asarray(tenant.packed_host)`` for every occupied slot between
    dispatches — placement uploads the mirror, dispatch replaces both
    sides coherently — so the compacted diff is exact per tenant."""

    def __init__(self, slots: int, s: int, n: int, k: int):
        self.key = (s, n, k)
        self.slots, self.s, self.n, self.k = slots, s, n, k
        base_src = np.tile(
            np.arange(n, dtype=np.int32)[None, :, None], (slots, 1, k)
        )
        self.src_dev = jnp.asarray(base_src)
        self.w_dev = jnp.asarray(
            np.full((slots, n, k), INF, dtype=np.int32)
        )
        self.ov_dev = jnp.asarray(np.zeros((slots, n), dtype=bool))
        self.srcs_dev = jnp.asarray(
            np.zeros((slots, s), dtype=np.int32)
        )
        self.d_dev = jnp.asarray(
            np.zeros((slots, s, n), dtype=np.int32)
        )
        self.packed_dev = jnp.asarray(
            np.zeros((slots, 2 * s, n), dtype=np.int32)
        )
        self.tenants: List[Optional[TenantWorld]] = [None] * slots
        self.delta_cap = min(slots * 2 * s, _DELTA_CAP_MAX)

    def free_slot(self) -> Optional[int]:
        for i, t in enumerate(self.tenants):
            if t is None:
                return i
        return None

    def occupancy(self) -> int:
        return sum(1 for t in self.tenants if t is not None)


# externally serialized, never internally locked: the serve plane
# drives its manager only under SolverService._mgr_lock, and every
# other instance (tenancy tests, twin replay) lives on one thread.
# The rule merges all instances by class, so cross-role access to one
# instance is impossible by construction — hence "owner" confinement.
@thread_confined(
    "owner",
    "_buckets",
    "_clock",
    "_corrupt_events",
    "_graph_share",
    "_patch_share",
    "_slo_classes",
    "_tenants",
)
class WorldManager(ResidentEngineContract):
    """The residency arbiter + dispatch front end (see module
    docstring). One per process by default (``get_world_manager``) —
    the device blocks it owns are process-global state, like the
    ``_ELL_RESIDENT`` cache in decision.spf_solver."""

    audit_kind = "world_batch"

    def __init__(self, slots_per_bucket: Optional[int] = None,
                 max_resident: Optional[int] = None):
        if slots_per_bucket is None:
            slots_per_bucket = int(
                os.environ.get("OPENR_WORLD_SLOTS", "8") or 8
            )
        if max_resident is None:
            max_resident = int(
                os.environ.get("OPENR_WORLD_RESIDENT", "64") or 64
            )
        self.slots_per_bucket = _pow2_at_least(
            max(1, slots_per_bucket), 1
        )
        self.max_resident = max(1, max_resident)
        self._buckets: Dict[Tuple[int, int, int], WorldBucket] = {}
        self._tenants: Dict[str, TenantWorld] = {}
        # vantage-view packing: tenants viewing the SAME LinkState share
        # one compiled EllGraph (and one journaled patch per version
        # transition) instead of paying compile_ell/ell_patch N times —
        # the fleet-twin admission path. Weakly keyed so a dead
        # LinkState never pins its graphs.
        self._graph_share: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self._patch_share: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self._clock = 0
        self._corrupt_events = 0
        # SLO class labels survive drop/re-admit (a client's class is
        # a property of the tenant NAME, assigned at registration)
        self._slo_classes: Dict[str, str] = {}
        get_auditor().register(self)

    # -- public API --------------------------------------------------------

    def solve_views(self, items) -> List[Tuple]:
        """Sync + batch-solve a set of tenants in as few dispatches as
        buckets allow. ``items``: [(tenant_id, ls, root)] — or
        4-tuples [(tenant_id, ls, root, override)] where ``override``
        is a vantage-local {node: overloaded} view layered over the
        shared LSDB (the twin's per-node what-if seam). Returns the
        aligned [(graph, srcs, packed [2b, n_pad])] views. More
        requested tenants than a bucket has slots are solved in waves
        (each wave fills the bucket, solves, and yields its slots to
        the next — eviction/rehydration do the bookkeeping)."""
        tenants = []
        for item in items:
            tid, ls, root = item[0], item[1], item[2]
            override = item[3] if len(item) > 3 else None
            tenants.append(self._sync(tid, ls, root, override))
        pending = [t for t in tenants if t.needs_solve]
        with da.event_window("world_window"):
            self._solve_waves(tenants, pending)
        self._enforce_residency()
        self._update_gauges()
        # the corruption seam sits AFTER the dispatches settle: a bit
        # flipped pre-dispatch would be washed by world_dispatch's
        # wholesale packed/d replacement and never model the silent
        # between-solves decay the audit plane exists to catch
        if consume_fault(FAULT_CORRUPT):
            self._corrupt_events += 1
            self.corrupt_resident(self._corrupt_events)
        return [t.view() for t in tenants]

    def _solve_waves(self, tenants, pending) -> None:
        """The wave loop of ``solve_views``, factored out so the whole
        multi-wave solve runs under ONE committed accounting window
        (``ops.host_touches.world_window``)."""
        waves = 0
        recoveries = 0
        while pending:
            waves += 1
            assert (
                waves <= 2 * len(tenants) + 2 + 2 * recoveries
            ), "tenancy livelock"
            for t in pending:
                self._ensure_resident(t)
            # launch every bucket's fused solve before blocking on the
            # first readback: dispatches are async, so bucket B's
            # compute overlaps bucket A's delta fan-out
            try:
                ctxs = [
                    self._dispatch_launch(bucket)
                    for bucket in {t.bucket for t in pending if t.bucket}
                ]
                ctxs = [ctx for ctx in ctxs if ctx is not None]
                if len(ctxs) > 1:
                    da.note_pipelined_dispatch(len(ctxs))
                for i, ctx in enumerate(ctxs):
                    if i + 1 < len(ctxs):
                        da.note_overlapped_reap()
                    self._dispatch_finish(ctx)
            except Exception as exc:  # noqa: BLE001 - loss triage below
                if not is_device_loss(exc) or recoveries >= 2:
                    raise
                recoveries += 1
                self._recover_device_loss()
            pending = [t for t in pending if t.needs_solve]

    def solve_view(self, tenant_id: str, ls, root: str,
                   override: Optional[Dict[str, bool]] = None):
        return self.solve_views([(tenant_id, ls, root, override)])[0]

    def solve_views_pipelined(self, batches) -> List[List[Tuple]]:
        """Pipelined multi-batch front end: batch i+1's bucket
        dispatches are SUBMITTED before batch i's readbacks are
        reaped, so the whole burst of solve waves costs one drain of
        host turnarounds instead of one per batch. ``batches`` is a
        sequence of ``solve_views`` item lists; returns the aligned
        per-batch view lists, bit-identical to calling ``solve_views``
        per batch in order.

        Hazard rule (the slot-reuse seam): a batch whose placement or
        re-dispatch would touch a bucket with an in-flight readback
        drains the pipeline first — an eviction or journal re-emission
        under an unreaped dispatch would misattribute the compacted
        delta fan-out. Same-ls batches therefore pipeline only when
        their tenants land in disjoint shape buckets; the degenerate
        sequential order is always correct, never silent (the drain
        just shortens)."""
        batches = [list(b) for b in batches]
        results: List[Optional[List[Tuple]]] = [None] * len(batches)
        if not batches:
            return []
        with da.pipeline_drain("world_drain"):
            # in-flight entries: (batch index, synced tenants, launch
            # contexts whose readbacks have not been reaped yet)
            inflight: List[Tuple[int, list, list]] = []
            try:
                for bi, items in enumerate(batches):
                    tenants = []
                    for item in items:
                        tid, ls, root = item[0], item[1], item[2]
                        override = item[3] if len(item) > 3 else None
                        tenants.append(
                            self._sync(tid, ls, root, override)
                        )
                    pending = [t for t in tenants if t.needs_solve]
                    busy = {
                        id(ctx[0])
                        for _pbi, _tn, ctxs in inflight
                        for ctx in ctxs
                    }
                    if busy and any(
                        id(self._buckets.get(t.dims)) in busy
                        or (t.bucket is not None and id(t.bucket) in busy)
                        for t in pending
                    ):
                        self._drain_inflight(inflight, results)
                    for t in pending:
                        self._ensure_resident(t)
                    if any(t.slot is None for t in pending):
                        # a batch wider than its bucket needs the
                        # multi-wave loop; that loop reuses slots, so
                        # it owns the whole device alone
                        self._drain_inflight(inflight, results)
                        self._solve_waves(tenants, pending)
                        results[bi] = [t.view() for t in tenants]
                        da.note_window()
                        continue
                    ctxs = [
                        ctx
                        for ctx in (
                            self._dispatch_launch(bucket)
                            for bucket in {
                                t.bucket for t in pending if t.bucket
                            }
                        )
                        if ctx is not None
                    ]
                    if ctxs and inflight:
                        da.note_pipelined_dispatch(len(inflight) + 1)
                    inflight.append((bi, tenants, ctxs))
                    da.note_window()
                self._drain_inflight(inflight, results)
            except Exception as exc:  # noqa: BLE001 - loss triage below
                if not is_device_loss(exc):
                    raise
                # the in-flight contexts died with the device; recovery
                # demotes everyone to host snapshots and the stragglers
                # re-solve sequentially below (warm rehydration)
                inflight.clear()
                self._recover_device_loss()
        for bi, items in enumerate(batches):
            if results[bi] is None:
                results[bi] = self.solve_views(items)
        self._enforce_residency()
        self._update_gauges()
        return results

    def _drain_inflight(self, inflight, results) -> None:
        """Reap every in-flight launch in submission order and settle
        its batch's views. Reaps drained while later batches' launches
        are still in flight are the double-buffer overlap the
        accounting witnesses."""
        while inflight:
            bi, tenants, ctxs = inflight.pop(0)
            for ctx in ctxs:
                if inflight:
                    da.note_overlapped_reap()
                self._dispatch_finish(ctx)
            results[bi] = [t.view() for t in tenants]

    def ksp2_view(self, tenant_id: str, dsts: Sequence[str]):
        """Second-path (KSP2) view for a SOLVED tenant: first paths
        traced from the resident SP view's root distance row, per-dst
        edge masks over the first paths' links, ONE batched masked
        device solve per pow2 chunk (``ell_masked_distances`` — rides
        the committed ``ksp2_masked_host`` AOT executable, so warm
        waves never retrace), second paths traced from the masked rows.
        Returns ``{dst: [first_paths..., second_paths...]}`` in exactly
        ``ls.get_kth_paths(root, dst, 1) + (…, 2)`` layout (byte-equal
        traces: same canonical predecessor order). Destinations whose
        exclusion set is unrepresentable in the packed layout fall back
        to the host oracle — deterministic, never silent (counted in
        ``tenancy.ksp2_host_fallbacks``)."""
        from openr_tpu.decision.ksp2_engine import (
            make_cands_of,
            trace_paths_from_row,
        )
        from openr_tpu.ops import spf_sparse

        t = self._tenants[tenant_id]
        ls = t.ls_ref()
        if ls is None or not t.solved or t.needs_solve:
            raise RuntimeError(
                f"ksp2_view({tenant_id!r}) requires a settled solve"
            )
        graph, srcs, packed = t.view()
        root = t.root
        sid = srcs[0]
        d_base = packed[0].astype(np.int64)
        cands_of = make_cands_of(ls, graph.node_index)
        transit_blocked = {
            name
            for name in graph.node_names
            if ls.is_node_overloaded(name) and name != root
        }
        out: Dict[str, List] = {}
        excl: Dict[str, set] = {}
        preds_cache: Dict[str, list] = {}
        for dst in dsts:
            firsts = trace_paths_from_row(
                root, dst, graph.node_index, d_base, set(),
                cands_of, transit_blocked, preds_cache,
            )
            out[dst] = list(firsts)
            excl[dst] = {l for p in firsts for l in p}
        TENANCY_COUNTERS["ksp2_views"] += 1
        par = (
            ls.parallel_pairs() if graph.slot_of is None else None
        )
        host_fallbacks = 0
        order = list(dsts)
        for start in range(0, len(order), 64):
            batch = order[start : start + 64]
            bucket = 8
            while bucket < len(batch):
                bucket *= 2
            pad = bucket - len(batch)
            masks, ok = spf_sparse.build_edge_masks(
                graph, [excl[d] for d in batch] + [set()] * pad, par
            )
            drows = spf_sparse.ell_masked_distances(graph, sid, masks)
            for i, dst in enumerate(batch):
                if not ok[i]:
                    host_fallbacks += 1
                    out[dst] = ls.get_kth_paths(
                        root, dst, 1
                    ) + ls.get_kth_paths(root, dst, 2)
                    continue
                out[dst] = out[dst] + trace_paths_from_row(
                    root, dst, graph.node_index,
                    drows[i].astype(np.int64), excl[dst],
                    cands_of, transit_blocked,
                )
        if host_fallbacks:
            _get_registry().counter_bump(
                "tenancy.ksp2_host_fallbacks", host_fallbacks
            )
        return out

    def drop(self, tenant_id: str) -> None:
        t = self._tenants.pop(tenant_id, None)
        if t is not None and t.slot is not None:
            self._detach(t)
        self._update_gauges()

    def park(self, tenant_id: str) -> None:
        """Warm detach: free the tenant's device slot but KEEP its host
        record (mirror + journal), so a later solve rehydrates warm.
        The serve plane's client-disconnect path — a vanished client
        must not poison the bucket its tenants shared, and must not
        cold-solve if it reconnects."""
        t = self._tenants.get(tenant_id)
        if t is not None and t.slot is not None:
            self._detach(t)
        self._update_gauges()

    # -- live migration (fleet plane) --------------------------------------

    def export_tenant(self, tenant_id: str) -> Dict[str, object]:
        """Serialize a tenant's host record for live migration: the
        packed mirror, the un-replayed journal tail, and the solve
        flags — everything ``import_tenant`` needs to rehydrate WARM
        on another manager. The record is valid on the far side
        because ``compile_ell`` is deterministic: a LinkState rebuilt
        from the same adjacency content reproduces the numbering the
        mirror and journal are expressed in. The tenant is parked
        first (slot freed) so the record cannot race a resident
        dispatch; the CALLER owns draining any in-flight wave before
        exporting (the serve plane's quiesce)."""
        t = self._tenants.get(tenant_id)
        if t is None:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        if t.slot is not None:
            self._detach(t)
            self._update_gauges()
        rec: Dict[str, object] = {
            "tenant_id": t.tenant_id,
            "root": t.root,
            "srcs": [int(s) for s in t.srcs],
            "slo": self._slo_classes.get(tenant_id, t.slo),
            "solved": bool(t.solved),
            "needs_solve": bool(t.needs_solve),
            "force_reset": bool(t.force_reset),
            "pending_structural": bool(t.pending_structural),
            "override": dict(t.override),
            "pending_rows": sorted(int(r) for r in t.pending_rows),
            "pending_edges": [
                [int(s), int(h), int(snap), int(cur)]
                for (s, h), (snap, cur) in sorted(
                    t.pending_edges.items()
                )
            ],
            "ov_solved_b64": base64.b64encode(
                np.ascontiguousarray(
                    t.ov_solved, dtype=bool
                ).tobytes()
            ).decode("ascii"),
            "packed_host": None,
        }
        if t.packed_host is not None:
            ph = np.ascontiguousarray(t.packed_host, dtype=np.int32)
            rec["packed_host"] = {
                "shape": list(ph.shape),
                "b64": base64.b64encode(ph.tobytes()).decode("ascii"),
            }
        TENANCY_COUNTERS["tenant_exports"] += 1
        return rec

    def import_tenant(self, ls, record: Dict[str, object]) -> TenantWorld:
        """Rehydrate an exported record against ``ls`` (a LinkState
        rebuilt from the same adjacency content the exporter held).
        The shipped mirror seeds the next placement warm — the first
        post-migration solve is a warm solve with zero compiles, the
        live-migration no-cold-solve contract. A record whose source
        batch no longer matches (content drift between export and
        import) degrades to a cold admission: bits stay correct, the
        miss is counted (``tenancy.tenant_import_colds``), never
        silent."""
        tid = str(record["tenant_id"])
        self.drop(tid)
        root = str(record["root"])
        graph = self._shared_graph(ls)
        srcs = ell_source_batch(graph, ls, root)
        t = TenantWorld(tid, ls, root, graph, srcs)
        self._tenants[tid] = t
        slo = str(record.get("slo", "standard"))
        self._slo_classes[tid] = slo
        t.slo = slo
        t.version = ls.topology_version
        TENANCY_COUNTERS["admissions"] += 1
        TENANCY_COUNTERS["tenant_imports"] += 1
        ph = record.get("packed_host")
        warm = (
            bool(record.get("solved"))
            and isinstance(ph, dict)
            and [int(s) for s in record.get("srcs", [])]
            == [int(s) for s in srcs]
        )
        if not warm:
            TENANCY_COUNTERS["tenant_import_colds"] += 1
            self._update_gauges()
            return t
        shape = tuple(int(x) for x in ph["shape"])
        t.packed_host = (
            np.frombuffer(base64.b64decode(ph["b64"]), dtype=np.int32)
            .reshape(shape)
            .copy()
        )
        t.ov_solved = np.frombuffer(
            base64.b64decode(record["ov_solved_b64"]), dtype=bool
        ).copy()
        t.pending_edges = {
            (int(s), int(h)): (int(snap), int(cur))
            for s, h, snap, cur in record.get("pending_edges", [])
        }
        t.pending_rows = {
            int(r) for r in record.get("pending_rows", [])
        }
        t.pending_structural = bool(record.get("pending_structural"))
        t.force_reset = bool(record.get("force_reset"))
        t.needs_solve = bool(record.get("needs_solve"))
        t.solved = True
        t.override = {
            str(k): bool(v)
            for k, v in (record.get("override") or {}).items()
        }
        if t.override:
            # a vantage-local override diverges from the shared LSDB
            # truth; the shipped journal cannot vouch for it here —
            # same forced-cold rule as _apply_override
            t.force_reset = True
            t.needs_solve = True
        self._update_gauges()
        return t

    def set_slo_class(self, tenant_id: str, slo: str) -> None:
        """Stamp a tenant's SLO class (serve plane admission input).
        Sticky across drop/re-admit; unknown class names are rejected
        here so a typo never silently lands in ``standard``."""
        if slo not in SLO_CLASSES:
            raise ValueError(f"unknown SLO class: {slo!r}")
        self._slo_classes[tenant_id] = slo
        t = self._tenants.get(tenant_id)
        if t is not None:
            t.slo = slo

    def slo_class(self, tenant_id: str) -> str:
        return self._slo_classes.get(tenant_id, "standard")

    def reset(self) -> None:
        """Release every device block and tenant record (the
        degradation ladder's cold rung — nothing cached across a torn
        dispatch may leak into the recovered state)."""
        self._buckets = {}
        self._tenants = {}
        self._graph_share = weakref.WeakKeyDictionary()
        self._patch_share = weakref.WeakKeyDictionary()
        self._update_gauges()

    def _recover_device_loss(self) -> None:
        """Device-loss fault boundary: every resident block is suspect,
        so demote every tenant to its host snapshot and drop the device
        buckets. The mirrors and journals are pre-dispatch state —
        ``_dispatch_finish`` settles them only on success, so a torn
        dispatch leaves nothing half-committed on the host — and the
        next wave re-places each pending tenant from ``packed_host``
        (a warm rehydration, not a cold solve). Never silent: counted
        in ``tenancy.device_loss_recoveries`` + ``recovery.device_lost``."""
        for t in self._tenants.values():
            if t.slot is not None:
                self._detach(t)
        self._buckets = {}
        TENANCY_COUNTERS["device_loss_recoveries"] += 1
        _get_registry().counter_bump("recovery.device_lost")

    def resident_count(self) -> int:
        return sum(
            1 for t in self._tenants.values() if t.slot is not None
        )

    def bucket_count(self) -> int:
        return len(self._buckets)

    # -- sync / journal ----------------------------------------------------

    def _sync(self, tenant_id: str, ls, root: str,
              override: Optional[Dict[str, bool]] = None) -> TenantWorld:
        self._clock += 1
        t = self._tenants.get(tenant_id)
        if t is not None and (t.ls_ref() is not ls or t.root != root):
            # a new world under an old name: identity goes through the
            # live object, never id()/name reuse
            self.drop(tenant_id)
            t = None
        if t is None:
            graph = self._shared_graph(ls)
            t = TenantWorld(
                tenant_id, ls, root, graph,
                ell_source_batch(graph, ls, root),
            )
            self._tenants[tenant_id] = t
            t.slo = self._slo_classes.get(tenant_id, "standard")
            TENANCY_COUNTERS["admissions"] += 1
        elif t.version != ls.topology_version:
            shared = self._shared_patched(t, ls)
            if shared is None:
                # journal gap or node-set change: recompile from the
                # LinkState; numbering may move, so the old mirror and
                # journal are unusable — cold solve
                graph = self._shared_graph(ls)
                self._reset_world(
                    t, graph, ell_source_batch(graph, ls, root)
                )
            else:
                patched, stripped = shared
                self._apply_patch(t, patched, stripped)
                srcs = ell_source_batch(t.graph, ls, root)
                if srcs != t.srcs:
                    # the source batch moved (neighbor set churn):
                    # same contract as EllState._warm_key — previous
                    # distance rows describe other sources, force the
                    # cold seed
                    t.srcs = list(srcs)
                    t.srcs_dirty = True
                    t.force_reset = True
            t.version = ls.topology_version
            t.needs_solve = True
        self._apply_override(t, ls, override)
        t.last_used = self._clock
        return t

    # -- vantage-view packing ----------------------------------------------

    def _shared_graph(self, ls) -> EllGraph:
        """Version-current compiled EllGraph for ``ls``, shared across
        every tenant viewing the same world: a fleet twin admitting N
        vantages pays ONE ``compile_ell``, and the shared object
        identity is what lets ``_shared_patched`` share the per-version
        patch across those tenants afterwards."""
        entry = self._graph_share.get(ls)
        if entry is not None and entry[0] == ls.topology_version:
            TENANCY_COUNTERS["graph_shares"] += 1
            return entry[1]
        graph = compile_ell(ls)
        self._graph_share[ls] = (ls.topology_version, graph)
        return graph

    def _shared_patched(self, t: TenantWorld, ls):
        """One journaled ``ell_patch`` per (ls, version transition,
        base graph), shared by every tenant whose graph IS that base —
        the common fleet case where all vantages sync in lockstep.
        Returns ``(patched, stripped)`` (with/without the ``changed``
        row map) or None when the journal has a gap or the node set
        moved (caller recompiles via ``_shared_graph``). The stripped
        twin is cached alongside so sharing tenants land on the SAME
        object identity and keep hitting this cache next transition."""
        entries = self._patch_share.get(ls)
        if entries:
            for fv, tv, base, patched, stripped in entries:
                if (
                    fv == t.version
                    and tv == ls.topology_version
                    and base is t.graph
                ):
                    TENANCY_COUNTERS["graph_shares"] += 1
                    return patched, stripped
        affected = ls.affected_since(t.version)
        patched = (
            ell_patch(t.graph, ls, sorted(affected), widen=True)
            if affected is not None
            else None
        )
        if patched is None:
            return None
        stripped = _replace(patched, changed=None)
        # bounded FIFO per ls: staggered fleets (vantages at mixed
        # versions) keep a few transitions live without thrash
        entries = list(entries or [])[-3:]
        entries.append(
            (t.version, ls.topology_version, t.graph, patched, stripped)
        )
        self._patch_share[ls] = entries
        return patched, stripped

    def _base_overloaded(self, t: TenantWorld, ls) -> np.ndarray:
        """The ls-truth overload vector in ``t.graph``'s numbering —
        the baseline per-vantage overrides fold into (and restore
        from)."""
        entry = self._graph_share.get(ls)
        if (
            entry is not None
            and entry[0] == ls.topology_version
            and len(entry[1].overloaded) == len(t.graph.overloaded)
        ):
            return np.array(entry[1].overloaded, copy=True)
        adj = ls.get_adjacency_databases()
        base = np.array(t.graph.overloaded, copy=True)
        for node, i in t.graph.node_index.items():
            db = adj.get(node)
            if db is not None and i < len(base):
                base[i] = bool(db.is_overloaded)
        return base

    def _apply_override(self, t: TenantWorld, ls,
                        override: Optional[Dict[str, bool]]) -> None:
        """Per-node override: a vantage-local overload view layered
        over the shared LSDB (the twin's what-if drain seam). A tenant
        with an active override always solves via the forced-reset
        sentinel — same executable, same dispatch wave, never a
        retrace — because the warm-start journal argues soundness
        against the SHARED overload state, which an override
        deliberately diverges from."""
        ov_map = {str(k): bool(v) for k, v in (override or {}).items()}
        changed = ov_map != t.override
        if changed:
            t.override = ov_map
            t.needs_solve = True
        if not ov_map and not changed:
            return
        ov = self._base_overloaded(t, ls)
        idx = t.graph.node_index
        for node, flag in ov_map.items():
            i = idx.get(node)
            if i is not None and i < len(ov):
                ov[i] = flag
        if not np.array_equal(ov, np.asarray(t.graph.overloaded)):
            t.graph = _replace(t.graph, overloaded=ov)
            if t.slot is not None and t.bucket is not None:
                full = np.zeros(t.bucket.n, dtype=bool)
                full[: len(ov)] = ov
                t.bucket.ov_dev = _slot_set(
                    t.bucket.ov_dev, np.int32(t.slot), full
                )
        # overridden OR just-restored state: the journal cannot vouch
        # for either transition, so the next solve is cold
        t.force_reset = True
        if t.needs_solve:
            TENANCY_COUNTERS["override_solves"] += 1

    def _reset_world(self, t: TenantWorld, graph: EllGraph,
                     srcs: List[int]) -> None:
        old_dims = t.dims
        t.graph = graph
        t.srcs = list(srcs)
        t.packed_host = None
        t.pending_edges = {}
        t.pending_rows = set()
        t.ov_solved = np.array(graph.overloaded, copy=True)
        t.pending_structural = False
        t.force_reset = True
        t.solved = False
        t.srcs_dirty = True
        if t.slot is not None and t.dims != old_dims:
            self._detach(t)

    def _apply_patch(self, t: TenantWorld, patched: EllGraph,
                     stripped: Optional[EllGraph] = None) -> None:
        ov_changed = not np.array_equal(
            t.graph.overloaded, patched.overloaded
        )
        self._journal_patch(t, patched, ov_changed)
        rows = sorted(
            int(patched.bands[bi].start) + int(r)
            for bi, rs in (patched.changed or {}).items()
            for r in np.asarray(rs)
        )
        old_dims = t.dims
        # the caller-provided stripped twin keeps same-ls tenants on
        # ONE graph object (vantage-view packing's identity contract)
        t.graph = (
            stripped if stripped is not None
            else _replace(patched, changed=None)
        )
        # changed rows go STALE on device and ride the next fused
        # dispatch as in-kernel scatter operands (placement's full
        # re-pack subsumes them for non-residents and migrants)
        t.pending_rows.update(rows)
        if t.slot is None:
            return  # non-resident: placement re-packs from the graph
        if t.dims != old_dims:
            # a widened row outgrew the bucket's k: migrate (the warm
            # mirror + journal move with the tenant — placement decides
            # whether the shapes still permit a warm seed)
            self._detach(t)
            TENANCY_COUNTERS["bucket_migrations"] += 1
            return
        bucket = t.bucket
        if ov_changed:
            ov = np.zeros(bucket.n, dtype=bool)
            ov[: len(t.graph.overloaded)] = t.graph.overloaded
            bucket.ov_dev = _slot_set(
                bucket.ov_dev, np.int32(t.slot), ov
            )

    def _journal_patch(self, t: TenantWorld, patched: EllGraph,
                       ov_changed: bool) -> None:
        """EllState._note_patch over the host tenant record: merge the
        patch's edge delta into the warm-start journal (first-touch
        snapshots), journal flipped nodes' out-edges across an
        overload change. Skipped before the first solve — there is
        nothing warm to protect yet."""
        if not t.solved:
            return
        if ov_changed:
            t.pending_structural = True
            flipped = np.nonzero(
                np.asarray(t.graph.overloaded)
                != np.asarray(patched.overloaded)
            )[0]
            collapsed: Dict[Tuple[int, int], int] = {}
            pos = 0
            for src_b, w_b in zip(t.graph.src, t.graph.w):
                hit = np.isin(src_b, flipped) & (w_b < INF)
                for r, sl in zip(*np.nonzero(hit)):
                    key = (int(src_b[r, sl]), pos + int(r))
                    wv = int(w_b[r, sl])
                    if wv < collapsed.get(key, INF):
                        collapsed[key] = wv
                pos += src_b.shape[0]
            for key, wv in collapsed.items():
                t.pending_edges.setdefault(key, (wv, wv))
        if not patched.changed:
            return
        structural = False
        for s, h, wo, wn in band_row_edge_changes(t.graph, patched):
            snap, _cur = t.pending_edges.get((s, h), (wo, wo))
            t.pending_edges[(s, h)] = (snap, wn)
            structural = structural or wo >= INF or wn >= INF
        if structural:
            t.pending_structural = True

    def _emit_increases(self, t: TenantWorld, ov_now: np.ndarray):
        """EllState._emit_increases over the tenant journal (same
        effective-weight soundness argument)."""
        inc = []
        for (s, h), (snap, cur) in t.pending_edges.items():
            if snap >= INF:
                continue
            snap_eff = INF if t.ov_solved[s] else snap
            cur_eff = INF if ov_now[s] else cur
            if cur > snap or cur_eff > snap_eff:
                inc.append((s, h, snap))
        return inc

    # -- placement / residency ---------------------------------------------

    def _bucket_for(self, dims: Tuple[int, int, int]) -> WorldBucket:
        bucket = self._buckets.get(dims)
        if bucket is None:
            bucket = WorldBucket(self.slots_per_bucket, *dims)
            self._buckets[dims] = bucket
            TENANCY_COUNTERS["bucket_compiles"] += 1
        return bucket

    def _ensure_resident(self, t: TenantWorld) -> None:
        dims = t.dims
        if (
            t.slot is not None
            and t.bucket is not None
            and t.bucket.key == dims
        ):
            return
        if t.slot is not None:
            self._detach(t)
            TENANCY_COUNTERS["bucket_migrations"] += 1
        bucket = self._bucket_for(dims)
        slot = bucket.free_slot()
        if slot is None and bucket.slots < self.slots_per_bucket:
            # a previously compacted bucket refilled: grow it back
            # toward the configured width before evicting anyone
            bucket = self._resize_bucket(bucket, bucket.slots * 2)
            slot = bucket.free_slot()
        if slot is None:
            slot = self._evict_lru(bucket)
        self._place(t, bucket, slot)

    def _resize_bucket(self, bucket: WorldBucket,
                       slots: int) -> WorldBucket:
        """Replace a bucket with a ``slots``-wide twin and warm
        re-place its occupants (mirror + journal ride along — same
        upload path as rehydration, so bits are preserved). A resized
        block is a NEW dispatch shape: the executable for the new B
        compiles once (counted in ``bucket_compiles``), which is why
        compaction only fires past a real vacancy threshold."""
        fresh = WorldBucket(slots, *bucket.key)
        self._buckets[bucket.key] = fresh
        TENANCY_COUNTERS["bucket_compiles"] += 1
        occupants = [t for t in bucket.tenants if t is not None]
        for t in occupants:
            self._detach(t)
        for t in occupants:
            self._place(t, fresh, fresh.free_slot())
        return fresh

    def compact_buckets(self, vacancy: float = 0.5) -> int:
        """Occupancy-sized dispatch: shrink every bucket whose vacancy
        exceeds ``vacancy`` down to the power-of-two width that fits
        its occupants (empty buckets are dropped outright), so a
        half-empty fleet stops paying full-width solves. Returns the
        number of buckets compacted. The serve plane calls this
        between waves; callers that never compact keep the old
        fixed-width behavior."""
        compacted = 0
        for key in sorted(self._buckets):
            bucket = self._buckets[key]
            occ = bucket.occupancy()
            if occ == 0:
                del self._buckets[key]
                compacted += 1
                TENANCY_COUNTERS["bucket_compactions"] += 1
                continue
            target = _pow2_at_least(occ, 1)
            if target >= bucket.slots or occ > bucket.slots * (
                1.0 - vacancy
            ):
                continue
            self._resize_bucket(bucket, target)
            compacted += 1
            TENANCY_COUNTERS["bucket_compactions"] += 1
        return compacted

    def _place(self, t: TenantWorld, bucket: WorldBucket,
               slot: int) -> None:
        s_slot, n_slot, k_slot = bucket.key
        mirror_shape = (2 * s_slot, n_slot)
        if t.packed_host is None or t.packed_host.shape != mirror_shape:
            # no (shape-compatible) previous view: the warm seed has
            # nothing sound to start from
            t.packed_host = np.zeros(mirror_shape, dtype=np.int32)
            t.force_reset = True
            t.solved = False
        elif t.solved:
            TENANCY_COUNTERS["rehydrations"] += 1
        TENANCY_COUNTERS["placements"] += 1
        src, w, ov = ell_pack_uniform(t.graph, n_slot, k_slot)
        srcs_row = np.full(s_slot, t.srcs[0], dtype=np.int32)
        srcs_row[: len(t.srcs)] = t.srcs
        sl = np.int32(slot)
        bucket.src_dev = _slot_set(bucket.src_dev, sl, src)
        bucket.w_dev = _slot_set(bucket.w_dev, sl, w)
        bucket.ov_dev = _slot_set(bucket.ov_dev, sl, ov)
        bucket.srcs_dev = _slot_set(bucket.srcs_dev, sl, srcs_row)
        bucket.d_dev = _slot_set(
            bucket.d_dev, sl, t.packed_host[:s_slot]
        )
        bucket.packed_dev = _slot_set(
            bucket.packed_dev, sl, t.packed_host
        )
        bucket.tenants[slot] = t
        t.bucket = bucket
        t.slot = slot
        t.srcs_dirty = False
        t.pending_rows = set()  # the full pack above subsumed them

    def _detach(self, t: TenantWorld) -> None:
        """Demote to the host snapshot. The vacated slot's device rows
        stay in place — an unoccupied slot re-solves its stale fixed
        point idempotently (no packed change, no readback) until the
        next occupant's placement overwrites it."""
        if t.bucket is not None and t.slot is not None:
            t.bucket.tenants[t.slot] = None
        t.bucket = None
        t.slot = None

    def _evict_lru(self, bucket: WorldBucket) -> int:
        victims = [
            (t.last_used, slot)
            for slot, t in enumerate(bucket.tenants)
            if t is not None
        ]
        _, slot = min(victims)
        self._detach(bucket.tenants[slot])
        TENANCY_COUNTERS["evictions"] += 1
        return slot

    def _enforce_residency(self) -> None:
        while self.resident_count() > self.max_resident:
            t = min(
                (
                    t
                    for t in self._tenants.values()
                    if t.slot is not None
                ),
                key=lambda t: t.last_used,
            )
            self._detach(t)
            TENANCY_COUNTERS["evictions"] += 1

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, bucket: WorldBucket) -> None:
        ctx = self._dispatch_launch(bucket)
        if ctx is not None:
            self._dispatch_finish(ctx)

    @committed_dispatch
    def _dispatch_launch(self, bucket: WorldBucket):
        """Phase 1 of a bucket dispatch: journal emission, patch-operand
        prep, and the (async) fused device call. Returns the in-flight
        context for _dispatch_finish, which owns the blocking readback
        — solve_views launches EVERY bucket before finishing the first,
        so bucket B's solve overlaps bucket A's readback and host
        fan-out instead of serializing on it."""
        solving = [
            (slot, t)
            for slot, t in enumerate(bucket.tenants)
            if t is not None and t.needs_solve
        ]
        if not solving:
            return None
        _tracer = _get_tracer()
        _span = _tracer.span_active("ops.tenant_dispatch")
        _t0 = time.perf_counter()
        bsz, s, n, k = bucket.slots, bucket.s, bucket.n, bucket.k
        inc_t = np.zeros((bsz, _INC_SLOTS), dtype=np.int32)
        inc_h = np.zeros((bsz, _INC_SLOTS), dtype=np.int32)
        inc_w = np.full((bsz, _INC_SLOTS), INF, dtype=np.int32)
        # in-kernel patch operands; the out-of-bounds row id ``n``
        # marks padding (and untouched slots) — the fused scatter
        # drops it, so idle lanes cost nothing
        p_rows = np.full((bsz, _PATCH_SLOTS), n, dtype=np.int32)
        p_src = np.zeros((bsz, _PATCH_SLOTS, k), dtype=np.int32)
        p_w = np.zeros((bsz, _PATCH_SLOTS, k), dtype=np.int32)
        warm_ct = cold_ct = 0
        for slot, t in solving:
            if t.srcs_dirty:
                srcs_row = np.full(s, t.srcs[0], dtype=np.int32)
                srcs_row[: len(t.srcs)] = t.srcs
                bucket.srcs_dev = _slot_set(
                    bucket.srcs_dev, np.int32(slot), srcs_row
                )
                t.srcs_dirty = False
            if t.pending_rows:
                rows = sorted(t.pending_rows)
                t.pending_rows = set()
                if len(rows) > _PATCH_SLOTS:
                    # patch wider than the in-kernel row budget:
                    # re-upload the whole slot (one warm executable)
                    # instead of growing a scatter-shape ladder
                    TENANCY_COUNTERS["patch_overflows"] += 1
                    src_u, w_u, _ov = ell_pack_uniform(t.graph, n, k)
                    sl = np.int32(slot)
                    bucket.src_dev = _slot_set(
                        bucket.src_dev, sl, src_u
                    )
                    bucket.w_dev = _slot_set(bucket.w_dev, sl, w_u)
                else:
                    ids = np.asarray(rows, dtype=np.int32)
                    src_rows, w_rows = ell_uniform_rows(t.graph, ids, k)
                    p_rows[slot, : len(rows)] = ids
                    p_src[slot, : len(rows)] = src_rows
                    p_w[slot, : len(rows)] = w_rows
            ov_now = np.asarray(t.graph.overloaded)
            edges = None
            if t.solved and not t.force_reset:
                edges = self._emit_increases(t, ov_now)
                if len(edges) > _INC_SLOTS:
                    edges = None  # journal wider than the slot budget
            if edges is None:
                edges = [_FORCE_RESET_EDGE]
                cold_ct += 1
            else:
                warm_ct += 1
            for x, (tt, hh, ww) in enumerate(edges):
                inc_t[slot, x] = tt
                inc_h[slot, x] = hh
                inc_w[slot, x] = ww
        cap = bucket.delta_cap
        fault_point(FAULT_DEVICE_LOST)
        slo_counts = {cls: 0 for cls in SLO_CLASSES}
        for _slot, t in solving:
            slo_counts[t.slo] = slo_counts.get(t.slo, 0) + 1
        # label the sampled device timing with this bucket's shape key
        # and its dominant SLO class, so ops.device_ms.by_bucket.* /
        # by_slo.* attribute the wave per tenant bucket and SLO
        dominant = max(slo_counts, key=slo_counts.get) if solving \
            else "idle"
        with _get_profiler().labels(
            bucket=f"{bucket.s}x{bucket.n}x{bucket.k}", slo=dominant,
        ):
            # ell_dispatch (not plain aot_call): the fused solve bakes
            # the uniform-block relax impl into its trace, so the tag
            # must re-key when a kernel is armed for this (n, k)
            packed, d, src_new, w_new, ch_count, out = ell_dispatch(
                "world_dispatch", world_dispatch,
                (
                    bucket.src_dev, bucket.w_dev, bucket.ov_dev,
                    bucket.srcs_dev, p_rows, p_src, p_w,
                    inc_t, inc_h, inc_w, bucket.d_dev,
                    bucket.packed_dev,
                ),
                dict(cap=cap),
                shape=(n, k),
            )
        bucket.src_dev = src_new
        bucket.w_dev = w_new
        bucket.d_dev = d
        bucket.packed_dev = packed
        # both readback lanes kicked at submit; _dispatch_finish reaps
        da.kick_async(ch_count)
        da.kick_async(out)
        # launch-epoch versions: _dispatch_finish may reap AFTER a
        # tenant was parked (fleet migration drains make that window
        # routine) or even re-synced; the finish-side settle must know
        # which world this dispatch actually solved
        launch_ver = {slot: t.version for slot, t in solving}
        return (
            bucket, solving, warm_ct, cold_ct,
            packed, ch_count, out, _span, _t0, slo_counts, launch_ver,
        )

    @committed_dispatch
    def _dispatch_finish(self, ctx) -> None:
        """Phase 2: block on the in-flight solve, fan the compacted
        delta back out to the per-tenant host mirrors, and settle the
        journals + counters + span."""
        (
            bucket, solving, warm_ct, cold_ct,
            packed, ch_count, out, _span, _t0, slo_counts, launch_ver,
        ) = ctx
        cap = bucket.delta_cap
        mirror_shape = (2 * bucket.s, bucket.n)

        # A tenant parked between submit and reap vacated its
        # bucket.tenants slot, but it is still OWED this dispatch's
        # delta — its journal was emitted into this solve. Dropping
        # the rows while the settle loop below clears the journal
        # would leave a stale mirror marked solved (the un-reaped-
        # delta bug; the fleet migration drain makes the window
        # routine). Attribute vacated slots back to the launch-time
        # occupant, as long as its record still describes the world
        # this dispatch solved (same version, shape-intact mirror).
        launched = dict(solving)

        def _sink_of(slot_i: int) -> Optional[TenantWorld]:
            t = bucket.tenants[slot_i]
            if t is not None:
                return t
            lt = launched.get(slot_i)
            if (
                lt is not None
                and lt.version == launch_ver[slot_i]
                and lt.packed_host is not None
                and lt.packed_host.shape == mirror_shape
            ):
                return lt
            return None  # record moved under the dispatch: drop

        # count + compacted rows were both kicked at launch: reaping
        # them here is the window's single read phase, overlapped with
        # the other buckets' still-running solves
        cnt = int(da.reap_read(ch_count, kicked=True))
        out_host = da.reap_read(out, kicked=True)
        # openr-lint: disable=host-branch-in-chain -- post-reap settle: overflow-vs-delta here picks which already-reaped buffer to copy, not what to submit (audited)
        if cnt > cap:
            TENANCY_COUNTERS["delta_overflows"] += 1
            full = da.reap_read(packed)
            for slot in range(bucket.slots):
                t = _sink_of(slot)
                if t is not None:
                    t.packed_host = np.array(full[slot])
        # openr-lint: disable=host-branch-in-chain -- post-reap settle: the count only sizes the host mirror patch (audited)
        elif cnt:
            rows = out_host[:cnt]
            slots = rows[:, 0]
            for slot in np.unique(slots):
                t = _sink_of(int(slot))
                if t is None:
                    continue  # vacated slot: stale rows, drop
                m = slots == slot
                t.packed_host[rows[m, 1]] = rows[m, 2:]
        TENANCY_COUNTERS["delta_rows"] += cnt
        TENANCY_COUNTERS["dispatches"] += 1
        TENANCY_COUNTERS["warm_solves"] += warm_ct
        TENANCY_COUNTERS["cold_solves"] += cold_ct
        for _slot, t in solving:
            if bucket.tenants[_slot] is not t:
                # parked (or dropped) between submit and reap
                if _sink_of(_slot) is not t:
                    # the record moved under the dispatch (re-synced
                    # or reset): the delta was dropped above, so the
                    # journal must survive and the next admission
                    # must not trust the mirror — cold, never silent
                    TENANCY_COUNTERS["park_midflight_resets"] += 1
                    t.force_reset = True
                    t.solved = False
                    continue
                # mirror received the delta: the host record is
                # current and re-admission rehydrates warm with bits
                TENANCY_COUNTERS["park_midflight_carries"] += 1
            t.pending_edges = {}
            t.pending_structural = False
            t.ov_solved = np.array(t.graph.overloaded, copy=True)
            t.force_reset = False
            t.needs_solve = False
            t.solved = True
        _get_registry().observe(
            "tenancy.dispatch_ms",
            (time.perf_counter() - _t0) * 1000.0,
        )
        TENANCY_COUNTERS["wave_occupancy"] = int(
            round(100 * bucket.occupancy() / bucket.slots)
        )
        _get_tracer().end_span_active(
            _span,
            slots=bucket.slots,
            resident=bucket.occupancy(),
            solving=len(solving),
            warm=warm_ct,
            cold=cold_ct,
            delta_rows=cnt,
            slo_premium=slo_counts.get("premium", 0),
            slo_standard=slo_counts.get("standard", 0),
            slo_bulk=slo_counts.get("bulk", 0),
        )

    # -- integrity plane ---------------------------------------------------
    # The tenant plane's audit surface (``ResidentEngineContract``).
    # Note WorldBucket deliberately carries no ``@resident_buffers``
    # marker: its blocks flow through the bare-jit ``world_dispatch``,
    # which the donation/sharding rules would misread as a single-graph
    # engine dispatch. Healability is declared here instead — every
    # block re-derives from the per-tenant ``packed_host`` mirrors plus
    # each tenant's compiled graph, which is exactly what
    # ``integrity_heal`` (and ``_recover_device_loss``) replay.

    def audit_ready(self) -> bool:
        """Auditable between solve waves only: every occupied slot
        settled (solved, no pending patch rows, mirror present) and at
        least one slot occupied. A mid-churn audit would alarm on
        in-flight state, not corruption."""
        if not self._buckets:
            return False
        occupied = 0
        for bucket in self._buckets.values():
            for t in bucket.tenants:
                if t is None:
                    continue
                occupied += 1
                if (
                    not t.solved
                    or t.needs_solve
                    or t.pending_rows
                    or t.packed_host is None
                ):
                    return False
        return occupied > 0

    def audit_residual(self) -> int:
        total = 0
        for key in sorted(self._buckets):
            bucket = self._buckets[key]
            total += int(jax.device_get(integrity_kernels.world_residual(
                bucket.src_dev, bucket.w_dev,
                bucket.ov_dev, bucket.d_dev,
            )))
        return total

    def audit_digest_pair(self) -> Tuple[int, int]:
        """Wraparound sum of per-slot packed digests over OCCUPIED
        slots, device vs the per-tenant host mirrors. Vacated slots are
        excluded on both sides (their device rows are stale by design),
        and the order-independent fold makes bucket/slot iteration
        order immaterial."""
        dev_sum = 0
        host_sum = 0
        for key in sorted(self._buckets):
            bucket = self._buckets[key]
            slot_digests = np.asarray(jax.device_get(
                integrity_kernels.fnv_slots(bucket.packed_dev)
            ))
            for slot, t in enumerate(bucket.tenants):
                if t is None or t.packed_host is None:
                    continue
                dev_sum = (dev_sum + int(slot_digests[slot])) & 0xFFFFFFFF
                host_sum = (
                    host_sum + integrity_kernels.fnv_host(t.packed_host)
                ) & 0xFFFFFFFF
        return dev_sum, host_sum

    def _occupied_lanes(self) -> List[Tuple[WorldBucket, int, int]]:
        """Stable enumeration of (bucket, slot, source lane) triples
        the row oracle samples from — real lanes only (padding lanes
        duplicate ``srcs[0]`` and add no coverage)."""
        lanes: List[Tuple[WorldBucket, int, int]] = []
        for key in sorted(self._buckets):
            bucket = self._buckets[key]
            for slot, t in enumerate(bucket.tenants):
                if t is None or not t.solved:
                    continue
                for lane in range(len(t.srcs)):
                    lanes.append((bucket, slot, lane))
        return lanes

    def audit_row_count(self) -> int:
        return len(self._occupied_lanes())

    def audit_sample_rows(self, rows: Sequence[int]) -> int:
        """Tier-3 oracle: group the sampled lane indices by slot, cold
        re-solve each touched slot once (``world_cold_slot`` replicates
        the tenant solve's cold path), bit-compare the sampled lanes
        against the resident distance block."""
        lanes = self._occupied_lanes()
        if not lanes:
            return 0
        picked: Dict[
            Tuple[Tuple[int, int, int], int],
            Tuple[WorldBucket, int, List[int]],
        ] = {}
        for i in rows:
            bucket, slot, lane = lanes[i % len(lanes)]
            picked.setdefault(
                (bucket.key, slot), (bucket, slot, [])
            )[2].append(lane)
        mismatches = 0
        for bucket, slot, lns in picked.values():
            cold = np.asarray(jax.device_get(
                integrity_kernels.world_cold_slot(
                    bucket.src_dev[slot], bucket.w_dev[slot],
                    bucket.ov_dev[slot], bucket.srcs_dev[slot],
                )
            ))
            resident = np.asarray(jax.device_get(bucket.d_dev[slot]))
            for lane in sorted(set(lns)):
                if not np.array_equal(cold[lane], resident[lane]):
                    mismatches += 1
        return mismatches

    def quarantine(self, reason: str) -> None:
        """Poison every device block: demote each resident tenant to
        its host snapshot (mirrors + journals are the last verified
        product — they were never device state, so they are not
        suspect) and drop the buckets. Views keep serving from the
        mirrors, so downstream route products never flap."""
        for t in self._tenants.values():
            if t.slot is not None:
                self._detach(t)
        self._buckets = {}
        TENANCY_COUNTERS["quarantines"] += 1
        self._update_gauges()

    def integrity_heal(self) -> bool:
        """Warm heal: re-place every settled tenant from its mirror —
        the same upload path ``_recover_device_loss`` relies on, so the
        re-audit's digest cross-check against the untouched mirrors is
        the bit-identity witness."""
        healed = False
        for tid in sorted(self._tenants):
            t = self._tenants[tid]
            if (
                t.slot is None
                and t.solved
                and not t.needs_solve
                and t.packed_host is not None
            ):
                self._ensure_resident(t)
                healed = True
        if healed:
            self._enforce_residency()
            TENANCY_COUNTERS["integrity_heals"] += 1
        self._update_gauges()
        return healed

    def corrupt_resident(self, seed: int) -> None:
        """Deterministic silent-corruption seam: pick an occupied slot
        from the seeded stream, XOR one bit of its packed view block
        (tier 2 catches this unconditionally) and OR one bit into its
        distance block (tier 1/3 territory). Device state only — the
        host mirrors stay good, which is what makes the heal warm."""
        rng = random.Random(seed)
        occupied = [
            (key, slot)
            for key in sorted(self._buckets)
            for slot, t in enumerate(self._buckets[key].tenants)
            if t is not None and t.solved
        ]
        if not occupied:
            return
        key, slot = occupied[rng.randrange(len(occupied))]
        bucket = self._buckets[key]
        r = rng.randrange(2 * bucket.s)
        c = rng.randrange(bucket.n)
        bit = jnp.int32(1 << rng.randrange(31))
        bucket.packed_dev = bucket.packed_dev.at[slot, r, c].set(
            bucket.packed_dev[slot, r, c] ^ bit
        )
        lane = rng.randrange(bucket.s)
        c2 = rng.randrange(bucket.n)
        bit2 = jnp.int32(1 << rng.randrange(20))
        bucket.d_dev = bucket.d_dev.at[slot, lane, c2].set(
            bucket.d_dev[slot, lane, c2] | bit2
        )
        _get_registry().counter_bump("integrity.corruptions")

    def _update_gauges(self) -> None:
        TENANCY_COUNTERS["active"] = len(self._tenants)
        TENANCY_COUNTERS["resident"] = self.resident_count()


_WORLDS: Optional[WorldManager] = None


def get_world_manager() -> WorldManager:
    """Process-wide arbiter (the device blocks are process-global
    state, like spf_solver's resident ELL cache)."""
    global _WORLDS
    if _WORLDS is None:
        _WORLDS = WorldManager()
    return _WORLDS


def reset_world_manager() -> None:
    """Drop the process-wide arbiter and every device block it owns
    (wired into decision.spf_solver.reset_device_caches: the cold rung
    must not leak half-synced tenant state)."""
    global _WORLDS
    if _WORLDS is not None:
        _WORLDS.reset()
    _WORLDS = None
