"""Pallas TPU kernel for the sliced-ELL relaxation — the sparse hot
path (ops.spf_sparse._ell_relax) as an explicit VMEM-tiled kernel.

Per band the relaxation computes, for every source row s and band row j:

    out[s, j] = min(d[s, j], min_slot(d[s, src[j, slot]] + w[j, slot]))

with the overload mask folded in (edges originating at overloaded nodes
never extend paths: w_eff = INF where overloaded[src]). The jnp
formulation leaves the [S, rows, k] gather+broadcast to XLA — the
single hottest dispatch in the system at scale (every warm churn solve,
frontier re-solve and batched-world dispatch iterates it to the fixed
point). This kernel tiles it so the work stays in VMEM:

  - the source-rows distance panel is the RESIDENT block: one
    (TILE_S, n_pad) panel per sublane step, reused across the whole
    band-row sweep (at 100k nodes: 8 x 100096 x 4 B ~= 3.2 MB —
    comfortably inside the ~16 MB VMEM budget, see vmem_bytes);
  - the (src, w) slot panels stream through as (TILE_N, k) blocks
    (k is the full slot extent — legal at any size per Mosaic's
    full-extent rule; TILE_N = 128 rides the lane axis);
  - the gather temporary is (TILE_S, TILE_N, k) int32 — the largest
    per-step allocation, bounded by the declared tile dims.

Padding discipline (provably inert): band rows pad to a TILE_N multiple
with src = 0 (a valid gather index) and w = INF — min(d + INF -> INF)
never wins, and the padded output columns are sliced off; source rows
pad to a TILE_S multiple with d = INF — garbage rows, also sliced off.
INF = 2^30 - 1 keeps d + w < 2^31 (no int32 overflow), exactly the jnp
kernel's saturation contract, so the result is BIT-identical (int32
exact) to the jnp formulation — the unique-fixed-point property of the
int32 min-relaxation then makes every downstream fixed point identical
too, which is what the parity suites assert.

Three variants mirror the three jnp relax flavors:

  - ``ell_band_relax``: the plain banded relax (spf_sparse._ell_relax)
  - ``ell_band_relax_masked``: + a per-batch edge exclusion mask
    (spf_sparse._ell_relax_masked, the KSP2 second-path graphs)
  - ``rev_band_relax``: the reversed-graph sweep relax with the
    row-dependent transit mask (route_sweep._rev_relax): edge (s -> v)
    extends a v ~> t path unless v is overloaded and v != t.

Like the dense and grouped kernels, selection is BY MEASUREMENT
(ops.autotune, family key "ell_relax"); ``interpret=None`` resolves to
interpret mode off-TPU so tier-1 gates bit parity on CPU without
hardware. On-hardware risk to note: the in-kernel gather ``d[:, src]``
relies on Mosaic's dynamic-gather lowering — the scale bench's
``ell_kernel_bench`` leg is the on-chip acceptance run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

INF = np.int32((1 << 30) - 1)

TILE_S = 8  # source rows per grid step (sublane axis of the d panel)
TILE_N = 128  # band rows per grid step (lane axis)


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def vmem_bytes(n_pad: int, k: int, masked: bool = False) -> int:
    """Per-grid-step VMEM bound in bytes, from the declared tile dims:
    the resident (TILE_S, n_pad) distance panel, the streaming
    (TILE_N, k) src/w panels, the (1, n_pad) overload row, the
    (TILE_S, TILE_N) current/output blocks, and the (TILE_S, TILE_N, k)
    gather temporary (doubled when a per-batch mask block rides along).
    The autotuner never needs this — it measures — but the kernel-smoke
    gate and the vmem-budget lint both check the declared tiles bound
    the temporary."""
    elems = (
        TILE_S * n_pad  # resident distance panel
        + 2 * TILE_N * k  # src + w slot panels
        + n_pad  # overload row
        + 2 * TILE_S * TILE_N  # d_cur block + output block
        + TILE_S * TILE_N * k  # gather temporary
    )
    if masked:
        elems += 2 * TILE_S * TILE_N * k  # mask block + masked weights
    return elems * 4


def _relax_kernel(d_ref, src_ref, w_ref, ov_ref, dcur_ref, o_ref):
    src = src_ref[...]  # (TILE_N, k)
    ov = ov_ref[0, :]  # (n_pad,) int32 (0/1)
    w_eff = jnp.where(ov[src] != 0, INF, w_ref[...])  # (TILE_N, k)
    g = d_ref[...][:, src]  # (TILE_S, TILE_N, k) gather
    relaxed = jnp.min(
        jnp.minimum(g + w_eff[None, :, :], INF), axis=2
    ).astype(jnp.int32)
    o_ref[...] = jnp.minimum(dcur_ref[...], relaxed)


def _masked_relax_kernel(d_ref, src_ref, w_ref, m_ref, ov_ref,
                         dcur_ref, o_ref):
    src = src_ref[...]  # (TILE_N, k)
    ov = ov_ref[0, :]
    w_eff = jnp.where(ov[src] != 0, INF, w_ref[...])  # (TILE_N, k)
    m = m_ref[...]  # (TILE_S, TILE_N, k) int32 (0/1)
    w_b = jnp.where(m != 0, INF, w_eff[None, :, :])
    g = d_ref[...][:, src]
    relaxed = jnp.min(jnp.minimum(g + w_b, INF), axis=2).astype(
        jnp.int32
    )
    o_ref[...] = jnp.minimum(dcur_ref[...], relaxed)


def _rev_relax_kernel(d_ref, v_ref, w_ref, t_ref, ov_ref, dcur_ref,
                      o_ref):
    v = v_ref[...]  # (TILE_N, k)
    ov_g = ov_ref[0, :][v] != 0  # (TILE_N, k)
    t = t_ref[...]  # (TILE_S, 1)
    # edge (s -> v) extends a v ~> t path unless v is overloaded
    # transit (v != t): row-dependent, never source-dependent
    blocked = ov_g[None, :, :] & (v[None, :, :] != t[:, :, None])
    w_eff = jnp.where(blocked, INF, w_ref[...][None, :, :])
    g = d_ref[...][:, v]
    relaxed = jnp.min(jnp.minimum(g + w_eff, INF), axis=2).astype(
        jnp.int32
    )
    o_ref[...] = jnp.minimum(dcur_ref[...], relaxed)


def _interpret(interpret):
    if interpret is not None:
        return interpret
    return jax.devices()[0].platform != "tpu"


def _pad_band(d, src, w, pos, rows):
    """Shared inert-padding prep: returns (d_padded [s_pad, n_pad],
    src/w [rows_pad, k], d_cur [s_pad, rows_pad], s_pad, rows_pad,
    real (s, rows)). pos/rows are static band coordinates."""
    s, _n_pad = d.shape
    s_pad = _pad_to(max(s, TILE_S), TILE_S)
    rows_pad = _pad_to(max(rows, TILE_N), TILE_N)
    d_cur = d[:, pos : pos + rows]
    if s_pad != s:
        d = jnp.pad(d, ((0, s_pad - s), (0, 0)), constant_values=INF)
    d_cur = jnp.pad(
        d_cur,
        ((0, s_pad - s), (0, rows_pad - rows)),
        constant_values=INF,
    )
    src_p = jnp.pad(src, ((0, rows_pad - rows), (0, 0)))
    w_p = jnp.pad(
        w, ((0, rows_pad - rows), (0, 0)), constant_values=INF
    )
    return d, src_p, w_p, d_cur, s_pad, rows_pad, (s, rows)


def _ov_row(overloaded):
    """[n_pad] bool -> (1, n_pad) int32: Mosaic handles int32 blocks
    uniformly; the kernels test `!= 0`."""
    return overloaded.astype(jnp.int32)[None, :]


def _run(kernel, operands, in_specs, s_pad, rows_pad, real, interpret):
    s, rows = real
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((s_pad, rows_pad), jnp.int32),
        grid=(s_pad // TILE_S, rows_pad // TILE_N),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((TILE_S, TILE_N), lambda i, j: (i, j)),
        interpret=_interpret(interpret),
    )(*operands)
    return out[:s, :rows]


# the shared block plan: d panel resident across the j sweep, slot
# panels streaming, overload row broadcast, current/output tiled
def _d_spec(n_pad):
    return pl.BlockSpec((TILE_S, n_pad), lambda i, j: (i, 0))


def _slot_spec(k):
    return pl.BlockSpec((TILE_N, k), lambda i, j: (j, 0))


def _ov_spec(n_pad):
    return pl.BlockSpec((1, n_pad), lambda i, j: (0, 0))


def _tile_spec():
    return pl.BlockSpec((TILE_S, TILE_N), lambda i, j: (i, j))


def ell_band_relax(d, src, w, overloaded, pos, interpret=None):
    """One band of the plain sliced-ELL relax: d [S, n_pad], band
    tensors src/w [rows, k], overloaded [n_pad] bool; returns the
    band's output block [S, rows] = min(d[:, pos:pos+rows],
    min_slot(d[:, src] + w_eff)). Bit-identical to the jnp band body
    in spf_sparse._ell_relax."""
    rows = src.shape[0]
    k = src.shape[1]
    n_pad = d.shape[1]
    d_p, src_p, w_p, d_cur, s_pad, rows_pad, real = _pad_band(
        d, src, w, pos, rows
    )
    return _run(
        _relax_kernel,
        [d_p, src_p, w_p, _ov_row(overloaded), d_cur],
        [
            _d_spec(n_pad), _slot_spec(k), _slot_spec(k),
            _ov_spec(n_pad), _tile_spec(),
        ],
        s_pad, rows_pad, real, interpret,
    )


def ell_band_relax_masked(d, src, w, mask, overloaded, pos,
                          interpret=None):
    """One band of the per-batch-masked relax (KSP2 second-path
    graphs): mask [S, rows, k] bool, True == edge excluded for that
    batch element. Bit-identical to spf_sparse._ell_relax_masked's
    band body."""
    rows = src.shape[0]
    k = src.shape[1]
    n_pad = d.shape[1]
    d_p, src_p, w_p, d_cur, s_pad, rows_pad, real = _pad_band(
        d, src, w, pos, rows
    )
    s = d.shape[0]
    m = jnp.pad(
        mask.astype(jnp.int32),
        ((0, s_pad - s), (0, rows_pad - rows), (0, 0)),
    )
    return _run(
        _masked_relax_kernel,
        [d_p, src_p, w_p, m, _ov_row(overloaded), d_cur],
        [
            _d_spec(n_pad), _slot_spec(k), _slot_spec(k),
            pl.BlockSpec((TILE_S, TILE_N, k), lambda i, j: (i, j, 0)),
            _ov_spec(n_pad), _tile_spec(),
        ],
        s_pad, rows_pad, real, interpret,
    )


def rev_band_relax(d, v, w, t_ids, overloaded, pos, interpret=None):
    """One band of the reversed-graph sweep relax (route_sweep
    ._rev_relax): t_ids [S] destination ids; the transit mask blocks
    edge (s -> v) when v is overloaded and v != t. Bit-identical to
    the jnp band body."""
    rows = v.shape[0]
    k = v.shape[1]
    n_pad = d.shape[1]
    d_p, v_p, w_p, d_cur, s_pad, rows_pad, real = _pad_band(
        d, v, w, pos, rows
    )
    s = d.shape[0]
    t = jnp.pad(t_ids.astype(jnp.int32), (0, s_pad - s))[:, None]
    return _run(
        _rev_relax_kernel,
        [d_p, v_p, w_p, t, _ov_row(overloaded), d_cur],
        [
            _d_spec(n_pad), _slot_spec(k), _slot_spec(k),
            pl.BlockSpec((TILE_S, 1), lambda i, j: (i, 0)),
            _ov_spec(n_pad), _tile_spec(),
        ],
        s_pad, rows_pad, real, interpret,
    )
