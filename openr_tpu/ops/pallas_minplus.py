"""Pallas TPU kernel for the min-plus product (tropical matmul).

The relaxation step of the batched SPF is ``out[s, j] = min_k a[s, k] +
b[k, j]`` — a matmul over the (min, +) semiring. XLA's fused
broadcast+reduce handles it well for moderate N, but tiling it explicitly
keeps the k-panel resident in VMEM and bounds the broadcast temporary to
(TS, TK, TN) regardless of N, which matters once N is in the thousands.

Layout: TPU Mosaic requires every VMEM block's (sublane, lane) dims to be
multiples of (8, 128) (or equal to the full array dims). Blocks of ``a``
are therefore (TILE_S, TILE_K) = (8, 128) — tall-K, short-S — so both
operands are consumed untransposed with legal tiles, and the broadcast
temporary is (TS, TK, TN) = (8, 128, 128) int32 ≈ 0.5 MB of VMEM.

Tiling: grid (S/TS, N/TN, K/TK) with k innermost; the output tile is
revisited across k and accumulated with minimum (initialized to INF at
k == 0 via pl.when).

Enable through ``openr_tpu.ops.spf.set_minplus_impl("pallas")`` (bench
auto-probes and falls back to the jnp formulation on any failure);
interpret mode is used for CPU correctness tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

INF = np.int32((1 << 30) - 1)

TILE_S = 8
TILE_N = 128
TILE_K = 128


def vmem_bytes() -> int:
    """Per-grid-step VMEM residency in bytes: the a/b input blocks,
    the revisited output tile, and the (TS, TK, TN) broadcast
    temporary — all int32. The tile sizes are static, so the budget
    is a constant (~0.6 MB), independent of N."""
    elems = (
        TILE_S * TILE_K  # a block
        + TILE_K * TILE_N  # b block
        + TILE_S * TILE_N  # output tile
        + TILE_S * TILE_K * TILE_N  # broadcast temporary
    )
    return elems * 4


def _minplus_kernel(a_ref, b_ref, o_ref):
    k = pl.program_id(2)
    a = a_ref[...]  # (TILE_S, TILE_K)
    b = b_ref[...]  # (TILE_K, TILE_N)
    cand = jnp.min(a[:, :, None] + b[None, :, :], axis=1)
    cand = jnp.minimum(cand, INF).astype(jnp.int32)

    @pl.when(k == 0)
    def _init():
        o_ref[...] = jnp.full_like(o_ref, INF)

    o_ref[...] = jnp.minimum(o_ref[...], cand)


@functools.partial(jax.jit, static_argnames=("interpret",))
def minplus(a: jnp.ndarray, b: jnp.ndarray, interpret: bool = False):
    """(a (x) b) over (min, +): [S, K] x [K, N] -> [S, N] int32.

    Shapes must be multiples of the tile sizes (the snapshot layer pads
    to 128, which satisfies this).
    """
    s, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    assert s % TILE_S == 0 and n % TILE_N == 0 and k % TILE_K == 0, (
        a.shape,
        b.shape,
    )
    grid = (s // TILE_S, n // TILE_N, k // TILE_K)
    return pl.pallas_call(
        _minplus_kernel,
        out_shape=jax.ShapeDtypeStruct((s, n), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_S, TILE_K), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((TILE_K, TILE_N), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((TILE_S, TILE_N), lambda i, j, kk: (i, j)),
        interpret=interpret,
    )(a, b)
