"""Batched shortest-path kernels.

The TPU-native replacement for the reference's per-source Dijkstra
(reference: openr/decision/LinkState.cpp:809-882 runSpf). Instead of a
heap walk per source, shortest paths are computed *algebraically* over the
snapshot's dense int32 metric matrix:

- ``all_pairs_distances``: min-plus matrix "squaring" — doubles the covered
  path length each iteration, so it converges in ceil(log2(diameter))
  fixed-point steps inside a ``lax.while_loop``.
- ``distances_from_sources``: Bellman-Ford relaxation for a (small) batch of
  sources — S x N x N work per step, diameter steps; used by the daemon
  path where only this node + its neighbors are needed.
- ``first_hop_matrix``: ECMP first-hop set reconstruction. A neighbor ``v``
  of source ``s`` is a valid first hop toward ``j`` iff

      W[s,v] + D[v,j] == D[s,j]      (v not overloaded, transit case)
      W[s,v] == D[s,j] and v == j    (directly-connected case)

  which reproduces exactly the Dijkstra ECMP accumulation semantics of the
  reference (nextHops union over equal-cost predecessors, directly-connected
  nodes contributing themselves; reference LinkState.cpp:857-873), including
  overloaded-node transit exclusion (reference: LinkState.cpp:831-838).

Transit exclusion is encoded by masking *rows* of the one-hop matrix: an
overloaded node's outgoing edges never extend a path, while paths may still
start at (source exemption: initial D rows are direct edges) or terminate
on (columns stay intact) an overloaded node.

All kernels are jit-compiled with static padded shapes; distances saturate
at INF = 2**30 - 1 (int32-safe: INF + INF == 2**31 - 2 < 2**31 - 1).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

INF = np.int32((1 << 30) - 1)


def _mask_transit_rows(d: jnp.ndarray, overloaded: jnp.ndarray) -> jnp.ndarray:
    """Replace rows of overloaded nodes with the min-plus identity row
    (0 on the diagonal, INF elsewhere): their paths never extend others."""
    n = d.shape[0]
    ident_row = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (n, n), 1),
        jnp.int32(0),
        INF,
    )
    return jnp.where(overloaded[:, None], ident_row, d)


# min-plus implementation selector: "jnp" (XLA fused broadcast+reduce),
# "pallas" (explicit VMEM tiling, openr_tpu.ops.pallas_minplus), or
# "auto" — a MEASURED per-shape winner picked by ops.autotune at the
# first eager call for each operand shape (the jnp-vs-pallas winner
# flips with shape and hardware; see ops/autotune.py). Resolution
# happens in the public wrappers below, before jit entry, so traces
# only ever see a concrete impl as their static argument.
_MINPLUS_IMPL = os.environ.get("OPENR_MINPLUS", "jnp")


def set_minplus_impl(impl: str) -> None:
    global _MINPLUS_IMPL
    assert impl in ("jnp", "pallas", "auto"), impl
    _MINPLUS_IMPL = impl


def get_minplus_impl() -> str:
    return _MINPLUS_IMPL


def _impl_for(shape) -> str:
    """Concrete impl for one dispatch: "auto" resolves to the measured
    per-shape winner ([rows, n] against [n, n])."""
    if _MINPLUS_IMPL != "auto":
        return _MINPLUS_IMPL
    from openr_tpu.ops import autotune

    return autotune.resolve_minplus(tuple(shape))


def _minplus(a: jnp.ndarray, b: jnp.ndarray, impl: str = "jnp") -> jnp.ndarray:
    """(a (x) b)[s, j] = min_k a[s, k] + b[k, j], saturating at INF.

    jnp path: XLA fuses the broadcast-add into the min-reduction, so the
    [S, N, N] intermediate is never materialized in HBM.
    """
    if impl == "pallas":
        from openr_tpu.ops.pallas_minplus import minplus as pallas_minplus

        return pallas_minplus(a, b)
    return jnp.minimum(
        jnp.min(a[:, :, None] + b[None, :, :], axis=1), INF
    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("impl",))
def _all_pairs_distances(
    w: jnp.ndarray, overloaded: jnp.ndarray, impl: str
) -> jnp.ndarray:
    n = w.shape[0]
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    )
    d0 = jnp.where(eye, jnp.int32(0), w)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        d_transit = _mask_transit_rows(d, overloaded)
        nxt = jnp.minimum(d, _minplus(d, d_transit, impl))
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d


def all_pairs_distances(
    w: jnp.ndarray, overloaded: jnp.ndarray
) -> jnp.ndarray:
    """All-sources shortest path distances, [N, N] int32.

    w: [N, N] one-hop metric matrix (INF = no edge). Diagonal is forced
    to 0. overloaded: [N] bool transit-exclusion mask.
    """
    return _all_pairs_distances(w, overloaded, _impl_for(w.shape))


@functools.partial(jax.jit, static_argnames=("impl",))
def _distances_from_sources(
    w: jnp.ndarray,
    overloaded: jnp.ndarray,
    src_ids: jnp.ndarray,
    impl: str,
) -> jnp.ndarray:
    n = w.shape[0]
    t = _mask_transit_rows(w, overloaded)
    d0 = w[src_ids, :]
    d0 = d0.at[jnp.arange(src_ids.shape[0]), src_ids].set(0)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        nxt = jnp.minimum(d, _minplus(d, t, impl))
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d


def distances_from_sources(
    w: jnp.ndarray, overloaded: jnp.ndarray, src_ids: jnp.ndarray
) -> jnp.ndarray:
    """Shortest-path distances from a batch of sources, [S, N] int32.

    Bellman-Ford over the transit-masked one-hop matrix. Initial rows are
    the sources' direct edges (so an overloaded source still originates).
    """
    return _distances_from_sources(
        w, overloaded, src_ids,
        _impl_for((src_ids.shape[0], w.shape[-1])),
    )


@jax.jit
def first_hop_matrix(
    w: jnp.ndarray,
    overloaded: jnp.ndarray,
    src_id: jnp.ndarray,
    d_src: jnp.ndarray,
    d_all: jnp.ndarray,
) -> jnp.ndarray:
    """ECMP first-hop membership, [N, N] bool: out[v, j] == True iff
    neighbor v of the source lies on an equal-cost shortest path to j.

    d_src: [N] distances from the source. d_all: [N, N] distances from
    every node (rows for non-neighbors are ignored).
    """
    n = w.shape[0]
    w_sv = w[src_id, :]  # [N] direct metric source -> v
    is_neighbor = w_sv < INF
    reachable = d_src < INF

    # transit case: s -> v -> ... -> j, v must not be overloaded
    total = jnp.minimum(w_sv[:, None] + d_all, INF)
    transit_ok = (
        is_neighbor[:, None]
        & (~overloaded)[:, None]
        & (total == d_src[None, :])
    )
    # direct case: v == j and the direct edge achieves the shortest metric
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    )
    direct_ok = eye & (is_neighbor & (w_sv == d_src))[:, None]

    mask = (transit_ok | direct_ok) & reachable[None, :]
    # the source is never its own first hop
    mask = mask.at[src_id, :].set(False)
    return mask


def source_batch(snap, sid: int):
    """Build the hot-path source batch for ``spf_view_batch``: the source
    followed by its sorted unique neighbor ids, padded by repeating the
    source up to a power-of-two bucket (>= 8, capped at the snapshot's
    padded dimension). Padding rows are inert: the source is never its
    own neighbor, so their first-hop rows are all False.

    Returns (real_srcs, padded_device_ids); row i of the kernel output
    corresponds to real_srcs[i] for i < len(real_srcs). This is the one
    place the batch layout is defined — the solver, the bench, and the
    tests all share it.
    """
    nbrs = sorted({dl.dst_id for dl in snap.links_from[sid]})
    srcs = [sid] + nbrs
    bucket = 8
    while bucket < len(srcs):
        bucket *= 2
    bucket = min(bucket, snap.n_pad)
    padded = srcs + [sid] * (bucket - len(srcs))
    return srcs, jnp.asarray(np.asarray(padded, dtype=np.int32))


@functools.partial(jax.jit, static_argnames=("use_link_metric", "impl"))
def _spf_view_batch(
    metric: jnp.ndarray,
    overloaded: jnp.ndarray,
    srcs: jnp.ndarray,
    use_link_metric: bool,
    impl: str,
):
    n = metric.shape[0]
    b = srcs.shape[0]
    w = metric if use_link_metric else jnp.where(metric < INF, jnp.int32(1), INF)
    t = _mask_transit_rows(w, overloaded)
    d0 = w[srcs, :]
    d0 = d0.at[jnp.arange(b), srcs].set(0)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        d, _, it = state
        nxt = jnp.minimum(d, _minplus(d, t, impl))
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))

    # ECMP first-hop membership for the batch rows. Row 0 is the source
    # itself (w[src, src] == INF => never a neighbor => all False); padding
    # rows that repeat the source behave identically.
    src_id = srcs[0]
    d_src = d[0]
    w_sv = w[src_id, srcs]  # [B] direct metric source -> batch node
    is_neighbor = w_sv < INF
    reachable = d_src < INF
    total = jnp.minimum(w_sv[:, None] + d, INF)
    transit_ok = (
        is_neighbor[:, None]
        & (~overloaded[srcs])[:, None]
        & (total == d_src[None, :])
    )
    # direct case: batch node v == destination j and the direct edge
    # achieves the shortest metric
    col_is_self = srcs[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (b, n), 1
    )
    direct_ok = col_is_self & (is_neighbor & (w_sv == d_src[srcs]))[:, None]
    fh = (transit_ok | direct_ok) & reachable[None, :]
    # pack into one output buffer: a single device->host fetch returns
    # both (per-transfer latency dominates on relay-backed platforms)
    return jnp.concatenate([d, fh.astype(jnp.int32)], axis=0)


def spf_view_batch(
    metric: jnp.ndarray,
    overloaded: jnp.ndarray,
    srcs: jnp.ndarray,
    use_link_metric: bool = True,
):
    """Daemon hot-path kernel: distances + ECMP first hops for a batch of
    sources ``srcs = [src, neighbor_0, neighbor_1, ...]`` (padded by
    repeating ``src``).

    This is what one route rebuild actually consumes (reference:
    openr/decision/Decision.cpp:1124 getNextHopsWithMetric needs the
    source's distance vector plus each neighbor's, and LFA at :1192 needs
    neighbor rows only) — S x N x N work instead of the N x N x N
    all-pairs product. Returns (d [B, N], fh [B, N] bool) where fh[i, j]
    is True iff batch node i is a valid ECMP first hop from the source
    toward j.
    """
    packed = _spf_view_batch(
        metric, overloaded, srcs, use_link_metric,
        _impl_for((srcs.shape[0], metric.shape[-1])),
    )
    b = srcs.shape[0]
    return packed[:b], packed[b:].astype(jnp.bool_)


def spf_view_batch_packed(
    metric: jnp.ndarray,
    overloaded: jnp.ndarray,
    srcs: jnp.ndarray,
    use_link_metric: bool = True,
):
    """Single-buffer variant of ``spf_view_batch``: returns [2B, N] int32
    (rows [0, B) distances, rows [B, 2B) first-hop 0/1) so the host pays
    exactly one device->host transfer."""
    return _spf_view_batch(
        metric, overloaded, srcs, use_link_metric,
        _impl_for((srcs.shape[0], metric.shape[-1])),
    )


@functools.partial(
    jax.jit, static_argnames=("use_link_metric", "impl")
)
def _reconverge_step(
    metric: jnp.ndarray,
    patch_ids: jnp.ndarray,
    patch_vals: jnp.ndarray,
    overloaded: jnp.ndarray,
    srcs: jnp.ndarray,
    use_link_metric: bool,
    impl: str,
):
    m = metric.at[patch_ids, :].set(patch_vals)
    packed = _spf_view_batch(m, overloaded, srcs, use_link_metric, impl)
    return m, packed


def reconverge_step(
    metric: jnp.ndarray,
    patch_ids: jnp.ndarray,
    patch_vals: jnp.ndarray,
    overloaded: jnp.ndarray,
    srcs: jnp.ndarray,
    use_link_metric: bool = True,
):
    """Fused churn step, one dispatch: scatter changed metric rows into
    the resident matrix, then run the batched SPF view from it.

    Returns (patched metric [N, N], packed [2B, N] int32: distances then
    first-hop 0/1 rows). The patched matrix becomes the new resident
    snapshot array — the host never re-uploads O(N^2) state on
    steady-state churn — and the packed result costs one transfer.
    """
    return _reconverge_step(
        metric, patch_ids, patch_vals, overloaded, srcs, use_link_metric,
        _impl_for((srcs.shape[0], metric.shape[-1])),
    )


@functools.partial(jax.jit, static_argnames=("use_link_metric", "impl"))
def _spf_from_source_with_first_hops(
    metric: jnp.ndarray,
    hop: jnp.ndarray,
    overloaded: jnp.ndarray,
    src_id: jnp.ndarray,
    use_link_metric: bool,
    impl: str,
):
    w = metric if use_link_metric else hop
    d_all = _all_pairs_distances(w, overloaded, impl)
    d_src = d_all[src_id, :]
    fh = first_hop_matrix(w, overloaded, src_id, d_src, d_all)
    return d_src, d_all, fh


def spf_from_source_with_first_hops(
    metric: jnp.ndarray,
    hop: jnp.ndarray,
    overloaded: jnp.ndarray,
    src_id: jnp.ndarray,
    use_link_metric: bool = True,
):
    """One fused device step for the daemon hot path: distances from the
    source and from all nodes, plus the ECMP first-hop matrix.

    Returns (d_src [N], d_all [N, N], first_hops [N, N] bool).
    """
    return _spf_from_source_with_first_hops(
        metric, hop, overloaded, src_id, use_link_metric,
        _impl_for(metric.shape),
    )
