"""Process-wide observability spine: counters, gauges, latency
histograms, and end-to-end trace spans.

Three pieces, one export surface:

- ``registry.py``: a thread-safe fb303-style metric registry. Modules
  register dotted-name counters/gauges/histograms; ``snapshot()``
  flattens everything (histograms expand to ``.p50/.p95/.p99/.max/
  .avg/.count``) into the dict served by ``OpenrCtrl.get_counters``
  and ``breeze monitor counters``.
- ``trace.py``: structured spans over the PerfEvents chain. A trace is
  born at KvStore publication, rides the Publication/RouteUpdate
  objects through Decision and Fib, and lands in a bounded ring
  exportable as Chrome-trace JSON or JSONL.
- ``jax_hooks.py``: jax.monitoring listeners mapping jit compiles to
  ``jax.compile_count`` / ``jax.compile_ms`` so compile-cache
  regressions show up as counters, not silent latency cliffs.
- ``profiler.py``: always-on device-time attribution — measured
  ``ops.device_ms.<tag>`` / ``ops.host_ms.<tag>`` per dispatch tag and
  a live ``ops.host_overhead_ratio`` gauge.
- ``flight.py``: the flight recorder — a lock-cheap activity ring that
  survives trace-ring overflow, with anomaly triggers that freeze it
  and dump post-mortem bundles.
"""

from openr_tpu.telemetry.registry import (  # noqa: F401
    CounterDict,
    Histogram,
    Registry,
    get_registry,
)
from openr_tpu.telemetry.trace import (  # noqa: F401
    Span,
    Trace,
    Tracer,
    get_tracer,
)
from openr_tpu.telemetry.profiler import (  # noqa: F401
    Profiler,
    get_profiler,
    reset_profiler,
)
from openr_tpu.telemetry.flight import (  # noqa: F401
    BUNDLE_SCHEMA,
    CompileAfterWarmupTrigger,
    CounterDeltaTrigger,
    FlightRecorder,
    P99BreachTrigger,
    fnv1a,
    get_flight_recorder,
    install_default_triggers,
    load_bundle,
    reset_flight_recorder,
)

__all__ = [
    "BUNDLE_SCHEMA",
    "CompileAfterWarmupTrigger",
    "CounterDeltaTrigger",
    "CounterDict",
    "FlightRecorder",
    "Histogram",
    "P99BreachTrigger",
    "Profiler",
    "Registry",
    "Span",
    "Trace",
    "Tracer",
    "fnv1a",
    "get_flight_recorder",
    "get_profiler",
    "get_registry",
    "get_tracer",
    "install_default_triggers",
    "load_bundle",
    "reset_flight_recorder",
    "reset_profiler",
]
