"""Thread-safe metric registry with fb303-style dotted names.

One process-wide ``Registry`` (``get_registry()``) owns every counter,
gauge, and histogram. Modules keep their historical idioms:

- legacy module-global counter dicts (``SPF_COUNTERS``,
  ``ELL_COUNTERS``) become ``CounterDict`` shims — same ``d[k] += 1``
  / ``dict(d)`` / ``.items()`` call sites, but the backing store is
  the registry, so ``OpenrCtrl.get_counters`` and bench artifacts see
  them without per-module merge loops;
- latency distributions are ``Histogram``s over a sliding window of
  the most recent observations, exported as streaming percentiles
  (``<name>.p50/.p95/.p99/.max/.avg/.count``) — per DeltaPath, means
  hide the warm/cold split that the churn path must account for.

Everything here must stay cheap on the hot path: a counter bump is a
lock + dict add; a histogram observation is a lock + ring append.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Union

from collections.abc import MutableMapping

_PERCENTILES = ((".p50", 0.50), (".p95", 0.95), (".p99", 0.99))


class Histogram:
    """Streaming latency distribution over a sliding window.

    Keeps the last ``window`` observations in a ring buffer plus
    cumulative ``count``/``max`` over the histogram's whole life, so
    the percentiles track recent behaviour while the count keeps
    monotonic fb303 semantics.

    Observations land from several module threads at once (decision
    rebuild, fib program, monitor scrape) while snapshot() reads from
    another — the per-histogram lock keeps ring/next/filled mutually
    consistent. A plain Lock, never held while calling out.
    """

    __slots__ = (
        "name", "_lock", "_ring", "_next", "_filled", "_count", "_max",
        "_sum",
    )

    def __init__(self, name: str, window: int = 1024) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._ring: List[float] = [0.0] * window
        self._next = 0
        self._filled = 0
        self._count = 0
        self._max = 0.0
        self._sum = 0.0

    def observe(self, value: float) -> None:
        with self._lock:
            self._ring[self._next] = value
            self._next = (self._next + 1) % len(self._ring)
            self._filled = min(self._filled + 1, len(self._ring))
            self._count += 1
            self._sum += value
            if value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def stats(self) -> Dict[str, float]:
        """Flattened ``<name>.p50/.p95/.p99/.max/.avg/.count`` dict."""
        with self._lock:
            count, filled = self._count, self._filled
            ring = self._ring[:filled]
            hmax, hsum = self._max, self._sum
        out: Dict[str, float] = {self.name + ".count": count}
        if count == 0:
            return out
        window = sorted(ring)
        n = len(window)
        for suffix, q in _PERCENTILES:
            # nearest-rank over the sliding window
            idx = min(n - 1, max(0, int(round(q * (n - 1)))))
            out[self.name + suffix] = round(window[idx], 4)
        out[self.name + ".max"] = round(hmax, 4)
        out[self.name + ".avg"] = round(hsum / count, 4)
        return out

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the sliding window (same rule
        as ``stats``), 0.0 when empty — the serve plane's live SLO
        breach check reads this between waves instead of snapshotting
        the whole registry."""
        with self._lock:
            ring = list(self._ring[: self._filled])
        if not ring:
            return 0.0
        window = sorted(ring)
        n = len(window)
        idx = min(n - 1, max(0, int(round(q * (n - 1)))))
        return window[idx]


class Registry:
    """Process-wide metric store. All methods are thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._counters: Dict[str, Union[int, float]] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._histograms: Dict[str, Histogram] = {}

    # -- counters ---------------------------------------------------
    def counter_bump(self, name: str, delta: Union[int, float] = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def counter_set(self, name: str, value: Union[int, float]) -> None:
        with self._lock:
            self._counters[name] = value

    def counter_get(self, name: str) -> Union[int, float]:
        with self._lock:
            return self._counters.get(name, 0)

    def counter_dict(
        self,
        initial: Iterable[str] = (),
        prefix: str = "",
    ) -> "CounterDict":
        """A dict-shaped shim over registry counters (see CounterDict)."""
        d = CounterDict(self, prefix)
        with self._lock:
            for key in initial:
                d.setdefault(key, 0)
        return d

    # -- gauges -----------------------------------------------------
    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        """Register a callable sampled at snapshot time. A gauge that
        raises is dropped from that snapshot (never poisons export)."""
        with self._lock:
            self._gauges[name] = fn

    # -- histograms -------------------------------------------------
    def histogram(self, name: str, window: int = 1024) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, window)
            return h

    def histogram_if_exists(self, name: str) -> Optional[Histogram]:
        """The histogram, or None if nothing has observed it yet —
        anomaly triggers poll through this so they never materialize
        empty histograms (the telemetry smoke fails on any registered
        histogram with count 0)."""
        with self._lock:
            return self._histograms.get(name)

    def observe(self, name: str, value: float) -> None:
        self.histogram(name).observe(value)

    def percentile(self, name: str, q: float) -> float:
        return self.histogram(name).percentile(q)

    @contextmanager
    def timed(self, name: str) -> Iterator[None]:
        """Context manager observing the block's wall-clock into the
        ``name`` histogram in milliseconds — the one-liner the fleet
        twin's converge waves (and any future timed section) use
        instead of hand-rolled perf_counter bookkeeping."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, (time.perf_counter() - t0) * 1000.0)

    def histograms(self) -> Dict[str, Histogram]:
        with self._lock:
            return dict(self._histograms)

    # -- export -----------------------------------------------------
    def snapshot(self) -> Dict[str, Union[int, float]]:
        """One flat fb303-style dict: counters, sampled gauges, and
        expanded histogram stats."""
        with self._lock:
            out: Dict[str, Union[int, float]] = dict(self._counters)
            gauges = dict(self._gauges)
            hists = list(self._histograms.values())
        for name, fn in gauges.items():
            try:
                out[name] = fn()
            except Exception:
                pass
        for h in hists:
            out.update(h.stats())
        return out

    def reset(self) -> None:
        """Zero counters and drop histogram samples (tests only).
        Registered names survive so snapshots keep a stable shape."""
        with self._lock:
            for name in self._counters:
                self._counters[name] = 0
            for name, h in list(self._histograms.items()):
                self._histograms[name] = Histogram(name, len(h._ring))


class CounterDict(MutableMapping):
    """Compatibility shim: looks like the historical module-global
    counter dict (``SPF_COUNTERS[k] += 1``, ``dict(SPF_COUNTERS)``,
    ``.items()``), stores in the shared registry under
    ``prefix + key``. Keys read before first write register at 0, so
    ``before = COUNTERS[k]`` works for names no code path bumped yet.
    """

    __slots__ = ("_registry", "_prefix", "_keys")

    def __init__(self, registry: Registry, prefix: str = "") -> None:
        self._registry = registry
        self._prefix = prefix
        self._keys: Dict[str, None] = {}  # insertion-ordered key set

    def __getitem__(self, key: str) -> Union[int, float]:
        self.setdefault(key, 0)
        return self._registry.counter_get(self._prefix + key)

    def __setitem__(self, key: str, value: Union[int, float]) -> None:
        self._keys[key] = None
        self._registry.counter_set(self._prefix + key, value)

    def __delitem__(self, key: str) -> None:
        del self._keys[key]

    def __iter__(self) -> Iterator[str]:
        return iter(list(self._keys))

    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, key: object) -> bool:
        return key in self._keys

    def setdefault(self, key, default=0):
        if key not in self._keys:
            self._keys[key] = None
            name = self._prefix + key
            self._registry.counter_set(
                name, self._registry.counter_get(name) or default
            )
        return self._registry.counter_get(self._prefix + key)


_REGISTRY: Optional[Registry] = None
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> Registry:
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = Registry()
    return _REGISTRY
