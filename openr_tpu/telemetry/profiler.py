"""Always-on device-time attribution with bounded overhead.

The ROADMAP's open claim — ``host_overhead_ratio`` within 2x of
``device_only_ms`` — was only checkable by bench-side arithmetic
(``benchmarks/bench_scale.py:_chained_device_only_ms`` models a chained
dispatch; nothing measures one). This module makes device time a
*measured, always-on* output of the dispatch plane itself:

- every ``aot_call`` dispatch is wall-timed on the host
  (``ops.host_ms.<tag>``), and every ``sample_every``-th call per tag
  additionally blocks until the result is ready so the full
  submit-to-ready device time lands in ``ops.device_ms.<tag>`` — the
  timed-dispatch sampling fallback that works on CPU where
  ``jax.profiler`` device traces don't exist;
- where a ``jax.profiler`` session IS collecting, ``annotate(tag)``
  wraps the same dispatches in ``TraceAnnotation`` so the XLA timeline
  carries the stage names (free when no session is active);
- call sites label dispatches (``labels(bucket=..., slo=...)``) so the
  sampled device time also lands per tenant bucket and per SLO class
  (``ops.device_ms.by_<key>.<value>``);
- ``dispatch_accounting.event_window`` reports every window's wall
  clock here, so ``ops.host_overhead_ratio`` is a live gauge of
  window-wall over attributed device time — the measured number that
  replaces the bench-derived one.

Overhead budget (<5% on the churn bench, gated by ``make obs-smoke``):
the un-sampled path is one ``perf_counter`` pair, one histogram
observe, and a thread-local read. The sampled path adds ONE
``block_until_ready`` per ``sample_every`` dispatches — a deliberate,
counted pipeline bubble (``ops.profile_samples``), never inside the
two-touch accounting (it does not ride ``reap_read``).

Disabled (``OPENR_PROFILE=0``) the plane costs one attribute read per
dispatch and nothing else.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Iterator, Optional, Tuple

from openr_tpu.telemetry.registry import get_registry

_EWMA = 0.2  # weight of the newest device-time sample per tag


def _sanitize(value: Any) -> str:
    """fb303-safe label value: lowercase alnum + underscore."""
    s = str(value).lower()
    return "".join(c if c.isalnum() else "_" for c in s).strip("_") or "x"


class _TagState:
    __slots__ = ("calls", "device_ewma_ms")

    def __init__(self) -> None:
        self.calls = 0
        self.device_ewma_ms: Optional[float] = None


class Profiler:
    """Process-wide device-time attributor. All methods thread-safe."""

    def __init__(
        self,
        sample_every: Optional[int] = None,
        enabled: Optional[bool] = None,
    ) -> None:
        if sample_every is None:
            sample_every = int(os.environ.get("OPENR_PROFILE_SAMPLE", "8"))
        if enabled is None:
            enabled = os.environ.get("OPENR_PROFILE", "1") != "0"
        self.sample_every = max(1, sample_every)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._tags: Dict[str, _TagState] = {}
        self._tls = threading.local()
        self._warm = False
        # recent (window_wall_ms, window_device_ms) pairs: the ratio
        # gauge reads these, bounded so it tracks current behaviour
        self._windows: deque = deque(maxlen=256)
        self._annotation_cls: Any = None
        get_registry().gauge(
            "ops.host_overhead_ratio", self.host_overhead_ratio
        )

    # -- warmup marker ----------------------------------------------
    def mark_warm(self) -> None:
        """Callers declare warmup done; compiles after this point are
        anomalies (see flight.CompileAfterWarmupTrigger)."""
        self._warm = True

    @property
    def warm(self) -> bool:
        return self._warm

    # -- labels ------------------------------------------------------
    @contextmanager
    def labels(self, **kv: Any) -> Iterator[None]:
        """Attach label dimensions (bucket=..., slo=...) to every
        sampled dispatch inside the block. Thread-local; nests by
        overlay."""
        if not self.enabled:
            yield
            return
        prev = getattr(self._tls, "labels", None)
        merged = dict(prev or ())
        merged.update({k: _sanitize(v) for k, v in kv.items()})
        self._tls.labels = merged
        try:
            yield
        finally:
            self._tls.labels = prev

    def _active_labels(self) -> Optional[Dict[str, str]]:
        return getattr(self._tls, "labels", None)

    # -- jax.profiler annotations -----------------------------------
    def annotate(self, tag: str):
        """``jax.profiler.TraceAnnotation(tag)`` when available — names
        the dispatch on the XLA timeline when a profiler session is
        collecting; a fast no-op TraceMe otherwise."""
        if not self.enabled:
            return nullcontext()
        cls = self._annotation_cls
        if cls is None:
            try:
                from jax.profiler import TraceAnnotation as cls  # noqa: N813
            except Exception:  # noqa: BLE001 - no jax / old jax
                cls = nullcontext
            self._annotation_cls = cls
        try:
            return cls(tag)
        except Exception:  # noqa: BLE001 - annotation never breaks dispatch
            return nullcontext()

    # -- per-dispatch attribution -----------------------------------
    def on_dispatch(self, tag: str, out: Any, host_ms: float) -> float:
        """Record one dispatch's host wall time; on sampled calls also
        block for the device result and record measured device time.
        Returns the best device-time estimate for this call (measured,
        else the tag's EWMA, else the host time)."""
        if not self.enabled:
            return host_ms
        reg = get_registry()
        reg.observe(f"ops.host_ms.{tag}", host_ms)
        with self._lock:
            st = self._tags.get(tag)
            if st is None:
                st = self._tags[tag] = _TagState()
            st.calls += 1
            sampled = (st.calls % self.sample_every) == 1 or \
                self.sample_every == 1
            ewma = st.device_ewma_ms
        if not sampled:
            return ewma if ewma is not None else host_ms
        t0 = time.perf_counter()
        try:
            import jax

            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 - host shims / non-arrays
            pass
        device_ms = host_ms + (time.perf_counter() - t0) * 1000.0
        reg.counter_bump("ops.profile_samples")
        reg.observe(f"ops.device_ms.{tag}", device_ms)
        labels = self._active_labels()
        if labels:
            for key, val in labels.items():
                reg.observe(f"ops.device_ms.by_{key}.{val}", device_ms)
        with self._lock:
            st = self._tags[tag]
            if st.device_ewma_ms is None:
                st.device_ewma_ms = device_ms
            else:
                st.device_ewma_ms = (
                    (1.0 - _EWMA) * st.device_ewma_ms + _EWMA * device_ms
                )
        return device_ms

    # -- per-window attribution -------------------------------------
    def on_window(self, tag: str, wall_ms: float, device_ms: float) -> None:
        """One committed event window retired: its host wall clock and
        the device time attributed inside it. Feeds the live
        ``ops.host_overhead_ratio`` gauge."""
        if not self.enabled or device_ms <= 0.0:
            return
        with self._lock:
            self._windows.append((wall_ms, device_ms))

    def host_overhead_ratio(self) -> float:
        """Measured window-wall over attributed device time across the
        recent windows (the ROADMAP's target: < 2.0 on real hardware)."""
        with self._lock:
            pairs = list(self._windows)
        wall = sum(p[0] for p in pairs)
        dev = sum(p[1] for p in pairs)
        return round(wall / dev, 4) if dev > 0.0 else 0.0

    # -- export ------------------------------------------------------
    def attribution(self) -> Dict[str, Dict[str, float]]:
        """Per-tag measured stage costs: ``{tag: {device_ms_p50,
        device_ms_p99, host_ms_p50, host_ms_p99, calls,
        device_samples}}`` read straight from the registry histograms
        (label histograms ``by_*`` excluded)."""
        hists = get_registry().histograms()
        out: Dict[str, Dict[str, float]] = {}
        for name, h in hists.items():
            for prefix, dev in (("ops.device_ms.", True),
                                ("ops.host_ms.", False)):
                if not name.startswith(prefix):
                    continue
                tag = name[len(prefix):]
                if tag.startswith("by_"):
                    continue
                row = out.setdefault(tag, {})
                kind = "device_ms" if dev else "host_ms"
                row[f"{kind}_p50"] = round(h.percentile(0.50), 4)
                row[f"{kind}_p99"] = round(h.percentile(0.99), 4)
                if dev:
                    row["device_samples"] = float(h.count)
                else:
                    row["calls"] = float(h.count)
        return out


_PROFILER: Optional[Profiler] = None
_PROFILER_LOCK = threading.Lock()


def get_profiler() -> Profiler:
    global _PROFILER
    if _PROFILER is None:
        with _PROFILER_LOCK:
            if _PROFILER is None:
                _PROFILER = Profiler()
    return _PROFILER


def reset_profiler(**kwargs: Any) -> Profiler:
    """Tests / smoke gates: replace the singleton (re-reads env unless
    overridden by kwargs)."""
    global _PROFILER
    with _PROFILER_LOCK:
        _PROFILER = Profiler(**kwargs)
    return _PROFILER
