"""Structured end-to-end traces over the PerfEvents chain.

A ``Trace`` is born when KvStore accepts a key-set that produces a
publication, rides the in-process ``Publication`` /
``DecisionRouteUpdate`` objects through Decision's debounce and solve,
and is ``finish()``-ed by Fib after route programming. Each stage
contributes a timed ``Span``; spans may nest (the ELL warm/cold solve
span sits inside Decision's rebuild span).

Design points:

- Only *completed* traces enter the tracer's bounded ring. An
  in-flight trace lives solely on the carrying queue object, so a
  publication that Decision drops (no route impact) costs nothing and
  cannot leak.
- Deep call sites (``ops.spf_sparse``) must not know about queue
  plumbing: the tracer keeps a per-thread *active trace* stack
  (``activate()``), and ``span_active()`` attaches to whatever trace
  the enclosing module activated — a no-op when none is.
- ``finish()`` validates that every span is closed and properly
  nested; violations bump ``telemetry.traces_unclosed_spans`` /
  ``telemetry.traces_bad_nesting`` instead of raising, and the trace
  is kept (marked) so the smoke gate can fail loudly.
- Export: Chrome-trace JSON (``chrome://tracing`` / Perfetto, ``ph:X``
  complete events, µs) or JSONL (one trace per line).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from openr_tpu.analysis.annotations import thread_confined
from openr_tpu.telemetry.registry import get_registry

_trace_ids = itertools.count(1)


class Span:
    """One timed stage of a trace. ``dur_ms`` is perf_counter-based;
    ``ts_ms`` anchors the span on the wall clock for export."""

    __slots__ = ("name", "ts_ms", "dur_ms", "attrs", "_t0", "depth")

    def __init__(self, name: str, depth: int = 0) -> None:
        self.name = name
        self.ts_ms = time.time() * 1000.0
        self._t0 = time.perf_counter()
        self.dur_ms: Optional[float] = None
        self.attrs: Dict[str, Any] = {}
        self.depth = depth

    @property
    def closed(self) -> bool:
        return self.dur_ms is not None

    def end(self, **attrs: Any) -> "Span":
        if self.dur_ms is None:
            self.dur_ms = (time.perf_counter() - self._t0) * 1000.0
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "ts_ms": round(self.ts_ms, 3),
            "dur_ms": round(self.dur_ms, 4) if self.closed else None,
            "depth": self.depth,
            "attrs": self.attrs,
        }


@thread_confined("owner", "spans", "_stack", "complete")
class Trace:
    """An ordered list of spans sharing one trace id. Not thread-safe
    by itself — a trace is owned by exactly one module thread at a
    time (it travels through the queues with the payload); the
    ``"owner"`` confinement above states exactly that hand-off
    discipline for the shared-state rule."""

    __slots__ = ("trace_id", "origin", "ts_ms", "spans", "_stack", "complete")

    def __init__(self, origin: str = "kvstore.publish") -> None:
        self.trace_id = next(_trace_ids)
        self.origin = origin
        self.ts_ms = time.time() * 1000.0
        self.spans: List[Span] = []
        self._stack: List[Span] = []
        self.complete = False

    def begin_span(self, name: str, **attrs: Any) -> Span:
        span = Span(name, depth=len(self._stack))
        span.attrs.update(attrs)
        self.spans.append(span)
        self._stack.append(span)
        return span

    def end_span(self, span: Span, **attrs: Any) -> Span:
        span.end(**attrs)
        # pop through the stack to this span; anything above it left
        # open is a nesting bug the finish() validator will count
        while self._stack:
            top = self._stack.pop()
            if top is span:
                break
        return span

    def instant(self, name: str, **attrs: Any) -> Span:
        """A zero-duration marker (e.g. the publication itself)."""
        span = Span(name, depth=len(self._stack))
        span.attrs.update(attrs)
        span.dur_ms = 0.0
        self.spans.append(span)
        return span

    @property
    def e2e_ms(self) -> Optional[float]:
        if not self.spans:
            return None
        ends = [s.ts_ms + s.dur_ms for s in self.spans if s.closed]
        if not ends:
            return None
        return max(ends) - self.ts_ms

    def well_formed(self) -> bool:
        """Every span closed and the open/close order properly nested
        (a child span never outlives its parent's duration window)."""
        if any(not s.closed for s in self.spans):
            return False
        if self._stack:
            return False
        for i, s in enumerate(self.spans):
            for t in self.spans[i + 1 :]:
                if t.depth > s.depth and t.ts_ms < s.ts_ms + s.dur_ms:
                    # t starts inside s: it must also end inside s
                    # (tolerance for clock granularity)
                    if t.ts_ms + t.dur_ms > s.ts_ms + s.dur_ms + 0.5:
                        return False
        return True

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "origin": self.origin,
            "ts_ms": round(self.ts_ms, 3),
            "e2e_ms": round(self.e2e_ms, 4) if self.e2e_ms is not None else None,
            "complete": self.complete,
            "spans": [s.to_dict() for s in self.spans],
        }


class Tracer:
    """Process-wide sink for completed traces + per-thread active-trace
    stack for deep call sites.

    The ring depth defaults from ``OPENR_TRACE_RING`` (256): at 200+
    events/s the default overflows in ~1 s, which is why every retired
    trace's overflow is counted (``telemetry.trace_ring_overflows``)
    and a compact summary also lands in the flight recorder's much
    cheaper ring."""

    def __init__(self, ring: Optional[int] = None) -> None:
        if ring is None:
            ring = int(os.environ.get("OPENR_TRACE_RING", "256"))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, ring))
        self._tls = threading.local()
        # finish listeners: the sustained-load harness samples e2e per
        # retired trace through these instead of polling the ring (the
        # 256-deep ring overflows in ~1s at 200+ events/s)
        self._finish_listeners: List[Any] = []

    # -- lifecycle --------------------------------------------------
    def start(self, origin: str = "kvstore.publish", **attrs: Any) -> Trace:
        t = Trace(origin)
        t.instant(origin, **attrs)
        get_registry().counter_bump("telemetry.traces_started")
        return t

    def finish(self, trace: Optional[Trace], ok: bool = True) -> None:
        """Validate and retire a trace into the export ring."""
        if trace is None:
            return
        reg = get_registry()
        unclosed = sum(1 for s in trace.spans if not s.closed)
        if unclosed:
            reg.counter_bump("telemetry.traces_unclosed_spans", unclosed)
        elif not trace.well_formed():
            reg.counter_bump("telemetry.traces_bad_nesting")
        trace.complete = ok and unclosed == 0
        reg.counter_bump("telemetry.traces_finished")
        e2e = trace.e2e_ms
        if trace.complete and e2e is not None:
            reg.observe("convergence.e2e_ms", e2e)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                reg.counter_bump("telemetry.trace_ring_overflows")
            self._ring.append(trace)
            listeners = list(self._finish_listeners)
        # compact summary into the flight recorder's deeper ring — the
        # evidence that survives this ring's ~1 s overflow horizon.
        # Lazy import: flight imports this module for chrome export.
        from openr_tpu.telemetry.flight import get_flight_recorder

        fr = get_flight_recorder()
        if fr.enabled:
            fr.note(
                "trace",
                origin=trace.origin,
                trace_id=trace.trace_id,
                e2e_ms=round(e2e, 4) if e2e is not None else None,
                complete=trace.complete,
                spans=[s.name for s in trace.spans],
            )
        for fn in listeners:
            try:
                fn(trace, ok)
            except Exception:  # noqa: BLE001 - observers never poison Fib
                reg.counter_bump("telemetry.finish_listener_errors")

    def add_finish_listener(self, fn) -> None:
        """Register ``fn(trace, ok)`` called after every finish(). Runs
        on the finishing thread (Fib's event base) — keep it cheap."""
        with self._lock:
            self._finish_listeners.append(fn)

    def remove_finish_listener(self, fn) -> None:
        with self._lock:
            if fn in self._finish_listeners:
                self._finish_listeners.remove(fn)

    # -- thread-local activation ------------------------------------
    def activate(self, trace: Optional[Trace]) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(trace)

    def deactivate(self) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack:
            stack.pop()

    def active(self) -> Optional[Trace]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def span_active(self, name: str, **attrs: Any) -> Optional[Span]:
        """Open a span on the current thread's active trace (None if no
        trace is active — callers must pass the result back through
        ``end_span_active``, which tolerates None)."""
        t = self.active()
        return t.begin_span(name, **attrs) if t is not None else None

    def end_span_active(self, span: Optional[Span], **attrs: Any) -> None:
        t = self.active()
        if t is not None and span is not None:
            t.end_span(span, **attrs)

    # -- export -----------------------------------------------------
    def traces(self, limit: int = 0) -> List[Trace]:
        with self._lock:
            out = list(self._ring)
        return out[-limit:] if limit else out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def jsonl(self, limit: int = 0) -> str:
        return "\n".join(
            json.dumps(t.to_dict()) for t in self.traces(limit)
        )

    def chrome_trace(self, limit: int = 0) -> Dict[str, Any]:
        """Chrome-trace / Perfetto ``traceEvents`` document. One "pid"
        per trace so concurrent churn events render as parallel rows;
        span depth maps to "tid" to keep nesting visible."""
        events: List[Dict[str, Any]] = []
        for t in self.traces(limit):
            for s in t.spans:
                events.append(
                    {
                        "name": s.name,
                        "cat": t.origin,
                        "ph": "X",
                        "pid": t.trace_id,
                        "tid": s.depth,
                        "ts": s.ts_ms * 1000.0,
                        "dur": (s.dur_ms or 0.0) * 1000.0,
                        "args": dict(s.attrs),
                    }
                )
        return {"traceEvents": events, "displayTimeUnit": "ms"}


_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = Tracer()
    return _TRACER
