"""JAX runtime hooks: surface jit compiles as registry metrics.

The persistent compile cache (PR 1) makes first-dispatch latency
bimodal: a cache hit costs microseconds, a miss costs a full XLA
compile (30-200s over a degraded relay). Without a counter, a cache
regression reads as an unexplained latency cliff in the churn bench.
These listeners map ``jax.monitoring`` backend-compile events to:

- ``jax.compile_count``          — number of backend compiles
- ``jax.compile_ms`` histogram   — per-compile wall time distribution
- ``jax.events.<suffix>``        — count per distinct monitoring event

Import is gated: a build without jax (or with a jax too old for
``jax.monitoring``) degrades to a no-op, matching the repo's
no-new-deps rule.
"""

from __future__ import annotations

import threading

from openr_tpu.telemetry.registry import get_registry

_INSTALL_LOCK = threading.Lock()
_installed = False

# jax.monitoring event keys are paths like "/jax/core/compile" —
# anything mentioning compile/lower/trace on the duration channel is a
# stage of program building worth a histogram sample.
_COMPILE_MARKERS = ("compile", "lowering", "tracing", "jaxpr")


def _suffix(event: str) -> str:
    return event.strip("/").replace("/", ".")


def _on_event(event: str, **_kw) -> None:
    get_registry().counter_bump("jax.events." + _suffix(event))


def _on_duration(event: str, duration_secs: float, **_kw) -> None:
    reg = get_registry()
    low = event.lower()
    if any(m in low for m in _COMPILE_MARKERS):
        reg.counter_bump("jax.compile_count")
        reg.observe("jax.compile_ms", duration_secs * 1000.0)
    reg.observe("jax.duration_ms." + _suffix(event), duration_secs * 1000.0)


def install() -> bool:
    """Register the listeners once per process. Returns True when the
    hooks are live, False when jax.monitoring is unavailable."""
    global _installed
    with _INSTALL_LOCK:
        if _installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        try:
            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        _installed = True
        get_registry().counter_set("jax.hooks_installed", 1)
        return True
