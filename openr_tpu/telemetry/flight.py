"""Flight recorder: a lock-cheap ring of recent system activity that
survives trace-ring overflow, plus anomaly triggers that freeze it and
dump a post-mortem bundle to disk.

Why a second ring: the span tracer keeps ~256 *full* traces — at 200+
events/s that is ~1 s of history, gone before anyone asks "what
happened right before the p99 breach / the quarantine / the compile
storm". A flight record is a flat dict (one event window's touch
counts, one ladder rung, one audit verdict, one wave admission), so a
2048-deep ring holds tens of seconds of causally-ordered activity for
the cost of a lock + deque append per record.

Record kinds (see docs/ARCHITECTURE.md "Flight recorder"):

- ``window``   — one retired event window: tag, wall_ms, touches,
  dispatches, blocking_syncs, async_reaps, attributed device_ms, and
  per-stage {calls, host_ms, device_ms} (from
  ``ops/dispatch_accounting.py``);
- ``trace``    — compact summary of every retired trace (origin,
  e2e_ms, span names) noted by ``Tracer.finish`` — survives the trace
  ring's own overflow;
- ``engine``   — route-engine decision points (cold build, full
  refresh, frontier resolve/fallback);
- ``ladder``   — degradation-ladder walks that left the warm rung;
- ``audit``    — integrity audit verdicts;
- ``admission``— wave-scheduler admission: admitted count, class mix,
  preemption delta;
- ``anomaly``  — a trigger firing.

Triggers: each ``check()`` is a couple of registry reads per retired
event window (and per serve wave). On fire the ring FREEZES (new notes
are dropped and counted, so the pre-anomaly evidence survives), a
bundle is written (``flight.dumps.<trigger>``), and the ring thaws.

THE HAZARD (lint-enforced via ``@flight_callback``): a dump is file
I/O plus a full counter snapshot — it must NEVER run inside a solve
window. ``_fire`` defers the dump while ``dispatch_accounting`` has an
active window and flushes it at the next window retirement, which
runs strictly after the window pops.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from openr_tpu.telemetry.registry import get_registry

_DEF_RING = 2048
_DEF_DIR = "/tmp/openr_tpu_flight"


class Trigger:
    """One anomaly detector. ``check(reg)`` returns a human-readable
    reason string to fire, or None. Checks run per retired event window
    — keep them to a few registry reads."""

    name = "trigger"

    def check(self, reg) -> Optional[str]:  # pragma: no cover - interface
        raise NotImplementedError


class CounterDeltaTrigger(Trigger):
    """Fires when a counter moves by >= min_delta since the last check.
    The baseline updates on every check, so one burst fires once."""

    def __init__(self, name: str, counter: str, min_delta: int = 1) -> None:
        self.name = name
        self.counter = counter
        self.min_delta = min_delta
        self._last: Optional[float] = None

    def check(self, reg) -> Optional[str]:
        cur = float(reg.counter_get(self.counter))
        last, self._last = self._last, cur
        if last is None:
            return None
        delta = cur - last
        if delta >= self.min_delta:
            return f"{self.counter} +{delta:g} (was {last:g})"
        return None


class P99BreachTrigger(Trigger):
    """Fires when a latency histogram's p99 breaches ``factor`` x its
    own rolling EWMA baseline (and an absolute floor, so microsecond
    noise on a quiet histogram can't trip it). Re-baselines on fire so
    a sustained regression fires once, not every window."""

    def __init__(self, name: str, hist: str, factor: float = 3.0,
                 min_samples: int = 32, floor_ms: float = 5.0,
                 alpha: float = 0.1) -> None:
        self.name = name
        self.hist = hist
        self.factor = factor
        self.min_samples = min_samples
        self.floor_ms = floor_ms
        self.alpha = alpha
        self._baseline: Optional[float] = None
        self._last_count = -1

    def check(self, reg) -> Optional[str]:
        h = reg.histogram_if_exists(self.hist)
        if h is None:
            return None
        count = h.count
        if count < self.min_samples or count == self._last_count:
            return None
        self._last_count = count
        p99 = h.percentile(0.99)
        if self._baseline is None:
            self._baseline = p99
            return None
        threshold = max(self.floor_ms, self.factor * self._baseline)
        baseline = self._baseline
        self._baseline = (1.0 - self.alpha) * self._baseline + \
            self.alpha * p99
        if p99 > threshold:
            self._baseline = p99  # re-baseline: fire once per regression
            return (f"{self.hist} p99 {p99:.2f}ms > {self.factor:g}x "
                    f"baseline {baseline:.2f}ms")
        return None


class CompileAfterWarmupTrigger(Trigger):
    """Any jit or AOT compile after the profiler's warmup marker is a
    retrace — the exact regression the zero-retrace contract forbids."""

    name = "compile_after_warmup"

    def __init__(self) -> None:
        self._last: Optional[float] = None

    def check(self, reg) -> Optional[str]:
        cur = float(reg.counter_get("ops.aot_compiles")) + \
            float(reg.counter_get("jax.compile_count"))
        from openr_tpu.telemetry.profiler import get_profiler

        if not get_profiler().warm:
            self._last = cur
            return None
        last, self._last = self._last, cur
        if last is not None and cur > last:
            return f"compile after warmup (+{cur - last:g} compiles)"
        return None


class FlightRecorder:
    """Process-wide activity ring + trigger host + post-mortem dumper."""

    def __init__(
        self,
        ring: Optional[int] = None,
        enabled: Optional[bool] = None,
        dump_dir: Optional[str] = None,
        min_dump_interval_s: float = 2.0,
        max_dumps: int = 16,
    ) -> None:
        if ring is None:
            ring = int(os.environ.get("OPENR_FLIGHT_RING", str(_DEF_RING)))
        if enabled is None:
            enabled = os.environ.get("OPENR_FLIGHT", "1") != "0"
        if dump_dir is None:
            dump_dir = os.environ.get("OPENR_FLIGHT_DIR", _DEF_DIR)
        self.enabled = bool(enabled)
        self.dump_dir = dump_dir
        self.min_dump_interval_s = min_dump_interval_s
        self.max_dumps = max_dumps
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, ring))
        self._frozen = False
        self._seq = 0
        self._dumps = 0
        self._last_dump_t = 0.0
        self._triggers: List[Trigger] = []
        self._pending: Optional[tuple] = None
        budget = os.environ.get("OPENR_TOUCH_BUDGET", "")
        self._touch_budget: Optional[int] = int(budget) if budget else None

    # -- recording ---------------------------------------------------
    def note(self, kind: str, /, **data: Any) -> None:
        """Append one activity record. Lock + deque append; drops (and
        counts) while frozen so pre-anomaly evidence survives.
        ``kind`` is positional-only: a data key named ``kind`` rides in
        the record instead of colliding (the record's own kind wins)."""
        if not self.enabled:
            return
        rec = dict(data)
        rec["ts"] = round(time.time(), 4)
        rec["kind"] = kind
        with self._lock:
            if self._frozen:
                dropped = True
            else:
                dropped = False
                if len(self._ring) == self._ring.maxlen:
                    get_registry().counter_bump("flight.ring_overflows")
                self._ring.append(rec)
        if dropped:
            get_registry().counter_bump("flight.dropped_while_frozen")

    def records(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
        return out[-limit:] if limit else out

    def freeze(self) -> None:
        with self._lock:
            self._frozen = True

    def unfreeze(self) -> None:
        with self._lock:
            self._frozen = False

    # -- budgets -----------------------------------------------------
    def set_touch_budget(self, budget: Optional[int]) -> None:
        """Arm (or disarm with None) the per-window host-touch budget.
        Disarmed by default: cold builds legitimately exceed the warm
        two-touch contract."""
        self._touch_budget = budget

    # -- triggers ----------------------------------------------------
    def add_trigger(self, trigger: Trigger) -> None:
        with self._lock:
            self._triggers.append(trigger)

    def trigger_names(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._triggers]

    def check_triggers(self) -> None:
        """Run every registered trigger. Called per retired event
        window and per serve wave — a few registry reads per trigger."""
        if not self.enabled:
            return
        reg = get_registry()
        with self._lock:
            triggers = list(self._triggers)
        for t in triggers:
            try:
                reason = t.check(reg)
            except Exception:  # noqa: BLE001 - a bad trigger never
                reg.counter_bump("flight.trigger_errors")  # poisons solve
                continue
            if reason:
                self._fire(t.name, reason)

    def anomaly(self, name: str, /, reason: str = "", **data: Any) -> None:
        """Direct anomaly entry point for call sites that already know
        (quarantine conviction, ladder exhaustion) — no polling
        trigger needed."""
        if not self.enabled:
            return
        self.note("anomaly", trigger=name, reason=reason, **data)
        self._fire(name, reason)

    def _fire(self, name: str, reason: str) -> None:
        reg = get_registry()
        reg.counter_bump(f"flight.triggers.{name}")
        now = time.monotonic()
        with self._lock:
            if self._dumps >= self.max_dumps or \
                    (now - self._last_dump_t) < self.min_dump_interval_s:
                reg.counter_bump("flight.dumps_suppressed")
                return
            self._last_dump_t = now
            self._frozen = True
        # NEVER dump inside a solve window: the bundle write is file
        # I/O + a full snapshot. Defer; the next window retirement
        # (which runs after the window pops) flushes it.
        from openr_tpu.ops import dispatch_accounting as da

        if da.current_window() is not None:
            with self._lock:
                self._pending = (name, reason)
            return
        self.dump_postmortem(trigger=name, reason=reason)

    def _flush_pending(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is not None:
            self.dump_postmortem(trigger=pending[0], reason=pending[1])

    # -- window hook -------------------------------------------------
    def on_window(self, tag: str, wall_ms: float, window: Any) -> None:
        """One committed event window retired (called by
        ``dispatch_accounting.event_window`` AFTER the window pops, so
        everything here — including a deferred dump — runs outside the
        solve window)."""
        if not self.enabled:
            return
        stages = {
            t: {"calls": s[0], "host_ms": round(s[1], 4),
                "device_ms": round(s[2], 4)}
            for t, s in window.stages.items()
        }
        self.note(
            "window",
            tag=tag,
            wall_ms=round(wall_ms, 4),
            touches=window.touches,
            dispatches=window.dispatches,
            blocking_syncs=window.blocking_syncs,
            async_reaps=window.async_reaps,
            device_ms=round(window.device_ms, 4),
            stages=stages,
        )
        budget = self._touch_budget
        if budget is not None and window.touches > budget:
            self.anomaly(
                "touch_budget",
                reason=f"{tag}: {window.touches} touches > budget {budget}",
                tag=tag,
                touches=window.touches,
                budget=budget,
            )
        self._flush_pending()
        self.check_triggers()

    # -- post-mortem bundles -----------------------------------------
    def dump_postmortem(self, trigger: str = "manual",
                        reason: str = "") -> Optional[str]:
        """Write the bundle (JSON + sibling Chrome trace), thaw the
        ring, return the bundle path (None when disabled or the write
        fails — a dump failure never propagates into the pipeline)."""
        if not self.enabled:
            return None
        reg = get_registry()
        from openr_tpu.telemetry.profiler import get_profiler
        from openr_tpu.telemetry.trace import get_tracer

        prof = get_profiler()
        with self._lock:
            self._seq += 1
            seq = self._seq
            records = list(self._ring)
        bundle = {
            "trigger": trigger,
            "reason": reason,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "seq": seq,
            "records": records,
            "counters": reg.snapshot(),
            "attribution": prof.attribution(),
            "host_overhead_ratio": prof.host_overhead_ratio(),
        }
        stamp = int(bundle["ts"] * 1000.0)
        base = f"postmortem-{trigger}-{stamp}-{os.getpid()}-{seq}"
        path = os.path.join(self.dump_dir, base + ".json")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(bundle, f, indent=1)
            with open(os.path.join(self.dump_dir,
                                   base + "-trace.json"), "w") as f:
                json.dump(get_tracer().chrome_trace(), f)
        except OSError:
            reg.counter_bump("flight.dump_errors")
            path = None
        with self._lock:
            if path is not None:
                self._dumps += 1
            self._frozen = False
        if path is not None:
            reg.counter_bump(f"flight.dumps.{trigger}")
        return path


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()
_DEFAULTS_INSTALLED = False


def get_flight_recorder() -> FlightRecorder:
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


def reset_flight_recorder(**kwargs: Any) -> FlightRecorder:
    """Tests / smoke gates: replace the singleton (re-reads env unless
    overridden by kwargs). Default triggers must be re-installed."""
    global _RECORDER, _DEFAULTS_INSTALLED
    with _RECORDER_LOCK:
        _RECORDER = FlightRecorder(**kwargs)
        _DEFAULTS_INSTALLED = False
    return _RECORDER


def install_default_triggers() -> FlightRecorder:
    """Idempotent: arm the standing anomaly set — convergence p99
    breach, compile-after-warmup, reshard delta. Touch budget stays
    disarmed until a caller sets it; quarantine and ladder exhaustion
    fire directly from their call sites via ``anomaly()``."""
    global _DEFAULTS_INSTALLED
    fr = get_flight_recorder()
    with _RECORDER_LOCK:
        if _DEFAULTS_INSTALLED:
            return fr
        _DEFAULTS_INSTALLED = True
    fr.add_trigger(P99BreachTrigger("p99_breach", "convergence.e2e_ms"))
    fr.add_trigger(CompileAfterWarmupTrigger())
    fr.add_trigger(CounterDeltaTrigger("reshard", "ops.reshard_events"))
    # a handful of speculation cancels per window is the normal
    # latest-wins tax; a burst of them means every speculative
    # dispatch is being thrown away (composition churning faster than
    # the debounce terminal) — capture the window for the runbook's
    # speculation-miss-storm recipe
    fr.add_trigger(CounterDeltaTrigger(
        "spec_cancel_storm", "ops.spec_cancels", min_delta=8,
    ))
    return fr
