"""Flight recorder: a lock-cheap ring of recent system activity that
survives trace-ring overflow, plus anomaly triggers that freeze it and
dump a post-mortem bundle to disk.

Why a second ring: the span tracer keeps ~256 *full* traces — at 200+
events/s that is ~1 s of history, gone before anyone asks "what
happened right before the p99 breach / the quarantine / the compile
storm". A flight record is a flat dict (one event window's touch
counts, one ladder rung, one audit verdict, one wave admission), so a
2048-deep ring holds tens of seconds of causally-ordered activity for
the cost of a lock + deque append per record.

Record kinds (see docs/ARCHITECTURE.md "Flight recorder"):

- ``window``   — one retired event window: tag, wall_ms, touches,
  dispatches, blocking_syncs, async_reaps, attributed device_ms, and
  per-stage {calls, host_ms, device_ms} (from
  ``ops/dispatch_accounting.py``);
- ``trace``    — compact summary of every retired trace (origin,
  e2e_ms, span names) noted by ``Tracer.finish`` — survives the trace
  ring's own overflow;
- ``engine``   — route-engine decision points (cold build, full
  refresh, frontier resolve/fallback);
- ``ladder``   — degradation-ladder walks that left the warm rung;
- ``audit``    — integrity audit verdicts;
- ``admission``— wave-scheduler admission: admitted count, class mix,
  preemption delta;
- ``anomaly``  — a trigger firing.

Besides the activity ring there is a second, independent bounded ring:
the **event journal** (``journal_note`` / ``journal_mark``). Where an
activity record is a human-facing breadcrumb, a journal record is a
*replayable* fact: one adopted post-CRDT publication (area, key,
serialized value, version, trace id) or one dispatch-wave boundary
mark. The journal self-compacts: a pub record evicted from the ring
folds into a rolling per-(area, key) base LSDB, so ``base + ring
slice`` is always the complete adopted history — every post-mortem
bundle embeds both plus an anchor (checkpoint seq + FNV-1a graph
digest) and is therefore self-contained and deterministically
replayable by ``twin/replay.py``. The journal does NOT drop while the
activity ring is frozen: dropping a pub would break the
base-plus-slice completeness of every later bundle.

Triggers: each ``check()`` is a couple of registry reads per retired
event window (and per serve wave). On fire the ring FREEZES (new notes
are dropped and counted, so the pre-anomaly evidence survives), a
bundle is written (``flight.dumps.<trigger>``), and the ring thaws.

THE HAZARD (lint-enforced via ``@flight_callback``): a dump is file
I/O plus a full counter snapshot — it must NEVER run inside a solve
window. ``_fire`` defers the dump while ``dispatch_accounting`` has an
active window and flushes it at the next window retirement, which
runs strictly after the window pops.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from openr_tpu.telemetry.registry import get_registry

_DEF_RING = 2048
_DEF_JOURNAL = 4096
_DEF_MAX_DUMP_BYTES = 8 << 20
_DEF_DIR = "/tmp/openr_tpu_flight"

BUNDLE_SCHEMA = 2


def fnv1a(data: bytes, h: int = 0x811C9DC5) -> int:
    """FNV-1a over ``data`` (same digest family as ``SolverView.digest``
    and the multi-client wire parity check)."""
    for b in data:
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def _lsdb_digest(lsdb: Dict[str, Dict[str, Dict[str, Any]]]) -> int:
    """FNV-1a over a serialized base LSDB in sorted (area, key) order —
    the bundle's graph anchor digest. ``twin/replay.py`` recomputes it
    to detect a corrupt or hand-edited bundle."""
    h = 0x811C9DC5
    for area in sorted(lsdb):
        kv = lsdb[area]
        for key in sorted(kv):
            rec = kv[key]
            blob = "|".join((area, key, str(rec.get("version", 0)),
                             rec.get("value_b64") or "", ";"))
            h = fnv1a(blob.encode(), h)
    return h


def load_bundle(path: str) -> Dict[str, Any]:
    """Load a post-mortem bundle written by ``dump_postmortem`` —
    transparently handles the gzip form (``.json.gz``)."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


class Trigger:
    """One anomaly detector. ``check(reg)`` returns a human-readable
    reason string to fire, or None. Checks run per retired event window
    — keep them to a few registry reads."""

    name = "trigger"

    def check(self, reg) -> Optional[str]:  # pragma: no cover - interface
        raise NotImplementedError


class CounterDeltaTrigger(Trigger):
    """Fires when a counter moves by >= min_delta since the last check.
    The baseline updates on every check, so one burst fires once."""

    def __init__(self, name: str, counter: str, min_delta: int = 1) -> None:
        self.name = name
        self.counter = counter
        self.min_delta = min_delta
        self._last: Optional[float] = None

    def check(self, reg) -> Optional[str]:
        cur = float(reg.counter_get(self.counter))
        last, self._last = self._last, cur
        if last is None:
            return None
        delta = cur - last
        if delta >= self.min_delta:
            return f"{self.counter} +{delta:g} (was {last:g})"
        return None


class P99BreachTrigger(Trigger):
    """Fires when a latency histogram's p99 breaches ``factor`` x its
    own rolling EWMA baseline (and an absolute floor, so microsecond
    noise on a quiet histogram can't trip it). Re-baselines on fire so
    a sustained regression fires once, not every window."""

    def __init__(self, name: str, hist: str, factor: float = 3.0,
                 min_samples: int = 32, floor_ms: float = 5.0,
                 alpha: float = 0.1) -> None:
        self.name = name
        self.hist = hist
        self.factor = factor
        self.min_samples = min_samples
        self.floor_ms = floor_ms
        self.alpha = alpha
        self._baseline: Optional[float] = None
        self._last_count = -1

    def check(self, reg) -> Optional[str]:
        h = reg.histogram_if_exists(self.hist)
        if h is None:
            return None
        count = h.count
        if count < self.min_samples or count == self._last_count:
            return None
        self._last_count = count
        p99 = h.percentile(0.99)
        if self._baseline is None:
            self._baseline = p99
            return None
        threshold = max(self.floor_ms, self.factor * self._baseline)
        baseline = self._baseline
        self._baseline = (1.0 - self.alpha) * self._baseline + \
            self.alpha * p99
        if p99 > threshold:
            self._baseline = p99  # re-baseline: fire once per regression
            return (f"{self.hist} p99 {p99:.2f}ms > {self.factor:g}x "
                    f"baseline {baseline:.2f}ms")
        return None


class CompileAfterWarmupTrigger(Trigger):
    """Any jit or AOT compile after the profiler's warmup marker is a
    retrace — the exact regression the zero-retrace contract forbids."""

    name = "compile_after_warmup"

    def __init__(self) -> None:
        self._last: Optional[float] = None

    def check(self, reg) -> Optional[str]:
        cur = float(reg.counter_get("ops.aot_compiles")) + \
            float(reg.counter_get("jax.compile_count"))
        from openr_tpu.telemetry.profiler import get_profiler

        if not get_profiler().warm:
            self._last = cur
            return None
        last, self._last = self._last, cur
        if last is not None and cur > last:
            return f"compile after warmup (+{cur - last:g} compiles)"
        return None


class FlightRecorder:
    """Process-wide activity ring + trigger host + post-mortem dumper."""

    def __init__(
        self,
        ring: Optional[int] = None,
        enabled: Optional[bool] = None,
        dump_dir: Optional[str] = None,
        min_dump_interval_s: float = 2.0,
        max_dumps: int = 16,
        journal: Optional[int] = None,
        max_dump_bytes: Optional[int] = None,
        gzip_dumps: Optional[bool] = None,
    ) -> None:
        if ring is None:
            ring = int(os.environ.get("OPENR_FLIGHT_RING", str(_DEF_RING)))
        if enabled is None:
            enabled = os.environ.get("OPENR_FLIGHT", "1") != "0"
        if dump_dir is None:
            dump_dir = os.environ.get("OPENR_FLIGHT_DIR", _DEF_DIR)
        if journal is None:
            journal = int(os.environ.get(
                "OPENR_FLIGHT_JOURNAL", str(_DEF_JOURNAL)))
        if max_dump_bytes is None:
            max_dump_bytes = int(os.environ.get(
                "OPENR_FLIGHT_MAX_DUMP_BYTES", str(_DEF_MAX_DUMP_BYTES)))
        if gzip_dumps is None:
            gzip_dumps = os.environ.get("OPENR_FLIGHT_GZIP", "0") == "1"
        self.enabled = bool(enabled)
        self.dump_dir = dump_dir
        self.min_dump_interval_s = min_dump_interval_s
        self.max_dumps = max_dumps
        self.max_dump_bytes = max(4096, int(max_dump_bytes))
        self.gzip_dumps = bool(gzip_dumps)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, ring))
        self._frozen = False
        self._seq = 0
        self._dumps = 0
        self._last_dump_t = 0.0
        self._triggers: List[Trigger] = []
        self._pending: Optional[tuple] = None
        # -- event journal: pub/mark ring + rolling base LSDB ---------
        self._journal: deque = deque(maxlen=max(64, journal))
        self._journal_seq = 0
        self._journal_base: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._journal_base_seq = 0
        self._anchor_provider: Optional[Callable[[], Dict[str, Any]]] = None
        self._counter_baseline: Dict[str, float] = {}
        budget = os.environ.get("OPENR_TOUCH_BUDGET", "")
        self._touch_budget: Optional[int] = int(budget) if budget else None

    # -- recording ---------------------------------------------------
    def note(self, kind: str, /, **data: Any) -> None:
        """Append one activity record. Lock + deque append; drops (and
        counts) while frozen so pre-anomaly evidence survives.
        ``kind`` is positional-only: a data key named ``kind`` rides in
        the record instead of colliding (the record's own kind wins)."""
        if not self.enabled:
            return
        rec = dict(data)
        rec["ts"] = round(time.time(), 4)
        rec["kind"] = kind
        with self._lock:
            if self._frozen:
                dropped = True
            else:
                dropped = False
                if len(self._ring) == self._ring.maxlen:
                    get_registry().counter_bump("flight.ring_overflows")
                self._ring.append(rec)
        if dropped:
            get_registry().counter_bump("flight.dropped_while_frozen")

    def records(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
        return out[-limit:] if limit else out

    def freeze(self) -> None:
        with self._lock:
            self._frozen = True

    def unfreeze(self) -> None:
        with self._lock:
            self._frozen = False

    # -- event journal -----------------------------------------------
    def journal_anchor(self, area: str,
                       key_vals: Dict[str, Dict[str, Any]]) -> None:
        """Seed (or extend) the rolling base LSDB wholesale — used by a
        source whose starting state never flowed through ``journal_note``
        (e.g. a twin built directly from a topology). ``key_vals`` maps
        key -> {value_b64, version, originator}."""
        if not self.enabled:
            return
        with self._lock:
            base = self._journal_base.setdefault(area, {})
            for key, rec in key_vals.items():
                base[key] = dict(rec)

    def journal_note(self, area: str, key: str, *, value_b64: str,
                     version: int, originator: str = "",
                     trace_id: Optional[int] = None) -> None:
        """Record one adopted post-CRDT publication. Keeps appending
        while the activity ring is frozen: the journal is bounded and
        self-compacting, and a dropped pub would break the
        base-plus-slice completeness of every later bundle."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {
            "area": area,
            "key": key,
            "value_b64": value_b64,
            "version": int(version),
            "originator": originator,
        }
        if trace_id is not None:
            rec["trace_id"] = trace_id
        with self._lock:
            self._journal_seq += 1
            rec["seq"] = self._journal_seq
            self._journal_append_locked(rec)

    def journal_mark(self, kind: str, /, **data: Any) -> None:
        """Record one dispatch-wave / debounce-window boundary (kind
        ``wave``) or an analyzer verdict (kind ``analysis``). Marks
        delimit the replay windows: the replayer applies the pubs since
        the previous mark, then converges exactly the mark's vantages."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {"mark": kind}
        rec.update(data)
        with self._lock:
            self._journal_seq += 1
            rec["seq"] = self._journal_seq
            self._journal_append_locked(rec)

    def _journal_append_locked(self, rec: Dict[str, Any]) -> None:
        ring = self._journal
        if len(ring) == ring.maxlen:
            evicted = ring[0]
            if "mark" not in evicted:
                self._journal_base.setdefault(evicted["area"], {})[
                    evicted["key"]] = {
                    "value_b64": evicted["value_b64"],
                    "version": evicted["version"],
                    "originator": evicted.get("originator", ""),
                }
            self._journal_base_seq = evicted["seq"]
            get_registry().counter_bump("flight.journal_evictions")
        ring.append(rec)

    def journal_records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._journal]

    def journal_len(self) -> int:
        with self._lock:
            return len(self._journal)

    def journal_base(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        with self._lock:
            return {a: {k: dict(v) for k, v in kv.items()}
                    for a, kv in self._journal_base.items()}

    def set_anchor_provider(
            self, fn: Optional[Callable[[], Dict[str, Any]]]) -> None:
        """Install a callable returning extra anchor fields for the next
        bundle (the state plane installs one that reports its checkpoint
        seq). Errors are swallowed and counted — same contract as the
        dump itself."""
        self._anchor_provider = fn

    def _anchor_digest_locked(self) -> int:
        return _lsdb_digest(self._journal_base)

    def journal_anchor_digest(self) -> int:
        """FNV-1a digest over the rolling base LSDB (sorted area/key
        order) — the bundle's graph anchor, recomputed by the replayer
        to detect a corrupt or mis-paired bundle."""
        with self._lock:
            return self._anchor_digest_locked()

    # -- budgets -----------------------------------------------------
    def set_touch_budget(self, budget: Optional[int]) -> None:
        """Arm (or disarm with None) the per-window host-touch budget.
        Disarmed by default: cold builds legitimately exceed the warm
        two-touch contract."""
        self._touch_budget = budget

    # -- triggers ----------------------------------------------------
    def add_trigger(self, trigger: Trigger) -> None:
        with self._lock:
            self._triggers.append(trigger)

    def trigger_names(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._triggers]

    def check_triggers(self) -> None:
        """Run every registered trigger. Called per retired event
        window and per serve wave — a few registry reads per trigger."""
        if not self.enabled:
            return
        reg = get_registry()
        with self._lock:
            triggers = list(self._triggers)
        for t in triggers:
            try:
                reason = t.check(reg)
            except Exception:  # noqa: BLE001 - a bad trigger never
                reg.counter_bump("flight.trigger_errors")  # poisons solve
                continue
            if reason:
                self._fire(t.name, reason)

    def anomaly(self, name: str, /, reason: str = "", **data: Any) -> None:
        """Direct anomaly entry point for call sites that already know
        (quarantine conviction, ladder exhaustion) — no polling
        trigger needed."""
        if not self.enabled:
            return
        self.note("anomaly", trigger=name, reason=reason, **data)
        self._fire(name, reason)

    def _fire(self, name: str, reason: str) -> None:
        reg = get_registry()
        reg.counter_bump(f"flight.triggers.{name}")
        now = time.monotonic()
        with self._lock:
            if self._dumps >= self.max_dumps or \
                    (now - self._last_dump_t) < self.min_dump_interval_s:
                reg.counter_bump("flight.dumps_suppressed")
                return
            self._last_dump_t = now
            self._frozen = True
        # NEVER dump inside a solve window: the bundle write is file
        # I/O + a full snapshot. Defer; the next window retirement
        # (which runs after the window pops) flushes it.
        from openr_tpu.ops import dispatch_accounting as da

        if da.current_window() is not None:
            with self._lock:
                self._pending = (name, reason)
            return
        self.dump_postmortem(trigger=name, reason=reason)

    def _flush_pending(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is not None:
            self.dump_postmortem(trigger=pending[0], reason=pending[1])

    # -- window hook -------------------------------------------------
    def on_window(self, tag: str, wall_ms: float, window: Any) -> None:
        """One committed event window retired (called by
        ``dispatch_accounting.event_window`` AFTER the window pops, so
        everything here — including a deferred dump — runs outside the
        solve window)."""
        if not self.enabled:
            return
        stages = {
            t: {"calls": s[0], "host_ms": round(s[1], 4),
                "device_ms": round(s[2], 4)}
            for t, s in window.stages.items()
        }
        self.note(
            "window",
            tag=tag,
            wall_ms=round(wall_ms, 4),
            touches=window.touches,
            dispatches=window.dispatches,
            blocking_syncs=window.blocking_syncs,
            async_reaps=window.async_reaps,
            device_ms=round(window.device_ms, 4),
            stages=stages,
        )
        budget = self._touch_budget
        if budget is not None and window.touches > budget:
            self.anomaly(
                "touch_budget",
                reason=f"{tag}: {window.touches} touches > budget {budget}",
                tag=tag,
                touches=window.touches,
                budget=budget,
            )
        self._flush_pending()
        self.check_triggers()

    # -- post-mortem bundles -----------------------------------------
    def _encode_bundle(self, bundle: Dict[str, Any]) -> bytes:
        """Serialize compactly; if over the size ceiling, shed the bulk
        in evidence order — activity records first, then the oldest
        journal pubs (folded into the bundle's own anchor LSDB so the
        bundle stays replayable, just from a later anchor)."""
        payload = json.dumps(bundle, separators=(",", ":")).encode()
        truncated = False
        while len(payload) > self.max_dump_bytes:
            recs = bundle["records"]
            jrn = bundle["journal"]
            if recs:
                del recs[:max(1, len(recs) // 2)]
            elif len(jrn["records"]) > 1:
                drop = jrn["records"][:max(1, len(jrn["records"]) // 2)]
                del jrn["records"][:len(drop)]
                lsdb = jrn["anchor"]["lsdb"]
                for rec in drop:
                    if "mark" in rec:
                        continue
                    lsdb.setdefault(rec["area"], {})[rec["key"]] = {
                        "value_b64": rec["value_b64"],
                        "version": rec["version"],
                        "originator": rec.get("originator", ""),
                    }
                    jrn["base_seq"] = rec["seq"]
                # the anchor moved: its digest no longer matches the
                # recorded one, so recompute over the folded LSDB
                jrn["anchor"]["graph_digest"] = _lsdb_digest(lsdb)
            else:
                break
            truncated = True
            bundle["truncated"] = True
            payload = json.dumps(bundle, separators=(",", ":")).encode()
        if truncated:
            get_registry().counter_bump("flight.dump_truncations")
        return payload

    def dump_postmortem(self, trigger: str = "manual",
                        reason: str = "") -> Optional[str]:
        """Write the bundle (JSON or gzip + sibling Chrome trace), thaw
        the ring, return the bundle path (None when disabled or the
        write fails — a dump failure never propagates into the
        pipeline). The bundle embeds the journal slice plus the LSDB
        anchor, so it is self-contained for ``twin/replay.py``."""
        if not self.enabled:
            return None
        reg = get_registry()
        from openr_tpu.telemetry.profiler import get_profiler
        from openr_tpu.telemetry.trace import get_tracer

        prof = get_profiler()
        with self._lock:
            self._seq += 1
            seq = self._seq
            records = list(self._ring)
            journal_records = [dict(r) for r in self._journal]
            journal_base = {a: {k: dict(v) for k, v in kv.items()}
                            for a, kv in self._journal_base.items()}
            base_seq = self._journal_base_seq
            graph_digest = self._anchor_digest_locked()
        anchor: Dict[str, Any] = {
            "checkpoint_seq": base_seq,
            "graph_digest": graph_digest,
            "lsdb": journal_base,
        }
        provider = self._anchor_provider
        if provider is not None:
            try:
                anchor.update(provider() or {})
            except Exception:  # noqa: BLE001 - anchor extras are
                reg.counter_bump("flight.anchor_errors")  # best-effort
        counters = reg.snapshot()
        # the baseline dict is swapped wholesale under _lock on reset;
        # grab the reference under the same lock so a dump racing a
        # reset reads one coherent snapshot, never a torn swap
        with self._lock:
            baseline = self._counter_baseline
        delta = {k: round(v - baseline.get(k, 0.0), 6)
                 for k, v in counters.items()
                 if v != baseline.get(k, 0.0)}
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "trigger": trigger,
            "reason": reason,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "seq": seq,
            "records": records,
            "counters": counters,
            "counters_delta": delta,
            "journal": {
                "base_seq": base_seq,
                "records": journal_records,
                "anchor": anchor,
            },
            "attribution": prof.attribution(),
            "host_overhead_ratio": prof.host_overhead_ratio(),
        }
        stamp = int(bundle["ts"] * 1000.0)
        base = f"postmortem-{trigger}-{stamp}-{os.getpid()}-{seq}"
        path = os.path.join(self.dump_dir,
                            base + (".json.gz" if self.gzip_dumps
                                    else ".json"))
        try:
            payload = self._encode_bundle(bundle)
            os.makedirs(self.dump_dir, exist_ok=True)
            if self.gzip_dumps:
                with gzip.open(path, "wb") as f:
                    f.write(payload)
            else:
                with open(path, "wb") as f:
                    f.write(payload)
            reg.observe("ops.flight.dump_bytes",
                        float(os.path.getsize(path)))
            with open(os.path.join(self.dump_dir,
                                   base + "-trace.json"), "w") as f:
                json.dump(get_tracer().chrome_trace(), f,
                          separators=(",", ":"))
        except (OSError, TypeError, ValueError):
            reg.counter_bump("flight.dump_errors")
            path = None
        with self._lock:
            if path is not None:
                self._dumps += 1
                self._counter_baseline = dict(counters)
            self._frozen = False
        if path is not None:
            reg.counter_bump(f"flight.dumps.{trigger}")
        return path


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()
_DEFAULTS_INSTALLED = False


def get_flight_recorder() -> FlightRecorder:
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


def reset_flight_recorder(**kwargs: Any) -> FlightRecorder:
    """Tests / smoke gates: replace the singleton (re-reads env unless
    overridden by kwargs). Default triggers must be re-installed."""
    global _RECORDER, _DEFAULTS_INSTALLED
    with _RECORDER_LOCK:
        _RECORDER = FlightRecorder(**kwargs)
        _DEFAULTS_INSTALLED = False
    return _RECORDER


def install_default_triggers() -> FlightRecorder:
    """Idempotent: arm the standing anomaly set — convergence p99
    breach, compile-after-warmup, reshard delta. Touch budget stays
    disarmed until a caller sets it; quarantine and ladder exhaustion
    fire directly from their call sites via ``anomaly()``."""
    global _DEFAULTS_INSTALLED
    fr = get_flight_recorder()
    with _RECORDER_LOCK:
        if _DEFAULTS_INSTALLED:
            return fr
        _DEFAULTS_INSTALLED = True
    fr.add_trigger(P99BreachTrigger("p99_breach", "convergence.e2e_ms"))
    fr.add_trigger(CompileAfterWarmupTrigger())
    fr.add_trigger(CounterDeltaTrigger("reshard", "ops.reshard_events"))
    # a handful of speculation cancels per window is the normal
    # latest-wins tax; a burst of them means every speculative
    # dispatch is being thrown away (composition churning faster than
    # the debounce terminal) — capture the window for the runbook's
    # speculation-miss-storm recipe
    fr.add_trigger(CounterDeltaTrigger(
        "spec_cancel_storm", "ops.spec_cancels", min_delta=8,
    ))
    return fr
