"""Device-mesh sharding of the all-sources SPF.

The scaling axis of this framework is the *source* dimension of the
batched shortest-path computation: every device owns a contiguous block of
source rows of the distance matrix while the (transit-masked) one-hop
metric matrix is replicated. Relaxation steps are purely local; the only
cross-device communication is a 1-bit "any row changed" OR (``psum``) per
iteration to agree on the fixed point — so the kernel scales linearly
across ICI with no distance-matrix traffic at all.

This is the TPU-native analogue of the reference's scale story (per-source
Dijkstra memoization + multi-area partitioning, reference:
openr/decision/LinkState.cpp:794); instead of memoizing per source we
recompute all sources in parallel from the HBM-resident snapshot.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from openr_tpu.utils.jax_compat import shard_map

from openr_tpu.ops.spf import INF, _mask_transit_rows, _minplus

SOURCES_AXIS = "sources"


def make_mesh(devices=None, axis_name: str = SOURCES_AXIS) -> Mesh:
    """1-D mesh over all (or the given) devices, sharding the source axis."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def pad_for_mesh(n: int, mesh: Mesh, align: int = 128) -> int:
    """Rows must divide evenly across mesh devices and stay lane-aligned
    (128 on TPU; tests on virtual CPU meshes may pass a smaller align)."""
    devs = mesh.devices.size
    block = align * devs
    return max(block, ((n + block - 1) // block) * block)


class ShardingPlan:
    """Build-time placement contract for the mesh-sharded engines.

    Every resident buffer the sharded dispatches touch gets an explicit
    ``NamedSharding`` at creation so the steady-state churn path never
    pays an XLA-inserted reshard or replication copy: row-striped
    residents (`[n_pad, ...]` products, digests) live on the source
    axis, the band/segment topology tensors and small edge uploads are
    replicated to every device, and destination-batched KSP2 masks are
    striped over the same axis by batch row.

    ``ensure`` is the churn-path tripwire: it verifies an operand is
    already committed to its planned placement, and when it is not it
    bumps ``ops.reshard_events`` and corrects the placement with an
    explicit ``device_put`` — so the acceptance gate
    (``ops.reshard_events == 0`` across a churn run) measures real
    placement discipline rather than hoping ``jax.transfer_guard``
    notices (device-to-device resharding is invisible to the guard).
    """

    __slots__ = ("mesh", "axis", "rows", "vec", "batch3", "replicated")

    def __init__(self, mesh: Mesh, axis: str = SOURCES_AXIS) -> None:
        self.mesh = mesh
        self.axis = axis
        # [n_pad, W]-shaped residents, striped by source row
        self.rows = NamedSharding(mesh, P(axis, None))
        # [n_pad] per-row vectors (digests)
        self.vec = NamedSharding(mesh, P(axis))
        # [B, slots, k] destination-batched mask stacks, striped by batch
        self.batch3 = NamedSharding(mesh, P(axis, None, None))
        # topology bands / edge uploads / overload vector: every device
        # reads all of it, so commit a replica per device up front
        self.replicated = NamedSharding(mesh, P())

    def place(self, x, sharding: NamedSharding) -> jnp.ndarray:
        """Explicit build-time placement (host->device; transfer-guard
        exempt because device_put is an explicit transfer)."""
        return jax.device_put(jnp.asarray(x), sharding)

    def shard_rows(self, x) -> jnp.ndarray:
        return self.place(x, self.rows if np.ndim(x) > 1 else self.vec)

    def replicate(self, x) -> jnp.ndarray:
        return self.place(x, self.replicated)

    def ensure(self, x: jnp.ndarray, sharding: NamedSharding,
               name: str = "") -> jnp.ndarray:
        """Churn-path placement check: already-committed-as-planned is a
        no-op; anything else is a reshard event (counted, then fixed)."""
        cur = getattr(x, "sharding", None)
        if cur is not None and cur.is_equivalent_to(sharding, x.ndim):
            return x
        from openr_tpu.telemetry import get_registry

        get_registry().counter_bump("ops.reshard_events")
        return jax.device_put(x, sharding)


@functools.lru_cache(maxsize=None)
def replicated_jit(fn, mesh: Mesh):
    """A jitted dispatch of ``fn`` whose every input and output is
    committed replicated across ``mesh``.

    Used for the small patch dispatches (`_patch_bands` /
    `_patch_segments`): their outputs feed the shard_map churn
    dispatches as replicated operands, so committing them replicated at
    the producer keeps XLA from inserting a broadcast copy at the
    consumer (SNIPPETS.md [2]: out specs of one dispatch must match the
    in specs of the next). A single NamedSharding broadcasts as a
    pytree prefix over every argument/result.
    """
    rep = NamedSharding(mesh, P())
    return jax.jit(fn, in_shardings=rep, out_shardings=rep)


@functools.partial(jax.jit, static_argnames=("mesh",))
def sharded_all_sources(
    w: jnp.ndarray, overloaded: jnp.ndarray, mesh: Mesh
) -> jnp.ndarray:
    """All-sources shortest-path distances [N, N], rows sharded over the
    mesh. ``w`` must be padded so N % mesh.devices.size == 0.

    Bellman-Ford over the replicated transit matrix; convergence agreed
    via a psum'd change flag so every shard exits the while_loop together.
    """
    n = w.shape[0]
    t = _mask_transit_rows(w, overloaded)

    def shard_fn(w_blk: jnp.ndarray, t_full: jnp.ndarray) -> jnp.ndarray:
        rows = w_blk.shape[0]
        shard_idx = jax.lax.axis_index(SOURCES_AXIS)
        row_ids = shard_idx * rows + jnp.arange(rows, dtype=jnp.int32)
        # initial distances: this shard's source rows, diagonal zeroed
        d0 = w_blk
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, n), 1)
        d0 = jnp.where(col_ids == row_ids[:, None], jnp.int32(0), d0)

        def cond(state):
            _, changed, it = state
            return jnp.logical_and(changed > 0, it < n)

        def body(state):
            d, _, it = state
            nxt = jnp.minimum(d, _minplus(d, t_full))
            local_changed = jnp.any(nxt < d).astype(jnp.int32)
            global_changed = jax.lax.psum(local_changed, SOURCES_AXIS)
            return nxt, global_changed, it + 1

        d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
        return d

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SOURCES_AXIS, None), P(None, None)),
        out_specs=P(SOURCES_AXIS, None),
    )(w, t)


@functools.partial(jax.jit, static_argnames=("mesh",))
def sharded_reconvergence_step(
    w: jnp.ndarray,
    overloaded: jnp.ndarray,
    dest_mask: jnp.ndarray,
    mesh: Mesh,
):
    """One full sharded "reconvergence" step: all-sources SPF plus a
    per-source nearest-advertiser reduction (the batched analogue of
    best-route selection's min-metric destination filter,
    reference: openr/decision/Decision.cpp:1099 getMinCostNodes).

    dest_mask: [P, N] bool — advertisers per prefix group.
    Returns (distances [N, N] row-sharded, best_metric [N, P]).
    """
    d = sharded_all_sources(w, overloaded, mesh)

    def reduce_fn(d_blk: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        # min over advertisers of each prefix: [rows, N] x [P, N] -> [rows, P]
        masked = jnp.where(mask[None, :, :], d_blk[:, None, :], INF)
        return jnp.min(masked, axis=2)

    best = shard_map(
        reduce_fn,
        mesh=mesh,
        in_specs=(P(SOURCES_AXIS, None), P(None, None)),
        out_specs=P(SOURCES_AXIS, None),
    )(d, dest_mask)
    return d, best
