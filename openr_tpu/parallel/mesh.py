"""Device-mesh sharding of the all-sources SPF.

The scaling axis of this framework is the *source* dimension of the
batched shortest-path computation: every device owns a contiguous block of
source rows of the distance matrix while the (transit-masked) one-hop
metric matrix is replicated. Relaxation steps are purely local; the only
cross-device communication is a 1-bit "any row changed" OR (``psum``) per
iteration to agree on the fixed point — so the kernel scales linearly
across ICI with no distance-matrix traffic at all.

This is the TPU-native analogue of the reference's scale story (per-source
Dijkstra memoization + multi-area partitioning, reference:
openr/decision/LinkState.cpp:794); instead of memoizing per source we
recompute all sources in parallel from the HBM-resident snapshot.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from openr_tpu.utils.jax_compat import shard_map

from openr_tpu.ops.spf import INF, _mask_transit_rows, _minplus

SOURCES_AXIS = "sources"


def make_mesh(devices=None, axis_name: str = SOURCES_AXIS) -> Mesh:
    """1-D mesh over all (or the given) devices, sharding the source axis."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def pad_for_mesh(n: int, mesh: Mesh, align: int = 128) -> int:
    """Rows must divide evenly across mesh devices and stay lane-aligned
    (128 on TPU; tests on virtual CPU meshes may pass a smaller align)."""
    devs = mesh.devices.size
    block = align * devs
    return max(block, ((n + block - 1) // block) * block)


@functools.partial(jax.jit, static_argnames=("mesh",))
def sharded_all_sources(
    w: jnp.ndarray, overloaded: jnp.ndarray, mesh: Mesh
) -> jnp.ndarray:
    """All-sources shortest-path distances [N, N], rows sharded over the
    mesh. ``w`` must be padded so N % mesh.devices.size == 0.

    Bellman-Ford over the replicated transit matrix; convergence agreed
    via a psum'd change flag so every shard exits the while_loop together.
    """
    n = w.shape[0]
    t = _mask_transit_rows(w, overloaded)

    def shard_fn(w_blk: jnp.ndarray, t_full: jnp.ndarray) -> jnp.ndarray:
        rows = w_blk.shape[0]
        shard_idx = jax.lax.axis_index(SOURCES_AXIS)
        row_ids = shard_idx * rows + jnp.arange(rows, dtype=jnp.int32)
        # initial distances: this shard's source rows, diagonal zeroed
        d0 = w_blk
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, n), 1)
        d0 = jnp.where(col_ids == row_ids[:, None], jnp.int32(0), d0)

        def cond(state):
            _, changed, it = state
            return jnp.logical_and(changed > 0, it < n)

        def body(state):
            d, _, it = state
            nxt = jnp.minimum(d, _minplus(d, t_full))
            local_changed = jnp.any(nxt < d).astype(jnp.int32)
            global_changed = jax.lax.psum(local_changed, SOURCES_AXIS)
            return nxt, global_changed, it + 1

        d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.int32(1), 0))
        return d

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SOURCES_AXIS, None), P(None, None)),
        out_specs=P(SOURCES_AXIS, None),
    )(w, t)


@functools.partial(jax.jit, static_argnames=("mesh",))
def sharded_reconvergence_step(
    w: jnp.ndarray,
    overloaded: jnp.ndarray,
    dest_mask: jnp.ndarray,
    mesh: Mesh,
):
    """One full sharded "reconvergence" step: all-sources SPF plus a
    per-source nearest-advertiser reduction (the batched analogue of
    best-route selection's min-metric destination filter,
    reference: openr/decision/Decision.cpp:1099 getMinCostNodes).

    dest_mask: [P, N] bool — advertisers per prefix group.
    Returns (distances [N, N] row-sharded, best_metric [N, P]).
    """
    d = sharded_all_sources(w, overloaded, mesh)

    def reduce_fn(d_blk: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        # min over advertisers of each prefix: [rows, N] x [P, N] -> [rows, P]
        masked = jnp.where(mask[None, :, :], d_blk[:, None, :], INF)
        return jnp.min(masked, axis=2)

    best = shard_map(
        reduce_fn,
        mesh=mesh,
        in_specs=(P(SOURCES_AXIS, None), P(None, None)),
        out_specs=P(SOURCES_AXIS, None),
    )(d, dest_mask)
    return d, best
