"""Typed in-process message bus: multi-writer fan-out queues.

Behavioral parity with the reference ``openr/messaging/ReplicateQueue.h``
and ``Queue.h``: a ``ReplicateQueue`` replicates every pushed message to
every reader endpoint; readers block on ``get`` until a message arrives or
the queue closes. This is the only inter-module communication mechanism in
the daemon (modules share no mutable state — reference: Main.cpp:269-280
wires 11 of these between the modules).

Service-plane instrumentation: every named reader exports a depth gauge
(``messaging.queue.depth.<reader>``), an oldest-item-age gauge
(``messaging.queue.age_ms.<reader>``) and a high-watermark counter
(``messaging.queue.hwm.<reader>``) through the process registry — the
primary backpressure signals the admission path keys on. A reader may
opt into a bound (``maxlen``): when full, the OLDEST item is dropped to
admit the new one (newest state wins; KvStore-style streams are
re-convergent) and ``messaging.queue.overflow.<reader>`` counts the
shed instead of the queue growing without bound.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Deque, Generic, List, Optional, Tuple, TypeVar

from openr_tpu.telemetry import get_registry

T = TypeVar("T")

_METRIC_SAFE_RE = re.compile(r"[^a-z0-9_]+")


def _metric_leaf(name: str) -> str:
    """Reader name -> fb303-safe metric leaf (``decision:a`` ->
    ``decision_a``)."""
    return _METRIC_SAFE_RE.sub("_", name.lower()).strip("_") or "anon"


class QueueClosedError(Exception):
    """Raised by get() once the queue is closed and drained."""


class QueueTimeoutError(Exception):
    """Raised by get(timeout=...) when no message arrives in time."""


class RQueue(Generic[T]):
    """Reader endpoint of a ReplicateQueue (reference: messaging/Queue.h)."""

    def __init__(self, name: str = "", maxlen: Optional[int] = None):
        self.name = name
        # (enqueue_monotonic, item): the timestamp feeds the age gauge
        self._items: Deque[Tuple[float, T]] = deque()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        self._maxlen = maxlen
        self._hwm = 0
        self._overflows = 0
        self._leaf = _metric_leaf(name)
        if name:
            reg = get_registry()
            reg.gauge(f"messaging.queue.depth.{self._leaf}", self.size)
            reg.gauge(
                f"messaging.queue.age_ms.{self._leaf}", self.oldest_age_ms
            )

    def _push(self, item: T) -> None:
        overflowed = False
        with self._cv:
            if self._closed:
                return
            if (
                self._maxlen is not None
                and len(self._items) >= self._maxlen
            ):
                # bounded mode: shed the OLDEST entry so the newest
                # state wins, and count it — never grow silently
                self._items.popleft()
                self._overflows += 1
                overflowed = True
            self._items.append((time.monotonic(), item))
            depth = len(self._items)
            new_hwm = depth > self._hwm
            if new_hwm:
                self._hwm = depth
            self._cv.notify()
        if self.name:
            reg = get_registry()
            if overflowed:
                reg.counter_bump(f"messaging.queue.overflow.{self._leaf}")
            if new_hwm:
                key = f"messaging.queue.hwm.{self._leaf}"
                reg.counter_set(key, max(reg.counter_get(key), depth))

    def _close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def get(self, timeout: Optional[float] = None) -> T:
        """Block until a message is available. Raises QueueClosedError when
        the queue is closed and fully drained; QueueTimeoutError on
        timeout."""
        with self._cv:
            if not self._cv.wait_for(
                lambda: self._items or self._closed, timeout=timeout
            ):
                raise QueueTimeoutError(self.name)
            if self._items:
                return self._items.popleft()[1]
            raise QueueClosedError(self.name)

    def try_get(self) -> Optional[T]:
        with self._cv:
            if self._items:
                return self._items.popleft()[1]
            if self._closed:
                raise QueueClosedError(self.name)
            return None

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    def oldest_age_ms(self) -> float:
        """Age of the head-of-line item — the time the slowest consumer
        is running behind (0 when drained)."""
        with self._lock:
            if not self._items:
                return 0.0
            return (time.monotonic() - self._items[0][0]) * 1000.0

    @property
    def high_watermark(self) -> int:
        with self._lock:
            return self._hwm

    @property
    def overflows(self) -> int:
        with self._lock:
            return self._overflows

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed and not self._items


class ReplicateQueue(Generic[T]):
    """Multi-writer fan-out queue: every push is replicated to every
    reader. reference: messaging/ReplicateQueue.h:22."""

    def __init__(self, name: str = ""):
        self.name = name
        self._readers: List[RQueue[T]] = []
        self._lock = threading.Lock()
        self._closed = False
        self._writes = 0

    def get_reader(
        self, name: str = "", maxlen: Optional[int] = None
    ) -> RQueue[T]:
        with self._lock:
            if self._closed:
                raise QueueClosedError(self.name)
            reader = RQueue(
                name or f"{self.name}::reader{len(self._readers)}",
                maxlen=maxlen,
            )
            self._readers.append(reader)
            return reader

    def push(self, item: T) -> bool:
        with self._lock:
            if self._closed:
                return False
            readers = list(self._readers)
            self._writes += 1
        for reader in readers:
            reader._push(item)
        return True

    def close(self) -> None:
        with self._lock:
            self._closed = True
            readers = list(self._readers)
        for reader in readers:
            reader._close()

    def open(self) -> None:
        with self._lock:
            self._closed = False

    @property
    def num_readers(self) -> int:
        with self._lock:
            return len(self._readers)

    @property
    def num_writes(self) -> int:
        with self._lock:
            return self._writes
