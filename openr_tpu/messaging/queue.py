"""Typed in-process message bus: multi-writer fan-out queues.

Behavioral parity with the reference ``openr/messaging/ReplicateQueue.h``
and ``Queue.h``: a ``ReplicateQueue`` replicates every pushed message to
every reader endpoint; readers block on ``get`` until a message arrives or
the queue closes. This is the only inter-module communication mechanism in
the daemon (modules share no mutable state — reference: Main.cpp:269-280
wires 11 of these between the modules).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Generic, List, Optional, TypeVar

T = TypeVar("T")


class QueueClosedError(Exception):
    """Raised by get() once the queue is closed and drained."""


class QueueTimeoutError(Exception):
    """Raised by get(timeout=...) when no message arrives in time."""


class RQueue(Generic[T]):
    """Reader endpoint of a ReplicateQueue (reference: messaging/Queue.h)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._items: Deque[T] = deque()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False

    def _push(self, item: T) -> None:
        with self._cv:
            if self._closed:
                return
            self._items.append(item)
            self._cv.notify()

    def _close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def get(self, timeout: Optional[float] = None) -> T:
        """Block until a message is available. Raises QueueClosedError when
        the queue is closed and fully drained; QueueTimeoutError on
        timeout."""
        with self._cv:
            if not self._cv.wait_for(
                lambda: self._items or self._closed, timeout=timeout
            ):
                raise QueueTimeoutError(self.name)
            if self._items:
                return self._items.popleft()
            raise QueueClosedError(self.name)

    def try_get(self) -> Optional[T]:
        with self._cv:
            if self._items:
                return self._items.popleft()
            if self._closed:
                raise QueueClosedError(self.name)
            return None

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed and not self._items


class ReplicateQueue(Generic[T]):
    """Multi-writer fan-out queue: every push is replicated to every
    reader. reference: messaging/ReplicateQueue.h:22."""

    def __init__(self, name: str = ""):
        self.name = name
        self._readers: List[RQueue[T]] = []
        self._lock = threading.Lock()
        self._closed = False
        self._writes = 0

    def get_reader(self, name: str = "") -> RQueue[T]:
        with self._lock:
            if self._closed:
                raise QueueClosedError(self.name)
            reader = RQueue(name or f"{self.name}::reader{len(self._readers)}")
            self._readers.append(reader)
            return reader

    def push(self, item: T) -> bool:
        with self._lock:
            if self._closed:
                return False
            readers = list(self._readers)
            self._writes += 1
        for reader in readers:
            reader._push(item)
        return True

    def close(self) -> None:
        with self._lock:
            self._closed = True
            readers = list(self._readers)
        for reader in readers:
            reader._close()

    def open(self) -> None:
        with self._lock:
            self._closed = False

    @property
    def num_readers(self) -> int:
        with self._lock:
            return len(self._readers)

    @property
    def num_writes(self) -> int:
        with self._lock:
            return self._writes
