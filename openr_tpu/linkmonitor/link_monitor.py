"""LinkMonitor: neighbor events + kernel links -> adjacency advertisement.

Behavioral parity with the reference ``openr/link-monitor/LinkMonitor.cpp``:

- consumes Spark neighbor events: UP records an adjacency (metric from
  config or RTT), starts KvStore peering with the neighbor, and
  (re-)advertises our ``adj:<node>`` key (neighborUpEvent,
  LinkMonitor.cpp:300; advertiseKvStorePeers :508;
  advertiseAdjacencies :602)
- consumes netlink link/address events into an interface database with
  per-interface flap damping (ExponentialBackoff backing off rapidly
  flapping links; LinkMonitor.h:201-206), republished to Spark
  (processNetlinkEvent, LinkMonitor.cpp:914; syncInterfaces :854)
- drain control: node overload, per-link overload, per-link metric
  override — persisted via the config store so they survive restart
- adjacency advertisement is throttled to coalesce bursts
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

from openr_tpu.monitor.monitor import push_log_sample
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.netlink import (
    NetlinkEvent,
    NetlinkProtocolSocket,
)
from openr_tpu.types import Adjacency, AdjacencyDatabase, PerfEvents
from openr_tpu.types.spark import (
    InterfaceDatabase,
    InterfaceInfo,
    SparkNeighbor,
    SparkNeighborEvent,
    SparkNeighborEventType,
)
from openr_tpu.allocators.range_allocator import RangeAllocator
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import (
    AsyncThrottle,
    ExponentialBackoff,
    OpenrEventBase,
)

# persisted drain-state key in the config store
# (reference: LinkMonitor persists thrift::LinkMonitorState)
LINK_MONITOR_STATE_KEY = "link-monitor-config"

# SR global label block node labels are elected from
# (reference: Constants.h:59 kSrGlobalRange)
SR_GLOBAL_RANGE = (101, 49999)
# claim-key marker (reference: Constants.h:205 kNodeLabelRangePrefix)
NODE_LABEL_MARKER = "nodeLabel:"
NODE_LABELS_PERSIST_KEY = "link-monitor-node-labels"


@dataclass
class _InterfaceEntry:
    """Per-interface state with flap damping
    (reference: link-monitor/InterfaceEntry)."""

    info: InterfaceInfo
    backoff: ExponentialBackoff
    advertised_up: bool = False


class LinkMonitor:
    def __init__(
        self,
        my_node_name: str,
        neighbor_updates_queue: ReplicateQueue,
        interface_updates_queue: ReplicateQueue,
        kvstore_client=None,
        kvstore=None,
        peer_transport_factory: Optional[
            Callable[[SparkNeighbor], object]
        ] = None,
        netlink: Optional[NetlinkProtocolSocket] = None,
        netlink_events_queue: Optional[ReplicateQueue] = None,
        config_store=None,
        area: str = "0",
        areas: Optional[List[str]] = None,
        node_label: int = 0,
        enable_segment_routing: bool = False,
        use_rtt_metric: bool = False,
        flap_initial_backoff_s: float = 0.05,
        flap_max_backoff_s: float = 2.0,
        advertise_throttle_s: float = 0.02,
        log_sample_queue: Optional[ReplicateQueue] = None,
    ):
        self.my_node_name = my_node_name
        self.area = area
        # all areas this node participates in (border routers list several);
        # each gets its own adj:<node> advertisement holding only that
        # area's adjacencies
        self.areas = list(areas) if areas else [area]
        self.node_label = node_label
        self.use_rtt_metric = use_rtt_metric
        self.evb = OpenrEventBase(name=f"linkmonitor:{my_node_name}")
        self._interface_updates = interface_updates_queue
        self._kvstore_client = kvstore_client
        self._kvstore = kvstore
        self._peer_transport_factory = peer_transport_factory
        self._netlink = netlink
        self._config_store = config_store
        self._flap_initial = flap_initial_backoff_s
        self._flap_max = flap_max_backoff_s
        self._log_sample_queue = log_sample_queue

        # (if_name, neighbor) -> (SparkNeighbor, Adjacency)
        self._adjacencies: Dict[Tuple[str, str], Tuple[SparkNeighbor, Adjacency]] = {}
        # (area, node) KvStore peers currently advertised — ADD_PEER is
        # logged only on a genuinely new peer, not each RTT re-advertise
        self._advertised_peers: Set[Tuple[str, str]] = set()
        self._interfaces: Dict[str, _InterfaceEntry] = {}
        self._metric_overrides: Dict[Tuple[str, str], int] = {}
        # interface-wide override (reference: setInterfaceMetric) —
        # the per-(iface, neighbor) override wins when both are set
        self._iface_metric_overrides: Dict[str, int] = {}
        self._link_overloads: Set[str] = set()
        self.is_overloaded = False
        self.counters: Dict[str, int] = {
            "link_monitor.neighbor_up": 0,
            "link_monitor.neighbor_down": 0,
            "link_monitor.advertise_adjacencies": 0,
            "link_monitor.advertise_interfaces": 0,
        }
        self._load_persisted_state()

        self._advertise_adj_throttled = AsyncThrottle(
            self.evb, advertise_throttle_s, self._advertise_adjacencies
        )

        # SR node-label election: one RangeAllocator per area over the
        # global SR block, consensus via the KvStore merge ordering
        # (reference: LinkMonitor.cpp:171-205 — per-area
        # RangeAllocator<int32_t> over kSrGlobalRange, elected label
        # re-advertised and persisted). A non-zero static node_label
        # short-circuits election, like the reference's static config.
        self._node_labels: Dict[str, int] = {}
        self._label_allocators: Dict[str, RangeAllocator] = {}
        if (
            enable_segment_routing
            and node_label == 0
            and kvstore_client is not None
        ):
            persisted: Dict[str, int] = {}
            if config_store is not None:
                persisted = config_store.load(NODE_LABELS_PERSIST_KEY) or {}
            # the allocator FSM must live on the SAME event base the
            # KvStore client delivers publications on
            alloc_evb = kvstore_client.evb
            for lm_area in self.areas:
                alloc = RangeAllocator(
                    alloc_evb,
                    kvstore_client,
                    my_node_name,
                    NODE_LABEL_MARKER,
                    SR_GLOBAL_RANGE,
                    lambda label, a=lm_area: self._on_node_label(a, label),
                    area=lm_area,
                )
                self._label_allocators[lm_area] = alloc
                alloc.start_allocator(init_value=persisted.get(lm_area))
        self._advertise_ifaces_throttled = AsyncThrottle(
            self.evb, advertise_throttle_s, self._advertise_interfaces
        )

        self.evb.add_queue_reader(
            neighbor_updates_queue.get_reader(f"lm:{my_node_name}"),
            self._on_neighbor_event,
        )
        if netlink_events_queue is not None:
            self.evb.add_queue_reader(
                netlink_events_queue.get_reader(f"lm:{my_node_name}"),
                self._on_netlink_event,
            )

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.evb.run_in_thread()
        if self._netlink is not None:
            self.evb.run_in_event_base(self._sync_interfaces)

    def stop(self) -> None:
        for alloc in self._label_allocators.values():
            alloc.stop()
        self.evb.stop()
        self.evb.join()

    # -- SR node-label election ------------------------------------------

    def _on_node_label(self, area: str, label: Optional[int]) -> None:
        """Elected (or lost) a node label for one area: record, persist,
        re-advertise (reference: LinkMonitor.cpp:180-186 callback).
        Fires on the allocator's event base — marshal onto ours."""

        def apply() -> None:
            if label is None:
                self._node_labels.pop(area, None)
            else:
                self._node_labels[area] = label
            if self._config_store is not None:
                self._config_store.store(
                    NODE_LABELS_PERSIST_KEY, dict(self._node_labels)
                )
            self._advertise_adj_throttled()

        self.evb.run_immediately_or_in_event_base(apply)

    def node_label_for(self, area: str) -> int:
        return self._node_labels.get(area, self.node_label)

    # -- persisted drain state -------------------------------------------

    def _load_persisted_state(self) -> None:
        if self._config_store is None:
            return
        state = self._config_store.load(LINK_MONITOR_STATE_KEY)
        if state is None:
            return
        self.is_overloaded = bool(state.get("is_overloaded", False))
        self._link_overloads = set(state.get("link_overloads", []))
        self._iface_metric_overrides = dict(
            state.get("iface_metric_overrides", {})
        )
        self._metric_overrides = {
            (i, n): m
            for (i, n), m in (
                (tuple(k.split("|", 1)), v)
                for k, v in state.get("metric_overrides", {}).items()
            )
        }

    def _persist_state(self) -> None:
        if self._config_store is None:
            return
        self._config_store.store(
            LINK_MONITOR_STATE_KEY,
            {
                "is_overloaded": self.is_overloaded,
                "link_overloads": sorted(self._link_overloads),
                "metric_overrides": {
                    f"{i}|{n}": m
                    for (i, n), m in self._metric_overrides.items()
                },
                "iface_metric_overrides": dict(
                    self._iface_metric_overrides
                ),
            },
        )

    def _log_sample(self, **fields) -> None:
        """reference: LinkMonitor.cpp:1287 logNeighborEvent, :1303
        logLinkEvent, :1326 logPeerEvent."""
        push_log_sample(
            self._log_sample_queue, node_name=self.my_node_name, **fields
        )

    # -- spark events -----------------------------------------------------

    def _on_neighbor_event(self, event: SparkNeighborEvent) -> None:
        et = event.event_type
        nbr = event.neighbor
        if et != SparkNeighborEventType.NEIGHBOR_RTT_CHANGE:
            # transitions only — RTT jitter on a noisy fabric would
            # evict the rare UP/DOWN events from the bounded history
            self._log_sample(
                event=et.name,
                neighbor=nbr.node_name,
                interface=nbr.local_if_name,
                remote_interface=nbr.remote_if_name,
                area=nbr.area or self.area,
                rtt_us=nbr.rtt_us,
            )
        if et == SparkNeighborEventType.NEIGHBOR_UP:
            self._neighbor_up(event.neighbor)
        elif et == SparkNeighborEventType.NEIGHBOR_RESTARTED:
            self._neighbor_up(event.neighbor)
        elif et == SparkNeighborEventType.NEIGHBOR_DOWN:
            self._neighbor_down(event.neighbor)
        elif et == SparkNeighborEventType.NEIGHBOR_RESTARTING:
            # graceful restart: keep the adjacency, stop nothing
            pass
        elif et == SparkNeighborEventType.NEIGHBOR_RTT_CHANGE:
            self._rtt_change(event.neighbor)

    def _metric_for(self, nbr: SparkNeighbor) -> int:
        key = (nbr.local_if_name, nbr.node_name)
        if key in self._metric_overrides:
            return self._metric_overrides[key]
        if self.use_rtt_metric:
            # reference: metric = max(1, rtt_us / 100)
            return max(1, nbr.rtt_us // 100)
        return 1

    def _neighbor_up(self, nbr: SparkNeighbor) -> None:
        """reference: LinkMonitor.cpp:300 neighborUpEvent."""
        self.counters["link_monitor.neighbor_up"] += 1
        adj = Adjacency(
            other_node_name=nbr.node_name,
            if_name=nbr.local_if_name,
            other_if_name=nbr.remote_if_name,
            metric=self._metric_for(nbr),
            next_hop_v6=nbr.transport_address_v6,
            next_hop_v4=nbr.transport_address_v4,
            is_overloaded=nbr.local_if_name in self._link_overloads,
            rtt=nbr.rtt_us,
            timestamp=int(time.time()),
        )
        self._adjacencies[(nbr.local_if_name, nbr.node_name)] = (nbr, adj)
        self._advertise_kvstore_peer(nbr)
        self._advertise_adj_throttled()

    def _neighbor_down(self, nbr: SparkNeighbor) -> None:
        self.counters["link_monitor.neighbor_down"] += 1
        area = nbr.area or self.area
        self._adjacencies.pop((nbr.local_if_name, nbr.node_name), None)
        if self._kvstore is not None and not any(
            n.node_name == nbr.node_name and (n.area or self.area) == area
            for (n, _) in self._adjacencies.values()
        ):
            # drop the advertisement record first: a del_peer failure
            # must not suppress the ADD_PEER sample when the neighbor
            # later re-establishes
            self._advertised_peers.discard((area, nbr.node_name))
            try:
                self._kvstore.del_peer(area, nbr.node_name)
                self._log_sample(
                    event="DEL_PEER", peer_name=nbr.node_name, area=area
                )
            except Exception:
                pass
        self._advertise_adj_throttled()

    def _rtt_change(self, nbr: SparkNeighbor) -> None:
        entry = self._adjacencies.get((nbr.local_if_name, nbr.node_name))
        if entry is None:
            return
        if self.use_rtt_metric:
            self._neighbor_up(nbr)  # recompute metric + readvertise
        else:
            # record new rtt without metric change
            old_nbr, adj = entry
            self._adjacencies[(nbr.local_if_name, nbr.node_name)] = (
                nbr,
                Adjacency(
                    other_node_name=adj.other_node_name,
                    if_name=adj.if_name,
                    other_if_name=adj.other_if_name,
                    metric=adj.metric,
                    next_hop_v6=adj.next_hop_v6,
                    next_hop_v4=adj.next_hop_v4,
                    is_overloaded=adj.is_overloaded,
                    rtt=nbr.rtt_us,
                    timestamp=adj.timestamp,
                ),
            )

    def _advertise_kvstore_peer(self, nbr: SparkNeighbor) -> None:
        """Start KvStore flooding with the new neighbor
        (reference: LinkMonitor.cpp:508 advertiseKvStorePeers)."""
        if self._kvstore is None or self._peer_transport_factory is None:
            return
        try:
            transport = self._peer_transport_factory(nbr)
            if transport is not None:
                area = nbr.area or self.area
                self._kvstore.add_peer(area, nbr.node_name, transport)
                if (area, nbr.node_name) not in self._advertised_peers:
                    self._advertised_peers.add((area, nbr.node_name))
                    self._log_sample(
                        event="ADD_PEER",
                        peer_name=nbr.node_name,
                        area=area,
                    )
        except Exception:
            pass

    # -- adjacency advertisement -----------------------------------------

    def _build_adj_db(self, area: Optional[str] = None) -> AdjacencyDatabase:
        """Adjacencies for one area (or all, area=None for introspection)."""
        adjacencies = []
        for (if_name, node), (nbr, adj) in sorted(self._adjacencies.items()):
            if area is not None and (nbr.area or self.area) != area:
                continue
            metric = self._metric_overrides.get(
                (if_name, node),
                self._iface_metric_overrides.get(if_name, adj.metric),
            )
            adjacencies.append(
                Adjacency(
                    other_node_name=adj.other_node_name,
                    if_name=adj.if_name,
                    other_if_name=adj.other_if_name,
                    metric=metric,
                    next_hop_v6=adj.next_hop_v6,
                    next_hop_v4=adj.next_hop_v4,
                    adj_label=adj.adj_label,
                    is_overloaded=if_name in self._link_overloads,
                    rtt=adj.rtt,
                    timestamp=adj.timestamp,
                    weight=adj.weight,
                )
            )
        resolved_area = area if area is not None else self.area
        return AdjacencyDatabase(
            this_node_name=self.my_node_name,
            is_overloaded=self.is_overloaded,
            adjacencies=tuple(adjacencies),
            node_label=self.node_label_for(resolved_area),
            area=resolved_area,
        )

    def _advertise_adjacencies(self) -> None:
        """reference: LinkMonitor.cpp:602 advertiseAdjacencies (one
        adj:<node> advertisement per configured area)."""
        if self._kvstore_client is None:
            return
        self.counters["link_monitor.advertise_adjacencies"] += 1
        for area in self.areas:
            adj_db = self._build_adj_db(area)
            # originate the convergence perf chain here, so the e2e
            # account starts at the adjacency change, not at Decision
            # (reference: LinkMonitor.cpp:602 addPerfEvent
            # ADJ_DB_UPDATED)
            perf = PerfEvents()
            perf.add(self.my_node_name, "ADJ_DB_UPDATED")
            adj_db = AdjacencyDatabase(
                this_node_name=adj_db.this_node_name,
                is_overloaded=adj_db.is_overloaded,
                adjacencies=adj_db.adjacencies,
                node_label=adj_db.node_label,
                area=adj_db.area,
                perf_events=perf,
            )
            self._kvstore_client.persist_key(
                area,
                keyutil.adj_key(self.my_node_name),
                wire.dumps(adj_db),
            )

    # -- netlink interface tracking --------------------------------------

    def _sync_interfaces(self) -> None:
        """reference: LinkMonitor.cpp:854 syncInterfaces."""
        for link in self._netlink.get_all_links():
            self._apply_link_state(link.if_name, link.is_up, link.addresses)
        self._advertise_ifaces_throttled()

    def _on_netlink_event(self, event: NetlinkEvent) -> None:
        """reference: LinkMonitor.cpp:914 processNetlinkEvent."""
        if event.link is None:
            return
        self._apply_link_state(
            event.link.if_name, event.link.is_up, event.link.addresses
        )
        self._advertise_ifaces_throttled()

    def _apply_link_state(self, if_name, is_up, addresses) -> None:
        entry = self._interfaces.get(if_name)
        if entry is None:
            entry = self._interfaces[if_name] = _InterfaceEntry(
                info=InterfaceInfo(is_up=is_up, networks=tuple(addresses)),
                backoff=ExponentialBackoff(self._flap_initial, self._flap_max),
            )
            return
        was_up = entry.info.is_up
        entry.info = InterfaceInfo(is_up=is_up, networks=tuple(addresses))
        backoff_ms = 0
        if is_up and not was_up:
            # flap damping: a link coming back up is held for the current
            # backoff window; rapid flapping doubles the window
            entry.backoff.report_error()
            delay = entry.backoff.get_time_remaining_until_retry()
            backoff_ms = int(delay * 1000)
            if delay > 0:
                self.evb.schedule_timeout(
                    delay, self._advertise_ifaces_throttled
                )
        if was_up != is_up:  # reference logLinkEvent: transitions only
            self._log_sample(
                event=f"IFACE_{'UP' if is_up else 'DOWN'}",
                interface=if_name,
                backoff_ms=backoff_ms,
            )

    def _advertise_interfaces(self) -> None:
        self.counters["link_monitor.advertise_interfaces"] += 1
        interfaces: Dict[str, InterfaceInfo] = {}
        for if_name, entry in self._interfaces.items():
            is_up = entry.info.is_up
            if is_up and not entry.backoff.can_try_now():
                is_up = False  # still damped
            interfaces[if_name] = InterfaceInfo(
                is_up=is_up,
                if_index=entry.info.if_index,
                networks=entry.info.networks,
            )
        self._interface_updates.push(
            InterfaceDatabase(
                this_node_name=self.my_node_name, interfaces=interfaces
            )
        )

    # -- drain / overload APIs (thread-safe) ------------------------------

    def set_node_overload(self, overloaded: bool) -> None:
        def apply() -> None:
            if self.is_overloaded != overloaded:
                self.is_overloaded = overloaded
                self._persist_state()
                self._advertise_adj_throttled()

        self.evb.call_and_wait(apply)

    def set_link_overload(self, if_name: str, overloaded: bool) -> None:
        def apply() -> None:
            if overloaded:
                self._link_overloads.add(if_name)
            else:
                self._link_overloads.discard(if_name)
            self._persist_state()
            self._advertise_adj_throttled()

        self.evb.call_and_wait(apply)

    def set_link_metric(
        self, if_name: str, neighbor: str, metric: Optional[int]
    ) -> None:
        def apply() -> None:
            if metric is None:
                self._metric_overrides.pop((if_name, neighbor), None)
            else:
                self._metric_overrides[(if_name, neighbor)] = metric
            self._persist_state()
            self._advertise_adj_throttled()

        self.evb.call_and_wait(apply)

    def set_interface_metric(
        self, if_name: str, metric: Optional[int]
    ) -> None:
        """Interface-wide metric override for every adjacency on the
        interface (reference: OpenrCtrl setInterfaceMetric /
        unsetInterfaceMetric). None clears it."""

        def apply() -> None:
            if metric is None:
                self._iface_metric_overrides.pop(if_name, None)
            else:
                self._iface_metric_overrides[if_name] = metric
            self._persist_state()
            self._advertise_adj_throttled()

        self.evb.call_and_wait(apply)

    # -- introspection ----------------------------------------------------

    def get_adjacencies(self) -> AdjacencyDatabase:
        return self.evb.call_and_wait(self._build_adj_db)

    def get_interfaces(self) -> Dict[str, InterfaceInfo]:
        return self.evb.call_and_wait(
            lambda: {n: e.info for n, e in self._interfaces.items()}
        )

    def get_interface_details(self):
        """One-snapshot dump for the ctrl getInterfaces RPC (reference:
        LinkMonitor.thrift DumpLinksReply): node overload bit plus, per
        interface, (InterfaceInfo, link overload, interface-wide metric
        override or None). The per-(iface, neighbor) overrides ride
        getLinkMonitorAdjacencies, as in the reference."""

        def snap():
            return (
                self.is_overloaded,
                {
                    n: (
                        e.info,
                        n in self._link_overloads,
                        self._iface_metric_overrides.get(n),
                    )
                    for n, e in self._interfaces.items()
                },
            )

        return self.evb.call_and_wait(snap)

    def get_counters(self) -> Dict[str, int]:
        return self.evb.call_and_wait(lambda: dict(self.counters))
