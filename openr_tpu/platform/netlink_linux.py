"""Real Linux rtnetlink implementation of NetlinkProtocolSocket.

The reference's kernel access layer (openr/nl/NetlinkProtocolSocket.h:96
with message builders in nl/NetlinkMessage.h, nl/NetlinkRoute.h) is
~4,750 lines of C++ over libnl-style structs; this is the same protocol
spoken directly through a raw ``socket(AF_NETLINK, SOCK_RAW,
NETLINK_ROUTE)``: link dumps (RTM_GETLINK), route add/delete
(RTM_NEWROUTE / RTM_DELROUTE, including RTA_MULTIPATH ECMP next-hop
groups), route dumps filtered by our protocol id, and an optional
subscription to link events (RTMGRP_LINK) published onto a
ReplicateQueue — mirroring the reference's NetlinkEvent fan-out.

Routes are tagged with protocol id 99 (the reference's kAqRouteProtoId,
openr/common/Constants.h) so dumps and deletes only ever touch
openr-owned routes.

Requires CAP_NET_ADMIN for mutations; ``is_available()`` probes the
socket so tests and the daemon can fall back to the mock on unprivileged
hosts.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.telemetry import get_registry
from openr_tpu.platform.netlink import (
    NUD_VALID,
    NetlinkError,
    NetlinkEvent,
    NetlinkEventType,
    NetlinkProtocolSocket,
    NlLink,
    NlNeighbor,
)
from openr_tpu.types import (
    BinaryAddress,
    IpPrefix,
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    UnicastRoute,
)

# netlink message types
RTM_NEWLINK = 16
RTM_DELLINK = 17
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_DELADDR = 21
RTM_GETADDR = 22
RTM_NEWROUTE = 24
RTM_DELROUTE = 25
RTM_GETROUTE = 26
RTM_NEWNEIGH = 28
RTM_DELNEIGH = 29
RTM_GETNEIGH = 30
NLMSG_ERROR = 2
NLMSG_DONE = 3

# flags
NLM_F_REQUEST = 0x1
NLM_F_MULTI = 0x2
NLM_F_ACK = 0x4
NLM_F_ROOT = 0x100
NLM_F_MATCH = 0x200
NLM_F_DUMP = NLM_F_ROOT | NLM_F_MATCH
NLM_F_REPLACE = 0x100
NLM_F_EXCL = 0x200
NLM_F_CREATE = 0x400

# rtattr types (route)
RTA_DST = 1
RTA_OIF = 4
RTA_GATEWAY = 5
RTA_PRIORITY = 6
RTA_MULTIPATH = 9
RTA_VIA = 18  # MPLS nexthop: rtvia { u16 family; u8 addr[] }
RTA_NEWDST = 19  # MPLS swap: outgoing label stack

# rtattr types (neighbor, linux/neighbour.h)
NDA_DST = 1
NDA_LLADDR = 2

# rtattr types (address, linux/if_addr.h)
IFA_ADDRESS = 1
IFA_LOCAL = 2

AF_MPLS = 28
MPLS_LABEL_IMPLICIT_NULL = 3  # PHP: pop, forward by inner header

# rtattr types (link)
IFLA_IFNAME = 3
IFLA_LINKINFO = 18
IFLA_INFO_KIND = 1
IFF_UP = 0x1

# rtmsg fields
RT_TABLE_MAIN = 254
RT_SCOPE_UNIVERSE = 0
RTN_UNICAST = 1
OPENR_ROUTE_PROTO_ID = 99  # reference: Constants.h kAqRouteProtoId

# rtnetlink multicast groups (linux/rtnetlink.h)
RTMGRP_LINK = 0x1
RTMGRP_NEIGH = 0x4
RTMGRP_IPV4_IFADDR = 0x10
RTMGRP_IPV4_ROUTE = 0x40
RTMGRP_IPV6_IFADDR = 0x100
RTMGRP_IPV6_ROUTE = 0x400
RTMGRP_ALL = (
    RTMGRP_LINK
    | RTMGRP_NEIGH
    | RTMGRP_IPV4_IFADDR
    | RTMGRP_IPV4_ROUTE
    | RTMGRP_IPV6_IFADDR
    | RTMGRP_IPV6_ROUTE
)

_NLMSGHDR = struct.Struct("=IHHII")
_RTMSG = struct.Struct("=BBBBBBBBI")
_IFINFOMSG = struct.Struct("=BxHiII")
_IFADDRMSG = struct.Struct("=BBBBi")
_NDMSG = struct.Struct("=BxxxiHBB")
_RTATTR = struct.Struct("=HH")
_RTNEXTHOP = struct.Struct("=HBBi")


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _attr(attr_type: int, payload: bytes) -> bytes:
    length = _RTATTR.size + len(payload)
    return (
        _RTATTR.pack(length, attr_type)
        + payload
        + b"\x00" * (_align4(length) - length)
    )


def _parse_attrs(data: bytes) -> Dict[int, bytes]:
    out: Dict[int, bytes] = {}
    off = 0
    while off + _RTATTR.size <= len(data):
        length, attr_type = _RTATTR.unpack_from(data, off)
        if length < _RTATTR.size:
            break
        out[attr_type] = data[off + _RTATTR.size : off + length]
        off += _align4(length)
    return out


class LinuxNetlinkProtocolSocket(NetlinkProtocolSocket):
    """Raw rtnetlink socket. One request at a time (internally locked),
    kernel acks checked on every mutation."""

    def __init__(self, events_queue: Optional[ReplicateQueue] = None):
        self._lock = threading.Lock()
        self._seq = 0
        self._sock = socket.socket(
            socket.AF_NETLINK, socket.SOCK_RAW, socket.NETLINK_ROUTE
        )
        self._sock.bind((0, 0))
        self.events_queue = events_queue
        self._event_thread: Optional[threading.Thread] = None
        self._event_sock: Optional[socket.socket] = None
        self._running = False
        # name -> ifindex cache: interfaces change rarely; invalidated on
        # any local link mutation and on subscribed link events
        self._links_cache: Optional[Dict[str, int]] = None

    @staticmethod
    def is_available() -> bool:
        """A netlink route socket can be opened (this alone needs no
        privileges — reads work unprivileged)."""
        try:
            s = socket.socket(
                socket.AF_NETLINK, socket.SOCK_RAW, socket.NETLINK_ROUTE
            )
            s.close()
            return True
        except (OSError, AttributeError):  # AttributeError: non-Linux
            return False

    @staticmethod
    def has_net_admin() -> bool:
        """Mutations (link/route changes) additionally need
        CAP_NET_ADMIN: check the effective capability set."""
        CAP_NET_ADMIN_BIT = 12
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("CapEff:"):
                        cap_eff = int(line.split()[1], 16)
                        return bool(cap_eff & (1 << CAP_NET_ADMIN_BIT))
        except OSError:
            # unreadable /proc/self/status: count it — an unexpected
            # probe failure silently downgrading to mock is the kind of
            # deployment surprise the counter surfaces
            get_registry().counter_bump("platform.capability_probe_errors")
        return False

    @classmethod
    def is_admin_available(cls) -> bool:
        return cls.is_available() and cls.has_net_admin()

    def close(self) -> None:
        self.stop_events()
        self._sock.close()

    # -- request plumbing -------------------------------------------------

    def _request(
        self, msg_type: int, flags: int, body: bytes
    ) -> List[Tuple[int, bytes]]:
        """Send one request; collect replies until ACK/DONE/single part.
        Returns (msg_type, payload-after-nlmsghdr) tuples."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            hdr = _NLMSGHDR.pack(
                _NLMSGHDR.size + len(body), msg_type, flags, seq, 0
            )
            self._sock.send(hdr + body)
            parts: List[Tuple[int, bytes]] = []
            dumping = bool(flags & NLM_F_DUMP)
            while True:
                data = self._sock.recv(1 << 18)
                off = 0
                while off + _NLMSGHDR.size <= len(data):
                    (length, mtype, mflags, mseq, _pid) = _NLMSGHDR.unpack_from(
                        data, off
                    )
                    payload = data[off + _NLMSGHDR.size : off + length]
                    off += _align4(length)
                    if mseq != seq:
                        continue
                    if mtype == NLMSG_ERROR:
                        (errno_neg,) = struct.unpack_from("=i", payload)
                        if errno_neg != 0:
                            raise NetlinkError(
                                -errno_neg,
                                f"netlink error {-errno_neg} for "
                                f"msg_type={msg_type}",
                            )
                        return parts  # ACK
                    if mtype == NLMSG_DONE:
                        return parts
                    parts.append((mtype, payload))
                    if not dumping and not (mflags & NLM_F_MULTI):
                        return parts

    # -- links ------------------------------------------------------------

    def get_all_links(self) -> List[NlLink]:
        """RTM_GETLINK dump. reference: NetlinkProtocolSocket::getAllLinks."""
        body = _IFINFOMSG.pack(socket.AF_UNSPEC, 0, 0, 0, 0)
        links = []
        for mtype, payload in self._request(
            RTM_GETLINK, NLM_F_REQUEST | NLM_F_DUMP, body
        ):
            if mtype != RTM_NEWLINK:
                continue
            links.append(self._parse_link(payload))
        return links

    @staticmethod
    def _parse_link(payload: bytes) -> NlLink:
        _family, _type, index, flags, _change = _IFINFOMSG.unpack_from(payload)
        attrs = _parse_attrs(payload[_IFINFOMSG.size :])
        name = attrs.get(IFLA_IFNAME, b"?\x00")[:-1].decode()
        return NlLink(
            if_name=name, if_index=index, is_up=bool(flags & IFF_UP)
        )

    def link_index(self, if_name: str) -> Optional[int]:
        # rides the cached link table (invalidated on local link
        # mutations and subscribed link events) so the address APIs
        # don't pay a full RTM_GETLINK dump per call
        return self._link_table().get(if_name)

    def create_link(self, if_name: str, kind: str = "dummy") -> None:
        """RTM_NEWLINK with linkinfo kind (test/loopback use). Kernels
        differ in which kinds are compiled in — callers fall back across
        e.g. ("dummy", "ifb")."""
        body = _IFINFOMSG.pack(socket.AF_UNSPEC, 0, 0, 0, 0)
        body += _attr(IFLA_IFNAME, if_name.encode() + b"\x00")
        body += _attr(IFLA_LINKINFO, _attr(IFLA_INFO_KIND, kind.encode()))
        self._request(
            RTM_NEWLINK,
            NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_EXCL,
            body,
        )
        self._links_cache = None

    def create_dummy_link(self, if_name: str) -> None:
        self.create_link(if_name, kind="dummy")

    def set_link_up(self, if_name: str, up: bool = True) -> None:
        index = self.link_index(if_name)
        if index is None:
            raise NetlinkError(19, f"no such link {if_name}")
        body = _IFINFOMSG.pack(
            socket.AF_UNSPEC, 0, index, IFF_UP if up else 0, IFF_UP
        )
        self._request(RTM_NEWLINK, NLM_F_REQUEST | NLM_F_ACK, body)

    def delete_link(self, if_name: str) -> None:
        index = self.link_index(if_name)
        if index is None:
            return
        body = _IFINFOMSG.pack(socket.AF_UNSPEC, 0, index, 0, 0)
        self._request(RTM_DELLINK, NLM_F_REQUEST | NLM_F_ACK, body)
        self._links_cache = None

    # -- routes -----------------------------------------------------------

    def _route_body(self, route_dest: IpPrefix) -> bytes:
        family = socket.AF_INET if route_dest.is_v4 else socket.AF_INET6
        return _RTMSG.pack(
            family,
            route_dest.prefix_length,
            0,
            0,
            RT_TABLE_MAIN,
            OPENR_ROUTE_PROTO_ID,
            RT_SCOPE_UNIVERSE,
            RTN_UNICAST,
            0,
        ) + _attr(RTA_DST, route_dest.prefix_address.addr)

    def _link_table(self) -> Dict[str, int]:
        """name -> ifindex, cached (bulk route programming must not
        issue a link dump per route)."""
        if self._links_cache is None:
            self._links_cache = {
                l.if_name: l.if_index for l in self.get_all_links()
            }
        return self._links_cache

    @staticmethod
    def _gateway_attr(nh: NextHop) -> bytes:
        if nh.address.addr and set(nh.address.addr) != {0}:
            return _attr(RTA_GATEWAY, nh.address.addr)
        return b""

    def add_route(self, route: UnicastRoute) -> None:
        """RTM_NEWROUTE (replace). Multiple next-hops become an
        RTA_MULTIPATH ECMP group — the reference builds the same nexthop
        list in nl/NetlinkRoute.h."""
        body = self._route_body(route.dest)
        nhs = list(route.next_hops)
        needs_index = any(nh.address.if_name for nh in nhs)
        links = self._link_table() if needs_index else {}
        if len(nhs) == 1:
            nh = nhs[0]
            body += self._gateway_attr(nh)
            index = links.get(nh.address.if_name or "")
            if index is not None:
                body += _attr(RTA_OIF, struct.pack("=i", index))
        elif len(nhs) > 1:
            group = b""
            for nh in nhs:
                # rtnh_ifindex carries the egress interface; RTA_OIF
                # inside a multipath nexthop would be redundant
                nh_attrs = self._gateway_attr(nh)
                index = links.get(nh.address.if_name or "", 0)
                rtnh_len = _RTNEXTHOP.size + len(nh_attrs)
                group += _RTNEXTHOP.pack(rtnh_len, 0, 0, index) + nh_attrs
            body += _attr(RTA_MULTIPATH, group)
        self._request(
            RTM_NEWROUTE,
            NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE,
            body,
        )

    def delete_route(self, prefix: IpPrefix) -> None:
        body = self._route_body(prefix)
        try:
            self._request(RTM_DELROUTE, NLM_F_REQUEST | NLM_F_ACK, body)
        except NetlinkError as exc:
            if exc.errno != 3:  # ESRCH: already gone
                raise

    def get_all_routes(self) -> List[UnicastRoute]:
        """RTM_GETROUTE dump filtered to our protocol id."""
        routes: List[UnicastRoute] = []
        for family in (socket.AF_INET6, socket.AF_INET):
            body = _RTMSG.pack(family, 0, 0, 0, 0, 0, 0, 0, 0)
            for mtype, payload in self._request(
                RTM_GETROUTE, NLM_F_REQUEST | NLM_F_DUMP, body
            ):
                if mtype != RTM_NEWROUTE:
                    continue
                route = self._parse_route(payload)
                if route is not None:
                    routes.append(route)
        return sorted(routes, key=lambda r: r.dest)

    @staticmethod
    def _parse_route(payload: bytes) -> Optional[UnicastRoute]:
        (
            family, dst_len, _src_len, _tos, table, proto, _scope, rtype,
            _flags,
        ) = _RTMSG.unpack_from(payload)
        if proto != OPENR_ROUTE_PROTO_ID or rtype != RTN_UNICAST:
            return None
        if table != RT_TABLE_MAIN:
            return None
        attrs = _parse_attrs(payload[_RTMSG.size :])
        addr_len = 4 if family == socket.AF_INET else 16
        dst = attrs.get(RTA_DST, b"\x00" * addr_len)
        dest = IpPrefix(
            prefix_address=BinaryAddress(addr=dst), prefix_length=dst_len
        )
        nhs: List[NextHop] = []
        if RTA_MULTIPATH in attrs:
            data = attrs[RTA_MULTIPATH]
            off = 0
            while off + _RTNEXTHOP.size <= len(data):
                rtnh_len, _f, _h, _index = _RTNEXTHOP.unpack_from(data, off)
                nh_attrs = _parse_attrs(
                    data[off + _RTNEXTHOP.size : off + rtnh_len]
                )
                gw = nh_attrs.get(RTA_GATEWAY, b"")
                nhs.append(NextHop(address=BinaryAddress(addr=gw)))
                off += _align4(rtnh_len)
        elif RTA_GATEWAY in attrs or RTA_OIF in attrs:
            gw = attrs.get(RTA_GATEWAY, b"")
            nhs.append(NextHop(address=BinaryAddress(addr=gw)))
        return UnicastRoute(dest=dest, next_hops=tuple(nhs))

    def add_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        # ifaddrmsg: family, prefixlen, flags, scope, index
        index = self.link_index(if_name)
        if index is None:
            raise NetlinkError(19, f"no such link {if_name}")
        family = socket.AF_INET if prefix.is_v4 else socket.AF_INET6
        body = _IFADDRMSG.pack(family, prefix.prefix_length, 0, 0, index)
        body += _attr(IFA_LOCAL, prefix.prefix_address.addr)
        self._request(
            RTM_NEWADDR,
            NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_EXCL,
            body,
        )

    def del_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        index = self.link_index(if_name)
        if index is None:
            raise NetlinkError(19, f"no such link {if_name}")
        family = socket.AF_INET if prefix.is_v4 else socket.AF_INET6
        body = _IFADDRMSG.pack(family, prefix.prefix_length, 0, 0, index)
        body += _attr(IFA_LOCAL, prefix.prefix_address.addr)
        self._request(RTM_DELADDR, NLM_F_REQUEST | NLM_F_ACK, body)

    def get_ifaddresses(self, if_name: str) -> List[IpPrefix]:
        """RTM_GETADDR dump filtered to one interface (reference:
        NetlinkProtocolSocket::getAllIfAddresses)."""
        index = self.link_index(if_name)
        if index is None:
            raise NetlinkError(19, f"no such link {if_name}")
        body = _IFADDRMSG.pack(socket.AF_UNSPEC, 0, 0, 0, 0)
        out: List[IpPrefix] = []
        for mtype, payload in self._request(
            RTM_GETADDR, NLM_F_REQUEST | NLM_F_DUMP, body
        ):
            if mtype != RTM_NEWADDR:
                continue
            _family, plen, _flags, _scope, ifindex = _IFADDRMSG.unpack_from(
                payload
            )
            if ifindex != index:
                continue
            attrs = _parse_attrs(payload[_IFADDRMSG.size :])
            addr = attrs.get(IFA_LOCAL) or attrs.get(IFA_ADDRESS)
            if addr is None:
                continue
            out.append(
                IpPrefix(
                    prefix_address=BinaryAddress(addr=addr),
                    prefix_length=plen,
                )
            )
        return out

    # -- neighbor table ---------------------------------------------------

    def get_all_neighbors(self) -> List[NlNeighbor]:
        """RTM_GETNEIGH dump (reference:
        NetlinkProtocolSocket::getAllNeighbors,
        nl/NetlinkProtocolSocket.h:176)."""
        body = _NDMSG.pack(socket.AF_UNSPEC, 0, 0, 0, 0)
        out: List[NlNeighbor] = []
        for mtype, payload in self._request(
            RTM_GETNEIGH, NLM_F_REQUEST | NLM_F_DUMP, body
        ):
            if mtype != RTM_NEWNEIGH:
                continue
            nbr = self._parse_neighbor(payload)
            if nbr is not None:
                out.append(nbr)
        return sorted(out, key=lambda n: (n.if_index, n.destination))

    @staticmethod
    def _parse_neighbor(payload: bytes) -> Optional[NlNeighbor]:
        family, ifindex, state, _flags, _typ = _NDMSG.unpack_from(payload)
        if family not in (socket.AF_INET, socket.AF_INET6):
            return None
        attrs = _parse_attrs(payload[_NDMSG.size :])
        dst = attrs.get(NDA_DST)
        if dst is None:
            return None
        plen = 32 if family == socket.AF_INET else 128
        return NlNeighbor(
            if_index=ifindex,
            destination=IpPrefix(
                prefix_address=BinaryAddress(addr=dst), prefix_length=plen
            ),
            link_address=attrs.get(NDA_LLADDR, b""),
            state=state,
            is_reachable=bool(state & NUD_VALID),
        )

    # -- MPLS label routes ------------------------------------------------

    @staticmethod
    def _mpls_label_bytes(label: int, bos: bool = True) -> bytes:
        """One MPLS label stack entry: label(20) tc(3) s(1) ttl(8), BE."""
        return struct.pack(
            ">I", ((label & 0xFFFFF) << 12) | (0x100 if bos else 0)
        )

    def _mpls_nh_attrs(self, nh: NextHop, links: Dict[str, int]) -> bytes:
        """RTA_VIA (+ RTA_NEWDST for SWAP) + RTA_OIF for one MPLS
        next hop."""
        attrs = b""
        act = nh.mpls_action
        if act is not None and act.action == MplsActionCode.SWAP:
            attrs += _attr(
                RTA_NEWDST, self._mpls_label_bytes(act.swap_label)
            )
        # PHP / POP_AND_LOOKUP: no NEWDST — the kernel pops
        addr = nh.address.addr
        has_via = bool(addr) and set(addr) != {0}
        if has_via:
            family = (
                socket.AF_INET if len(addr) == 4 else socket.AF_INET6
            )
            attrs += _attr(
                RTA_VIA, struct.pack("=H", family) + addr
            )
        index = links.get(nh.address.if_name or "")
        if index is None and not has_via:
            # POP_AND_LOOKUP (our own label): Linux encodes "pop and
            # forward by inner header" as a label route out of loopback
            # — without any nexthop attr the kernel rejects the route
            index = links.get("lo")
        if index is not None:
            attrs += _attr(RTA_OIF, struct.pack("=i", index))
        return attrs

    def _mpls_body(self, label: int) -> bytes:
        return _RTMSG.pack(
            AF_MPLS,
            20,  # dst_len: one 20-bit label
            0,
            0,
            RT_TABLE_MAIN,
            OPENR_ROUTE_PROTO_ID,
            RT_SCOPE_UNIVERSE,
            RTN_UNICAST,
            0,
        ) + _attr(RTA_DST, self._mpls_label_bytes(label))

    def add_mpls_route(self, route: MplsRoute) -> None:
        """RTM_NEWROUTE with family AF_MPLS (reference:
        nl/NetlinkRoute label-route builders; requires the kernel
        mpls_router module)."""
        body = self._mpls_body(route.top_label)
        nhs = list(route.next_hops)
        links = self._link_table()
        if len(nhs) == 1:
            body += self._mpls_nh_attrs(nhs[0], links)
        elif len(nhs) > 1:
            group = b""
            for nh in nhs:
                nh_attrs = self._mpls_nh_attrs(nh, links)
                rtnh_len = _RTNEXTHOP.size + len(nh_attrs)
                group += (
                    _RTNEXTHOP.pack(
                        rtnh_len, 0, 0,
                        links.get(nh.address.if_name or "", 0),
                    )
                    + nh_attrs
                )
            body += _attr(RTA_MULTIPATH, group)
        self._request(
            RTM_NEWROUTE,
            NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE,
            body,
        )

    def delete_mpls_route(self, label: int) -> None:
        try:
            self._request(
                RTM_DELROUTE,
                NLM_F_REQUEST | NLM_F_ACK,
                self._mpls_body(label),
            )
        except NetlinkError as exc:
            if exc.errno != 3:  # ESRCH: already gone
                raise

    def get_all_mpls_routes(self) -> List[MplsRoute]:
        body = _RTMSG.pack(AF_MPLS, 0, 0, 0, 0, 0, 0, 0, 0)
        out: List[MplsRoute] = []
        for mtype, payload in self._request(
            RTM_GETROUTE, NLM_F_REQUEST | NLM_F_DUMP, body
        ):
            if mtype != RTM_NEWROUTE:
                continue
            route = self._parse_mpls_route(payload)
            if route is not None:
                out.append(route)
        return sorted(out, key=lambda r: r.top_label)

    def _parse_mpls_route(self, payload: bytes) -> Optional[MplsRoute]:
        (
            family, _dst_len, _sl, _tos, _table, proto, _scope, _rtype,
            _flags,
        ) = _RTMSG.unpack_from(payload)
        if family != AF_MPLS or proto != OPENR_ROUTE_PROTO_ID:
            return None
        attrs = _parse_attrs(payload[_RTMSG.size :])
        dst = attrs.get(RTA_DST)
        if dst is None:
            return None
        label = struct.unpack(">I", dst)[0] >> 12

        lo_index = self._link_table().get("lo")

        def parse_nh(
            nh_attrs: Dict[int, bytes], rtnh_index: Optional[int] = None
        ) -> NextHop:
            addr = b""
            via = nh_attrs.get(RTA_VIA)
            if via is not None:
                addr = via[2:]
            oif = nh_attrs.get(RTA_OIF)
            index = (
                struct.unpack("=i", oif)[0]
                if oif is not None
                else rtnh_index
            )
            newdst = nh_attrs.get(RTA_NEWDST)
            if newdst is not None:
                action = MplsAction(
                    action=MplsActionCode.SWAP,
                    swap_label=struct.unpack(">I", newdst[:4])[0] >> 12,
                )
            elif via is None and index is not None and index == lo_index:
                # no via, out of loopback: the POP_AND_LOOKUP encoding
                # (mirrors _mpls_nh_attrs) — reporting it as PHP would
                # make desired-vs-dumped reconciliation mismatch forever
                action = MplsAction(action=MplsActionCode.POP_AND_LOOKUP)
            else:
                action = MplsAction(action=MplsActionCode.PHP)
            return NextHop(
                address=BinaryAddress(addr=addr), mpls_action=action
            )

        nhs: List[NextHop] = []
        if RTA_MULTIPATH in attrs:
            data = attrs[RTA_MULTIPATH]
            off = 0
            while off + _RTNEXTHOP.size <= len(data):
                rtnh_len, _f, _h, idx = _RTNEXTHOP.unpack_from(data, off)
                nhs.append(
                    parse_nh(
                        _parse_attrs(
                            data[off + _RTNEXTHOP.size : off + rtnh_len]
                        ),
                        rtnh_index=idx,
                    )
                )
                off += _align4(rtnh_len)
        else:
            nhs.append(parse_nh(attrs))
        return MplsRoute(top_label=label, next_hops=tuple(nhs))

    @staticmethod
    def mpls_supported() -> bool:
        """The kernel has the MPLS forwarding module loaded."""
        import os

        return os.path.exists("/proc/sys/net/mpls")

    # -- event subscription ------------------------------------------------

    def start_events(self, groups: int = RTMGRP_ALL) -> None:
        """Join the rtnetlink multicast groups (links, addresses,
        routes, neighbors) and publish NetlinkEvents (reference:
        NetlinkProtocolSocket's event publication queue; the reference
        subscribes the same groups, nl/NetlinkProtocolSocket.cpp)."""
        if self.events_queue is None or self._event_thread is not None:
            return
        self._event_sock = socket.socket(
            socket.AF_NETLINK, socket.SOCK_RAW, socket.NETLINK_ROUTE
        )
        self._event_sock.bind((0, groups))
        self._event_sock.settimeout(0.2)
        self._running = True
        self._event_thread = threading.Thread(
            target=self._event_loop, name="netlink-events", daemon=True
        )
        self._event_thread.start()

    def stop_events(self) -> None:
        self._running = False
        if self._event_thread is not None:
            self._event_thread.join()
            self._event_thread = None
        if self._event_sock is not None:
            self._event_sock.close()
            self._event_sock = None

    def _event_loop(self) -> None:
        while self._running:
            try:
                data = self._event_sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                # the socket died under us (close race at shutdown, or
                # a kernel-side failure): the thread exits either way,
                # but an unplanned exit must be visible next to
                # monitor.backend_errors in the counter dump
                if self._running:
                    get_registry().counter_bump(
                        "platform.netlink_event_errors"
                    )
                return
            off = 0
            while off + _NLMSGHDR.size <= len(data):
                length, mtype, _f, _s, _p = _NLMSGHDR.unpack_from(data, off)
                payload = data[off + _NLMSGHDR.size : off + length]
                off += _align4(length)
                event = self._parse_event(mtype, payload)
                if event is not None:
                    self.events_queue.push(event)

    def _parse_event(
        self, mtype: int, payload: bytes
    ) -> Optional[NetlinkEvent]:
        if mtype in (RTM_NEWLINK, RTM_DELLINK):
            self._links_cache = None
            return NetlinkEvent(
                event_type=NetlinkEventType.LINK,
                link=self._parse_link(payload),
                deleted=mtype == RTM_DELLINK,
            )
        if mtype in (RTM_NEWADDR, RTM_DELADDR):
            family, plen, _fl, _sc, ifindex = _IFADDRMSG.unpack_from(
                payload
            )
            attrs = _parse_attrs(payload[_IFADDRMSG.size :])
            addr = attrs.get(IFA_LOCAL) or attrs.get(IFA_ADDRESS)
            if addr is None:
                return None
            return NetlinkEvent(
                event_type=NetlinkEventType.ADDRESS,
                prefix=IpPrefix(
                    prefix_address=BinaryAddress(addr=addr),
                    prefix_length=plen,
                ),
                if_index=ifindex,
                deleted=mtype == RTM_DELADDR,
            )
        if mtype in (RTM_NEWROUTE, RTM_DELROUTE):
            route = self._parse_route(payload)
            if route is None:
                return None  # not an openr-owned unicast route
            return NetlinkEvent(
                event_type=NetlinkEventType.ROUTE,
                prefix=route.dest,
                deleted=mtype == RTM_DELROUTE,
            )
        if mtype in (RTM_NEWNEIGH, RTM_DELNEIGH):
            nbr = self._parse_neighbor(payload)
            if nbr is None:
                return None
            return NetlinkEvent(
                event_type=NetlinkEventType.NEIGHBOR,
                neighbor=nbr,
                deleted=mtype == RTM_DELNEIGH,
            )
        return None
