"""FibService platform boundary in the reference thrift wire format.

The reference's Fib module programs routes into a platform agent over
thrift ``FibService`` (openr/if/Platform.thrift:70-135; agent default
port 60100, Constants.h:260). This module serves/dials that contract
as framed CompactProtocol RPC (shared transport: utils/thrift_rpc.py;
Network.thrift struct schemas: utils/thrift_compact.py), so this
daemon's Fib can program a stock FibService agent (an FBOSS-style
switch agent) and a stock Open/R's Fib can program THIS framework's
netlink-backed handler.

Methods (Platform.thrift:90-135, clientId is i16):
- addUnicastRoutes / deleteUnicastRoutes / syncFib
- addMplsRoutes / deleteMplsRoutes / syncMplsFib
- getRouteTableByClient / getMplsRouteTableByClient
- aliveSince (fb303 surface, i64 epoch ms)
"""

from __future__ import annotations

import time
from typing import Dict, List

from openr_tpu.faults.injector import fault_point, register_fault_site
from openr_tpu.platform.fib_service import FibService
from openr_tpu.telemetry import get_registry
from openr_tpu.types import MplsRoute, UnicastRoute
from openr_tpu.utils import thrift_compact as tc
from openr_tpu.utils.eventbase import ExponentialBackoff
from openr_tpu.utils.thrift_rpc import (
    FramedCompactClient,
    FramedCompactServer,
)

# injection seam for the programming transport: fires before the wire
# call, exactly where a dead agent or a torn connection would surface
FAULT_FIB_TRANSPORT = register_fault_site("fib.thrift_transport")

_VOID = tc.StructSchema("void_result", ())


def _args(name: str, second=None) -> tc.StructSchema:
    fields = [tc.Field(1, ("i16",), "clientId")]
    if second is not None:
        fields.append(tc.Field(2, second, "payload"))
    return tc.StructSchema(f"{name}_args", tuple(fields))


_UNICAST_LIST = ("list", ("struct", tc.UNICAST_ROUTE))
_MPLS_LIST = ("list", ("struct", tc.MPLS_ROUTE))
_PREFIX_LIST = ("list", ("struct", tc.IP_PREFIX))

_ADD_UNICAST = _args("addUnicastRoutes", _UNICAST_LIST)
_DEL_UNICAST = _args("deleteUnicastRoutes", _PREFIX_LIST)
_SYNC_FIB = _args("syncFib", _UNICAST_LIST)
_ADD_MPLS = _args("addMplsRoutes", _MPLS_LIST)
_DEL_MPLS = _args("deleteMplsRoutes", ("list", ("i32",)))
_SYNC_MPLS = _args("syncMplsFib", _MPLS_LIST)
_GET_UNICAST = _args("getRouteTableByClient")
_GET_MPLS = _args("getMplsRouteTableByClient")
_ALIVE_ARGS = tc.StructSchema("aliveSince_args", ())

_UNICAST_RESULT = tc.StructSchema(
    "unicast_result",
    (tc.Field(0, _UNICAST_LIST, "success", optional=True),),
)
_MPLS_RESULT = tc.StructSchema(
    "mpls_result", (tc.Field(0, _MPLS_LIST, "success", optional=True),)
)
_ALIVE_RESULT = tc.StructSchema(
    "aliveSince_result",
    (tc.Field(0, ("i64",), "success", optional=True),),
)


class FibThriftServer:
    """Serve any FibService implementation (the netlink-backed
    NetlinkFibHandler, or the mock agent) on the reference wire."""

    def __init__(self, handler: FibService, host: str = "0.0.0.0",
                 port: int = 0):
        self._handler = handler
        h = handler
        self._server = FramedCompactServer(
            {
                "addUnicastRoutes": (
                    _ADD_UNICAST,
                    self._void(
                        lambda a: h.add_unicast_routes(
                            a.get("clientId", 0),
                            [
                                tc._unicast_route_from_wire(r)
                                for r in a.get("payload", [])
                            ],
                        )
                    ),
                ),
                "deleteUnicastRoutes": (
                    _DEL_UNICAST,
                    self._void(
                        lambda a: h.delete_unicast_routes(
                            a.get("clientId", 0),
                            [
                                tc._ip_prefix_from_wire(p)
                                for p in a.get("payload", [])
                            ],
                        )
                    ),
                ),
                "syncFib": (
                    _SYNC_FIB,
                    self._void(
                        lambda a: h.sync_fib(
                            a.get("clientId", 0),
                            [
                                tc._unicast_route_from_wire(r)
                                for r in a.get("payload", [])
                            ],
                        )
                    ),
                ),
                "addMplsRoutes": (
                    _ADD_MPLS,
                    self._void(
                        lambda a: h.add_mpls_routes(
                            a.get("clientId", 0),
                            [
                                tc._mpls_route_from_wire(r)
                                for r in a.get("payload", [])
                            ],
                        )
                    ),
                ),
                "deleteMplsRoutes": (
                    _DEL_MPLS,
                    self._void(
                        lambda a: h.delete_mpls_routes(
                            a.get("clientId", 0), a.get("payload", [])
                        )
                    ),
                ),
                "syncMplsFib": (
                    _SYNC_MPLS,
                    self._void(
                        lambda a: h.sync_mpls_fib(
                            a.get("clientId", 0),
                            [
                                tc._mpls_route_from_wire(r)
                                for r in a.get("payload", [])
                            ],
                        )
                    ),
                ),
                "getRouteTableByClient": (
                    _GET_UNICAST, self._get_unicast,
                ),
                "getMplsRouteTableByClient": (
                    _GET_MPLS, self._get_mpls,
                ),
                "aliveSince": (_ALIVE_ARGS, self._alive),
            },
            host=host,
            port=port,
        )
        self.port = self._server.port

    @staticmethod
    def _void(fn):
        def handler(args: Dict):
            fn(args)
            return _VOID, {}

        return handler

    def _get_unicast(self, args: Dict):
        routes = self._handler.get_route_table_by_client(
            args.get("clientId", 0)
        )
        return _UNICAST_RESULT, {
            "success": [tc._unicast_route_to_wire(r) for r in routes]
        }

    def _get_mpls(self, args: Dict):
        routes = self._handler.get_mpls_route_table_by_client(
            args.get("clientId", 0)
        )
        return _MPLS_RESULT, {
            "success": [tc._mpls_route_to_wire(r) for r in routes]
        }

    def _alive(self, args: Dict):
        return _ALIVE_RESULT, {"success": self._handler.alive_since()}

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()


class ThriftFibAgent(FibService):
    """FibService client over the reference wire — what Fib uses when
    the platform agent speaks thrift (reference: Fib.h:72
    createFibClient)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 10.0,
        retry_min_s: float = 0.05,
        retry_max_s: float = 1.0,
        max_attempts: int = 4,
    ):
        self._client = FramedCompactClient(host, port, timeout_s)
        # bounded retry-with-backoff around every wire call: the
        # underlying client reconnects per call after a transport
        # error, so each attempt is a fresh connection. max_attempts
        # caps the loop — a dead agent costs at most max_attempts-1
        # backoff sleeps, never an unbounded spin.
        self._backoff = ExponentialBackoff(retry_min_s, retry_max_s)
        self._max_attempts = max(1, max_attempts)

    def _call(self, name, schema, args, result_schema) -> Dict:
        last: Exception = RuntimeError("no attempts made")
        for attempt in range(1, self._max_attempts + 1):
            try:
                fault_point(FAULT_FIB_TRANSPORT)
                out = self._client.call(name, schema, args, result_schema)
                self._backoff.report_success()
                return out
            except Exception as exc:  # transport or injected fault
                last = exc
                self._backoff.report_error()
                if attempt == self._max_attempts:
                    break
                get_registry().counter_bump("fib.program_retries")
                time.sleep(
                    self._backoff.get_time_remaining_until_retry()
                )
        get_registry().counter_bump("fib.program_failures")
        raise last

    def _void_call(self, name, schema, client_id, payload=None) -> None:
        args: Dict = {"clientId": client_id}
        if payload is not None:
            args["payload"] = payload
        self._call(name, schema, args, _VOID)

    def add_unicast_routes(self, client_id, routes) -> None:
        self._void_call(
            "addUnicastRoutes", _ADD_UNICAST, client_id,
            [tc._unicast_route_to_wire(r) for r in routes],
        )

    def delete_unicast_routes(self, client_id, prefixes) -> None:
        self._void_call(
            "deleteUnicastRoutes", _DEL_UNICAST, client_id,
            [tc._ip_prefix_to_wire(p) for p in prefixes],
        )

    def sync_fib(self, client_id, routes) -> None:
        self._void_call(
            "syncFib", _SYNC_FIB, client_id,
            [tc._unicast_route_to_wire(r) for r in routes],
        )

    def add_mpls_routes(self, client_id, routes) -> None:
        self._void_call(
            "addMplsRoutes", _ADD_MPLS, client_id,
            [tc._mpls_route_to_wire(r) for r in routes],
        )

    def delete_mpls_routes(self, client_id, labels) -> None:
        self._void_call(
            "deleteMplsRoutes", _DEL_MPLS, client_id, list(labels)
        )

    def sync_mpls_fib(self, client_id, routes) -> None:
        self._void_call(
            "syncMplsFib", _SYNC_MPLS, client_id,
            [tc._mpls_route_to_wire(r) for r in routes],
        )

    def get_route_table_by_client(
        self, client_id
    ) -> List[UnicastRoute]:
        result = self._call(
            "getRouteTableByClient", _GET_UNICAST,
            {"clientId": client_id}, _UNICAST_RESULT,
        )
        return [
            tc._unicast_route_from_wire(r)
            for r in result.get("success", [])
        ]

    def get_mpls_route_table_by_client(
        self, client_id
    ) -> List[MplsRoute]:
        result = self._call(
            "getMplsRouteTableByClient", _GET_MPLS,
            {"clientId": client_id}, _MPLS_RESULT,
        )
        return [
            tc._mpls_route_from_wire(r)
            for r in result.get("success", [])
        ]

    def alive_since(self) -> int:
        result = self._call(
            "aliveSince", _ALIVE_ARGS, {}, _ALIVE_RESULT
        )
        if "success" not in result:
            raise RuntimeError("aliveSince returned no result")
        return result["success"]

    def close(self) -> None:
        self._client.close()
