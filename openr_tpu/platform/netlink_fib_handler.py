"""NetlinkFibHandler: the platform agent programming kernel routes.

Behavioral parity with the reference ``openr/platform/NetlinkFibHandler``
(implements thrift FibService against rtnetlink; started standalone via
LinuxPlatformMain.cpp or in-process, reference: Main.cpp:343-361): keeps
per-client route tables, programs them through a NetlinkProtocolSocket
(mock in-memory kernel or real rtnetlink), and reports liveness.

``FibAgentServer`` / ``TcpFibAgent`` expose/consume it over wire-RPC
(default port 60100, reference: Constants.h:260) so Fib can talk to an
out-of-process agent exactly like the reference's thrift boundary.
"""

from __future__ import annotations

import errno
import time
from typing import Dict, List, Optional

from openr_tpu.faults.injector import fault_point, register_fault_site
from openr_tpu.platform.fib_service import FibService
from openr_tpu.platform.netlink import NetlinkError, NetlinkProtocolSocket
from openr_tpu.telemetry import get_registry
from openr_tpu.types import IpPrefix, MplsRoute, UnicastRoute
from openr_tpu.utils.rpc import RpcClient, RpcServer

FIB_AGENT_RPC_PORT = 60100

# injection seam for kernel programming: fires before the first netlink
# write of a batch, so an injected fault leaves the per-client table
# untouched (like an rtnetlink error on the first route)
FAULT_NETLINK_PROGRAM = register_fault_site("platform.netlink_program")


class NetlinkFibHandler(FibService):
    def __init__(self, netlink: NetlinkProtocolSocket):
        self._nl = netlink
        self._unicast: Dict[int, Dict[IpPrefix, UnicastRoute]] = {}
        self._mpls: Dict[int, Dict[int, MplsRoute]] = {}
        self._alive_since = int(time.time() * 1000)

    # -- FibService -------------------------------------------------------

    def add_unicast_routes(self, client_id, routes) -> None:
        fault_point(FAULT_NETLINK_PROGRAM)
        table = self._unicast.setdefault(client_id, {})
        for route in routes:
            self._nl.add_route(route)
            table[route.dest] = route

    def delete_unicast_routes(self, client_id, prefixes) -> None:
        fault_point(FAULT_NETLINK_PROGRAM)
        table = self._unicast.setdefault(client_id, {})
        for prefix in prefixes:
            self._nl.delete_route(prefix)
            table.pop(prefix, None)

    # errnos that mean "this kernel cannot do MPLS at all" — only these
    # degrade to table-only programming; anything else (EINVAL from a
    # bad next hop, ENODEV from a vanished interface...) is a REAL
    # programming failure and must propagate, not be recorded as success
    _MPLS_UNSUPPORTED_ERRNOS = frozenset(
        {
            errno.EAFNOSUPPORT,
            errno.EPFNOSUPPORT,
            errno.EPROTONOSUPPORT,
            errno.EOPNOTSUPP,
            errno.ENOENT,  # /proc/sys/net/mpls absent: module not loaded
        }
    )

    def _nl_mpls(self, op, *args) -> None:
        """Program MPLS through netlink where the backing socket (and
        kernel) support it; on kernels without MPLS modules the
        per-client table alone is authoritative (reference:
        NetlinkFibHandler MPLS programming, gated on mpls_router)."""
        fn = getattr(self._nl, op, None)
        if fn is None:
            return
        try:
            fn(*args)
        except NotImplementedError:
            # backend has no MPLS entry point at all
            get_registry().counter_bump("platform.mpls_unsupported_ops")
        except NetlinkError as exc:
            if exc.errno not in self._MPLS_UNSUPPORTED_ERRNOS:
                raise
            # kernel without mpls_router: per-client table stays
            # authoritative, but the skipped programming is counted
            get_registry().counter_bump("platform.mpls_unsupported_ops")

    def add_mpls_routes(self, client_id, routes) -> None:
        table = self._mpls.setdefault(client_id, {})
        for route in routes:
            self._nl_mpls("add_mpls_route", route)
            table[route.top_label] = route

    def delete_mpls_routes(self, client_id, labels) -> None:
        table = self._mpls.setdefault(client_id, {})
        for label in labels:
            self._nl_mpls("delete_mpls_route", label)
            table.pop(label, None)

    def sync_fib(self, client_id, routes) -> None:
        """Full-state reconciliation: program adds/changes, remove strays
        (reference: NetlinkFibHandler syncFib semantics)."""
        fault_point(FAULT_NETLINK_PROGRAM)
        desired = {r.dest: r for r in routes}
        current = self._unicast.get(client_id, {})
        for prefix in list(current):
            if prefix not in desired:
                self._nl.delete_route(prefix)
        for route in desired.values():
            self._nl.add_route(route)
        self._unicast[client_id] = desired

    def sync_mpls_fib(self, client_id, routes) -> None:
        desired = {r.top_label: r for r in routes}
        current = self._mpls.get(client_id, {})
        for label in list(current):
            if label not in desired:
                self._nl_mpls("delete_mpls_route", label)
        for route in desired.values():
            self._nl_mpls("add_mpls_route", route)
        self._mpls[client_id] = desired

    def get_route_table_by_client(self, client_id) -> List[UnicastRoute]:
        return sorted(
            self._unicast.get(client_id, {}).values(), key=lambda r: r.dest
        )

    def get_mpls_route_table_by_client(self, client_id) -> List[MplsRoute]:
        return sorted(
            self._mpls.get(client_id, {}).values(),
            key=lambda r: r.top_label,
        )

    def alive_since(self) -> int:
        return self._alive_since


class FibAgentServer:
    """Serve any FibService over wire-RPC (the standalone platform agent,
    reference: LinuxPlatformMain.cpp)."""

    def __init__(
        self, handler: FibService, host: str = "0.0.0.0", port: int = 0
    ):
        self.handler = handler
        self._server = RpcServer(host=host, port=port)
        r = self._server.register
        r("addUnicastRoutes", handler.add_unicast_routes,
          [int, List[UnicastRoute]], type(None))
        r("deleteUnicastRoutes", handler.delete_unicast_routes,
          [int, List[IpPrefix]], type(None))
        r("addMplsRoutes", handler.add_mpls_routes,
          [int, List[MplsRoute]], type(None))
        r("deleteMplsRoutes", handler.delete_mpls_routes,
          [int, List[int]], type(None))
        r("syncFib", handler.sync_fib, [int, List[UnicastRoute]], type(None))
        r("syncMplsFib", handler.sync_mpls_fib,
          [int, List[MplsRoute]], type(None))
        r("getRouteTableByClient", handler.get_route_table_by_client,
          [int], List[UnicastRoute])
        r("getMplsRouteTableByClient",
          handler.get_mpls_route_table_by_client, [int], List[MplsRoute])
        r("aliveSince", handler.alive_since, [], int)
        self.port = self._server.port

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()


class TcpFibAgent(FibService):
    """FibService client over wire-RPC (what Fib uses when the agent runs
    out-of-process; reference: Fib.h:72 createFibClient)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._client = RpcClient(host, port, timeout_s=timeout_s)

    def add_unicast_routes(self, client_id, routes) -> None:
        self._client.call(
            "addUnicastRoutes", [client_id, list(routes)], type(None)
        )

    def delete_unicast_routes(self, client_id, prefixes) -> None:
        self._client.call(
            "deleteUnicastRoutes", [client_id, list(prefixes)], type(None)
        )

    def add_mpls_routes(self, client_id, routes) -> None:
        self._client.call(
            "addMplsRoutes", [client_id, list(routes)], type(None)
        )

    def delete_mpls_routes(self, client_id, labels) -> None:
        self._client.call(
            "deleteMplsRoutes", [client_id, list(labels)], type(None)
        )

    def sync_fib(self, client_id, routes) -> None:
        self._client.call("syncFib", [client_id, list(routes)], type(None))

    def sync_mpls_fib(self, client_id, routes) -> None:
        self._client.call(
            "syncMplsFib", [client_id, list(routes)], type(None)
        )

    def get_route_table_by_client(self, client_id) -> List[UnicastRoute]:
        return self._client.call(
            "getRouteTableByClient", [client_id], List[UnicastRoute]
        )

    def get_mpls_route_table_by_client(self, client_id) -> List[MplsRoute]:
        return self._client.call(
            "getMplsRouteTableByClient", [client_id], List[MplsRoute]
        )

    def alive_since(self) -> int:
        return self._client.call("aliveSince", [], int)
