"""FibService: the platform-agent RPC surface Fib programs routes into.

Interface parity with the reference thrift ``FibService``
(openr/if/Platform.thrift:171): per-client-id unicast/MPLS route
add/delete/sync plus liveness (aliveSince) so Fib can detect agent
restarts and trigger a full resync.

``MockFibAgent`` is the in-memory implementation used by tests
(reference: openr/tests/mocks/MockNetlinkFibHandler.{h,cpp}) with
injectable failures; the Linux netlink-backed implementation lives in
``openr_tpu.platform.netlink``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from openr_tpu.types import IpPrefix, MplsRoute, UnicastRoute


class FibAgentError(Exception):
    pass


class FibService:
    """Abstract platform agent interface."""

    def add_unicast_routes(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        raise NotImplementedError

    def delete_unicast_routes(
        self, client_id: int, prefixes: List[IpPrefix]
    ) -> None:
        raise NotImplementedError

    def add_mpls_routes(self, client_id: int, routes: List[MplsRoute]) -> None:
        raise NotImplementedError

    def delete_mpls_routes(self, client_id: int, labels: List[int]) -> None:
        raise NotImplementedError

    def sync_fib(self, client_id: int, routes: List[UnicastRoute]) -> None:
        raise NotImplementedError

    def sync_mpls_fib(self, client_id: int, routes: List[MplsRoute]) -> None:
        raise NotImplementedError

    def get_route_table_by_client(self, client_id: int) -> List[UnicastRoute]:
        raise NotImplementedError

    def get_mpls_route_table_by_client(self, client_id: int) -> List[MplsRoute]:
        raise NotImplementedError

    def alive_since(self) -> int:
        raise NotImplementedError


class MockFibAgent(FibService):
    """In-memory FibService with failure injection for tests."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._unicast: Dict[int, Dict[IpPrefix, UnicastRoute]] = {}
        self._mpls: Dict[int, Dict[int, MplsRoute]] = {}
        self._alive_since = int(time.time())
        self.fail_requests = False
        self.counters = {
            "add_unicast": 0,
            "delete_unicast": 0,
            "add_mpls": 0,
            "delete_mpls": 0,
            "sync_fib": 0,
            "sync_mpls_fib": 0,
        }

    # -- test controls ----------------------------------------------------

    def restart(self) -> None:
        """Simulate agent restart: state wiped, aliveSince bumps."""
        with self._lock:
            self._unicast.clear()
            self._mpls.clear()
            self._alive_since = int(time.time() * 1000)  # strictly increases

    def set_fail(self, fail: bool) -> None:
        self.fail_requests = fail

    def _maybe_fail(self) -> None:
        if self.fail_requests:
            raise FibAgentError("injected failure")

    # -- FibService -------------------------------------------------------

    def add_unicast_routes(self, client_id, routes) -> None:
        self._maybe_fail()
        with self._lock:
            table = self._unicast.setdefault(client_id, {})
            for r in routes:
                table[r.dest] = r
            self.counters["add_unicast"] += len(routes)

    def delete_unicast_routes(self, client_id, prefixes) -> None:
        self._maybe_fail()
        with self._lock:
            table = self._unicast.setdefault(client_id, {})
            for p in prefixes:
                table.pop(p, None)
            self.counters["delete_unicast"] += len(prefixes)

    def add_mpls_routes(self, client_id, routes) -> None:
        self._maybe_fail()
        with self._lock:
            table = self._mpls.setdefault(client_id, {})
            for r in routes:
                table[r.top_label] = r
            self.counters["add_mpls"] += len(routes)

    def delete_mpls_routes(self, client_id, labels) -> None:
        self._maybe_fail()
        with self._lock:
            table = self._mpls.setdefault(client_id, {})
            for label in labels:
                table.pop(label, None)
            self.counters["delete_mpls"] += len(labels)

    def sync_fib(self, client_id, routes) -> None:
        self._maybe_fail()
        with self._lock:
            self._unicast[client_id] = {r.dest: r for r in routes}
            self.counters["sync_fib"] += 1

    def sync_mpls_fib(self, client_id, routes) -> None:
        self._maybe_fail()
        with self._lock:
            self._mpls[client_id] = {r.top_label: r for r in routes}
            self.counters["sync_mpls_fib"] += 1

    def get_route_table_by_client(self, client_id) -> List[UnicastRoute]:
        with self._lock:
            return sorted(
                self._unicast.get(client_id, {}).values(),
                key=lambda r: r.dest,
            )

    def get_mpls_route_table_by_client(self, client_id) -> List[MplsRoute]:
        with self._lock:
            return sorted(
                self._mpls.get(client_id, {}).values(),
                key=lambda r: r.top_label,
            )

    def alive_since(self) -> int:
        with self._lock:
            return self._alive_since
