"""Netlink layer: kernel interface/address/route access.

Interface parity with the reference ``openr/nl/NetlinkProtocolSocket.h``
(get_all_links / add_route / delete_route + event publication) with a
mock in-memory kernel for tests
(reference: openr/tests/mocks/MockNetlinkProtocolSocket.{h,cpp}).

The real Linux implementation (AF_NETLINK rtnetlink socket) is provided
in ``LinuxNetlinkSocket`` guarded by platform availability; everything
above it (LinkMonitor, Fib handler) only sees this interface.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import IpPrefix, UnicastRoute


class NetlinkError(OSError):
    """Kernel (or mock) rejected a netlink operation; errno carried."""


@dataclass
class NlLink:
    """reference: fbnl::Link (openr/nl/NetlinkTypes.h)."""

    if_name: str
    if_index: int
    is_up: bool = True
    addresses: Tuple[IpPrefix, ...] = ()

    def __post_init__(self) -> None:
        if not isinstance(self.addresses, tuple):
            self.addresses = tuple(self.addresses)


@dataclass(frozen=True)
class NlNeighbor:
    """Kernel neighbor-table (ARP/NDP) entry.
    reference: fbnl::Neighbor (openr/nl/NetlinkTypes.h:1-632)."""

    if_index: int
    destination: IpPrefix  # host address of the neighbor
    link_address: bytes = b""  # MAC, empty when not yet resolved
    state: int = 0  # NUD_* bitmask
    is_reachable: bool = False


# NUD_* neighbor states (linux/neighbour.h)
NUD_INCOMPLETE = 0x01
NUD_REACHABLE = 0x02
NUD_STALE = 0x04
NUD_DELAY = 0x08
NUD_PROBE = 0x10
NUD_FAILED = 0x20
NUD_NOARP = 0x40
NUD_PERMANENT = 0x80
# states the reference treats as usable
NUD_VALID = (
    NUD_PERMANENT | NUD_NOARP | NUD_REACHABLE | NUD_PROBE
    | NUD_STALE | NUD_DELAY
)


class NetlinkEventType(enum.IntEnum):
    LINK = 1
    ADDRESS = 2
    NEIGHBOR = 3
    ROUTE = 4


@dataclass
class NetlinkEvent:
    event_type: NetlinkEventType
    # set ONLY for LINK events — LinkMonitor treats a non-None link as
    # an interface state change, so ADDRESS/ROUTE events must not
    # fabricate one (their payload rides prefix/if_index)
    link: Optional[NlLink] = None
    neighbor: Optional[NlNeighbor] = None
    # ADDRESS: the touched prefix; ROUTE: the route's destination
    prefix: Optional[IpPrefix] = None
    if_index: int = 0
    deleted: bool = False


class NetlinkProtocolSocket:
    """Abstract kernel access interface.
    reference surface: openr/nl/NetlinkProtocolSocket.h:96-196 (routes,
    MPLS label routes, links, addresses, neighbors, event fan-out)."""

    def get_all_links(self) -> List[NlLink]:
        raise NotImplementedError

    def add_route(self, route: UnicastRoute) -> None:
        raise NotImplementedError

    def delete_route(self, prefix: IpPrefix) -> None:
        raise NotImplementedError

    def get_all_routes(self) -> List[UnicastRoute]:
        raise NotImplementedError

    def add_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        raise NotImplementedError

    def del_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        raise NotImplementedError

    def get_ifaddresses(self, if_name: str) -> List[IpPrefix]:
        raise NotImplementedError

    def get_all_neighbors(self) -> List[NlNeighbor]:
        raise NotImplementedError

    def add_mpls_route(self, route) -> None:
        """Program one MPLS label route (types.MplsRoute): top_label ->
        next hops whose mpls_action is SWAP/PHP/POP_AND_LOOKUP.
        reference: nl/NetlinkProtocolSocket.h:131 addRoute(label)."""
        raise NotImplementedError

    def delete_mpls_route(self, label: int) -> None:
        raise NotImplementedError

    def get_all_mpls_routes(self) -> List:
        raise NotImplementedError


class MockNetlinkProtocolSocket(NetlinkProtocolSocket):
    """In-memory kernel with event injection
    (reference: tests/mocks/MockNetlinkProtocolSocket.h +
    NetlinkEventsInjector)."""

    def __init__(self, events_queue: Optional[ReplicateQueue] = None):
        self.events_queue = events_queue or ReplicateQueue(name="netlinkEvents")
        self._lock = threading.Lock()
        self._links: Dict[str, NlLink] = {}
        self._routes: Dict[IpPrefix, UnicastRoute] = {}
        self._neighbors: Dict[Tuple[int, IpPrefix], NlNeighbor] = {}
        self._mpls: Dict[int, object] = {}
        self._next_index = 1

    # -- neighbor-table injection (reference:
    # tests/mocks/NetlinkEventsInjector) --------------------------------

    def _link_or_raise(self, if_name: str) -> NlLink:
        link = self._links.get(if_name)
        if link is None:
            raise NetlinkError(19, f"no such link {if_name}")
        return link

    def set_neighbor(
        self,
        if_name: str,
        destination: IpPrefix,
        link_address: bytes = b"",
        state: int = NUD_REACHABLE,
    ) -> NlNeighbor:
        with self._lock:
            link = self._link_or_raise(if_name)
            nbr = NlNeighbor(
                if_index=link.if_index,
                destination=destination,
                link_address=link_address,
                state=state,
                is_reachable=bool(state & NUD_VALID),
            )
            self._neighbors[(link.if_index, destination)] = nbr
        self.events_queue.push(
            NetlinkEvent(
                event_type=NetlinkEventType.NEIGHBOR, neighbor=nbr
            )
        )
        return nbr

    def del_neighbor(self, if_name: str, destination: IpPrefix) -> None:
        with self._lock:
            link = self._link_or_raise(if_name)
            nbr = self._neighbors.pop((link.if_index, destination), None)
        if nbr is not None:
            self.events_queue.push(
                NetlinkEvent(
                    event_type=NetlinkEventType.NEIGHBOR,
                    neighbor=nbr,
                    deleted=True,
                )
            )

    # -- test injection ---------------------------------------------------

    def add_link(
        self, if_name: str, is_up: bool = True, addresses: Tuple = ()
    ) -> NlLink:
        with self._lock:
            link = NlLink(
                if_name=if_name,
                if_index=self._next_index,
                is_up=is_up,
                addresses=tuple(addresses),
            )
            self._next_index += 1
            self._links[if_name] = link
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.LINK, link=link)
        )
        return link

    def set_link_state(self, if_name: str, is_up: bool) -> None:
        with self._lock:
            link = self._links[if_name]
            link.is_up = is_up
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.LINK, link=link)
        )

    # -- NetlinkProtocolSocket -------------------------------------------

    def get_all_links(self) -> List[NlLink]:
        with self._lock:
            return list(self._links.values())

    def add_route(self, route: UnicastRoute) -> None:
        with self._lock:
            self._routes[route.dest] = route
        self.events_queue.push(
            NetlinkEvent(
                event_type=NetlinkEventType.ROUTE, prefix=route.dest
            )
        )

    def delete_route(self, prefix: IpPrefix) -> None:
        with self._lock:
            existed = self._routes.pop(prefix, None) is not None
        if existed:
            self.events_queue.push(
                NetlinkEvent(
                    event_type=NetlinkEventType.ROUTE,
                    prefix=prefix,
                    deleted=True,
                )
            )

    def get_all_routes(self) -> List[UnicastRoute]:
        with self._lock:
            return sorted(self._routes.values(), key=lambda r: r.dest)

    def add_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        with self._lock:
            link = self._links[if_name]
            link.addresses = tuple(link.addresses) + (prefix,)
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.ADDRESS, link=link)
        )

    def del_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        with self._lock:
            link = self._links[if_name]
            link.addresses = tuple(
                a for a in link.addresses if a != prefix
            )
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.ADDRESS, link=link)
        )

    def get_ifaddresses(self, if_name: str) -> List[IpPrefix]:
        with self._lock:
            link = self._links.get(if_name)
            if link is None:
                raise NetlinkError(19, f"no such link {if_name}")
            return list(link.addresses)

    def get_all_neighbors(self) -> List[NlNeighbor]:
        with self._lock:
            return sorted(
                self._neighbors.values(),
                key=lambda n: (n.if_index, n.destination),
            )

    def add_mpls_route(self, route) -> None:
        with self._lock:
            self._mpls[route.top_label] = route

    def delete_mpls_route(self, label: int) -> None:
        with self._lock:
            self._mpls.pop(label, None)

    def get_all_mpls_routes(self) -> List:
        with self._lock:
            return sorted(
                self._mpls.values(), key=lambda r: r.top_label
            )
