"""Netlink layer: kernel interface/address/route access.

Interface parity with the reference ``openr/nl/NetlinkProtocolSocket.h``
(get_all_links / add_route / delete_route + event publication) with a
mock in-memory kernel for tests
(reference: openr/tests/mocks/MockNetlinkProtocolSocket.{h,cpp}).

The real Linux implementation (AF_NETLINK rtnetlink socket) is provided
in ``LinuxNetlinkSocket`` guarded by platform availability; everything
above it (LinkMonitor, Fib handler) only sees this interface.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import IpPrefix, UnicastRoute


class NetlinkError(OSError):
    """Kernel (or mock) rejected a netlink operation; errno carried."""


@dataclass
class NlLink:
    """reference: fbnl::Link (openr/nl/NetlinkTypes.h)."""

    if_name: str
    if_index: int
    is_up: bool = True
    addresses: Tuple[IpPrefix, ...] = ()

    def __post_init__(self) -> None:
        if not isinstance(self.addresses, tuple):
            self.addresses = tuple(self.addresses)


class NetlinkEventType(enum.IntEnum):
    LINK = 1
    ADDRESS = 2
    NEIGHBOR = 3


@dataclass
class NetlinkEvent:
    event_type: NetlinkEventType
    link: Optional[NlLink] = None


class NetlinkProtocolSocket:
    """Abstract kernel access interface."""

    def get_all_links(self) -> List[NlLink]:
        raise NotImplementedError

    def add_route(self, route: UnicastRoute) -> None:
        raise NotImplementedError

    def delete_route(self, prefix: IpPrefix) -> None:
        raise NotImplementedError

    def get_all_routes(self) -> List[UnicastRoute]:
        raise NotImplementedError

    def add_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        raise NotImplementedError

    def del_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        raise NotImplementedError

    def get_ifaddresses(self, if_name: str) -> List[IpPrefix]:
        raise NotImplementedError


class MockNetlinkProtocolSocket(NetlinkProtocolSocket):
    """In-memory kernel with event injection
    (reference: tests/mocks/MockNetlinkProtocolSocket.h +
    NetlinkEventsInjector)."""

    def __init__(self, events_queue: Optional[ReplicateQueue] = None):
        self.events_queue = events_queue or ReplicateQueue(name="netlinkEvents")
        self._lock = threading.Lock()
        self._links: Dict[str, NlLink] = {}
        self._routes: Dict[IpPrefix, UnicastRoute] = {}
        self._next_index = 1

    # -- test injection ---------------------------------------------------

    def add_link(
        self, if_name: str, is_up: bool = True, addresses: Tuple = ()
    ) -> NlLink:
        with self._lock:
            link = NlLink(
                if_name=if_name,
                if_index=self._next_index,
                is_up=is_up,
                addresses=tuple(addresses),
            )
            self._next_index += 1
            self._links[if_name] = link
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.LINK, link=link)
        )
        return link

    def set_link_state(self, if_name: str, is_up: bool) -> None:
        with self._lock:
            link = self._links[if_name]
            link.is_up = is_up
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.LINK, link=link)
        )

    # -- NetlinkProtocolSocket -------------------------------------------

    def get_all_links(self) -> List[NlLink]:
        with self._lock:
            return list(self._links.values())

    def add_route(self, route: UnicastRoute) -> None:
        with self._lock:
            self._routes[route.dest] = route

    def delete_route(self, prefix: IpPrefix) -> None:
        with self._lock:
            self._routes.pop(prefix, None)

    def get_all_routes(self) -> List[UnicastRoute]:
        with self._lock:
            return sorted(self._routes.values(), key=lambda r: r.dest)

    def add_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        with self._lock:
            link = self._links[if_name]
            link.addresses = tuple(link.addresses) + (prefix,)
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.ADDRESS, link=link)
        )

    def del_ifaddress(self, if_name: str, prefix: IpPrefix) -> None:
        with self._lock:
            link = self._links[if_name]
            link.addresses = tuple(
                a for a in link.addresses if a != prefix
            )
        self.events_queue.push(
            NetlinkEvent(event_type=NetlinkEventType.ADDRESS, link=link)
        )

    def get_ifaddresses(self, if_name: str) -> List[IpPrefix]:
        with self._lock:
            link = self._links.get(if_name)
            if link is None:
                raise NetlinkError(19, f"no such link {if_name}")
            return list(link.addresses)
