"""Standalone platform agent: serve FibService over TCP against the
kernel (the reference's LinuxPlatformMain.cpp binary).

Run:  python -m openr_tpu.platform.agent [--port 60100] [--mock]

The daemon's Fib module connects with ``TcpFibAgent`` (reference: Fib
dialing the platform agent on port 60100, Constants.h:260).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from openr_tpu.platform.netlink import MockNetlinkProtocolSocket
from openr_tpu.telemetry import get_registry
from openr_tpu.platform.netlink_fib_handler import (
    FIB_AGENT_RPC_PORT,
    FibAgentServer,
    NetlinkFibHandler,
)


def build_netlink(force_mock: bool = False):
    if not force_mock:
        try:
            from openr_tpu.platform.netlink_linux import (
                LinuxNetlinkProtocolSocket,
            )

            # mutations need CAP_NET_ADMIN, not just a socket
            if LinuxNetlinkProtocolSocket.is_admin_available():
                return LinuxNetlinkProtocolSocket()
        except (OSError, AttributeError):  # AttributeError: non-Linux
            # count the downgrade: a prod agent meant to program the
            # kernel that silently fell back to the in-memory mock is
            # invisible without this
            get_registry().counter_bump("platform.netlink_probe_errors")
    return MockNetlinkProtocolSocket()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="openr-tpu-platform-agent")
    parser.add_argument("--port", type=int, default=FIB_AGENT_RPC_PORT)
    parser.add_argument(
        "--mock", action="store_true",
        help="in-memory kernel instead of rtnetlink",
    )
    parser.add_argument(
        "--thrift", action="store_true",
        help="serve the reference FibService thrift wire (framed "
             "CompactProtocol, Platform.thrift:70) instead of the "
             "framework RPC codec — a stock Open/R Fib can program "
             "this agent",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger("openr_tpu.platform.agent")

    netlink = build_netlink(force_mock=args.mock)
    handler = NetlinkFibHandler(netlink)
    if args.thrift:
        from openr_tpu.platform.thrift_fib import FibThriftServer

        server = FibThriftServer(handler, port=args.port)
    else:
        server = FibAgentServer(handler, port=args.port)
    server.start()
    log.info(
        "platform agent (%s kernel, %s wire) listening on port %d",
        type(netlink).__name__,
        "thrift-compact" if args.thrift else "framework-rpc",
        server.port,
    )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
