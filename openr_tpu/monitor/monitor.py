"""Monitor: structured event-log drain + system metrics.

Behavioral parity with the reference ``openr/monitor/``:
- ``LogSample`` structured JSON-style event records with common fields
  merged in (monitor/LogSample.h)
- a Monitor module draining the log-sample queue, retaining a bounded
  history and forwarding to a pluggable backend (monitor/MonitorBase.h:32)
- ``SystemMetrics``: process RSS / CPU sampling (monitor/SystemMetrics.h)
"""

from __future__ import annotations

import json
import os
import resource
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.telemetry import get_registry
from openr_tpu.utils.eventbase import OpenrEventBase


class LogSample:
    """Structured event record (reference: monitor/LogSample.h)."""

    def __init__(self, **values):
        self._values: Dict[str, object] = dict(values)
        self._values.setdefault("time", int(time.time()))

    def add_string(self, key: str, value: str) -> "LogSample":
        self._values[key] = value
        return self

    def add_int(self, key: str, value: int) -> "LogSample":
        self._values[key] = int(value)
        return self

    def get(self, key: str):
        return self._values.get(key)

    def to_json(self) -> str:
        return json.dumps(self._values, sort_keys=True)

    @staticmethod
    def from_json(raw: str) -> "LogSample":
        return LogSample(**json.loads(raw))


def push_log_sample(queue: Optional[ReplicateQueue], **fields) -> None:
    """Push one structured event sample toward the Monitor; no-op when
    the producing module runs without a wired log queue. The single
    shared helper for every module's event-log site (reference pattern:
    logSampleQueue_.push in KvStore.cpp:3104, LinkMonitor.cpp:1287,
    Fib.cpp:891, PrefixAllocator.cpp logPrefixEvent)."""
    if queue is not None:
        queue.push(LogSample(**fields))


class SystemMetrics:
    """reference: monitor/SystemMetrics.h — RSS/CPU snapshots."""

    @staticmethod
    def rss_bytes() -> int:
        """CURRENT resident set size. ru_maxrss is the process's *peak*
        RSS — reporting it as current hides every memory release, so on
        Linux read /proc/self/statm (field 2, pages); the rusage peak
        stays available as rss_peak_bytes and as the fallback here."""
        try:
            with open("/proc/self/statm") as f:
                return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            # /proc/self/statm unreadable or malformed (non-Linux):
            # count the fallback — peak-RSS-as-current hides memory
            # releases, so a dashboard reading this metric should be
            # able to see it is degraded
            get_registry().counter_bump("monitor.statm_fallbacks")
            return SystemMetrics.rss_peak_bytes()

    @staticmethod
    def rss_peak_bytes() -> int:
        # ru_maxrss is KiB on Linux
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    @staticmethod
    def cpu_seconds() -> float:
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return ru.ru_utime + ru.ru_stime


class Monitor:
    """Drains the log-sample queue; merges common fields; keeps a bounded
    history; forwards to an optional backend callback.
    reference: monitor/MonitorBase.h:32, Monitor.h:27."""

    def __init__(
        self,
        node_name: str,
        log_sample_queue: ReplicateQueue,
        max_history: int = 1024,
        backend: Optional[Callable[[LogSample], None]] = None,
        common_fields: Optional[Dict[str, object]] = None,
    ):
        self.node_name = node_name
        self.evb = OpenrEventBase(name=f"monitor:{node_name}")
        self._history: Deque[LogSample] = deque(maxlen=max_history)
        self._backend = backend
        self._common = dict(common_fields or {})
        self._common.setdefault("node_name", node_name)
        self.num_processed = 0
        self.evb.add_queue_reader(
            log_sample_queue.get_reader(f"monitor:{node_name}"),
            self._process_event_log,
        )

    def start(self) -> None:
        self.evb.run_in_thread()

    def stop(self) -> None:
        self.evb.stop()
        self.evb.join()

    def _process_event_log(self, sample: LogSample) -> None:
        """reference: Monitor::processEventLog."""
        for key, value in self._common.items():
            if sample.get(key) is None:
                sample.add_string(key, value) if isinstance(
                    value, str
                ) else sample.add_int(key, value)
        self._history.append(sample)
        self.num_processed += 1
        if self._backend is not None:
            try:
                self._backend(sample)
            except Exception:
                # a broken backend must not take the drain loop down,
                # but the drop has to be countable (was a silent pass)
                get_registry().counter_bump("monitor.backend_errors")

    def get_event_logs(self, limit: int = 100) -> List[LogSample]:
        return self.evb.call_and_wait(
            lambda: list(self._history)[-limit:]
        )

    def get_counters(self) -> Dict[str, object]:
        def collect() -> Dict[str, object]:
            # the process-wide registry snapshot (telemetry spine) +
            # monitor-local and system gauges, one flat fb303 dict
            out: Dict[str, object] = dict(get_registry().snapshot())
            out.update(
                {
                    "monitor.log_samples_processed": self.num_processed,
                    "process.rss_bytes": SystemMetrics.rss_bytes(),
                    "process.rss_peak_bytes": SystemMetrics.rss_peak_bytes(),
                    "process.cpu_seconds": SystemMetrics.cpu_seconds(),
                }
            )
            return out

        return self.evb.call_and_wait(collect)
