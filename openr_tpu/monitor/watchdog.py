"""Watchdog: liveness + memory-limit enforcement for module event bases.

Behavioral parity with the reference ``openr/watchdog/Watchdog.h:24-42``:
every module's event base registers (addEvb); a periodic check verifies
each loop has made progress recently and that process RSS is under the
limit; violations invoke ``fire_crash`` (default: abort the process so a
supervisor restarts it — overridable for tests).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from openr_tpu.monitor.monitor import SystemMetrics
from openr_tpu.telemetry import get_registry
from openr_tpu.utils.eventbase import OpenrEventBase


class Watchdog:
    def __init__(
        self,
        interval_s: float = 1.0,
        thread_timeout_s: float = 30.0,
        max_memory_bytes: Optional[int] = None,
        crash_handler: Optional[Callable[[str], None]] = None,
    ):
        self.evb = OpenrEventBase(name="watchdog")
        self._interval = interval_s
        self._thread_timeout = thread_timeout_s
        self._max_memory = max_memory_bytes
        self._crash_handler = crash_handler or self._default_crash
        self._monitored: List[Tuple[str, OpenrEventBase]] = []
        self._timer = None
        self.violations: List[str] = []
        # how many monitored event bases the LAST check found stalled —
        # a gauge a dashboard can alert on before fire_crash aborts
        self._stalled = 0
        get_registry().gauge("watchdog.stalled", lambda: self._stalled)

    # -- registration -----------------------------------------------------

    def add_evb(self, name: str, evb: OpenrEventBase) -> None:
        """reference: Watchdog.h:32 addEvb."""
        self._monitored.append((name, evb))

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.evb.run_in_thread()
        self._timer = self.evb.schedule_periodic(
            self._interval, self._check, jitter_first=True
        )

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
        self.evb.stop()
        self.evb.join()

    # -- checks -----------------------------------------------------------

    def _check(self) -> None:
        now = time.monotonic()
        stalled = 0
        for name, evb in self._monitored:
            if not evb.is_running:
                continue
            stalled_for = now - evb.last_loop_ts
            if stalled_for > self._thread_timeout:
                stalled += 1
                get_registry().counter_bump(f"watchdog.stalls.{name}")
                self._fire_crash(
                    f"event base {name!r} stalled for {stalled_for:.1f}s"
                )
        # openr-lint: disable=shared-state -- stall gauge reads this single int unlocked; a GIL-atomic stale read only ages one scrape
        self._stalled = stalled
        if self.memory_limit_exceeded():
            self._fire_crash(
                f"memory limit exceeded: rss={SystemMetrics.rss_bytes()}"
                f" > {self._max_memory}"
            )

    def memory_limit_exceeded(self) -> bool:
        """reference: Watchdog.h:34 memoryLimitExceeded."""
        return (
            self._max_memory is not None
            and SystemMetrics.rss_bytes() > self._max_memory
        )

    def _fire_crash(self, reason: str) -> None:
        """reference: Watchdog.h:40-42 fireCrash."""
        self.violations.append(reason)
        self._crash_handler(reason)

    @staticmethod
    def _default_crash(reason: str) -> None:
        import logging

        logging.getLogger(__name__).critical("watchdog: %s — aborting", reason)
        os.abort()
