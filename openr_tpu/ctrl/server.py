"""CtrlServer: TCP transport for the control API.

The analogue of the reference's thrift ctrl server (port 2018,
reference: Main.cpp:587-592): length-prefixed JSON frames
``{"method": ..., "kwargs": {...}}`` -> ``{"ok": true, "result": ...}``.
Results are projected through ``utils.jsonable``. Streaming subscriptions
(``subscribe_kvstore_filtered`` / ``subscribe_fib``) hold the connection
open and push one frame per event until the client disconnects.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, Optional

from openr_tpu.ctrl.handler import OpenrCtrlHandler
from openr_tpu.messaging.queue import QueueClosedError, QueueTimeoutError
from openr_tpu.utils.jsonable import to_jsonable

_STREAM_METHODS = {"subscribe_kvstore_filtered", "subscribe_fib"}

# Each JSON connection is served by one dedicated thread, so the
# connection identity rides a thread-local: handlers that care which
# client is speaking (the solver service ties tenants to connections
# for graceful detach) read ``current_connection()`` during a dispatch
# and implement ``connection_closed(conn_id)`` for the teardown.
_CONN = threading.local()
_CONN_SEQ = [0]
_CONN_LOCK = threading.Lock()


def current_connection() -> Optional[int]:
    """The serving connection's id inside a handler dispatch (None
    outside one — e.g. a handler called in-process without a socket)."""
    return getattr(_CONN, "conn_id", None)


def current_trace_context() -> Optional[Dict[str, Any]]:
    """The caller-stamped trace context of the frame being dispatched
    (None when the client sent none, or outside a dispatch). A client
    that wants end-to-end attribution adds a top-level ``"trace"``
    object — ``{"trace_id", "span_id", "origin"}`` — to its request
    frame; handlers adopt it into their own spans so a client-observed
    latency breach can be chased through the service's wave records."""
    return getattr(_CONN, "trace_ctx", None)


class CtrlRedirect(Exception):
    """Raised inside a handler dispatch to answer with a redirect: the
    reply frame carries ``moved_to: {host, port}`` next to the error
    text, and a fleet-aware client re-dials the target it names (the
    solver client counts the hop and follows it; a plain ``CtrlClient``
    surfaces it as the usual RuntimeError)."""

    def __init__(self, message: str, host: str, port: int):
        super().__init__(message)
        self.host = host
        self.port = port


class CtrlRetry(Exception):
    """Raised inside a handler dispatch to answer retry-later: the
    target exists but is transiently unroutable (a tenant frozen
    mid-migration). The reply frame carries ``retry: true`` and a
    ``retry_after_ms`` hint the client's backoff respects."""

    def __init__(self, message: str, retry_after_ms: float = 50.0):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack(">I", len(payload)) + payload)


# the frame cap (and its stay-below-a-ClientHello invariant) lives in
# ONE place: utils/rpc.py
from openr_tpu.utils.rpc import MAX_FRAME


def _recv_frame(sock: socket.socket) -> Optional[Dict]:
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        if not chunk:
            return None
        header += chunk
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        return None  # garbage or a TLS handshake: hang up
    payload = b""
    while len(payload) < length:
        chunk = sock.recv(length - len(payload))
        if not chunk:
            return None
        payload += chunk
    return json.loads(payload.decode("utf-8"))


class CtrlServer:
    """``ssl_context``: serve the ctrl API over TLS (reference: the
    thrift ctrl server's optional TLS; clients use the secure-then-
    plain fallback factory, openr_client.py:27-140).

    The port is DUAL-STACKED by byte-sniffing the first bytes of every
    connection (same trick as kvstore/dualstack.py, mirroring the
    reference's wire-migration listeners KvStore.cpp:2940-2973):

    - ``0x16`` first          -> TLS ClientHello: handshake, then sniff
      the DECRYPTED stream the same way (thrift or JSON over TLS);
    - ``0x82`` at offset 4    -> framed thrift CompactProtocol: the
      stock-toolchain OpenrCtrl service (ctrl/thrift_ctrl.py,
      reference if/OpenrCtrl.thrift:168-577);
    - ``0x0F 0xFF`` at 4      -> THeader-wrapped thrift (the fbthrift
      client default; utils/theader.py);
    - anything else           -> plain framework JSON frames.

    When TLS is configured, EVERY wire must arrive inside it — a
    plaintext thrift dial is rejected exactly like a plaintext JSON
    dial (no sniff path may bypass the operator's TLS requirement).
    """

    def __init__(self, handler: OpenrCtrlHandler, host="127.0.0.1",
                 port=0, ssl_context=None):
        from openr_tpu.ctrl.thrift_ctrl import ThriftCtrlServer

        self.handler = handler
        self._ssl_context = ssl_context
        # thrift backend used for its serve_connection loop only;
        # listen=False builds a pure dispatcher with no socket bound
        # (start/stop are no-ops — see utils/thrift_rpc.py)
        self._thrift_backend = ThriftCtrlServer(
            handler, listen=False
        )
        # live accepted sockets, severed on stop(): a stopped server
        # must look DEAD to connected clients (the fleet failover
        # detector and the client reconnect path both depend on open
        # connections dying with the service, as they do when a real
        # process/device is lost)
        self._live_lock = threading.Lock()
        self._live: set = set()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                with outer._live_lock:
                    outer._live.add(self.request)
                try:
                    self._handle_classified()
                finally:
                    with outer._live_lock:
                        outer._live.discard(self.request)

            def _handle_classified(self) -> None:
                from openr_tpu.utils.rpc import (
                    peek_first_bytes,
                    wrap_server_connection,
                )

                head = peek_first_bytes(self.request, 6)
                if head is None:
                    return
                self.request.settimeout(None)
                if head[0] == 0x16:
                    # TLS: handshake first, then classify the DECRYPTED
                    # stream (SSL sockets cannot MSG_PEEK — read the
                    # first frame and replay it to the chosen backend)
                    wrapped = wrap_server_connection(
                        self.request, outer._ssl_context
                    )
                    if wrapped is None:
                        return
                    outer._serve_classified_tls(wrapped)
                    return
                if outer._ssl_context is not None:
                    return  # TLS required: reject every plaintext wire
                if _is_thrift_head(head):
                    outer._thrift_backend.serve_connection(self.request)
                    return
                outer._serve_json(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"ctrl-server:{self.port}",
            daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._live_lock:
            live = list(self._live)
        for sock in live:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def _serve_json(self, sock) -> None:
        with _CONN_LOCK:
            _CONN_SEQ[0] += 1
            conn_id = _CONN_SEQ[0]
        _CONN.conn_id = conn_id
        try:
            while True:
                try:
                    request = _recv_frame(sock)
                except (ConnectionError, OSError):
                    return
                if request is None:
                    return
                self._dispatch(sock, request)
        finally:
            _CONN.conn_id = None
            # duck-typed teardown: a handler that tracks per-connection
            # state (solver service tenants) detaches it here — abrupt
            # client death lands on the same path as a clean close
            closed = getattr(self.handler, "connection_closed", None)
            if closed is not None:
                try:
                    closed(conn_id)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass

    def _serve_classified_tls(self, tls_sock) -> None:
        """Read the first frame head off the TLS stream, classify it,
        and hand a replaying socket to the matching backend."""
        try:
            head = _read_exact_sock(tls_sock, 6)
        except (ConnectionError, OSError):
            return
        if head is None:
            return
        replay = _ReplaySocket(tls_sock, head)
        if _is_thrift_head(head):
            self._thrift_backend.serve_connection(replay)
            return
        self._serve_json(replay)

    def _dispatch(self, sock: socket.socket, request: Dict) -> None:
        method_name = request.get("method", "")
        kwargs = request.get("kwargs", {})
        # cross-wire trace propagation: an extra top-level "trace" key
        # rides the frame (ignored by older servers) and is visible to
        # the handler for the duration of this dispatch
        trace_ctx = request.get("trace")
        _CONN.trace_ctx = trace_ctx if isinstance(trace_ctx, dict) else None
        method = getattr(self.handler, method_name, None)
        if method is None or method_name.startswith("_"):
            _send_frame(sock, {"ok": False, "error": f"no method {method_name}"})
            return
        if method_name in _STREAM_METHODS:
            self._stream(sock, method, kwargs)
            return
        try:
            result = method(**kwargs)
            _send_frame(sock, {"ok": True, "result": to_jsonable(result)})
        except CtrlRedirect as e:
            _send_frame(sock, {
                "ok": False,
                "error": str(e),
                "moved_to": {"host": e.host, "port": e.port},
            })
        except CtrlRetry as e:
            _send_frame(sock, {
                "ok": False,
                "error": str(e),
                "retry": True,
                "retry_after_ms": e.retry_after_ms,
            })
        except Exception as e:  # noqa: BLE001 - relayed to client
            _send_frame(sock, {"ok": False, "error": repr(e)})
        finally:
            _CONN.trace_ctx = None

    def _stream(self, sock: socket.socket, method, kwargs: Dict) -> None:
        try:
            reader = method(**kwargs)
        except Exception as e:  # noqa: BLE001
            _send_frame(sock, {"ok": False, "error": repr(e)})
            return
        _send_frame(sock, {"ok": True, "stream": True})
        while True:
            try:
                item = reader.get(timeout=1.0)
            except QueueTimeoutError:
                continue
            except QueueClosedError:
                return
            try:
                _send_frame(sock, {"ok": True, "event": to_jsonable(item)})
            except (ConnectionError, OSError):
                return


def _is_thrift_head(head: bytes) -> bool:
    from openr_tpu.utils.thrift_rpc import is_thrift_head

    return is_thrift_head(head)


def _read_exact_sock(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _ReplaySocket:
    """Socket adapter that serves pre-read bytes before delegating —
    the TLS demux consumed the classification head from the decrypted
    stream and the backend's frame reader must still see it."""

    def __init__(self, sock, head: bytes):
        self._sock = sock
        self._head = head

    def recv(self, n: int) -> bytes:
        if self._head:
            out, self._head = self._head[:n], self._head[n:]
            return out
        return self._sock.recv(n)

    def sendall(self, data: bytes) -> None:
        self._sock.sendall(data)

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def close(self) -> None:
        self._sock.close()


class CtrlClient:
    """Client for CtrlServer (used by the breeze CLI remotely).

    Connection behavior mirrors the reference client factory
    (openr_client.py get_openr_ctrl_client): try a TLS handshake first
    — accepting the daemon's self-signed onbox cert — and fall back to
    plain text when the server does not speak TLS."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2018):
        from openr_tpu.utils.rpc import probe_tls

        ctx = probe_tls(host, port, timeout_s=30)
        sock = socket.create_connection((host, port), timeout=30)
        self._sock = (
            ctx.wrap_socket(sock, server_hostname=host)
            if ctx is not None
            else sock
        )

    def call(self, method: str, **kwargs) -> Any:
        _send_frame(self._sock, {"method": method, "kwargs": kwargs})
        response = _recv_frame(self._sock)
        if response is None:
            raise ConnectionError("server closed connection")
        if not response.get("ok"):
            raise RuntimeError(response.get("error", "unknown error"))
        return response.get("result")

    def stream(self, method: str, **kwargs):
        """Generator over streamed events."""
        _send_frame(self._sock, {"method": method, "kwargs": kwargs})
        first = _recv_frame(self._sock)
        if first is None or not first.get("ok"):
            raise RuntimeError(first.get("error") if first else "closed")
        while True:
            frame = _recv_frame(self._sock)
            if frame is None:
                return
            yield frame.get("event")

    def close(self) -> None:
        self._sock.close()
