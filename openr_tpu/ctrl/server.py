"""CtrlServer: TCP transport for the control API.

The analogue of the reference's thrift ctrl server (port 2018,
reference: Main.cpp:587-592): length-prefixed JSON frames
``{"method": ..., "kwargs": {...}}`` -> ``{"ok": true, "result": ...}``.
Results are projected through ``utils.jsonable``. Streaming subscriptions
(``subscribe_kvstore_filtered`` / ``subscribe_fib``) hold the connection
open and push one frame per event until the client disconnects.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, Optional

from openr_tpu.ctrl.handler import OpenrCtrlHandler
from openr_tpu.messaging.queue import QueueClosedError, QueueTimeoutError
from openr_tpu.utils.jsonable import to_jsonable

_STREAM_METHODS = {"subscribe_kvstore_filtered", "subscribe_fib"}


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack(">I", len(payload)) + payload)


# the frame cap (and its stay-below-a-ClientHello invariant) lives in
# ONE place: utils/rpc.py
from openr_tpu.utils.rpc import MAX_FRAME


def _recv_frame(sock: socket.socket) -> Optional[Dict]:
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        if not chunk:
            return None
        header += chunk
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        return None  # garbage or a TLS handshake: hang up
    payload = b""
    while len(payload) < length:
        chunk = sock.recv(length - len(payload))
        if not chunk:
            return None
        payload += chunk
    return json.loads(payload.decode("utf-8"))


class CtrlServer:
    """``ssl_context``: serve the ctrl API over TLS (reference: the
    thrift ctrl server's optional TLS; clients use the secure-then-
    plain fallback factory, openr_client.py:27-140)."""

    def __init__(self, handler: OpenrCtrlHandler, host="127.0.0.1",
                 port=0, ssl_context=None):
        self.handler = handler
        self._ssl_context = ssl_context
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                from openr_tpu.utils.rpc import wrap_server_connection

                wrapped = wrap_server_connection(
                    self.request, outer._ssl_context
                )
                if wrapped is None:
                    return
                self.request = wrapped
                while True:
                    try:
                        request = _recv_frame(self.request)
                    except (ConnectionError, OSError):
                        return
                    if request is None:
                        return
                    outer._dispatch(self.request, request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"ctrl-server:{self.port}",
            daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def _dispatch(self, sock: socket.socket, request: Dict) -> None:
        method_name = request.get("method", "")
        kwargs = request.get("kwargs", {})
        method = getattr(self.handler, method_name, None)
        if method is None or method_name.startswith("_"):
            _send_frame(sock, {"ok": False, "error": f"no method {method_name}"})
            return
        if method_name in _STREAM_METHODS:
            self._stream(sock, method, kwargs)
            return
        try:
            result = method(**kwargs)
            _send_frame(sock, {"ok": True, "result": to_jsonable(result)})
        except Exception as e:  # noqa: BLE001 - relayed to client
            _send_frame(sock, {"ok": False, "error": repr(e)})

    def _stream(self, sock: socket.socket, method, kwargs: Dict) -> None:
        try:
            reader = method(**kwargs)
        except Exception as e:  # noqa: BLE001
            _send_frame(sock, {"ok": False, "error": repr(e)})
            return
        _send_frame(sock, {"ok": True, "stream": True})
        while True:
            try:
                item = reader.get(timeout=1.0)
            except QueueTimeoutError:
                continue
            except QueueClosedError:
                return
            try:
                _send_frame(sock, {"ok": True, "event": to_jsonable(item)})
            except (ConnectionError, OSError):
                return


class CtrlClient:
    """Client for CtrlServer (used by the breeze CLI remotely).

    Connection behavior mirrors the reference client factory
    (openr_client.py get_openr_ctrl_client): try a TLS handshake first
    — accepting the daemon's self-signed onbox cert — and fall back to
    plain text when the server does not speak TLS."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2018):
        from openr_tpu.utils.rpc import probe_tls

        ctx = probe_tls(host, port, timeout_s=30)
        sock = socket.create_connection((host, port), timeout=30)
        self._sock = (
            ctx.wrap_socket(sock, server_hostname=host)
            if ctx is not None
            else sock
        )

    def call(self, method: str, **kwargs) -> Any:
        _send_frame(self._sock, {"method": method, "kwargs": kwargs})
        response = _recv_frame(self._sock)
        if response is None:
            raise ConnectionError("server closed connection")
        if not response.get("ok"):
            raise RuntimeError(response.get("error", "unknown error"))
        return response.get("result")

    def stream(self, method: str, **kwargs):
        """Generator over streamed events."""
        _send_frame(self._sock, {"method": method, "kwargs": kwargs})
        first = _recv_frame(self._sock)
        if first is None or not first.get("ok"):
            raise RuntimeError(first.get("error") if first else "closed")
        while True:
            frame = _recv_frame(self._sock)
            if frame is None:
                return
            yield frame.get("event")

    def close(self) -> None:
        self._sock.close()
