"""OpenrCtrl on the thrift wire: the operator surface a STOCK Open/R
toolchain speaks.

The framework's own ctrl codec (ctrl/server.py, JSON frames) remains
the native surface; THIS module exposes the high-traffic subset of the
reference thrift service (`/root/reference/openr/if/OpenrCtrl.thrift:
168-577`, handler `ctrl-server/OpenrCtrlHandler.h:24`) as framed
CompactProtocol — the same interop wire the KvStore peer channel and
FibService already speak (utils/thrift_rpc.py). A stock breeze or
external automation dialing the ctrl port with classic framed transport
round-trips these RPCs against an openr-tpu node.

The FULL request/response service surface is implemented — all the
IDL's RPCs: KvStore get/dump/hash/set + peers + long-poll + DUAL +
flood topology + spanning-tree info, routes computed/installed
(unicast + MPLS), advertised/received routes (+filters), PrefixManager
advertise/withdraw/sync/get (+byType), adjacency/prefix dbs,
counters/aliveSince/perfDb, node and interface drain, interface and
adjacency metric overrides, interfaces/neighbors dumps,
version/buildInfo/config (string + thrift) + config-store keys +
areas, RibPolicy get/set, event logs. Streaming subscriptions stay on
the framework wire (the reference serves those over fbthrift Rocket
streams, a different outer transport from classic framed thrift;
stock-shaped clients can follow changes via longPollKvStoreAdj +
filtered re-dump, the documented long-poll emulation).

Thrift service conventions: per-method args struct (ids from the IDL),
result struct with ``success`` at field 0 and declared ``OpenrError``
exceptions at field 1; undeclared failures become
TApplicationException (utils/thrift_rpc.py handles the envelope).

Dual-stacking on the ctrl port is byte-sniffed in ctrl/server.py: a
compact-protocol message leads with 0x82 after the 4-byte frame
length, a TLS ClientHello leads with 0x16, the framework JSON codec
with ``{`` — all three wire shapes share one advertised port.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

from openr_tpu.types import IpPrefix as _IpPrefix
from openr_tpu.utils import thrift_compact as tc
from openr_tpu.utils.thrift_rpc import (
    FramedCompactClient,
    FramedCompactServer,
    MethodTable,
)

OPENR_VERSION = 20200825  # reference: common/Constants.h:274
OPENR_LOWEST_SUPPORTED_VERSION = 20200604  # Constants.h:277

_VOID = object()  # sentinel: method returns nothing


def _result_schema(name: str, ret, throws: bool):
    fields = []
    if ret is not _VOID:
        fields.append(tc.Field(0, ret, "success", optional=True))
    if throws:
        fields.append(
            tc.Field(
                1, ("struct", tc.OPENR_ERROR), "error", optional=True
            )
        )
    return tc.StructSchema(f"{name}_result", tuple(fields))


class _Method:
    def __init__(self, name, arg_fields, ret, fn, throws=False):
        self.name = name
        self.args_schema = tc.StructSchema(
            f"{name}_args", tuple(arg_fields)
        )
        self.result_schema = _result_schema(name, ret, throws)
        self.ret = ret
        self.fn = fn
        self.throws = throws

    def handle(self, args: Dict) -> Tuple[object, Dict]:
        try:
            value = self.fn(args)
        except Exception as exc:
            if self.throws:
                return self.result_schema, {
                    "error": {"message": f"{type(exc).__name__}: {exc}"}
                }
            raise
        if self.ret is _VOID:
            return self.result_schema, {}
        return self.result_schema, {"success": value}


def _pub_to_wire(key_vals, area: str) -> Dict:
    return {
        "keyVals": {
            k: tc._value_to_wire(v) for k, v in key_vals.items()
        },
        "expiredKeys": [],
        "area": area,
    }


def _node_of(key) -> str:
    """PrefixState entry keys are node names or (node, area) pairs."""
    return key[0] if isinstance(key, tuple) else key


def build_method_table(handler) -> MethodTable:
    """Method table for utils.thrift_rpc.FramedCompactServer wrapping
    an OpenrCtrlHandler."""
    F = tc.Field

    def kv_publication(args, dump=False, hashes=False):
        area = args.get("area", "0")
        if hashes:
            prefix = (args.get("filter") or {}).get("prefix", "")
            kvs = handler.get_kvstore_hash_filtered(
                prefix=prefix, area=area
            )
        elif dump:
            params = tc._key_dump_params_from_wire(
                args.get("filter") or {}
            )
            pub = handler._kvstore.dump_with_filters(area, params)
            kvs = pub.key_vals
        else:
            kvs = handler.get_kvstore_key_vals(
                list(args.get("filterKeys", [])), area=area
            )
        return _pub_to_wire(kvs, area)

    def set_key_vals(args):
        params = tc._key_set_params_from_wire(
            args.get("setParams") or {}
        )
        handler._kvstore.set_key_vals(args.get("area", "0"), params)

    def peers_map(args):
        area = args.get("area", "0")
        # one eventbase round trip: peer_endpoints' keys ARE the peer
        # names. In-process transports have no endpoint; stock tooling
        # renders the empty PeerSpec as "no address known".
        return {
            name: {
                "peerAddr": ep[0] if ep else "",
                "cmdUrl": "",
                "ctrlPort": ep[1] if ep else 0,
            }
            for name, ep in handler._kvstore.peer_endpoints(
                area
            ).items()
        }

    def route_db(args=None, node=None):
        db = (
            handler.get_route_db()
            if node is None
            else handler.get_route_db_computed(node or None)
        )
        return tc.route_db_to_wire(db)

    def unicast_routes(args, filtered=False):
        prefixes = (
            list(args.get("prefixes", [])) if filtered else None
        )
        routes = handler.get_unicast_routes(prefixes or None)
        return [tc._unicast_route_to_wire(r) for r in routes]

    def mpls_routes(args, filtered=False):
        labels = set(args.get("labels", [])) if filtered else None
        routes = handler.get_route_db().mpls_routes
        return [
            tc._mpls_route_to_wire(r)
            for r in routes
            if not labels or r.top_label in labels
        ]

    def flat_adj_dbs() -> Dict[str, Any]:
        # handler returns {area: {node: AdjacencyDatabase}}; the thrift
        # AdjDbs is a per-node map (first area wins on collision, like
        # the reference's single-area legacy view)
        out: Dict[str, Any] = {}
        for _area, dbs in sorted(
            handler.get_decision_adjacency_dbs().items()
        ):
            for name, db in dbs.items():
                out.setdefault(name, db)
        return out

    def adj_dbs(args):
        return {
            name: tc.adjacency_db_to_wire(db)
            for name, db in flat_adj_dbs().items()
        }

    def all_adj_dbs(args):
        return [
            tc.adjacency_db_to_wire(db)
            for _, db in sorted(flat_adj_dbs().items())
        ]

    def prefix_dbs(args):
        from openr_tpu.types import PrefixDatabase

        by_node: Dict[str, List] = {}
        for _prefix, entries in handler.get_decision_prefix_dbs().items():
            for key, entry in entries.items():
                by_node.setdefault(_node_of(key), []).append(entry)
        return {
            node: tc.prefix_db_to_wire(
                PrefixDatabase(
                    this_node_name=node,
                    prefix_entries=tuple(entries),
                )
            )
            for node, entries in by_node.items()
        }

    def counters(args):
        return {
            k: int(v)
            for k, v in handler.get_counters().items()
            if isinstance(v, (int, float, bool))
        }

    def _entry_metrics_key(e) -> Tuple:
        """Best-advertisement ordering (reference best-route-selection,
        decision/PrefixState.cpp): higher path preference wins, then
        higher source preference, then lower distance."""
        m = e.metrics
        return (-m.path_preference, -m.source_preference, m.distance)

    def advertised_routes(args, filtered=False):
        entries = handler.get_advertised_routes()
        if filtered:
            f = args.get("filter") or {}
            want_prefixes = {
                tc._ip_prefix_from_wire(p)
                for p in f.get("prefixes") or ()
            }
            want_type = f.get("prefixType")
            if want_prefixes:
                entries = [
                    e for e in entries if e.prefix in want_prefixes
                ]
            if want_type is not None:
                entries = [
                    e for e in entries if int(e.type.value) == want_type
                ]
        by_prefix: Dict[Any, List] = {}
        for e in entries:
            by_prefix.setdefault(e.prefix, []).append(e)
        out = []
        for prefix, group in sorted(
            by_prefix.items(), key=lambda kv: str(kv[0])
        ):
            ranked = sorted(
                group,
                key=lambda e: (_entry_metrics_key(e), int(e.type.value)),
            )
            best = ranked[0]
            best_ties = [
                int(e.type.value)
                for e in ranked
                if _entry_metrics_key(e) == _entry_metrics_key(best)
            ]
            out.append({
                "prefix": tc._ip_prefix_to_wire(prefix),
                "bestKey": int(best.type.value),
                "bestKeys": sorted(best_ties),
                "routes": [
                    {
                        "key": int(e.type.value),
                        "route": tc._prefix_entry_to_wire(e),
                    }
                    for e in ranked
                ],
            })
        return out

    def _naa(key) -> Dict:
        if isinstance(key, tuple):
            return {"node": key[0], "area": key[1]}
        return {"node": key, "area": "0"}

    def received_routes(args, filtered=False):
        dbs = handler.get_received_routes()
        f = (args.get("filter") or {}) if filtered else {}
        want_prefixes = {
            tc._ip_prefix_from_wire(p) for p in f.get("prefixes") or ()
        }
        want_node = f.get("nodeName")
        want_area = f.get("areaName")
        out = []
        for prefix, entries in sorted(
            dbs.items(), key=lambda kv: str(kv[0])
        ):
            if want_prefixes and prefix not in want_prefixes:
                continue
            items = [
                (_naa(key), e) for key, e in sorted(
                    entries.items(), key=lambda kv: str(kv[0])
                )
            ]
            if want_node is not None:
                items = [
                    (k, e) for k, e in items if k["node"] == want_node
                ]
            if want_area is not None:
                items = [
                    (k, e) for k, e in items if k["area"] == want_area
                ]
            if not items:
                continue
            ranked = sorted(
                items,
                key=lambda ke: (
                    _entry_metrics_key(ke[1]),
                    ke[0]["node"], ke[0]["area"],
                ),
            )
            best_k, best_e = ranked[0]
            best_ties = [
                k for k, e in ranked
                if _entry_metrics_key(e) == _entry_metrics_key(best_e)
            ]
            out.append({
                "prefix": tc._ip_prefix_to_wire(prefix),
                "bestKey": best_k,
                "bestKeys": best_ties,
                "routes": [
                    {"key": k, "route": tc._prefix_entry_to_wire(e)}
                    for k, e in ranked
                ],
            })
        return out

    def _ptype_name(value: int) -> str:
        from openr_tpu.types import PrefixType

        return PrefixType(value).name

    def advertise_prefixes(args):
        handler._prefix_manager.advertise_prefixes([
            tc._prefix_entry_from_wire(p)
            for p in args.get("prefixes", [])
        ])

    def withdraw_prefixes(args):
        handler._prefix_manager.withdraw_prefixes([
            tc._prefix_entry_from_wire(p).prefix
            for p in args.get("prefixes", [])
        ])

    def sync_prefixes_by_type(args):
        from openr_tpu.types import PrefixType

        ptype = PrefixType(args.get("prefixType", 0))
        handler._prefix_manager.sync_prefixes_by_type(
            ptype,
            [tc._prefix_entry_from_wire(p)
             for p in args.get("prefixes", [])],
        )

    def rib_policy_to_wire(args):
        policy = handler.get_rib_policy()
        if policy is None:
            # reference contract: throws when not set / not enabled
            raise RuntimeError("rib policy is not set")
        return {
            "ttl_secs": int(policy["ttl_remaining_s"]),
            "statements": [
                {
                    "name": s["name"],
                    "matcher": {
                        "prefixes": [
                            tc._ip_prefix_to_wire(_IpPrefix.from_str(p))
                            for p in s["prefixes"]
                        ],
                    },
                    "action": {
                        "set_weight": {
                            "default_weight": s["action"]
                            .get("set_weight", {})
                            .get("default_weight", 0),
                            "area_to_weight": s["action"]
                            .get("set_weight", {})
                            .get("area_to_weight", {}),
                            "neighbor_to_weight": s["action"]
                            .get("set_weight", {})
                            .get("neighbor_to_weight", {}),
                        },
                    },
                }
                for s in policy["statements"]
            ],
        }

    def set_rib_policy(args):
        p = args.get("ribPolicy") or {}
        statements = []
        for s in p.get("statements", []):
            w = (s.get("action") or {}).get("set_weight") or {}
            statements.append({
                "name": s.get("name", ""),
                "prefixes": [
                    tc._ip_prefix_from_wire(x).to_str()
                    for x in (s.get("matcher") or {}).get(
                        "prefixes"
                    ) or ()
                ],
                "default_weight": w.get("default_weight", 0),
                "area_to_weight": w.get("area_to_weight", {}),
                "neighbor_to_weight": w.get("neighbor_to_weight", {}),
            })
        handler.set_rib_policy(
            statements, ttl_secs=float(p.get("ttl_secs", 300))
        )

    def perf_db(args):
        return {
            "thisNodeName": handler.get_my_node_name(),
            "eventInfo": [
                {
                    "events": [
                        {
                            "nodeName": ev.node_name,
                            "eventDescr": ev.event_descr,
                            "unixTs": int(ev.unix_ts),
                        }
                        for ev in pe.events
                    ],
                }
                for pe in handler.get_perf_db()
            ],
        }

    def dump_links(args):
        overloaded, details = (
            handler._link_monitor.get_interface_details()
        )
        out: Dict[str, Any] = {}
        for name, (info, link_overloaded, override) in sorted(
            details.items()
        ):
            d: Dict[str, Any] = {
                "info": {
                    "isUp": bool(info.is_up),
                    "ifIndex": int(info.if_index),
                    "networks": [
                        tc._ip_prefix_to_wire(p) for p in info.networks
                    ],
                },
                "isOverloaded": bool(link_overloaded),
            }
            if override is not None:
                d["metricOverride"] = int(override)
            out[name] = d
        return {
            "thisNodeName": handler.get_my_node_name(),
            "isOverloaded": bool(overloaded),
            "interfaceDetails": out,
        }

    def spark_neighbors(args):
        out = []
        for if_name, neighbors in sorted(
            handler.get_spark_neighbors().items()
        ):
            for node, state in sorted(neighbors.items()):
                out.append({
                    "nodeName": node,
                    "state": state,
                    "area": "0",
                    "transportAddressV6": {"addr": b""},
                    "transportAddressV4": {"addr": b""},
                    "openrCtrlThriftPort": 0,
                    "kvStoreCmdPort": 0,
                    "remoteIfName": "",
                    "localIfName": if_name,
                    "rttUs": 0,
                    "label": 0,
                })
        return out

    def spt_infos(args):
        snap = handler.get_spanning_tree_infos(args.get("area", "0"))
        out: Dict[str, Any] = {
            "infos": {
                root: {
                    "passive": i["passive"],
                    "cost": i["cost"],
                    "children": set(i["children"]),
                    **({"parent": i["parent"]}
                       if i["parent"] is not None else {}),
                }
                for root, i in snap["infos"].items()
            },
            # packet/message counters are not tracked per neighbor in
            # this implementation; the maps are structurally present
            "counters": {"neighborCounters": {}, "rootCounters": {}},
            "floodPeers": set(snap["flood_peers"]),
        }
        if snap["flood_root_id"] is not None:
            out["floodRootId"] = snap["flood_root_id"]
        return out

    def process_dual(args):
        src_id, msgs = tc.dual_messages_from_wire(
            args.get("messages") or {}
        )
        handler._kvstore.process_dual_messages(
            args.get("area", "0"), src_id, msgs
        )

    def flood_topo_child(args):
        p = args.get("params") or {}
        handler._kvstore.set_flood_topo_child(
            args.get("area", "0"),
            p.get("rootId", ""),
            p.get("srcId", ""),
            p.get("setChild", False),
            all_roots=p.get("allRoots", False),
        )

    def get_config_key(args):
        value = handler.get_config_key(args.get("key", ""))
        if value is None:
            raise RuntimeError(f"no config key {args.get('key')!r}")
        if isinstance(value, bytes):
            return value
        if isinstance(value, str):
            return value.encode("utf-8")
        return json.dumps(value).encode("utf-8")

    def running_config_thrift(args):
        cfg = handler._config
        if cfg is None:
            # no explicit config: serialize the dataclass DEFAULTS (one
            # source of truth — config/config.py), not literal copies
            from openr_tpu.config.config import OpenrConfig

            cfg = OpenrConfig(node_name=handler.get_my_node_name())
        first_area = cfg.areas[0] if cfg.areas else None
        return {
            "node_name": cfg.node_name,
            "domain": cfg.domain,
            "areas": [
                {
                    "area_id": a.area_id,
                    "interface_regexes": list(
                        a.include_interface_regexes
                    ),
                    "neighbor_regexes": list(a.neighbor_regexes),
                }
                for a in cfg.areas
            ],
            "listen_addr": cfg.listen_addr,
            "openr_ctrl_port": cfg.openr_ctrl_port,
            "dryrun": cfg.dryrun,
            "enable_v4": cfg.enable_v4,
            "enable_netlink_fib_handler": cfg.enable_netlink_fib_handler,
            "prefix_forwarding_type": int(
                cfg.prefix_forwarding_type.value
            ),
            "prefix_forwarding_algorithm": int(
                cfg.prefix_forwarding_algorithm.value
            ),
            "enable_segment_routing": cfg.enable_segment_routing,
            "kvstore_config": {
                "key_ttl_ms": int(cfg.kvstore.key_ttl_ms),
                "sync_interval_s": int(cfg.kvstore.sync_interval_s),
                "ttl_decrement_ms": int(cfg.kvstore.ttl_decrement_ms),
                "enable_flood_optimization":
                    cfg.kvstore.enable_flood_optimization,
                "is_flood_root": cfg.kvstore.is_flood_root,
            },
            "link_monitor_config": {
                "linkflap_initial_backoff_ms": int(
                    cfg.link_monitor.linkflap_initial_backoff_ms
                ),
                "linkflap_max_backoff_ms": int(
                    cfg.link_monitor.linkflap_max_backoff_ms
                ),
                "use_rtt_metric": cfg.link_monitor.use_rtt_metric,
                "include_interface_regexes": list(
                    first_area.include_interface_regexes
                    if first_area else []
                ),
                "exclude_interface_regexes": list(
                    first_area.exclude_interface_regexes
                    if first_area else []
                ),
                "redistribute_interface_regexes": [],
            },
            "spark_config": {
                "neighbor_discovery_port": int(cfg.spark.mcast_port),
                "hello_time_s": int(cfg.spark.hello_time_s),
                "fastinit_hello_time_ms": int(
                    cfg.spark.fastinit_hello_time_ms
                ),
                "keepalive_time_s": int(cfg.spark.keepalive_time_s),
                "hold_time_s": int(cfg.spark.hold_time_s),
                "graceful_restart_time_s": int(
                    cfg.spark.graceful_restart_time_s
                ),
            },
            "enable_watchdog": cfg.enable_watchdog,
            "watchdog_config": {
                "interval_s": int(cfg.watchdog.interval_s),
                "thread_timeout_s": int(cfg.watchdog.thread_timeout_s),
                "max_memory_mb": int(cfg.watchdog.max_memory_mb),
            },
            "enable_ordered_fib_programming":
                cfg.enable_ordered_fib_programming,
            "enable_rib_policy": cfg.enable_rib_policy,
            "enable_best_route_selection":
                cfg.enable_best_route_selection,
        }

    def long_poll_adj(args):
        # reference semantics (OpenrCtrlHandler.h:250): the client's
        # snapshot is COMPARED first — any adj: key newer than (or
        # absent from) the snapshot answers true immediately; only a
        # matching snapshot blocks for the next change
        snapshot = args.get("snapshot") or {}
        current = handler.get_kvstore_keys_filtered(prefix="adj:")
        for key, val in current.items():
            snap = snapshot.get(key)
            if snap is None or snap.get("version", 0) < val.version:
                return True
        return bool(handler.long_poll_kvstore_adj())

    methods = [
        _Method("getMyNodeName", (), ("string",),
                lambda a: handler.get_my_node_name()),
        _Method("getOpenrVersion", (),
                ("struct", tc.OPENR_VERSIONS),
                lambda a: {
                    "version": OPENR_VERSION,
                    "lowestSupportedVersion":
                        OPENR_LOWEST_SUPPORTED_VERSION,
                }, throws=True),
        _Method("aliveSince", (), ("i64",),
                lambda a: handler.alive_since()),
        _Method("getCounters", (), ("map", ("string",), ("i64",)),
                counters),
        _Method("getRunningConfig", (), ("string",),
                lambda a: json.dumps(handler.get_running_config())),
        _Method("dryrunConfig", (F(1, ("string",), "file"),),
                ("string",),
                lambda a: json.dumps(
                    handler.dryrun_config(a.get("file", "{}"))
                ), throws=True),
        # -- KvStore ------------------------------------------------------
        _Method("getKvStoreKeyVals",
                (F(1, ("list", ("string",)), "filterKeys"),),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a), throws=True),
        _Method("getKvStoreKeyValsArea",
                (F(1, ("list", ("string",)), "filterKeys"),
                 F(2, ("string",), "area")),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a), throws=True),
        _Method("getKvStoreKeyValsFiltered",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, dump=True), throws=True),
        _Method("getKvStoreKeyValsFilteredArea",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),
                 F(2, ("string",), "area")),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, dump=True), throws=True),
        _Method("getKvStoreHashFiltered",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, hashes=True), throws=True),
        _Method("getKvStoreHashFilteredArea",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),
                 F(2, ("string",), "area")),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, hashes=True), throws=True),
        _Method("setKvStoreKeyVals",
                (F(1, ("struct", tc.KEY_SET_PARAMS), "setParams"),
                 F(2, ("string",), "area")),
                _VOID, set_key_vals, throws=True),
        _Method("longPollKvStoreAdj",
                (F(1, ("map", ("string",), ("struct", tc.VALUE)),
                   "snapshot"),),
                ("bool",),
                long_poll_adj,
                throws=True),
        _Method("getKvStorePeers", (),
                ("map", ("string",), ("struct", tc.PEER_SPEC)),
                peers_map, throws=True),
        _Method("getKvStorePeersArea", (F(1, ("string",), "area"),),
                ("map", ("string",), ("struct", tc.PEER_SPEC)),
                peers_map, throws=True),
        # -- routes -------------------------------------------------------
        _Method("getRouteDb", (), ("struct", tc.ROUTE_DATABASE),
                lambda a: route_db(), throws=True),
        _Method("getRouteDbComputed", (F(1, ("string",), "nodeName"),),
                ("struct", tc.ROUTE_DATABASE),
                lambda a: route_db(node=a.get("nodeName", "")),
                throws=True),
        _Method("getUnicastRoutes", (),
                ("list", ("struct", tc.UNICAST_ROUTE)),
                lambda a: unicast_routes(a), throws=True),
        _Method("getUnicastRoutesFiltered",
                (F(1, ("list", ("string",)), "prefixes"),),
                ("list", ("struct", tc.UNICAST_ROUTE)),
                lambda a: unicast_routes(a, filtered=True),
                throws=True),
        _Method("getMplsRoutes", (),
                ("list", ("struct", tc.MPLS_ROUTE)),
                lambda a: mpls_routes(a), throws=True),
        _Method("getMplsRoutesFiltered",
                (F(1, ("list", ("i32",)), "labels"),),
                ("list", ("struct", tc.MPLS_ROUTE)),
                lambda a: mpls_routes(a, filtered=True), throws=True),
        # -- decision -----------------------------------------------------
        _Method("getDecisionAdjacencyDbs", (),
                ("map", ("string",), ("struct", tc.ADJACENCY_DATABASE)),
                adj_dbs, throws=True),
        _Method("getAllDecisionAdjacencyDbs", (),
                ("list", ("struct", tc.ADJACENCY_DATABASE)),
                all_adj_dbs, throws=True),
        _Method("getDecisionPrefixDbs", (),
                ("map", ("string",), ("struct", tc.PREFIX_DATABASE)),
                prefix_dbs, throws=True),
        # -- drain / link overrides --------------------------------------
        _Method("setNodeOverload", (), _VOID,
                lambda a: handler.set_node_overload(True), throws=True),
        _Method("unsetNodeOverload", (), _VOID,
                lambda a: handler.set_node_overload(False),
                throws=True),
        _Method("setInterfaceOverload",
                (F(1, ("string",), "interfaceName"),), _VOID,
                lambda a: handler.set_link_overload(
                    a.get("interfaceName", ""), True
                ), throws=True),
        _Method("unsetInterfaceOverload",
                (F(1, ("string",), "interfaceName"),), _VOID,
                lambda a: handler.set_link_overload(
                    a.get("interfaceName", ""), False
                ), throws=True),
        _Method("setInterfaceMetric",
                (F(1, ("string",), "interfaceName"),
                 F(2, ("i32",), "overrideMetric")), _VOID,
                lambda a: handler.set_interface_metric(
                    a.get("interfaceName", ""),
                    a.get("overrideMetric", 0),
                ), throws=True),
        _Method("unsetInterfaceMetric",
                (F(1, ("string",), "interfaceName"),), _VOID,
                lambda a: handler.unset_interface_metric(
                    a.get("interfaceName", "")
                ), throws=True),
        # -- config -------------------------------------------------------
        _Method("getRunningConfigThrift", (),
                ("struct", tc.OPENR_CONFIG),
                running_config_thrift),
        _Method("getAreasConfig", (), ("struct", tc.AREAS_CONFIG),
                lambda a: {"areas": set(handler.get_kvstore_areas())},
                throws=True),
        _Method("getConfigKey", (F(1, ("string",), "key"),),
                ("binary",), get_config_key, throws=True),
        _Method("setConfigKey",
                (F(1, ("string",), "key"), F(2, ("binary",), "value")),
                _VOID,
                lambda a: handler.set_config_key(
                    a.get("key", ""), bytes(a.get("value", b""))
                ), throws=True),
        _Method("eraseConfigKey", (F(1, ("string",), "key"),), _VOID,
                lambda a: handler.erase_config_key(a.get("key", "")),
                throws=True),
        # -- PrefixManager ------------------------------------------------
        _Method("advertisePrefixes",
                (F(1, ("list", ("struct", tc.PREFIX_ENTRY)),
                   "prefixes"),),
                _VOID, advertise_prefixes, throws=True),
        _Method("withdrawPrefixes",
                (F(1, ("list", ("struct", tc.PREFIX_ENTRY)),
                   "prefixes"),),
                _VOID, withdraw_prefixes, throws=True),
        _Method("withdrawPrefixesByType",
                (F(1, ("i32",), "prefixType"),), _VOID,
                lambda a: handler.withdraw_prefixes_by_type(
                    _ptype_name(a.get("prefixType", 0))
                ), throws=True),
        _Method("syncPrefixesByType",
                (F(1, ("i32",), "prefixType"),
                 F(2, ("list", ("struct", tc.PREFIX_ENTRY)),
                   "prefixes")),
                _VOID, sync_prefixes_by_type, throws=True),
        _Method("getPrefixes", (),
                ("list", ("struct", tc.PREFIX_ENTRY)),
                lambda a: [
                    tc._prefix_entry_to_wire(e)
                    for e in handler.get_prefixes()
                ], throws=True),
        _Method("getPrefixesByType", (F(1, ("i32",), "prefixType"),),
                ("list", ("struct", tc.PREFIX_ENTRY)),
                lambda a: [
                    tc._prefix_entry_to_wire(e)
                    for e in handler.get_prefixes_by_type(
                        _ptype_name(a.get("prefixType", 0))
                    )
                ], throws=True),
        # -- advertised / received routes ---------------------------------
        _Method("getAdvertisedRoutes", (),
                ("list", ("struct", tc.ADVERTISED_ROUTE_DETAIL)),
                lambda a: advertised_routes(a)),
        _Method("getAdvertisedRoutesFiltered",
                (F(1, ("struct", tc.ADVERTISED_ROUTE_FILTER),
                   "filter"),),
                ("list", ("struct", tc.ADVERTISED_ROUTE_DETAIL)),
                lambda a: advertised_routes(a, filtered=True),
                throws=True),
        _Method("getReceivedRoutes", (),
                ("list", ("struct", tc.RECEIVED_ROUTE_DETAIL)),
                lambda a: received_routes(a)),
        _Method("getReceivedRoutesFiltered",
                (F(1, ("struct", tc.RECEIVED_ROUTE_FILTER),
                   "filter"),),
                ("list", ("struct", tc.RECEIVED_ROUTE_DETAIL)),
                lambda a: received_routes(a, filtered=True),
                throws=True),
        # -- perf ---------------------------------------------------------
        _Method("getPerfDb", (), ("struct", tc.PERF_DATABASE),
                perf_db, throws=True),
        # -- LinkMonitor --------------------------------------------------
        _Method("getInterfaces", (),
                ("struct", tc.DUMP_LINKS_REPLY),
                dump_links, throws=True),
        _Method("getLinkMonitorAdjacencies", (),
                ("struct", tc.ADJACENCY_DATABASE),
                lambda a: tc.adjacency_db_to_wire(
                    handler.get_link_monitor_adjacencies()
                ), throws=True),
        _Method("setAdjacencyMetric",
                (F(1, ("string",), "interfaceName"),
                 F(2, ("string",), "adjNodeName"),
                 F(3, ("i32",), "overrideMetric")), _VOID,
                lambda a: handler.set_link_metric(
                    a.get("interfaceName", ""),
                    a.get("adjNodeName", ""),
                    a.get("overrideMetric", 0),
                ), throws=True),
        _Method("unsetAdjacencyMetric",
                (F(1, ("string",), "interfaceName"),
                 F(2, ("string",), "adjNodeName")), _VOID,
                lambda a: handler.set_link_metric(
                    a.get("interfaceName", ""),
                    a.get("adjNodeName", ""),
                    None,
                ), throws=True),
        _Method("getBuildInfo", (), ("struct", tc.BUILD_INFO),
                lambda a: {
                    "buildUser": "", "buildTime": "",
                    "buildTimeUnix": 0, "buildHost": "",
                    "buildPath": "", "buildRevision": "",
                    "buildRevisionCommitTimeUnix": 0,
                    "buildUpstreamRevision": "",
                    "buildUpstreamRevisionCommitTimeUnix": 0,
                    "buildPackageName": "openr-tpu",
                    "buildPackageVersion": str(OPENR_VERSION),
                    "buildPackageRelease": "",
                    "buildPlatform": "tpu",
                    "buildRule": "", "buildType": "",
                    "buildTool": "", "buildMode": "",
                }, throws=True),
        # -- Spark --------------------------------------------------------
        _Method("getNeighbors", (),
                ("list", ("struct", tc.SPARK_NEIGHBOR)),
                spark_neighbors, throws=True),
        # -- DUAL / flood topology ----------------------------------------
        _Method("processKvStoreDualMessage",
                (F(1, ("struct", tc.DUAL_MESSAGES), "messages"),
                 F(2, ("string",), "area")),
                _VOID, process_dual, throws=True),
        _Method("updateFloodTopologyChild",
                (F(1, ("struct", tc.FLOOD_TOPO_SET_PARAMS), "params"),
                 F(2, ("string",), "area")),
                _VOID, flood_topo_child, throws=True),
        _Method("getSpanningTreeInfos", (F(1, ("string",), "area"),),
                ("struct", tc.SPT_INFOS), spt_infos, throws=True),
        # -- RibPolicy ----------------------------------------------------
        _Method("setRibPolicy",
                (F(1, ("struct", tc.RIB_POLICY), "ribPolicy"),),
                _VOID, set_rib_policy, throws=True),
        _Method("getRibPolicy", (), ("struct", tc.RIB_POLICY),
                rib_policy_to_wire, throws=True),
        # -- misc ---------------------------------------------------------
        _Method("floodRestartingMsg", (), _VOID,
                lambda a: handler.flood_restarting_msg(), throws=True),
        _Method("getEventLogs", (), ("list", ("string",)),
                lambda a: list(handler.get_event_logs()), throws=True),
    ]
    return {
        m.name: (m.args_schema, m.handle) for m in methods
    }, {m.name: m for m in methods}


class ThriftCtrlServer(FramedCompactServer):
    """Framed-compact OpenrCtrl server. Normally not run on its own
    port: ctrl/server.py byte-sniffs the shared ctrl port and hands
    compact-protocol connections to ``serve_connection``."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0,
                 listen: bool = True):
        table, self.methods = build_method_table(handler)
        super().__init__(table, host=host, port=port, listen=listen)


class ThriftCtrlClient:
    """Typed client for the thrift ctrl surface — the repo's own codec
    standing in for a stock thrift client (byte-identical wire). Used
    by tests and tools/thrift_ctrl_probe.py."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        # default comfortably above the server's 10s long-poll block:
        # an idle longPollKvStoreAdj must come back as a False reply,
        # not a client-side socket timeout
        self._client = FramedCompactClient(host, port, timeout_s)
        # method schemas are handler-independent: build against a dummy
        _, self._methods = build_method_table(_SchemaOnly())

    def call(self, name: str, **args) -> Any:
        m = self._methods[name]
        result = self._client.call(
            name, m.args_schema, args, m.result_schema
        )
        if result.get("error") is not None:
            raise RuntimeError(
                f"OpenrError: {result['error'].get('message')}"
            )
        if m.ret is _VOID:
            return None
        return result.get("success")

    def close(self) -> None:
        self._client.close()


class _SchemaOnly:
    """Attribute sink so build_method_table can run clientside (the
    lambdas close over the handler but are never invoked)."""

    def __getattr__(self, name):  # pragma: no cover - schema only
        return None
