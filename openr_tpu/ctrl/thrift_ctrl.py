"""OpenrCtrl on the thrift wire: the operator surface a STOCK Open/R
toolchain speaks.

The framework's own ctrl codec (ctrl/server.py, JSON frames) remains
the native surface; THIS module exposes the high-traffic subset of the
reference thrift service (`/root/reference/openr/if/OpenrCtrl.thrift:
168-577`, handler `ctrl-server/OpenrCtrlHandler.h:24`) as framed
CompactProtocol — the same interop wire the KvStore peer channel and
FibService already speak (utils/thrift_rpc.py). A stock breeze or
external automation dialing the ctrl port with classic framed transport
round-trips these RPCs against an openr-tpu node.

Implemented subset (the VERDICT-ranked operator surface): KvStore
get/dump/hash/set + peers + long-poll, routes computed/installed
(unicast + MPLS), adjacency/prefix dbs, counters/aliveSince, node and
interface drain, interface metric overrides, version/config/identity,
event logs. Streaming subscriptions stay on the framework wire (the
reference serves those over fbthrift Rocket streams, out of scope for
classic framed transport).

Thrift service conventions: per-method args struct (ids from the IDL),
result struct with ``success`` at field 0 and declared ``OpenrError``
exceptions at field 1; undeclared failures become
TApplicationException (utils/thrift_rpc.py handles the envelope).

Dual-stacking on the ctrl port is byte-sniffed in ctrl/server.py: a
compact-protocol message leads with 0x82 after the 4-byte frame
length, a TLS ClientHello leads with 0x16, the framework JSON codec
with ``{`` — all three wire shapes share one advertised port.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

from openr_tpu.utils import thrift_compact as tc
from openr_tpu.utils.thrift_rpc import (
    FramedCompactClient,
    FramedCompactServer,
    MethodTable,
)

OPENR_VERSION = 20200825  # reference: common/Constants.h:274
OPENR_LOWEST_SUPPORTED_VERSION = 20200604  # Constants.h:277

_VOID = object()  # sentinel: method returns nothing


def _result_schema(name: str, ret, throws: bool):
    fields = []
    if ret is not _VOID:
        fields.append(tc.Field(0, ret, "success", optional=True))
    if throws:
        fields.append(
            tc.Field(
                1, ("struct", tc.OPENR_ERROR), "error", optional=True
            )
        )
    return tc.StructSchema(f"{name}_result", tuple(fields))


class _Method:
    def __init__(self, name, arg_fields, ret, fn, throws=False):
        self.name = name
        self.args_schema = tc.StructSchema(
            f"{name}_args", tuple(arg_fields)
        )
        self.result_schema = _result_schema(name, ret, throws)
        self.ret = ret
        self.fn = fn
        self.throws = throws

    def handle(self, args: Dict) -> Tuple[object, Dict]:
        try:
            value = self.fn(args)
        except Exception as exc:
            if self.throws:
                return self.result_schema, {
                    "error": {"message": f"{type(exc).__name__}: {exc}"}
                }
            raise
        if self.ret is _VOID:
            return self.result_schema, {}
        return self.result_schema, {"success": value}


def _pub_to_wire(key_vals, area: str) -> Dict:
    return {
        "keyVals": {
            k: tc._value_to_wire(v) for k, v in key_vals.items()
        },
        "expiredKeys": [],
        "area": area,
    }


def _node_of(key) -> str:
    """PrefixState entry keys are node names or (node, area) pairs."""
    return key[0] if isinstance(key, tuple) else key


def build_method_table(handler) -> MethodTable:
    """Method table for utils.thrift_rpc.FramedCompactServer wrapping
    an OpenrCtrlHandler."""
    F = tc.Field

    def kv_publication(args, dump=False, hashes=False):
        area = args.get("area", "0")
        if hashes:
            prefix = (args.get("filter") or {}).get("prefix", "")
            kvs = handler.get_kvstore_hash_filtered(
                prefix=prefix, area=area
            )
        elif dump:
            params = tc._key_dump_params_from_wire(
                args.get("filter") or {}
            )
            pub = handler._kvstore.dump_with_filters(area, params)
            kvs = pub.key_vals
        else:
            kvs = handler.get_kvstore_key_vals(
                list(args.get("filterKeys", [])), area=area
            )
        return _pub_to_wire(kvs, area)

    def set_key_vals(args):
        params = tc._key_set_params_from_wire(
            args.get("setParams") or {}
        )
        handler._kvstore.set_key_vals(args.get("area", "0"), params)

    def peers_map(args):
        area = args.get("area", "0")
        # one eventbase round trip: peer_endpoints' keys ARE the peer
        # names. In-process transports have no endpoint; stock tooling
        # renders the empty PeerSpec as "no address known".
        return {
            name: {
                "peerAddr": ep[0] if ep else "",
                "cmdUrl": "",
                "ctrlPort": ep[1] if ep else 0,
            }
            for name, ep in handler._kvstore.peer_endpoints(
                area
            ).items()
        }

    def route_db(args=None, node=None):
        db = (
            handler.get_route_db()
            if node is None
            else handler.get_route_db_computed(node or None)
        )
        return tc.route_db_to_wire(db)

    def unicast_routes(args, filtered=False):
        prefixes = (
            list(args.get("prefixes", [])) if filtered else None
        )
        routes = handler.get_unicast_routes(prefixes or None)
        return [tc._unicast_route_to_wire(r) for r in routes]

    def mpls_routes(args, filtered=False):
        labels = set(args.get("labels", [])) if filtered else None
        routes = handler.get_route_db().mpls_routes
        return [
            tc._mpls_route_to_wire(r)
            for r in routes
            if not labels or r.top_label in labels
        ]

    def flat_adj_dbs() -> Dict[str, Any]:
        # handler returns {area: {node: AdjacencyDatabase}}; the thrift
        # AdjDbs is a per-node map (first area wins on collision, like
        # the reference's single-area legacy view)
        out: Dict[str, Any] = {}
        for _area, dbs in sorted(
            handler.get_decision_adjacency_dbs().items()
        ):
            for name, db in dbs.items():
                out.setdefault(name, db)
        return out

    def adj_dbs(args):
        return {
            name: tc.adjacency_db_to_wire(db)
            for name, db in flat_adj_dbs().items()
        }

    def all_adj_dbs(args):
        return [
            tc.adjacency_db_to_wire(db)
            for _, db in sorted(flat_adj_dbs().items())
        ]

    def prefix_dbs(args):
        from openr_tpu.types import PrefixDatabase

        by_node: Dict[str, List] = {}
        for _prefix, entries in handler.get_decision_prefix_dbs().items():
            for key, entry in entries.items():
                by_node.setdefault(_node_of(key), []).append(entry)
        return {
            node: tc.prefix_db_to_wire(
                PrefixDatabase(
                    this_node_name=node,
                    prefix_entries=tuple(entries),
                )
            )
            for node, entries in by_node.items()
        }

    def counters(args):
        return {
            k: int(v)
            for k, v in handler.get_counters().items()
            if isinstance(v, (int, float, bool))
        }

    def long_poll_adj(args):
        # reference semantics (OpenrCtrlHandler.h:250): the client's
        # snapshot is COMPARED first — any adj: key newer than (or
        # absent from) the snapshot answers true immediately; only a
        # matching snapshot blocks for the next change
        snapshot = args.get("snapshot") or {}
        current = handler.get_kvstore_keys_filtered(prefix="adj:")
        for key, val in current.items():
            snap = snapshot.get(key)
            if snap is None or snap.get("version", 0) < val.version:
                return True
        return bool(handler.long_poll_kvstore_adj())

    methods = [
        _Method("getMyNodeName", (), ("string",),
                lambda a: handler.get_my_node_name()),
        _Method("getOpenrVersion", (),
                ("struct", tc.OPENR_VERSIONS),
                lambda a: {
                    "version": OPENR_VERSION,
                    "lowestSupportedVersion":
                        OPENR_LOWEST_SUPPORTED_VERSION,
                }, throws=True),
        _Method("aliveSince", (), ("i64",),
                lambda a: handler.alive_since()),
        _Method("getCounters", (), ("map", ("string",), ("i64",)),
                counters),
        _Method("getRunningConfig", (), ("string",),
                lambda a: json.dumps(handler.get_running_config())),
        _Method("dryrunConfig", (F(1, ("string",), "file"),),
                ("string",),
                lambda a: json.dumps(
                    handler.dryrun_config(a.get("file", "{}"))
                ), throws=True),
        # -- KvStore ------------------------------------------------------
        _Method("getKvStoreKeyVals",
                (F(1, ("list", ("string",)), "filterKeys"),),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a), throws=True),
        _Method("getKvStoreKeyValsArea",
                (F(1, ("list", ("string",)), "filterKeys"),
                 F(2, ("string",), "area")),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a), throws=True),
        _Method("getKvStoreKeyValsFiltered",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, dump=True), throws=True),
        _Method("getKvStoreKeyValsFilteredArea",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),
                 F(2, ("string",), "area")),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, dump=True), throws=True),
        _Method("getKvStoreHashFiltered",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, hashes=True), throws=True),
        _Method("getKvStoreHashFilteredArea",
                (F(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),
                 F(2, ("string",), "area")),
                ("struct", tc.PUBLICATION),
                lambda a: kv_publication(a, hashes=True), throws=True),
        _Method("setKvStoreKeyVals",
                (F(1, ("struct", tc.KEY_SET_PARAMS), "setParams"),
                 F(2, ("string",), "area")),
                _VOID, set_key_vals, throws=True),
        _Method("longPollKvStoreAdj",
                (F(1, ("map", ("string",), ("struct", tc.VALUE)),
                   "snapshot"),),
                ("bool",),
                long_poll_adj,
                throws=True),
        _Method("getKvStorePeers", (),
                ("map", ("string",), ("struct", tc.PEER_SPEC)),
                peers_map, throws=True),
        _Method("getKvStorePeersArea", (F(1, ("string",), "area"),),
                ("map", ("string",), ("struct", tc.PEER_SPEC)),
                peers_map, throws=True),
        # -- routes -------------------------------------------------------
        _Method("getRouteDb", (), ("struct", tc.ROUTE_DATABASE),
                lambda a: route_db(), throws=True),
        _Method("getRouteDbComputed", (F(1, ("string",), "nodeName"),),
                ("struct", tc.ROUTE_DATABASE),
                lambda a: route_db(node=a.get("nodeName", "")),
                throws=True),
        _Method("getUnicastRoutes", (),
                ("list", ("struct", tc.UNICAST_ROUTE)),
                lambda a: unicast_routes(a), throws=True),
        _Method("getUnicastRoutesFiltered",
                (F(1, ("list", ("string",)), "prefixes"),),
                ("list", ("struct", tc.UNICAST_ROUTE)),
                lambda a: unicast_routes(a, filtered=True),
                throws=True),
        _Method("getMplsRoutes", (),
                ("list", ("struct", tc.MPLS_ROUTE)),
                lambda a: mpls_routes(a), throws=True),
        _Method("getMplsRoutesFiltered",
                (F(1, ("list", ("i32",)), "labels"),),
                ("list", ("struct", tc.MPLS_ROUTE)),
                lambda a: mpls_routes(a, filtered=True), throws=True),
        # -- decision -----------------------------------------------------
        _Method("getDecisionAdjacencyDbs", (),
                ("map", ("string",), ("struct", tc.ADJACENCY_DATABASE)),
                adj_dbs, throws=True),
        _Method("getAllDecisionAdjacencyDbs", (),
                ("list", ("struct", tc.ADJACENCY_DATABASE)),
                all_adj_dbs, throws=True),
        _Method("getDecisionPrefixDbs", (),
                ("map", ("string",), ("struct", tc.PREFIX_DATABASE)),
                prefix_dbs, throws=True),
        # -- drain / link overrides --------------------------------------
        _Method("setNodeOverload", (), _VOID,
                lambda a: handler.set_node_overload(True), throws=True),
        _Method("unsetNodeOverload", (), _VOID,
                lambda a: handler.set_node_overload(False),
                throws=True),
        _Method("setInterfaceOverload",
                (F(1, ("string",), "interfaceName"),), _VOID,
                lambda a: handler.set_link_overload(
                    a.get("interfaceName", ""), True
                ), throws=True),
        _Method("unsetInterfaceOverload",
                (F(1, ("string",), "interfaceName"),), _VOID,
                lambda a: handler.set_link_overload(
                    a.get("interfaceName", ""), False
                ), throws=True),
        _Method("setInterfaceMetric",
                (F(1, ("string",), "interfaceName"),
                 F(2, ("i32",), "overrideMetric")), _VOID,
                lambda a: handler.set_interface_metric(
                    a.get("interfaceName", ""),
                    a.get("overrideMetric", 0),
                ), throws=True),
        _Method("unsetInterfaceMetric",
                (F(1, ("string",), "interfaceName"),), _VOID,
                lambda a: handler.unset_interface_metric(
                    a.get("interfaceName", "")
                ), throws=True),
        # -- misc ---------------------------------------------------------
        _Method("floodRestartingMsg", (), _VOID,
                lambda a: handler.flood_restarting_msg(), throws=True),
        _Method("getEventLogs", (), ("list", ("string",)),
                lambda a: list(handler.get_event_logs()), throws=True),
    ]
    return {
        m.name: (m.args_schema, m.handle) for m in methods
    }, {m.name: m for m in methods}


class ThriftCtrlServer(FramedCompactServer):
    """Framed-compact OpenrCtrl server. Normally not run on its own
    port: ctrl/server.py byte-sniffs the shared ctrl port and hands
    compact-protocol connections to ``serve_connection``."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0,
                 listen: bool = True):
        table, self.methods = build_method_table(handler)
        super().__init__(table, host=host, port=port, listen=listen)


class ThriftCtrlClient:
    """Typed client for the thrift ctrl surface — the repo's own codec
    standing in for a stock thrift client (byte-identical wire). Used
    by tests and tools/thrift_ctrl_probe.py."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._client = FramedCompactClient(host, port, timeout_s)
        # method schemas are handler-independent: build against a dummy
        _, self._methods = build_method_table(_SchemaOnly())

    def call(self, name: str, **args) -> Any:
        m = self._methods[name]
        result = self._client.call(
            name, m.args_schema, args, m.result_schema
        )
        if result.get("error") is not None:
            raise RuntimeError(
                f"OpenrError: {result['error'].get('message')}"
            )
        if m.ret is _VOID:
            return None
        return result.get("success")

    def close(self) -> None:
        self._client.close()


class _SchemaOnly:
    """Attribute sink so build_method_table can run clientside (the
    lambdas close over the handler but are never invoked)."""

    def __getattr__(self, name):  # pragma: no cover - schema only
        return None
