"""SolverCtrlHandler: the solver service's wire surface.

Rides the existing ctrl transport (``CtrlServer`` — length-prefixed
JSON frames, duck-typed method dispatch, the same dual-stacked port
Decision's handler serves), so clients reach the solver with the stock
``CtrlClient`` machinery: no new listener, no new framing, TLS for
free. Every method is prefixed ``solver_`` to keep the namespace
disjoint from the OpenrCtrl surface.

Worlds cross the wire as base64 ``utils.wire`` AdjacencyDatabase
blobs — the LSDB's own serialization — and the server builds each
tenant's ``LinkState`` from them (clients stay jax-free and
graph-free; see serve/client.py). Views return as base64 int32 packed
blocks plus the node-name table, which is everything a client needs to
reconstruct per-destination distances/first-hops and everything the
parity gates digest.

Fleet plane (openr_tpu/fleet): the handler is also one *managed
service* in a fleet — three roles ride the same surface:

- **Routing.** A tenant sealed away by a live migration answers every
  later call with a ``CtrlRedirect`` carrying the destination
  (``moved_to``, counted ``fleet.client_redirects``); a tenant frozen
  mid-migration answers ``CtrlRetry`` so the client backs off instead
  of racing the drain.
- **Migration.** ``solver_export`` freezes + drains + serializes
  (host mirror, un-replayed journal tail, world blobs);
  ``solver_import`` rehydrates WARM on the destination and journals
  the tenant into the destination's OWN replica stream;
  ``solver_seal_migration`` drops the source copy and installs the
  redirect. Abort unfreezes, leaving the tenant parked warm.
- **Replication.** A primary appends every adopted mutation to its
  ``FleetJournal``; the standby's handler applies shipped suffixes
  (``solver_replica_apply``, idempotent on replayed prefixes),
  absorbs the solves so it stays hot, and ``solver_promote`` runs the
  one graceful-restart reconcile — per-tenant route-DB diffs against
  the held products, with zero deletes as the no-flap gate.

FIB-level tenant views: ``solver_fib`` returns the tenant's full
``RouteDatabase`` (unicast + MPLS, built through the Decision rib the
same way the digital twin's vantages are), not just the SP/KSP2 view —
so a client can consume route products without owning a graph stack.

The ``serve.slow_client`` fault seam fires on the reply path of
``solver_solve``: an armed delay schedule stalls only THIS client's
connection thread — the wave loop and other clients never feel it.
"""

from __future__ import annotations

import base64
import threading
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from openr_tpu.analysis.annotations import runs_on
from openr_tpu.ctrl.server import (
    CtrlRedirect,
    CtrlRetry,
    current_connection,
    current_trace_context,
)
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver, fleet_preload_views
from openr_tpu.faults import fault_point
from openr_tpu.fleet.journal import FleetJournal, FleetRecord
from openr_tpu.fleet.placement import FLEET_COUNTERS
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.serve.service import FAULT_SLOW_CLIENT, SolverService
from openr_tpu.serve.slo import SLO_TABLE
from openr_tpu.types.lsdb import AdjacencyDatabase, PrefixDatabase
from openr_tpu.utils import wire


def _decode_db(blob: str) -> AdjacencyDatabase:
    return wire.loads(base64.b64decode(blob), AdjacencyDatabase)


def _decode_prefix_db(blob: str) -> PrefixDatabase:
    return wire.loads(base64.b64decode(blob), PrefixDatabase)


def _fnv(data: bytes) -> int:
    """FNV-1a, the same digest the jax-free client computes — one
    digest algorithm across both ends of every parity gate."""
    h = 0x811C9DC5
    for b in data:
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def _path_links(path) -> List[List]:
    """Canonical wire form of one path: per link, the sorted
    ((node, iface), (node, iface)) endpoint key — identical for the
    served trace and a host-oracle trace of the same links."""
    return [
        [end for pair in sorted(
            ((l.n1, l.if1), (l.n2, l.if2))
        ) for end in pair]
        for l in path
    ]


@runs_on("ctrl")
class SolverCtrlHandler:
    """One per service process. Tenants registered over a connection
    are tied to it (``ctrl.server.current_connection``); the server's
    ``connection_closed`` teardown parks them warm through
    ``SolverService.connection_closed``. Every method runs on a
    per-connection ctrl server thread (``@runs_on`` seeds the
    shared-state rule's role inference across the duck-typed
    dispatch).

    ``journal`` arms the primary role: every adopted mutation is
    appended for the standby stream. ``role`` is advisory ("primary" /
    "standby") until a promotion flips it."""

    def __init__(self, service: SolverService,
                 journal: Optional[FleetJournal] = None,
                 role: str = "primary"):
        self._svc = service
        self._lock = threading.RLock()
        self._ls: Dict[str, LinkState] = {}
        self._roots: Dict[str, str] = {}
        # fleet plane state (all under _lock)
        self._journal = journal
        self._role = role
        self._areas: Dict[str, str] = {}
        self._slos: Dict[str, str] = {}
        self._prefix: Dict[str, PrefixState] = {}
        self._prefix_blobs: Dict[str, Dict[str, str]] = {}
        self._moved: Dict[str, Tuple[str, int]] = {}
        self._frozen: Set[str] = set()
        self._applied_seq = 0
        self._fib_solver: Dict[str, SpfSolver] = {}
        self._held_fib: Dict[str, object] = {}

    # -- transport teardown hook (CtrlServer duck-types this) --------------

    def connection_closed(self, conn: int) -> None:
        self._svc.connection_closed(conn)

    # -- fleet routing ------------------------------------------------------

    def _check_routable(self, tenant_id: str) -> None:
        """Every tenant-scoped method passes here first: a sealed-away
        tenant redirects (counted), a frozen one asks for a retry."""
        with self._lock:
            moved = self._moved.get(tenant_id)
            frozen = tenant_id in self._frozen
        if moved is not None:
            FLEET_COUNTERS["client_redirects"] += 1
            raise CtrlRedirect(
                f"tenant {tenant_id!r} migrated", moved[0], moved[1]
            )
        if frozen:
            raise CtrlRetry(
                f"tenant {tenant_id!r} is migrating", 50.0
            )

    def _journal_append(self, kind: str, tenant_id: str,
                        payload: Dict[str, object]) -> None:
        if self._journal is not None:
            self._journal.append(kind, tenant_id, payload)

    # -- methods (JSON-frame dispatched) -----------------------------------

    def solver_hello(self) -> Dict:
        return {
            "classes": sorted(SLO_TABLE),
            "slots_per_bucket": self._svc.manager.slots_per_bucket,
            "role": self._role,
        }

    def solver_register(self, tenant_id: str, slo: str = "standard",
                        area: str = "0") -> Dict:
        self._check_routable(tenant_id)
        self._svc.register(
            tenant_id, slo, conn=current_connection()
        )
        with self._lock:
            if tenant_id not in self._ls:
                self._ls[tenant_id] = LinkState(area=area)
            self._areas[tenant_id] = area
            self._slos[tenant_id] = slo
        self._journal_append(
            "register", tenant_id, {"slo": slo, "area": area}
        )
        return {"tenant_id": tenant_id, "slo": slo}

    def solver_update(self, tenant_id: str, adj_dbs: List[str],
                      root: Optional[str] = None,
                      prefix_dbs: Optional[List[str]] = None) -> Dict:
        """Apply a world snapshot or churn delta: each entry is one
        node's AdjacencyDatabase (b64 wire). The FIRST update must be
        the full snapshot; later calls send only changed nodes.
        ``prefix_dbs`` (b64 PrefixDatabase blobs) feed the FIB-level
        view — optional, per changed node, same delta discipline."""
        self._check_routable(tenant_id)
        with self._lock:
            ls = self._ls[tenant_id]
            for blob in adj_dbs:
                ls.update_adjacency_database(_decode_db(blob))
            if root is not None:
                self._roots[tenant_id] = root
            if prefix_dbs:
                pfx = self._prefix.get(tenant_id)
                if pfx is None:
                    pfx = self._prefix[tenant_id] = PrefixState()
                blobs = self._prefix_blobs.setdefault(tenant_id, {})
                for blob in prefix_dbs:
                    pdb = _decode_prefix_db(blob)
                    pfx.update_prefix_database(pdb)
                    blobs[pdb.this_node_name] = blob
            out = {
                "topology_version": ls.topology_version,
                "nodes": len(ls.get_adjacency_databases()),
            }
        self._journal_append("update", tenant_id, {
            "adj_dbs": list(adj_dbs),
            "prefix_dbs": list(prefix_dbs or []),
            "root": root,
        })
        return out

    def solver_solve(self, tenant_id: str,
                     timeout: float = 60.0) -> Dict:
        self._check_routable(tenant_id)
        with self._lock:
            ls = self._ls[tenant_id]
            root = self._roots.get(tenant_id)
            if root is None:
                root = sorted(ls.get_adjacency_databases())[0]
        graph, srcs, packed = self._svc.solve(
            tenant_id, ls, root, timeout=timeout,
            trace_ctx=current_trace_context(),
        )
        # slow-client seam: a delay schedule armed here models a
        # client draining its reply slowly — only this connection
        # thread stalls, the wave loop has already moved on
        fault_point(FAULT_SLOW_CLIENT)
        packed = np.ascontiguousarray(packed.astype(np.int32))
        names = [
            name
            for name, _i in sorted(
                graph.node_index.items(), key=lambda kv: kv[1]
            )
        ]
        return {
            "root": root,
            "srcs": [int(s) for s in srcs],
            "n_pad": int(graph.n_pad),
            "shape": list(packed.shape),
            "packed_b64": base64.b64encode(packed.tobytes()).decode(),
            "nodes": names,
        }

    def solver_ksp2(self, tenant_id: str, dsts: List[str]) -> Dict:
        self._check_routable(tenant_id)
        paths = self._svc.ksp2(tenant_id, dsts)
        return {
            dst: [_path_links(p) for p in path_list]
            for dst, path_list in paths.items()
        }

    def solver_detach(self, tenant_id: str,
                      warm: bool = True) -> Dict:
        self._check_routable(tenant_id)
        self._svc.detach(tenant_id, warm=warm)
        self._journal_append(
            "detach", tenant_id, {"warm": warm, "moved_to": None}
        )
        return {"tenant_id": tenant_id, "warm": warm}

    # -- FIB-level tenant views --------------------------------------------

    def _build_fib_locked(self, tenant_id: str, view):
        """Route-product build for one tenant (caller holds ``_lock``
        and provides the wave's solved view): preload the view so the
        rib build consumes it with zero further device work — the
        digital twin's fan-in recipe, per tenant."""
        ls = self._ls[tenant_id]
        root = self._roots.get(tenant_id)
        if root is None:
            root = sorted(ls.get_adjacency_databases())[0]
        solver = self._fib_solver.get(tenant_id)
        if solver is None or solver.my_node_name != root:
            solver = SpfSolver(root, backend="device")
            self._fib_solver[tenant_id] = solver
        fleet_preload_views(ls, [view])
        pfx = self._prefix.get(tenant_id)
        if pfx is None:
            pfx = self._prefix[tenant_id] = PrefixState()
        area = self._areas.get(tenant_id, ls.area)
        return solver.build_route_db(root, {area: ls}, pfx)

    def solver_fib(self, tenant_id: str,
                   timeout: float = 60.0) -> Dict:
        """The tenant's full route product: solve (or join the next
        wave), build the Decision rib from the solved view, and return
        the canonical ``RouteDatabase`` (b64 wire) + its FNV digest —
        what the migration/promotion parity gates compare."""
        self._check_routable(tenant_id)
        with self._lock:
            ls = self._ls[tenant_id]
            root = self._roots.get(tenant_id)
            if root is None:
                root = sorted(ls.get_adjacency_databases())[0]
        view = self._svc.solve(
            tenant_id, ls, root, timeout=timeout,
            trace_ctx=current_trace_context(),
        )
        with self._lock:
            ddb = self._build_fib_locked(tenant_id, view)
            if ddb is None:
                raise RuntimeError(
                    f"root {root!r} not in tenant {tenant_id!r} world"
                )
            self._held_fib[tenant_id] = ddb
            rd = ddb.to_route_db(root)
        blob = wire.dumps(rd)
        return {
            "root": root,
            "route_db_b64": base64.b64encode(blob).decode(),
            "digest": _fnv(blob),
            "unicast": len(rd.unicast_routes),
            "mpls": len(rd.mpls_routes),
        }

    # -- live migration (source side) --------------------------------------

    def solver_export(self, tenant_id: str) -> Dict:
        """Freeze + drain + serialize: after this returns, the tenant
        answers every call with retry-later until the migration seals
        (redirect thereafter) or aborts (thaw, parked warm)."""
        with self._lock:
            if tenant_id not in self._ls:
                raise KeyError(f"unknown tenant {tenant_id!r}")
            self._frozen.add(tenant_id)
        try:
            self._svc.quiesce(tenant_id)
            with self._lock:
                record = self._svc.export_tenant(tenant_id)
                ls = self._ls[tenant_id]
                adj_blobs = [
                    base64.b64encode(wire.dumps(db)).decode()
                    for _node, db in sorted(
                        ls.get_adjacency_databases().items()
                    )
                ]
                prefix_blobs = [
                    blob for _node, blob in sorted(
                        self._prefix_blobs.get(tenant_id, {}).items()
                    )
                ]
                return {
                    "record": record,
                    "adj_dbs": adj_blobs,
                    "prefix_dbs": prefix_blobs,
                    "root": self._roots.get(tenant_id),
                    "area": self._areas.get(tenant_id, ls.area),
                    "slo": self._slos.get(
                        tenant_id, str(record.get("slo", "standard"))
                    ),
                }
        except Exception:
            # a failed export must never wedge the tenant: thaw and
            # let the next solve rehydrate it warm where it stands
            with self._lock:
                self._frozen.discard(tenant_id)
            raise

    def solver_seal_migration(self, tenant_id: str, host: str,
                              port: int) -> Dict:
        """Destination confirmed the import: drop the source copy and
        install the redirect. Journaled so the source's standby drops
        its replica too."""
        with self._lock:
            self._frozen.discard(tenant_id)
            self._moved[tenant_id] = (host, int(port))
            self._ls.pop(tenant_id, None)
            self._roots.pop(tenant_id, None)
            self._areas.pop(tenant_id, None)
            self._slos.pop(tenant_id, None)
            self._prefix.pop(tenant_id, None)
            self._prefix_blobs.pop(tenant_id, None)
            self._fib_solver.pop(tenant_id, None)
            self._held_fib.pop(tenant_id, None)
        self._svc.detach(tenant_id, warm=False)
        self._journal_append("detach", tenant_id, {
            "warm": False, "moved_to": [host, int(port)],
        })
        return {"tenant_id": tenant_id, "moved_to": [host, int(port)]}

    def solver_abort_migration(self, tenant_id: str) -> Dict:
        """Import failed: thaw. The tenant sits parked warm (export
        drained it) and the next solve rehydrates in place."""
        with self._lock:
            self._frozen.discard(tenant_id)
        return {"tenant_id": tenant_id, "aborted": True}

    # -- live migration (destination side) ---------------------------------

    def solver_import(self, bundle: Dict) -> Dict:
        """Rehydrate a migrated tenant WARM: rebuild the LinkState
        from the shipped world blobs (``compile_ell`` determinism makes
        the shipped mirror valid against it), import the host record,
        and journal the tenant into THIS service's replica stream so
        its standby replicates the newcomer."""
        record = dict(bundle["record"])
        tenant_id = str(record["tenant_id"])
        area = str(bundle.get("area") or "0")
        slo = str(bundle.get("slo") or "standard")
        root = bundle.get("root")
        adj_blobs = list(bundle.get("adj_dbs", []))
        prefix_blobs = list(bundle.get("prefix_dbs", []))
        ls = LinkState(area=area)
        for blob in adj_blobs:
            ls.update_adjacency_database(_decode_db(blob))
        pfx = PrefixState()
        by_node: Dict[str, str] = {}
        for blob in prefix_blobs:
            pdb = _decode_prefix_db(blob)
            pfx.update_prefix_database(pdb)
            by_node[pdb.this_node_name] = blob
        record["slo"] = slo
        t = self._svc.import_tenant(ls, record)
        self._svc.register(tenant_id, slo, conn=None)
        with self._lock:
            self._ls[tenant_id] = ls
            self._areas[tenant_id] = area
            self._slos[tenant_id] = slo
            if root:
                self._roots[tenant_id] = str(root)
            self._prefix[tenant_id] = pfx
            self._prefix_blobs[tenant_id] = by_node
            self._moved.pop(tenant_id, None)
            self._frozen.discard(tenant_id)
        self._journal_append(
            "register", tenant_id, {"slo": slo, "area": area}
        )
        self._journal_append("update", tenant_id, {
            "adj_dbs": adj_blobs,
            "prefix_dbs": prefix_blobs,
            "root": root,
        })
        return {"tenant_id": tenant_id, "warm": bool(t.solved)}

    # -- hot-standby replication (standby side) ----------------------------

    def _apply_record_locked(self, rec: FleetRecord,
                             dirty: Set[str]) -> List:
        """One journal record onto the replica's maps (caller holds
        ``_lock``); returns deferred service calls to run unlocked."""
        tid = rec.tenant_id
        calls: List = []
        if rec.kind == "register":
            area = str(rec.payload.get("area") or "0")
            slo = str(rec.payload.get("slo") or "standard")
            if tid not in self._ls:
                self._ls[tid] = LinkState(area=area)
            self._areas[tid] = area
            self._slos[tid] = slo
            calls.append(
                lambda: self._svc.register(tid, slo, conn=None)
            )
        elif rec.kind == "update":
            ls = self._ls.get(tid)
            if ls is None:
                ls = self._ls[tid] = LinkState(
                    area=self._areas.get(tid, "0")
                )
            for blob in rec.payload.get("adj_dbs", []):
                ls.update_adjacency_database(_decode_db(blob))
            root = rec.payload.get("root")
            if root:
                self._roots[tid] = str(root)
            pblobs = rec.payload.get("prefix_dbs", [])
            if pblobs:
                pfx = self._prefix.get(tid)
                if pfx is None:
                    pfx = self._prefix[tid] = PrefixState()
                blobs = self._prefix_blobs.setdefault(tid, {})
                for blob in pblobs:
                    pdb = _decode_prefix_db(blob)
                    pfx.update_prefix_database(pdb)
                    blobs[pdb.this_node_name] = blob
            dirty.add(tid)
        elif rec.kind == "detach":
            warm = bool(rec.payload.get("warm", True))
            if not warm:
                # migrated or dropped for good: forget the replica
                self._ls.pop(tid, None)
                self._roots.pop(tid, None)
                self._areas.pop(tid, None)
                self._slos.pop(tid, None)
                self._prefix.pop(tid, None)
                self._prefix_blobs.pop(tid, None)
                self._fib_solver.pop(tid, None)
                self._held_fib.pop(tid, None)
            dirty.discard(tid)
            calls.append(
                lambda: self._svc.detach(tid, warm=warm)
            )
        return calls

    def solver_replica_apply(self, records: List[Dict],
                             absorb: bool = True) -> Dict:
        """Apply a shipped journal suffix in order, idempotent on
        replayed prefixes (records at or below the applied seq are
        skipped, so a retried half-failed ship is safe). ``absorb``
        solves the dirtied tenants and rebuilds their held route
        products immediately — the standby stays HOT, which is what
        makes promotion one reconcile instead of a cold boot."""
        dirty: Set[str] = set()
        deferred: List = []
        with self._lock:
            for frame in records:
                rec = FleetRecord.from_wire(frame)
                if rec.seq <= self._applied_seq:
                    continue
                deferred.extend(
                    self._apply_record_locked(rec, dirty)
                )
                self._applied_seq = rec.seq
            applied = self._applied_seq
        for call in deferred:
            call()
        if absorb and dirty:
            self._absorb(sorted(dirty))
        return {"applied_seq": applied}

    def _absorb(self, tenant_ids: List[str]) -> None:
        """Solve the dirtied replicas as one wave and hold their route
        products — the promotion diff's 'before' side."""
        reqs = []
        with self._lock:
            items = [
                (
                    tid,
                    self._ls[tid],
                    self._roots.get(tid)
                    or sorted(
                        self._ls[tid].get_adjacency_databases()
                    )[0],
                )
                for tid in tenant_ids
                if tid in self._ls
            ]
        for tid, ls, root in items:
            reqs.append(
                (tid, self._svc.request_solve(tid, ls, root))
            )
        for tid, req in reqs:
            view = req.wait(60.0)
            with self._lock:
                if tid not in self._ls:
                    continue
                ddb = self._build_fib_locked(tid, view)
                if ddb is not None:
                    self._held_fib[tid] = ddb

    def solver_promote(self) -> Dict:
        """Graceful-restart takeover: ONE ``sync_fib``-equivalent
        reconcile across every replicated tenant — resolve each, diff
        the new route product against the held one, and count deletes
        (the no-flap gate demands zero: the standby's journal-fed
        state must reproduce the primary's products exactly). Flips
        the role to primary. The promotion happens AT the applied seq
        — the controller owns the never-promote-past-an-un-shipped-
        suffix rule and the counted surrender when the primary died
        with journal in hand."""
        deletes = 0
        digests: Dict[str, int] = {}
        with self._lock:
            tids = sorted(self._ls)
            self._role = "primary"
            applied = self._applied_seq
        # the reconcile diff runs against the held products the
        # ONGOING absorbs built (the standby's data-plane view at the
        # moment the primary died) — NOT a product rebuilt here, which
        # would make the no-flap gate compare a thing to itself
        with self._lock:
            items = [
                (
                    tid,
                    self._ls[tid],
                    self._roots.get(tid)
                    or sorted(
                        self._ls[tid].get_adjacency_databases()
                    )[0],
                )
                for tid in tids
                if tid in self._ls
            ]
        for tid, ls, root in items:
            view = self._svc.solve(tid, ls, root)
            with self._lock:
                if tid not in self._ls:
                    continue
                new_ddb = self._build_fib_locked(tid, view)
                held = self._held_fib.get(tid)
                if new_ddb is None:
                    continue
                if held is not None:
                    delta = held.calculate_update(new_ddb)
                    deletes += len(delta.unicast_routes_to_delete)
                    deletes += len(delta.mpls_routes_to_delete)
                self._held_fib[tid] = new_ddb
                digests[tid] = _fnv(
                    wire.dumps(new_ddb.to_route_db(root))
                )
        return {
            "tenants": tids,
            "deletes": deletes,
            "applied_seq": applied,
            "digests": digests,
            "role": self._role,
        }

    def solver_role(self) -> Dict:
        with self._lock:
            return {
                "role": self._role,
                "applied_seq": self._applied_seq,
                "tenants": sorted(self._ls),
            }

    def solver_journal_stat(self) -> Dict:
        """Primary-side journal introspection (lag tests + the
        controller's hazard accounting)."""
        if self._journal is None:
            return {"last_seq": 0, "horizon_seq": 0, "records": 0}
        return {
            "last_seq": self._journal.last_seq,
            "horizon_seq": self._journal.horizon_seq,
            "records": len(self._journal),
        }

    # -- introspection ------------------------------------------------------

    def solver_counters(self) -> Dict:
        return self._svc.counters()

    def solver_ping(self) -> Dict:
        return {"ok": True, "waves": self._svc.waves()}

    def solver_stage_attribution(self) -> Dict:
        """Per-SLO-class p99 beside the measured per-stage device/host
        costs (``SolverService.stage_attribution``)."""
        return self._svc.stage_attribution()

    def get_flight_record(self, limit: int = 0) -> Dict:
        """Same surface as the OpenrCtrl handler: the flight ring +
        live attribution, so ``breeze monitor flight`` works against a
        solver process too."""
        from openr_tpu.telemetry import get_flight_recorder, get_profiler

        fr = get_flight_recorder()
        prof = get_profiler()
        return {
            "records": fr.records(limit),
            "triggers": fr.trigger_names(),
            "attribution": prof.attribution(),
            "host_overhead_ratio": prof.host_overhead_ratio(),
        }

    def dump_postmortem(self, trigger: str = "manual",
                        reason: str = "") -> Dict:
        from openr_tpu.telemetry import get_flight_recorder

        reason = reason or "operator request"
        ctx = current_trace_context()
        if ctx and ctx.get("span_id"):
            # stamp the requesting client's span so the bundle pairs
            # with the client-side observation that asked for it
            reason = f"{reason} [client span {ctx['span_id']}]"
        path = get_flight_recorder().dump_postmortem(
            trigger=trigger, reason=reason
        )
        return {"path": path}
