"""SolverCtrlHandler: the solver service's wire surface.

Rides the existing ctrl transport (``CtrlServer`` — length-prefixed
JSON frames, duck-typed method dispatch, the same dual-stacked port
Decision's handler serves), so clients reach the solver with the stock
``CtrlClient`` machinery: no new listener, no new framing, TLS for
free. Every method is prefixed ``solver_`` to keep the namespace
disjoint from the OpenrCtrl surface.

Worlds cross the wire as base64 ``utils.wire`` AdjacencyDatabase
blobs — the LSDB's own serialization — and the server builds each
tenant's ``LinkState`` from them (clients stay jax-free and
graph-free; see serve/client.py). Views return as base64 int32 packed
blocks plus the node-name table, which is everything a client needs to
reconstruct per-destination distances/first-hops and everything the
parity gates digest.

The ``serve.slow_client`` fault seam fires on the reply path of
``solver_solve``: an armed delay schedule stalls only THIS client's
connection thread — the wave loop and other clients never feel it.
"""

from __future__ import annotations

import base64
import threading
from typing import Dict, List, Optional

import numpy as np

from openr_tpu.analysis.annotations import runs_on
from openr_tpu.ctrl.server import current_connection, current_trace_context
from openr_tpu.faults import fault_point
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.serve.service import FAULT_SLOW_CLIENT, SolverService
from openr_tpu.serve.slo import SLO_TABLE
from openr_tpu.types.lsdb import AdjacencyDatabase
from openr_tpu.utils import wire


def _decode_db(blob: str) -> AdjacencyDatabase:
    return wire.loads(base64.b64decode(blob), AdjacencyDatabase)


def _path_links(path) -> List[List]:
    """Canonical wire form of one path: per link, the sorted
    ((node, iface), (node, iface)) endpoint key — identical for the
    served trace and a host-oracle trace of the same links."""
    return [
        [end for pair in sorted(
            ((l.n1, l.if1), (l.n2, l.if2))
        ) for end in pair]
        for l in path
    ]


@runs_on("ctrl")
class SolverCtrlHandler:
    """One per service process. Tenants registered over a connection
    are tied to it (``ctrl.server.current_connection``); the server's
    ``connection_closed`` teardown parks them warm through
    ``SolverService.connection_closed``. Every method runs on a
    per-connection ctrl server thread (``@runs_on`` seeds the
    shared-state rule's role inference across the duck-typed
    dispatch)."""

    def __init__(self, service: SolverService):
        self._svc = service
        self._lock = threading.RLock()
        self._ls: Dict[str, LinkState] = {}
        self._roots: Dict[str, str] = {}

    # -- transport teardown hook (CtrlServer duck-types this) --------------

    def connection_closed(self, conn: int) -> None:
        self._svc.connection_closed(conn)

    # -- methods (JSON-frame dispatched) -----------------------------------

    def solver_hello(self) -> Dict:
        return {
            "classes": sorted(SLO_TABLE),
            "slots_per_bucket": self._svc.manager.slots_per_bucket,
        }

    def solver_register(self, tenant_id: str, slo: str = "standard",
                        area: str = "0") -> Dict:
        self._svc.register(
            tenant_id, slo, conn=current_connection()
        )
        with self._lock:
            if tenant_id not in self._ls:
                self._ls[tenant_id] = LinkState(area=area)
        return {"tenant_id": tenant_id, "slo": slo}

    def solver_update(self, tenant_id: str, adj_dbs: List[str],
                      root: Optional[str] = None) -> Dict:
        """Apply a world snapshot or churn delta: each entry is one
        node's AdjacencyDatabase (b64 wire). The FIRST update must be
        the full snapshot; later calls send only changed nodes."""
        with self._lock:
            ls = self._ls[tenant_id]
            for blob in adj_dbs:
                ls.update_adjacency_database(_decode_db(blob))
            if root is not None:
                self._roots[tenant_id] = root
            return {
                "topology_version": ls.topology_version,
                "nodes": len(ls.get_adjacency_databases()),
            }

    def solver_solve(self, tenant_id: str,
                     timeout: float = 60.0) -> Dict:
        with self._lock:
            ls = self._ls[tenant_id]
            root = self._roots.get(tenant_id)
            if root is None:
                root = sorted(ls.get_adjacency_databases())[0]
        graph, srcs, packed = self._svc.solve(
            tenant_id, ls, root, timeout=timeout,
            trace_ctx=current_trace_context(),
        )
        # slow-client seam: a delay schedule armed here models a
        # client draining its reply slowly — only this connection
        # thread stalls, the wave loop has already moved on
        fault_point(FAULT_SLOW_CLIENT)
        packed = np.ascontiguousarray(packed.astype(np.int32))
        names = [
            name
            for name, _i in sorted(
                graph.node_index.items(), key=lambda kv: kv[1]
            )
        ]
        return {
            "root": root,
            "srcs": [int(s) for s in srcs],
            "n_pad": int(graph.n_pad),
            "shape": list(packed.shape),
            "packed_b64": base64.b64encode(packed.tobytes()).decode(),
            "nodes": names,
        }

    def solver_ksp2(self, tenant_id: str, dsts: List[str]) -> Dict:
        paths = self._svc.ksp2(tenant_id, dsts)
        return {
            dst: [_path_links(p) for p in path_list]
            for dst, path_list in paths.items()
        }

    def solver_detach(self, tenant_id: str,
                      warm: bool = True) -> Dict:
        self._svc.detach(tenant_id, warm=warm)
        return {"tenant_id": tenant_id, "warm": warm}

    def solver_counters(self) -> Dict:
        return self._svc.counters()

    def solver_ping(self) -> Dict:
        return {"ok": True, "waves": self._svc.waves()}

    def solver_stage_attribution(self) -> Dict:
        """Per-SLO-class p99 beside the measured per-stage device/host
        costs (``SolverService.stage_attribution``)."""
        return self._svc.stage_attribution()

    def get_flight_record(self, limit: int = 0) -> Dict:
        """Same surface as the OpenrCtrl handler: the flight ring +
        live attribution, so ``breeze monitor flight`` works against a
        solver process too."""
        from openr_tpu.telemetry import get_flight_recorder, get_profiler

        fr = get_flight_recorder()
        prof = get_profiler()
        return {
            "records": fr.records(limit),
            "triggers": fr.trigger_names(),
            "attribution": prof.attribution(),
            "host_overhead_ratio": prof.host_overhead_ratio(),
        }

    def dump_postmortem(self, trigger: str = "manual",
                        reason: str = "") -> Dict:
        from openr_tpu.telemetry import get_flight_recorder

        reason = reason or "operator request"
        ctx = current_trace_context()
        if ctx and ctx.get("span_id"):
            # stamp the requesting client's span so the bundle pairs
            # with the client-side observation that asked for it
            reason = f"{reason} [client span {ctx['span_id']}]"
        path = get_flight_recorder().dump_postmortem(
            trigger=trigger, reason=reason
        )
        return {"path": path}
